"""Networked RADOS client (Objecter + librados roles).

Reference parity: Objecter (/root/reference/src/osdc/Objecter.cc) —
placement computed client-side with the same CRUSH/OSDMap math the OSDs
use (`_calc_target` Objecter.cc:2692), ops tagged with the client's map
epoch and resent when the map changes or the primary bounces them
(EAGAIN / replay_epoch), lossy connections simply re-established —
and librados::IoCtx (librados_cxx.cc:1247) as the user-facing surface.
"""

from __future__ import annotations

import asyncio
import contextlib
import contextvars
import logging
from typing import Any, Dict, List, Optional, Tuple

from ceph_tpu.common import flags
from ceph_tpu.msg import Connection, Messenger
from ceph_tpu.msg.messages import (
    MAuth,
    MAuthReply,
    MClientCaps,
    MClientReply,
    MGetMap,
    MMonCommand,
    MMonCommandReply,
    MOSDCommand,
    MOSDCommandReply,
    MOSDCompute,
    MOSDComputeReply,
    MOSDMapMsg,
    MOSDOp,
    MOSDOpReply,
    MWatchNotify,
    MWatchNotifyAck,
    Message,
    OSDOp,
    decode_kv_map,
    encode_kv_map,
    encode_str_list,
)
from ceph_tpu.ops.rjenkins import ceph_str_hash_rjenkins
from ceph_tpu.osd.osdmap import OSDMap, PgId

log = logging.getLogger("rados")

EAGAIN = -11
ENOENT = -2
EBUSY = -16
ESTALE = -116

#: QoS tenant identity riding MOSDOp v4.  A ContextVar instead of a
#: parameter on every I/O call: the S3 gateway authenticates a request
#: and every rados op that request fans into inherits the tenant with
#: zero signature churn (each asyncio task gets its own copy).  An
#: explicit `IoCtx.tenant` overrides it.
CURRENT_TENANT: contextvars.ContextVar[str] = contextvars.ContextVar(
    "rados_tenant", default="")


@contextlib.contextmanager
def tenant_scope(tenant: str):
    """Ops submitted inside the scope carry `tenant` (unless the
    IoCtx pins its own)."""
    token = CURRENT_TENANT.set(tenant)
    try:
        yield
    finally:
        CURRENT_TENANT.reset(token)


def full_jitter(base: float, attempt: int, cap: float = 5.0) -> float:
    """Retry sleep with FULL jitter: U(0, min(cap, base * 2^attempt)).

    The op/mon hunt loops used fixed (or linearly ramped) sleeps —
    when a device breaker trips cluster-wide, every client that failed
    in the same instant would retry in the same instant, and keep
    re-colliding each round (the thundering-herd resonance the AWS
    backoff analysis quantifies).  Sampling the WHOLE window decorrelates
    the herd in one round while keeping the same mean pressure."""
    import random

    return random.uniform(0.0, min(cap, base * (2 ** attempt)))


class RadosError(Exception):
    def __init__(self, rc: int, what: str = ""):
        super().__init__(f"rc={rc} {what}")
        self.rc = rc


class ObjectNotFound(RadosError):
    pass


class ServiceTracker:
    """Client half of dmClock delta/rho piggybacking (the dmclock
    ServiceTracker role).

    Per tenant it counts completions cluster-wide (all-phase and
    reservation-phase); per (tenant, OSD) it remembers how many of
    those happened at OTHER OSDs as of the tenant's last op there.
    An outgoing MOSDOp to OSD s then carries

        delta = 1 + other-OSD completions since the last op to s
        rho   = 1 + other-OSD reservation completions since then

    and s advances its mClock tags by delta x cost — so a tenant
    spreading load over N primaries is charged at each for what the
    other N-1 served, and its reservation/limit hold CLUSTER-wide
    instead of N-times over.  With one OSD (or the piggyback off)
    both collapse to 1: classic local mClock."""

    #: bounded bookkeeping: (tenant, osd) rows beyond this are evicted
    #: (their delta restarts at 1 — an under-charge for one op, not
    #: an error)
    STATE_CAP = 4096

    def __init__(self):
        # tenant -> [completions, reservation-phase completions]
        self._done: Dict[str, List[int]] = {}
        # (tenant, osd) -> [done_here, done_here_resv,
        #                   seen_other, seen_other_resv]
        self._srv: Dict[Tuple[str, int], List[int]] = {}

    def obtain(self, tenant: str, osd: int) -> Tuple[int, int]:
        """(delta, rho) for an op to `osd`; advances the per-server
        marker (call once per send)."""
        tot = self._done.setdefault(tenant, [0, 0])
        st = self._srv.get((tenant, osd))
        if st is None:
            if len(self._srv) >= self.STATE_CAP:
                # evict arbitrary rows; see STATE_CAP
                for key in list(self._srv)[:self.STATE_CAP // 4]:
                    del self._srv[key]
            st = self._srv[(tenant, osd)] = [0, 0, 0, 0]
        other = tot[0] - st[0]
        other_resv = tot[1] - st[1]
        delta = 1 + max(other - st[2], 0)
        rho = 1 + max(other_resv - st[3], 0)
        st[2], st[3] = other, other_resv
        return delta, rho

    def note_reply(self, tenant: str, osd: int, phase: str) -> None:
        """Count a completed (scheduled) op: the reply's qos_phase
        says which dmClock phase the grant won."""
        tot = self._done.setdefault(tenant, [0, 0])
        tot[0] += 1
        st = self._srv.get((tenant, osd))
        if st is None:
            st = self._srv[(tenant, osd)] = [0, 0, 0, 0]
        st[0] += 1
        if phase == "reservation":
            tot[1] += 1
            st[1] += 1


class RadosClient:
    def __init__(self, mon_addr, name: Optional[str] = None,
                 op_timeout: float = 10.0, max_retries: int = 30,
                 secret: Optional[str] = None, secure: bool = False,
                 config: Optional[dict] = None):
        # mon_addr: one address, a comma-separated list, or a list —
        # the client hunts across them on failure (MonClient hunting)
        if isinstance(mon_addr, str):
            self.mon_addrs = [a for a in mon_addr.split(",") if a]
        else:
            self.mon_addrs = list(mon_addr)
        self._mon_idx = 0
        if name is None:
            # entity names must be GLOBALLY unique: the OSDs' reqid
            # dedup cache keys on (client name, tid), and two clients
            # sharing a name would replay each other's cached replies
            # (the mon-assigned global_id role, MonClient::get_global_id)
            import uuid

            name = f"client.{uuid.uuid4().hex[:12]}"
        from ceph_tpu.common.auth import parse_secret

        self.msgr = Messenger(name, secret=parse_secret(secret))
        self.msgr.secure = secure
        self.msgr.local_fastpath = True
        self.msgr.dispatcher = self._dispatch
        # ms_compress_* applies to EVERY messenger, not just daemons —
        # without this a cluster-wide compression setting silently
        # skips client links
        self.msgr.apply_compress_config(config or {})
        # blkin-role tracing: when trace_all is on, every submitted op
        # opens a client span and carries its context to the OSDs
        from ceph_tpu.common.tracing import Tracer

        self.tracer = Tracer(name)
        self.trace_all = bool((config or {}).get("client_trace_all"))
        self.osdmap: Optional[OSDMap] = None
        self.op_timeout = op_timeout
        self.max_retries = max_retries
        # random tid base: a RESTARTED daemon client reusing a fixed
        # name (mds.a, mgr.x) must not collide with its previous
        # incarnation's reqids in OSD dedup caches
        import random as _random

        self._tid = _random.getrandbits(48)
        # dmClock piggyback state (CEPH_TPU_DMCLOCK): shared across
        # this client's ioctxs — delta/rho are per (tenant, OSD)
        self.qos_tracker = ServiceTracker()
        self._futures: Dict[int, asyncio.Future] = {}
        self._map_waiters: List[asyncio.Event] = []
        self._placement_cache: Dict[Tuple[int, PgId], int] = {}
        # (pool, oid, cookie) -> (ioctx, callback); re-registered with
        # the primary on every map change (linger resend role)
        self._watches: Dict[Tuple[int, str, int], tuple] = {}
        self._watch_cookie = 0
        self._watch_keepalive: Optional[asyncio.Task] = None
        # CephFS cap recalls arriving on this shared messenger are
        # routed to the mounted filesystem (set by CephFS.__init__)
        self.fs_caps_handler = None

    def _next_watch_cookie(self) -> int:
        self._watch_cookie += 1
        return self._watch_cookie

    def _ensure_watch_keepalive(self) -> None:
        """Watches must survive silent TCP drops, not just map
        changes: periodically re-register every live watch (the
        registration is idempotent on the primary)."""
        if self._watch_keepalive is None or \
                self._watch_keepalive.done():
            self._watch_keepalive = \
                asyncio.get_running_loop().create_task(
                    self._watch_keepalive_loop())

    async def _watch_keepalive_loop(self) -> None:
        while self._watches:
            await asyncio.sleep(3.0)
            await self._reregister_watches()

    @property
    def mon_addr(self) -> str:
        return self.mon_addrs[self._mon_idx % len(self.mon_addrs)]

    def _hunt_mon(self) -> None:
        """Rotate to the next mon in the monmap after a failure."""
        stale = self.msgr._conns.get(self.mon_addr)
        if stale is not None:
            stale.close()
        self._mon_idx += 1

    # -- lifecycle ---------------------------------------------------------

    async def connect(self) -> None:
        await self.msgr.bind()
        last: Optional[Exception] = None
        for _attempt in range(3 * len(self.mon_addrs)):
            try:
                mon = await self.msgr.connect(self.mon_addr)
                await mon.send(MGetMap(subscribe=True))
            except (ConnectionError, OSError) as e:
                last = e
                self._hunt_mon()
                await asyncio.sleep(full_jitter(0.2, _attempt, cap=2.0))
                continue
            for _ in range(500):
                if self.osdmap is not None:
                    return
                await asyncio.sleep(0.01)
            self._hunt_mon()
        raise TimeoutError(f"no osdmap from any mon ({last!r})")

    async def shutdown(self) -> None:
        if self._watch_keepalive is not None:
            self._watch_keepalive.cancel()
        await self.msgr.shutdown()

    # -- dispatch ----------------------------------------------------------

    async def _dispatch(self, conn: Connection, msg: Message) -> None:
        if isinstance(msg, MOSDMapMsg):
            if self._advance_map(msg):
                for event in self._map_waiters:
                    event.set()
                self._map_waiters.clear()
                if self._watches:
                    # primaries may have moved: re-register watches
                    self.msgr._spawn(self._reregister_watches())
        elif isinstance(msg, MWatchNotify):
            # run the callback, then ack so the notifier unblocks
            for (pool, oid, cookie), (ioctx, cb) in \
                    list(self._watches.items()):
                if pool == msg.pool and oid == msg.oid and \
                        cookie == msg.cookie:
                    try:
                        res = cb(msg.payload)
                        if asyncio.iscoroutine(res):
                            await res
                    except Exception:
                        log.exception("watch callback failed")
            try:
                await conn.send(MWatchNotifyAck(msg.notify_id,
                                                msg.cookie))
            except (ConnectionError, OSError):
                pass
        elif isinstance(msg, MClientCaps):
            if self.fs_caps_handler is not None:
                await self.fs_caps_handler(conn, msg)
        elif isinstance(msg, (MAuthReply,
                              MOSDOpReply, MMonCommandReply,
                              MOSDCommandReply, MOSDComputeReply,
                              MClientReply)):
            fut = self._futures.pop(msg.tid, None)
            if fut is not None and not fut.done():
                fut.set_result(msg)

    def _advance_map(self, msg: MOSDMapMsg) -> bool:
        """Advance the local map from a publish: contiguous
        incrementals apply directly; a gap (or a fresh client) falls
        back to the full map or a refresh pull."""
        from ceph_tpu.osd.osdmap import Incremental

        advanced = False
        if msg.incrementals and self.osdmap is not None:
            for raw in msg.incrementals:
                inc = Incremental.decode(raw)
                if inc.epoch <= self.osdmap.epoch:
                    continue
                if inc.epoch != self.osdmap.epoch + 1:
                    break  # gap: handled below
                self.osdmap.apply_incremental(inc)
                advanced = True
            if advanced and msg.epoch <= self.osdmap.epoch:
                return True
        if msg.full_map is not None:
            newmap = OSDMap.decode(msg.full_map)
            newmap.enable_placement_cache()
            if self.osdmap is None or newmap.epoch > self.osdmap.epoch:
                self.osdmap = newmap
                return True
            return advanced
        if self.osdmap is not None and msg.epoch > self.osdmap.epoch:
            # inc-only publish we could not apply: pull a fresh map
            self.msgr._spawn(self.refresh_map())
        return advanced

    async def _reregister_watches(self) -> None:
        for (pool, oid, cookie), (ioctx, _cb) in \
                list(self._watches.items()):
            try:
                await ioctx._submit(
                    oid, [OSDOp("watch", args={"cookie": cookie})])
            except Exception:
                pass  # next map change retries

    def _next_tid(self) -> int:
        self._tid += 1
        return self._tid

    def _primary_cached(self, osdmap: OSDMap, pg: PgId) -> int:
        """Placement memoized per (epoch, pg): the host CRUSH mapper
        costs milliseconds per PG and the answer is a pure function of
        the map (Objecter keeps the same cache implicitly in its
        session targets)."""
        key = (osdmap.epoch, pg)
        hit = self._placement_cache.get(key)
        if hit is None:
            _acting, hit = osdmap.pg_to_acting_osds(pg)
            if len(self._placement_cache) > 4096:
                self._placement_cache.clear()
            self._placement_cache[key] = hit
        return hit

    async def wait_for_new_map(self, timeout: float = 5.0) -> None:
        event = asyncio.Event()
        self._map_waiters.append(event)
        try:
            await asyncio.wait_for(event.wait(), timeout)
        except asyncio.TimeoutError:
            pass

    async def refresh_map(self) -> None:
        try:
            mon = await self.msgr.connect(self.mon_addr)
            await mon.send(MGetMap(subscribe=True))
        except (ConnectionError, OSError):
            # called from op-retry paths: a dead/faulted mon must not
            # crash the op — hunt and let the caller's retry loop spin
            self._hunt_mon()
        await self.wait_for_new_map(1.0)

    # -- cephx tickets (MonClient auth role) -------------------------------

    async def auth_get_ticket(self) -> bytes:
        """Fetch a mon-granted cephx ticket (two-step challenge proof,
        CephxServiceHandler shape) and attach it to every subsequent
        outbound connection's hello.  Services validate the ticket
        offline and bind the connection's session key to it."""
        from ceph_tpu.common import auth as auth_mod

        keyring = self.msgr.secret
        if keyring is None:
            raise RadosError(-95, "auth disabled (no keyring)")
        mon = await self.msgr.connect(self.mon_addr)

        async def ask(msg):
            fut = asyncio.get_running_loop().create_future()
            self._futures[msg.tid] = fut
            try:
                await mon.send(msg)
                return await asyncio.wait_for(fut, self.op_timeout)
            finally:
                self._futures.pop(msg.tid, None)

        entity = self.msgr.entity_name
        r1 = await ask(MAuth(self._next_tid(), entity, 1))
        if r1.rc != 0:
            raise RadosError(r1.rc, "auth stage 1 refused")
        client_challenge = auth_mod.new_nonce()
        proof = auth_mod.auth_proof(
            keyring.active_key, entity, client_challenge,
            bytes(r1.server_challenge))
        r2 = await ask(MAuth(self._next_tid(), entity, 2,
                             kid=keyring.active,
                             client_challenge=client_challenge,
                             proof=proof))
        if r2.rc != 0:
            raise RadosError(r2.rc, "auth proof rejected")
        self.msgr.ticket = bytes(r2.ticket)
        return self.msgr.ticket

    # -- mon commands ------------------------------------------------------

    async def mon_command(self, cmd: Dict[str, Any]
                          ) -> Tuple[int, Dict[str, Any]]:
        last: Optional[Exception] = None
        resubscribe = False
        for attempt in range(max(4, 3 * len(self.mon_addrs))):
            tid = self._next_tid()
            fut: asyncio.Future = \
                asyncio.get_running_loop().create_future()
            self._futures[tid] = fut
            try:
                mon = await self.msgr.connect(self.mon_addr)
                if resubscribe:
                    # the dropped connection carried the map
                    # subscription: renew it or map updates silently
                    # stop flowing to this client
                    await mon.send(MGetMap(subscribe=True))
                    resubscribe = False
                await mon.send(MMonCommand(tid, cmd))
                reply = await asyncio.wait_for(fut, self.op_timeout)
                if reply.rc == -11 and "quorum" in str(
                        reply.out.get("error", "")):
                    # election in progress: wait it out and retry
                    # (jittered — every client sees the same election)
                    last = RadosError(-11, str(reply.out))
                    await asyncio.sleep(full_jitter(0.8, attempt,
                                                    cap=4.0))
                    continue
                return reply.rc, reply.out
            except (asyncio.TimeoutError, ConnectionError,
                    OSError) as e:
                # a restarted/dead mon leaves a stale cached connection
                # that may not have seen EOF yet: drop it, hunt to the
                # next mon in the monmap, retry after a jittered beat
                last = e
                self._hunt_mon()
                resubscribe = True
                await asyncio.sleep(full_jitter(0.6, attempt, cap=4.0))
            finally:
                self._futures.pop(tid, None)
        raise RadosError(EAGAIN, f"mon command {cmd!r} failed ({last!r})")

    async def osd_command(self, osd_id: int, cmd: Dict[str, Any]
                          ) -> Tuple[int, Dict[str, Any]]:
        """`ceph tell osd.N <cmd>`: the OSD admin surface over the
        wire (perf dump, dump_pgs, scrub, ...)."""
        osdmap = self.osdmap
        if osdmap is None or not osdmap.is_up(osd_id):
            raise RadosError(ENOENT, f"osd.{osd_id} not up")
        addr = osdmap.osd_addrs.get(osd_id)
        if addr is None:
            raise RadosError(ENOENT, f"osd.{osd_id} has no address")
        last: Optional[Exception] = None
        for attempt in range(2):
            tid = self._next_tid()
            fut: asyncio.Future = \
                asyncio.get_running_loop().create_future()
            self._futures[tid] = fut
            try:
                await self.msgr.send_to(addr, MOSDCommand(tid, cmd))
                reply = await asyncio.wait_for(fut, self.op_timeout)
                return reply.rc, reply.out
            except (asyncio.TimeoutError, ConnectionError, OSError) as e:
                last = e
                await asyncio.sleep(full_jitter(0.4, attempt, cap=2.0))
            finally:
                self._futures.pop(tid, None)
        # same error contract as mon_command/_submit: RadosError, not
        # raw transport exceptions
        raise RadosError(EAGAIN,
                         f"tell osd.{osd_id} {cmd!r} failed ({last!r})")

    async def create_replicated_pool(self, name: str, size: int = 3,
                                     pg_num: int = 32) -> int:
        rc, out = await self.mon_command({
            "prefix": "osd pool create", "name": name,
            "pool_type": "replicated", "size": size, "pg_num": pg_num})
        if rc != 0:
            raise RadosError(rc, str(out))
        await self._wait_for_pool(name)
        return out["pool_id"]

    async def create_ec_pool(self, name: str, profile: Dict[str, str],
                             pg_num: int = 32,
                             profile_name: str = "") -> int:
        profile_name = profile_name or f"{name}_profile"
        rc, out = await self.mon_command({
            "prefix": "osd erasure-code-profile set",
            "name": profile_name, "profile": profile})
        if rc != 0:
            raise RadosError(rc, str(out))
        rc, out = await self.mon_command({
            "prefix": "osd pool create", "name": name,
            "pool_type": "erasure", "erasure_code_profile": profile_name,
            "pg_num": pg_num})
        if rc != 0:
            raise RadosError(rc, str(out))
        await self._wait_for_pool(name)
        return out["pool_id"]

    async def _wait_for_pool(self, name: str) -> None:
        for _ in range(500):
            if self.osdmap is not None and \
                    self.osdmap.lookup_pool(name) >= 0:
                return
            await asyncio.sleep(0.01)
        raise TimeoutError(f"pool {name!r} never appeared in the map")

    def open_ioctx(self, pool_name: str,
                   tenant: str = "") -> "IoCtx":
        pool_id = self.osdmap.lookup_pool(pool_name)
        if pool_id < 0:
            raise KeyError(f"no pool {pool_name!r}")
        io = IoCtx(self, pool_id)
        io.tenant = tenant
        return io

    async def df(self) -> Dict[str, Any]:
        """Cluster + per-pool usage (the librados cluster_stat /
        get_pool_stats roles behind `ceph df` / `rados df`): pulls
        each up OSD's statfs over the tell surface and aggregates.
        Raw bytes are what the stores hold (all copies/chunks);
        logical objects divide the raw head count by the pool's
        replication/stripe width (approximate mid-recovery)."""
        async def one(osd: int):
            # an unreachable OSD degrades the report, never fails it
            try:
                rc, out = await self.osd_command(osd,
                                                 {"prefix": "statfs"})
                return out if rc == 0 else None
            except (RadosError, ConnectionError, OSError,
                    asyncio.TimeoutError):
                return None

        reports = await asyncio.gather(
            *(one(o) for o in self.osdmap.get_up_osds()))
        total = avail = used = 0
        raw: Dict[int, Dict[str, int]] = {}
        for out in reports:
            if out is None:
                continue
            total += int(out.get("total", 0))
            avail += int(out.get("available", 0))
            used += int(out.get("allocated", 0))
            for pid, st in out.get("pools", {}).items():
                agg = raw.setdefault(int(pid),
                                     {"objects": 0, "bytes": 0})
                agg["objects"] += int(st.get("objects", 0))
                agg["bytes"] += int(st.get("bytes", 0))
        pools = []
        # every pool in the map is listed (zeros when no OSD reported
        # it yet), like the reference's `ceph df`
        for pid, pool in sorted(self.osdmap.pools.items()):
            agg = raw.get(pid, {"objects": 0, "bytes": 0})
            width = max(1, getattr(pool, "size", 1))
            pools.append({
                "id": pid, "name": pool.name,
                # ceiling: a degraded pool (fewer copies than size)
                # must not under-count its logical objects to zero
                "objects": -(-agg["objects"] // width),
                "objects_raw": agg["objects"],
                "bytes_used": agg["bytes"]})
        return {"cluster": {"total_bytes": total,
                            "avail_bytes": avail,
                            "used_bytes": used},
                "pools": pools}


class IoCtx:
    """librados::IoCtx over the wire."""

    def __init__(self, client: RadosClient, pool_id: int):
        self.client = client
        self.pool_id = pool_id
        # write-time snap context (librados set_snap_context role) and
        # read-time snap id (snap_set_read role); 0 = head
        self.snapc_seq = 0
        self.snapc_snaps: List[int] = []
        self.read_snap = 0
        # QoS tenant pinned to this IoCtx ("" = inherit the ambient
        # tenant_scope / CURRENT_TENANT)
        self.tenant = ""

    @property
    def pool(self):
        return self.client.osdmap.pools[self.pool_id]

    # -- self-managed snapshots (librados selfmanaged_snap_* roles) --------

    async def create_selfmanaged_snap(self) -> int:
        """Allocate a snap id from the mon and fold it into this
        IoCtx's snap context."""
        rc, out = await self.client.mon_command({
            "prefix": "osd pool mksnap", "name": self.pool.name})
        if rc != 0:
            raise RadosError(rc, str(out))
        snap_id = out["snap_id"]
        self.set_snap_context(snap_id, [snap_id] + self.snapc_snaps)
        return snap_id

    async def remove_selfmanaged_snap(self, snap_id: int) -> None:
        rc, out = await self.client.mon_command({
            "prefix": "osd pool rmsnap", "name": self.pool.name,
            "snap_id": snap_id})
        if rc != 0:
            raise RadosError(rc, str(out))
        self.set_snap_context(
            self.snapc_seq,
            [s for s in self.snapc_snaps if s != snap_id])

    def set_snap_context(self, seq: int, snaps: List[int]) -> None:
        self.snapc_seq = seq
        self.snapc_snaps = sorted(snaps, reverse=True)

    def snap_set_read(self, snap_id: int) -> None:
        """Subsequent reads resolve at this snap (0 = head)."""
        self.read_snap = snap_id

    def object_pg(self, name: str) -> PgId:
        ps = ceph_str_hash_rjenkins(name.encode())
        return self.pool.raw_pg_to_pg(PgId(self.pool_id, ps))

    # -- op submission (Objecter::_op_submit + resend discipline) ----------

    async def _submit(self, oid: str, ops: List[OSDOp]) -> MOSDOpReply:
        client = self.client
        last_error: Optional[Exception] = None
        # ONE tid for the op's whole lifetime: a resend after a lost
        # reply carries the same reqid, so the primary's dedup cache
        # can replay the stored reply instead of re-executing a
        # non-idempotent op (append, exec) — the osd_reqid_t
        # discipline (PrimaryLogPG check_in_progress_op)
        tid = client._next_tid()
        span = None
        owned = False  # root span in this client's tracer ring
        if client.trace_all:
            span = client.tracer.start(
                f"{'+'.join(op.op for op in ops)} {oid}")
            owned = True
        else:
            # ambient trace (an S3 frontend's ingress span, or any
            # caller running under tracing.current_span): the rados
            # submit becomes a child stage in THAT tree, and the op
            # carries its context to the OSDs
            from ceph_tpu.common import tracing

            parent = tracing.current_span.get()
            if parent is not None and parent:
                span = parent.child(
                    f"rados {'+'.join(op.op for op in ops)} {oid}")
        try:
            return await self._submit_traced(oid, ops, tid, span)
        finally:
            if span is not None:
                if owned:
                    client.tracer.finish(span)
                else:
                    span.finish()

    async def _submit_traced(self, oid: str, ops: List[OSDOp],
                             tid: int, span) -> MOSDOpReply:
        client = self.client
        last_error: Optional[Exception] = None
        for attempt in range(client.max_retries):
            osdmap = client.osdmap
            # placement recomputed per attempt: a pg_num split between
            # retries remaps the object to a CHILD pg, and the primary
            # bounces misdirected ops with EAGAIN until we follow
            pg = self.object_pg(oid)
            primary = client._primary_cached(osdmap, pg)
            addr = osdmap.osd_addrs.get(primary, None) \
                if primary >= 0 else None
            if addr is None or not osdmap.is_up(primary):
                await client.wait_for_new_map(1.0)
                continue
            fut: asyncio.Future = \
                asyncio.get_running_loop().create_future()
            client._futures[tid] = fut
            tenant = self.tenant or CURRENT_TENANT.get()
            qos_delta = qos_rho = 1
            if tenant and flags.enabled("CEPH_TPU_DMCLOCK"):
                qos_delta, qos_rho = \
                    client.qos_tracker.obtain(tenant, primary)
            try:
                msg = MOSDOp(tid, client.msgr.entity_name, pg, oid,
                             ops, osdmap.epoch,
                             snapc_seq=self.snapc_seq,
                             snapc_snaps=self.snapc_snaps,
                             snap_id=self.read_snap,
                             tenant=tenant,
                             qos_delta=qos_delta,
                             qos_rho=qos_rho)
                if span is not None:
                    # propagation follows the sampling decision: an
                    # unsampled ambient trace (gateway sampling off)
                    # must leave the OSD to its own
                    # osd_trace_sample_rate instead of forcing the
                    # whole downstream tree retained
                    if span.sampled:
                        msg.trace = span.context
                    span.event(f"sent to osd.{primary}"
                               + (f" (retry {attempt})" if attempt
                                  else ""))
                await client.msgr.send_to(addr, msg)
                reply = await asyncio.wait_for(fut, client.op_timeout)
                if span is not None:
                    span.event("reply")
            except (ConnectionError, OSError) as e:
                last_error = e
                client._futures.pop(tid, None)
                await client.refresh_map()
                continue
            except asyncio.TimeoutError as e:
                last_error = e
                client._futures.pop(tid, None)
                await client.refresh_map()
                continue
            if reply.rc == EAGAIN:
                # wrong/new primary or pg not active: wait for progress.
                # The floor sleep matters: during bring-up/peering churn
                # maps arrive continuously, and without it the retry
                # budget burns in milliseconds while PGs are still
                # peering (Objecter's backoff discipline).  Jittered:
                # a cluster-wide bounce must not resynchronize every
                # client's resend onto the same instant.
                await client.wait_for_new_map(0.5)
                await asyncio.sleep(0.05 + full_jitter(0.2, 0))
                continue
            if tenant and getattr(reply, "qos_phase", ""):
                # a scheduled completion: feeds the NEXT op's
                # delta/rho (EAGAIN bounces above never reached the
                # scheduler and carry no phase)
                client.qos_tracker.note_reply(
                    tenant, primary, reply.qos_phase)
            return reply
        raise RadosError(EAGAIN, f"op on {oid!r} exhausted retries"
                                 f" ({last_error!r})")

    # -- public API --------------------------------------------------------

    async def write_full(self, oid: str, data: bytes) -> Dict[str, Any]:
        """Returns the op's out map — for EC pools it carries
        {"data_crc": crc32c of the written bytes}, the OSD-computed
        content digest (librados returnvec role)."""
        reply = await self._submit(oid, [OSDOp("write_full", data=data)])
        if reply.rc != 0:
            raise RadosError(reply.rc, f"write_full {oid!r}")
        return reply.out or {}

    async def write(self, oid: str, data: bytes, offset: int) -> None:
        reply = await self._submit(
            oid, [OSDOp("write", offset=offset, data=data)])
        if reply.rc != 0:
            raise RadosError(reply.rc, f"write {oid!r}@{offset}")

    async def read(self, oid: str, offset: int = 0,
                   length: int = 0) -> bytes:
        reply = await self._submit(
            oid, [OSDOp("read", offset=offset, length=length)])
        if reply.rc == ENOENT:
            raise ObjectNotFound(reply.rc, oid)
        if reply.rc != 0:
            raise RadosError(reply.rc, f"read {oid!r}")
        # local-fastpath replies carry zero-copy views of the OSD's
        # buffers; the public API hands out real bytes (callers
        # json-decode, hash, and cache them).  Wire replies decode to
        # bytes already, so this materializes nothing there.
        data = reply.data
        return data if isinstance(data, bytes) else bytes(data)

    async def stat(self, oid: str) -> Dict[str, Any]:
        reply = await self._submit(oid, [OSDOp("stat")])
        if reply.rc == ENOENT:
            raise ObjectNotFound(reply.rc, oid)
        if reply.rc != 0:
            raise RadosError(reply.rc, f"stat {oid!r}")
        return reply.out

    async def remove(self, oid: str) -> None:
        reply = await self._submit(oid, [OSDOp("remove")])
        if reply.rc == ENOENT:
            raise ObjectNotFound(reply.rc, oid)
        if reply.rc != 0:
            raise RadosError(reply.rc, f"remove {oid!r}")

    async def append(self, oid: str, data: bytes) -> None:
        reply = await self._submit(oid, [OSDOp("append", data=data)])
        if reply.rc != 0:
            raise RadosError(reply.rc, f"append {oid!r}")

    # -- xattrs ------------------------------------------------------------

    async def execute(self, oid: str, cls: str, method: str,
                      data: bytes = b"") -> bytes:
        """Run an object-class method server-side (rados_exec role).
        Returns the method's output bytes; errors raise RadosError
        with the method's rc."""
        reply = await self._submit(
            oid, [OSDOp("call", data=data,
                        args={"cls": cls, "method": method})])
        if reply.rc == ENOENT:
            raise ObjectNotFound(reply.rc, oid)
        if reply.rc != 0:
            raise RadosError(reply.rc, f"exec {cls}.{method} on {oid!r}")
        data = reply.data
        return data if isinstance(data, bytes) else bytes(data)

    # -- coded compute (scan/aggregate/score pushdown) ---------------------

    async def compute(self, kernel: str, oids: List[str],
                      args: Optional[Dict[str, Any]] = None,
                      wave: int = 1024
                      ) -> Tuple[Dict[str, bytes], Dict[str, int]]:
        """Run a registered compute kernel over many objects WHERE
        THEY LIVE (MOSDCompute, ceph_tpu/compute): one SET-valued op
        per primary per wave, only kernel results come back.  Returns
        ({oid: result bytes}, {oid: rc}) — partial results survive
        per-object errors, the scan-shaped contract.

        Kill switch CEPH_TPU_COMPUTE=0 falls back to client-side
        read-then-compute with the same kernel reference
        implementations — bit-identical results, every payload byte
        over the wire (the parity leg the tests drive)."""
        from ceph_tpu import compute as compute_mod

        if not compute_mod.env_enabled():
            return await self._compute_client_side(kernel, oids, args)
        import json as _json

        client = self.client
        args_raw = _json.dumps(args, sort_keys=True) if args else ""
        results: Dict[str, bytes] = {}
        errors: Dict[str, int] = {}
        pending = list(dict.fromkeys(oids))
        for attempt in range(client.max_retries):
            if not pending:
                break
            osdmap = client.osdmap
            by_primary: Dict[str, List[str]] = {}
            next_pending: List[str] = []
            for oid in pending:
                pg = self.object_pg(oid)
                primary = client._primary_cached(osdmap, pg)
                addr = osdmap.osd_addrs.get(primary) \
                    if primary >= 0 and osdmap.is_up(primary) else None
                if addr is None:
                    next_pending.append(oid)
                    continue
                by_primary.setdefault(addr, []).append(oid)
            sem = asyncio.Semaphore(8)

            async def one_wave(addr: str, part: List[str]) -> None:
                async with sem:
                    tid = client._next_tid()
                    fut: asyncio.Future = \
                        asyncio.get_running_loop().create_future()
                    client._futures[tid] = fut
                    try:
                        await client.msgr.send_to(addr, MOSDCompute(
                            tid, client.msgr.entity_name,
                            self.pool_id, part, kernel, args_raw,
                            osdmap.epoch,
                            tenant=self.tenant
                            or CURRENT_TENANT.get()))
                        # a scan wave legitimately outlives a single
                        # op's budget: scale the wait with the wave
                        reply = await asyncio.wait_for(
                            fut, client.op_timeout
                            + len(part) / 100.0)
                    except (ConnectionError, OSError,
                            asyncio.TimeoutError):
                        next_pending.extend(part)
                        await client.refresh_map()
                        return
                    finally:
                        client._futures.pop(tid, None)
                    if reply.rc == EAGAIN:
                        next_pending.extend(part)
                        return
                    if reply.rc != 0:
                        for oid in part:
                            errors[oid] = reply.rc
                        return
                    for oid in part:
                        rc, data = reply.results.get(oid, (EAGAIN,
                                                           b""))
                        if rc == 0:
                            results[oid] = data if isinstance(
                                data, bytes) else bytes(data)
                        elif rc == EAGAIN:
                            next_pending.append(oid)
                        else:
                            errors[oid] = rc

            # waves fly concurrently (bounded): the scan is one
            # logical op — it must not serialize on primary count or
            # wave count
            await asyncio.gather(*(
                one_wave(addr, batch[lo:lo + wave])
                for addr, batch in by_primary.items()
                for lo in range(0, len(batch), wave)))
            if next_pending:
                await client.wait_for_new_map(0.5)
                await asyncio.sleep(0.05 + full_jitter(0.2, 0))
            pending = next_pending
        for oid in pending:
            errors.setdefault(oid, EAGAIN)
        return results, errors

    async def _compute_client_side(self, kernel: str,
                                   oids: List[str],
                                   args: Optional[Dict[str, Any]]
                                   ) -> Tuple[Dict[str, bytes],
                                              Dict[str, int]]:
        """CEPH_TPU_COMPUTE=0: read every object and evaluate the
        kernel locally — the bit-exact parity oracle for the pushdown
        path (and its bytes-moved foil in the bench)."""
        from ceph_tpu import compute as compute_mod
        from ceph_tpu.osd.osdmap import TYPE_ERASURE

        kern = compute_mod.get_kernel(kernel)
        if kern is None:
            raise RadosError(-22, f"unknown kernel {kernel!r}")
        kargs = args or {}
        try:
            kern.validate_args(kargs)
        except compute_mod.ComputeError as e:
            raise RadosError(e.rc, str(e))
        pool = self.pool
        k, chunk = 1, 0
        if pool.type == TYPE_ERASURE:
            from ceph_tpu.ec.registry import create_erasure_code

            profile = self.client.osdmap.erasure_code_profiles[
                pool.erasure_code_profile]
            codec = create_erasure_code(dict(profile))
            k = codec.get_data_chunk_count()
            # default osd_pool_erasure_code_stripe_unit (the linear
            # kernels' striping parameter; clusters overriding it
            # must scan with CEPH_TPU_COMPUTE=1)
            chunk = codec.get_chunk_size(k * 4096)
        results: Dict[str, bytes] = {}
        errors: Dict[str, int] = {}
        sem = asyncio.Semaphore(16)

        async def one(oid: str) -> None:
            async with sem:
                try:
                    data = await self.read(oid)
                except ObjectNotFound:
                    errors[oid] = ENOENT
                    return
                except RadosError as e:
                    errors[oid] = e.rc
                    return
            try:
                results[oid] = kern.reference(data, kargs, k, chunk)
            except compute_mod.ComputeError as e:
                errors[oid] = e.rc

        await asyncio.gather(*(one(oid)
                               for oid in dict.fromkeys(oids)))
        return results, errors

    # -- coded inference serving (Fisher-fused approximate scoring) --------

    async def store_model(self, name: str, kind: str, params,
                          m: int = 1, fisher_info=None
                          ) -> Dict[str, Any]:
        """Shard + Fisher-fuse a model into THIS EC pool's stripe
        geometry (ceph_tpu/inference/registry): the pool's k data
        chunks carry the k_model = k_pool - m data parameter shards
        plus the m fused shards, and the manifest object carries the
        calibrated spec.  Returns the spec."""
        from ceph_tpu.ec.registry import create_erasure_code
        from ceph_tpu.inference import registry as inf_registry
        from ceph_tpu.osd.osdmap import TYPE_ERASURE

        pool = self.pool
        if pool.type != TYPE_ERASURE:
            raise RadosError(-22, "store_model needs an EC pool")
        profile = self.client.osdmap.erasure_code_profiles[
            pool.erasure_code_profile]
        codec = create_erasure_code(dict(profile))
        k_pool = codec.get_data_chunk_count()
        if not 0 < m < k_pool:
            raise RadosError(-22, f"bad fused-shard count m={m}")
        chunk = codec.get_chunk_size(k_pool * 4096)
        spec, blobs = inf_registry.build(
            name, kind, params, k_pool - m, m, chunk,
            fisher_info=fisher_info)
        for oid, blob in blobs.items():
            await self.write_full(oid, blob)
        return spec

    async def load_model(self, name: str) -> Dict[str, Any]:
        """Read + cache a stored model's manifest (the spec rides
        every query's args, so the cache makes a query one round
        trip, not two)."""
        cache = getattr(self, "_model_cache", None)
        if cache is None:
            cache = self._model_cache = {}
        spec = cache.get(name)
        if spec is None:
            import json as _json

            from ceph_tpu.inference import registry as inf_registry

            spec = _json.loads(
                await self.read(inf_registry.manifest_oid(name)))
            cache[name] = spec
        return spec

    async def infer(self, name, queries, exact: bool = False,
                    budget: Optional[float] = None
                    ) -> Dict[str, Any]:
        """Score a query batch against a stored model THROUGH the
        code (MOSDCompute `infer`): per-shard forward passes run on
        the OSDs holding the serving streams, the primary combines
        the first sufficient arrival set (Fisher-averaged when fused
        shards substitute for stragglers), and the per-query error
        budget — `osd_inference_error_budget` when None — gates every
        approximate result.  exact=True demands the bit-exact
        full-decode path.  Returns the decoded result dict:
        scores (nq x out float32), mode, est_error, substituted.

        Kill switch CEPH_TPU_INFERENCE=0 falls back to client-side
        read-then-infer with the same host reference forward —
        bit-identical result bytes, every parameter byte over the
        wire (the parity leg tests/test_inference.py drives)."""
        from ceph_tpu import inference as inf_mod
        from ceph_tpu.inference import kernels as inf_kernels
        from ceph_tpu.inference import model as inf_model

        spec = name if isinstance(name, dict) \
            else await self.load_model(name)
        try:
            inf_model.validate_spec(spec)
        except ValueError as e:
            raise RadosError(-22, str(e))
        if not inf_mod.env_enabled():
            return await self._infer_client_side(spec, queries)
        args: Dict[str, Any] = {
            "model": spec,
            "q": inf_kernels.encode_queries(queries),
        }
        if exact:
            args["exact"] = True
        if budget is not None:
            args["budget"] = float(budget)
        oid = spec["params_oid"]
        results, errors = await self.compute(
            inf_mod.INFER_KERNEL, [oid], args)
        if oid not in results:
            raise RadosError(errors.get(oid, EAGAIN),
                             f"infer {spec.get('name')!r}")
        return inf_kernels.decode_result(results[oid])

    async def _infer_client_side(self, spec: Dict[str, Any],
                                 queries) -> Dict[str, Any]:
        """CEPH_TPU_INFERENCE=0: read the whole params object and run
        the host reference forward — the same exact_forward + blob
        the engine's exact fallback uses, so the result bytes are
        bit-identical to exact=True serving."""
        from ceph_tpu.inference import kernels as inf_kernels
        from ceph_tpu.inference import model as inf_model

        data = await self.read(spec["params_oid"])
        scores = inf_model.exact_forward(spec, data, queries)
        return inf_kernels.decode_result(
            inf_kernels.result_blob(scores, "exact", 0.0, 0))

    async def setxattr(self, oid: str, name: str, value: bytes) -> None:
        reply = await self._submit(
            oid, [OSDOp("setxattr", data=value, args={"name": name})])
        if reply.rc != 0:
            raise RadosError(reply.rc, f"setxattr {oid!r}.{name}")

    async def rmxattr(self, oid: str, name: str) -> None:
        reply = await self._submit(
            oid, [OSDOp("rmxattr", args={"name": name})])
        if reply.rc != 0:
            raise RadosError(reply.rc, f"rmxattr {oid!r}.{name}")

    async def getxattr(self, oid: str, name: str) -> bytes:
        reply = await self._submit(
            oid, [OSDOp("getxattr", args={"name": name})])
        if reply.rc != 0:
            raise RadosError(reply.rc, f"getxattr {oid!r}.{name}")
        return reply.data

    async def getxattrs(self, oid: str) -> Dict[str, bytes]:
        reply = await self._submit(oid, [OSDOp("getxattrs")])
        if reply.rc != 0:
            raise RadosError(reply.rc, f"getxattrs {oid!r}")
        return {k: v.encode("latin-1")
                for k, v in reply.out.get("xattrs", {}).items()}

    # -- omap (replicated pools only, like the reference) ------------------

    async def omap_set(self, oid: str,
                       kv: Dict[str, bytes]) -> None:
        reply = await self._submit(
            oid, [OSDOp("omap_set", data=encode_kv_map(kv))])
        if reply.rc != 0:
            raise RadosError(reply.rc, f"omap_set {oid!r}")

    async def omap_rm_keys(self, oid: str, keys: List[str]) -> None:
        reply = await self._submit(
            oid, [OSDOp("omap_rm", data=encode_str_list(keys))])
        if reply.rc != 0:
            raise RadosError(reply.rc, f"omap_rm {oid!r}")

    async def omap_get(self, oid: str) -> Dict[str, bytes]:
        reply = await self._submit(oid, [OSDOp("omap_get")])
        if reply.rc == ENOENT:
            raise ObjectNotFound(reply.rc, oid)
        if reply.rc != 0:
            raise RadosError(reply.rc, f"omap_get {oid!r}")
        return decode_kv_map(reply.data) if reply.data else {}

    # -- watch / notify ----------------------------------------------------

    async def watch(self, oid: str, callback) -> int:
        """Register a watch; callback(payload: bytes) fires on every
        notify.  Returns the watch cookie (linger op role — the client
        re-registers automatically when the map changes)."""
        cookie = self.client._next_watch_cookie()
        self.client._watches[(self.pool_id, oid, cookie)] = \
            (self, callback)
        reply = await self._submit(
            oid, [OSDOp("watch", args={"cookie": cookie})])
        if reply.rc != 0:
            self.client._watches.pop((self.pool_id, oid, cookie), None)
            raise RadosError(reply.rc, f"watch {oid!r}")
        self.client._ensure_watch_keepalive()
        return cookie

    async def unwatch(self, oid: str, cookie: int) -> None:
        self.client._watches.pop((self.pool_id, oid, cookie), None)
        reply = await self._submit(
            oid, [OSDOp("watch", args={"cookie": cookie,
                                       "unwatch": True})])
        if reply.rc != 0:
            raise RadosError(reply.rc, f"unwatch {oid!r}")

    async def notify(self, oid: str,
                     payload: bytes = b"") -> Dict[str, Any]:
        """Fire a notify; returns {"acked": [...], "missed": [...]}."""
        reply = await self._submit(
            oid, [OSDOp("notify", data=payload)])
        if reply.rc != 0:
            raise RadosError(reply.rc, f"notify {oid!r}")
        return reply.out

    async def list_objects(self) -> List[str]:
        """pgls across every PG of the pool (ListObjects role)."""
        names: set = set()
        seen_pgs: set = set()
        for ps in range(self.pool.pg_num):
            pg = self.pool.raw_pg_to_pg(PgId(self.pool_id, ps))
            if pg in seen_pgs:
                continue
            seen_pgs.add(pg)
            client = self.client
            for attempt in range(client.max_retries):
                osdmap = client.osdmap
                _a, primary = osdmap.pg_to_acting_osds(pg)
                addr = osdmap.osd_addrs.get(primary) \
                    if primary >= 0 and osdmap.is_up(primary) else None
                if addr is None:
                    await client.wait_for_new_map(1.0)
                    continue
                tid = client._next_tid()
                fut: asyncio.Future = \
                    asyncio.get_running_loop().create_future()
                client._futures[tid] = fut
                try:
                    await client.msgr.send_to(
                        addr, MOSDOp(tid, client.msgr.entity_name, pg,
                                     "", [OSDOp("pgls")], osdmap.epoch))
                    reply = await asyncio.wait_for(fut,
                                                   client.op_timeout)
                except (ConnectionError, OSError, asyncio.TimeoutError):
                    client._futures.pop(tid, None)
                    await client.refresh_map()
                    continue
                if reply.rc == EAGAIN:
                    await client.wait_for_new_map(0.5)
                    continue
                if reply.rc == 0:
                    names.update(reply.out.get("objects", []))
                break
        return sorted(names)

"""Embedded single-process RADOS: the end-to-end storage slice.

SURVEY.md §7 step 6 — every layer below the wire, in one process:
`put(obj)` hashes the name onto a PG (ceph_str_hash_rjenkins, the
hobject_t hash), CRUSH places the PG's acting set, the object stripes
through ECUtil, the TPU encodes all stripes in one batched GF matmul,
and each shard lands in its OSD's ObjectStore with the cumulative-crc
HashInfo ledger in an xattr (the hinfo_key of ECBackend).  `get` reads
any k shards — reconstructing through minimum_to_decode + the TPU decode
path when shards are lost or fail their checksums.  Deep scrub re-hashes
every shard against its ledger (ECBackend::be_deep_scrub); repair
re-encodes and rewrites bad shards (RecoveryOp).

The multi-process RADOS-lite daemons reuse these PG-level paths; this
module is also the reference harness for BASELINE config #5's object
write shape.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from ceph_tpu.crush.map import CRUSH_ITEM_NONE
from ceph_tpu.ec.registry import create_erasure_code
from ceph_tpu.ops.rjenkins import ceph_str_hash_rjenkins
from ceph_tpu.os import ObjectId, ObjectStore, Transaction
from ceph_tpu.os.memstore import MemStore
from ceph_tpu.osd import ec_util
from ceph_tpu.osd.osdmap import (
    CEPH_OSD_UP,
    Incremental,
    OSDMap,
    PgId,
    TYPE_ERASURE,
    TYPE_REPLICATED,
)

OI_ATTR = "_"            # object_info_t xattr key
HINFO_ATTR = ec_util.HINFO_KEY
SS_ATTR = "snapset"      # SnapSet xattr key (SS_ATTR role)


def shard_collection(pg: PgId, shard: int) -> str:
    """cid for a PG shard (spg_t: `<pool>.<ps>s<shard>_head`)."""
    return f"{pg.pool}.{pg.ps:x}s{shard}_head" if shard >= 0 else \
        f"{pg.pool}.{pg.ps:x}_head"


class LocalCluster:
    """N ObjectStores + an OSDMap, no networking."""

    def __init__(self, num_osds: int = 6, osds_per_host: int = 2,
                 store_path: Optional[str] = None, config=None):
        self.osdmap = OSDMap.build_simple(num_osds,
                                          osds_per_host=osds_per_host)
        # the embedded cluster mutates its map only through
        # apply_incremental (mark_osd_down/up), so the per-epoch
        # placement memo is safe — and the open-loop load harness
        # issues enough ops that an uncached CRUSH walk per op would
        # measure the mapper, not the store
        self.osdmap.enable_placement_cache()
        self.stores: Dict[int, ObjectStore] = {}
        self._codecs: Dict[int, object] = {}
        self._stripe_unit = 4096  # osd_pool_erasure_code_stripe_unit
        if config is not None:
            self._stripe_unit = int(
                config.get("osd_pool_erasure_code_stripe_unit"))
        for osd in range(num_osds):
            if store_path is None:
                store: ObjectStore = MemStore()
            else:
                from ceph_tpu.os.tpustore import TPUStore

                store = TPUStore(f"{store_path}/osd.{osd}", config=config)
            store.mkfs()
            store.mount()
            self.stores[osd] = store

    def shutdown(self) -> None:
        for store in self.stores.values():
            store.umount()

    # -- pool management ---------------------------------------------------

    def create_replicated_pool(self, name: str, size: int = 3,
                               pg_num: int = 32):
        return self.osdmap.create_pool(name, size=size, pg_num=pg_num)

    def create_erasure_pool(self, name: str, profile: Dict[str, str],
                            pg_num: int = 32,
                            profile_name: Optional[str] = None):
        """EC-profile flow of OSDMonitor.cc:7373-7712: store the profile in
        the map, build the codec, create its crush rule, create the pool."""
        profile = dict(profile)
        profile_name = profile_name or f"{name}_profile"
        codec = create_erasure_code(profile)
        self.osdmap.erasure_code_profiles[profile_name] = profile
        ruleno = codec.create_rule(f"{name}_rule", self.osdmap.crush)
        assert ruleno >= 0
        pool = self.osdmap.create_pool(
            name, type_=TYPE_ERASURE, size=codec.get_chunk_count(),
            pg_num=pg_num, crush_rule=ruleno,
            erasure_code_profile=profile_name)
        self._codecs[pool.id] = codec
        return pool

    def _codec(self, pool_id: int):
        codec = self._codecs.get(pool_id)
        if codec is None:
            pool = self.osdmap.pools[pool_id]
            profile = self.osdmap.erasure_code_profiles[
                pool.erasure_code_profile]
            codec = create_erasure_code(dict(profile))
            self._codecs[pool_id] = codec
        return codec

    def open_ioctx(self, pool_name: str) -> "IoCtx":
        pool_id = self.osdmap.lookup_pool(pool_name)
        if pool_id < 0:
            raise KeyError(f"no pool {pool_name!r}")
        return IoCtx(self, pool_id)

    # -- failure injection -------------------------------------------------

    def mark_osd_down(self, osd: int) -> None:
        inc = Incremental(epoch=self.osdmap.epoch + 1)
        inc.new_state[osd] = CEPH_OSD_UP
        self.osdmap.apply_incremental(inc)

    def mark_osd_up(self, osd: int) -> None:
        if self.osdmap.is_down(osd):
            inc = Incremental(epoch=self.osdmap.epoch + 1)
            inc.new_state[osd] = CEPH_OSD_UP
            self.osdmap.apply_incremental(inc)


class IoCtx:
    """librados::IoCtx shape over the embedded cluster."""

    def __init__(self, cluster: LocalCluster, pool_id: int):
        self.cluster = cluster
        self.pool_id = pool_id

    @property
    def pool(self):
        return self.cluster.osdmap.pools[self.pool_id]

    # -- placement ---------------------------------------------------------

    def object_pg(self, name: str) -> PgId:
        ps = ceph_str_hash_rjenkins(name.encode())
        return self.pool.raw_pg_to_pg(PgId(self.pool_id, ps))

    def acting(self, pg: PgId) -> Tuple[List[int], int]:
        return self.cluster.osdmap.pg_to_acting_osds(pg)

    # -- EC helpers --------------------------------------------------------

    def _sinfo(self, codec) -> ec_util.StripeInfo:
        k = codec.get_data_chunk_count()
        unit = codec.get_chunk_size(k * self.cluster._stripe_unit)
        return ec_util.StripeInfo(k, k * unit)

    # -- write -------------------------------------------------------------

    def write_full(self, name: str, data: bytes) -> None:
        pg = self.object_pg(name)
        acting, _primary = self.acting(pg)
        if self.pool.type == TYPE_REPLICATED:
            oi = json.dumps({"size": len(data)}).encode()
            for osd in acting:
                if osd == CRUSH_ITEM_NONE:
                    continue
                store = self.cluster.stores[osd]
                cid = shard_collection(pg, -1)
                t = Transaction()
                if not store.collection_exists(cid):
                    t.create_collection(cid)
                oid = ObjectId(name)
                t.truncate(cid, oid, 0)
                t.write(cid, oid, 0, len(data), data)
                t.setattr(cid, oid, OI_ATTR, oi)
                store.queue_transaction(t)
            return

        codec = self.cluster._codec(self.pool_id)
        sinfo = self._sinfo(codec)
        width = sinfo.get_stripe_width()
        padded = data + bytes(-len(data) % width)
        shards = ec_util.encode(sinfo, codec, padded,
                                range(codec.get_chunk_count()))
        hinfo = ec_util.HashInfo(codec.get_chunk_count())
        hinfo.append(0, shards)
        oi = json.dumps({"size": len(data)}).encode()
        hinfo_raw = json.dumps(hinfo.to_dict()).encode()
        for shard, osd in enumerate(acting):
            if osd == CRUSH_ITEM_NONE:
                continue
            store = self.cluster.stores[osd]
            cid = shard_collection(pg, shard)
            t = Transaction()
            if not store.collection_exists(cid):
                t.create_collection(cid)
            oid = ObjectId(name)
            t.truncate(cid, oid, 0)
            buf = shards.get(shard, b"")  # zero-length object: no chunks
            t.write(cid, oid, 0, len(buf), buf)
            t.setattr(cid, oid, OI_ATTR, oi)
            t.setattr(cid, oid, HINFO_ATTR, hinfo_raw)
            store.queue_transaction(t)

    # -- read --------------------------------------------------------------

    def _gather_shards(self, name: str, pg: PgId, acting: List[int],
                       verify: bool = True
                       ) -> Tuple[Dict[int, bytes], Optional[int], dict]:
        """Read every reachable shard; returns (shards, size, hinfo)."""
        shards: Dict[int, bytes] = {}
        size: Optional[int] = None
        hinfo: dict = {}
        for shard, osd in enumerate(acting):
            if osd == CRUSH_ITEM_NONE or \
                    self.cluster.osdmap.is_down(osd):
                continue
            store = self.cluster.stores[osd]
            cid = shard_collection(pg, shard)
            oid = ObjectId(name)
            try:
                buf = store.read(cid, oid)
                oi = json.loads(store.getattr(cid, oid, OI_ATTR))
                hi = json.loads(store.getattr(cid, oid, HINFO_ATTR))
            except (KeyError, IOError, ValueError):
                continue  # missing or failed csum -> treat as erasure
            if verify:
                # hinfo cumulative crc check (handle_sub_read,
                # ECBackend.cc:1010): shard bytes must match the ledger
                ledger = ec_util.HashInfo.from_dict(hi)
                import ceph_tpu.ops.checksum as cks

                if ledger.has_chunk_hash() and cks.crc32c(
                        0xFFFFFFFF, buf) != ledger.get_chunk_hash(shard):
                    continue  # corrupt shard -> erasure
            shards[shard] = buf
            size = oi["size"]
            hinfo = hi
        return shards, size, hinfo

    def read(self, name: str, offset: int = 0,
             length: int = 0) -> bytes:
        """Full read, or a ranged read when offset/length given
        (length 0 = to the end) — the librados read(off, len) shape
        the load harness's ranged-GET blend drives."""
        pg = self.object_pg(name)
        acting, _primary = self.acting(pg)
        if self.pool.type == TYPE_REPLICATED:
            for osd in acting:
                if osd == CRUSH_ITEM_NONE or \
                        self.cluster.osdmap.is_down(osd):
                    continue
                store = self.cluster.stores[osd]
                try:
                    cid = shard_collection(pg, -1)
                    data = store.read(cid, ObjectId(name))
                    oi = json.loads(store.getattr(cid, ObjectId(name),
                                                  OI_ATTR))
                    return self._slice(data[:oi["size"]], offset,
                                       length)
                except (KeyError, IOError):
                    continue
            raise KeyError(name)

        codec = self.cluster._codec(self.pool_id)
        sinfo = self._sinfo(codec)
        shards, size, _hinfo = self._gather_shards(name, pg, acting)
        if size is None:
            raise KeyError(name)
        k = codec.get_data_chunk_count()
        # data positions honor the chunk mapping
        # (get_want_to_read_shards, ECBackend.cc:2380)
        want = {codec.chunk_index(i) for i in range(k)}
        # plan the read like objects_read_and_reconstruct: which shards
        # do we need, given what's available?
        minimum = codec.minimum_to_decode(want, set(shards))
        use = {s: shards[s] for s in minimum if s in shards}
        data = ec_util.decode(sinfo, codec, use)
        return self._slice(data[:size], offset, length)

    @staticmethod
    def _slice(data: bytes, offset: int, length: int) -> bytes:
        if offset <= 0 and length <= 0:
            return data
        end = offset + length if length > 0 else len(data)
        return data[max(offset, 0):end]

    def stat(self, name: str) -> Dict[str, int]:
        pg = self.object_pg(name)
        acting, _primary = self.acting(pg)
        shard = -1 if self.pool.type == TYPE_REPLICATED else 0
        for s, osd in enumerate(acting):
            if osd == CRUSH_ITEM_NONE or self.cluster.osdmap.is_down(osd):
                continue
            cid = shard_collection(pg, shard if shard < 0 else s)
            try:
                oi = json.loads(self.cluster.stores[osd].getattr(
                    cid, ObjectId(name), OI_ATTR))
                return {"size": oi["size"]}
            except (KeyError, IOError):
                continue
        raise KeyError(name)

    def remove(self, name: str) -> None:
        pg = self.object_pg(name)
        acting, _primary = self.acting(pg)
        for shard, osd in enumerate(acting):
            if osd == CRUSH_ITEM_NONE:
                continue
            sh = -1 if self.pool.type == TYPE_REPLICATED else shard
            t = Transaction()
            t.remove(shard_collection(pg, sh), ObjectId(name))
            try:
                self.cluster.stores[osd].queue_transaction(t)
            except KeyError:
                pass

    def list_objects(self) -> List[str]:
        names = set()
        for pool_pg in range(self.pool.pg_num):
            pg = PgId(self.pool_id, pool_pg)
            acting, _p = self.acting(pg)
            for shard, osd in enumerate(acting):
                if osd == CRUSH_ITEM_NONE or \
                        self.cluster.osdmap.is_down(osd):
                    continue
                sh = -1 if self.pool.type == TYPE_REPLICATED else shard
                cid = shard_collection(pg, sh)
                store = self.cluster.stores[osd]
                if cid in store.list_collections():
                    names.update(str(o) for o in store.list_objects(cid))
        return sorted(names)

    # -- scrub / repair (be_deep_scrub + RecoveryOp) -----------------------

    def deep_scrub(self, name: str) -> List[Tuple[int, str]]:
        """Re-hash every shard against the hinfo ledger; returns
        [(shard, problem)] inconsistencies."""
        import ceph_tpu.ops.checksum as cks

        pg = self.object_pg(name)
        acting, _primary = self.acting(pg)
        problems: List[Tuple[int, str]] = []
        if self.pool.type == TYPE_REPLICATED:
            copies = {}
            for osd in acting:
                if osd == CRUSH_ITEM_NONE or \
                        self.cluster.osdmap.is_down(osd):
                    continue
                try:
                    copies[osd] = self.cluster.stores[osd].read(
                        shard_collection(pg, -1), ObjectId(name))
                except (KeyError, IOError) as e:
                    problems.append((osd, f"unreadable: {e}"))
            digests = {osd: cks.crc32c(0xFFFFFFFF, c)
                       for osd, c in copies.items()}
            if len(set(digests.values())) > 1:
                problems.append((-1, f"digest mismatch: {digests}"))
            return problems
        for shard, osd in enumerate(acting):
            if osd == CRUSH_ITEM_NONE or self.cluster.osdmap.is_down(osd):
                problems.append((shard, "shard unavailable"))
                continue
            store = self.cluster.stores[osd]
            cid = shard_collection(pg, shard)
            oid = ObjectId(name)
            try:
                buf = store.read(cid, oid)
                hi = ec_util.HashInfo.from_dict(
                    json.loads(store.getattr(cid, oid, HINFO_ATTR)))
            except (KeyError, IOError, ValueError) as e:
                problems.append((shard, f"unreadable: {e}"))
                continue
            if hi.has_chunk_hash() and cks.crc32c(
                    0xFFFFFFFF, buf) != hi.get_chunk_hash(shard):
                problems.append((shard, "hinfo crc mismatch"))
        return problems

    def repair(self, name: str) -> List[int]:
        """Reconstruct and rewrite bad/missing shards; returns repaired
        shard ids (the RecoveryOp role)."""
        pg = self.object_pg(name)
        acting, _primary = self.acting(pg)
        if self.pool.type == TYPE_REPLICATED:
            data = self.read(name)
            self.write_full(name, data)
            return []
        codec = self.cluster._codec(self.pool_id)
        sinfo = self._sinfo(codec)
        shards, size, hinfo = self._gather_shards(name, pg, acting)
        if size is None:
            raise KeyError(name)
        bad = [s for s, _p in self.deep_scrub(name)]
        data = ec_util.decode(
            sinfo, codec,
            {s: b for s, b in shards.items()})
        padded = data
        full = ec_util.encode(sinfo, codec, padded,
                              range(codec.get_chunk_count()))
        oi = json.dumps({"size": size}).encode()
        hinfo_raw = json.dumps(hinfo).encode()
        repaired = []
        for shard in bad:
            osd = acting[shard] if shard < len(acting) else CRUSH_ITEM_NONE
            if osd == CRUSH_ITEM_NONE or self.cluster.osdmap.is_down(osd):
                continue
            store = self.cluster.stores[osd]
            cid = shard_collection(pg, shard)
            t = Transaction()
            if not store.collection_exists(cid):
                t.create_collection(cid)
            oid = ObjectId(name)
            t.truncate(cid, oid, 0)
            t.write(cid, oid, 0, len(full[shard]), full[shard])
            t.setattr(cid, oid, OI_ATTR, oi)
            t.setattr(cid, oid, HINFO_ATTR, hinfo_raw)
            store.queue_transaction(t)
            repaired.append(shard)
        return repaired

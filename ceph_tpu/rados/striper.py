"""RadosStriper: byte-addressed striped objects over an IoCtx.

Reference parity: libradosstriper
(/root/reference/src/libradosstriper/RadosStriperImpl.cc) — a logical
"striped object" soid maps onto rados objects `soid.%016x`, byte
ranges spread RAID-0 style across a stripe set (stripe_unit x
stripe_count, object_size per backing object), layout + logical size
recorded on the FIRST object so any client can reopen the stream.

Layout math is the Striper::file_to_extents shape
(/root/reference/src/osdc/Striper.cc): offset -> (stripe unit index,
object set, object within set, in-object offset).
"""

from __future__ import annotations

import asyncio
import json
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ceph_tpu.rados.client import IoCtx, ObjectNotFound, RadosError

LOCK_NAME = "striper.lock"
LOCK_DURATION = 30.0

DEFAULT_STRIPE_UNIT = 512 * 1024
DEFAULT_STRIPE_COUNT = 4
DEFAULT_OBJECT_SIZE = 4 << 20

LAYOUT_ATTR = "striper.layout"


class RadosStriper:
    """libradosstriper::RadosStriper role over one IoCtx."""

    def __init__(self, ioctx: IoCtx,
                 stripe_unit: int = DEFAULT_STRIPE_UNIT,
                 stripe_count: int = DEFAULT_STRIPE_COUNT,
                 object_size: int = DEFAULT_OBJECT_SIZE):
        if object_size % stripe_unit:
            raise RadosError(-22, "object_size % stripe_unit != 0")
        self.ioctx = ioctx
        self._renewals: Dict[str, "asyncio.Task"] = {}
        self.stripe_unit = stripe_unit
        self.stripe_count = stripe_count
        self.object_size = object_size

    @staticmethod
    def _obj(soid: str, objectno: int) -> str:
        return f"{soid}.{objectno:016x}"

    async def _layout(self, soid: str) -> Dict[str, Any]:
        try:
            raw = await self.ioctx.getxattr(self._obj(soid, 0),
                                            LAYOUT_ATTR)
        except RadosError as e:
            if e.rc in (-2, -61):   # ENOENT / ENODATA
                raise ObjectNotFound(-2, soid)
            raise
        return json.loads(raw.decode())

    async def _save_layout(self, soid: str, size: int,
                           max_size: Optional[int] = None) -> None:
        """max_size is the HIGH-WATER size: truncate only zeroes data,
        so backing objects can outlive `size` — remove() walks the
        high-water extent or it would orphan them (the reference
        striper tracks this via the object-set it actually deletes)."""
        await self.ioctx.setxattr(
            self._obj(soid, 0), LAYOUT_ATTR,
            json.dumps({"stripe_unit": self.stripe_unit,
                        "stripe_count": self.stripe_count,
                        "object_size": self.object_size,
                        "size": size,
                        "max_size": size if max_size is None
                        else max_size}).encode())

    # -- exclusive op lock (RadosStriperImpl lock-on-first-object) --------

    async def _lock(self, soid: str, timeout: float = 10.0) -> str:
        """Exclusive cls_lock on object 0: append/write/truncate/remove
        are read-modify-writes of the stored layout (size), and two
        unsynchronized appends would both read size S and overwrite
        each other — the reference serializes these under a cls lock
        on the first object (RadosStriperImpl.cc aioWrite/truncate
        lockObject).  Busy-waits with backoff until acquired; taken
        with a 30s duration so a crashed holder expires instead of
        bricking the object (lock_info_t expiration)."""
        cookie = uuid.uuid4().hex
        req = json.dumps({"name": LOCK_NAME, "type": "exclusive",
                          "cookie": cookie, "duration": LOCK_DURATION,
                          "owner": f"striper.{cookie[:8]}"}).encode()
        deadline = asyncio.get_running_loop().time() + timeout
        while True:
            try:
                await self.ioctx.execute(self._obj(soid, 0), "lock",
                                         "lock", req)
                break
            except RadosError as e:
                if e.rc != -16:   # EBUSY: another striper op holds it
                    raise
                if asyncio.get_running_loop().time() > deadline:
                    raise
                await asyncio.sleep(0.02)
        # renewal heartbeat: an op outliving the duration (recovery
        # stalls, huge objects) must not silently lose its exclusion —
        # re-locking with the same (owner, cookie) extends the expiry
        # (the reference renews across long ops); a CRASHED holder
        # stops renewing and expires within LOCK_DURATION
        async def renew():
            while True:
                await asyncio.sleep(LOCK_DURATION / 3)
                try:
                    await self.ioctx.execute(self._obj(soid, 0),
                                             "lock", "lock", req)
                except Exception:
                    return  # lost/removed: the op will fail on its own
        task = asyncio.get_running_loop().create_task(renew())
        self._renewals[cookie] = task
        return cookie

    async def _unlock(self, soid: str, cookie: str) -> None:
        task = self._renewals.pop(cookie, None)
        if task is not None:
            task.cancel()
        req = json.dumps({"name": LOCK_NAME, "cookie": cookie,
                          "owner": f"striper.{cookie[:8]}"}).encode()
        try:
            await self.ioctx.execute(self._obj(soid, 0), "lock",
                                     "unlock", req)
        except (ObjectNotFound, RadosError):
            pass  # object 0 removed with the stream: lock died with it

    def _extents(self, offset: int, length: int,
                 layout: Dict[str, Any] = None
                 ) -> List[Tuple[int, int, int]]:
        """byte range -> [(objectno, in-object offset, span)] — the
        file_to_extents RAID-0 walk.  Geometry comes from the STORED
        layout when given (reads/truncates of an existing stream must
        follow how it was written, not this handle's defaults)."""
        if layout is not None:
            su = layout["stripe_unit"]
            sc = layout["stripe_count"]
            osz = layout["object_size"]
        else:
            su, sc, osz = (self.stripe_unit, self.stripe_count,
                           self.object_size)
        per_set = osz * sc           # bytes per object set
        units_per_obj = osz // su
        out: List[Tuple[int, int, int]] = []
        end = offset + length
        while offset < end:
            unit = offset // su      # global stripe unit index
            in_unit = offset % su
            setno = offset // per_set
            unit_in_set = unit % (sc * units_per_obj)
            obj_in_set = unit_in_set % sc
            row = unit_in_set // sc  # unit row within the object
            objectno = setno * sc + obj_in_set
            obj_off = row * su + in_unit
            span = min(su - in_unit, end - offset)
            out.append((objectno, obj_off, span))
            offset += span
        return out

    # -- API (libradosstriper surface) -------------------------------------

    async def write(self, soid: str, data: bytes,
                    offset: int = 0) -> None:
        cookie = await self._lock(soid)
        try:
            await self._write_locked(soid, data, offset)
        finally:
            await self._unlock(soid, cookie)

    async def _write_locked(self, soid: str, data: bytes,
                            offset: int) -> None:
        layout_size = offset + len(data)
        try:
            cur = await self._layout(soid)
        except ObjectNotFound:
            cur = None  # fresh stream
        # any OTHER error propagates: treating a transient read
        # failure as "fresh" would rewrite the stored size downward
        # (silent truncation)
        max_size = layout_size
        if cur is not None:
            if (cur["stripe_unit"], cur["stripe_count"],
                    cur["object_size"]) != (self.stripe_unit,
                                            self.stripe_count,
                                            self.object_size):
                raise RadosError(-22, "layout mismatch with existing"
                                      " striped object")
            layout_size = max(cur["size"], layout_size)
            max_size = max(cur.get("max_size", cur["size"]),
                           layout_size)
        jobs = []
        pos = 0
        for objectno, obj_off, span in self._extents(offset, len(data)):
            chunk = data[pos:pos + span]
            pos += span
            jobs.append(self.ioctx.write(self._obj(soid, objectno),
                                         chunk, obj_off))
        if jobs:
            await asyncio.gather(*jobs)
        await self._save_layout(soid, layout_size, max_size)

    async def write_full(self, soid: str, data: bytes) -> None:
        try:
            await self.remove(soid)
        except ObjectNotFound:
            pass
        await self.write(soid, data, 0)

    async def append(self, soid: str, data: bytes) -> None:
        """size read + write UNDER ONE LOCK: two appends that both read
        size S would otherwise write the same extents, silently
        overwriting each other."""
        await self._layout(soid)  # exist-check BEFORE locking (below)
        cookie = await self._lock(soid)
        try:
            size = (await self._layout_or_cleanup(soid))["size"]
            await self._write_locked(soid, data, size)
        finally:
            await self._unlock(soid, cookie)

    async def _layout_or_cleanup(self, soid: str) -> Dict[str, Any]:
        """Layout read INSIDE the op lock.  If the stream vanished
        between the pre-lock exist-check and here (a concurrent
        remove), our lock exec has re-created object 0 as a bare
        lock holder — delete it before failing, or every such race
        leaks a phantom object (we hold the lock, so the delete
        cannot race another writer)."""
        try:
            return await self._layout(soid)
        except ObjectNotFound:
            try:
                await self.ioctx.remove(self._obj(soid, 0))
            except Exception:
                pass
            raise

    async def read(self, soid: str, offset: int = 0,
                   length: int = 0) -> bytes:
        layout = await self._layout(soid)
        size = layout["size"]
        if offset >= size:
            return b""
        if length == 0 or offset + length > size:
            length = size - offset

        async def one(objectno: int, obj_off: int, span: int) -> bytes:
            try:
                buf = await self.ioctx.read(
                    self._obj(soid, objectno), obj_off, span)
            except ObjectNotFound:
                return bytes(span)   # sparse
            if len(buf) < span:
                buf += bytes(span - len(buf))
            return buf

        parts = await asyncio.gather(
            *(one(*ext)
              for ext in self._extents(offset, length, layout)))
        return b"".join(parts)

    async def size(self, soid: str) -> int:
        return (await self._layout(soid))["size"]

    async def stat(self, soid: str) -> Dict[str, Any]:
        return dict(await self._layout(soid))

    async def remove(self, soid: str) -> None:
        # under the op lock like every other layout RMW: an unlocked
        # remove racing an append could delete extents the append is
        # writing and then be resurrected by its _save_layout.
        # Existence is checked BEFORE locking: the lock exec would
        # CREATE object 0 (a WR exec creates), so probing a missing
        # soid would otherwise litter the pool with lock-only orphans
        await self._layout(soid)
        cookie = await self._lock(soid)
        try:
            await self._remove_locked(soid)
        finally:
            await self._unlock(soid, cookie)

    async def _remove_locked(self, soid: str) -> None:
        layout = await self._layout_or_cleanup(soid)
        per_set = layout["object_size"] * layout["stripe_count"]
        # walk the HIGH-WATER extent: a truncate only zeroes/removes
        # data objects, so objects past the current size may exist
        hw = max(layout["size"], layout.get("max_size", layout["size"]))
        nsets = max(1, -(-hw // per_set))
        nobjs = nsets * layout["stripe_count"]

        async def rm(objectno: int) -> None:
            try:
                await self.ioctx.remove(self._obj(soid, objectno))
            except ObjectNotFound:
                pass

        # shadows concurrently; the layout holder (object 0) LAST so a
        # crashed remove leaves the stream reopenable, never orphaned
        if nobjs > 1:
            await asyncio.gather(*(rm(i) for i in range(1, nobjs)))
        await rm(0)

    async def truncate(self, soid: str, size: int) -> None:
        await self._layout(soid)  # exist-check BEFORE locking (remove())
        cookie = await self._lock(soid)
        try:
            layout = await self._layout_or_cleanup(soid)
            hw = max(layout["size"],
                     layout.get("max_size", layout["size"]))
            if size > layout["size"]:
                await self._save_layout(soid, size, max(hw, size))
                return
            su = layout["stripe_unit"]
            sc = layout["stripe_count"]
            per_set = layout["object_size"] * sc
            nsets = max(1, -(-hw // per_set))
            # objects whose FIRST stored byte is past the new end hold
            # no live data: actually remove them (the reference
            # truncates/deletes backing objects, RadosStriperImpl.cc
            # truncate) — zeroing alone would orphan space
            removed = set()
            for objectno in range(nsets * sc):
                if objectno == 0:
                    continue  # layout holder stays
                first = ((objectno // sc) * per_set
                         + (objectno % sc) * su)
                if first >= size:
                    removed.add(objectno)
                    try:
                        await self.ioctx.remove(
                            self._obj(soid, objectno))
                    except ObjectNotFound:
                        pass
            # zero the dropped range (up to the high-water mark) on
            # the objects that survive
            for objectno, obj_off, span in self._extents(
                    size, hw - size, layout):
                if objectno in removed:
                    continue
                try:
                    await self.ioctx.write(self._obj(soid, objectno),
                                           bytes(span), obj_off)
                except ObjectNotFound:
                    pass
            await self._save_layout(soid, size, size)
        finally:
            await self._unlock(soid, cookie)

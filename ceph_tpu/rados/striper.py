"""RadosStriper: byte-addressed striped objects over an IoCtx.

Reference parity: libradosstriper
(/root/reference/src/libradosstriper/RadosStriperImpl.cc) — a logical
"striped object" soid maps onto rados objects `soid.%016x`, byte
ranges spread RAID-0 style across a stripe set (stripe_unit x
stripe_count, object_size per backing object), layout + logical size
recorded on the FIRST object so any client can reopen the stream.

Layout math is the Striper::file_to_extents shape
(/root/reference/src/osdc/Striper.cc): offset -> (stripe unit index,
object set, object within set, in-object offset).
"""

from __future__ import annotations

import asyncio
import json
from typing import Any, Dict, List, Tuple

from ceph_tpu.rados.client import IoCtx, ObjectNotFound, RadosError

DEFAULT_STRIPE_UNIT = 512 * 1024
DEFAULT_STRIPE_COUNT = 4
DEFAULT_OBJECT_SIZE = 4 << 20

LAYOUT_ATTR = "striper.layout"


class RadosStriper:
    """libradosstriper::RadosStriper role over one IoCtx."""

    def __init__(self, ioctx: IoCtx,
                 stripe_unit: int = DEFAULT_STRIPE_UNIT,
                 stripe_count: int = DEFAULT_STRIPE_COUNT,
                 object_size: int = DEFAULT_OBJECT_SIZE):
        if object_size % stripe_unit:
            raise RadosError(-22, "object_size % stripe_unit != 0")
        self.ioctx = ioctx
        self.stripe_unit = stripe_unit
        self.stripe_count = stripe_count
        self.object_size = object_size

    @staticmethod
    def _obj(soid: str, objectno: int) -> str:
        return f"{soid}.{objectno:016x}"

    async def _layout(self, soid: str) -> Dict[str, Any]:
        try:
            raw = await self.ioctx.getxattr(self._obj(soid, 0),
                                            LAYOUT_ATTR)
        except RadosError as e:
            if e.rc in (-2, -61):   # ENOENT / ENODATA
                raise ObjectNotFound(-2, soid)
            raise
        return json.loads(raw.decode())

    async def _save_layout(self, soid: str, size: int) -> None:
        await self.ioctx.setxattr(
            self._obj(soid, 0), LAYOUT_ATTR,
            json.dumps({"stripe_unit": self.stripe_unit,
                        "stripe_count": self.stripe_count,
                        "object_size": self.object_size,
                        "size": size}).encode())

    def _extents(self, offset: int, length: int,
                 layout: Dict[str, Any] = None
                 ) -> List[Tuple[int, int, int]]:
        """byte range -> [(objectno, in-object offset, span)] — the
        file_to_extents RAID-0 walk.  Geometry comes from the STORED
        layout when given (reads/truncates of an existing stream must
        follow how it was written, not this handle's defaults)."""
        if layout is not None:
            su = layout["stripe_unit"]
            sc = layout["stripe_count"]
            osz = layout["object_size"]
        else:
            su, sc, osz = (self.stripe_unit, self.stripe_count,
                           self.object_size)
        per_set = osz * sc           # bytes per object set
        units_per_obj = osz // su
        out: List[Tuple[int, int, int]] = []
        end = offset + length
        while offset < end:
            unit = offset // su      # global stripe unit index
            in_unit = offset % su
            setno = offset // per_set
            unit_in_set = unit % (sc * units_per_obj)
            obj_in_set = unit_in_set % sc
            row = unit_in_set // sc  # unit row within the object
            objectno = setno * sc + obj_in_set
            obj_off = row * su + in_unit
            span = min(su - in_unit, end - offset)
            out.append((objectno, obj_off, span))
            offset += span
        return out

    # -- API (libradosstriper surface) -------------------------------------

    async def write(self, soid: str, data: bytes,
                    offset: int = 0) -> None:
        layout_size = offset + len(data)
        try:
            cur = await self._layout(soid)
        except ObjectNotFound:
            cur = None  # fresh stream
        # any OTHER error propagates: treating a transient read
        # failure as "fresh" would rewrite the stored size downward
        # (silent truncation)
        if cur is not None:
            if (cur["stripe_unit"], cur["stripe_count"],
                    cur["object_size"]) != (self.stripe_unit,
                                            self.stripe_count,
                                            self.object_size):
                raise RadosError(-22, "layout mismatch with existing"
                                      " striped object")
            layout_size = max(cur["size"], layout_size)
        jobs = []
        pos = 0
        for objectno, obj_off, span in self._extents(offset, len(data)):
            chunk = data[pos:pos + span]
            pos += span
            jobs.append(self.ioctx.write(self._obj(soid, objectno),
                                         chunk, obj_off))
        if jobs:
            await asyncio.gather(*jobs)
        await self._save_layout(soid, layout_size)

    async def write_full(self, soid: str, data: bytes) -> None:
        try:
            await self.remove(soid)
        except ObjectNotFound:
            pass
        await self.write(soid, data, 0)

    async def append(self, soid: str, data: bytes) -> None:
        size = await self.size(soid)
        await self.write(soid, data, size)

    async def read(self, soid: str, offset: int = 0,
                   length: int = 0) -> bytes:
        layout = await self._layout(soid)
        size = layout["size"]
        if offset >= size:
            return b""
        if length == 0 or offset + length > size:
            length = size - offset

        async def one(objectno: int, obj_off: int, span: int) -> bytes:
            try:
                buf = await self.ioctx.read(
                    self._obj(soid, objectno), obj_off, span)
            except ObjectNotFound:
                return bytes(span)   # sparse
            if len(buf) < span:
                buf += bytes(span - len(buf))
            return buf

        parts = await asyncio.gather(
            *(one(*ext)
              for ext in self._extents(offset, length, layout)))
        return b"".join(parts)

    async def size(self, soid: str) -> int:
        return (await self._layout(soid))["size"]

    async def stat(self, soid: str) -> Dict[str, Any]:
        return dict(await self._layout(soid))

    async def remove(self, soid: str) -> None:
        layout = await self._layout(soid)
        per_set = layout["object_size"] * layout["stripe_count"]
        nsets = max(1, -(-layout["size"] // per_set))
        nobjs = nsets * layout["stripe_count"]

        async def rm(objectno: int) -> None:
            try:
                await self.ioctx.remove(self._obj(soid, objectno))
            except ObjectNotFound:
                pass

        # shadows concurrently; the layout holder (object 0) LAST so a
        # crashed remove leaves the stream reopenable, never orphaned
        if nobjs > 1:
            await asyncio.gather(*(rm(i) for i in range(1, nobjs)))
        await rm(0)

    async def truncate(self, soid: str, size: int) -> None:
        layout = await self._layout(soid)
        if size > layout["size"]:
            await self._save_layout(soid, size)
            return
        # drop data past the new end (object granularity via
        # zeroing), walking the STORED geometry
        for objectno, obj_off, span in self._extents(
                size, layout["size"] - size, layout):
            try:
                await self.ioctx.write(self._obj(soid, objectno),
                                       bytes(span), obj_off)
            except ObjectNotFound:
                pass
        await self._save_layout(soid, size)

"""RADOS layer: object access over placed, erasure-coded storage."""

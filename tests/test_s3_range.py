"""S3 ranged GET tier: `Range: bytes=a-b` -> 206/Content-Range, with
suffix and unsatisfiable (416) cases (RGWGetObj::parse_range role,
rgw_op.cc:99), and ranged GETs on EC buckets counting as read-tier
reads on the OSDs that serve the stripes.
"""

from __future__ import annotations

import asyncio
import urllib.parse

import numpy as np
import pytest

from cluster_helpers import Cluster

from ceph_tpu.rgw import RGWLite
from ceph_tpu.rgw.s3_frontend import (
    RANGE_UNSATISFIABLE,
    S3Frontend,
    parse_byte_range,
    sign_request,
)

ACCESS, SECRET = "AKIDEXAMPLE", "s3cr3t-key-for-tests"


# -- parse_byte_range unit tier ---------------------------------------------


@pytest.mark.parametrize("spec,size,want", [
    ("bytes=0-99", 1000, (0, 99)),
    ("bytes=100-", 1000, (100, 999)),
    ("bytes=0-0", 1000, (0, 0)),
    ("bytes=999-999", 1000, (999, 999)),
    ("bytes=900-5000", 1000, (900, 999)),      # end clamped
    ("bytes=-100", 1000, (900, 999)),          # suffix
    ("bytes=-5000", 1000, (0, 999)),           # suffix > size
    ("  bytes=1-2 ", 1000, (1, 2)),
])
def test_parse_valid_ranges(spec, size, want):
    assert parse_byte_range(spec, size) == want


@pytest.mark.parametrize("spec,size", [
    ("bytes=1000-", 1000),                     # start at EOF
    ("bytes=5000-6000", 1000),                 # start past EOF
    ("bytes=-0", 1000),                        # empty suffix
    ("bytes=-10", 0),                          # suffix of empty object
])
def test_parse_unsatisfiable_ranges(spec, size):
    assert parse_byte_range(spec, size) is RANGE_UNSATISFIABLE


@pytest.mark.parametrize("spec,size", [
    ("", 1000),
    ("bits=0-1", 1000),                        # wrong unit
    ("bytes=5-2", 1000),                       # inverted
    ("bytes=a-b", 1000),                       # non-numeric
    ("bytes=0-1,5-9", 1000),                   # multi-range: S3 -> 200
    ("bytes=5", 1000),                         # no dash
    ("bytes=--5", 1000),                       # signed suffix length
    ("bytes=+1-5", 1000),                      # signed start
    ("bytes=-", 1000),                         # bare dash
])
def test_parse_ignored_ranges(spec, size):
    assert parse_byte_range(spec, size) is None


# -- HTTP round-trip through the frontend -----------------------------------


class RangeS3:
    """Raw-socket sigv4 client that can attach extra (signed)
    headers, e.g. Range."""

    def __init__(self, addr: str):
        self.host, port = addr.rsplit(":", 1)
        self.port = int(port)
        self._r = self._w = None

    async def request(self, method, path, body=b"", extra=None):
        if self._w is None or self._w.is_closing():
            self._r, self._w = await asyncio.open_connection(
                self.host, self.port, limit=8 << 20)
        headers = {"Host": f"{self.host}:{self.port}",
                   **(extra or {})}
        headers = sign_request(method, path, {}, headers, body,
                               ACCESS, SECRET)
        target = urllib.parse.quote(path)
        req = [f"{method} {target} HTTP/1.1\r\n"]
        headers["Content-Length"] = str(len(body))
        for k, v in headers.items():
            req.append(f"{k}: {v}\r\n")
        req.append("\r\n")
        self._w.write("".join(req).encode() + body)
        await self._w.drain()
        status_line = await self._r.readline()
        status = int(status_line.split()[1])
        rhdrs = {}
        while True:
            line = await self._r.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            k, _, v = line.decode().partition(":")
            rhdrs[k.strip().lower()] = v.strip()
        length = int(rhdrs.get("content-length", "0"))
        rbody = await self._r.readexactly(length) if length and \
            method != "HEAD" else b""
        return status, rhdrs, rbody

    async def close(self):
        if self._w is not None:
            self._w.close()
            self._w = None


def test_ranged_get_206_suffix_and_416():
    async def main():
        # promotion parked (min_recency 100): the transfer-volume
        # assertion below must see only the ranged read itself, not a
        # background promotion's one-time full decode
        cluster = Cluster(num_osds=3, osds_per_host=1,
                          osd_config={
                              "osd_tier_promote_min_recency": 100})
        await cluster.start()
        fe = None
        try:
            await cluster.client.create_replicated_pool(
                "rgw.meta", size=2, pg_num=4)
            await cluster.client.create_ec_pool(
                "rgw.data",
                {"plugin": "ec_jax", "technique": "reed_sol_van",
                 "k": "2", "m": "1", "crush-failure-domain": "osd",
                 "tpu": "false"}, pg_num=4)
            rgw = RGWLite(cluster.client, "rgw.data", "rgw.meta")
            fe = S3Frontend(rgw, {ACCESS: SECRET})
            addr = await fe.start()
            s3 = RangeS3(addr)
            st, _, _ = await s3.request("PUT", "/b")
            assert st == 200
            data = np.random.default_rng(7).integers(
                0, 256, 300_000, dtype=np.uint8).tobytes()
            st, _, _ = await s3.request("PUT", "/b/obj", body=data)
            assert st == 200

            # plain GET advertises range support
            st, h, got = await s3.request("GET", "/b/obj")
            assert st == 200 and got == data
            assert h.get("accept-ranges") == "bytes"

            # bytes=a-b -> 206 + Content-Range; the pushdown fetches
            # O(range) from the OSDs, not the whole object
            sub0 = sum(osd.perf["subread_bytes"]
                       for osd in cluster.osds.values())
            st, h, got = await s3.request(
                "GET", "/b/obj", extra={"Range": "bytes=100-355"})
            assert st == 206
            assert got == data[100:356]
            assert h["content-range"] == f"bytes 100-355/{len(data)}"
            assert h["content-length"] == "256"
            moved = sum(osd.perf["subread_bytes"]
                        for osd in cluster.osds.values()) - sub0
            assert moved < 64 << 10, \
                f"ranged GET moved {moved}B (O(object), not O(range))"

            # open-ended + clamped tail
            st, h, got = await s3.request(
                "GET", "/b/obj", extra={"Range": "bytes=299000-"})
            assert st == 206 and got == data[299000:]
            assert h["content-range"] == \
                f"bytes 299000-{len(data) - 1}/{len(data)}"

            # suffix bytes=-n
            st, h, got = await s3.request(
                "GET", "/b/obj", extra={"Range": "bytes=-1000"})
            assert st == 206 and got == data[-1000:]
            assert h["content-range"] == \
                f"bytes {len(data) - 1000}-{len(data) - 1}/{len(data)}"

            # unsatisfiable -> 416 + bytes */size
            st, h, body = await s3.request(
                "GET", "/b/obj", extra={"Range": "bytes=9999999-"})
            assert st == 416
            assert h["content-range"] == f"bytes */{len(data)}"
            assert b"InvalidRange" in body

            # malformed/multi-range -> whole object, 200
            st, _, got = await s3.request(
                "GET", "/b/obj", extra={"Range": "bytes=5-2"})
            assert st == 200 and got == data
            st, _, got = await s3.request(
                "GET", "/b/obj", extra={"Range": "bytes=0-1,10-11"})
            assert st == 200 and got == data

            # ranged GETs on the EC data pool counted as tier reads
            records = sum(osd.tier.perf.get("records")
                          for osd in cluster.osds.values())
            assert records >= 1, "ranged GETs did not reach the tier"
            await s3.close()
        finally:
            if fe is not None:
                await fe.stop()
            await cluster.stop()

    asyncio.run(asyncio.wait_for(main(), 120))

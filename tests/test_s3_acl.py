"""S3 canned ACLs: ownership, public/authenticated access grades,
the ?acl subresource, and x-amz-acl at PUT/multipart-init time.

Reference parity: rgw_acl.cc / rgw_acl_s3.cc verify_permission — the
canned-policy subset (private, public-read, public-read-write,
authenticated-read) with the bucket owner holding FULL_CONTROL."""

import asyncio
import xml.etree.ElementTree as ET

from cluster_helpers import Cluster

from ceph_tpu.rgw import RGWLite
from ceph_tpu.rgw.s3_frontend import S3Frontend

from test_s3_http import ACCESS, SECRET, MiniS3

OTHER_ACCESS, OTHER_SECRET = "AKIDOTHERUSER", "other-secret"


async def _stack(cluster):
    await cluster.client.create_replicated_pool(
        "rgw.meta", size=2, pg_num=4)
    await cluster.client.create_replicated_pool(
        "rgw.data", size=2, pg_num=4)
    rgw = RGWLite(cluster.client, "rgw.data", "rgw.meta")
    fe = S3Frontend(rgw, {ACCESS: SECRET,
                          OTHER_ACCESS: OTHER_SECRET})
    addr = await fe.start()
    return fe, addr


def test_s3_canned_acls_end_to_end():
    async def run():
        cluster = Cluster(num_osds=2, osds_per_host=1)
        await cluster.start()
        fe = None
        try:
            fe, addr = await _stack(cluster)
            owner = MiniS3(addr)
            other = MiniS3(addr, access=OTHER_ACCESS,
                           secret=OTHER_SECRET)
            anon = MiniS3(addr)

            # private bucket (default): owner-only
            st, _, _ = await owner.request("PUT", "/priv")
            assert st == 200
            st, _, _ = await owner.request(
                "PUT", "/priv/o", body=b"secret")
            assert st == 200
            st, _, body = await other.request("GET", "/priv/o")
            assert st == 403 and b"AccessDenied" in body
            st, _, _ = await anon.request("GET", "/priv/o", sign=False)
            assert st == 403
            st, _, _ = await other.request("GET", "/priv")
            assert st == 403  # listing too
            # non-owner writes refused
            st, _, _ = await other.request("PUT", "/priv/x", body=b"w")
            assert st == 403

            # anonymous bucket creation refused outright
            st, _, _ = await anon.request("PUT", "/anonb", sign=False)
            assert st == 403

            # public-read at creation: world-readable, owner-writable
            st, _, _ = await owner.request("PUT", "/pub")
            assert st == 200
            st, _, _ = await owner.request(
                "PUT", "/pub/img", body=b"jpeg bytes")
            assert st == 200
            # flip the bucket ACL via the ?acl subresource
            # (MiniS3 cannot add headers; raw signed request below)
            import urllib.parse

            from ceph_tpu.rgw.s3_frontend import sign_request

            async def req_with_headers(cli, method, path, query,
                                       extra, body=b""):
                await cli._connect()
                headers = {"Host": f"{cli.host}:{cli.port}"}
                headers.update(extra)
                headers = sign_request(method, path, query, headers,
                                       body, cli.access, cli.secret)
                qs = urllib.parse.urlencode(query)
                target = path + ("?" + qs if qs else "")
                req = [f"{method} {target} HTTP/1.1\r\n"]
                headers["Content-Length"] = str(len(body))
                for k, v in headers.items():
                    req.append(f"{k}: {v}\r\n")
                req.append("\r\n")
                cli._w.write("".join(req).encode() + body)
                await cli._w.drain()
                status = int((await cli._r.readline()).split()[1])
                rhdrs = {}
                while True:
                    line = await cli._r.readline()
                    if line in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = line.decode().partition(":")
                    rhdrs[k.strip().lower()] = v.strip()
                n = int(rhdrs.get("content-length", "0"))
                rbody = await cli._r.readexactly(n) if n else b""
                return status, rhdrs, rbody

            st, _, _ = await req_with_headers(
                owner, "PUT", "/pub", {"acl": ""},
                {"x-amz-acl": "public-read"})
            assert st == 200
            # anonymous + other user can now read objects and list
            st, _, got = await anon.request("GET", "/pub/img",
                                            sign=False)
            assert st == 200 and got == b"jpeg bytes"
            st, _, got = await other.request("GET", "/pub/img")
            assert st == 200
            st, _, _ = await anon.request("GET", "/pub", sign=False)
            assert st == 200
            # ...but still cannot write
            st, _, _ = await anon.request("PUT", "/pub/w", sign=False,
                                          body=b"nope")
            assert st == 403

            # GET ?acl renders the canned policy (owner-only)
            st, _, xml_body = await owner.request(
                "GET", "/pub", query={"acl": ""})
            assert st == 200
            root = ET.fromstring(xml_body)
            assert root.find("Owner/ID").text == ACCESS
            assert b"AllUsers" in xml_body and b"READ" in xml_body
            st, _, _ = await other.request("GET", "/pub",
                                           query={"acl": ""})
            assert st == 403

            # public-read-write: anonymous PUT and DELETE work
            st, _, _ = await req_with_headers(
                owner, "PUT", "/pub", {"acl": ""},
                {"x-amz-acl": "public-read-write"})
            assert st == 200
            st, _, _ = await anon.request("PUT", "/pub/anon-obj",
                                          sign=False, body=b"drop")
            assert st == 200
            st, _, got = await anon.request("GET", "/pub/anon-obj",
                                            sign=False)
            assert st == 200 and got == b"drop"
            st, _, _ = await anon.request("DELETE", "/pub/anon-obj",
                                          sign=False)
            assert st == 204

            # authenticated-read: other user reads, anonymous denied
            st, _, _ = await req_with_headers(
                owner, "PUT", "/pub", {"acl": ""},
                {"x-amz-acl": "authenticated-read"})
            assert st == 200
            st, _, _ = await other.request("GET", "/pub/img")
            assert st == 200
            st, _, _ = await anon.request("GET", "/pub/img",
                                          sign=False)
            assert st == 403

            # per-object ACL: x-amz-acl on PUT opens ONE object in a
            # private bucket
            st, _, _ = await req_with_headers(
                owner, "PUT", "/priv/open", {},
                {"x-amz-acl": "public-read"}, body=b"shared")
            assert st == 200
            st, _, got = await anon.request("GET", "/priv/open",
                                            sign=False)
            assert st == 200 and got == b"shared"
            st, _, _ = await anon.request("GET", "/priv/o",
                                          sign=False)
            assert st == 403  # sibling stays private
            # object ?acl subresource round-trip
            st, _, xml_body = await owner.request(
                "GET", "/priv/open", query={"acl": ""})
            assert st == 200 and b"AllUsers" in xml_body
            st, _, _ = await req_with_headers(
                owner, "PUT", "/priv/open", {"acl": ""},
                {"x-amz-acl": "private"})
            assert st == 200
            st, _, _ = await anon.request("GET", "/priv/open",
                                          sign=False)
            assert st == 403

            await owner.close()
            await other.close()
            await anon.close()
        finally:
            if fe is not None:
                await fe.stop()
            await cluster.stop()

    asyncio.run(asyncio.wait_for(run(), 120))

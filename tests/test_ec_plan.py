"""ExecPlan cache tier (ceph_tpu/ec/plan.py): bucketed-padding
correctness against the numpy host oracle, plan-key stability across
processes, donation never aliasing live caller buffers, stripe
coalescing, the fused encode+crc plan, and the acceptance bound —
encoding 256 stripes of a fixed profile compiles at most 3 plans.
"""

from __future__ import annotations

import subprocess
import sys

import numpy as np
import pytest

import conftest

jax = pytest.importorskip("jax")

from ceph_tpu.ec import plan  # noqa: E402
from ceph_tpu.ec.registry import ErasureCodePluginRegistry  # noqa: E402
from ceph_tpu.models import reed_solomon as rs  # noqa: E402
from ceph_tpu.ops import checksum as cks  # noqa: E402
from ceph_tpu.ops import gf  # noqa: E402

RNG = np.random.default_rng(7)


def _codec(k=4, m=2, **extra):
    profile = {"plugin": "ec_jax", "technique": "reed_sol_van",
               "k": str(k), "m": str(m), **extra}
    return ErasureCodePluginRegistry.instance().factory(
        "ec_jax", profile)


def _host_parity(mat, data):
    if data.ndim == 2:
        return gf.gf_matmul_ref(mat, data)
    return np.stack([gf.gf_matmul_ref(mat, data[i])
                     for i in range(data.shape[0])])


# -- bucketing policy -------------------------------------------------------


def test_bucket_bytes_policy():
    assert plan.bucket_bytes(1) == 64
    assert plan.bucket_bytes(64) == 64
    assert plan.bucket_bytes(65) == 80   # quarter-octave: <25% pad
    for s in (1, 7, 65, 777, 4096, 65537):
        b = plan.bucket_bytes(s)
        assert b >= max(s, 64)
        assert b % 16 == 0          # mesh sp-axis and word divisibility
        assert b < 2 * max(s, 64)   # bounded waste
    # monotone: a bigger request never lands in a smaller bucket
    buckets = [plan.bucket_bytes(s) for s in range(1, 5000)]
    assert buckets == sorted(buckets)
    # few buckets per octave: real traffic collapses onto a handful
    assert len({plan.bucket_bytes(s) for s in range(1025, 2049)}) <= 4


def test_bucket_batch_policy():
    assert plan.bucket_batch(1) == 1
    assert plan.bucket_batch(3) == 4
    assert plan.bucket_batch(256) == 256
    for b in (1, 2, 5, 100, 257):
        bb = plan.bucket_batch(b)
        assert bb >= b and bb & (bb - 1) == 0  # power of two
    # above 512 the bucket is capped to the next multiple of 128 — a
    # huge one-shot object must not pad ~2x its stripes to a pow2
    assert plan.bucket_batch(513) == 640
    assert plan.bucket_batch(6144) == 6144
    for b in (513, 1000, 4100, 6145):
        bb = plan.bucket_batch(b)
        assert b <= bb < b * 1.25 and bb % 128 == 0


# -- padded-encode correctness ---------------------------------------------


@pytest.mark.parametrize("batch,chunk", [
    (1, 777),       # odd chunk size
    (3, 1000),      # ragged batch x odd chunk
    (7, 333),
    (5, 4096),      # exact bucket
])
@pytest.mark.skipif(conftest.DEVICE_INJECTION,
                    reason="asserts live device-dispatch counters/plans;\
 subject absent under scripted device-fault injection")
def test_bucketed_padding_matches_host_reference(batch, chunk):
    mat = rs.reed_sol_van_matrix(4, 2)
    data = RNG.integers(0, 256, (batch, 4, chunk), dtype=np.uint8)
    got = plan.encode(mat, data)
    assert got is not None
    assert got.shape == (batch, 2, chunk)
    assert np.array_equal(got, _host_parity(mat, data))


@pytest.mark.skipif(conftest.DEVICE_INJECTION,
                    reason="asserts live device-dispatch counters/plans;\
 subject absent under scripted device-fault injection")
def test_plan_matmul_matches_host_and_squeezes_2d():
    mat = rs.reed_sol_van_matrix(6, 3)
    data = RNG.integers(0, 256, (3, 6, 1000), dtype=np.uint8)
    got = plan.matmul(mat, data)
    assert got is not None and got.shape == (3, 3, 1000)
    assert np.array_equal(got, _host_parity(mat, data))
    d2 = RNG.integers(0, 256, (6, 512), dtype=np.uint8)
    assert np.array_equal(plan.matmul(mat, d2),
                          gf.gf_matmul_ref(mat, d2))


def test_decode_roundtrip_through_plan_dispatch():
    """decode_batch rides the same plan.matmul entry (decode matrices
    share one shape-keyed plan as runtime operands)."""
    codec = _codec(k=4, m=2)
    data = RNG.integers(0, 256, (5, 4, 512), dtype=np.uint8)
    parity = codec.encode_batch(data)
    have, erased = (2, 3, 4, 5), (0, 1)
    survivors = np.concatenate([data[:, 2:, :], parity], axis=1)
    recovered = codec.decode_batch(have, erased, survivors)
    assert np.array_equal(np.asarray(recovered), data[:, :2, :])


# -- plan-key stability across processes -----------------------------------

_KEY_SNIPPET = """
import json
from ceph_tpu.ec import plan
from ceph_tpu.models import reed_solomon as rs
mat = rs.reed_sol_van_matrix(8, 3)
sig = plan.codec_signature("reed_sol_van", 8, 3, 8, mat)
print(json.dumps(plan.plan_key(sig, "encode", 3, 8, 37, 5000)))
"""


def test_plan_key_stable_across_processes():
    """The cache key must contain only process-stable parts (sha256
    sigs + ints) — no id()/hash() randomization — so a restarted OSD
    rebuilds the identical plan set."""
    mat = rs.reed_sol_van_matrix(8, 3)
    sig = plan.codec_signature("reed_sol_van", 8, 3, 8, mat)
    local = plan.plan_key(sig, "encode", 3, 8, 37, 5000)
    r = subprocess.run([sys.executable, "-c", _KEY_SNIPPET],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]
    import json

    remote = json.loads(r.stdout.strip())
    assert json.loads(json.dumps(local)) == remote
    # and bucketing is baked into the key: same bucket, same key
    assert plan.plan_key(sig, "encode", 3, 8, 33, 4100) == local
    # the mesh element is part of the key (a plan compiled for a
    # device set must miss for any other set), pure ints — stable
    meshed = plan.plan_key(sig, "encode", 3, 8, 33, 4100,
                           mesh=(0, 1, 2))
    assert meshed != local and meshed[7] == (0, 1, 2)
    # mesh batch bucket rounds to a multiple of the mesh size (whole
    # stripes per chip): pow2 bucket 64 -> 66 on a 3-chip mesh
    assert meshed[4] == 66


def test_codec_signature_distinguishes_profiles():
    m1 = rs.reed_sol_van_matrix(8, 3)
    m2 = rs.reed_sol_van_matrix(8, 4)
    assert plan.codec_signature("reed_sol_van", 8, 3, 8, m1) != \
        plan.codec_signature("reed_sol_van", 8, 4, 8, m2)
    assert plan.codec_signature("reed_sol_van", 8, 3, 8, m1) != \
        plan.codec_signature("cauchy_good", 8, 3, 8, m1)


# -- donation safety --------------------------------------------------------


@pytest.mark.skipif(conftest.DEVICE_INJECTION,
                    reason="asserts live device-dispatch counters/plans;\
 subject absent under scripted device-fault injection")
def test_donation_does_not_alias_live_buffers():
    """Encoding twice from the same source array must give identical
    parity and leave the source readable: the plan only ever donates
    buffers it created itself (or that the caller explicitly
    relinquished with donate=True)."""
    import jax.numpy as jnp

    mat = rs.reed_sol_van_matrix(4, 2)
    src_np = RNG.integers(0, 256, (2, 4, 600), dtype=np.uint8)
    want = _host_parity(mat, src_np)

    # host input: padding/placement buffers are plan-owned
    p1 = plan.encode(mat, src_np)
    p2 = plan.encode(mat, src_np)
    assert np.array_equal(p1, want) and np.array_equal(p2, want)
    assert np.array_equal(src_np, src_np.copy())  # still intact

    # device-resident input WITHOUT donate=True: stays caller-owned
    src_dev = jnp.asarray(src_np)
    p1 = plan.encode(mat, src_dev)
    p2 = plan.encode(mat, src_dev)
    assert np.array_equal(p1, want) and np.array_equal(p2, want)
    assert np.array_equal(np.asarray(src_dev), src_np)  # not invalidated


# -- stripe coalescing ------------------------------------------------------


@pytest.mark.skipif(conftest.DEVICE_INJECTION,
                    reason="asserts live device-dispatch counters/plans;\
 subject absent under scripted device-fault injection")
def test_coalescer_folds_ragged_pending_encodes():
    mat = rs.reed_sol_van_matrix(4, 2)
    co = plan.StripeCoalescer(mat, max_pending=8)
    # ragged widths that land in ONE byte bucket (512)
    datas = [RNG.integers(0, 256, (4, s), dtype=np.uint8)
             for s in (450, 512, 512, 460, 500)]
    tickets = [co.add(d) for d in datas]
    assert tickets == list(range(5)) and len(co) == 5
    plan.reset_stats()
    outs = co.flush()
    assert len(co) == 0
    for d, o in zip(datas, outs):
        assert o.shape == (2, d.shape[1])
        assert np.array_equal(o, gf.gf_matmul_ref(mat, d))
    # ONE batched dispatch served all five requests
    st = plan.stats()
    assert sum(p["dispatches"] for p in st["per_plan"].values()) == 1


@pytest.mark.skipif(conftest.DEVICE_INJECTION,
                    reason="asserts live device-dispatch counters/plans;\
 subject absent under scripted device-fault injection")
def test_coalescer_groups_by_bucket_so_outliers_do_not_inflate():
    """One wide outlier must not pad every pending small stripe to its
    width — stripes group per byte bucket (the small ones still share
    one dispatch), and results come back in ticket order."""
    mat = rs.reed_sol_van_matrix(4, 2)
    datas = [RNG.integers(0, 256, (4, s), dtype=np.uint8)
             for s in (4096, 65536, 4000, 4096)]
    plan.reset_stats()
    outs = plan.encode_coalesced(mat, datas)
    for d, o in zip(datas, outs):
        assert np.array_equal(o, gf.gf_matmul_ref(mat, d))
    st = plan.stats()
    # two groups -> two dispatches (not one 4x65536 blow-up, not four)
    assert sum(p["dispatches"] for p in st["per_plan"].values()) == 2


def test_codec_encode_many_coalesces():
    codec = _codec(k=4, m=2)
    datas = [RNG.integers(0, 256, (4, s), dtype=np.uint8)
             for s in (512, 300, 512)]
    outs = codec.encode_many(datas)
    assert len(outs) == 3
    for d, o in zip(datas, outs):
        assert np.array_equal(np.asarray(o), gf.gf_matmul_ref(
            codec.matrix, d))


# -- fused encode + crc -----------------------------------------------------


@pytest.mark.skipif(conftest.DEVICE_INJECTION,
                    reason="asserts live device-dispatch counters/plans;\
 subject absent under scripted device-fault injection")
def test_fused_encode_crc_matches_host():
    mat = rs.reed_sol_van_matrix(4, 2)
    data = RNG.integers(0, 256, (3, 4, 500), dtype=np.uint8)
    out = plan.encode_with_crc(mat, data)
    assert out is not None
    parity, crcs = out
    assert np.array_equal(parity, _host_parity(mat, data))
    chunks = np.concatenate([data, parity], axis=1)
    for b in range(3):
        for c in range(6):
            assert int(crcs[b, c]) == cks.crc32c(
                0, chunks[b, c].tobytes())


@pytest.mark.skipif(conftest.DEVICE_INJECTION,
                    reason="asserts live device-dispatch counters/plans;\
 subject absent under scripted device-fault injection")
def test_codec_fused_api_applies_seed():
    codec = _codec(k=4, m=2)
    data = RNG.integers(0, 256, (2, 4, 256), dtype=np.uint8)
    out = codec.encode_batch_with_crc(data, init=0xFFFFFFFF)
    assert out is not None
    parity, crcs = out
    chunks = np.concatenate([data, np.asarray(parity)], axis=1)
    for b in range(2):
        for c in range(6):
            assert int(crcs[b, c]) == cks.crc32c(
                0xFFFFFFFF, chunks[b, c].tobytes())


def test_encode_with_hinfo_fused_device_tier(monkeypatch):
    """The fused device path of ECUtil::encode_with_hinfo is bit-exact
    with the unfused host ledger."""
    from ceph_tpu.osd import ec_util

    monkeypatch.setenv("CEPH_TPU_FUSE_MIN_BYTES", "0")
    codec = _codec(k=4, m=2)
    sinfo = ec_util.StripeInfo(4, 4 * 512)
    data = RNG.integers(0, 256, 6 * 4 * 512, dtype=np.uint8).tobytes()
    shards, hinfo, crc = ec_util.encode_with_hinfo(
        sinfo, codec, data, range(6), logical_len=len(data) - 17)
    ref = ec_util.encode(sinfo, codec, data, range(6))
    ref_hinfo = ec_util.HashInfo(6)
    ref_hinfo.append(0, ref)
    for i in range(6):
        assert bytes(shards[i]) == bytes(ref[i])
    assert hinfo.cumulative_shard_hashes == \
        ref_hinfo.cumulative_shard_hashes
    assert hinfo.total_chunk_size == ref_hinfo.total_chunk_size
    assert crc == cks.crc32c(0xFFFFFFFF,
                             memoryview(data)[:len(data) - 17])


# -- observability + the acceptance bound ----------------------------------


@pytest.mark.skipif(conftest.DEVICE_INJECTION,
                    reason="asserts live device-dispatch counters/plans;\
 subject absent under scripted device-fault injection")
def test_stats_counters_track_hits_and_misses():
    plan.clear()
    plan.reset_stats()
    mat = rs.reed_sol_van_matrix(4, 2)
    data = RNG.integers(0, 256, (2, 4, 300), dtype=np.uint8)
    plan.encode(mat, data)
    st = plan.stats()
    assert st["misses"] == 1 and st["hits"] == 0
    plan.encode(mat, data)
    st = plan.stats()
    assert st["misses"] == 1 and st["hits"] == 1
    assert st["plans"] >= 1 and st["enabled"]
    label, entry = next(iter(st["per_plan"].items()))
    assert entry["dispatches"] >= 1 and entry["seconds"] >= 0


@pytest.mark.skipif(conftest.DEVICE_INJECTION,
                    reason="asserts live device-dispatch counters/plans;\
 subject absent under scripted device-fault injection")
def test_fixed_profile_256_stripes_compiles_at_most_3_plans():
    """The acceptance bound: encoding 256 stripes of one fixed profile
    — arriving as ragged batches inside one power-of-two bucket plus
    one full batch — compiles <= 3 plans (plan.stats() retraces)."""
    plan.clear()
    plan.reset_stats()
    codec = _codec(k=4, m=2)
    chunk = 1024
    total = 0
    # 128 stripes arrive ragged: every batch pads into the B=128 bucket
    for b in (65, 128, 100, 128, 90):
        if total + b > 128:
            b = 128 - total
        if b <= 0:
            break
        data = RNG.integers(0, 256, (b, 4, chunk), dtype=np.uint8)
        parity = codec.encode_batch(data)
        assert np.asarray(parity).shape == (b, 2, chunk)
        total += b
    # ...and 128 more as one full batch
    data = RNG.integers(0, 256, (128, 4, chunk), dtype=np.uint8)
    codec.encode_batch(data)
    total += 128
    assert total == 256
    st = plan.stats()
    assert st["retraces"] <= 3, st
    assert st["hits"] >= 1, st


def test_no_plan_cache_toggle_bypasses():
    plan.clear()
    plan.reset_stats()
    codec = _codec(k=4, m=2, **{"plan-cache": "false"})
    assert not codec.use_plan
    data = RNG.integers(0, 256, (2, 4, 512), dtype=np.uint8)
    parity = codec.encode_batch(data)
    assert np.array_equal(np.asarray(parity),
                          _host_parity(codec.matrix, data))
    assert plan.stats()["misses"] == 0  # never consulted the cache


# -- the satellite LRU fix --------------------------------------------------


def test_gf_mul_table_cache_evicts_lru_not_everything():
    cache = gf._table_cache()
    cache.clear()
    mats = []
    for i in range(70):  # 70 distinct matrices > cap 64
        m = np.full((2, 3), 1 + (i % 255), dtype=np.uint8)
        m[0, 0] = 1 + ((i * 7) % 255)
        m[1, 2] = 1 + ((i * 13) % 255)
        m = np.ascontiguousarray(m)
        mats.append(m)
        gf.gf_mul_tables(m)
    assert len(cache) == 64  # bounded, NOT dumped to zero on overflow
    hot = mats[-1]
    key = (hot.shape, hot.tobytes())
    assert key in cache            # most-recent survived
    cold = mats[0]
    assert (cold.shape, cold.tobytes()) not in cache  # LRU evicted
    # correctness after eviction churn
    tables = gf.gf_mul_tables(hot)
    idx = np.arange(256, dtype=np.uint8)
    assert np.array_equal(tables[0], gf.gf_mul(
        np.full(256, hot[0, 0], np.uint8), idx))

"""Codec-compiler tier (ec/xsched.py): schedule-vs-naive
bit-exactness across the bitmatrix family (all techniques x legal w
values x every 1- and 2-erasure pattern), GF(2^8) bit-expansion
equivalence on ragged chunk sizes, the CEPH_TPU_XSCHED=0 kill-switch
parity leg through a live cluster, the shared decode-rows cache
(cross-instance hits), schedule survival across plan rebuilds, and
the device-tier `xor_sched` plan kind next to the matmul lowering.
"""

from __future__ import annotations

import asyncio
import itertools

import numpy as np
import pytest

import conftest
from ceph_tpu.ec import dispatch, plan, xsched
from ceph_tpu.ec.registry import create_erasure_code
from ceph_tpu.models import bitmatrix as bmx
from ceph_tpu.models import reed_solomon as rs
from ceph_tpu.ops import gf

from cluster_helpers import Cluster

RNG = np.random.default_rng(0xEC5)

needs_jax = pytest.mark.skipif(not gf.backend_available(),
                               reason="no jax backend")


def _exec(sched: xsched.XorSchedule, pk: np.ndarray) -> np.ndarray:
    """Run a schedule over a (B, C, ps) packet stack, returning the
    (B, R, ps) outputs — the naive_xor_matmul calling convention."""
    b, c, ps = pk.shape
    out = np.zeros((b, sched.n_out, ps), dtype=np.uint8)
    xsched.execute_host(sched, [pk[:, i, :] for i in range(c)],
                        [out[:, r, :] for r in range(sched.n_out)])
    return out


def _codec(technique: str, **extra):
    profile = {"plugin": "ec_jax", "technique": technique, "k": "4",
               "m": "2", "packetsize": "32", "tpu": "false"}
    profile.update({k: str(v) for k, v in extra.items()})
    return create_erasure_code(profile)


# -- compiler properties: every technique x its legal w values ---------

# (technique, k, w) across the legal parameter space: liberation w
# prime >= k, blaum_roth w+1 prime >= k, liber8tion w=8 k<=8
MATRIX_SPACE = [
    ("liberation", 4, 5), ("liberation", 4, 7),
    ("liberation", 4, 11), ("liberation", 4, 13),
    ("blaum_roth", 4, 4), ("blaum_roth", 4, 6),
    ("blaum_roth", 4, 10), ("blaum_roth", 4, 12),
    ("liber8tion", 2, 8), ("liber8tion", 4, 8), ("liber8tion", 8, 8),
]


def _matrix(technique: str, k: int, w: int) -> np.ndarray:
    if technique == "liberation":
        return bmx.liberation_bitmatrix(k, w)
    if technique == "blaum_roth":
        return bmx.blaum_roth_bitmatrix(k, w)
    return bmx.liber8tion_bitmatrix(k)


@pytest.mark.parametrize("technique,k,w", MATRIX_SPACE)
def test_schedule_matches_naive_encode_matrix(technique, k, w):
    bm = _matrix(technique, k, w)
    sched = xsched.compile_matrix(bm)
    pk = RNG.integers(0, 256, (3, bm.shape[1], 24), dtype=np.uint8)
    assert np.array_equal(_exec(sched, pk),
                          xsched.naive_xor_matmul(bm, pk))
    # CSE never costs ops, and the bookkeeping is consistent
    assert sched.xors_scheduled <= sched.xors_naive
    assert sched.n_slots <= max(len(sched.ops), 1)


@pytest.mark.parametrize("technique,k,w", MATRIX_SPACE)
def test_schedule_matches_naive_every_erasure_pattern(technique, k, w):
    """Decode rows for EVERY 1- and 2-erasure pattern execute
    bit-exactly: the dense inverted submatrices are where the CSE
    bites hardest (the deepest temp chains + slot reuse)."""
    bm = _matrix(technique, k, w)
    n = k + 2
    for nerased in (1, 2):
        for erased in itertools.combinations(range(n), nerased):
            have = tuple(i for i in range(n) if i not in erased)[:k]
            rows = bmx.decode_bitmatrix(bm, k, w, have,
                                        tuple(erased))
            sched = xsched.compile_matrix(rows)
            pk = RNG.integers(0, 256, (2, rows.shape[1], 16),
                              dtype=np.uint8)
            assert np.array_equal(
                _exec(sched, pk), xsched.naive_xor_matmul(rows, pk)), \
                (technique, w, erased)


def test_compile_is_deterministic():
    bm = bmx.liberation_bitmatrix(4, 7)
    s1 = xsched.compile_matrix(bm)
    xsched.clear()
    s2 = xsched.compile_matrix(bm)
    assert s1 == s2


def test_decode_reduction_clears_acceptance_bar():
    """The measured-XOR-count acceptance: >= 25% reduction on at
    least one bitmatrix technique (the decode inverses)."""
    best = 0.0
    for technique, k, w in (("liberation", 4, 7),
                            ("liber8tion", 4, 8)):
        bm = _matrix(technique, k, w)
        rows = bmx.decode_bitmatrix(bm, k, w, tuple(range(2, k + 2)),
                                    (0, 1))
        best = max(best, xsched.compile_matrix(rows).reduction_pct)
    assert best >= 25.0


# -- codec-level kill-switch parity ------------------------------------

SWEEP = [("liberation", {"w": 7}), ("blaum_roth", {"w": 6}),
         ("liber8tion", {"w": 8})]


@pytest.mark.parametrize("technique,extra", SWEEP)
def test_kill_switch_parity_every_erasure_pattern(monkeypatch,
                                                  technique, extra):
    """Scheduled and naive paths are bit-identical end to end: same
    parity chunks, and every 1-/2-erasure decode recovers the same
    bytes under both modes (decoding xsched-encoded chunks with the
    kill switch on, and vice versa)."""
    codec = _codec(technique, **extra)
    n = codec.k + codec.m
    payload = bytes(RNG.integers(
        0, 256, 2 * codec.get_alignment() - 11, dtype=np.uint8))
    monkeypatch.setenv("CEPH_TPU_XSCHED", "1")
    enc_on = codec.encode(range(n), payload)
    monkeypatch.setenv("CEPH_TPU_XSCHED", "0")
    enc_off = codec.encode(range(n), payload)
    assert {i: bytes(b) for i, b in enc_on.items()} == \
        {i: bytes(b) for i, b in enc_off.items()}
    chunk_len = len(enc_on[0])
    for nerased in (1, 2):
        for erased in itertools.combinations(range(n), nerased):
            avail = {i: bytes(enc_on[i]) for i in range(n)
                     if i not in erased}
            monkeypatch.setenv("CEPH_TPU_XSCHED", "0")
            dec_off = codec.decode(range(n), avail, chunk_len)
            monkeypatch.setenv("CEPH_TPU_XSCHED", "1")
            dec_on = codec.decode(range(n), avail, chunk_len)
            for i in range(n):
                assert bytes(dec_on[i]) == bytes(enc_on[i]), \
                    (technique, erased, i)
                assert bytes(dec_off[i]) == bytes(enc_on[i]), \
                    (technique, erased, i)


# -- GF(2^8) bit-expansion equivalence on ragged chunk sizes -----------

@pytest.mark.parametrize("builder,k,m", [
    (rs.cauchy_good_matrix, 4, 2),
    (rs.cauchy_orig_matrix, 3, 3),
    (rs.reed_sol_van_matrix, 4, 2),
])
@pytest.mark.parametrize("ps", [1, 3, 17, 33])
def test_gf256_bit_expansion_equivalence_ragged(builder, k, m, ps):
    """jerasure/cauchy-style GF(2^8) matrices expanded to bits via
    gf_matrix_to_bits schedule-execute bit-exactly on ragged packet
    widths (no alignment assumptions in the executor)."""
    bits = gf.gf_matrix_to_bits(builder(k, m))
    sched = xsched.compile_matrix(bits)
    pk = RNG.integers(0, 256, (2, bits.shape[1], ps), dtype=np.uint8)
    assert np.array_equal(_exec(sched, pk),
                          xsched.naive_xor_matmul(bits, pk))


# -- the live-cluster kill-switch leg ----------------------------------

LIBERATION_PROFILE = {"plugin": "ec_jax", "technique": "liberation",
                      "k": "4", "m": "2", "w": "7",
                      "packetsize": "64",
                      "crush-failure-domain": "osd"}


def test_kill_switch_parity_live_cluster(monkeypatch):
    """Writes encoded under one mode read back bit-identically under
    the other, through real daemons: the schedule is a pure lowering
    change, invisible on the wire and on disk."""
    payload = bytes(RNG.integers(0, 256, 7168, dtype=np.uint8))

    async def main():
        cluster = Cluster(num_osds=6, osds_per_host=6)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "xspool", profile=LIBERATION_PROFILE, pg_num=8)
            io = cluster.client.open_ioctx("xspool")
            monkeypatch.setenv("CEPH_TPU_XSCHED", "1")
            await io.write_full("o-sched", payload)
            monkeypatch.setenv("CEPH_TPU_XSCHED", "0")
            await io.write_full("o-naive", payload)
            # cross-mode reads: naive decode of scheduled encode and
            # the reverse
            assert bytes(await io.read("o-sched")) == payload
            monkeypatch.setenv("CEPH_TPU_XSCHED", "1")
            assert bytes(await io.read("o-naive")) == payload
            assert bytes(await io.read("o-sched")) == payload
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(main(), 120))


# -- shared decode-rows cache ------------------------------------------

def test_decode_rows_shared_across_instances(monkeypatch):
    """Re-instantiated codecs (pool remount / registry re-resolution)
    must NOT re-invert submatrices another instance already paid
    for: the cache lives in ec/dispatch.py keyed by codec signature,
    not on the instance."""
    calls = {"n": 0}
    real = bmx.decode_bitmatrix

    def counting(*a, **k):
        calls["n"] += 1
        return real(*a, **k)

    monkeypatch.setattr(bmx, "decode_bitmatrix", counting)
    # a geometry no other test uses, so the shared cache starts cold
    c1 = _codec("liberation", k=3, w=5)
    n = c1.k + c1.m
    payload = bytes(RNG.integers(0, 256, c1.get_alignment(),
                                 dtype=np.uint8))
    enc = c1.encode(range(n), payload)
    chunk_len = len(enc[0])
    avail = {i: bytes(enc[i]) for i in range(n) if i not in (0, 1)}
    c1.decode(range(n), avail, chunk_len)
    assert calls["n"] == 1                 # cold: one inversion
    hits_before = dispatch.decode_rows_stats()["hits"]
    c2 = _codec("liberation", k=3, w=5)    # a FRESH instance
    assert c2 is not c1
    out = c2.decode(range(n), avail, chunk_len)
    assert calls["n"] == 1                 # no re-inversion
    assert dispatch.decode_rows_stats()["hits"] > hits_before
    for i in range(n):
        assert bytes(out[i]) == bytes(enc[i])


# -- memoization + plan.stats() observability --------------------------

def test_schedules_survive_plan_rebuilds():
    """The acceptance invariant: compiled schedules are keyed by
    matrix signature, so plan-cache rebuilds (mesh shrink retires
    keys, quarantine evicts them, clear() drops everything) never
    cost a recompilation — visible in plan.stats()['xsched']."""
    codec = _codec("liber8tion", w=8)
    n = codec.k + codec.m
    payload = bytes(RNG.integers(0, 256, codec.get_alignment(),
                                 dtype=np.uint8))
    xsched.clear()
    xsched.reset_stats()
    codec.encode(range(n), payload)
    st1 = plan.stats()["xsched"]
    assert st1["compiled"] >= 1
    assert st1["xors_scheduled"] <= st1["xors_naive"]
    plan.clear()                      # every ExecPlan key retired
    codec2 = _codec("liber8tion", w=8)
    codec2.encode(range(n), payload)
    st2 = plan.stats()["xsched"]
    assert st2["compiled"] == st1["compiled"]     # NO recompilation
    assert st2["cache_hits"] > st1["cache_hits"]
    assert st2["enabled"] is True


def test_kill_switch_compiles_nothing(monkeypatch):
    monkeypatch.setenv("CEPH_TPU_XSCHED", "0")
    codec = _codec("liberation", w=7)
    n = codec.k + codec.m
    payload = bytes(RNG.integers(0, 256, codec.get_alignment(),
                                 dtype=np.uint8))
    xsched.reset_stats()
    codec.encode(range(n), payload)
    st = plan.stats()["xsched"]
    assert st["compiled"] == 0 and st["enabled"] is False


# -- the schedule-vs-matmul pick ---------------------------------------

def test_prefer_schedule_policy(monkeypatch):
    sparse = xsched.compile_matrix(bmx.liberation_bitmatrix(4, 7))
    dense = xsched.compile_matrix(
        gf.gf_matrix_to_bits(rs.reed_sol_van_matrix(8, 3)))
    # the dense k8m3 expansion keeps the MXU matmul by op count
    assert dense.xors_scheduled > 256
    assert not xsched.prefer_schedule(dense)
    # the sparse encode matrix saves < 25% (minimal-density codes
    # are near-optimal already): not preferred by default...
    assert not xsched.prefer_schedule(sparse)
    # ...but the knobs are live
    monkeypatch.setenv("CEPH_TPU_XSCHED_MIN_REDUCTION", "0")
    assert xsched.prefer_schedule(sparse)
    monkeypatch.setenv("CEPH_TPU_XSCHED", "0")
    assert not xsched.prefer_schedule(sparse)


@needs_jax
@pytest.mark.skipif(conftest.DEVICE_INJECTION,
                    reason="asserts live device-dispatch counters/plans;\
 subject absent under scripted device-fault injection")
def test_xor_sched_plan_kind_next_to_matmul(monkeypatch):
    """The device tier: a matrix whose schedule wins by measured op
    count dispatches through the `xor_sched` plan kind, bit-exact
    with the host oracle; the kill switch pins the matmul kind."""
    monkeypatch.setenv("CEPH_TPU_XSCHED_MIN_REDUCTION", "0")
    mat = rs.reed_sol_van_matrix(4, 2)
    data = RNG.integers(0, 256, (2, 4, 256), dtype=np.uint8)
    want = np.stack([gf.gf_matmul_host(mat, data[i])
                     for i in range(2)])
    plan.clear()
    plan.reset_stats()
    out = plan.encode(mat, data)
    assert out is not None and np.array_equal(out, want)
    labels = plan.stats()["per_plan"]
    assert any(lbl.startswith("xor_sched") for lbl in labels), labels
    # second dispatch in the bucket: a plan-cache hit, no retrace
    assert plan.encode(mat, data) is not None
    assert plan.stats()["hits"] >= 1
    # kill switch: same math through the matmul kind, bit-identical
    monkeypatch.setenv("CEPH_TPU_XSCHED", "0")
    plan.clear()
    plan.reset_stats()
    out2 = plan.encode(mat, data)
    assert out2 is not None and np.array_equal(out2, want)
    assert not any(lbl.startswith("xor_sched")
                   for lbl in plan.stats()["per_plan"])


@needs_jax
def test_gf_matmul_device_consumer_pick(monkeypatch):
    """ops/gf.gf_matmul_device consumers pick schedule-vs-matmul by
    measured op count: the direct (non-plan) entry routes a winning
    matrix through the jitted schedule executor, bit-exactly."""
    monkeypatch.setenv("CEPH_TPU_XSCHED_MIN_REDUCTION", "0")
    mat = rs.cauchy_good_matrix(4, 2)
    assert plan.xor_sched_direct(mat) is not None
    data = RNG.integers(0, 256, (4, 128), dtype=np.uint8)
    out = np.asarray(gf.gf_matmul_device(mat, data))
    assert np.array_equal(out, gf.gf_matmul_ref(mat, data))
    monkeypatch.setenv("CEPH_TPU_XSCHED", "0")
    assert plan.xor_sched_direct(mat) is None
    out2 = np.asarray(gf.gf_matmul_device(mat, data))
    assert np.array_equal(out2, out)

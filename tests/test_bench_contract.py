"""bench.py driver-contract tier: the one-line JSON contract must go
out within the time budget even when TPU device init hangs (the
BENCH_r05 rc=124 wedged-tunnel failure), and even when the bench body
itself dies.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")

sys.path.insert(0, REPO)
import bench  # noqa: E402

CONTRACT_KEYS = {"metric", "value", "unit", "vs_baseline",
                 "plan_cache", "encode_service", "tier",
                 "device_health", "tail", "load", "durability",
                 "mesh", "multihost", "trace", "group_commit",
                 "compute", "xsched", "spmd", "repair", "inference",
                 "chaos", "truncated"}


def test_contract_line_despite_hanging_backend(tmp_path):
    """Simulated wedged tunnel: the backend probe hangs forever; the
    bench must fall back to the host/CPU tier and still print the
    contract line first, within the budget."""
    env = dict(os.environ)
    env.update({
        # the stubbed backend: hangs until the probe's hard timeout
        "CEPH_TPU_BENCH_PROBE": "import time; time.sleep(300)",
        "CEPH_TPU_BENCH_PROBE_TIMEOUT": "1",
        "CEPH_TPU_BENCH_PROBE_ATTEMPTS": "2",
        "CEPH_TPU_BENCH_PROBE_RETRY_SLEEP": "0",
        "CEPH_TPU_BENCH_SMOKE": "1",
    })
    r = subprocess.run([sys.executable, BENCH], capture_output=True,
                       text=True, timeout=240, cwd=str(tmp_path),
                       env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    stdout_lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert stdout_lines, f"no stdout; stderr: {r.stderr[-2000:]}"
    contract = json.loads(stdout_lines[0])  # FIRST line, parseable
    assert set(contract) == CONTRACT_KEYS
    assert contract["metric"] == "ec_jax_encode_k8m3_4MiB_stripe"
    assert contract["unit"] == "GiB/s"
    assert contract["value"] is not None and contract["value"] > 0
    # the plan-cache probe ran: one miss (compile) and one hit on the
    # same bucketed shape
    assert contract["plan_cache"]["misses"] >= 1
    assert contract["plan_cache"]["hits"] >= 1
    # the encode-service probe ran: concurrent requests shared batched
    # dispatches (bit-exactness is asserted inside the probe)
    assert contract["encode_service"]["requests"] >= 1
    assert contract["encode_service"]["batches"] >= 1
    assert contract["encode_service"]["batched"] >= 1
    # the tier probe ran: device-batched bloom matched the host
    # oracle bit-exactly and the agent promoted + served hot reads
    assert contract["tier"]["device_bitexact"] == 1
    assert contract["tier"]["records"] >= 1
    assert contract["tier"]["promote"] >= 1
    assert contract["tier"]["hit"] >= 1
    # the device-health probe ran: forced device failure degraded to
    # the bit-exact host path, tripped the breaker, and a half-open
    # probe re-closed it once injection cleared
    assert contract["device_health"]["bitexact"] == 1
    assert contract["device_health"]["trips"] >= 1
    assert contract["device_health"]["failures"] >= 1
    assert contract["device_health"]["probes"] >= 1
    assert contract["device_health"]["recovered"] == 1
    # the hedge probe ran: the need=4 gather completed from the first
    # four distinct arrivals, the 1 s stragglers were hedged around
    # and cancelled, and nothing leaked
    assert contract["tail"]["completed_shards"] >= 4
    assert contract["tail"]["straggler_avoided"] == 1
    assert contract["tail"]["hedges_fired"] >= 1
    assert contract["tail"]["cancelled_subreads"] >= 1
    assert contract["tail"]["leaked_tasks"] == 0
    # the open-loop load probe ran: hundreds of tenants drove the
    # embedded cluster, goodput + streaming percentiles came back,
    # and the schedule generator is deterministic
    assert contract["load"]["tenants"] >= 100
    assert contract["load"]["completed"] >= 1
    assert contract["load"]["goodput_mib_s"] > 0
    assert contract["load"]["p99_ms"] is not None
    assert contract["load"]["p99_ms"] > 0
    assert contract["load"]["deterministic"] == 1
    # the crash-consistency probe ran: the smoke power-cut sweep
    # explored crash points with ZERO invariant violations, and the
    # deliberately-broken store (fsync removed) was caught by the
    # same sweep (the harness self-test)
    assert contract["durability"]["points"] >= 20
    assert contract["durability"]["violations"] == 0
    assert contract["durability"]["broken_store_caught"] == 1
    # the mesh probe ran: the same batch was bit-identical through
    # the single-device plan, the N-device mesh plan and the host
    # oracle, and a scripted sick chip SHRANK the mesh (per-device
    # breaker tripped, survivors re-planned) instead of degrading
    # the batch to host
    assert contract["mesh"]["devices"] >= 2
    assert contract["mesh"]["bitexact"] == 1
    assert contract["mesh"]["mesh_dispatches"] >= 1
    assert contract["mesh"]["sick_chip_shrunk"] == 1
    assert contract["mesh"]["host_fallbacks"] == 0
    # the multihost probe ran: a REAL 2-process jax.distributed group
    # encoded bit-exactly on the hybrid DCN x ICI mesh, and the
    # host-loss leg retired the lost host as ONE event (one shrink,
    # no per-chip breaker storm, zero host fallbacks, the fused-crc
    # family still closed)
    mh = contract["multihost"]
    assert mh["processes_max"] >= 2
    assert mh["multihost_bitexact"] == 1
    assert mh["host_loss_bitexact"] == 1
    assert mh["host_loss_shrunk"] == 1
    assert mh["host_loss_one_event"] == 1
    assert mh["host_loss_host_fallbacks"] == 0
    assert mh["host_loss_fused_crc_closed"] == 1
    # the trace probe ran: the critical-path reducer reconstructed
    # the hand-built tree (longest hedged child on the path, the
    # cancelled straggler off it), live ops fed the per-stage
    # histograms, and the spans-on-vs-kill-switch overhead was
    # measured at sample rate 0 (the ≤2% production bound is judged
    # on quiet bench hardware, not asserted in this noisy tier)
    assert contract["trace"]["cp_ok"] == 1
    assert contract["trace"]["stages_seen"] >= 1
    assert contract["trace"]["stage_samples"] >= 1
    assert isinstance(contract["trace"]["overhead_pct"], (int, float))
    # the stable decomposition enforces the <=2% bound: measured
    # span-layer cost per op over the measured live EC op cost
    assert contract["trace"]["overhead_ratio_pct"] <= 2.0
    # the group-commit probe ran: N concurrent durable writes shared
    # barriers (fsyncs strictly under the writer count) bit-exactly,
    # while the kill-switch leg paid one sync commit per txn
    gc = contract["group_commit"]
    assert gc["writers"] >= 8
    assert gc["fsyncs_lt_writers"] == 1
    assert gc["fsyncs"] < gc["writers"]
    assert gc["kv_commits"] < gc["kv_commits_inline"]
    assert gc["kv_commits_inline"] == gc["writers"]
    assert gc["bitexact"] == 1
    assert gc["batches"] >= 1
    # the coded-compute probe ran: every registered linear kernel
    # evaluated on a parity-including k-subset of one object's coded
    # shards result-domain-decoded bit-exactly to the host reference,
    # and the hedged sub-compute straggler leg completed from the
    # first k shard-results (the 1 s straggler cancelled)
    cp = contract["compute"]
    assert cp["bitexact"] == 1
    assert cp["linear_kernels"] >= 2
    assert cp["straggler_avoided"] == 1
    assert cp["first_k_bitexact"] == 1
    assert cp["cancelled_subcomputes"] >= 1
    # the codec-compiler probe ran: every compiled XOR schedule
    # executed bit-exactly against the naive row-walk oracle, the
    # memo served repeat compiles, and the best measured XOR-count
    # reduction cleared the >=25% acceptance bar
    xs = contract["xsched"]
    assert xs["bitexact"] == 1
    assert xs["xor_reduction_pct"] >= 25
    assert xs["schedules"] >= 1
    assert xs["cache_hits"] >= 1
    assert xs["xors_scheduled"] < xs["xors_naive"]
    # the native fused-tape executor leg: when the C++ executor is
    # buildable (it is, in CI) the lowered tape ran bit-exactly on a
    # packed multi-object arena AND through the execute() seam, with
    # the tape memo serving the re-lower
    assert xs["native_available"] in (0, 1)
    if xs["native_available"]:
        assert xs["native_bitexact"] == 1
        assert xs["exec_native"] >= 2
        assert xs["tape_misses"] >= 1
        assert xs["tape_hits"] >= 1
    # the SPMD collective-safety probe ran: the static collective-site
    # map is non-empty, the 2-process smoke leg's runtime-observed
    # collective trace was a subset of it, and every process observed
    # the same collective order (the analyzer's runtime cross-check
    # riding the multihost sweep)
    sp = contract["spmd"]
    assert sp["static_sites"] >= 5
    assert sp["static_lines"] >= sp["static_sites"]
    assert sp["runtime_sites"] >= 1
    assert sp["runtime_subset_static"] == 1
    assert sp["order_congruent"] == 1
    # the MSR repair probe ran: every single-erasure pattern rebuilt
    # bit-exact from d beta-fragments, and the fragment bytes beat the
    # classic k-read (the regenerating-code point: ratio < 1)
    rp = contract["repair"]
    assert rp["patterns_bitexact"] == rp["k"] + rp["m"]
    assert rp["alpha"] == rp["d"] - rp["k"] + 1
    assert 0 < rp["bytes_ratio_vs_kread"] < 1
    # the coded-inference probe ran: the full-set Fisher combine is
    # bit-exact against the host oracle, every single-shard-loss
    # pattern stayed within the error budget with an honest estimate
    # (rel <= est <= budget), and the hedged sub-infer straggler leg
    # completed from the first sufficient arrival set (slow stream
    # substituted by a fused shard, straggler cancelled)
    inf = contract["inference"]
    assert inf["bitexact"] == 1
    assert inf["within_budget"] == 1
    assert inf["patterns"] >= 3
    assert inf["max_rel_err"] <= inf["max_est_error"] <= inf["budget"]
    assert inf["straggler_avoided"] == 1
    assert inf["straggler_within_budget"] == 1
    assert inf["substituted_streams"] >= 1
    assert inf["cancelled_subinfers"] >= 1
    # the compound-chaos probe ran: a seeded composed 3-hazard
    # scenario (stragglers x device faults x kill-switch flips) over
    # live two-tenant traffic with every invariant monitor armed —
    # zero violations, zero client errors, reads verified bit-exact,
    # the seed echoed so a red round replays from the contract line
    ch = contract["chaos"]
    assert ch["violations"] == 0
    assert ch["errors"] == 0
    assert ch["seed"] == 20107
    assert ch["events_fired"] >= 2
    assert ch["reads_verified"] >= 1
    assert ch["flag_flips"] >= 1
    assert contract["truncated"] is False
    # details stayed out of stdout (they belong in bench_details.json)
    assert len(stdout_lines) == 1
    assert (tmp_path / "bench_details.json").exists()
    details = json.loads((tmp_path / "bench_details.json").read_text())
    assert "plan_cache" in details and "retraces" in details["plan_cache"]


def test_fallback_contract_when_bench_body_dies(monkeypatch, capsys):
    """Even a crash in main() yields the contract line (null value)."""
    monkeypatch.setattr(bench, "_ensure_backend", lambda: "cpu")
    monkeypatch.setattr(
        bench, "main",
        lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    monkeypatch.setattr(bench, "_contract_emitted", False)
    assert bench.cli() == 0
    out = capsys.readouterr().out.strip().splitlines()
    contract = json.loads(out[0])
    assert set(contract) == CONTRACT_KEYS
    assert contract["value"] is None


def test_budget_truncates_optional_sections(tmp_path):
    """An exhausted wall-clock budget (CEPH_TPU_BENCH_BUDGET) skips
    the optional sections but still emits the full contract line,
    flagged truncated, well inside the harness timeout."""
    env = dict(os.environ)
    env.update({
        "CEPH_TPU_BENCH_PROBE": "print('cpu')",
        "CEPH_TPU_BENCH_SMOKE": "1",
        "CEPH_TPU_BENCH_BUDGET": "0",
    })
    r = subprocess.run([sys.executable, BENCH], capture_output=True,
                       text=True, timeout=240, cwd=str(tmp_path),
                       env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    stdout_lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    contract = json.loads(stdout_lines[0])
    assert set(contract) == CONTRACT_KEYS
    assert contract["truncated"] is True
    assert contract["value"] is not None and contract["value"] > 0
    details = json.loads((tmp_path / "bench_details.json").read_text())
    assert details["truncated"] is True
    assert details["skipped_sections"]
    # the new open-loop sections ride the SAME single budget
    # decision: a tiny budget must skip them (never hang on them),
    # and the skip is recorded
    assert "load" in details["skipped_sections"]
    assert "load_sweep" not in details
    # the mesh sweep section too (the probe's `mesh` contract key is
    # pre-contract and still rides, budget permitting)
    assert "mesh" in details["skipped_sections"]
    assert "mesh_sweep" not in details
    # and the multihost process sweep
    assert "multihost" in details["skipped_sections"]
    assert "process_sweep" not in details
    # and the trace decomposition section
    assert "trace" in details["skipped_sections"]
    assert "trace_stage_summary" not in details
    # and the codec-compiler sweep (its `xsched` contract key is
    # pre-contract and still rides, budget permitting)
    assert "xsched" in details["skipped_sections"]
    assert "xsched_sweep" not in details
    # and the small-op open-loop section
    assert "smallop" in details["skipped_sections"]
    assert "smallop_modes" not in details
    # and the coded-inference serving section (its `inference`
    # contract key is pre-contract and still rides, budget permitting)
    assert "inference" in details["skipped_sections"]
    assert "inference_modes" not in details
    # the full chaos matrix is smoke-gated (like qos/durability), so
    # a budget-0 smoke run skips the section body without recording
    # it — but the pre-contract chaos probe key must NOT ride when
    # the budget is already spent
    assert "chaos_violations" not in details


def test_watchdog_contract_line_survives_outer_kill(tmp_path):
    """The BENCH_r05 rc=124 regression: a bench body that WEDGES in a
    mandatory stage under a tiny wall-clock budget must still flush a
    parseable (truncated) contract line via the deadline watchdog
    BEFORE the outer harness timeout kills the process."""
    env = dict(os.environ)
    env.update({
        "CEPH_TPU_BENCH_PROBE": "print('cpu')",
        "CEPH_TPU_BENCH_SMOKE": "1",
        "CEPH_TPU_BENCH_BUDGET": "1",         # artificially tiny
        "CEPH_TPU_BENCH_WATCHDOG_MARGIN": "2",
        "CEPH_TPU_BENCH_STALL_S": "120",      # the wedge
    })
    proc = subprocess.Popen([sys.executable, BENCH],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True,
                            cwd=str(tmp_path), env=env)
    # wait only until the watchdog's line actually lands (~budget +
    # margin = 3 s), then play the harness and kill the stalled
    # process — no need to burn the whole stall on the clock
    import threading

    box: dict = {}

    def reader():
        box["line"] = proc.stdout.readline()

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    t.join(45)
    proc.kill()
    proc.wait()
    assert proc.returncode != 0  # the outer kill DID happen (rc=124 shape)
    line = box.get("line", "")
    assert line.strip(), "no contract line before the kill"
    contract = json.loads(line)
    assert set(contract) == CONTRACT_KEYS
    assert contract["metric"] == "ec_jax_encode_k8m3_4MiB_stripe"
    assert contract["truncated"] is True
    assert contract["value"] is None  # no measurement this round


def test_probe_timeout_contained():
    """A hanging probe is killed at the timeout, not waited out."""
    env_probe = os.environ.get("CEPH_TPU_BENCH_PROBE")
    os.environ["CEPH_TPU_BENCH_PROBE"] = "import time; time.sleep(60)"
    try:
        assert bench._probe_backend(timeout_s=1.0) is None
    finally:
        if env_probe is None:
            os.environ.pop("CEPH_TPU_BENCH_PROBE", None)
        else:
            os.environ["CEPH_TPU_BENCH_PROBE"] = env_probe


def test_probe_reports_platform():
    env_probe = os.environ.get("CEPH_TPU_BENCH_PROBE")
    os.environ["CEPH_TPU_BENCH_PROBE"] = "print('cpu')"
    try:
        assert bench._probe_backend(timeout_s=30.0) == "cpu"
    finally:
        if env_probe is None:
            os.environ.pop("CEPH_TPU_BENCH_PROBE", None)
        else:
            os.environ["CEPH_TPU_BENCH_PROBE"] = env_probe

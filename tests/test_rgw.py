"""RGW-lite tier: processor units + multipart PUT over a live cluster.

The cluster case is BASELINE config #5's shape: a 64 MiB multipart PUT
into an EC 8+3 pool (qa equivalent: s3-tests multipart suite +
rgw_putobj_processor unit tests in the reference)."""

import asyncio

import numpy as np
import pytest

from ceph_tpu.rgw import Manifest, PutObjProcessor, RGWError, RGWLite
from ceph_tpu.rgw.put_processor import StripeWriter

from cluster_helpers import Cluster

EC83_PROFILE = {"plugin": "ec_jax", "technique": "reed_sol_van",
                "k": "8", "m": "3", "crush-failure-domain": "osd",
                # cluster tests run on the CPU backend where the XLA
                # bit-matmul is slower than the native SIMD host path
                "tpu": "false"}


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 180))


# -- processor unit tier ---------------------------------------------------


class FakeIoCtx:
    def __init__(self):
        self.objects = {}

    async def write_full(self, oid, data):
        self.objects[oid] = bytes(data)

    async def read(self, oid):
        return self.objects[oid]

    async def remove(self, oid):
        del self.objects[oid]


def test_processor_stripe_cutting():
    async def main():
        io = FakeIoCtx()
        writer = StripeWriter(io, window=4)
        proc = PutObjProcessor(writer, "head", stripe_size=1000)
        payload = bytes(range(256)) * 11  # 2816 bytes -> 2 full + tail
        # feed in awkward runs to exercise buffering
        for i in range(0, len(payload), 300):
            await proc.process(payload[i:i + 300])
        manifest = await proc.complete()
        assert manifest.obj_size == len(payload)
        assert [s["size"] for s in manifest.stripes] == [1000, 1000, 816]
        assert manifest.stripes[0]["oid"] == "head"
        assert manifest.stripes[1]["oid"] == "head_shadow_1"
        got = b"".join(io.objects[s["oid"]] for s in manifest.stripes)
        assert got == payload

    run(main())


def test_processor_exact_multiple_and_cancel():
    async def main():
        io = FakeIoCtx()
        writer = StripeWriter(io, window=2)
        proc = PutObjProcessor(writer, "x", stripe_size=512)
        await proc.process(b"a" * 1024)  # exactly 2 stripes, no tail
        manifest = await proc.complete()
        assert [s["size"] for s in manifest.stripes] == [512, 512]
        # cancel path deletes what was written
        writer2 = StripeWriter(io, window=2)
        proc2 = PutObjProcessor(writer2, "y", stripe_size=256)
        await proc2.process(b"b" * 600)
        await writer2.drain()
        await writer2.cancel()
        assert not any(o.startswith("y") for o in io.objects)

    run(main())


def test_manifest_stitch():
    m1 = Manifest(10, [{"oid": "a", "size": 10}])
    m2 = Manifest(7, [{"oid": "b", "size": 7}])
    m1.append(m2)
    assert m1.obj_size == 17
    assert [s["oid"] for s in m1.stripes] == ["a", "b"]


# -- cluster tier ----------------------------------------------------------


async def _gateway(cluster) -> RGWLite:
    await cluster.client.create_replicated_pool(
        "rgw.meta", size=3, pg_num=8)
    await cluster.client.create_ec_pool(
        "rgw.data", profile=EC83_PROFILE, pg_num=8)
    return RGWLite(cluster.client, "rgw.data", "rgw.meta")


@pytest.mark.slow
def test_multipart_put_64mib_ec8p3():
    """BASELINE #5 shape: 64 MiB multipart PUT into EC 8+3, round-trip."""
    async def main():
        cluster = Cluster(num_osds=12, osds_per_host=3)
        await cluster.start()
        try:
            rgw = await _gateway(cluster)
            await rgw.create_bucket("b")
            payload = np.random.default_rng(42).integers(
                0, 256, 64 << 20, dtype=np.uint8).tobytes()
            upload = await rgw.init_multipart("b", "big")
            parts = []
            psize = 16 << 20
            for num in range(1, 5):
                chunk = payload[(num - 1) * psize:num * psize]
                etag = await rgw.upload_part("b", "big", upload, num,
                                             chunk)
                parts.append((num, etag))
            combined = await rgw.complete_multipart("b", "big", upload,
                                                    parts)
            assert combined.endswith("-4")
            got = await rgw.get_object("b", "big")
            assert got == payload
            listing = await rgw.list_objects("b")
            assert listing[0]["key"] == "big"
            assert listing[0]["size"] == len(payload)
        finally:
            await cluster.stop()

    run(main())


def test_atomic_put_get_delete_and_errors():
    async def main():
        cluster = Cluster(num_osds=12, osds_per_host=3)
        await cluster.start()
        try:
            rgw = await _gateway(cluster)
            await rgw.create_bucket("b")
            with pytest.raises(RGWError):
                await rgw.create_bucket("b")
            data = np.random.default_rng(1).integers(
                0, 256, 9_000_000, dtype=np.uint8).tobytes()
            etag = await rgw.put_object("b", "obj", data)
            assert await rgw.get_object("b", "obj") == data
            assert (await rgw.list_objects("b"))[0]["etag"] == etag
            await rgw.delete_object("b", "obj")
            with pytest.raises(RGWError):
                await rgw.get_object("b", "obj")
            with pytest.raises(RGWError):
                await rgw.get_object("nope", "obj")
        finally:
            await cluster.stop()

    run(main())


def test_multipart_validation_and_abort():
    async def main():
        cluster = Cluster(num_osds=12, osds_per_host=3)
        await cluster.start()
        try:
            rgw = await _gateway(cluster)
            await rgw.create_bucket("b")
            upload = await rgw.init_multipart("b", "k")
            e1 = await rgw.upload_part("b", "k", upload, 1, b"x" * 5000)
            with pytest.raises(RGWError):   # bad etag
                await rgw.complete_multipart("b", "k", upload,
                                             [(1, "deadbeef")])
            with pytest.raises(RGWError):   # out-of-order parts
                await rgw.complete_multipart("b", "k", upload,
                                             [(2, e1), (1, e1)])
            # re-upload replaces a part
            e1b = await rgw.upload_part("b", "k", upload, 1,
                                        b"y" * 6000)
            await rgw.complete_multipart("b", "k", upload, [(1, e1b)])
            assert await rgw.get_object("b", "k") == b"y" * 6000
            # abort of a fresh upload removes its parts
            up2 = await rgw.init_multipart("b", "gone")
            await rgw.upload_part("b", "gone", up2, 1, b"z" * 4000)
            await rgw.abort_multipart("b", "gone", up2)
            with pytest.raises(RGWError):
                await rgw.upload_part("b", "gone", up2, 2, b"w")
        finally:
            await cluster.stop()

    run(main())

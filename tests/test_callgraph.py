"""Unit tier for the interprocedural layer (analysis/callgraph.py):
call-edge resolution (relative imports, `self.` method binding, the
unique-method fallback), transitive blocking summaries, async-context
inference (locks held at each suspension point, try/finally coverage,
shield detection), and atomicity-window extraction with protection
verdicts — the facts rules_async.py and the interleave cross-check
both build on."""

from __future__ import annotations

import pytest

from ceph_tpu.analysis.callgraph import (
    CallGraph, async_context, await_site_map,
    function_atomicity_windows,
)
from ceph_tpu.analysis.core import build_project


def _project(tmp_path, files):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    for name, src in files.items():
        (pkg / name).write_text(src)
    return build_project([str(pkg)])


def _fi(proj, modname, qualname):
    return proj.modules[modname].functions[qualname]


# -- call-edge resolution ----------------------------------------------


def test_callees_resolve_through_relative_imports(tmp_path):
    proj = _project(tmp_path, {
        "a.py": ("from .b import helper\n"
                 "from . import b\n\n\n"
                 "async def serve():\n"
                 "    helper()\n"
                 "    b.other()\n"),
        "b.py": ("def helper():\n    pass\n\n\n"
                 "def other():\n    pass\n"),
    })
    cg = CallGraph(proj)
    callees = {c.qualname for _, c in
               cg.callees(_fi(proj, "pkg.a", "serve"))}
    assert callees == {"helper", "other"}


def test_callees_bind_self_methods_through_class(tmp_path):
    proj = _project(tmp_path, {
        "svc.py": ("class A:\n"
                   "    def work(self):\n"
                   "        self.step()\n"
                   "    def step(self):\n"
                   "        pass\n\n\n"
                   "class B:\n"
                   "    def step(self):\n"
                   "        pass\n"),
    })
    cg = CallGraph(proj)
    (_, callee), = cg.callees(_fi(proj, "pkg.svc", "A.work"))
    assert callee.qualname == "A.step"     # A's, not B's


def test_unique_method_fallback_binds_foreign_receivers(tmp_path):
    """`conn.flush()` on a non-self receiver still resolves when
    exactly ONE class project-wide defines flush; two definitions must
    leave it unresolved rather than bind nondeterministically."""
    proj = _project(tmp_path, {
        "conn.py": ("class Conn:\n"
                    "    def flush(self):\n"
                    "        pass\n"
                    "    def close(self):\n"
                    "        pass\n"),
        "other.py": ("class Store:\n"
                     "    def close(self):\n"
                     "        pass\n"),
        "use.py": ("def run(conn):\n"
                   "    conn.flush()\n"
                   "    conn.close()\n"),
    })
    cg = CallGraph(proj)
    callees = {c.qualname for _, c in
               cg.callees(_fi(proj, "pkg.use", "run"))}
    assert callees == {"Conn.flush"}       # close is ambiguous


# -- transitive blocking summaries -------------------------------------


BLOCKING_SRC = {
    "deep.py": ("import time\n\n\n"
                "def leaf():\n"
                "    time.sleep(0.1)\n"),
    "mid.py": ("from .deep import leaf\n\n\n"
               "def helper():\n"
               "    leaf()\n\n\n"
               "async def aio_helper():\n"
               "    pass\n"),
    "top.py": ("from .mid import helper\n\n\n"
               "async def serve():\n"
               "    helper()\n"),
}


def test_blocking_chain_names_the_whole_helper_chain(tmp_path):
    proj = _project(tmp_path, BLOCKING_SRC)
    cg = CallGraph(proj)
    chain = cg.blocking_chain(_fi(proj, "pkg.mid", "helper"))
    assert chain == ["helper", "leaf", "time.sleep"]


def test_blocking_chain_skips_async_callees_and_exempt_names(tmp_path):
    """Awaiting an async callee never blocks the loop, and exempted
    memoized one-shot inits (native.get_lib's prewarmed class) are
    treated as the dict reads they are in steady state."""
    proj = _project(tmp_path, {
        "x.py": ("import time\n\n\n"
                 "async def aio():\n"
                 "    time.sleep(1)\n\n\n"
                 "def get_lib():\n"
                 "    time.sleep(1)\n\n\n"
                 "def clean():\n"
                 "    get_lib()\n"),
    })
    cg = CallGraph(proj, blocking_exempt=("get_lib",))
    assert cg.blocking_chain(_fi(proj, "pkg.x", "clean")) is None
    # the exempt helper itself still reports its own blocking call
    assert cg.blocking_chain(_fi(proj, "pkg.x", "get_lib")) == \
        ["get_lib", "time.sleep"]
    # module-qualified entries scope the exemption to ONE definition:
    # pkg.x.get_lib matches, another module's get_lib would not
    cg2 = CallGraph(proj, blocking_exempt=("pkg.x.get_lib",))
    assert cg2.blocking_chain(_fi(proj, "pkg.x", "clean")) is None
    cg3 = CallGraph(proj, blocking_exempt=("pkg.other.get_lib",))
    assert cg3.blocking_chain(_fi(proj, "pkg.x", "clean")) == \
        ["clean", "get_lib", "time.sleep"]


def test_blocking_chain_survives_recursion(tmp_path):
    proj = _project(tmp_path, {
        "r.py": ("def ping():\n"
                 "    pong()\n\n\n"
                 "def pong():\n"
                 "    ping()\n"),
    })
    cg = CallGraph(proj)
    assert cg.blocking_chain(_fi(proj, "pkg.r", "ping")) is None


def test_blocking_chain_cycle_member_not_poisoned_by_memo(tmp_path):
    """Querying a cycle member FIRST must not cache a pruned None for
    its partner: with a() -> b(), c(); b() -> a(); c() -> time.sleep,
    computing chain(a) visits b while a is on the recursion stack (b's
    only callee is pruned, no evidence).  A later fresh chain(b) query
    must still find b -> a -> c -> time.sleep."""
    proj = _project(tmp_path, {
        "cyc.py": ("import time\n\n\n"
                   "def a():\n"
                   "    b()\n"
                   "    c()\n\n\n"
                   "def b():\n"
                   "    a()\n\n\n"
                   "def c():\n"
                   "    time.sleep(1)\n"),
    })
    cg = CallGraph(proj)
    assert cg.blocking_chain(_fi(proj, "pkg.cyc", "a")) == \
        ["a", "c", "time.sleep"]
    assert cg.blocking_chain(_fi(proj, "pkg.cyc", "b")) == \
        ["b", "a", "c", "time.sleep"]


# -- async-context inference -------------------------------------------


CTX_SRC = {
    "d.py": ("import asyncio\n\n"
             "from ceph_tpu.common import lockdep\n\n\n"
             "class D:\n"
             "    def __init__(self):\n"
             "        self._lock = lockdep.Lock('fx.ctx')\n\n"
             "    async def locked(self):\n"
             "        async with self._lock:\n"
             "            await asyncio.sleep(0)\n\n"
             "    async def covered(self):\n"
             "        try:\n"
             "            await asyncio.sleep(0)\n"
             "        finally:\n"
             "            await asyncio.sleep(0)\n\n"
             "    async def shielded(self):\n"
             "        await asyncio.shield(asyncio.sleep(0))\n"),
}


def test_async_context_tracks_lock_scopes(tmp_path):
    proj = _project(tmp_path, CTX_SRC)
    ctx = async_context(proj, _fi(proj, "pkg.d", "D.locked"))
    kinds = {s.kind: s for s in ctx.suspensions}
    # the async-with ENTER suspends before the lock is held…
    assert kinds["async-with"].locks == ()
    # …the await inside the body holds it
    assert kinds["await"].locks == ("fx.ctx",)
    assert kinds["await"].lock_scopes != ()


def test_async_context_try_finally_coverage(tmp_path):
    proj = _project(tmp_path, CTX_SRC)
    ctx = async_context(proj, _fi(proj, "pkg.d", "D.covered"))
    by_line = sorted(ctx.suspensions, key=lambda s: s.line)
    assert by_line[0].in_try_finally       # the try-body await
    assert not by_line[1].in_try_finally   # the finalbody keeps outer


def test_async_context_shield_detection(tmp_path):
    proj = _project(tmp_path, CTX_SRC)
    ctx = async_context(proj, _fi(proj, "pkg.d", "D.shielded"))
    (susp,) = ctx.suspensions
    assert susp.shielded


# -- atomicity windows -------------------------------------------------


WINDOW_SRC = {
    "w.py": ("import asyncio\n\n"
             "from ceph_tpu.common import lockdep\n\n\n"
             "class W:\n"
             "    def __init__(self):\n"
             "        self._lock = lockdep.Lock('fx.win')\n"
             "        self.seq = 0\n\n"
             "    async def bare(self):\n"
             "        v = self.seq\n"
             "        await asyncio.sleep(0)\n"
             "        self.seq = v + 1\n\n"
             "    async def held(self):\n"
             "        async with self._lock:\n"
             "            v = self.seq\n"
             "            await asyncio.sleep(0)\n"
             "            self.seq = v + 1\n\n"
             "    async def split_scopes(self):\n"
             "        async with self._lock:\n"
             "            v = self.seq\n"
             "        async with self._lock:\n"
             "            self.seq = v + 1\n\n"
             "    async def no_window(self):\n"
             "        await asyncio.sleep(0)\n"
             "        v = self.seq\n"
             "        self.seq = v + 1\n"),
}


@pytest.mark.parametrize("qualname,n,protected", [
    ("W.bare", 1, False),
    ("W.held", 1, True),
    # same lock label in two SEPARATE scopes does not protect: the
    # suspension between the blocks runs unlocked
    ("W.split_scopes", 1, False),
    ("W.no_window", 0, None),
])
def test_atomicity_window_protection_verdicts(tmp_path, qualname, n,
                                              protected):
    proj = _project(tmp_path, WINDOW_SRC)
    windows = function_atomicity_windows(proj, _fi(proj, "pkg.w",
                                                   qualname))
    assert len(windows) == n
    if n:
        (w,) = windows
        assert w.attr == "self.seq"
        assert w.protected is protected


def test_await_site_map_spans_and_lock_claims(tmp_path):
    proj = _project(tmp_path, CTX_SRC)
    site_map = await_site_map(proj)
    by_qual = {}
    for (path, line), info in site_map.items():
        assert path.endswith("d.py")
        by_qual.setdefault(info["qualname"], set()).add(line)
    assert "D.locked" in by_qual and "D.shielded" in by_qual
    locked_await = [info for info in site_map.values()
                    if info["qualname"] == "D.locked"
                    and info["kind"] == "await"]
    assert locked_await and all(i["locks"] == {"fx.ctx"}
                                for i in locked_await)

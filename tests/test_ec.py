"""Erasure-code framework tests.

Modeled on the reference's typed sweeps
(/root/reference/src/test/erasure-code/TestErasureCodeJerasure.cc): per
technique — encode/decode roundtrip, erasure recovery, minimum_to_decode,
padding/alignment, chunk mapping; plus matrix-construction properties
(systematic MDS, jerasure row-k-ones invariant) and the registry contract.
"""

import numpy as np
import pytest

from ceph_tpu.ec.interface import ErasureCodeError
from ceph_tpu.ec.jax_plugin import ErasureCodeJax
from ceph_tpu.ec.registry import ErasureCodePluginRegistry, create_erasure_code
from ceph_tpu.models import reed_solomon as rs
from ceph_tpu.ops import gf

TECHNIQUES = ["reed_sol_van", "reed_sol_r6_op", "cauchy_orig", "cauchy_good"]


def make(technique, k, m, **extra):
    codec = ErasureCodeJax(technique)
    profile = {"k": str(k), "m": str(m)}
    profile.update({key: str(v) for key, v in extra.items()})
    codec.init(profile)
    return codec


# -- matrix constructions -------------------------------------------------


def test_vandermonde_first_coding_row_all_ones():
    # jerasure decodes reed_sol_van with row_k_ones=1: row k is the XOR row.
    for k, m in [(2, 1), (4, 2), (8, 3), (10, 4)]:
        mat = rs.reed_sol_van_matrix(k, m)
        assert np.all(mat[0] == 1), (k, m)


def test_vandermonde_mds_property():
    # every k x k submatrix of [I; C] must be invertible
    import itertools

    k, m = 4, 3
    mat = rs.reed_sol_van_matrix(k, m)
    gen = np.concatenate([np.eye(k, dtype=np.uint8), mat])
    for rows in itertools.combinations(range(k + m), k):
        sub = gen[list(rows)]
        gf.gf_invert_matrix(sub)  # raises if singular


def test_cauchy_mds_property():
    import itertools

    k, m = 5, 3
    for build in (rs.cauchy_orig_matrix, rs.cauchy_good_matrix):
        mat = build(k, m)
        gen = np.concatenate([np.eye(k, dtype=np.uint8), mat])
        for rows in itertools.combinations(range(k + m), k):
            gf.gf_invert_matrix(gen[list(rows)])


def test_r6_matrix_shape():
    mat = rs.reed_sol_r6_matrix(5)
    assert np.all(mat[0] == 1)
    assert list(mat[1]) == [1, 2, 4, 8, 16]


# -- roundtrip sweeps (the TestErasureCodeJerasure pattern) ---------------


@pytest.mark.parametrize("technique", TECHNIQUES)
def test_encode_decode_roundtrip(technique):
    k, m = (4, 2)
    codec = make(technique, k, m)
    rng = np.random.default_rng(42)
    data = rng.integers(0, 256, 4000, dtype=np.uint8).tobytes()  # forces padding
    want = set(range(k + m))
    encoded = codec.encode(want, data)
    assert len(encoded) == k + m
    chunk_size = codec.get_chunk_size(len(data))
    assert all(len(c) == chunk_size for c in encoded.values())

    # no erasure
    decoded = codec.decode(set(range(k)), encoded)
    assert codec.decode_concat(encoded)[: len(data)] == data

    # every single and double erasure
    import itertools

    for lost in itertools.chain(
            itertools.combinations(range(k + m), 1),
            itertools.combinations(range(k + m), 2)):
        degraded = {i: c for i, c in encoded.items() if i not in lost}
        decoded = codec.decode(set(lost) | set(range(k)), degraded)
        for i in range(k):
            assert decoded[i] == encoded[i], (technique, lost, i)


@pytest.mark.parametrize("k,m", [(2, 1), (3, 2), (8, 3), (10, 4)])
def test_roundtrip_shapes_reed_sol(k, m):
    codec = make("reed_sol_van", k, m)
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, 1 << 14, dtype=np.uint8).tobytes()
    encoded = codec.encode(set(range(k + m)), data)
    lost = (0, k)  # one data, one coding
    degraded = {i: c for i, c in encoded.items() if i not in lost[:m]}
    assert codec.decode_concat(degraded)[: len(data)] == data


def test_minimum_to_decode():
    codec = make("reed_sol_van", 4, 2)
    # want available -> itself
    mini = codec.minimum_to_decode({0, 1}, {0, 1, 2, 3, 4, 5})
    assert set(mini) == {0, 1}
    # want missing -> first k available
    mini = codec.minimum_to_decode({0}, {1, 2, 3, 4, 5})
    assert set(mini) == {1, 2, 3, 4}
    with pytest.raises(ErasureCodeError):
        codec.minimum_to_decode({0}, {1, 2, 3})


def test_chunk_size_alignment_matches_reference_formula():
    # reed_sol_van w=8: alignment = k*w*4 = 32k (ErasureCodeJerasure.cc:173-184)
    codec = make("reed_sol_van", 4, 2)
    assert codec.get_alignment() == 4 * 8 * 4
    # object 4000 -> padded to 4096 -> chunk 1024
    assert codec.get_chunk_size(4000) == 1024
    codec2 = make("reed_sol_van", 4, 2, **{"jerasure-per-chunk-alignment": "true"})
    # per-chunk: ceil(4000/4)=1000 -> pad to w*16=128 multiple -> 1024
    assert codec2.get_chunk_size(4000) == 1024


def test_chunk_mapping():
    codec = make("reed_sol_van", 2, 1, mapping="_DD")
    assert codec.get_chunk_mapping() == [1, 2, 0]
    data = bytes(range(128))
    encoded = codec.encode({0, 1, 2}, data)
    # data chunks live at positions 1 and 2, parity at 0 (chunks are
    # zero-copy views since the interface went frozen-view)
    assert bytes(encoded[1]) + bytes(encoded[2]) == data
    degraded = {i: c for i, c in encoded.items() if i != 1}
    assert codec.decode_concat(degraded)[: len(data)] == data


def test_padding_all_zero_tail_chunks():
    # tiny object: chunks beyond the data are pure padding
    k, m = 4, 2
    codec = make("reed_sol_van", k, m)
    data = b"x" * 10
    encoded = codec.encode(set(range(k + m)), data)
    cs = codec.get_chunk_size(10)
    assert encoded[0][:10] == data[: cs][:10]
    for i in range(1, k):
        assert encoded[i] == b"\0" * cs
    assert codec.decode_concat(encoded)[:10] == data


# -- registry contract ----------------------------------------------------


def test_registry_factory_and_aliases():
    for plugin in ("ec_jax", "jerasure", "isa"):
        codec = create_erasure_code(
            {"plugin": plugin, "technique": "reed_sol_van", "k": "2", "m": "2"})
        assert codec.get_chunk_count() == 4


def test_registry_default_profile():
    # osd_pool_default_erasure_code_profile (options.cc:2703)
    codec = create_erasure_code(
        {"plugin": "jerasure", "technique": "reed_sol_van", "k": "2", "m": "2"})
    data = bytes(range(256)) * 8
    encoded = codec.encode({0, 1, 2, 3}, data)
    degraded = {i: c for i, c in encoded.items() if i not in (0, 1)}
    assert codec.decode_concat(degraded)[: len(data)] == data


def test_registry_load_errors():
    reg = ErasureCodePluginRegistry.instance()
    with pytest.raises(ErasureCodeError) as e:
        reg.load("no_such_plugin_xyz")
    assert e.value.errno == 2  # ENOENT


def test_profile_echo():
    codec = create_erasure_code(
        {"plugin": "ec_jax", "k": "4", "m": "2", "technique": "reed_sol_van"})
    prof = codec.get_profile()
    assert prof["k"] == "4" and prof["technique"] == "reed_sol_van"


def test_decode_table_cache():
    codec = make("reed_sol_van", 4, 2)
    data = bytes(range(256)) * 2
    encoded = codec.encode(set(range(6)), data)
    degraded = {i: c for i, c in encoded.items() if i != 0}
    codec.decode({0}, degraded)
    assert len(codec._decode_cache) == 1
    codec.decode({0}, degraded)
    assert len(codec._decode_cache) == 1  # cache hit, not regrown

"""CephFS snapshots: the .snap pseudo-directory over RADOS
self-managed snaps.

Reference parity targets (/root/reference/src/mds/SnapServer.h,
src/mds/snap.cc SnapRealm, src/mds/Server.cc handle_client_mksnap,
src/client/Client.cc snapdir traversal):

1. mkdir <dir>/.snap/<name> snapshots the subtree; files later
   overwritten/deleted keep their snapshot content readable through
   <dir>/.snap/<name>/...;
2. names created AFTER the snapshot do not appear in it;
3. rmdir <dir>/.snap/<name> removes it (and the OSDs trim the clones);
4. everything under .snap is read-only;
5. snapshots survive MDS failover (snap table + contexts re-armed on
   takeover);
6. a capped writer that never talks to the MDS again still COWs its
   first post-snapshot write (the recall carries the snap context).
"""

import asyncio

import pytest

from cluster_helpers import Cluster

from ceph_tpu.cephfs import CephFS, CephFSError
from ceph_tpu.mds import MDSDaemon
from ceph_tpu.rados.client import RadosClient

EROFS = -30


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 150))


async def _fs_cluster(num_clients=1, num_mds=1, num_ranks=1):
    cluster = Cluster(num_osds=4)
    await cluster.start()
    await cluster.client.create_replicated_pool(
        "cephfs.meta", size=2, pg_num=8)
    await cluster.client.create_replicated_pool(
        "cephfs.data", size=2, pg_num=8)
    mdss = []
    for i in range(num_mds):
        mds = MDSDaemon(cluster.mon.addr, "cephfs.meta", "cephfs.data",
                        name=chr(ord("a") + i), lock_interval=0.3,
                        rank=i % num_ranks, num_ranks=num_ranks)
        await mds.start()
        mdss.append(mds)
    clients, fss = [], []
    for i in range(num_clients):
        rc = RadosClient(cluster.mon.addr, name=f"client.snap{i}")
        await rc.connect()
        clients.append(rc)
        fss.append(CephFS(rc, "cephfs.meta", "cephfs.data"))
    return cluster, mdss, clients, fss


async def _teardown(cluster, mdss, clients):
    for mds in mdss:
        await mds.stop()
    for rc in clients:
        await rc.shutdown()
    await cluster.stop()


def test_snapshot_basic_cow():
    """Overwrite after mksnap: head shows new bytes, .snap the old."""
    async def main():
        cluster, mdss, clients, (fs,) = await _fs_cluster()
        try:
            await fs.mkdir("/proj")
            await fs.write_file("/proj/f", b"version-one")
            # the reference's surface: mkdir inside .snap IS mksnap
            await fs.mkdir("/proj/.snap/s1")
            await fs.write_file("/proj/f", b"version-TWO!")
            assert await fs.read_file("/proj/f") == b"version-TWO!"
            assert await fs.read_file("/proj/.snap/s1/f") == \
                b"version-one"
            st = await fs.stat("/proj/.snap/s1/f")
            assert st["size"] == len(b"version-one")
            assert await fs.listdir("/proj/.snap") == ["s1"]
            snaps = await fs.lssnap("/proj")
            assert [s["name"] for s in snaps] == ["s1"]
        finally:
            await _teardown(cluster, mdss, clients)
    run(main())


def test_snapshot_namespace_membership():
    """Deleted files stay in the snapshot; later files don't appear."""
    async def main():
        cluster, mdss, clients, (fs,) = await _fs_cluster()
        try:
            await fs.mkdir("/d")
            await fs.write_file("/d/a", b"alpha-bytes")
            await fs.write_file("/d/b", b"bravo-bytes")
            await fs.mksnap("/d", "before")
            await fs.unlink("/d/a")
            await fs.write_file("/d/c", b"charlie")
            assert sorted(await fs.listdir("/d")) == ["b", "c"]
            assert sorted(await fs.listdir("/d/.snap/before")) == \
                ["a", "b"]
            # the deleted file's DATA is still readable at the snap
            # (whiteout head + retained clone on the OSDs)
            assert await fs.read_file("/d/.snap/before/a") == \
                b"alpha-bytes"
            with pytest.raises(CephFSError):
                await fs.read_file("/d/.snap/before/c")
        finally:
            await _teardown(cluster, mdss, clients)
    run(main())


def test_nested_dirs_and_multiple_snaps():
    async def main():
        cluster, mdss, clients, (fs,) = await _fs_cluster()
        try:
            await fs.mkdir("/top")
            await fs.mkdir("/top/sub")
            await fs.write_file("/top/sub/deep", b"one")
            await fs.mksnap("/top", "s1")
            await fs.write_file("/top/sub/deep", b"two-longer")
            await fs.mkdir("/top/sub/later")
            await fs.mksnap("/top", "s2")
            assert await fs.read_file("/top/.snap/s1/sub/deep") == \
                b"one"
            assert await fs.read_file("/top/.snap/s2/sub/deep") == \
                b"two-longer"
            assert await fs.listdir("/top/.snap/s1/sub") == ["deep"]
            assert sorted(await fs.listdir("/top/.snap/s2/sub")) == \
                ["deep", "later"]
            # readdir entries at a snap are annotated read-only
            ents = await fs.readdir("/top/.snap/s1/sub")
            assert ents["deep"]["readonly"]
        finally:
            await _teardown(cluster, mdss, clients)
    run(main())


def test_rmsnap():
    async def main():
        cluster, mdss, clients, (fs,) = await _fs_cluster()
        try:
            await fs.mkdir("/r")
            await fs.write_file("/r/f", b"keep")
            await fs.mksnap("/r", "gone")
            await fs.rmdir("/r/.snap/gone")   # rmdir-on-snapdir form
            assert await fs.lssnap("/r") == []
            with pytest.raises(CephFSError):
                await fs.read_file("/r/.snap/gone/f")
            # head unaffected
            assert await fs.read_file("/r/f") == b"keep"
            with pytest.raises(CephFSError):
                await fs.rmsnap("/r", "gone")  # idempotence: ENOENT
        finally:
            await _teardown(cluster, mdss, clients)
    run(main())


def test_snap_paths_are_read_only():
    async def main():
        cluster, mdss, clients, (fs,) = await _fs_cluster()
        try:
            await fs.mkdir("/ro")
            await fs.write_file("/ro/f", b"data")
            await fs.mksnap("/ro", "s")
            for coro in (
                    fs.write_file("/ro/.snap/s/f", b"nope"),
                    fs.open("/ro/.snap/s/f", "r+"),
                    fs.mkdir("/ro/.snap/s/newdir"),
                    fs.unlink("/ro/.snap/s/f"),
                    fs.rename("/ro/.snap/s/f", "/ro/g"),
                    fs.truncate("/ro/.snap/s/f", 0)):
                with pytest.raises(CephFSError) as ei:
                    await coro
                assert ei.value.rc == EROFS, ei.value
        finally:
            await _teardown(cluster, mdss, clients)
    run(main())


def test_capped_writer_cows_after_recall():
    """The recall-carried snap context: a writer holding an rw cap
    keeps writing with no MDS round trip; a snapshot taken by another
    mount must still be COW-protected from those writes."""
    async def main():
        cluster, mdss, clients, (fs_a, fs_b) = \
            await _fs_cluster(num_clients=2)
        try:
            f = await fs_a.open("/hot", "w")
            await f.write(0, b"pre-snap!")
            await f.flush()
            # B snapshots the root while A still holds the handle
            await fs_b.mksnap("/", "r1")
            # A's next write goes straight to the OSDs — the cap
            # recall must have armed A's snap context already
            await f.write(0, b"POST-SNAP")
            await f.close()
            assert await fs_b.read_file("/.snap/r1/hot") == \
                b"pre-snap!"
            assert await fs_a.read_file("/hot") == b"POST-SNAP"
        finally:
            await _teardown(cluster, mdss, clients)
    run(main())


def test_snapshot_captures_buffered_cap_size():
    """A writer's size buffered under an rw cap (never yet flushed)
    must be visible in the snapshot: mksnap recalls the cap and
    persists the flushed size on the PRE-snapshot side."""
    async def main():
        cluster, mdss, clients, (fs_a, fs_b) = \
            await _fs_cluster(num_clients=2)
        try:
            f = await fs_a.open("/buf", "w")
            await f.write(0, b"0123456789abcdef")  # size only buffered
            # no flush/close: the 16-byte size lives in A's dirty caps
            await fs_b.mksnap("/", "s")
            st = await fs_b.stat("/.snap/s/buf")
            assert st["size"] == 16, st
            assert await fs_b.read_file("/.snap/s/buf") == \
                b"0123456789abcdef"
            await f.close()
        finally:
            await _teardown(cluster, mdss, clients)
    run(main())


def test_snapshots_survive_mds_failover():
    async def main():
        cluster, mdss, clients, (fs,) = await _fs_cluster()
        try:
            await fs.mkdir("/p")
            await fs.write_file("/p/f", b"gen-1")
            await fs.mksnap("/p", "keep")
            await mdss[0].stop()
            nxt = MDSDaemon(cluster.mon.addr, "cephfs.meta",
                            "cephfs.data", name="b",
                            lock_interval=0.3)
            await nxt.start()
            mdss[:] = [nxt]
            # takeover re-arms snap contexts: post-failover writes
            # still COW against the pre-failover snapshot
            await fs.write_file("/p/f", b"gen-2x")
            assert [s["name"] for s in await fs.lssnap("/p")] == \
                ["keep"]
            assert await fs.read_file("/p/.snap/keep/f") == b"gen-1"
            assert await fs.read_file("/p/f") == b"gen-2x"
        finally:
            await _teardown(cluster, mdss, clients)
    run(main())


def test_crashed_mksnap_pending_row_swept_on_takeover():
    """A PENDING snap-table row (mksnap crashed between snapid
    allocation and finalize) must be invisible to .snap readers and
    get swept on takeover — its pool snapids released so clones
    trim instead of leaking."""
    async def main():
        import json as _json
        cluster, mdss, clients, (fs,) = await _fs_cluster()
        try:
            await fs.mkdir("/p")
            await fs.write_file("/p/f", b"data")
            # simulate the crash artifact: allocate real pool snapids
            # and leave a pending row behind
            meta_io = clients[0].open_ioctx("cephfs.meta")
            data_io = clients[0].open_ioctx("cephfs.data")
            dsnap = await data_io.create_selfmanaged_snap()
            msnap = await meta_io.create_selfmanaged_snap()
            row = {"name": "ghost", "ino": 1, "meta_snap": msnap,
                   "data_snap": dsnap, "ctime": 0.0,
                   "pending": True, "rank": 0}
            await meta_io.omap_set(
                "mds_snaptable",
                {f"{dsnap:016x}": _json.dumps(row).encode()})
            # invisible while pending
            assert all(s["name"] != "ghost"
                       for s in await fs.lssnap("/"))
            # failover sweeps it
            await mdss[0].stop()
            nxt = MDSDaemon(cluster.mon.addr, "cephfs.meta",
                            "cephfs.data", name="b",
                            lock_interval=0.3)
            await nxt.start()
            mdss[:] = [nxt]
            for _ in range(50):
                omap = await meta_io.omap_get("mds_snaptable")
                if f"{dsnap:016x}" not in omap:
                    break
                await asyncio.sleep(0.2)
            omap = await meta_io.omap_get("mds_snaptable")
            assert f"{dsnap:016x}" not in omap, "row not swept"
            # the released snapid landed in removed_snaps (trimmable)
            await clients[0].refresh_map()
            pool = clients[0].osdmap.pools[data_io.pool_id]
            assert dsnap in getattr(pool, "removed_snaps", [])
        finally:
            await _teardown(cluster, mdss, clients)
    run(main())


def test_root_snapshot_covers_tree():
    async def main():
        cluster, mdss, clients, (fs,) = await _fs_cluster()
        try:
            await fs.mkdir("/a")
            await fs.mkdir("/a/b")
            await fs.write_file("/a/b/f", b"rooted")
            await fs.mksnap("/", "whole")
            await fs.unlink("/a/b/f")
            await fs.rmdir("/a/b")
            assert await fs.read_file("/.snap/whole/a/b/f") == \
                b"rooted"
            assert await fs.listdir("/.snap/whole/a") == ["b"]
        finally:
            await _teardown(cluster, mdss, clients)
    run(main())


def test_multi_rank_snapshot_refresh():
    """A snapshot on rank-1's subtree must make rank-0 (and every
    other rank) COW its own dir mutations too — the peer_snap_refresh
    fan-out."""
    async def main():
        cluster, mdss, clients, (fs,) = await _fs_cluster(
            num_mds=2, num_ranks=2)
        try:
            # find a top-level name owned by each rank
            from ceph_tpu.mds import owner_rank
            name1 = next(f"d{i}" for i in range(64)
                         if owner_rank(f"/d{i}/x", 2) == 1)
            name0 = next(f"e{i}" for i in range(64)
                         if owner_rank(f"/e{i}/x", 2) == 0)
            await fs.mkdir(f"/{name1}")
            await fs.mkdir(f"/{name0}")
            await fs.write_file(f"/{name1}/f", b"rank1-v1")
            await fs.write_file(f"/{name0}/f", b"rank0-v1")
            # snapshot ROOT (rank 0 adjudicates) — rank 1 must learn
            # the new context through the fan-out
            await fs.mksnap("/", "all")
            await fs.write_file(f"/{name1}/f", b"rank1-v2")
            await fs.write_file(f"/{name0}/f", b"rank0-v2")
            assert await fs.read_file(f"/.snap/all/{name1}/f") == \
                b"rank1-v1"
            assert await fs.read_file(f"/.snap/all/{name0}/f") == \
                b"rank0-v1"
            # and a snapshot ON the rank-1 subtree routes to rank 1
            await fs.mksnap(f"/{name1}", "mine")
            await fs.write_file(f"/{name1}/f", b"rank1-v3")
            assert await fs.read_file(
                f"/{name1}/.snap/mine/f") == b"rank1-v2"
        finally:
            await _teardown(cluster, mdss, clients)
    run(main())

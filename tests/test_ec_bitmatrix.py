"""Bitmatrix techniques + wide-word reed_sol_van + golden vectors.

Mirrors the reference's typed sweep across all seven jerasure
techniques (/root/reference/src/test/erasure-code/
TestErasureCodeJerasure.cc:34-43: reed_sol_van, reed_sol_r6_op,
cauchy_orig, cauchy_good, liberation, blaum_roth, liber8tion) with
the round-trip/erasure/minimum_to_decode/padding shapes of that file,
plus w in {16, 32} for reed_sol_van and golden chunk vectors that pin
the w=8 reed_sol_van construction BY DATA against an independent
in-test derivation of the published algorithm.
"""

import itertools

import numpy as np
import pytest

from ceph_tpu.ec.registry import create_erasure_code

# (technique, extra profile) — the 7-technique sweep + wide words
SWEEP = [
    ("reed_sol_van", {}),
    ("reed_sol_van", {"w": "16"}),
    ("reed_sol_van", {"w": "32"}),
    ("reed_sol_r6_op", {"m": "2"}),
    ("cauchy_orig", {}),
    ("cauchy_good", {}),
    ("liberation", {"m": "2", "w": "7", "packetsize": "32"}),
    ("blaum_roth", {"m": "2", "w": "6", "packetsize": "32"}),
    ("liber8tion", {"m": "2", "w": "8", "packetsize": "32"}),
]


def make(technique, k="4", m="2", **extra):
    profile = {"plugin": "ec_jax", "technique": technique,
               "k": k, "m": m, "tpu": "false"}
    profile.update(extra)
    return create_erasure_code(profile)


@pytest.mark.parametrize("technique,extra", SWEEP)
def test_encode_decode_roundtrip_all_erasures(technique, extra):
    """TestErasureCodeJerasure encode/decode shape (:57): every 1- and
    2-erasure pattern recovers the original chunks bit-exactly."""
    codec = make(technique, **extra)
    k, m = codec.k, codec.m
    n = k + m
    payload = bytes(np.random.default_rng(42).integers(
        0, 256, 3 * codec.get_alignment() - 17, dtype=np.uint8))
    encoded = codec.encode(range(n), payload)
    assert set(encoded) == set(range(n))
    chunk_len = len(encoded[0])
    for buf in encoded.values():
        assert len(buf) == chunk_len
    for nerased in (1, 2):
        for erased in itertools.combinations(range(n), nerased):
            avail = {i: bytes(encoded[i]) for i in range(n)
                     if i not in erased}
            decoded = codec.decode(range(n), avail, chunk_len)
            for i in range(n):
                assert bytes(decoded[i]) == bytes(encoded[i]), \
                    (technique, erased, i)


@pytest.mark.parametrize("technique,extra", SWEEP)
def test_minimum_to_decode(technique, extra):
    """minimum_to_decode shape (:132): available chunks that already
    cover the want-set come back verbatim; k survivors suffice."""
    codec = make(technique, **extra)
    k, m = codec.k, codec.m
    n = k + m
    want = set(range(k))
    got = codec.minimum_to_decode(want, set(range(n)))
    assert len(got) <= n
    # with exactly k survivors the minimum is those survivors
    # (returned as chunk -> subchunk-range map, get_sub_chunk_count=1)
    survivors = set(range(1, k + 1))
    got = codec.minimum_to_decode(want, survivors)
    assert set(got) == survivors


@pytest.mark.parametrize("technique,extra", SWEEP)
def test_padding_and_alignment(technique, extra):
    """encode pads the tail chunk (:230): short objects round-trip."""
    codec = make(technique, **extra)
    n = codec.k + codec.m
    for size in (1, codec.get_alignment() - 1,
                 codec.get_alignment() + 1):
        payload = bytes(np.random.default_rng(size).integers(
            0, 256, size, dtype=np.uint8))
        encoded = codec.encode(range(n), payload)
        avail = {i: bytes(encoded[i]) for i in range(codec.k)}
        out = codec.decode_concat(avail)
        assert out[:size] == payload


def test_bitmatrix_parameter_adjudication():
    """The reference reverts invalid geometry with a notice
    (ErasureCodeJerasure.cc:488-494); here invalid geometry is an
    explicit error (silent adjustment would change placement)."""
    from ceph_tpu.ec.interface import ErasureCodeError

    with pytest.raises(ErasureCodeError):
        make("liberation", k="4", m="2", w="6")   # w not prime
    with pytest.raises(ErasureCodeError):
        make("liberation", k="8", m="2", w="7")   # k > w
    with pytest.raises(ErasureCodeError):
        make("blaum_roth", k="4", m="2", w="7")   # w+1 not prime
    with pytest.raises(ErasureCodeError):
        make("liber8tion", k="4", m="2", w="7")   # w != 8
    with pytest.raises(ErasureCodeError):
        make("liberation", k="4", m="3")          # m != 2


def test_wide_words_reject_non_van_techniques():
    from ceph_tpu.ec.interface import ErasureCodeError

    with pytest.raises(ErasureCodeError):
        make("cauchy_good", w="16")
    with pytest.raises(ErasureCodeError):
        make("reed_sol_van", w="24")


# -- golden vectors ---------------------------------------------------------

def _independent_gf256_mul(a: int, b: int) -> int:
    """Schoolbook GF(2^8)/0x11d multiply — no ceph_tpu code involved."""
    p = 0
    for _ in range(8):
        if b & 1:
            p ^= a
        hi = a & 0x80
        a = (a << 1) & 0xFF
        if hi:
            a ^= 0x1D
        b >>= 1
    return p


def _independent_reed_sol_van(k: int, m: int) -> list:
    """The published jerasure construction (Plank's tutorial + 2003
    correction), re-derived here from scratch: extended Vandermonde,
    elementary column ops to systematic form, coding columns scaled so
    row k is all ones.  Pure-python, independent of models/."""
    mul = _independent_gf256_mul

    def inv(a):
        for x in range(1, 256):
            if mul(a, x) == 1:
                return x
        raise ZeroDivisionError

    rows, cols = k + m, k
    v = [[0] * cols for _ in range(rows)]
    v[0][0] = 1
    v[rows - 1][cols - 1] = 1
    for i in range(1, rows - 1):
        acc = 1
        for j in range(cols):
            v[i][j] = acc
            acc = mul(acc, i)
    for i in range(k):
        if v[i][i] == 0:
            for j in range(i + 1, k):
                if v[i][j]:
                    for r in range(rows):
                        v[r][i], v[r][j] = v[r][j], v[r][i]
                    break
        if v[i][i] != 1:
            c = inv(v[i][i])
            for r in range(rows):
                v[r][i] = mul(v[r][i], c)
        for j in range(k):
            if j != i and v[i][j]:
                c = v[i][j]
                for r in range(rows):
                    v[r][j] ^= mul(v[r][i], c)
    coding = [row[:] for row in v[k:]]
    for j in range(k):
        if coding[0][j] not in (0, 1):
            c = inv(coding[0][j])
            for r in range(m):
                coding[r][j] = mul(coding[r][j], c)
    return coding


def test_reed_sol_van_matrix_matches_independent_derivation():
    from ceph_tpu.models import reed_solomon as rs

    for k, m in [(2, 2), (4, 2), (8, 3), (10, 4)]:
        want = _independent_reed_sol_van(k, m)
        got = rs.reed_sol_van_matrix(k, m)
        assert got.tolist() == want, (k, m)


# Golden chunk vectors: fixed input -> fixed parity bytes.  The parity
# literals below were produced by _independent_reed_sol_van +
# _independent_gf256_mul (pure-python, derived from the published
# construction only) over the fixed input; the codec must reproduce
# them byte-for-byte forever — the ceph_erasure_code_non_regression
# corpus role (reference src/test/erasure-code/
# ceph_erasure_code_non_regression.cc:42-147) pinned by data.
# fixed pseudorandom input (structured patterns XOR to zero under the
# all-ones parity row and would pin nothing)
GOLDEN_INPUT = bytes(np.random.default_rng(0xCEF).integers(
    0, 256, 512, dtype=np.uint8))
GOLDEN_K, GOLDEN_M = 4, 2


def _golden_parity() -> list:
    coding = _independent_reed_sol_van(GOLDEN_K, GOLDEN_M)
    chunk = len(GOLDEN_INPUT) // GOLDEN_K
    chunks = [GOLDEN_INPUT[i * chunk:(i + 1) * chunk]
              for i in range(GOLDEN_K)]
    out = []
    for j in range(GOLDEN_M):
        row = bytearray(chunk)
        for i in range(GOLDEN_K):
            c = coding[j][i]
            for t in range(chunk):
                row[t] ^= _independent_gf256_mul(c, chunks[i][t])
        out.append(bytes(row))
    return out


# the first 16 parity bytes of each coding chunk, as literals
GOLDEN_P0_HEAD = bytes.fromhex("177234d6377a65eb229b49789bdb7bdd")
GOLDEN_P1_HEAD = bytes.fromhex("c37a76a15e6a505e1949fa9491c6428e")


def test_reed_sol_van_golden_vectors():
    """Bit-exactness pinned by data: codec parity == the independent
    derivation == the checked-in literals."""
    golden = _golden_parity()
    codec = make("reed_sol_van", k=str(GOLDEN_K), m=str(GOLDEN_M))
    # encode with chunk padding disabled by using aligned input
    encoded = codec.encode(range(GOLDEN_K + GOLDEN_M), GOLDEN_INPUT)
    chunk = len(GOLDEN_INPUT) // GOLDEN_K
    for j in range(GOLDEN_M):
        got = bytes(encoded[GOLDEN_K + j])[:chunk]
        assert got == golden[j], f"parity {j} drifted"
    assert golden[0][:16] == GOLDEN_P0_HEAD
    assert golden[1][:16] == GOLDEN_P1_HEAD


def test_bitmatrix_chunk_mapping_roundtrip():
    """A mapping profile repositions chunks on disk; the bitmatrix math
    must follow chunk_index (the review repro: data block read from a
    parity position corrupted the payload)."""
    codec = make("liberation", k="4", m="2", w="7", packetsize="32",
                 mapping="D_DDD_")
    n = codec.k + codec.m
    payload = bytes(np.random.default_rng(9).integers(
        0, 256, codec.get_alignment() * 2 - 5, dtype=np.uint8))
    encoded = codec.encode(range(n), payload)
    assert codec.decode_concat(
        {i: bytes(b) for i, b in encoded.items()})[:len(payload)] \
        == payload
    # erase two, recover, reassemble
    avail = {i: bytes(encoded[i]) for i in list(encoded)[:4]}
    assert codec.decode_concat(avail)[:len(payload)] == payload

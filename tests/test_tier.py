"""Read-tier coherency tier (osd/tier.py + the daemon agent wiring).

The acceptance shape: with a skewed read workload against an EC pool,
repeated reads of a promoted object add ZERO EC plan dispatches and
are byte-identical to the CEPH_TPU_TIER=0 cold path — including
immediately after an overwrite/RMW of the same object; eviction obeys
the byte budget; promotions run under the mClock
background_best_effort class; counters and hot-set dumps are visible
over the tell surface and the prometheus exporter.
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from ceph_tpu.ec import plan as ec_plan
from ceph_tpu.osd import scheduler as sched_mod
from ceph_tpu.osd.osdmap import PgId
from ceph_tpu.tools.rados import zipf_indices

from cluster_helpers import Cluster

EC42 = {"plugin": "ec_jax", "technique": "reed_sol_van",
        "k": "4", "m": "2", "crush-failure-domain": "osd",
        "tpu": "false"}

# promotion on the 2nd read; no background rotation mid-test
TIER_CFG = {"osd_tier_promote_min_recency": 2,
            "osd_hit_set_period": 3600.0}


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 180))


def _primary_of(cluster, pool_name: str, oid: str):
    osdmap = cluster.mon.osdmap
    pool = [p for p in osdmap.pools.values()
            if p.name == pool_name][0]
    from ceph_tpu.ops.rjenkins import ceph_str_hash_rjenkins

    ps = ceph_str_hash_rjenkins(oid.encode())
    pg = pool.raw_pg_to_pg(PgId(pool.id, ps))
    _acting, primary = osdmap.pg_to_acting_osds(pg)
    return cluster.osds[primary]


async def _wait_promoted(prim, oid: str, timeout: float = 10.0):
    for _ in range(int(timeout / 0.05)):
        if any(k[1] == oid for k in prim.tier.cache):
            return
        await asyncio.sleep(0.05)
    raise TimeoutError(f"{oid} never promoted (cache="
                       f"{list(prim.tier.cache)})")


def _dispatch_counters(cluster):
    return (ec_plan.stats()["dispatches"],
            sum(o.perf["decode_dispatches"]
                for o in cluster.osds.values()))


# -- the acceptance bound: hot-read decode bypass ---------------------------


def test_promoted_object_serves_with_zero_plan_dispatches():
    """Two reads promote; the next 16 reads of the hot object add
    zero EC plan dispatches and zero daemon decode dispatches, with
    every payload byte-identical to the written object."""
    async def main():
        cluster = Cluster(num_osds=6, osds_per_host=3,
                          osd_config=dict(TIER_CFG))
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ec", profile=EC42, pg_num=8)
            io = cluster.client.open_ioctx("ec")
            obj = bytes(np.random.default_rng(5).integers(
                0, 256, 150_000, dtype=np.uint8))
            await io.write_full("hot", obj)
            prim = _primary_of(cluster, "ec", "hot")
            assert prim.tier.enabled
            assert await io.read("hot") == obj      # hit_count 1
            assert await io.read("hot") == obj      # crosses recency 2
            await _wait_promoted(prim, "hot")
            plan0, dec0 = _dispatch_counters(cluster)
            for _ in range(16):
                assert await io.read("hot") == obj
            # ranged reads ride the same cached bytes
            assert await io.read("hot", offset=100_001,
                                 length=4096) == obj[100_001:104_097]
            assert await io.read("hot", offset=149_000,
                                 length=9999) == obj[149_000:]
            plan1, dec1 = _dispatch_counters(cluster)
            assert plan1 == plan0, "hot reads dispatched EC plans"
            assert dec1 == dec0, "hot reads hit the decode path"
            assert prim.tier.perf.get("hit") >= 18
            # the promotion ran under mClock background_best_effort
            assert prim.scheduler.granted.get(
                sched_mod.BEST_EFFORT, 0) >= 1
        finally:
            await cluster.stop()

    run(main())


def test_tier_reads_bit_identical_to_disabled_tier():
    """The same zipfian read schedule with the tier enabled and with
    CEPH_TPU_TIER=0 returns identical bytes for every read —
    including reads issued immediately after a full overwrite and
    after a stripe-level RMW of the promoted object (invalidation)."""
    async def one_mode(monkey_off: bool):
        cluster = Cluster(num_osds=6, osds_per_host=3,
                          osd_config=dict(TIER_CFG))
        await cluster.start()
        try:
            if monkey_off:
                for osd in cluster.osds.values():
                    osd.tier.enabled = False
            await cluster.client.create_ec_pool(
                "ec", profile=EC42, pg_num=8)
            io = cluster.client.open_ioctx("ec")
            rng = np.random.default_rng(9)
            objs = {f"o{i}": bytes(rng.integers(
                0, 256, 40_000 + 1000 * i, dtype=np.uint8))
                for i in range(6)}
            for name, data in objs.items():
                await io.write_full(name, data)
            outputs = []
            for i in zipf_indices(1.2, 6, 48, seed=3):
                outputs.append(await io.read(f"o{int(i)}"))
            await asyncio.sleep(0.2)   # promotions land (tier mode)
            # overwrite the hottest object, then read IMMEDIATELY
            hot = "o0"
            new = bytes(rng.integers(0, 256, 52_000, dtype=np.uint8))
            await io.write_full(hot, new)
            outputs.append(await io.read(hot))
            # stripe-level RMW on the (re-promotable) hot object
            for _ in range(3):
                outputs.append(await io.read(hot))
            await asyncio.sleep(0.2)
            await io.write(hot, b"RMW-BYTES", 12_345)
            outputs.append(await io.read(hot))
            outputs.append(await io.read(hot, offset=12_340,
                                         length=20))
            return outputs
        finally:
            await cluster.stop()

    async def main():
        with_tier = await one_mode(False)
        without = await one_mode(True)
        assert len(with_tier) == len(without)
        for i, (a, b) in enumerate(zip(with_tier, without)):
            assert a == b, f"read {i} diverged with tier on"

    run(main())


def test_overwrite_invalidates_promoted_entry():
    async def main():
        cluster = Cluster(num_osds=6, osds_per_host=3,
                          osd_config=dict(TIER_CFG))
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ec", profile=EC42, pg_num=8)
            io = cluster.client.open_ioctx("ec")
            v1 = b"a" * 30_000
            v2 = b"b" * 31_000
            await io.write_full("x", v1)
            assert await io.read("x") == v1
            assert await io.read("x") == v1
            prim = _primary_of(cluster, "ec", "x")
            await _wait_promoted(prim, "x")
            inval0 = prim.tier.perf.get("invalidate")
            await io.write_full("x", v2)
            assert prim.tier.perf.get("invalidate") > inval0
            assert not any(k[1] == "x" for k in prim.tier.cache)
            assert await io.read("x") == v2
            # remove after re-promotion: reads must go ENOENT, never
            # resurrect cached bytes
            assert await io.read("x") == v2
            await _wait_promoted(prim, "x")
            await io.remove("x")
            from ceph_tpu.rados.client import RadosError

            with pytest.raises(RadosError):
                await io.read("x")
        finally:
            await cluster.stop()

    run(main())


def test_eviction_under_byte_pressure():
    """A 100 KiB budget holds ~2 of the 40 KiB objects: promoting a
    hot set of 5 must evict LRU entries and never exceed the budget."""
    async def main():
        cluster = Cluster(
            num_osds=6, osds_per_host=3,
            osd_config={**TIER_CFG,
                        "osd_tier_cache_bytes": 100 << 10})
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ec", profile=EC42, pg_num=8)
            io = cluster.client.open_ioctx("ec")
            data = {f"e{i}": bytes([i]) * 40_000 for i in range(5)}
            for name, payload in data.items():
                await io.write_full(name, payload)
            for _ in range(3):
                for name in data:
                    assert await io.read(name) == data[name]
            await asyncio.sleep(0.3)
            evicted = promoted = 0
            for osd in cluster.osds.values():
                assert osd.tier.cache_bytes <= 100 << 10
                evicted += osd.tier.perf.get("evict")
                promoted += osd.tier.perf.get("promote")
            assert promoted >= 3
            assert evicted >= 1
            # evicted objects still read correctly (cold path)
            for name in data:
                assert await io.read(name) == data[name]
        finally:
            await cluster.stop()

    run(main())


def test_recovery_keeps_tier_reads_correct():
    """Kill a shard holder after promotion: reads of the hot object
    stay byte-identical through degradation and recovery."""
    async def main():
        cluster = Cluster(num_osds=6, osds_per_host=3,
                          osd_config=dict(TIER_CFG))
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ec", profile=EC42, pg_num=8)
            io = cluster.client.open_ioctx("ec")
            obj = bytes(np.random.default_rng(11).integers(
                0, 256, 80_000, dtype=np.uint8))
            await io.write_full("r", obj)
            assert await io.read("r") == obj
            assert await io.read("r") == obj
            prim = _primary_of(cluster, "ec", "r")
            await _wait_promoted(prim, "r")
            victim = next(o for o in cluster.osds
                          if cluster.osds[o] is not prim)
            await cluster.kill_osd(victim)
            await cluster.wait_for_osd_down(victim)
            assert await io.read("r") == obj
            await cluster.client.mon_command(
                {"prefix": "osd out", "osd": victim})
            await cluster.wait_for_clean(timeout=60)
            assert await io.read("r") == obj
        finally:
            await cluster.stop()

    run(main())


# -- observability ----------------------------------------------------------


def test_tell_surface_tier_status_and_hitset_dump():
    async def main():
        cluster = Cluster(num_osds=6, osds_per_host=3,
                          osd_config={**TIER_CFG,
                                      "osd_hit_set_period": 0.2})
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ec", profile=EC42, pg_num=8)
            io = cluster.client.open_ioctx("ec")
            await io.write_full("t", b"z" * 20_000)
            for _ in range(3):
                await io.read("t")
            prim = _primary_of(cluster, "ec", "t")
            await _wait_promoted(prim, "t")
            rc, status = await cluster.client.osd_command(
                prim.osd_id, {"prefix": "tier_status"})
            assert rc == 0 and status["enabled"]
            assert status["cached_objects"] >= 1
            assert status["counters"]["promote"] >= 1
            assert status["counters"]["hit"] >= 1
            rc, perf = await cluster.client.osd_command(
                prim.osd_id, {"prefix": "perf dump"})
            assert rc == 0
            assert perf["tier"]["hit"] >= 1
            assert "read_freq" in perf["tier"]
            assert "plan_cache" in perf and "hits" in perf["plan_cache"]
            assert "encode_service" in perf
            # rotation happened (0.2s period) -> hot sets persisted
            # into the pg-meta omap prefix; keep reading until one
            # lands, then assert the dump shows both stack + archive
            for _ in range(100):
                await io.read("t")
                rc, hs = await cluster.client.osd_command(
                    prim.osd_id, {"prefix": "hitset_dump"})
                assert rc == 0
                if hs["persisted"]:
                    break
                await asyncio.sleep(0.05)
            assert hs["stacks"], "no hot-set stacks on the primary"
            assert hs["persisted"], "no persisted hitset omap keys"
            keys = next(iter(hs["persisted"].values()))
            assert all(k.startswith("hitset_") for k in keys)
        finally:
            await cluster.stop()

    run(main())


def test_prometheus_exports_tier_and_plan_counters():
    """The exporter flattens the nested perf sections: tier counters,
    the read-frequency histogram, plan-cache and encode-service
    counters all appear as scrapeable rows."""
    async def main():
        from ceph_tpu.mgr import MgrDaemon

        cluster = Cluster(num_osds=6, osds_per_host=3,
                          osd_config={**TIER_CFG,
                                      "osd_hit_set_period": 0.2})
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ec", profile=EC42, pg_num=8)
            io = cluster.client.open_ioctx("ec")
            await io.write_full("p", b"q" * 10_000)
            for _ in range(30):
                await io.read("p")
                await asyncio.sleep(0.01)
            mgr = MgrDaemon(cluster.mon.addr, config={})
            await mgr.start()
            try:
                prom = mgr.modules["prometheus"]
                host, port = prom.addr.split(":")
                reader, writer = await asyncio.open_connection(
                    host, int(port))
                writer.write(b"GET /metrics HTTP/1.0\r\n\r\n")
                await writer.drain()
                raw = await asyncio.wait_for(reader.read(), 10.0)
                writer.close()
                body = raw.decode().split("\r\n\r\n", 1)[1]
                assert "ceph_osd_tier_hit" in body
                assert "ceph_osd_tier_miss" in body
                assert "ceph_osd_tier_records" in body
                # read-frequency histogram rows
                assert "ceph_osd_tier_read_freq_bucket" in body
                assert 'le="+Inf"' in body
                # PR-2/PR-3 counters now scrapeable
                assert "ceph_osd_plan_cache_hits" in body
                assert "ceph_osd_plan_cache_dispatches" in body
                assert "ceph_osd_encode_service_requests" in body
                # exposition stays parseable line by line
                for line in body.strip().splitlines():
                    if line.startswith("#"):
                        continue
                    name_part, value = line.rsplit(" ", 1)
                    float(value)
                    assert name_part[0].isalpha()
            finally:
                await mgr.stop()
        finally:
            await cluster.stop()

    run(main())


def test_kill_switch_disables_subsystem():
    """CEPH_TPU_TIER=0 (env) and osd_tier_enable=false (config) both
    leave the read path untouched: no recording, no promotions."""
    async def main():
        cluster = Cluster(
            num_osds=6, osds_per_host=3,
            osd_config={**TIER_CFG, "osd_tier_enable": False})
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ec", profile=EC42, pg_num=8)
            io = cluster.client.open_ioctx("ec")
            obj = b"k" * 30_000
            await io.write_full("k", obj)
            for _ in range(5):
                assert await io.read("k") == obj
            await asyncio.sleep(0.2)
            for osd in cluster.osds.values():
                assert not osd.tier.enabled
                assert not osd.tier.cache
                assert osd.tier.perf.get("records") == 0
        finally:
            await cluster.stop()

    run(main())


def test_env_kill_switch(monkeypatch):
    monkeypatch.setenv("CEPH_TPU_TIER", "0")
    from ceph_tpu.osd.tier import TierAgent

    agent = TierAgent("osd.t", {"osd_tier_enable": True})
    assert not agent.enabled
    assert agent.note_read("pg", "o") == 0
    agent.install("pg", "o", b"data")
    assert agent.lookup("pg", "o") is None


def test_cli_zipf_bench_leg_drives_tier_hits(capsys):
    """`rados bench seq --read-skew` against an EC pool: the skewed
    leg runs, reports deterministically-shaped output, and its hot
    ranks land in the tier (hit counters move)."""
    import argparse
    import json

    from ceph_tpu.tools import rados as rados_cli

    async def main():
        cluster = Cluster(num_osds=6, osds_per_host=3,
                          osd_config=dict(TIER_CFG))
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ec", profile=EC42, pg_num=8)
            io = cluster.client.open_ioctx("ec")
            args = argparse.Namespace(
                block_size=8192, concurrency=4, seconds=2,
                mode="seq", read_skew=1.2, objects=16, seed=0)
            assert await rados_cli._bench(io, args) == 0
            return sum(osd.tier.perf.get("hit")
                       for osd in cluster.osds.values())
        finally:
            await cluster.stop()

    hits = None
    try:
        hits = asyncio.run(asyncio.wait_for(main(), 120))
    finally:
        out = capsys.readouterr().out
    report = json.loads(out)
    assert report["mode"] == "seq" and report["read_skew"] == 1.2
    assert report["objects"] == 16 and report["ops"] > 0
    assert hits is not None and hits > 0, "skewed leg never hit the tier"


def test_oversize_object_never_wipes_the_cache():
    """An object bigger than the whole byte budget is refused without
    evicting the existing hot set, and is not re-promoted on every
    read — until a rewrite (which may shrink it) clears the marker."""
    from ceph_tpu.osd.tier import TierAgent

    t = TierAgent("osd.t", {"osd_tier_cache_bytes": 1000,
                            "osd_tier_promote_min_recency": 1})
    for i in range(4):
        t.install("pg", f"o{i}", bytes(200))
    assert len(t.cache) == 4
    t.install("pg", "giant", bytes(5000))
    assert len(t.cache) == 4 and t.cache_bytes <= 1000
    assert t.lookup("pg", "giant") is None
    assert not t.wants_promote("pg", "giant", 99)
    t.invalidate("pg", "giant")
    assert t.wants_promote("pg", "giant", 99)


def test_scrub_subreads_do_not_pollute_hitsets():
    """Scrub fans MOSDSubRead to every shard of every object; none of
    them may feed the hot-set tracking (only client-read gathers carry
    record=True), or the skew signal drowns every scrub cycle."""
    async def main():
        cluster = Cluster(num_osds=6, osds_per_host=3,
                          osd_config=dict(TIER_CFG))
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ec", profile=EC42, pg_num=8)
            io = cluster.client.open_ioctx("ec")
            for i in range(5):
                await io.write_full(f"s{i}", bytes([i]) * 9000)
            for osd in cluster.osds.values():
                for pg, state in list(osd.pgs.items()):
                    pool = osd.osdmap.pools.get(pg.pool)
                    if pool is None or state.primary != osd.osd_id \
                            or state.state != "active":
                        continue
                    await osd.scrub_pg(state, pool)
            assert sum(o.tier.perf.get("records")
                       for o in cluster.osds.values()) == 0, \
                "scrub sub-reads leaked into the hot-set tracking"
            # a real client read still records (on the primary AND on
            # the replicas its gather touches)
            await io.read("s0")
            assert sum(o.tier.perf.get("records")
                       for o in cluster.osds.values()) >= 1
        finally:
            await cluster.stop()

    run(main())

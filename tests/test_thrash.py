"""Thrasher tier: randomized kill/revive under sustained load with a
model checker.

The thrashosds/ceph_test_rados shape
(/root/reference/qa/tasks/ceph_manager.py:2702,2744 kill_osd/revive_osd;
/root/reference/src/test/osd/RadosModel.h): a workload of writes and
removes runs while OSDs are killed mid-write and revived; a client-side
model tracks every ACKED operation.  Invariants at the end (after the
cluster goes clean):

1. zero data loss: every object reads back as its last acked state or
   a later indeterminate (unacked) attempt;
2. shard/replica convergence: every stored copy of every object matches
   the re-encode (EC) or the bytes (replicated) of its readable state.

Three in-loop profiles (EC 2+2, EC 8+3, replicated size-3) run >= 60 s
of load and >= 40 thrash actions each; a separate process tier SIGKILLs
TPUStore-backed OSD processes and the mon mid-write.
"""

import asyncio
import random
import time

import numpy as np
import pytest

from ceph_tpu.ec.registry import create_erasure_code
from ceph_tpu.osd import ec_util
from ceph_tpu.rados.client import ObjectNotFound, RadosError

from cluster_helpers import Cluster


async def _thrash_once(rng, cluster, down: set, min_alive: int) -> None:
    """One thrash action: kill+out a random up OSD, or revive+in."""
    alive = sorted(set(cluster.osds) - down)
    if down and (len(alive) <= min_alive or rng.random() < 0.5):
        osd = rng.choice(sorted(down))
        down.discard(osd)
        await cluster.revive_osd(osd)
        await cluster.wait_for_osd_up(osd)
        await cluster.client.mon_command({"prefix": "osd in",
                                          "osd": osd})
    elif len(alive) > min_alive:
        osd = rng.choice(alive)
        down.add(osd)
        await cluster.kill_osd(osd)       # mid-write: no quiesce
        await cluster.wait_for_osd_down(osd)
        await cluster.client.mon_command({"prefix": "osd out",
                                          "osd": osd})


async def _run_thrash(*, seed: int, num_osds: int, osds_per_host: int,
                      pool: dict, min_alive: int,
                      duration_s: float = 60.0, min_actions: int = 40,
                      n_objects: int = 16,
                      osd_config: dict = None,
                      mon_config: dict = None,
                      clean_timeout: float = 180.0) -> None:
    rng = random.Random(seed)
    cluster = Cluster(num_osds=num_osds, osds_per_host=osds_per_host,
                      osd_config=osd_config, mon_config=mon_config)
    await cluster.start()
    try:
        if pool["kind"] == "ec":
            await cluster.client.create_ec_pool(
                "thrash", pool["profile"], pg_num=pool["pg_num"])
        else:
            await cluster.client.create_replicated_pool(
                "thrash", size=pool["size"], pg_num=pool["pg_num"])
        ioctx = cluster.client.open_ioctx("thrash")
        # RadosModel discipline: an ACKED op must stick; an UNACKED op
        # (error/timeout) may have committed anyway, so the legal
        # states are {last acked} U {unacked attempts since the ack}.
        # None models an acked remove.
        model: dict = {}       # oid -> acked payload | None
        maybe: dict = {}       # oid -> [indeterminate states since ack]
        stats = {"acked": 0, "unacked": 0, "removes": 0}
        down: set = set()

        async def workload():
            seq = 0
            while True:
                seq += 1
                oid = f"obj-{rng.randrange(n_objects)}"
                if oid in model and rng.random() < 0.08:
                    maybe.setdefault(oid, []).append(None)
                    try:
                        await ioctx.remove(oid)
                        model[oid] = None
                        maybe[oid] = []
                        stats["removes"] += 1
                    except (RadosError, ObjectNotFound):
                        stats["unacked"] += 1
                    continue
                data = np.random.default_rng(seed * 100_000 + seq) \
                    .integers(0, 256, rng.randrange(1000, 60_000),
                              dtype=np.uint8).tobytes()
                # record BEFORE submitting: a cancelled/failed attempt
                # may still commit (indeterminate)
                maybe.setdefault(oid, []).append(data)
                try:
                    await ioctx.write_full(oid, data)
                    model[oid] = data   # acked -> must survive
                    maybe[oid] = []     # pre-ack attempts are dead: the
                    # daemon fences zombie parked ops
                    stats["acked"] += 1
                except RadosError:
                    stats["unacked"] += 1
                await asyncio.sleep(0)

        task = asyncio.get_running_loop().create_task(workload())
        actions = 0
        t0 = time.monotonic()
        try:
            while time.monotonic() - t0 < duration_s or \
                    actions < min_actions:
                await asyncio.sleep(
                    max(0.2, duration_s / (min_actions + 5)))
                await _thrash_once(rng, cluster, down, min_alive)
                actions += 1
        finally:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
        # heal everything
        for osd in sorted(down):
            await cluster.revive_osd(osd)
            await cluster.wait_for_osd_up(osd)
            await cluster.client.mon_command(
                {"prefix": "osd in", "osd": osd})
        # stop any wire-fault injection for the heal: the thrash
        # window is what proves the retry/resend discipline; the heal
        # only needs to CONVERGE, and recovery pushes racing
        # every-Nth-frame connection kills on a busy 1-core host can
        # outlast any fixed budget (after the revives — revived
        # daemons boot with the injection config again)
        for d in list(cluster.osds.values()) + \
                list(cluster.mons.values()):
            d.msgr.inject_socket_failures = 0
            d.msgr.inject_internal_delays = 0.0
            # the CONFIG copies too: a central-config push mid-heal
            # re-applies msgr injection from the daemon's config dict
            d.config["ms_inject_socket_failures"] = 0
            d.config["ms_inject_internal_delays"] = 0.0
        try:
            await cluster.wait_for_clean(timeout=clean_timeout)
        except TimeoutError:
            # dump what is stuck before failing: distinguishes a
            # genuinely parked PG from slow-but-moving recovery
            print(f"MON epoch={cluster.mon.osdmap.epoch} "
                  f"addrs={cluster.mon.osdmap.osd_addrs}")
            for osd in cluster.osds.values():
                print(f"osd.{osd.osd_id} epoch="
                      f"{osd.osdmap.epoch if osd.osdmap else None}"
                      f" hb_task_done="
                      f"{osd._hb_task.done() if osd._hb_task else '?'}")
            for osd in cluster.osds.values():
                if osd.osdmap is None:
                    continue  # mapless zombie: printed above
                for pgid, st in osd.pgs.items():
                    if st.primary == osd.osd_id and \
                            (st.state != "active" or st.unfound):
                        plog = osd._load_log(
                            st, osd.osdmap.pools[pgid.pool])
                        print(f"STUCK pg {pgid} on osd.{osd.osd_id}:"
                              f" state={st.state}"
                              f" unfound={st.unfound}"
                              f" missing={dict(plog.missing)}"
                              f" peer_missing={ {k: dict(v) for k, v in st.peer_missing.items()} }")
            raise
        assert actions >= min_actions
        assert stats["acked"] >= 20, stats

        # invariant 1: zero data loss.  EAGAIN-exhaustion is NOT data
        # loss — it means recovery of that object is still settling
        # (post-clean churn under CPU-starved CI); retry with a
        # deadline so only real loss (ENOENT/mismatch) fails the run.
        async def read_settled(oid):
            deadline = time.monotonic() + 120
            while True:
                try:
                    return await ioctx.read(oid)
                except ObjectNotFound:
                    return None
                except RadosError:
                    if time.monotonic() > deadline:
                        raise
                    await asyncio.sleep(1.0)

        final: dict = {}
        for oid, data in model.items():
            got = await read_settled(oid)
            legal = [data] + maybe.get(oid, [])
            if not any(got == want for want in legal):
                # forensics: which generation does each shard hold?
                import json as _json

                from ceph_tpu.os import ObjectId as _OID

                pg = ioctx.object_pg(oid)
                acting, _p = cluster.mon.osdmap.pg_to_acting_osds(pg)
                state_dump = []
                for idx, osd in enumerate(acting):
                    if osd < 0 or osd not in cluster.osds:
                        continue
                    store = cluster.stores[osd]
                    cid = (f"{pg.pool}.{pg.ps:x}s{idx}_head"
                           if pool["kind"] == "ec"
                           else f"{pg.pool}.{pg.ps:x}_head")
                    for name in (oid, "_rbgen_" + oid):
                        try:
                            at = store.getattrs(cid, _OID(name))
                            oi = _json.loads(at.get("_", b"{}"))
                        except KeyError:
                            continue
                        state_dump.append(
                            (idx, osd, name, oi.get("version"),
                             oi.get("size")))
                raise AssertionError(
                    f"{oid}: read "
                    f"({len(got) if got is not None else 'ENOENT'})"
                    f" matches neither the acked state"
                    f" ({len(data) if data else 'removed'}) nor any"
                    f" of {len(maybe.get(oid, []))} indeterminate"
                    f" attempts; shards: {state_dump}")
            if got is not None:
                final[oid] = got

        # invariant 2: every stored copy converged to the read state.
        # Copies left stale by soft-failed fan-outs converge lazily via
        # scrub (the deep-scrub repair discipline), so run an explicit
        # scrub pass first — the invariant is "scrub reconciles
        # everything", not "no write ever leaves a stale copy behind".
        for osd_id in sorted(cluster.osds):
            try:
                await cluster.client.osd_command(
                    osd_id, {"prefix": "scrub"})
            except RadosError:
                pass
        await cluster.wait_for_clean(timeout=max(120.0,
                                                 clean_timeout))
        checked = 0
        if pool["kind"] == "ec":
            codec = create_erasure_code(dict(pool["profile"]))
            k = codec.get_data_chunk_count()
            unit = codec.get_chunk_size(k * 4096)
            sinfo = ec_util.StripeInfo(k, k * unit)
        from ceph_tpu.os import ObjectId

        for oid, data in final.items():
            pg = ioctx.object_pg(oid)
            acting, _p = cluster.mon.osdmap.pg_to_acting_osds(pg)
            if pool["kind"] == "ec":
                width = sinfo.get_stripe_width()
                padded = data + bytes(-len(data) % width)
                expect = ec_util.encode(
                    sinfo, codec, padded,
                    range(codec.get_chunk_count()))
            for idx, osd in enumerate(acting):
                if osd < 0 or osd not in cluster.osds:
                    continue
                store = cluster.stores[osd]
                if pool["kind"] == "ec":
                    cid = f"{pg.pool}.{pg.ps:x}s{idx}_head"
                    want = expect.get(idx, b"")
                else:
                    cid = f"{pg.pool}.{pg.ps:x}_head"
                    want = data
                try:
                    buf = store.read(cid, ObjectId(oid))
                except KeyError:
                    raise AssertionError(
                        f"{oid} copy {idx} missing on osd.{osd}")
                assert buf == want, \
                    f"{oid} copy {idx} on osd.{osd} diverged"
                checked += 1
        assert checked > 0
    finally:
        await cluster.stop()


def test_thrash_device_injection_toggle():
    """Device-fault thrash leg: CEPH_TPU_INJECT_DEVICE_FAIL flips on
    and off MID-WORKLOAD while client writes and reads keep flowing.
    The breaker guard must absorb every scripted device failure into
    the bit-exact host path — zero client-visible op errors — and the
    final readback must match every acked write byte for byte."""
    import os

    from ceph_tpu.common import circuit

    async def main():
        cluster = Cluster(num_osds=4, osds_per_host=1)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "devinj", {"plugin": "ec_jax",
                           "technique": "reed_sol_van",
                           "k": "2", "m": "1",
                           "crush-failure-domain": "osd"},
                pg_num=4)
            ioctx = cluster.client.open_ioctx("devinj")
            rng = np.random.default_rng(55)
            model: dict = {}
            for i in range(18):
                # flip the fault seam every few ops: on (every
                # dispatch fails), off (breakers probe + re-close)
                if i % 6 == 0:
                    os.environ["CEPH_TPU_INJECT_DEVICE_FAIL"] = "1.0"
                elif i % 6 == 3:
                    os.environ.pop("CEPH_TPU_INJECT_DEVICE_FAIL",
                                   None)
                    for fam in circuit.FAMILIES:
                        circuit.breaker(fam).force_probe()
                oid = f"obj-{i % 5}"
                data = rng.integers(
                    0, 256, 3000 + 977 * i,
                    dtype=np.uint8).tobytes()
                # a scripted device fault must NEVER fail a write
                await ioctx.write_full(oid, data)
                model[oid] = data
                # ... nor a read issued while injection is active
                assert await ioctx.read(oid) == data
            os.environ.pop("CEPH_TPU_INJECT_DEVICE_FAIL", None)
            for fam in circuit.FAMILIES:
                circuit.breaker(fam).force_probe()
            for oid, data in model.items():
                assert await ioctx.read(oid) == data
        finally:
            os.environ.pop("CEPH_TPU_INJECT_DEVICE_FAIL", None)
            circuit.reset_all()
            await cluster.stop()

    asyncio.run(asyncio.wait_for(main(), 240))


def test_thrash_hedged_reads_under_delay_injection():
    """Cancellation-safety leg: with ms_inject_internal_delays on
    EVERY daemon (each frame sleeps a random sub-hop delay) and
    hedging enabled, a concurrent write/read workload must see zero
    client-visible errors, every readback bit-exact, and — after the
    workload drains — no leaked hedge tasks and no connection killed
    by a cancellation-gapped frame seq (hedges constantly cancel
    sub-reads mid-flight here)."""
    inject = {"ms_inject_internal_delays": 0.01}

    async def main():
        cluster = Cluster(num_osds=5, osds_per_host=1,
                          osd_config=dict(inject),
                          mon_config=dict(inject))
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "hthrash", {"plugin": "ec_jax",
                            "technique": "reed_sol_van",
                            "k": "2", "m": "2",
                            "crush-failure-domain": "osd"},
                pg_num=4)
            ioctx = cluster.client.open_ioctx("hthrash")
            rng = np.random.default_rng(66)
            model: dict = {}

            async def one(i: int):
                oid = f"obj-{i % 6}"
                data = rng.integers(0, 256, 2000 + 531 * i,
                                    dtype=np.uint8).tobytes()
                # writes and reads interleave under injected delays;
                # hedged gathers cancel stragglers the whole time
                await ioctx.write_full(oid, data)
                model[oid] = data
                assert await ioctx.read(oid) == data

            # batches of concurrent ops (the cancellation thrash)
            for base in range(0, 24, 6):
                await asyncio.gather(*(one(base + j)
                                       for j in range(6)))
            # final bit-exact readback of every acked object
            for oid, data in model.items():
                assert await ioctx.read(oid) == data
            # hedging actually ran (this leg must not pass vacuously)
            assert any(
                osd.hedge.counters["hedged_gathers"] > 0
                for osd in cluster.osds.values())
            # drain, then the no-leak invariant
            await asyncio.sleep(0.3)
            leaked = [t for t in asyncio.all_tasks()
                      if t.get_name().startswith("hedge:")
                      and not t.done()]
            assert not leaked, f"leaked hedge tasks: {leaked}"
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(main(), 300))


@pytest.mark.slow
def test_thrash_ec_k2m2():
    asyncio.run(asyncio.wait_for(_run_thrash(
        seed=1234, num_osds=8, osds_per_host=1,
        pool={"kind": "ec", "pg_num": 8, "profile": {
            "plugin": "ec_jax", "technique": "reed_sol_van",
            "k": "2", "m": "2", "crush-failure-domain": "osd"}},
        min_alive=5), 600))


@pytest.mark.slow
def test_thrash_ec_k8m3():
    asyncio.run(asyncio.wait_for(_run_thrash(
        seed=77, num_osds=13, osds_per_host=1,
        pool={"kind": "ec", "pg_num": 8, "profile": {
            "plugin": "ec_jax", "technique": "reed_sol_van",
            "k": "8", "m": "3", "crush-failure-domain": "osd"}},
        min_alive=11, n_objects=10), 600))


@pytest.mark.slow
def test_thrash_replicated():
    asyncio.run(asyncio.wait_for(_run_thrash(
        seed=9, num_osds=6, osds_per_host=1,
        pool={"kind": "replicated", "size": 3, "pg_num": 8},
        min_alive=4), 600))


@pytest.mark.slow
def test_thrash_with_socket_injection():
    """Thrash WITH wire-fault injection on every daemon
    (ms_inject_socket_failures=50: every ~50th frame kills its
    connection; plus sub-ms internal delays).  The reference runs its
    msgr failure-injection this way in qa suites
    (/root/reference/src/common/options.cc:1087-1108) — the point is
    that retry/resend discipline, not lossless transport, carries the
    durability invariants."""
    inject = {"ms_inject_socket_failures": 50,
              "ms_inject_internal_delays": 0.002}
    asyncio.run(asyncio.wait_for(_run_thrash(
        seed=4242, num_osds=6, osds_per_host=1,
        pool={"kind": "replicated", "size": 3, "pg_num": 8},
        min_alive=4, duration_s=30.0, min_actions=20,
        # short sub-op timeout: an injected-away reply must recycle in
        # seconds or serialized recovery crawls past the clean budget
        osd_config=dict(inject, osd_heartbeat_grace=4.0,
                        osd_sub_op_timeout=2.0),
        mon_config=dict(inject, osd_heartbeat_grace=4.0),
        # injection runs through the whole THRASH window (that's the
        # claim: retry/resend discipline carries durability);
        # _run_thrash then disables it for the heal, whose only job
        # is to CONVERGE — still generously budgeted because a busy
        # 1-core host recovers slowly even fault-free
        clean_timeout=480.0), 1500))

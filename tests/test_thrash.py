"""Thrasher tier: randomized kill/revive under load with a model checker.

The thrashosds/ceph_test_rados shape
(/root/reference/qa/tasks/ceph_manager.py:2702,2744 kill_osd/revive_osd;
/root/reference/src/test/osd/RadosModel.h): a workload of writes runs
while OSDs are killed mid-write and revived; a client-side model tracks
every ACKED write.  Invariants at the end (after the cluster goes
clean):

1. zero data loss: every acked write reads back exactly;
2. log convergence: every shard of every object matches the re-encode
   of the object's current readable state (kill-replica-mid-write logs
   converged on all shards).
"""

import asyncio
import random

import numpy as np
import pytest

from ceph_tpu.ec.registry import create_erasure_code
from ceph_tpu.osd import ec_util
from ceph_tpu.osd.pg_log import PGMETA_OID
from ceph_tpu.rados.client import RadosError

from cluster_helpers import Cluster

EC_PROFILE = {"plugin": "ec_jax", "technique": "reed_sol_van",
              "k": "2", "m": "1", "crush-failure-domain": "osd"}


async def _thrash_once(rng, cluster, down: set) -> None:
    """One thrash action: kill+out a random up OSD, or revive+in."""
    alive = sorted(set(cluster.osds) - down)
    if down and (len(alive) <= 3 or rng.random() < 0.5):
        osd = rng.choice(sorted(down))
        down.discard(osd)
        await cluster.revive_osd(osd)
        await cluster.wait_for_osd_up(osd)
        await cluster.client.mon_command({"prefix": "osd in",
                                          "osd": osd})
    elif len(alive) > 3:
        osd = rng.choice(alive)
        down.add(osd)
        await cluster.kill_osd(osd)       # mid-write: no quiesce
        await cluster.wait_for_osd_down(osd)
        await cluster.client.mon_command({"prefix": "osd out",
                                          "osd": osd})


@pytest.mark.slow
def test_thrash_ec_no_data_loss_and_converged_shards():
    async def main():
        rng = random.Random(1234)
        cluster = Cluster(num_osds=5, osds_per_host=1)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool("ec", EC_PROFILE,
                                                pg_num=8)
            ioctx = cluster.client.open_ioctx("ec")
            # RadosModel discipline: an ACKED write must survive; an
            # UNACKED write (error/timeout) may have committed anyway,
            # so the legal states are {last acked} U {unacked attempts
            # since the last ack}
            model: dict = {}       # oid -> acked payload
            maybe: dict = {}       # oid -> [unacked payloads since ack]
            down: set = set()

            async def workload():
                seq = 0
                while True:
                    seq += 1
                    oid = f"obj-{rng.randrange(12)}"
                    data = np.random.default_rng(seq).integers(
                        0, 256, rng.randrange(1000, 60_000),
                        dtype=np.uint8).tobytes()
                    # record BEFORE submitting: a cancelled/failed
                    # attempt may still commit (indeterminate)
                    maybe.setdefault(oid, []).append(data)
                    try:
                        await ioctx.write_full(oid, data)
                        model[oid] = data   # acked -> must survive
                        maybe[oid] = []     # pre-ack attempts are dead:
                        # the daemon fences zombie parked ops
                    except RadosError:
                        pass
                    await asyncio.sleep(0)

            task = asyncio.get_running_loop().create_task(workload())
            try:
                for _round in range(6):
                    await asyncio.sleep(0.4)
                    await _thrash_once(rng, cluster, down)
            finally:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
            # heal everything
            for osd in sorted(down):
                await cluster.revive_osd(osd)
                await cluster.wait_for_osd_up(osd)
                await cluster.client.mon_command(
                    {"prefix": "osd in", "osd": osd})
            await cluster.wait_for_clean()

            # invariant 1: zero data loss — every object reads back as
            # its last acked payload or a later indeterminate attempt
            assert model, "workload never acked anything"
            final: dict = {}
            for oid, data in model.items():
                got = await ioctx.read(oid)
                legal = [data] + maybe.get(oid, [])
                assert any(got == want for want in legal), \
                    (f"{oid}: read ({len(got)}B) matches neither the "
                     f"acked write ({len(data)}B) nor any of "
                     f"{len(maybe.get(oid, []))} indeterminate attempts")
                final[oid] = got

            # invariant 2: all shards converged to the readable state
            codec = create_erasure_code(dict(EC_PROFILE))
            pool_id = ioctx.pool_id
            stripe_unit = 4096
            k = codec.get_data_chunk_count()
            unit = codec.get_chunk_size(k * stripe_unit)
            sinfo = ec_util.StripeInfo(k, k * unit)
            checked = 0
            for oid, data in final.items():
                pg = ioctx.object_pg(oid)
                acting, _p = cluster.mon.osdmap.pg_to_acting_osds(pg)
                width = sinfo.get_stripe_width()
                padded = data + bytes(-len(data) % width)
                expect = ec_util.encode(
                    sinfo, codec, padded,
                    range(codec.get_chunk_count()))
                for shard, osd in enumerate(acting):
                    if osd < 0 or osd not in cluster.osds:
                        continue
                    store = cluster.stores[osd]
                    cid = f"{pg.pool}.{pg.ps:x}s{shard}_head"
                    from ceph_tpu.os import ObjectId

                    try:
                        buf = store.read(cid, ObjectId(oid))
                    except KeyError:
                        raise AssertionError(
                            f"{oid} shard {shard} missing on osd.{osd}")
                    assert buf == expect.get(shard, b""), \
                        f"{oid} shard {shard} on osd.{osd} diverged"
                    checked += 1
            assert checked > 0
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(main(), 300))

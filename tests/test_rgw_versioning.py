"""RGW versioning + lifecycle + GC + ListObjectsV2.

Reference parity shapes: RGWPutObj under versioning
(/root/reference/src/rgw/rgw_op.cc:3712), delete markers and
per-version addressing (RGWDeleteObj), lifecycle expiration sweeps
(rgw_lc.cc), deferred data GC (rgw_gc.cc), and v2 bucket listing
(RGWListBucket).  A curl-if-available leg drives the HTTP frontend
with an INDEPENDENT sigv4 implementation (stock curl --aws-sigv4).
"""

import asyncio
import shutil
import subprocess
import time

import numpy as np
import pytest

from cluster_helpers import Cluster

from ceph_tpu.rgw import RGWLite
from ceph_tpu.rgw.gateway import RGWError
from ceph_tpu.rgw.s3_frontend import S3Frontend

ACCESS, SECRET = "AKIDEXAMPLE", "s3cr3t-key-for-tests"


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 180))


async def _rgw(cluster):
    await cluster.client.create_replicated_pool("meta", size=2,
                                                pg_num=4)
    await cluster.client.create_replicated_pool("data", size=2,
                                                pg_num=4)
    return RGWLite(cluster.client, "data", "meta",
                   stripe_size=64 * 1024)


def test_versioned_put_get_delete_cycle():
    async def main():
        cluster = Cluster(num_osds=4, osds_per_host=2)
        await cluster.start()
        try:
            rgw = await _rgw(cluster)
            await rgw.create_bucket("b")
            # pre-versioning object becomes the "null" version
            await rgw.put_object("b", "k", b"gen0")
            await rgw.put_bucket_versioning("b", "enabled")
            assert await rgw.get_bucket_versioning("b") == "enabled"
            _, v1 = await rgw.put_object_ex("b", "k", b"gen1")
            _, v2 = await rgw.put_object_ex("b", "k", b"gen2")
            assert v1 and v2 and v1 != v2
            # newest wins; every version stays addressable
            assert await rgw.get_object("b", "k") == b"gen2"
            assert (await rgw.get_object_ex("b", "k", v1))[0] == b"gen1"
            assert (await rgw.get_object_ex(
                "b", "k", "null"))[0] == b"gen0"
            # plain DELETE inserts a marker; GET turns NoSuchKey but
            # versions survive
            marker = await rgw.delete_object("b", "k")
            assert marker is not None
            with pytest.raises(RGWError):
                await rgw.get_object("b", "k")
            assert (await rgw.get_object_ex("b", "k", v2))[0] == b"gen2"
            versions = await rgw.list_object_versions("b")
            kinds = [(v["version_id"], v["delete_marker"])
                     for v in versions]
            assert kinds[0] == (marker, True)
            assert len(versions) == 4  # marker + gen2 + gen1 + null
            # deleting the MARKER undeletes (newest again visible)
            await rgw.delete_object("b", "k", version_id=marker)
            assert await rgw.get_object("b", "k") == b"gen2"
            # permanent per-version delete
            await rgw.delete_object("b", "k", version_id=v2)
            assert await rgw.get_object("b", "k") == b"gen1"
            # bucket with versions refuses deletion
            with pytest.raises(RGWError):
                await rgw.delete_bucket("b")
        finally:
            await cluster.stop()

    run(main())


def test_suspended_versioning_null_replacement():
    async def main():
        cluster = Cluster(num_osds=4, osds_per_host=2)
        await cluster.start()
        try:
            rgw = await _rgw(cluster)
            await rgw.create_bucket("b")
            await rgw.put_bucket_versioning("b", "enabled")
            _, v1 = await rgw.put_object_ex("b", "k", b"kept")
            await rgw.put_bucket_versioning("b", "suspended")
            _, n1 = await rgw.put_object_ex("b", "k", b"null-1")
            _, n2 = await rgw.put_object_ex("b", "k", b"null-2")
            assert n1 == n2 == "null"
            # the second null REPLACED the first; v1 survives
            versions = await rgw.list_object_versions("b")
            vids = [v["version_id"] for v in versions]
            assert vids.count("null") == 1 and v1 in vids
            assert await rgw.get_object("b", "k") == b"null-2"
            assert (await rgw.get_object_ex("b", "k", v1))[0] == b"kept"
        finally:
            await cluster.stop()

    run(main())


def test_gc_defers_and_drains():
    async def main():
        cluster = Cluster(num_osds=4, osds_per_host=2)
        await cluster.start()
        try:
            rgw = await _rgw(cluster)
            await rgw.create_bucket("b")
            await rgw.put_object("b", "k", b"A" * 100_000)
            await rgw.put_object("b", "k", b"B" * 100_000)  # replace
            await rgw.delete_object("b", "k")
            # replaced + deleted stripes are queued, not yet gone
            names_before = await rgw.data.list_objects()
            assert names_before, "stripes should still exist pre-GC"
            n = await rgw.gc_process()
            assert n >= 2
            assert await rgw.data.list_objects() == []
            assert await rgw.gc_process() == 0  # idempotent drain
        finally:
            await cluster.stop()

    run(main())


def test_lifecycle_sweep():
    async def main():
        cluster = Cluster(num_osds=4, osds_per_host=2)
        await cluster.start()
        try:
            rgw = await _rgw(cluster)
            await rgw.create_bucket("b")
            await rgw.put_bucket_versioning("b", "enabled")
            await rgw.put_object_ex("b", "logs/old", b"ancient")
            await rgw.put_object_ex("b", "logs/old", b"current")
            await rgw.put_object_ex("b", "keep/x", b"kept")
            up = await rgw.init_multipart("b", "logs/stale-upload")
            await rgw.put_bucket_lifecycle("b", [
                {"id": "expire-logs", "prefix": "logs/",
                 "status": "Enabled", "expiration_days": 7,
                 "noncurrent_days": 3, "abort_multipart_days": 2}])
            # nothing is old enough yet
            stats = await rgw.lifecycle_process()
            assert stats["expired"] == 0
            assert stats["uploads_aborted"] == 0
            # jump 10 days into the future
            future = time.time() + 10 * 86400
            stats = await rgw.lifecycle_process(now=future)
            assert stats["expired"] == 1          # logs/old current
            assert stats["noncurrent_pruned"] >= 1
            # the expiration's delete marker, left as the only
            # version, is cleaned up in the same sweep
            assert stats["markers_removed"] >= 1
            assert stats["uploads_aborted"] == 1
            with pytest.raises(RGWError):
                await rgw._upload("b", "logs/stale-upload", up)
            assert await rgw.list_object_versions("b", "logs/") == []
            # untouched prefix survives
            assert await rgw.get_object("b", "keep/x") == b"kept"
        finally:
            await cluster.stop()

    run(main())


def test_list_objects_v2_semantics():
    async def main():
        cluster = Cluster(num_osds=4, osds_per_host=2)
        await cluster.start()
        try:
            rgw = await _rgw(cluster)
            await rgw.create_bucket("b")
            for key in ("a.txt", "dir/one", "dir/two", "dir2/x",
                        "z.txt"):
                await rgw.put_object("b", key, b"x")
            res = await rgw.list_objects_v2("b", delimiter="/")
            assert [c["key"] for c in res["contents"]] == \
                ["a.txt", "z.txt"]
            assert res["common_prefixes"] == ["dir/", "dir2/"]
            assert not res["is_truncated"]
            # prefix + delimiter descends one level
            res = await rgw.list_objects_v2("b", prefix="dir/",
                                            delimiter="/")
            assert [c["key"] for c in res["contents"]] == \
                ["dir/one", "dir/two"]
            # pagination with continuation tokens covers everything
            got, token = [], ""
            while True:
                res = await rgw.list_objects_v2(
                    "b", continuation_token=token, max_keys=2)
                got.extend(c["key"] for c in res["contents"])
                if not res["is_truncated"]:
                    break
                token = res["next_token"]
            assert got == ["a.txt", "dir/one", "dir/two", "dir2/x",
                           "z.txt"]
        finally:
            await cluster.stop()

    run(main())


def test_http_versioning_and_v2_listing():
    """The same semantics through the HTTP frontend (sigv4)."""
    import sys

    sys.path.insert(0, "tests")
    from test_s3_http import MiniS3

    async def main():
        cluster = Cluster(num_osds=4, osds_per_host=2)
        await cluster.start()
        front = None
        client = None
        try:
            rgw = await _rgw(cluster)
            front = S3Frontend(rgw, {ACCESS: SECRET})
            addr = await front.start()
            client = MiniS3(addr)
            st, _, _ = await client.request("PUT", "/vb")
            assert st == 200
            st, _, _ = await client.request(
                "PUT", "/vb", {"versioning": ""},
                b"<VersioningConfiguration><Status>Enabled</Status>"
                b"</VersioningConfiguration>")
            assert st == 200
            st, h1, _ = await client.request("PUT", "/vb/k",
                                             body=b"one")
            st, h2, _ = await client.request("PUT", "/vb/k",
                                             body=b"two")
            v1 = h1["x-amz-version-id"]
            assert st == 200 and v1 != h2["x-amz-version-id"]
            st, _, body = await client.request(
                "GET", "/vb/k", {"versionId": v1})
            assert body == b"one"
            st, hdrs, _ = await client.request("DELETE", "/vb/k")
            assert hdrs.get("x-amz-delete-marker") == "true"
            st, _, _ = await client.request("GET", "/vb/k")
            assert st == 404
            st, _, body = await client.request(
                "GET", "/vb", {"versions": ""})
            assert b"DeleteMarker" in body and b"<Version>" in body
            # v2 listing with delimiter through HTTP
            for key in ("d/x", "d/y", "top"):
                await client.request("PUT", f"/vb/{key}", body=b"z")
            st, _, body = await client.request(
                "GET", "/vb", {"list-type": "2", "delimiter": "/"})
            assert b"<Prefix>d/</Prefix>" in body
            assert b"<Key>top</Key>" in body
            # lifecycle round-trip through HTTP
            st, _, _ = await client.request(
                "PUT", "/vb", {"lifecycle": ""},
                b"<LifecycleConfiguration><Rule><ID>r1</ID>"
                b"<Prefix>d/</Prefix><Status>Enabled</Status>"
                b"<Expiration><Days>5</Days></Expiration>"
                b"</Rule></LifecycleConfiguration>")
            assert st == 200
            st, _, body = await client.request(
                "GET", "/vb", {"lifecycle": ""})
            assert b"<Days>5</Days>" in body
        finally:
            if client:
                await client.close()
            if front:
                await front.stop()
            await cluster.stop()

    run(main())


def _curl_has_sigv4() -> bool:
    """--aws-sigv4 arrived in curl 7.75.0; probe instead of parsing
    versions so distro backports are honoured either way."""
    if shutil.which("curl") is None:
        return False
    try:
        probe = subprocess.run(
            ["curl", "--aws-sigv4", "aws:amz:us-east-1:s3", "--user",
             "a:b", "--max-time", "5", "http://127.0.0.1:1/"],
            capture_output=True, timeout=30)
    except (subprocess.TimeoutExpired, OSError):
        return False  # a hanging probe must skip, not error collection
    return b"is unknown" not in probe.stderr


@pytest.mark.skipif(shutil.which("curl") is None,
                    reason="curl not installed")
def test_curl_interop_leg():
    """Interop with an INDEPENDENT sigv4 implementation: stock curl
    --aws-sigv4 drives PUT/GET/DELETE + versioning against the
    frontend (the reproducible form of round 4's hand validation)."""
    # probed here, not in skipif: a decorator probe would spawn curl
    # at collection time on every pytest run that touches this file
    if not _curl_has_sigv4():
        pytest.skip("curl without --aws-sigv4 support")
    async def main():
        cluster = Cluster(num_osds=4, osds_per_host=2)
        await cluster.start()
        front = None
        try:
            rgw = await _rgw(cluster)
            front = S3Frontend(rgw, {ACCESS: SECRET})
            addr = await front.start()

            def curl(method, path, data=None, extra=()):
                cmd = ["curl", "-s", "-o", "-", "-w",
                       "\n%{http_code}", "-X", method,
                       "--aws-sigv4", "aws:amz:us-east-1:s3",
                       "--user", f"{ACCESS}:{SECRET}",
                       f"http://{addr}{path}", *extra]
                if data is not None:
                    cmd += ["--data-binary", data]
                out = subprocess.run(cmd, capture_output=True,
                                     timeout=30)
                body, _, code = out.stdout.rpartition(b"\n")
                return int(code), body

            loop = asyncio.get_running_loop()

            async def acurl(*a, **k):
                return await loop.run_in_executor(
                    None, lambda: curl(*a, **k))

            code, _ = await acurl("PUT", "/curlb")
            assert code == 200
            code, _ = await acurl("PUT", "/curlb/hello",
                                  data="payload-from-curl")
            assert code == 200
            code, body = await acurl("GET", "/curlb/hello")
            assert code == 200 and body == b"payload-from-curl"
            code, body = await acurl("GET", "/curlb",
                                     extra=["-G", "-d",
                                            "list-type=2"])
            assert code == 200 and b"<Key>hello</Key>" in body
            code, _ = await acurl("DELETE", "/curlb/hello")
            assert code == 204
            code, _ = await acurl("GET", "/curlb/hello")
            assert code == 404
            # a WRONG secret must be rejected by the verifier
            out = await loop.run_in_executor(None, lambda: subprocess.run(
                ["curl", "-s", "-o", "/dev/null", "-w", "%{http_code}",
                 "--aws-sigv4", "aws:amz:us-east-1:s3",
                 "--user", f"{ACCESS}:wrong-secret",
                 f"http://{addr}/curlb"],
                capture_output=True, timeout=30))
            assert out.stdout.strip() == b"403"
        finally:
            if front:
                await front.stop()
            await cluster.stop()

    run(main())


def test_multipart_complete_respects_versioning():
    """A multipart completion on a versioning-enabled bucket must land
    as a version (review finding: it wrote a legacy head, orphaning
    the multipart data behind the versions doc)."""
    async def main():
        cluster = Cluster(num_osds=4, osds_per_host=2)
        await cluster.start()
        try:
            rgw = await _rgw(cluster)
            await rgw.create_bucket("b")
            await rgw.put_bucket_versioning("b", "enabled")
            _, v1 = await rgw.put_object_ex("b", "k", b"atomic-gen")
            up = await rgw.init_multipart("b", "k")
            payload = bytes(np.random.default_rng(4).integers(
                0, 256, 200_000, dtype=np.uint8))
            e1 = await rgw.upload_part("b", "k", up, 1,
                                       payload[:100_000])
            e2 = await rgw.upload_part("b", "k", up, 2,
                                       payload[100_000:])
            await rgw.complete_multipart("b", "k", up,
                                         [(1, e1), (2, e2)])
            # the multipart object is the newest version; the atomic
            # generation is still addressable
            assert await rgw.get_object("b", "k") == payload
            assert (await rgw.get_object_ex(
                "b", "k", v1))[0] == b"atomic-gen"
            versions = await rgw.list_object_versions("b")
            assert len(versions) == 2
        finally:
            await cluster.stop()

    run(main())


def test_version_id_on_unversioned_bucket():
    """versionId semantics on never-versioned keys: "null" addresses
    the plain object; any other id is NoSuchVersion — never a silent
    whole-object delete (review finding)."""
    async def main():
        cluster = Cluster(num_osds=4, osds_per_host=2)
        await cluster.start()
        try:
            rgw = await _rgw(cluster)
            await rgw.create_bucket("b")
            await rgw.put_object("b", "k", b"data")
            with pytest.raises(RGWError) as ei:
                await rgw.delete_object("b", "k", version_id="bogus")
            assert ei.value.code == "NoSuchVersion"
            assert await rgw.get_object("b", "k") == b"data"
            await rgw.delete_object("b", "k", version_id="null")
            with pytest.raises(RGWError):
                await rgw.get_object("b", "k")
        finally:
            await cluster.stop()

    run(main())


def test_gc_two_phase_pending_protects_referenced_data():
    """The crash window between _gc_defer and the index mutation leaves
    PENDING entries: gc_process must NOT delete them (the data may
    still be referenced) until an operator reclaims explicitly."""
    async def main():
        cluster = Cluster(num_osds=4, osds_per_host=2)
        await cluster.start()
        try:
            rgw = await _rgw(cluster)
            await rgw.create_bucket("b")
            await rgw.put_object("b", "k", b"LIVE" * 25_000)
            # simulate the crash state: stripes deferred, index
            # mutation never committed (no _gc_commit)
            head = await rgw._load(rgw._meta_oid("head", "b", "k"))
            oids = [s["oid"] for s in head["manifest"]["stripes"]]
            await rgw._gc_defer(oids)
            assert await rgw.gc_process() == 0  # pending: untouchable
            # the object the entries still reference reads back intact
            assert await rgw.get_object("b", "k") == b"LIVE" * 25_000
            entries = await rgw.gc_list()
            assert entries and all(e["state"] == "pending"
                                   for e in entries)
            # explicit operator reclaim drains them
            n = await rgw.gc_process(reclaim_pending_after=0.0)
            assert n == len(oids)
            assert await rgw.gc_list() == []
        finally:
            await cluster.stop()

    run(main())


def test_list_v2_max_keys_zero():
    async def main():
        cluster = Cluster(num_osds=4, osds_per_host=2)
        await cluster.start()
        try:
            rgw = await _rgw(cluster)
            await rgw.create_bucket("b")
            await rgw.put_object("b", "k1", b"x")
            await rgw.put_object("b", "k2", b"y")
            out = await rgw.list_objects_v2("b", max_keys=0)
            # S3: max-keys=0 => empty, NOT truncated (a truncated
            # answer with an empty token loops naive paginators)
            assert out["contents"] == []
            assert out["is_truncated"] is False
            assert out["next_token"] == ""
        finally:
            await cluster.stop()

    run(main())

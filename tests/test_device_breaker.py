"""Device-tier fault tolerance: the circuit breaker state machine,
the guarded dispatch choke point (watchdog, OOM halving, poisoned-plan
quarantine), the scripted fault-injection seam, and the degradation
contract — a device fault NEVER surfaces to a caller, the bit-exact
numpy host path serves instead, and a half-open probe re-closes the
breaker once the device heals.
"""

from __future__ import annotations

import asyncio
import time

import numpy as np
import pytest

from ceph_tpu.common import circuit
from ceph_tpu.ec import dispatch as ec_dispatch
from ceph_tpu.ec import plan
from ceph_tpu.models import reed_solomon as rs

try:
    import jax  # noqa: F401

    HAVE_JAX = True
except Exception:  # pragma: no cover
    HAVE_JAX = False

needs_jax = pytest.mark.skipif(not HAVE_JAX, reason="needs jax")


@pytest.fixture(autouse=True)
def _clean_device_state(monkeypatch):
    """Every test starts with closed breakers, an empty plan cache,
    and no inherited injection spec — and leaks none of them to the
    next test module (breakers are process-global)."""
    monkeypatch.delenv("CEPH_TPU_INJECT_DEVICE_FAIL", raising=False)
    circuit.reset_all()
    plan.clear()
    plan.reset_stats()
    yield
    circuit.reset_all()
    plan.clear()


class FakeClock:
    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


def _mk_breaker(clk, threshold=2, base=1.0, cap=8.0, rng=lambda: 0.5):
    return circuit.CircuitBreaker("test", fail_threshold=threshold,
                                  base_backoff=base, max_backoff=cap,
                                  clock=clk, rng=rng)


# -- breaker state machine -------------------------------------------------


def test_trip_half_open_reclose_state_machine():
    clk = FakeClock()
    br = _mk_breaker(clk)
    assert br.state == "closed" and br.allow()
    br.record_failure()
    assert br.state == "closed"          # below threshold
    br.record_failure()                  # 2nd consecutive: trip
    assert br.state == "open" and br.counters["trips"] == 1
    # open with unexpired backoff (rng=0.5 * ceiling 1.0 => 0.5s)
    clk.t = 0.4
    assert not br.allow() and br.degraded()
    # backoff expired: exactly ONE probe is admitted
    clk.t = 0.6
    assert br.allow()
    assert br.state == "half_open" and br.counters["probes"] == 1
    assert not br.allow()                # concurrent caller refused
    br.record_success()                  # probe ok: re-close
    assert br.state == "closed" and br.counters["recoveries"] == 1
    assert br.allow() and not br.degraded()


def test_failed_probe_reopens_with_larger_backoff():
    clk = FakeClock()
    br = _mk_breaker(clk)
    br.record_failure()
    br.record_failure()                  # trip #1: ceiling 1.0 -> 0.5
    clk.t = 0.6
    assert br.allow()                    # the probe
    br.record_failure()                  # probe failed: reopen
    assert br.state == "open" and br.counters["trips"] == 2
    # exponential: ceiling now base * 2^1 = 2.0, jittered to 1.0
    assert br.stats()["retry_in_s"] == pytest.approx(1.0, abs=0.01)
    clk.t = 0.6 + 0.9
    assert not br.allow()
    clk.t = 0.6 + 1.1
    assert br.allow()
    br.record_success()
    # success resets the backoff exponent: next trip starts small again
    br.record_failure()
    br.record_failure()
    assert br.stats()["retry_in_s"] == pytest.approx(0.5, abs=0.01)


def test_watchdog_timeout_trips_immediately():
    clk = FakeClock()
    br = _mk_breaker(clk, threshold=5)
    br.record_failure(timeout=True)      # one hang beats the threshold
    assert br.state == "open"
    assert br.counters["watchdog_timeouts"] == 1


def test_force_open_and_force_probe():
    clk = FakeClock()
    br = _mk_breaker(clk)
    br.force_open(duration=100.0)
    assert br.degraded() and not br.allow()
    br.force_probe()
    assert br.allow() and br.state == "half_open"


# -- injection spec --------------------------------------------------------


def test_injection_spec_parsing():
    assert circuit.parse_injection(None) is None
    assert circuit.parse_injection("") is None
    assert circuit.parse_injection("0") is None
    assert circuit.parse_injection("1.0")["p"] == 1.0
    assert circuit.parse_injection("0.25")["p"] == 0.25
    spec = circuit.parse_injection("p=0.5,next=3,hang=20,oom=8")
    assert spec == {"p": 0.5, "next": 3, "hang_ms": 20.0,
                    "oom_batch": 8, "sick_device": None,
                    "down_host": None}
    assert circuit.parse_injection("sick=3")["sick_device"] == 3
    assert circuit.parse_injection("down_host=1")["down_host"] == 1
    with pytest.raises(ValueError):
        circuit.parse_injection("bogus=1")


def test_device_call_statuses(monkeypatch):
    calls = []

    def fn(x):
        calls.append(x)
        return x * 2

    # ok
    assert circuit.device_call("test-fam", fn, 21) == ("ok", 42)
    # fail (p=1.0)
    monkeypatch.setenv("CEPH_TPU_INJECT_DEVICE_FAIL", "1.0")
    status, err = circuit.device_call("test-fam", fn, 1)
    assert status == "fail" and isinstance(err, circuit.DeviceFault)
    # fail-next-N heals after N
    monkeypatch.setenv("CEPH_TPU_INJECT_DEVICE_FAIL", "next=2")
    assert circuit.device_call("test-fam2", fn, 1)[0] == "fail"
    assert circuit.device_call("test-fam2", fn, 1)[0] == "fail"
    assert circuit.device_call("test-fam2", fn, 1) == ("ok", 2)
    # oom above batch k; oom_to_fail at the floor
    monkeypatch.setenv("CEPH_TPU_INJECT_DEVICE_FAIL", "oom=4")
    status, err = circuit.device_call("test-fam3", fn, 1, batch=8)
    assert status == "oom" and circuit.is_resource_exhausted(err)
    assert circuit.device_call("test-fam3", fn, 1, batch=2) == \
        ("ok", 2)
    status, _ = circuit.device_call("test-fam3", fn, 1, batch=8,
                                    oom_to_fail=True)
    assert status == "fail"
    # hang drives the watchdog; the breaker trips on one timeout
    monkeypatch.setenv("CEPH_TPU_INJECT_DEVICE_FAIL", "hang=500")
    status, _ = circuit.device_call("test-fam4", fn, 1, timeout=0.05)
    assert status == "timeout"
    assert circuit.breaker("test-fam4").state == "open"
    # open breaker refuses without running fn
    monkeypatch.delenv("CEPH_TPU_INJECT_DEVICE_FAIL")
    n = len(calls)
    status, _ = circuit.device_call("test-fam4", fn, 1)
    assert status == "open" and len(calls) == n
    assert circuit.breaker("test-fam4").counters["fallbacks"] == 1
    # benign exceptions bypass breaker accounting
    def unsupported():
        raise NotImplementedError("rule")

    status, err = circuit.device_call("test-fam5", unsupported,
                                      benign=(NotImplementedError,))
    assert status == "benign"
    assert circuit.breaker("test-fam5").counters["failures"] == 0


def test_probe_slot_released_on_oom_and_benign(monkeypatch):
    """A half-open probe that ends in OOM (to be batch-halved) or a
    benign exception carries no health verdict: the probe slot must be
    handed back, not leaked — a leaked slot wedges the breaker in
    half_open forever (every later allow() refused)."""
    br = circuit.breaker("test-leak")
    br.force_open(duration=0.0)           # probe due immediately
    monkeypatch.setenv("CEPH_TPU_INJECT_DEVICE_FAIL", "oom=1")
    status, _ = circuit.device_call("test-leak", lambda: 1, batch=4)
    assert status == "oom"
    assert br.state == "half_open" and not br.degraded()
    monkeypatch.delenv("CEPH_TPU_INJECT_DEVICE_FAIL")
    status, out = circuit.device_call("test-leak", lambda: 1, batch=4)
    assert (status, out) == ("ok", 1) and br.state == "closed"

    def unsupported():
        raise NotImplementedError("rule")

    br2 = circuit.breaker("test-leak2")
    br2.force_open(duration=0.0)
    status, _ = circuit.device_call("test-leak2", unsupported,
                                    benign=(NotImplementedError,))
    assert status == "benign"
    assert br2.state == "half_open" and not br2.degraded()
    status, out = circuit.device_call("test-leak2", lambda: 2)
    assert (status, out) == ("ok", 2) and br2.state == "closed"


def test_kill_switch_restores_raw_dispatch(monkeypatch):
    monkeypatch.setenv("CEPH_TPU_BREAKER", "0")

    def boom():
        raise RuntimeError("raw")

    # guard bypassed: exceptions propagate, injection seam is off
    monkeypatch.setenv("CEPH_TPU_INJECT_DEVICE_FAIL", "1.0")
    assert circuit.device_call("test-kill", lambda: 7) == ("ok", 7)
    with pytest.raises(RuntimeError):
        circuit.device_call("test-kill", boom)


# -- host degradation through the EC dispatch layers -----------------------


@needs_jax
def test_gf_matmul_degrades_bit_exactly_and_recovers(monkeypatch):
    mat = rs.reed_sol_van_matrix(4, 2)
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (8, 4, 64), dtype=np.uint8)
    host = ec_dispatch.gf_matmul(mat, data, use_tpu=False)
    monkeypatch.setenv("CEPH_TPU_INJECT_DEVICE_FAIL", "1.0")
    for _ in range(6):   # past the trip threshold and into open state
        out = ec_dispatch.gf_matmul(mat, data, use_tpu=True)
        assert np.array_equal(out, host)   # bit-exact, no exception
    br = circuit.breaker("ec-encode")
    assert br.stats()["trips"] >= 1
    # injection clears: a forced half-open probe re-closes the breaker
    monkeypatch.delenv("CEPH_TPU_INJECT_DEVICE_FAIL")
    br.force_probe()
    out = ec_dispatch.gf_matmul(mat, data, use_tpu=True)
    assert np.array_equal(out, host)
    st = br.stats()
    assert st["state"] == "closed" and st["recoveries"] >= 1 \
        and st["probes"] >= 1
    # ... and the transitions are visible through plan.stats()
    health = plan.stats()["device_health"]["ec-encode"]
    assert health["trips"] >= 1 and health["recoveries"] >= 1


@needs_jax
def test_decode_family_trips_independently(monkeypatch):
    from ceph_tpu.ec.registry import create_erasure_code

    codec = create_erasure_code(
        {"plugin": "ec_jax", "technique": "reed_sol_van",
         "k": "4", "m": "2"})
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, (6, 4, 128), dtype=np.uint8)
    parity = codec.encode_batch(data)
    monkeypatch.setenv("CEPH_TPU_INJECT_DEVICE_FAIL", "1.0")
    survivors = np.concatenate([data[:, 2:, :], parity], axis=1)
    have, erased = (2, 3, 4, 5), (0, 1)
    for _ in range(4):
        recovered = codec.decode_batch(have, erased, survivors)
        assert np.array_equal(np.asarray(recovered), data[:, :2, :])
    assert circuit.breaker("ec-decode").stats()["failures"] >= 1
    # the decode storm tripped ec-decode, not the encode family
    assert circuit.breaker("ec-encode").stats()["trips"] == 0


@needs_jax
def test_oom_halving_bit_exact_vs_numpy_oracle(monkeypatch):
    mat = rs.reed_sol_van_matrix(4, 2)
    rng = np.random.default_rng(2)
    data = rng.integers(0, 256, (32, 4, 64), dtype=np.uint8)
    oracle = ec_dispatch.gf_matmul(mat, data, use_tpu=False)
    monkeypatch.setenv("CEPH_TPU_INJECT_DEVICE_FAIL", "oom=4")
    out = plan.encode(mat, data)
    # the split bottomed out at batches <= 4, each dispatched on
    # device, and the reassembled parity is bit-exact
    assert out is not None and np.array_equal(out, oracle)
    st = plan.stats()
    assert st["oom_splits"] >= 3          # 32 -> 16 -> 8 -> 4
    assert circuit.breaker("ec-encode").stats()["trips"] == 0


@needs_jax
def test_oom_halving_fused_crc(monkeypatch):
    mat = rs.reed_sol_van_matrix(4, 2)
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, (16, 4, 96), dtype=np.uint8)
    want = plan.encode_with_crc(mat, data)
    assert want is not None
    plan.clear()
    monkeypatch.setenv("CEPH_TPU_INJECT_DEVICE_FAIL", "oom=2")
    got = plan.encode_with_crc(mat, data)
    assert got is not None
    assert np.array_equal(got[0], want[0])
    assert np.array_equal(got[1], want[1])


@needs_jax
def test_oom_at_single_stripe_floor_falls_back_to_host(monkeypatch):
    mat = rs.reed_sol_van_matrix(4, 2)
    rng = np.random.default_rng(4)
    data = rng.integers(0, 256, (4, 4, 64), dtype=np.uint8)
    host = ec_dispatch.gf_matmul(mat, data, use_tpu=False)
    monkeypatch.setenv("CEPH_TPU_INJECT_DEVICE_FAIL", "oom=0")
    # every batch size OOMs, even a single stripe: the floor gives up
    # and the caller rides the host path — still bit-exact, no raise
    assert plan.encode(mat, data) is None
    out = ec_dispatch.gf_matmul(mat, data, use_tpu=True)
    assert np.array_equal(out, host)


@needs_jax
def test_watchdog_contains_wedged_dispatch(monkeypatch):
    mat = rs.reed_sol_van_matrix(4, 2)
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, (4, 4, 64), dtype=np.uint8)
    host = ec_dispatch.gf_matmul(mat, data, use_tpu=False)
    monkeypatch.setenv("CEPH_TPU_INJECT_DEVICE_FAIL", "hang=400")
    monkeypatch.setenv("CEPH_TPU_DEVICE_TIMEOUT_S", "0.05")
    t0 = time.monotonic()
    out = ec_dispatch.gf_matmul(mat, data, use_tpu=True)
    elapsed = time.monotonic() - t0
    assert np.array_equal(out, host)
    assert elapsed < 5.0                  # bounded, not the full hang
    br = circuit.breaker("ec-encode").stats()
    assert br["watchdog_timeouts"] >= 1 and br["state"] == "open"


# -- poisoned-plan quarantine ----------------------------------------------


@needs_jax
def test_poisoned_plan_quarantine_and_expiry(monkeypatch):
    monkeypatch.setenv("CEPH_TPU_PLAN_QUARANTINE_S", "0.25")
    mat = rs.reed_sol_van_matrix(4, 2)
    rng = np.random.default_rng(6)
    data = rng.integers(0, 256, (8, 4, 64), dtype=np.uint8)
    # keep the breaker out of the way: this test is about the PLAN
    # failure counter, which needs failures to keep reaching the key
    circuit.breaker("ec-encode").fail_threshold = 10_000
    monkeypatch.setenv("CEPH_TPU_INJECT_DEVICE_FAIL", "1.0")
    for _ in range(3):                    # CEPH_TPU_PLAN_FAIL_LIMIT
        assert plan.encode(mat, data) is None
    st = plan.stats()
    assert st["quarantines"] == 1 and st["quarantined_plans"] == 1
    assert plan.quarantine_info()["entries"]
    # injection clears, but the key stays blacklisted until the TTL:
    # callers keep riding the host path without rebuilding the plan
    monkeypatch.delenv("CEPH_TPU_INJECT_DEVICE_FAIL")
    misses_before = plan.stats()["misses"]
    assert plan.encode(mat, data) is None
    assert plan.stats()["misses"] == misses_before  # cache untouched
    time.sleep(0.3)                       # TTL expiry releases the key
    out = plan.encode(mat, data)
    assert out is not None
    assert np.array_equal(
        out, ec_dispatch.gf_matmul(mat, data, use_tpu=False))
    assert plan.stats()["quarantined_plans"] == 0


# -- hitset device hashing -------------------------------------------------


@needs_jax
def test_hitset_positions_degrade_bit_exactly(monkeypatch):
    from ceph_tpu.osd import hitset as hm

    hashes = np.array([hm.hash_oid(f"o{i}") for i in range(64)],
                      dtype=np.uint32)
    nbits, nhash = hm.bloom_geometry(1024, 0.05)
    host = hm.bloom_positions(hashes, nbits, nhash, xp=np)
    monkeypatch.setenv("CEPH_TPU_INJECT_DEVICE_FAIL", "1.0")
    got = hm.positions_for(hashes, nbits, nhash, device=True)
    assert np.array_equal(got, host)
    assert circuit.breaker("hitset-hash").stats()["failures"] >= 1


# -- encode service flush shedding -----------------------------------------


@needs_jax
def test_encode_service_flush_sheds_to_host(monkeypatch):
    """A device fault during _flush must NOT fail the per-request
    futures: the accumulated batch re-runs on the bit-exact host path
    and the shed is counted under device_fallback."""
    from ceph_tpu.ec.registry import create_erasure_code
    from ceph_tpu.osd import ec_util
    from ceph_tpu.osd.encode_service import EncodeService

    monkeypatch.setenv("CEPH_TPU_FUSE_MIN_BYTES", "0")
    codec = create_erasure_code(
        {"plugin": "ec_jax", "technique": "reed_sol_van",
         "k": "4", "m": "2"})
    sinfo = ec_util.StripeInfo(4, 4 * 1024)
    rng = np.random.default_rng(7)
    bufs = [rng.integers(0, 256, 8192, dtype=np.uint8).tobytes()
            for _ in range(8)]
    want = [ec_util.encode_with_hinfo(sinfo, codec, b, range(6),
                                      logical_len=len(b))
            for b in bufs]

    monkeypatch.setenv("CEPH_TPU_INJECT_DEVICE_FAIL", "1.0")

    async def run():
        svc = EncodeService(who="t")
        outs = await asyncio.gather(
            *(svc.encode_with_hinfo(sinfo, codec, b, range(6),
                                    logical_len=len(b))
              for b in bufs),
            return_exceptions=True)
        st = svc.stats()
        await svc.stop()
        return outs, st

    outs, st = asyncio.run(asyncio.wait_for(run(), 60))
    for b, out, (ws, wh, wc) in zip(bufs, outs, want):
        assert not isinstance(out, BaseException), out   # zero errors
        shards, hinfo, crc = out
        assert crc == wc
        assert hinfo.cumulative_shard_hashes == \
            wh.cumulative_shard_hashes
        assert all(bytes(shards[i]) == bytes(ws[i]) for i in range(6))
    assert st["device_fallback"] >= 1


# -- scrub repair under device faults --------------------------------------


def _run(coro):
    asyncio.run(asyncio.wait_for(coro, 180))


@needs_jax
def test_scrub_repair_survives_device_faults():
    """fail-next-N injection mid-scrub: the repair decode rides the
    host path, the object is repaired (not counted unrepaired), and a
    decode_many exception from the service is retried inline on host
    (_batch_reconstruct's resilience seam)."""
    from ceph_tpu.os import ObjectId, Transaction
    from ceph_tpu.osd.osdmap import PgId  # noqa: F401 (parity import)
    from ceph_tpu.rados.embedded import shard_collection

    from cluster_helpers import Cluster

    async def main():
        import os

        cluster = Cluster(num_osds=5)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ec", profile={"plugin": "ec_jax",
                               "technique": "reed_sol_van",
                               "k": "2", "m": "2",
                               "crush-failure-domain": "osd"},
                pg_num=8)
            io = cluster.client.open_ioctx("ec")
            data = bytes(np.random.default_rng(8).integers(
                0, 256, 50_000, dtype=np.uint8))
            await io.write_full("obj", data)
            osdmap = cluster.mon.osdmap
            pool = [p for p in osdmap.pools.values()
                    if p.name == "ec"][0]
            from ceph_tpu.ops.rjenkins import ceph_str_hash_rjenkins
            from ceph_tpu.osd.osdmap import PgId as _PgId

            pg = pool.raw_pg_to_pg(
                _PgId(pool.id, ceph_str_hash_rjenkins(b"obj")))
            _acting, primary = osdmap.pg_to_acting_osds(pg)
            prim = cluster.osds[primary]
            state = prim.pgs[pg]

            # round 1: the service's decode_many dies wholesale once —
            # _batch_reconstruct must retry on host, not give up
            victim = state.acting[1]
            store = cluster.osds[victim].store
            cid = shard_collection(pg, 1)
            raw = store.read(cid, ObjectId("obj"))
            t = Transaction()
            t.write(cid, ObjectId("obj"), 100, 4, b"\xde\xad\xbe\xef")
            store.queue_transaction(t)

            orig = prim.encode_service.decode_many
            calls = {"n": 0}

            async def flaky(sinfo, codec, maps):
                maps = list(maps)
                calls["n"] += 1
                if calls["n"] == 1:
                    return [RuntimeError("RESOURCE_EXHAUSTED (test)")
                            ] * len(maps)
                return await orig(sinfo, codec, maps)

            prim.encode_service.decode_many = flaky
            try:
                res = await prim.scrub_pg(state, pool)
            finally:
                prim.encode_service.decode_many = orig
            assert res["errors"] >= 1 and res["repaired"] >= 1, res
            assert prim.perf["decode_host_retries"] >= 1
            await cluster.wait_for_clean()
            assert store.read(cid, ObjectId("obj")) == raw
            assert await io.read("obj") == data

            # round 2: scripted injection at the dispatch seam while
            # the scrub runs — repair still succeeds via host fallback
            t = Transaction()
            t.write(cid, ObjectId("obj"), 200, 4, b"\xfe\xed\xfa\xce")
            store.queue_transaction(t)
            os.environ["CEPH_TPU_INJECT_DEVICE_FAIL"] = "next=8"
            try:
                res = await prim.scrub_pg(state, pool)
            finally:
                os.environ.pop("CEPH_TPU_INJECT_DEVICE_FAIL", None)
            assert res["errors"] >= 1 and res["repaired"] >= 1, res
            await cluster.wait_for_clean()
            assert store.read(cid, ObjectId("obj")) == raw
            assert await io.read("obj") == data
        finally:
            await cluster.stop()

    _run(main())

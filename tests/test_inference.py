"""Coded inference serving tier (ceph_tpu/inference): the Fisher
algebra's bound-honesty property sweep on the host (every arrival
pattern either refuses or serves with true error <= the estimate <=
the budget), the exact-path bit-parity contract, and the live-cluster
legs — CEPH_TPU_INFERENCE=0 read-then-infer parity, approximate
serving within budget under shard loss, and the hedged straggler
leg completing without the slow stream holder."""

import asyncio
import itertools
import os
import time

import numpy as np
import pytest

from cluster_helpers import Cluster
from ceph_tpu.inference import fisher, kernels, model, registry

EC32 = {"plugin": "ec_jax", "technique": "reed_sol_van",
        "k": "3", "m": "2", "crush-failure-domain": "osd"}


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 180))


def _built(kind, k, m, dim=24, out=36, seed=5, chunk=512):
    hidden = 24 if kind == "mlp" else 0  # divisible by every k here
    spec, blobs = registry.build(
        f"t-{kind}-{k}-{m}", kind,
        registry.make_model(kind, dim, out, seed=seed,
                            hidden=hidden), k, m, chunk)
    data = blobs[registry.params_oid(spec["name"])]
    streams = model.object_streams(spec, data)
    return spec, data, streams


def _queries(spec, nq=12, seed=3):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((nq, int(spec["dim"])
                                )).astype(np.float32)


# -- host property suite ---------------------------------------------------


@pytest.mark.parametrize("kind", ["linear", "mlp"])
def test_exact_combine_bit_parity(kind):
    """All k data contributions through fisher.combine are BIT-equal
    to the whole-object oracle — the contract every exact serving
    path (engine fallback, kill switch) leans on."""
    spec, data, streams = _built(kind, 3, 2)
    q = _queries(spec)
    parts = {i: model.shard_forward(spec, streams[i], q)
             for i in range(3)}
    res = fisher.combine(spec, parts, {}, q, 0.01)
    assert res is not None
    scores, est, substituted = res
    assert est == 0.0 and substituted == 0
    assert scores.tobytes() == \
        model.exact_forward(spec, data, q).tobytes()


@pytest.mark.parametrize("kind,k,m", [("linear", 2, 1),
                                      ("linear", 3, 2),
                                      ("linear", 4, 2),
                                      ("mlp", 2, 1),
                                      ("mlp", 3, 2)])
def test_bound_honesty_across_all_patterns(kind, k, m):
    """EVERY (data subset, fused subset) arrival pattern either
    refuses (structural_error None when |missing| > |fused answered|
    — nothing to solve with) or serves with true relative error <=
    the estimate.  The estimate is what the budget gate prices, so an
    estimate below the truth would let over-budget scores through."""
    spec, data, streams = _built(kind, k, m)
    q = _queries(spec)
    exact = model.exact_forward(spec, data, q)
    eref = float(np.linalg.norm(exact)) or 1.0
    parts = {i: model.shard_forward(spec, streams[i], q)
             for i in range(k)}
    fused = {j: model.shard_forward(spec, streams[k + j], q)
             for j in range(m)}
    served = refused = 0
    for nd in range(k + 1):
        for dsub in itertools.combinations(range(k), nd):
            for nf in range(m + 1):
                for fsub in itertools.combinations(range(m), nf):
                    dp = {i: parts[i] for i in dsub}
                    fp = {j: fused[j] for j in fsub}
                    # budget None: accept ANY estimate, so serve
                    # whenever the pattern is solvable at all
                    res = fisher.combine(spec, dp, fp, q, None)
                    if k - nd > nf:
                        assert res is None  # underdetermined
                        refused += 1
                        continue
                    assert res is not None, (dsub, fsub)
                    scores, est, substituted = res
                    assert substituted == k - nd
                    rel = float(np.linalg.norm(scores - exact)) / eref
                    assert rel <= max(est, 1e-6), (dsub, fsub, rel,
                                                   est)
                    served += 1
    assert served and refused


@pytest.mark.parametrize("kind,k,m", [("linear", 3, 2), ("mlp", 3, 1)])
def test_budget_gate_refuses_over_budget_patterns(kind, k, m):
    """A vanishing budget refuses every lossy pattern (est > 0) while
    still serving the full data set (est == 0) — the gate is the
    engine's exact-fallback trigger, not a soft preference."""
    spec, data, streams = _built(kind, k, m)
    q = _queries(spec)
    parts = {i: model.shard_forward(spec, streams[i], q)
             for i in range(k)}
    fused = {j: model.shard_forward(spec, streams[k + j], q)
             for j in range(m)}
    assert fisher.combine(spec, parts, {}, q, 1e-300) is not None
    for drop in range(k):
        dp = {i: parts[i] for i in range(k) if i != drop}
        assert fisher.combine(spec, dp, fused, q, 1e-300) is None
        assert fisher.combine(spec, dp, fused, q, None) is not None


def test_structural_error_prices_patterns_before_results():
    """The hedged gather's sufficiency predicate: structural_error is
    a pure function of WHICH streams answered, monotone enough to
    rank patterns — full data prices 0, every lossy pattern prices
    > 0, unsolvable prices None."""
    spec, _data, _streams = _built("linear", 3, 2)
    qscale = fisher.query_scale(_queries(spec))
    assert fisher.structural_error(spec, [0, 1, 2], [], qscale) == 0.0
    lossy = fisher.structural_error(spec, [0, 1], [0], qscale)
    assert lossy is not None and lossy > 0.0
    assert fisher.structural_error(spec, [0], [0], qscale) is None
    assert fisher.structural_error(spec, [0, 1], [], qscale) is None


def test_result_blob_roundtrip_and_exact_mode_bytes():
    """The wire result blob: decode(inverse) recovers scores, mode,
    est_error, substituted; two exact blobs over the same scores are
    byte-identical (what the kill-switch parity leg compares)."""
    scores = np.arange(24, dtype=np.float32).reshape(4, 6) / 7.0
    blob = kernels.result_blob(scores, "approx", 0.0125, 2)
    out = kernels.decode_result(blob)
    assert out["scores"].tobytes() == scores.tobytes()
    assert out["mode"] == "approx"
    assert out["est_error"] == pytest.approx(0.0125)
    assert out["substituted"] == 2
    assert kernels.result_blob(scores, "exact", 0.0, 0) == \
        kernels.result_blob(scores.copy(), "exact", 0.0, 0)


def test_validate_spec_rejects_malformed_manifests():
    """Manifests come off the wire: structural garbage must raise
    ValueError (the engine maps it to EINVAL), never KeyError."""
    spec, _data, _streams = _built("linear", 2, 1)
    model.validate_spec(spec)
    for mutate in (lambda s: s.pop("kind"),
                   lambda s: s.update(kind="rnn"),
                   lambda s: s.update(k=0),
                   lambda s: s.update(shard_rows=[1])):
        bad = dict(spec)
        mutate(bad)
        with pytest.raises(ValueError):
            model.validate_spec(bad)


# -- live-cluster legs -----------------------------------------------------


async def _serving_cluster(kind="linear", dim=32, out=64, seed=21):
    cluster = Cluster(num_osds=5, osds_per_host=5,
                      osd_config={"osd_heartbeat_interval": 3.0,
                                  "osd_heartbeat_grace": 30.0})
    await cluster.start()
    await cluster.client.create_ec_pool("ipool", profile=EC32,
                                        pg_num=8)
    io = cluster.client.open_ioctx("ipool")
    spec = await io.store_model(
        "m0", kind, registry.make_model(kind, dim, out, seed=seed),
        m=1)
    return cluster, io, spec


def test_killswitch_parity_and_approx_budget_live():
    """The acceptance parity leg: exact=True serving through the code
    is BIT-identical to CEPH_TPU_INFERENCE=0 client-side
    read-then-infer; default-budget serving stays within the budget
    of the exact scores and the engine counters attribute the ops."""
    async def main():
        cluster, io, spec = await _serving_cluster()
        try:
            budget = 0.05
            rng = np.random.default_rng(2)
            for _ in range(6):
                q = rng.standard_normal((8, 32)).astype(np.float32)
                ex = await io.infer(spec, q, exact=True)
                assert ex["mode"] == "exact"
                assert ex["est_error"] == 0.0
                os.environ["CEPH_TPU_INFERENCE"] = "0"
                try:
                    ref = await io.infer(spec, q)
                finally:
                    del os.environ["CEPH_TPU_INFERENCE"]
                assert ref["mode"] == "exact"
                assert ex["scores"].tobytes() == \
                    ref["scores"].tobytes()
                served = await io.infer(spec, q, budget=budget)
                assert served["est_error"] <= budget
                rel = float(np.linalg.norm(
                    served["scores"] - ex["scores"]) /
                    max(np.linalg.norm(ex["scores"]), 1e-12))
                assert rel <= budget
            counters = {}
            for osd in cluster.osds.values():
                for key, v in osd.inference.perf_dump().items():
                    if isinstance(v, int):
                        counters[key] = counters.get(key, 0) + v
            assert counters["ops"] >= 12  # exact + budget legs
            assert counters["exact_fallbacks"] >= 6
            assert counters["errors"] == 0
        finally:
            await cluster.stop()

    run(main())


def test_shard_loss_served_within_budget_live():
    """A DEAD serving-stream holder: queries keep serving through the
    survivors (fused substitution or full-decode fallback), always
    within budget of the pre-loss exact scores."""
    async def main():
        cluster, io, spec = await _serving_cluster()
        try:
            budget = 0.05
            q = np.random.default_rng(4).standard_normal(
                (8, 32)).astype(np.float32)
            ex = await io.infer(spec, q, exact=True)
            pg = io.object_pg(spec["params_oid"])
            acting, primary = \
                cluster.mon.osdmap.pg_to_acting_osds(pg)
            nstreams = int(spec["k"]) + int(spec["m"])
            victim = next(o for o in acting[:nstreams]
                          if o != primary and o >= 0)
            await cluster.kill_osd(victim)
            await cluster.wait_for_osd_down(victim)
            await cluster.wait_for_clean(60.0)
            res = await io.infer(spec, q, budget=budget)
            assert res["est_error"] <= budget
            rel = float(np.linalg.norm(res["scores"] - ex["scores"])
                        / max(np.linalg.norm(ex["scores"]), 1e-12))
            assert rel <= budget
        finally:
            await cluster.stop()

    run(main())


def test_straggler_first_sufficient_live():
    """One slow serving-stream holder: the hedged sub-infer fan-out
    completes from the first structurally-sufficient arrival set in a
    small fraction of the injected delay, within budget."""
    async def main():
        delay = 2.0
        cluster, io, spec = await _serving_cluster()
        try:
            budget = 0.05
            q = np.random.default_rng(6).standard_normal(
                (8, 32)).astype(np.float32)
            ex = await io.infer(spec, q, exact=True)
            await io.infer(spec, q)  # warm plans + admission
            pg = io.object_pg(spec["params_oid"])
            acting, primary = \
                cluster.mon.osdmap.pg_to_acting_osds(pg)
            nstreams = int(spec["k"]) + int(spec["m"])
            slow = next(o for o in acting[:nstreams]
                        if o != primary and o >= 0)
            cluster.osds[slow].msgr.inject_internal_delays = delay
            try:
                t0 = time.monotonic()
                res = await io.infer(spec, q, budget=budget)
                elapsed = time.monotonic() - t0
            finally:
                cluster.osds[slow].msgr.inject_internal_delays = 0
            assert elapsed < delay, elapsed
            assert res["est_error"] <= budget
            rel = float(np.linalg.norm(res["scores"] - ex["scores"])
                        / max(np.linalg.norm(ex["scores"]), 1e-12))
            assert rel <= budget
        finally:
            await cluster.stop()

    run(main())


def test_store_model_demands_ec_pool_and_validates():
    """store_model on a replicated pool and infer with a malformed
    spec both surface EINVAL-shaped RadosError, not engine
    tracebacks."""
    async def main():
        from ceph_tpu.rados.client import RadosError

        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "rp", size=3, pg_num=8)
            io = cluster.client.open_ioctx("rp")
            with pytest.raises(RadosError):
                await io.store_model(
                    "m1", "linear",
                    registry.make_model("linear", 8, 8, seed=1))
            with pytest.raises(RadosError):
                await io.infer({"kind": "rnn"}, np.zeros((1, 8)))
        finally:
            await cluster.stop()

    run(main())

"""Product-matrix MSR regenerating codec (ceph_tpu/ec/msr.py).

Repair-identity property suite: every single-erasure pattern x ragged
object sizes x d in {k..k+m-1} rebuilds bit-exact against the
full-decode oracle while helpers ship exactly beta = chunk/alpha
bytes each (the arXiv:1412.3022 product-matrix bound); RS
degeneration for d < 2k-2; stream-layout invariance through
ec_util's whole-stream batched path and ranged chunk slices;
host-fallback parity under CEPH_TPU_INJECT_DEVICE_FAIL; the `repair`
ExecPlan kind; and the daemon-level repair-aware recovery over a
live cluster, including the CEPH_TPU_MSR_REPAIR=0 kill switch
(bit-identical classic fallback, zero repair dispatches).
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from ceph_tpu.ec.interface import ErasureCodeError
from ceph_tpu.ec.registry import create_erasure_code
from ceph_tpu.ops import gf
from ceph_tpu.osd import ec_util

from cluster_helpers import Cluster

# d >= 2k-2 (after shortening) admits the product-matrix MSR
# construction; anything smaller degenerates to classic RS
FRACTIONAL = [(2, 2, 3), (2, 3, 3), (3, 3, 4), (3, 3, 5), (4, 3, 6)]
DEGENERATE = [(4, 3, 4), (4, 3, 5), (6, 3, 8)]

SIZES = [1, 517 * 3 + 13, 16 * 1024 + 5]  # ragged: padding exercised


def _msr(k: int, m: int, d: int):
    return create_erasure_code({
        "plugin": "ec_msr", "k": str(k), "m": str(m), "d": str(d)})


def _chunks(codec, data: bytes):
    n = codec.get_chunk_count()
    enc = codec.encode(range(n), data)
    return {i: bytes(enc[i]) for i in range(n)}


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


# -- profile validation -----------------------------------------------------


def test_profile_validation():
    with pytest.raises(ErasureCodeError):
        _msr(4, 3, 3)        # d < k
    with pytest.raises(ErasureCodeError):
        _msr(4, 3, 7)        # d > n-1
    with pytest.raises(ErasureCodeError):
        create_erasure_code({"plugin": "ec_msr", "k": "4", "m": "3",
                             "d": "6", "w": "16"})  # GF(2^8) only


def test_geometry():
    c = _msr(4, 3, 6)
    assert c.supports_fractional_repair()
    assert c.get_sub_chunk_count() == 3       # alpha = d - k + 1
    assert c.repair_degree() == 6
    # chunk sizes are alpha-aligned by construction
    assert c.get_chunk_size(4 * 1024) % 3 == 0


# -- repair identity property suite ----------------------------------------


@pytest.mark.parametrize("k,m,d", FRACTIONAL)
def test_repair_identity(k, m, d):
    """Every single erasure, every ragged size: repair from d
    fractional helpers == the stored chunk == the full-decode oracle,
    and the helpers collectively ship exactly beta*d bytes."""
    codec = _msr(k, m, d)
    n = k + m
    alpha = codec.get_sub_chunk_count()
    assert alpha == d - k + 1
    rng = np.random.default_rng(1000 * k + 10 * m + d)
    for size in SIZES:
        data = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
        chunks = _chunks(codec, data)
        beta = len(chunks[0]) // alpha
        for lost in range(n):
            avail = [i for i in range(n) if i != lost]
            spec = codec.minimum_to_repair(lost, avail)
            assert len(spec) == d
            frags = {h: codec.repair_project(lost, chunks[h])
                     for h in spec}
            total = sum(len(f) for f in frags.values())
            assert total <= beta * d
            assert total == beta * d  # exactly the MSR bound
            rep = codec.repair(lost, frags)
            # full-decode oracle over k arbitrary survivors
            oracle = codec.decode(
                {lost}, {i: chunks[i] for i in avail[:k]})
            assert rep == bytes(oracle[lost]) == chunks[lost]


@pytest.mark.parametrize("k,m,d", FRACTIONAL[:2])
def test_repair_prefers_ranked_helpers(k, m, d):
    codec = _msr(k, m, d)
    n = k + m
    avail = list(range(1, n))
    prefer = list(reversed(avail))
    spec = codec.minimum_to_repair(0, avail, prefer=prefer)
    assert sorted(spec) == sorted(prefer[:d])


@pytest.mark.parametrize("k,m,d", DEGENERATE)
def test_rs_degenerate_mode(k, m, d):
    """d < 2k-2 has no product-matrix form: the codec degenerates to
    classic RS (alpha=1, no fractional repair) but stays a correct
    (k, m) code."""
    codec = _msr(k, m, d)
    n = k + m
    assert not codec.supports_fractional_repair()
    assert codec.get_sub_chunk_count() == 1
    with pytest.raises(ErasureCodeError) as ei:
        codec.minimum_to_repair(0, list(range(1, n)))
    assert ei.value.errno == 95  # EOPNOTSUPP
    rng = np.random.default_rng(99)
    data = rng.integers(0, 256, 4099, dtype=np.uint8).tobytes()
    chunks = _chunks(codec, data)
    for lost in range(n):
        have = {i: v for i, v in chunks.items() if i != lost}
        dec = codec.decode({lost}, have)
        assert bytes(dec[lost]) == chunks[lost]


def test_double_erasure_full_decode():
    """Multi-loss stays on the full-decode path and stays correct —
    the repair API is single-loss by design."""
    codec = _msr(4, 3, 6)
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, 40_000, dtype=np.uint8).tobytes()
    chunks = _chunks(codec, data)
    for lost in [(0, 1), (0, 4), (4, 6)]:
        have = {i: v for i, v in chunks.items() if i not in lost}
        dec = codec.decode(set(lost), have)
        for l in lost:
            assert bytes(dec[l]) == chunks[l]


# -- stream layout invariance ----------------------------------------------


def test_stream_layout_matches_batched_path():
    """The byte-interleaved sub-chunk layout is invariant under
    stripe concatenation: fragments projected from whole multi-stripe
    shard STREAMS (what ec_util's batched encode stores and what the
    OSD helper reads) rebuild the stored stream bit-exact, and any
    chunk-aligned slice of a shard stream decodes standalone (ranged
    degraded reads)."""
    codec = _msr(4, 3, 6)
    k, n = 4, 7
    unit = codec.get_chunk_size(k * 4096)
    sinfo = ec_util.StripeInfo(k, k * unit)
    chunk = sinfo.get_chunk_size()
    nst = 4
    rng = np.random.default_rng(7)
    obj = rng.integers(0, 256, nst * sinfo.get_stripe_width(),
                       dtype=np.uint8).tobytes()
    shards = ec_util.encode(sinfo, codec, obj, range(n))
    alpha = codec.get_sub_chunk_count()
    for lost in range(n):
        helpers = codec.minimum_to_repair(
            lost, [i for i in range(n) if i != lost])
        frags = {h: codec.repair_project(lost, bytes(shards[h]))
                 for h in helpers}
        for f in frags.values():
            assert len(f) == nst * chunk // alpha
        assert codec.repair(lost, frags) == bytes(shards[lost])
    # ranged slice: stripes [1, 3) of each stream decode on their own
    sub = {i: bytes(shards[i][chunk:3 * chunk]) for i in range(n)}
    for lost in range(n):
        have = {i: v for i, v in sub.items() if i != lost}
        dec = codec.decode({lost}, have)
        assert bytes(dec[lost]) == sub[lost]


# -- device-failure parity --------------------------------------------------


def test_repair_host_fallback_parity(monkeypatch):
    """CEPH_TPU_INJECT_DEVICE_FAIL=1.0 forces every device dispatch
    to fail: repair degrades to the numpy host tier bit-exactly."""
    from ceph_tpu.common import circuit

    codec = _msr(4, 3, 6)
    n = 7
    rng = np.random.default_rng(17)
    data = rng.integers(0, 256, 50_000, dtype=np.uint8).tobytes()
    chunks = _chunks(codec, data)
    want = {}
    for lost in range(n):
        frags = {h: codec.repair_project(lost, chunks[h])
                 for h in codec.minimum_to_repair(
                     lost, [i for i in range(n) if i != lost])}
        want[lost] = codec.repair(lost, frags)
        assert want[lost] == chunks[lost]
    monkeypatch.setenv("CEPH_TPU_INJECT_DEVICE_FAIL", "1.0")
    circuit.reset_all()
    try:
        for lost in range(n):
            frags = {h: codec.repair_project(lost, chunks[h])
                     for h in codec.minimum_to_repair(
                         lost, [i for i in range(n) if i != lost])}
            assert codec.repair(lost, frags) == want[lost]
    finally:
        monkeypatch.delenv("CEPH_TPU_INJECT_DEVICE_FAIL")
        circuit.reset_all()


def test_repair_plan_kind():
    """The repair matmul rides the ExecPlan cache as its own `repair`
    (or compiled xor_sched) kind, bit-exact vs the host oracle."""
    jax = pytest.importorskip("jax")  # noqa: F841
    from ceph_tpu.ec import plan

    rng = np.random.default_rng(3)
    mat = rng.integers(1, 256, (3, 6), dtype=np.uint8)
    data = rng.integers(0, 256, (2, 6, 4096), dtype=np.uint8)
    out = plan.repair(mat, data)
    if out is None:
        pytest.skip("no jax backend for plan dispatch")
    ref = np.stack([gf.gf_matmul_ref(mat, data[i]) for i in range(2)])
    assert np.array_equal(out, ref)
    labels = [lbl for lbl in plan.stats()["per_plan"]
              if "repair" in lbl or "xor_sched" in lbl]
    assert labels


# -- live-cluster repair-aware recovery ------------------------------------

MSR_PROFILE = {"plugin": "ec_msr", "k": "2", "m": "2", "d": "3",
               "crush-failure-domain": "osd"}


async def _thrash_msr_pool(cluster: Cluster):
    """Shared scenario: write through an MSR pool, lose one OSD, mark
    it out so CRUSH remaps, wait for recovery to converge, and verify
    every object bit-exact.  Returns the payload map."""
    await cluster.client.create_ec_pool("msrpool", MSR_PROFILE,
                                        pg_num=4)
    ioctx = cluster.client.open_ioctx("msrpool")
    payloads = {f"o{i}": np.random.default_rng(300 + i).integers(
        0, 256, 30_000 + 17 * i, dtype=np.uint8).tobytes()
        for i in range(6)}
    for name, data in payloads.items():
        await ioctx.write_full(name, data)
    await cluster.kill_osd(0)
    await cluster.wait_for_osd_down(0)
    await cluster.client.mon_command({"prefix": "osd out", "osd": 0})
    await cluster.wait_for_clean(60)
    for name, data in payloads.items():
        assert await ioctx.read(name) == data
    return payloads


def test_cluster_repair_aware_recovery():
    """Losing one OSD of an MSR pool recovers through beta-fragment
    repair: repair_objects counts rebuilt chunks, and the payload
    bytes read per repaired byte stay under the d/alpha bound (1.5x
    here) — strictly below the classic k-read's 2x."""
    async def main():
        cluster = Cluster(num_osds=5)
        await cluster.start()
        try:
            await _thrash_msr_pool(cluster)
            repaired = sum(o.perf["repair_objects"]
                           for o in cluster.osds.values())
            fallbacks = sum(o.perf["repair_fallbacks"]
                            for o in cluster.osds.values())
            frags = sum(o.perf["repair_fragments"]
                        for o in cluster.osds.values())
            assert repaired > 0, "no object took the repair path"
            assert frags >= 3 * repaired  # d fragments per rebuild
            # bandwidth accounting on the primaries that repaired:
            # fragment bytes read <= (d/alpha + slack) * bytes rebuilt
            for osd in cluster.osds.values():
                if osd.perf["repair_objects"] and not fallbacks:
                    read = osd.perf["recovery_bytes_read"]
                    made = osd.perf["recovery_bytes_repaired"]
                    assert read <= 1.6 * made, (read, made)
        finally:
            await cluster.stop()

    run(main())


def test_cluster_repair_kill_switch(monkeypatch):
    """CEPH_TPU_MSR_REPAIR=0 reverts recovery to classic k-read
    reconstruction — zero repair dispatches, bit-identical data."""
    monkeypatch.setenv("CEPH_TPU_MSR_REPAIR", "0")

    async def main():
        cluster = Cluster(num_osds=5)
        await cluster.start()
        try:
            await _thrash_msr_pool(cluster)
            assert sum(o.perf["repair_objects"]
                       for o in cluster.osds.values()) == 0
            assert sum(o.perf["repair_fragments"]
                       for o in cluster.osds.values()) == 0
        finally:
            await cluster.stop()

    run(main())

"""CONNECTIVITY election strategy: a flapping low-rank mon must stop
winning elections.

Mirrors /root/reference/src/mon/ElectionLogic.cc (CONNECTIVITY) +
ConnectionTracker.cc: mons score peer reachability from liveness
probes, candidates carry their aggregate score, and voters defer to the
best-connected candidate with rank only breaking near-ties.
"""

import asyncio

from ceph_tpu.mon import paxos as paxos_mod
from ceph_tpu.mon.paxos import ConnectionTracker, Elector
from ceph_tpu.msg.messages import MMonElection

from cluster_helpers import Cluster

CONN_QUORUM = {
    "mon_lease": 0.8,
    "mon_election_timeout": 1.0,
    "mon_accept_timeout": 1.5,
    "mon_election_default_strategy": paxos_mod.STRATEGY_CONNECTIVITY,
    "mon_elector_ping_interval": 0.15,
    "mon_elector_score_halflife": 1.0,
}


# -- unit: tracker + vote rule ----------------------------------------------

def test_tracker_decay_and_scores():
    t = ConnectionTracker(half_life=1.0)
    assert t.score(1) == 1.0          # unseen peers start healthy
    # decay is TIME-based (dt=0 first touch is a no-op): one half-life
    # of sustained failure halves the score
    t.report(1, False, now=0.0)
    t.report(1, False, now=1.0)
    t.report(1, False, now=2.0)
    assert abs(t.score(1) - 0.25) < 1e-9
    # recovery climbs back at the same half-life
    t.report(1, True, now=3.0)
    t.report(1, True, now=4.0)
    assert t.score(1) > 0.5
    # aggregate: mean over the OTHER ranks
    t.report(2, False, now=4.0)
    lo, hi = sorted([t.score(1), t.score(2)])
    assert abs(t.my_score(3, 0) - (lo + hi) / 2) < 1e-9


def _elector(rank, n, strategy, config=None):
    async def _noop(*a):
        pass
    cfg = {"mon_election_default_strategy": strategy}
    cfg.update(config or {})
    return Elector(rank, n, _noop, _noop, _noop, cfg)


def test_defer_rule_classic_is_rank_only():
    e = _elector(1, 3, paxos_mod.STRATEGY_CLASSIC)
    e.tracker.report(0, False, now=0.0)   # even a dead-looking mon.0
    assert e._should_defer(MMonElection(1, 1, 0, score=0.0))
    assert not e._should_defer(MMonElection(1, 1, 2, score=1.0))


def test_defer_rule_connectivity():
    e = _elector(1, 3, paxos_mod.STRATEGY_CONNECTIVITY)
    # all healthy: near-tie falls back to rank priority
    assert e._should_defer(MMonElection(1, 1, 0, score=1.0))
    assert not e._should_defer(MMonElection(1, 1, 2, score=1.0))
    # mon.0 looks lossy from here AND self-reports weak: refuse it
    for now in (0.0, 1.0, 2.0):
        e.tracker.report(0, False, now=now)
    assert not e._should_defer(MMonElection(1, 1, 0, score=0.2))
    # a better-connected HIGHER rank beats me once I am the lossy one
    for now in (0.0, 1.0, 2.0):
        e.tracker.report(2, False, now=now)  # my links are bad
    assert e._should_defer(MMonElection(1, 1, 2, score=1.0))


def test_victory_preempt_gated_by_score():
    e = _elector(0, 3, paxos_mod.STRATEGY_CONNECTIVITY)
    win = MMonElection(3, 4, 1, quorum=[1, 2])
    # healthy everywhere: scores tie, no preempt thrash
    assert not e._should_preempt(win)
    # I can reach everyone but the tracker says mon.1 flaps: take over
    for now in (0.0, 1.0, 2.0):
        e.tracker.report(1, False, now=now)
    e.tracker.report(2, True, now=2.0)
    assert e._should_preempt(win)
    # classic always preempts on rank
    assert _elector(0, 3,
                    paxos_mod.STRATEGY_CLASSIC)._should_preempt(win)


def test_dethrone_requires_absolute_evidence():
    """The dethrone trigger must fire for a healthy peon watching the
    leader's link collapse — and must NOT fire from the lossy mon
    itself, whose view of EVERYONE (leader included) is degraded."""
    async def run():
        fired = []

        async def _noop():
            pass

        e = _elector(1, 3, paxos_mod.STRATEGY_CONNECTIVITY,
                     {"mon_election_timeout": 0.0,
                      "mon_elector_score_halflife": 1.0})
        e.leader = 0
        e.electing = False
        e.call_election = lambda: fired.append(1) or _noop()
        # healthy view: leader fine -> no trigger
        e._maybe_dethrone(now=100.0)
        assert not fired
        # leader collapsed, my link to mon.2 is solid -> trigger
        for now in (0.0, 1.0, 2.0, 3.0):
            e.tracker.report(0, False, now=now)
        e.tracker.report(2, True, now=3.0)
        e._maybe_dethrone(now=100.0)
        assert fired
        # lossy node: every view degraded, no solid link -> no trigger
        e2 = _elector(0, 3, paxos_mod.STRATEGY_CONNECTIVITY,
                      {"mon_election_timeout": 0.0,
                       "mon_elector_score_halflife": 1.0})
        e2.leader = 1
        e2.electing = False
        e2.call_election = lambda: fired.append(2) or _noop()
        for now in (0.0, 1.0, 2.0, 3.0):
            e2.tracker.report(1, False, now=now)
            e2.tracker.report(2, False, now=now)
        e2._maybe_dethrone(now=100.0)
        assert 2 not in fired, "lossy mon dethroned a healthy leader"
        await asyncio.sleep(0)  # drain the spawned election task

    asyncio.run(run())


# -- integration: lossy mon.0 loses the quorum lead -------------------------

def test_lossy_rank0_stops_leading():
    """3-mon quorum under CONNECTIVITY: healthy cluster elects mon.0
    (rank tie-break), then mon.0's links turn lossy — leadership must
    settle on a healthy mon and mon.0 must not win it back while it
    flaps (the ElectionLogic.cc scenario the strategy exists for)."""
    async def run():
        cluster = Cluster(num_osds=2, osds_per_host=1, num_mons=3,
                          mon_config=dict(CONN_QUORUM))
        await cluster.start()
        try:
            assert cluster.mons[0].is_leader()
            # every ~4th frame on any mon.0 connection kills it —
            # pings still occasionally round-trip (a flap, not a death)
            cluster.mons[0].msgr.inject_socket_failures = 4
            # let probes drag mon.0's score down and the quorum re-form
            await asyncio.sleep(3.0)
            observed = set()
            deadline = asyncio.get_running_loop().time() + 6.0
            while asyncio.get_running_loop().time() < deadline:
                for rank in (1, 2):
                    el = cluster.mons[rank].elector
                    if not el.electing and el.leader is not None:
                        observed.add(el.leader)
                await asyncio.sleep(0.1)
            assert observed, "healthy mons never reached a stable view"
            assert 0 not in observed, (
                f"flapping mon.0 still won leadership: {observed}")
            # the healthy pair holds a working quorum meanwhile (poll:
            # a sampled instant may land mid-election)
            healthy = [cluster.mons[r] for r in (1, 2)]
            for _ in range(40):
                if any(m.is_leader() for m in healthy):
                    break
                await asyncio.sleep(0.1)
            assert any(m.is_leader() for m in healthy)
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(run(), 90))

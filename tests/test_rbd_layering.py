"""RBD layering (clone/COW/flatten) + object map.

Mirrors the reference's clone semantics (src/librbd/ parent I/O,
cls_rbd children/protection bookkeeping) and object-map behavior
(src/librbd/object_map/): protected-snap gating, parent fallthrough,
copy-on-first-write, overlap clamping on shrink, flatten severing the
link, and the bitmap accelerating reads/removes — checked against a
flat-image oracle under a randomized op stream.
"""

import asyncio

import numpy as np
import pytest

from cluster_helpers import Cluster

from ceph_tpu.rbd import RBD, OM_EXISTS, _data
from ceph_tpu.rados.client import RadosError


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 180))


async def _cluster():
    cluster = Cluster(num_osds=4, osds_per_host=2)
    await cluster.start()
    await cluster.client.create_replicated_pool("rbd", size=2, pg_num=4)
    return cluster


def test_clone_requires_protected_snap():
    async def main():
        cluster = await _cluster()
        try:
            io = cluster.client.open_ioctx("rbd")
            rbd = RBD()
            await rbd.create(io, "parent", 1 << 20, order=16)
            img = await rbd.open(io, "parent")
            await img.snap_create("s1")
            with pytest.raises(RadosError):
                await rbd.clone(io, "parent", "s1", io, "child")
            await img.snap_protect("s1")
            assert await img.snap_is_protected("s1")
            await rbd.clone(io, "parent", "s1", io, "child")
            # protected snap cannot be removed; unprotect refused
            # while the clone exists
            with pytest.raises(RadosError):
                await img.snap_remove("s1")
            await img.refresh()
            with pytest.raises(RadosError):
                await img.snap_unprotect("s1")
            # parent cannot be removed while a clone depends on it
            with pytest.raises(RadosError):
                await rbd.remove(io, "parent")
        finally:
            await cluster.stop()

    run(main())


def test_clone_cow_and_flatten():
    async def main():
        cluster = await _cluster()
        try:
            io = cluster.client.open_ioctx("rbd")
            rbd = RBD()
            size = 1 << 18          # 4 objects of 64 KiB
            await rbd.create(io, "parent", size, order=16)
            parent = await rbd.open(io, "parent")
            base = bytes(np.random.default_rng(1).integers(
                0, 256, size, dtype=np.uint8))
            await parent.write(0, base)
            await parent.snap_create("gold")
            await parent.snap_protect("gold")
            # parent keeps changing AFTER the snap; the clone must not
            # see it (it reads at the snap)
            await parent.write(0, b"\xEE" * 4096)

            await rbd.clone(io, "parent", "gold", io, "child")
            child = await rbd.open(io, "child")
            assert await child.read(0, size) == base, "fallthrough"

            # partial write -> copyup: the rest of that object must
            # still be the parent's bytes
            await child.write(100, b"X" * 50)
            got = await child.read(0, 1 << 16)
            want = bytearray(base[:1 << 16])
            want[100:150] = b"X" * 50
            assert got == bytes(want), "copyup preserved parent bytes"
            # parent unchanged at the snap
            psnap = await rbd.open(io, "parent")
            psnap.snap_set("gold")
            assert await psnap.read(0, size) == base

            # discard inside the overlap zeroes (must NOT re-expose
            # the parent)
            await child.discard(0, 1 << 16)
            assert await child.read(0, 1 << 16) == bytes(1 << 16)

            # flatten: content identical before/after, link severed,
            # unprotect+remove of the parent snap now succeeds
            before = await child.read(0, size)
            await child.flatten()
            assert not child._has_parent()
            assert await child.read(0, size) == before
            await parent.refresh()
            await parent.snap_unprotect("gold")
            await parent.snap_remove("gold")
        finally:
            await cluster.stop()

    run(main())


def test_clone_shrink_clamps_overlap():
    async def main():
        cluster = await _cluster()
        try:
            io = cluster.client.open_ioctx("rbd")
            rbd = RBD()
            size = 1 << 18
            await rbd.create(io, "p2", size, order=16)
            parent = await rbd.open(io, "p2")
            base = bytes(np.random.default_rng(2).integers(
                0, 256, size, dtype=np.uint8))
            await parent.write(0, base)
            await parent.snap_create("s")
            await parent.snap_protect("s")
            await rbd.clone(io, "p2", "s", io, "c2")
            child = await rbd.open(io, "c2")
            await child.resize(1 << 16)       # shrink to one object
            await child.resize(size)          # grow back
            # the dropped range must now read ZEROS, not parent bytes
            # (overlap was clamped by the shrink)
            assert await child.read(1 << 16, 1 << 16) == bytes(1 << 16)
            assert await child.read(0, 1 << 16) == base[:1 << 16]
        finally:
            await cluster.stop()

    run(main())


def test_random_ops_vs_flat_oracle():
    """Randomized write/discard/read stream applied to a clone AND to
    a flat oracle image initialized with the parent content — contents
    must stay identical throughout (the ceph_test_rados model-based
    discipline, src/test/osd/RadosModel.h, for layering)."""
    async def main():
        cluster = await _cluster()
        try:
            io = cluster.client.open_ioctx("rbd")
            rbd = RBD()
            size = 3 << 16
            rng = np.random.default_rng(7)
            base = bytes(rng.integers(0, 256, size, dtype=np.uint8))
            await rbd.create(io, "pr", size, order=16)
            parent = await rbd.open(io, "pr")
            await parent.write(0, base)
            await parent.snap_create("s")
            await parent.snap_protect("s")
            await rbd.clone(io, "pr", "s", io, "cl")
            clone = await rbd.open(io, "cl")
            await rbd.create(io, "flat", size, order=16)
            flat = await rbd.open(io, "flat")
            await flat.write(0, base)
            for _ in range(40):
                op = rng.integers(0, 3)
                off = int(rng.integers(0, size - 1))
                ln = int(rng.integers(1, min(size - off, 100_000)))
                if op == 0:
                    buf = bytes(rng.integers(0, 256, ln,
                                             dtype=np.uint8))
                    await clone.write(off, buf)
                    await flat.write(off, buf)
                elif op == 1:
                    await clone.discard(off, ln)
                    await flat.discard(off, ln)
                else:
                    assert await clone.read(off, ln) == \
                        await flat.read(off, ln), (op, off, ln)
            assert await clone.read(0, size) == \
                await flat.read(0, size)
        finally:
            await cluster.stop()

    run(main())


def test_interrupted_copyup_retries_converge():
    """Crash-point shape: the first copyup write fails mid-flight; the
    retried write converges to the same content (copyup idempotence,
    the CopyupRequest restart discipline)."""
    async def main():
        cluster = await _cluster()
        try:
            io = cluster.client.open_ioctx("rbd")
            rbd = RBD()
            size = 1 << 17
            base = bytes(np.random.default_rng(3).integers(
                0, 256, size, dtype=np.uint8))
            await rbd.create(io, "p3", size, order=16)
            parent = await rbd.open(io, "p3")
            await parent.write(0, base)
            await parent.snap_create("s")
            await parent.snap_protect("s")
            await rbd.clone(io, "p3", "s", io, "c3")
            child = await rbd.open(io, "c3")

            orig = child.data_ioctx.write_full
            fails = {"n": 1}

            async def flaky(oid, data):
                if fails["n"]:
                    fails["n"] -= 1
                    raise ConnectionError("injected copyup failure")
                return await orig(oid, data)

            child.data_ioctx.write_full = flaky
            with pytest.raises(ConnectionError):
                await child.write(10, b"Y" * 10)
            # retry converges
            await child.write(10, b"Y" * 10)
            got = await child.read(0, 1 << 16)
            want = bytearray(base[:1 << 16])
            want[10:20] = b"Y" * 10
            assert got == bytes(want)
        finally:
            await cluster.stop()

    run(main())


def test_object_map_tracks_and_accelerates():
    async def main():
        cluster = await _cluster()
        try:
            io = cluster.client.open_ioctx("rbd")
            rbd = RBD()
            size = 4 << 16
            await rbd.create(io, "om", size, order=16,
                             exclusive_lock=True, object_map=True)
            img = await rbd.open(io, "om")
            await img.write(0, b"A" * 100)             # object 0
            await img.write(2 << 16, b"B" * 100)       # object 2
            assert await img.diff_objects() == [0, 2]
            await img.discard(2 << 16, 1 << 16)        # drop object 2
            assert await img.diff_objects() == [0]
            # reads of mapped-nonexistent objects skip the data pool:
            # break the data ioctx read to prove no round trip happens
            async def boom(*a, **k):
                raise AssertionError("data read despite NONEXISTENT map")
            orig = img.data_ioctx.read
            img.data_ioctx.read = boom
            assert await img.read(3 << 16, 100) == bytes(100)
            img.data_ioctx.read = orig
            # rebuild agrees with reality
            await img.rebuild_object_map()
            assert await img.diff_objects() == [0]
            # remove() deletes only mapped objects (and the map object)
            await img.close()
            await rbd.remove(io, "om")
            # object-map without exclusive-lock is refused
            with pytest.raises(RadosError):
                await rbd.create(io, "bad", size, object_map=True)
        finally:
            await cluster.stop()

    run(main())

"""Distributed tracing spans (blkin/zipkin role) + critical-path
attribution: one client op's trace context propagates client ->
primary -> replica sub-ops, each daemon's collected spans link into a
tree by parent span id, the critical-path reducer attributes every
instant of a finished op to exactly one stage, and the tail keeps its
full explanation (exemplar retention) even at head-sample rate 0.

Mirrors the reference's blkin tracepoint coverage
(/root/reference/src/blkin/, osd_blkin_trace_all): the point is the
CAUSAL CHAIN across daemons, not any single daemon's log."""

import asyncio
import os
import time

import numpy as np
import pytest

from cluster_helpers import Cluster

from ceph_tpu.common import tracing
from ceph_tpu.common.tracing import (
    NULL_SPAN, Tracer, critical_path, critical_path_spans,
    current_span,
)

EC22 = {"plugin": "ec_jax", "technique": "reed_sol_van",
        "k": "2", "m": "2", "crush-failure-domain": "osd"}


def _span(sid, parent, name, t0, dur, **attrs):
    return {"span_id": sid, "parent_id": parent, "name": name,
            "t0_us": t0, "duration_us": dur, "attrs": attrs}


def test_tracer_unit():
    t = Tracer("svc", max_spans=4)
    root = t.start("root")
    assert root.trace_id and root.span_id and root.parent_id == 0
    child = t.start("child", context=root.context)
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    child.event("did a thing")
    t.finish(child)
    t.finish(root)
    spans = t.dump()
    assert len(spans) == 2
    assert spans[0]["name"] == "child"
    assert spans[0]["parent_id"] == spans[1]["span_id"]
    assert spans[0]["events"][0]["what"] == "did a thing"
    assert spans[0]["duration_us"] >= 0
    # ring bound: old spans fall off
    for i in range(10):
        t.finish(t.start(f"s{i}"))
    assert len(t.dump()) == 4
    # trace_id filter
    only = t.dump(trace_id=root.trace_id)
    assert all(s["trace_id"] == f"{root.trace_id:016x}" for s in only)


def test_contextvar_isolation():
    """Two concurrent tasks each see their OWN current span."""
    async def run():
        t = Tracer("svc")
        seen = {}

        async def task(name):
            span = t.start(name)
            current_span.set(span)
            await asyncio.sleep(0.01)
            seen[name] = current_span.get().name

        await asyncio.gather(task("a"), task("b"))
        assert seen == {"a": "a", "b": "b"}

    asyncio.run(run())


def test_trace_propagates_client_to_replicas():
    async def run():
        cluster = Cluster(num_osds=3, osds_per_host=1)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "tp", size=3, pg_num=4)
            io = cluster.client.open_ioctx("tp")
            cluster.client.trace_all = True
            await io.write_full("traced-obj", b"x" * 8192)
            cluster.client.trace_all = False

            client_spans = cluster.client.tracer.dump()
            assert client_spans, "client recorded no spans"
            cspan = next(s for s in client_spans
                         if "traced-obj" in s["name"])
            trace_id = cspan["trace_id"]
            assert any("sent to osd" in e["what"]
                       for e in cspan["events"])

            # gather every OSD's spans for this trace over the tell
            # surface (the dump_traces asok command)
            by_osd = {}
            for osd in range(3):
                rc, doc = await cluster.client.osd_command(
                    osd, {"prefix": "dump_traces",
                          "trace_id": trace_id})
                assert rc == 0
                by_osd[osd] = doc["spans"]
            all_spans = [s for spans in by_osd.values()
                         for s in spans]
            assert all(s["trace_id"] == trace_id for s in all_spans)

            # primary op span: parented by the CLIENT span
            op_spans = [s for s in all_spans
                        if s["name"].startswith("osd_op")]
            assert len(op_spans) == 1, op_spans
            assert op_spans[0]["parent_id"] == cspan["span_id"]

            # the primary's per-peer subwrite stage spans (the ack
            # wait) parent to the op span...
            sub_local = [s for s in all_spans
                         if s["name"].startswith("subwrite")]
            assert len(sub_local) >= 2, sub_local
            for s in sub_local:
                assert s["parent_id"] == op_spans[0]["span_id"]
            # ...and replica sub-writes parent to the PER-PEER span
            # (the v3 tail field carried the sub-write span's context),
            # on size=3 at least the two REMOTE replicas contributed
            local_ids = {s["span_id"] for s in sub_local}
            sub_spans = [s for s in all_spans
                         if s["name"].startswith("sub_write")
                         and "_rbgen_" not in s["name"]]
            assert len(sub_spans) >= 2, sub_spans
            for s in sub_spans:
                assert s["parent_id"] in local_ids, s
            # the awaited rollback-trim removes attribute to their own
            # stage span, not to osd_op self-time
            trim = [s for s in all_spans if s["name"] == "rollback_trim"]
            rb_remote = [s for s in all_spans
                         if s["name"].startswith("sub_write")
                         and "_rbgen_" in s["name"]]
            if rb_remote:
                trim_ids = {s["span_id"] for s in trim}
                for s in rb_remote:
                    assert s["parent_id"] in trim_ids, s
            # spans came from more than one daemon
            contributing = {osd for osd, spans in by_osd.items()
                            if spans}
            assert len(contributing) >= 2, by_osd
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(run(), 120))


# -- critical-path reducer -------------------------------------------------


def test_critical_path_hedged_children():
    """Parallel hedged sub-reads: the LONGEST child owns the wait, the
    cancelled straggler is off the path even though it spans the whole
    op, and the gaps are the parent's self-time."""
    tree = [
        _span("r", "", "osd_op obj", 0, 10_000),
        _span("q", "r", "queue.client", 0, 2_000),
        # three parallel sub-reads from t=2ms: 3ms, 7ms, and a
        # straggler cancelled at 9.5ms (nothing waited for it)
        _span("a", "r", "subread osd.1", 2_000, 3_000),
        _span("b", "r", "subread osd.2", 2_000, 7_000),
        _span("c", "r", "subread osd.3", 2_000, 7_500,
              cancelled=True),
    ]
    cp = critical_path(tree)
    assert cp["total_us"] == 10_000
    # b (ends 9ms) is the latest-ending live child; a is fully
    # shadowed by b; the root keeps [9, 10]ms = 1ms self
    assert cp["stages"] == {"queue.client": 2_000, "subread": 7_000,
                            "osd_op": 1_000}
    names = [e["name"] for e in cp["path"]]
    assert "subread osd.2" in names
    assert "subread osd.3" not in names  # cancelled: off the path
    assert "subread osd.1" not in names  # shadowed by the longer read
    # path is root-first
    assert names[0] == "osd_op obj"


def test_critical_path_nested_and_sequential():
    """Sequential children hand the cursor back through the parent;
    a grandchild attributes inside its parent's interval."""
    tree = [
        _span("r", "", "osd_op w", 0, 12_000),
        _span("e", "r", "encode_wait x", 1_000, 4_000),
        _span("s", "r", "subwrite osd.1", 6_000, 5_000),
        _span("k", "s", "kv_commit", 7_000, 2_000),
    ]
    cp = critical_path(tree)
    assert cp["stages"]["encode_wait"] == 4_000
    assert cp["stages"]["kv_commit"] == 2_000
    assert cp["stages"]["subwrite"] == 3_000       # 5ms minus the kv
    assert cp["stages"]["osd_op"] == 3_000         # the gaps
    assert sum(cp["stages"].values()) == cp["total_us"]


def test_critical_path_spans_fast_lane_matches_dicts():
    """The allocation-light Span-tree reduction and the dict-based
    reducer agree on the same tree."""
    tr = Tracer("svc")
    root = tr.start("osd_op o")
    q = root.child("queue.client")
    time.sleep(0.002)
    q.finish()
    a = root.child("subread osd.1")
    b = root.child("subread osd.2")
    time.sleep(0.002)
    a.finish()
    b.set_attr("cancelled", True)
    b.finish()
    time.sleep(0.001)
    tr.finish(root)
    fast = critical_path_spans(root)
    slow = critical_path(root.tree_dicts())
    assert fast["stages"] == slow["stages"]
    assert fast["total_us"] == slow["total_us"]
    assert fast["path"] == []          # fast lane skips the rendering
    assert slow["path"]


def test_span_clocks_survive_wall_clock_step(monkeypatch):
    """Satellite regression: durations come from time.monotonic();
    an NTP step mid-span (time.time jumping backward) must not
    corrupt them — the wall clock is a display anchor only."""
    tr = Tracer("svc")
    span = tr.start("osd_op o")
    span.event("before step")
    # simulate a 1-hour backward NTP step
    real_time = time.time
    monkeypatch.setattr(time, "time", lambda: real_time() - 3600.0)
    time.sleep(0.005)
    tr.finish(span)
    d = span.to_dict()
    assert d["duration_us"] >= 5_000           # monotonic, unpoisoned
    assert d["duration_us"] < 60_000_000
    assert d["events"][0]["offset_us"] >= 0


def test_child_span_helpers_and_null_discipline():
    """child_span/child_span_sync attach to the current span, finish
    on every path (incl. cancellation, annotated), and no-op cleanly
    when untraced."""
    async def main():
        tr = Tracer("svc")
        root = tr.start("osd_op o")
        tok = current_span.set(root)
        try:
            async with tracing.child_span("stagea") as sp:
                assert current_span.get() is sp
            with tracing.child_span_sync("stageb", k=1) as sp2:
                assert sp2.attrs["k"] == 1

            async def cancelled_stage():
                async with tracing.child_span("stagec"):
                    await asyncio.sleep(30)

            t = asyncio.get_running_loop().create_task(
                cancelled_stage())
            await asyncio.sleep(0.01)
            t.cancel()
            with pytest.raises(asyncio.CancelledError):
                await t
        finally:
            current_span.reset(tok)
        tr.finish(root)
        names = {s.name: s for s in root._tree}
        assert {"stagea", "stageb", "stagec"} <= set(names)
        assert names["stagec"].attrs.get("cancelled") is True
        # untraced context: helpers yield the NULL_SPAN, nothing leaks
        assert current_span.get() is None
        async with tracing.child_span("ghost") as ghost:
            assert ghost is NULL_SPAN
        assert tracing.start_child("ghost2") is NULL_SPAN
        tracing.event("into the void")  # must not raise

    asyncio.run(main())


def test_kill_switch_and_sampling(monkeypatch):
    """CEPH_TPU_TRACE=0 makes start() return the NULL_SPAN; sample
    rate 0 still BUILDS spans (stage histograms + tail exemplars need
    them) but retains nothing in the ring."""
    monkeypatch.setenv("CEPH_TPU_TRACE", "0")
    tr = Tracer("svc")
    assert tr.start("osd_op o") is NULL_SPAN
    monkeypatch.delenv("CEPH_TPU_TRACE", raising=False)
    tr2 = Tracer("svc", sample_rate=0.0)
    sp = tr2.start("osd_op o")
    assert sp is not NULL_SPAN and not sp.sampled
    tr2.finish(sp)
    assert tr2.dump() == []            # unsampled: not retained
    tr2.record_stages(critical_path_spans(sp)["stages"])
    assert tr2.counters["stage_samples"] >= 1
    # a wire context inherits the sender's (positive) decision
    sp3 = tr2.start("osd_op o", context=(123, 456))
    assert sp3.sampled
    tr2.finish(sp3)
    assert tr2.dump(trace_id=123)


# -- encode-service span links ---------------------------------------------


def test_encode_flush_span_links_batched_ops(monkeypatch):
    """N concurrent traced encodes share one batched flush: the
    dispatch span carries LINKS to the N ops it served, and each op's
    own tree gets an encode_wait stage span."""
    monkeypatch.setenv("CEPH_TPU_FUSE_MIN_BYTES", "0")
    from ceph_tpu.ec.registry import ErasureCodePluginRegistry
    from ceph_tpu.osd import ec_util
    from ceph_tpu.osd.encode_service import EncodeService

    codec = ErasureCodePluginRegistry.instance().factory(
        "ec_jax", {"plugin": "ec_jax", "technique": "reed_sol_van",
                   "k": "4", "m": "2"})
    sinfo = ec_util.StripeInfo(4, 4 * 4096)
    rng = np.random.default_rng(7)
    bufs = [rng.integers(0, 256, 32 << 10, dtype=np.uint8).tobytes()
            for _ in range(8)]

    async def main():
        svc = EncodeService()
        tr = Tracer("osd.test")
        svc.tracer = tr
        roots = []

        async def one_op(buf):
            root = tr.start(f"osd_op o{len(roots)}")
            roots.append(root)
            tok = current_span.set(root)
            try:
                return await svc.encode_with_hinfo(
                    sinfo, codec, buf, range(6), logical_len=len(buf))
            finally:
                current_span.reset(tok)
                tr.finish(root)

        outs = await asyncio.gather(*(one_op(b) for b in bufs))
        await svc.stop()
        return outs, roots, tr

    outs, roots, tr = asyncio.run(asyncio.wait_for(main(), 120))
    assert len(outs) == 8
    flushes = [s for s in tr.dump()
               if s["name"].startswith("encode_flush")]
    assert flushes, "no flush spans retained"
    linked = [lk for s in flushes for lk in s.get("links", [])]
    # every op context that was linked is one of our roots
    root_ctxs = {f"{r.trace_id:016x}/{r.span_id:016x}" for r in roots}
    assert linked and set(linked) <= root_ctxs
    # batching actually shared dispatches: fewer flushes than ops,
    # with at least one flush serving multiple ops
    assert len(flushes) < 8
    assert max(s["attrs"]["requests"] for s in flushes) >= 2
    # and each op's own tree saw the encode_wait stage
    for r in roots:
        assert any(s.name == "encode_wait" for s in r._tree)


# -- cross-wire propagation (hedged EC sub-reads) --------------------------


def test_trace_propagates_through_hedged_ec_subreads():
    """An EC read's trace crosses the wire on MOSDSubRead v4: the
    primary's per-peer subread spans parent the REPLICA-side sub_read
    spans, all under the client's trace id."""
    async def main():
        cluster = Cluster(num_osds=5, osds_per_host=5)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ec", profile=EC22, pg_num=4)
            io = cluster.client.open_ioctx("ec")
            payload = b"x" * 20_000
            await io.write_full("traced", payload)
            cluster.client.trace_all = True
            got = await io.read("traced")
            cluster.client.trace_all = False
            assert bytes(got) == payload

            cspan = next(
                s for s in cluster.client.tracer.dump()
                if "traced" in s["name"] and "read" in s["name"])
            trace_id = cspan["trace_id"]
            all_spans = []
            for osd in cluster.osds:
                rc, doc = await cluster.client.osd_command(
                    osd, {"prefix": "dump_traces",
                          "trace_id": trace_id})
                assert rc == 0
                all_spans.extend(doc["spans"])
            op_spans = [s for s in all_spans
                        if s["name"].startswith("osd_op")]
            assert len(op_spans) == 1
            assert op_spans[0]["parent_id"] == cspan["span_id"]
            # the primary's per-peer subread stage spans live in the
            # same tree, under the op span
            sub_local = [s for s in all_spans
                         if s["name"].startswith("subread")]
            assert len(sub_local) >= 2, sub_local
            for s in sub_local:
                assert s["parent_id"] == op_spans[0]["span_id"]
            # replica-side sub_read spans parent to the PRIMARY'S
            # per-peer spans (the v4 tail field carried the context
            # of the sub-read span, not of the whole op)
            sub_remote = [s for s in all_spans
                          if s["name"].startswith("sub_read")]
            assert sub_remote, "no replica-side sub_read spans"
            local_ids = {s["span_id"] for s in sub_local}
            for s in sub_remote:
                assert s["trace_id"] == trace_id
                assert s["parent_id"] in local_ids
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(main(), 120))


# -- tail-exemplar retention ------------------------------------------------


def test_tail_exemplar_attributes_straggler_subread():
    """THE acceptance scenario: a slow EC read under injected slow
    peers keeps its FULL span tree (head sampling 0), and the
    critical-path breakdown pins the delay on the sub-read stage —
    not on queue/admission/encode — with the hedge visible.  EVERY
    non-primary acting member is slow, so the op genuinely waits for
    a straggling sub-read (hedging fires spares but every spare is
    slow too — the completed straggler's span owns the delay; the
    rest are cancelled and annotated)."""
    async def main():
        cluster = Cluster(
            num_osds=5, osds_per_host=5,
            osd_config={"osd_trace_sample_rate": 0.0,
                        "osd_op_complaint_time": 0.05,
                        "osd_tier_enable": False})
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ec", profile=EC22, pg_num=4)
            io = cluster.client.open_ioctx("ec")
            payload = b"y" * 30_000
            oid = "slowpoke"
            await io.write_full(oid, payload)
            pg = io.object_pg(oid)
            acting, primary = \
                cluster.mon.osdmap.pg_to_acting_osds(pg)
            slow_peers = [o for o in acting if o != primary]
            # client trace so we know the trace id (retention itself
            # is decided by the PRIMARY's tail policy, not sampling)
            cluster.client.trace_all = True
            # STAGGERED delays: identical delays can complete in one
            # event-loop wave, leaving no straggler in flight to
            # cancel — one peer must win, the rest must be cut loose
            for i, o in enumerate(slow_peers):
                cluster.osds[o].msgr.inject_internal_delays = \
                    0.15 + 0.1 * i
            try:
                got = await io.read(oid)
            finally:
                for o in slow_peers:
                    cluster.osds[o].msgr.inject_internal_delays = 0
                cluster.client.trace_all = False
            assert bytes(got) == payload
            cspan = next(s for s in cluster.client.tracer.dump()
                         if oid in s["name"])
            trace_id = cspan["trace_id"]

            # retention runs in the op handler's finally AFTER the
            # reply is sent (the design: the client never waits on the
            # exemplar pipeline), so a fast client can query before
            # the primary's finish hook lands — poll briefly
            for _ in range(50):
                rc, doc = await cluster.client.osd_command(
                    primary, {"prefix": "dump_op_trace",
                              "trace_id": trace_id})
                if rc == 0 and "error" not in doc:
                    break
                await asyncio.sleep(0.01)
            assert rc == 0, doc
            assert "error" not in doc, doc
            cp = doc["critical_path"]
            stages = cp["stages"]
            # the delay belongs to the sub-read fan-out, not to the
            # queue/admission/encode stages
            sub_us = stages.get("subread", 0)
            assert sub_us >= 0.5 * cp["total_us"], stages
            for quiet in ("queue.client", "admission", "encode_wait"):
                assert stages.get(quiet, 0) < sub_us / 2, stages
            assert doc["rendered"]          # the operator's tree view
            # the hedge fired around the straggler and is visible on
            # the op span's events
            op_span = next(s for s in doc["spans"]
                           if s["name"].startswith("osd_op"))
            events = " ".join(e["what"] for e in op_span["events"])
            assert "hedge" in events, events
            # a cancelled straggler sub-read is annotated in the tree
            cancelled = [s for s in doc["spans"]
                         if s["name"].startswith("subread")
                         and (s.get("attrs") or {}).get("cancelled")]
            assert cancelled, doc["spans"]

            # the historic ring shows the same per-stage breakdown
            rc, hist = await cluster.client.osd_command(
                primary, {"prefix": "dump_historic_ops"})
            assert rc == 0
            traced_ops = [o for o in hist["ops"] if "stages_us" in o]
            assert any(o.get("trace_id") == trace_id
                       for o in traced_ops)

            # per-stage histograms ride the perf dump
            rc, perf = await cluster.client.osd_command(
                primary, {"prefix": "perf dump"})
            assert rc == 0
            tr = perf["trace"]
            assert tr["enabled"] == 1
            assert tr["stage_samples"] >= 1
            assert "subread" in tr["stage"]
            hist_row = tr["stage"]["subread"]["self_seconds"]
            assert hist_row["count"] >= 1
            assert len(hist_row["bounds"]) == len(hist_row["buckets"])
            assert perf["op_tracker"]["ops_total"] >= 2
            assert perf["op_tracker"]["tail_exemplars"] >= 1
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(main(), 120))


def test_trace_kill_switch_bit_parity(monkeypatch):
    """CEPH_TPU_TRACE=0: identical op results, zero spans collected,
    zero stage histograms — the off path is the off path."""
    monkeypatch.setenv("CEPH_TPU_TRACE", "0")

    async def main():
        cluster = Cluster(num_osds=5, osds_per_host=5)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ec", profile=EC22, pg_num=4)
            io = cluster.client.open_ioctx("ec")
            payload = b"z" * 25_000
            await io.write_full("dark", payload)
            got = await io.read("dark")
            assert bytes(got) == payload
            for osd in cluster.osds.values():
                assert osd.tracer.dump() == []
                assert osd.tracer.stage_hist == {}
                assert osd.tracer.counters["traces"] == 0
            rc, perf = await cluster.client.osd_command(
                0, {"prefix": "perf dump"})
            assert rc == 0 and perf["trace"]["enabled"] == 0
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(main(), 120))

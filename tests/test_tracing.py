"""Distributed tracing spans (blkin/zipkin role): one client op's
trace context propagates client -> primary -> replica sub-writes, and
each daemon's collected spans link into a tree by parent span id.

Mirrors the reference's blkin tracepoint coverage
(/root/reference/src/blkin/, osd_blkin_trace_all): the point is the
CAUSAL CHAIN across daemons, not any single daemon's log."""

import asyncio

from cluster_helpers import Cluster

from ceph_tpu.common.tracing import Tracer, current_span


def test_tracer_unit():
    t = Tracer("svc", max_spans=4)
    root = t.start("root")
    assert root.trace_id and root.span_id and root.parent_id == 0
    child = t.start("child", context=root.context)
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    child.event("did a thing")
    t.finish(child)
    t.finish(root)
    spans = t.dump()
    assert len(spans) == 2
    assert spans[0]["name"] == "child"
    assert spans[0]["parent_id"] == spans[1]["span_id"]
    assert spans[0]["events"][0]["what"] == "did a thing"
    assert spans[0]["duration_us"] >= 0
    # ring bound: old spans fall off
    for i in range(10):
        t.finish(t.start(f"s{i}"))
    assert len(t.dump()) == 4
    # trace_id filter
    only = t.dump(trace_id=root.trace_id)
    assert all(s["trace_id"] == f"{root.trace_id:016x}" for s in only)


def test_contextvar_isolation():
    """Two concurrent tasks each see their OWN current span."""
    async def run():
        t = Tracer("svc")
        seen = {}

        async def task(name):
            span = t.start(name)
            current_span.set(span)
            await asyncio.sleep(0.01)
            seen[name] = current_span.get().name

        await asyncio.gather(task("a"), task("b"))
        assert seen == {"a": "a", "b": "b"}

    asyncio.run(run())


def test_trace_propagates_client_to_replicas():
    async def run():
        cluster = Cluster(num_osds=3, osds_per_host=1)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "tp", size=3, pg_num=4)
            io = cluster.client.open_ioctx("tp")
            cluster.client.trace_all = True
            await io.write_full("traced-obj", b"x" * 8192)
            cluster.client.trace_all = False

            client_spans = cluster.client.tracer.dump()
            assert client_spans, "client recorded no spans"
            cspan = next(s for s in client_spans
                         if "traced-obj" in s["name"])
            trace_id = cspan["trace_id"]
            assert any("sent to osd" in e["what"]
                       for e in cspan["events"])

            # gather every OSD's spans for this trace over the tell
            # surface (the dump_traces asok command)
            by_osd = {}
            for osd in range(3):
                rc, doc = await cluster.client.osd_command(
                    osd, {"prefix": "dump_traces",
                          "trace_id": trace_id})
                assert rc == 0
                by_osd[osd] = doc["spans"]
            all_spans = [s for spans in by_osd.values()
                         for s in spans]
            assert all(s["trace_id"] == trace_id for s in all_spans)

            # primary op span: parented by the CLIENT span
            op_spans = [s for s in all_spans
                        if s["name"].startswith("osd_op")]
            assert len(op_spans) == 1, op_spans
            assert op_spans[0]["parent_id"] == cspan["span_id"]

            # replica sub-writes: parented by the primary's op span,
            # on size=3 there are 3 shard spans (primary shard too if
            # it loops back over the wire) or 2 remote ones — at least
            # the two REMOTE replicas must have contributed
            sub_spans = [s for s in all_spans
                         if s["name"].startswith("sub_write")]
            assert len(sub_spans) >= 2, sub_spans
            for s in sub_spans:
                assert s["parent_id"] == op_spans[0]["span_id"]
            # spans came from more than one daemon
            contributing = {osd for osd, spans in by_osd.items()
                            if spans}
            assert len(contributing) >= 2, by_osd
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(run(), 120))

"""MDS subtree migration (the Migrator/MExportDir role,
/root/reference/src/mds/Migrator.cc): a directory rename that
RE-HOMES its subtree across ranks now migrates the metadata — the
importer re-creates the tree under fresh inos in its own fencing
domain (the reference's export-serialize/import-rejournal shape) —
instead of returning EXDEV.

1. re-homing renames succeed and preserve the whole tree (file data
   objects never move: file inos are unchanged);
2. deep sources/destinations work; the old dir objects are purged;
3. snapshotted subtrees refuse to migrate (EBUSY — snapshots key
   dirs by ino);
4. a coordinator crash after journaling the intent re-drives the
   export on takeover;
5. both ranks keep serving their other subtrees afterwards.
"""

import asyncio

import pytest

from cluster_helpers import Cluster

from ceph_tpu.cephfs import CephFS, CephFSError
from ceph_tpu.mds import MDSDaemon, owner_rank
from ceph_tpu.rados.client import RadosClient


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 180))


FAST = {"lock_interval": 0.3}


async def _stack(cluster, num_ranks=2):
    await cluster.client.create_replicated_pool("fsmeta", size=2,
                                                pg_num=4)
    await cluster.client.create_replicated_pool("fsdata", size=2,
                                                pg_num=4)
    daemons = []
    for r in range(num_ranks):
        mds = MDSDaemon(cluster.mon.addr, "fsmeta", "fsdata",
                        name=f"r{r}", rank=r, num_ranks=num_ranks,
                        **FAST)
        await mds.start()
        daemons.append(mds)
    fs = CephFS(cluster.client, "fsmeta", "fsdata")
    return daemons, fs


def _names_by_rank(num_ranks=2):
    by_rank = {}
    for i in range(200):
        name = f"dir{i}"
        by_rank.setdefault(owner_rank(f"/{name}/x", num_ranks), []) \
            .append(name)
        if all(len(v) >= 2 for v in by_rank.values()) and \
                len(by_rank) == num_ranks:
            break
    return by_rank


def test_rehoming_rename_migrates_subtree():
    async def main():
        cluster = Cluster(num_osds=2)
        await cluster.start()
        daemons = []
        try:
            daemons, fs = await _stack(cluster)
            by_rank = _names_by_rank()
            src, dst = by_rank[0][0], by_rank[1][0]
            await fs.mkdir(f"/{src}")
            await fs.mkdir(f"/{src}/inner")
            await fs.mkdir(f"/{src}/inner/deep")
            await fs.write_file(f"/{src}/top.txt", b"top file")
            await fs.write_file(f"/{src}/inner/mid.txt",
                                b"middle data here")
            await fs.write_file(f"/{src}/inner/deep/leaf.bin",
                                b"\x00\x01" * 512)
            await fs.symlink("top.txt", f"/{src}/lnk")
            old_stat = await fs.stat(f"/{src}/inner/mid.txt")
            # the move that USED to be EXDEV
            await fs.rename(f"/{src}", f"/{dst}")
            assert not await fs.exists(f"/{src}")
            assert sorted(await fs.listdir(f"/{dst}")) == \
                ["inner", "lnk", "top.txt"]
            assert await fs.read_file(f"/{dst}/top.txt") == \
                b"top file"
            assert await fs.read_file(f"/{dst}/inner/mid.txt") == \
                b"middle data here"
            assert await fs.read_file(
                f"/{dst}/inner/deep/leaf.bin") == b"\x00\x01" * 512
            assert await fs.readlink(f"/{dst}/lnk") == "top.txt"
            # file inos unchanged (data objects did not move)
            new_stat = await fs.stat(f"/{dst}/inner/mid.txt")
            assert new_stat["ino"] == old_stat["ino"]
            # writes through the NEW home work
            await fs.write_file(f"/{dst}/after.txt", b"post-move")
            assert await fs.read_file(f"/{dst}/after.txt") == \
                b"post-move"
        finally:
            for d in daemons:
                await d.stop()
            await cluster.stop()
    run(main())


def test_rehoming_deep_paths_and_purge():
    async def main():
        cluster = Cluster(num_osds=2)
        await cluster.start()
        daemons = []
        try:
            daemons, fs = await _stack(cluster)
            by_rank = _names_by_rank()
            a, b = by_rank[0][0], by_rank[1][0]
            await fs.mkdir(f"/{a}")
            await fs.mkdir(f"/{a}/proj")
            await fs.write_file(f"/{a}/proj/f", b"nested move")
            await fs.mkdir(f"/{b}")
            old_root = await fs.stat(f"/{a}/proj")
            # deep src -> deep dst across ranks
            await fs.rename(f"/{a}/proj", f"/{b}/proj")
            assert await fs.read_file(f"/{b}/proj/f") == \
                b"nested move"
            assert await fs.listdir(f"/{a}") == []
            # the OLD dir object was purged from the metadata pool
            meta = cluster.client.open_ioctx("fsmeta")
            from ceph_tpu.mds import dir_obj
            with pytest.raises(Exception):
                omap = await meta.omap_get(dir_obj(old_root["ino"]))
                assert not omap  # tolerated: empty leftover
        finally:
            for d in daemons:
                await d.stop()
            await cluster.stop()
    run(main())


def test_snapshotted_subtree_refuses_migration():
    async def main():
        cluster = Cluster(num_osds=2)
        await cluster.start()
        daemons = []
        try:
            daemons, fs = await _stack(cluster)
            by_rank = _names_by_rank()
            src, dst = by_rank[0][1], by_rank[1][1]
            await fs.mkdir(f"/{src}")
            await fs.write_file(f"/{src}/f", b"snapped")
            await fs.mksnap(f"/{src}", "hold")
            with pytest.raises(CephFSError) as ei:
                await fs.rename(f"/{src}", f"/{dst}")
            assert ei.value.rc == -16, ei.value  # EBUSY
            # dropping the snapshot unblocks the migration
            await fs.rmsnap(f"/{src}", "hold")
            await fs.rename(f"/{src}", f"/{dst}")
            assert await fs.read_file(f"/{dst}/f") == b"snapped"
        finally:
            for d in daemons:
                await d.stop()
            await cluster.stop()
    run(main())


def test_export_intent_redriven_after_coordinator_crash():
    """Crash the coordinator right after the export_intent lands:
    the standby takeover re-drives the whole export."""
    async def main():
        cluster = Cluster(num_osds=2)
        await cluster.start()
        daemons = []
        try:
            daemons, fs = await _stack(cluster)
            by_rank = _names_by_rank()
            src, dst = by_rank[0][0], by_rank[1][0]
            await fs.mkdir(f"/{src}")
            await fs.write_file(f"/{src}/f", b"survives crash")
            # src is top-level: the COORDINATOR is rank 0 (owner of
            # the root dentry).  Crash it right after the NEXT journal
            # append — the export_intent.
            daemons[0]._fail_after_journal = True
            with pytest.raises(CephFSError):
                await fs.rename(f"/{src}", f"/{dst}")
            # standby for rank 0 takes over and re-drives
            standby = MDSDaemon(cluster.mon.addr, "fsmeta", "fsdata",
                                name="r0b", rank=0, num_ranks=2,
                                **FAST)
            await standby.start()
            daemons.append(standby)
            for _ in range(100):
                if await fs.exists(f"/{dst}"):
                    break
                await asyncio.sleep(0.3)
            assert await fs.read_file(f"/{dst}/f") == \
                b"survives crash"
            assert not await fs.exists(f"/{src}")
        finally:
            for d in daemons:
                await d.stop()
            await cluster.stop()
    run(main())


def test_other_subtrees_keep_serving():
    async def main():
        cluster = Cluster(num_osds=2)
        await cluster.start()
        daemons = []
        try:
            daemons, fs = await _stack(cluster)
            by_rank = _names_by_rank()
            src, dst = by_rank[0][0], by_rank[1][0]
            keep0, keep1 = by_rank[0][1], by_rank[1][1]
            for d in (src, keep0, keep1):
                await fs.mkdir(f"/{d}")
            await fs.write_file(f"/{src}/f", b"mover")
            await fs.write_file(f"/{keep0}/f", b"stay0")
            await fs.write_file(f"/{keep1}/f", b"stay1")
            await fs.rename(f"/{src}", f"/{dst}")
            assert await fs.read_file(f"/{dst}/f") == b"mover"
            # bystander subtrees unaffected, still writable
            assert await fs.read_file(f"/{keep0}/f") == b"stay0"
            await fs.write_file(f"/{keep1}/f", b"stay1-v2")
            assert await fs.read_file(f"/{keep1}/f") == b"stay1-v2"
        finally:
            for d in daemons:
                await d.stop()
            await cluster.stop()
    run(main())

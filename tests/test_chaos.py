"""Compound-chaos tier: composed fault orchestration with invariant
monitors (ROADMAP item 6).

Every hazard here is proven in isolation elsewhere (test_hedge,
test_device_breaker, test_crash_consistency, test_thrash, the
per-subsystem kill-switch legs); these tests prove they COMPOSE.  A
seeded Scenario fires stragglers x device faults x kill-switch flips
x power cuts x drains over open-loop multi-tenant traffic, and the
monitors judge: zero client errors, bit-exact readback, acked writes
durable, bounded tails, no leaked slots/ops/probes.  Violations
replay from the seed in the report.

The dmClock leg is the cluster-wide QoS acceptance check: a limit-L
tenant spread over N primaries completes ~L ops/s TOTAL with the
delta/rho piggyback on (CEPH_TPU_DMCLOCK=1) and ~N x L with it off —
the same monitor that passes the ON leg must FLAG the OFF leg.
"""

import asyncio
import json

import pytest

from ceph_tpu.chaos import (ChaosEngine, HazardEvent, Scenario,
                            compose, run_scenario)
from ceph_tpu.chaos.monitors import evaluate_report
from ceph_tpu.chaos.scenario import DEFAULT_KILL_SWITCHES
from ceph_tpu.common import flags
from ceph_tpu.loadgen.runner import run_open_loop
from ceph_tpu.loadgen.targets import RadosTarget
from ceph_tpu.loadgen.workload import TenantSpec

from cluster_helpers import Cluster, tpustore_factory


def _no_violations(report):
    assert report["violations"] == [], (
        f"replay with seed={report['seed']}: {report['violations']}"
        + (f"\nworst op: {report.get('worst_op')}"
           if report.get("worst_op") else ""))


# -- scenario composition (pure) -------------------------------------------

def test_compose_deterministic():
    """Same seed -> bit-identical timeline; different seed -> not."""
    tenants = [TenantSpec("a", arrival_rate=10)]
    kw = dict(duration=40.0, tenants=tenants, osd_ids=[0, 1, 2, 3],
              hazards=("straggler", "device_fail", "kill_switch",
                       "powercut", "drain", "host_down"),
              persistent_osds=[1, 2, 3], protected_osds=[0])
    a = compose(7, **kw)
    b = compose(7, **kw)
    c = compose(8, **kw)
    assert [e.to_dict() for e in a.events] == \
        [e.to_dict() for e in b.events]
    assert [e.to_dict() for e in a.events] != \
        [e.to_dict() for e in c.events]
    assert a.events, "composer produced an empty timeline"
    kinds = {e.hazard for e in a.events}
    assert {"straggler", "device_fail", "kill_switch"} <= kinds
    # protected OSDs are never cut or drained
    for e in a.events:
        if e.hazard in ("powercut", "drain"):
            assert e.params["osd"] != 0


def test_compose_rejects_unknown_hazard():
    with pytest.raises(ValueError):
        compose(1, duration=10.0,
                tenants=[TenantSpec("a", arrival_rate=1)],
                osd_ids=[0], hazards=("meteor",))


def test_evaluate_report_judgments():
    """The monitor catches errors, blown p99s, starved tenants and
    rate-ceiling breaches from a report dict alone."""
    report = {
        "errors": 0, "offered": 100, "elapsed_s": 10.0,
        "per_tenant": {
            "good": {"count": 50, "errors": 0, "p99_ms": 20.0,
                     "completed": 50},
            "tail": {"count": 50, "errors": 0, "p99_ms": 900.0,
                     "completed": 50},
            "hog": {"count": 400, "errors": 0, "p99_ms": 5.0,
                    "completed": 400},
        },
    }
    vio = evaluate_report(report,
                          {"good": 100.0, "tail": 100.0,
                           "ghost": 50.0},
                          {"hog": 25.0})
    kinds = sorted(v.kind for v in vio)
    assert kinds == ["limit-exceeded", "p99-exceeded",
                     "tenant-starved"]
    assert evaluate_report(report, {"good": 100.0}, {}) == []


# -- composed scenarios on a live cluster (fast legs) ----------------------

def _tenants(n=2, rate=40, objects=16, size=4096):
    return [TenantSpec(f"t{i}", arrival_rate=rate, objects=objects,
                       object_size=size) for i in range(n)]


def test_kill_switch_flips_mid_traffic():
    """The cross-mode flip leg: XSCHED/COMPUTE/NATIVE_XSCHED/
    MSR_REPAIR/INFERENCE forced off and restored mid-traffic on a
    live cluster — clients must see bit-exact reads and zero errors,
    and every flip must land in the flags audit trail."""
    async def main():
        before = {f: flags.peek(f) for f in DEFAULT_KILL_SWITCHES}
        c = Cluster(num_osds=4)
        await c.start()
        try:
            sc = compose(seed=31, duration=6.0,
                         tenants=_tenants(), osd_ids=[0, 1, 2, 3],
                         hazards=("kill_switch",),
                         p99_bounds={"t0": 4000.0, "t1": 4000.0},
                         objects=16, object_size=4096)
            assert len(sc.events) >= 2
            rep = await run_scenario(c, sc)
            _no_violations(rep)
            assert rep["loadgen"]["errors"] == 0
            assert rep["reads_verified"] > 0
            assert rep["flag_flips"] >= 2 * len(rep["events_fired"])
            # every switch restored to its pre-scenario value
            assert {f: flags.peek(f)
                    for f in DEFAULT_KILL_SWITCHES} == before
        finally:
            await c.stop()

    asyncio.run(asyncio.wait_for(main(), 120))


def test_straggler_device_fail_composed():
    """Three concurrent hazard kinds over live traffic: messenger
    stragglers + probabilistic device faults + kill-switch flips.
    The breaker/hedge layers must mask everything."""
    async def main():
        c = Cluster(num_osds=4)
        await c.start()
        try:
            sc = compose(seed=47, duration=7.0,
                         tenants=_tenants(), osd_ids=[0, 1, 2, 3],
                         hazards=("straggler", "device_fail",
                                  "kill_switch"),
                         p99_bounds={"t0": 5000.0, "t1": 5000.0},
                         objects=16, object_size=4096)
            rep = await run_scenario(c, sc)
            _no_violations(rep)
            fired = {e["hazard"] for e in rep["events_fired"]}
            assert {"straggler", "device_fail",
                    "kill_switch"} <= fired
            assert rep["acked_writes_swept"] > 0
        finally:
            await c.stop()

    asyncio.run(asyncio.wait_for(main(), 120))


def test_dmclock_cluster_wide_limit():
    """The delta/rho acceptance demo.  Tenant `capped` has mClock
    limit 25 ops/s and its reads spread over 4 primaries.  With the
    piggyback ON its tags advance by cost x delta (delta ~ number of
    primaries serving it), so the limit holds CLUSTER-wide: ~25/s
    total.  With it OFF each OSD grants a full 25/s and the tenant
    completes ~4x its limit — the limit monitor must flag exactly the
    OFF leg."""
    LIMIT = 25.0
    CEIL = LIMIT * 1.8          # monitor ceiling: ON passes, OFF fails

    async def one_leg(c, dmclock: str):
        prev = flags.peek("CEPH_TPU_DMCLOCK")
        flags.set_flag("CEPH_TPU_DMCLOCK", dmclock)
        try:
            io = c.client.open_ioctx("qos")
            target = RadosTarget(io)
            await target.setup(32, 4096)
            spec = TenantSpec("capped", arrival_rate=80.0,
                              blend={"read": 1.0}, objects=32,
                              object_size=4096)
            report = await run_open_loop(target, [spec], 5.0,
                                         seed=3,
                                         per_tenant=["capped"])
            return report
        finally:
            if prev is None:
                flags.clear("CEPH_TPU_DMCLOCK")
            else:
                flags.set_flag("CEPH_TPU_DMCLOCK", prev)

    async def main():
        profiles = json.dumps({"capped": [0.0, 1.0, LIMIT]})
        c = Cluster(num_osds=4, osd_config={
            "osd_mclock_tenant_profiles": profiles})
        await c.start()
        try:
            await c.client.create_replicated_pool("qos", size=2,
                                                  pg_num=32)
            off = await one_leg(c, "0")
            on = await one_leg(c, "1")
            rate_off = off["per_tenant"]["capped"]["completed"] / \
                max(off["elapsed_s"], 1e-9)
            rate_on = on["per_tenant"]["capped"]["completed"] / \
                max(on["elapsed_s"], 1e-9)
            assert on["errors"] == 0 and off["errors"] == 0
            # the SAME monitor must pass ON and flag OFF
            vio_on = evaluate_report(on, {}, {"capped": CEIL})
            vio_off = evaluate_report(off, {}, {"capped": CEIL})
            assert vio_on == [], (
                f"on-leg rate {rate_on:.1f} breached {CEIL}: "
                f"{vio_on}")
            assert any(v.kind == "limit-exceeded" for v in vio_off), (
                f"off-leg rate {rate_off:.1f} did not demonstrate "
                f"the per-OSD-only violation (ceiling {CEIL})")
            assert rate_off > 1.5 * rate_on, (
                f"piggyback made no difference: off {rate_off:.1f} "
                f"vs on {rate_on:.1f}")
        finally:
            await c.stop()

    asyncio.run(asyncio.wait_for(main(), 180))


def test_backfill_throttle_drain_p99():
    """The elasticity leg regression: drain an OSD mid-traffic with
    osd_max_backfills=1 — the backfill semaphore paces recovery so a
    tenant's p99 stays bounded while the cluster rebalances."""
    async def main():
        c = Cluster(num_osds=4,
                    osd_config={"osd_max_backfills": 1})
        await c.start()
        try:
            sc = Scenario(
                seed=13, duration=9.0, tenants=_tenants(rate=30),
                events=[HazardEvent("drain", 1.5, 4.0, {"osd": 1})],
                p99_bounds={"t0": 5000.0, "t1": 5000.0},
                objects=24, object_size=8192)
            rep = await run_scenario(c, sc)
            _no_violations(rep)
            assert [e["hazard"] for e in rep["events_fired"]] == \
                ["drain"]
            # the throttle actually engaged somewhere: concurrent
            # _recover_pg waves contended for the single slot
            waits = sum(o.perf.get("backfill_waits", 0)
                        for o in c.osds.values())
            assert waits >= 1, \
                "drain never contended the backfill throttle"
        finally:
            await c.stop()

    asyncio.run(asyncio.wait_for(main(), 120))


# -- the full matrix (slow tier) -------------------------------------------

@pytest.mark.slow
def test_full_matrix_60s(tmp_path):
    """The acceptance scenario: >= 60 s of traffic x stragglers x
    host loss x power-cut revive (persistent FaultStore, synthesized
    power-cut images) x kill-switch flips x OSD drain, ZERO
    violations.  Any failure replays from the printed seed."""
    async def main():
        prev_ci = flags.peek("CEPH_TPU_CRASH_INJECT")
        flags.set_flag("CEPH_TPU_CRASH_INJECT", "1")
        c = Cluster(num_osds=6, persistent=True,
                    store_factory=tpustore_factory(tmp_path,
                                                   fault=True),
                    osd_config={"osd_max_backfills": 1})
        await c.start()
        try:
            sc = compose(
                seed=104729, duration=60.0,
                tenants=_tenants(n=3, rate=25, objects=24,
                                 size=8192),
                osd_ids=list(range(6)),
                hazards=("straggler", "device_fail", "host_down",
                         "kill_switch", "powercut", "drain"),
                persistent_osds=list(range(1, 6)),
                protected_osds=[0],
                p99_bounds={"t0": 10_000.0, "t1": 10_000.0,
                            "t2": 10_000.0},
                objects=24, object_size=8192)
            rep = await run_scenario(c, sc, pool_size=3)
            _no_violations(rep)
            assert rep["loadgen"]["elapsed_s"] >= 60.0
            fired = {e["hazard"] for e in rep["events_fired"]}
            assert {"straggler", "device_fail", "kill_switch",
                    "powercut", "drain"} <= fired
            assert rep["powercuts"], "no power cut fired"
            assert rep["acked_writes_swept"] > 0
            assert rep["reads_verified"] > 100
        finally:
            await c.stop()
            if prev_ci is None:
                flags.clear("CEPH_TPU_CRASH_INJECT")
            else:
                flags.set_flag("CEPH_TPU_CRASH_INJECT", prev_ci)

    asyncio.run(asyncio.wait_for(main(), 420))


@pytest.mark.slow
def test_violation_replays_from_seed():
    """Determinism of the replay loop itself: run the same seed twice
    over identical clusters — the timelines fired must match event
    for event (the property that makes a printed seed a repro)."""
    async def one_run():
        c = Cluster(num_osds=4)
        await c.start()
        try:
            sc = compose(seed=555, duration=6.0,
                         tenants=_tenants(), osd_ids=[0, 1, 2, 3],
                         hazards=("straggler", "kill_switch"),
                         objects=16, object_size=4096)
            rep = await run_scenario(c, sc)
            return [(e["hazard"], e["start"],
                     json.dumps(e["params"], sort_keys=True))
                    for e in rep["events_fired"]], rep["violations"]
        finally:
            await c.stop()

    async def main():
        fired1, vio1 = await one_run()
        fired2, vio2 = await one_run()
        assert fired1 == fired2
        assert vio1 == vio2 == []

    asyncio.run(asyncio.wait_for(main(), 240))

"""Messenger tier: frame discipline, message codecs, loopback dispatch.

Mirrors the reference's msgr unit coverage: frame crc enforcement
(frames_v2), typed message round-trips, and a live two-endpoint exchange
over loopback."""

import asyncio

import pytest

from ceph_tpu.msg import Messenger, frames
from ceph_tpu.msg.messages import (
    MGetMap,
    MHello,
    MMonCommand,
    MMonCommandReply,
    MOSDBoot,
    MOSDFailure,
    MOSDMapMsg,
    MOSDOp,
    MOSDOpReply,
    MOSDSubRead,
    MOSDSubReadReply,
    MOSDSubWrite,
    MOSDSubWriteReply,
    MPGLogMsg,
    MPGQuery,
    MPing,
    OSDOp,
    PING,
    ShardOp,
    decode_message,
)
from ceph_tpu.osd.osdmap import PgId


# -- frames ----------------------------------------------------------------


def test_frame_round_trip():
    payload = b"hello frame" * 100
    buf = frames.encode_frame(9, 7, payload)
    tag, flags, seq, length = frames.decode_preamble(
        buf[:frames.PREAMBLE_WIRE_LEN])
    assert (tag, flags, seq, length) == (9, 0, 7, len(payload))
    body = buf[frames.PREAMBLE_WIRE_LEN:frames.PREAMBLE_WIRE_LEN + length]
    frames.check_payload(body, buf[-4:])
    assert body == payload


def test_frame_bad_magic_rejected():
    buf = bytearray(frames.encode_frame(1, 0, b"x"))
    buf[0] ^= 0xFF
    with pytest.raises(frames.FrameError):
        frames.decode_preamble(bytes(buf[:frames.PREAMBLE_WIRE_LEN]))


def test_frame_preamble_crc_enforced():
    buf = bytearray(frames.encode_frame(1, 0, b"x"))
    buf[8] ^= 0x01  # flip a seq bit; crc must catch it
    with pytest.raises(frames.FrameError):
        frames.decode_preamble(bytes(buf[:frames.PREAMBLE_WIRE_LEN]))


def test_frame_payload_crc_enforced():
    payload = b"payload bytes"
    buf = bytearray(frames.encode_frame(1, 0, payload))
    buf[frames.PREAMBLE_WIRE_LEN] ^= 0x80
    body = bytes(buf[frames.PREAMBLE_WIRE_LEN:
                     frames.PREAMBLE_WIRE_LEN + len(payload)])
    with pytest.raises(frames.FrameError):
        frames.check_payload(body, bytes(buf[-4:]))


# -- message codecs --------------------------------------------------------


MESSAGES = [
    MHello("osd.3", "127.0.0.1:6800"),
    MPing(PING, 123.5, epoch=9, from_osd=2),
    MOSDBoot(5, "127.0.0.1:6805", boot_epoch=3),
    MOSDFailure(7, 2, 21.5, 14),
    MGetMap(since_epoch=4, subscribe=True),
    MOSDMapMsg(9, full_map=b"FULLMAP", incrementals=[b"i1", b"i2"]),
    MMonCommand(11, {"prefix": "osd pool create", "name": "data"}),
    MMonCommandReply(11, 0, {"pool_id": 1}),
    MOSDOp(42, "client.1", PgId(1, 0x1f), "obj-a",
           [OSDOp("write_full", data=b"payload"),
            OSDOp("setxattr", args={"name": "k"}, data=b"v")], 7),
    MOSDOpReply(42, 0, b"result", {"size": 7}, replay_epoch=8),
    MOSDSubWrite(43, PgId(2, 3), 1, "obj-b",
                 [ShardOp("create"), ShardOp("write", 0, b"shard data"),
                  ShardOp("setattr", name="hinfo_key", value=b"{}")],
                 epoch=7,
                 log_entry={"version": [7, 4], "op": "modify"},
                 from_osd=0),
    MOSDSubWriteReply(43, 0, shard=1),
    MOSDSubRead(44, PgId(2, 3), 2, "obj-b", 0, 4096, want_attrs=True),
    MOSDSubReadReply(44, 0, b"shard bytes", {"_": b"oi"}, shard=2),
    MPGQuery(45, PgId(2, 3), 9, from_osd=0),
    MPGLogMsg(45, PgId(2, 3), 1, {"last_update": [9, 12]},
              [{"version": [9, 12], "oid": "x", "op": "modify"}],
              epoch=9, from_osd=1),
]


@pytest.mark.parametrize(
    "msg", MESSAGES, ids=[type(m).__name__ for m in MESSAGES])
def test_message_round_trip(msg):
    back = decode_message(msg.TAG, msg.encode())
    assert type(back) is type(msg)
    for key, val in vars(msg).items():
        if key.startswith("_"):
            continue
        got = getattr(back, key)
        if key == "ops":
            assert [vars(o) for o in got] == [vars(o) for o in val]
        else:
            assert got == val, f"{type(msg).__name__}.{key}"


def test_unknown_tag_rejected():
    with pytest.raises(ValueError):
        decode_message(250, b"")


# -- live loopback exchange ------------------------------------------------


def test_loopback_request_reply():
    async def main():
        server = Messenger("osd.0")
        client = Messenger("client.1")
        got = asyncio.Queue()

        async def server_dispatch(conn, msg):
            assert conn.peer_name == "client.1"  # MHello applied
            await conn.send(MOSDOpReply(msg.tid, 0, b"pong"))

        async def client_dispatch(conn, msg):
            await got.put(msg)

        server.dispatcher = server_dispatch
        client.dispatcher = client_dispatch
        addr = await server.bind()
        conn = await client.connect(addr)
        await conn.send(MOSDOp(7, "client.1", PgId(1, 0), "o",
                               [OSDOp("read")], 1))
        reply = await asyncio.wait_for(got.get(), 5)
        assert reply.tid == 7 and reply.data == b"pong"
        # connection reuse: same object for the same addr
        assert await client.connect(addr) is conn
        await client.shutdown()
        await server.shutdown()

    asyncio.run(main())


def test_loopback_many_messages_ordered_and_intact():
    async def main():
        server = Messenger("osd.0")
        client = Messenger("client.1")
        received = []
        done = asyncio.Event()

        async def server_dispatch(conn, msg):
            received.append(msg)
            if len(received) == 50:
                done.set()

        server.dispatcher = server_dispatch
        addr = await server.bind()
        conn = await client.connect(addr)
        for i in range(50):
            await conn.send(MOSDOp(i, "client.1", PgId(1, i), f"obj{i}",
                                   [OSDOp("write_full",
                                          data=bytes([i]) * 1000)], 1))
        await asyncio.wait_for(done.wait(), 10)
        assert [m.tid for m in received] == list(range(50))
        assert all(m.ops[0].data == bytes([m.tid]) * 1000
                   for m in received)
        await client.shutdown()
        await server.shutdown()

    asyncio.run(main())


def test_connection_fault_callback():
    async def main():
        server = Messenger("osd.0")
        client = Messenger("client.1")
        faulted = asyncio.Event()
        client.on_connection_fault = lambda conn: faulted.set()
        addr = await server.bind()
        conn = await client.connect(addr)
        await conn.send(MPing(PING, 1.0))
        await server.shutdown()  # server dies; client read loop faults
        await asyncio.wait_for(faulted.wait(), 5)
        assert conn.closed
        await client.shutdown()

    asyncio.run(main())


# -- wire compression negotiation (frames_v2 compression role) --------------


def _comp_pair(server_methods, client_methods, **kw):
    server = Messenger("osd.0")
    client = Messenger("client.1")
    server.compress_methods = server_methods
    client.compress_methods = client_methods
    for k, v in kw.items():
        setattr(server, k, v)
        setattr(client, k, v)
    return server, client


def test_compression_negotiated_and_round_trips():
    """Both ends accept snappy: bulk frames ride compressed (flag on
    the wire, payload smaller) and round-trip byte-exact."""
    async def main():
        server, client = _comp_pair(("snappy", "zlib"), ("snappy",))
        got = asyncio.Queue()
        seen_flags = []

        orig = frames.decode_preamble

        def spy(buf):
            out = orig(buf)
            seen_flags.append(out[1])
            return out

        frames.decode_preamble = spy
        try:
            async def server_dispatch(conn, msg):
                await conn.send(MOSDOpReply(msg.tid, 0, msg.ops[0].data))

            server.dispatcher = server_dispatch
            client.dispatcher = lambda c, m: got.put(m)
            addr = await server.bind()
            conn = await client.connect(addr)
            # compressible payload well over min_size
            data = b"compress me! " * 20_000
            await conn.send(MOSDOp(9, "client.1", PgId(1, 0), "o",
                                   [OSDOp("write", data=data)], 1))
            reply = await asyncio.wait_for(got.get(), 5)
            assert bytes(reply.data) == data
            assert any(f & frames.FLAG_COMPRESSED for f in seen_flags), \
                "no frame carried FLAG_COMPRESSED"
            # the first client frame may race the server's hello
            # (keyless conns negotiate opportunistically); by the
            # second send both directions are settled on snappy
            await conn.send(MOSDOp(10, "client.1", PgId(1, 0), "o",
                                   [OSDOp("write", data=data)], 1))
            reply = await asyncio.wait_for(got.get(), 5)
            assert bytes(reply.data) == data
            assert conn._tx_comp[0] == "snappy"
        finally:
            frames.decode_preamble = orig
            await client.shutdown()
            await server.shutdown()

    asyncio.run(main())


def test_compression_no_common_method_stays_plain():
    async def main():
        server, client = _comp_pair(("zlib",), ("snappy",))
        got = asyncio.Queue()

        async def server_dispatch(conn, msg):
            await conn.send(MOSDOpReply(msg.tid, 0, msg.ops[0].data))

        server.dispatcher = server_dispatch
        client.dispatcher = lambda c, m: got.put(m)
        addr = await server.bind()
        conn = await client.connect(addr)
        data = b"plain " * 10_000
        await conn.send(MOSDOp(1, "client.1", PgId(1, 0), "o",
                               [OSDOp("write", data=data)], 1))
        reply = await asyncio.wait_for(got.get(), 5)
        assert bytes(reply.data) == data
        assert conn._negotiated_comp("tx") is None
        await client.shutdown()
        await server.shutdown()

    asyncio.run(main())


def test_compressed_frame_truncated_length_prefix_dropped():
    """A frame with FLAG_COMPRESSED but a <4-byte payload (the length
    prefix itself truncated) must take the malformed-frame drop path —
    not escape the read loop as a raw struct.error — and the server
    must keep serving other connections."""
    async def main():
        server, client = _comp_pair(("snappy",), ("snappy",))
        got = asyncio.Queue()

        async def server_dispatch(conn, msg):
            await conn.send(MOSDOpReply(msg.tid, 0, b"ok"))

        server.dispatcher = server_dispatch
        client.dispatcher = lambda c, m: got.put(m)
        addr = await server.bind()
        conn = await client.connect(addr)
        # settle negotiation (hello exchange) with one normal op
        await conn.send(MOSDOp(1, "client.1", PgId(1, 0), "o",
                               [OSDOp("read")], 1))
        await asyncio.wait_for(got.get(), 5)
        # hand-craft the poison frame on the raw socket
        conn.writer.write(frames.encode_frame(
            MOSDOp.TAG, next(conn._seq), b"\x01",
            flags=frames.FLAG_COMPRESSED))
        await conn.writer.drain()
        # server drops that connection...
        for _ in range(100):
            if conn.reader.at_eof():
                break
            await asyncio.sleep(0.05)
        assert conn.reader.at_eof(), "poison frame did not drop conn"
        # ...and still serves a fresh one
        conn2 = await client.connect(addr)
        await conn2.send(MOSDOp(2, "client.1", PgId(1, 0), "o",
                                [OSDOp("read")], 1))
        reply = await asyncio.wait_for(got.get(), 5)
        assert reply.tid == 2
        await client.shutdown()
        await server.shutdown()

    asyncio.run(main())


def test_compression_secure_gated():
    """On an AEAD connection compression stays OFF unless
    ms_compress_secure opts in (length side channel)."""
    async def main():
        from ceph_tpu.common import auth as auth_mod

        secret = auth_mod.generate_secret()
        for opt_in in (False, True):
            server, client = _comp_pair(("snappy",), ("snappy",),
                                        compress_secure=opt_in)
            server.secret = auth_mod.parse_secret(secret)
            client.secret = auth_mod.parse_secret(secret)
            server.secure = client.secure = True
            got = asyncio.Queue()

            async def server_dispatch(conn, msg):
                await conn.send(MOSDOpReply(msg.tid, 0, b"ok"))

            server.dispatcher = server_dispatch
            client.dispatcher = lambda c, m: got.put(m)
            addr = await server.bind()
            conn = await client.connect(addr)
            data = b"secret " * 10_000
            await conn.send(MOSDOp(2, "client.1", PgId(1, 0), "o",
                                   [OSDOp("write", data=data)], 1))
            reply = await asyncio.wait_for(got.get(), 5)
            assert reply.data == b"ok"
            # inspect what the sender actually did on the last bulk
            # frame via the negotiated state: with the gate closed the
            # compressor is never even resolved
            if not opt_in:
                assert conn._tx_comp is None, \
                    "secure frame compressed without ms_compress_secure"
            await client.shutdown()
            await server.shutdown()

    asyncio.run(main())

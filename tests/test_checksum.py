"""Checksum tests.

crc32c vectors are the reference's own
(/root/reference/src/test/common/test_crc32c.cc: Small/PartialWord/Big and
the crc32c_zeros equivalence); xxhash vectors are the published XXH32/XXH64
empty-string digests plus cross-checks of the native C++ against the
independent pure-python mirror.
"""

import numpy as np
import pytest

from ceph_tpu import native
from ceph_tpu.common import checksummer as cs
from ceph_tpu.ops import checksum as cks


class TestCrc32cHost:
    def test_small(self):
        a = b"foo bar baz"
        b = b"whiz bang boom"
        assert cks.crc32c(0, a) == 4119623852
        assert cks.crc32c(1234, a) == 881700046
        assert cks.crc32c(0, b) == 2360230088
        assert cks.crc32c(5678, b) == 3743019208

    def test_partial_word(self):
        assert cks.crc32c(0, b"\x01" * 5) == 2715569182
        assert cks.crc32c(0, b"\x01" * 35) == 440531800

    def test_big(self):
        buf = b"\x01" * 4096000
        assert cks.crc32c(0, buf) == 31583199
        assert cks.crc32c(1234, buf) == 1400919119

    def test_performance_vector(self):
        ln = 1 << 20
        a = np.arange(ln, dtype=np.uint32).astype(np.uint8)
        # independent cross-check native vs python table loop on a prefix
        assert cks.crc32c(0, a[:1000]) == cks._py_crc32c(0, a[:1000].tobytes())

    def test_null_buffer_is_zeros(self):
        for ln in (0, 1, 5, 16, 63, 64, 65, 1024, 123457):
            assert cks.crc32c(77, None, ln) == cks.crc32c(77, b"\x00" * ln)

    def test_zeros_matches_linear(self):
        for seed in (0, 1, 0xFFFFFFFF, 0xDEADBEEF):
            for ln in (0, 1, 3, 15, 16, 17, 255, 4096, 999999):
                assert cks.crc32c_zeros(seed, ln) == \
                    cks.crc32c(seed, b"\x00" * ln)

    def test_combine(self):
        rng = np.random.default_rng(7)
        a = rng.integers(0, 256, 1000, dtype=np.uint8)
        b = rng.integers(0, 256, 333, dtype=np.uint8)
        whole = cks.crc32c(0xFFFFFFFF, np.concatenate([a, b]))
        part = cks.crc32c_combine(cks.crc32c(0xFFFFFFFF, a),
                                  cks.crc32c(0, b), b.size)
        assert whole == part

    def test_python_fallback_agrees(self):
        rng = np.random.default_rng(3)
        buf = rng.integers(0, 256, 4097, dtype=np.uint8)
        assert cks.crc32c(0, buf) == cks._py_crc32c(0, buf.tobytes())

    def test_blocks(self):
        rng = np.random.default_rng(5)
        buf = rng.integers(0, 256, 16 * 512, dtype=np.uint8)
        vals = cks.crc32c_blocks(buf, 512, init=0xFFFFFFFF)
        for i in range(16):
            assert vals[i] == cks.crc32c(0xFFFFFFFF, buf[i * 512:(i + 1) * 512])


class TestXxhash:
    def test_xxh32_empty(self):
        assert cks.xxh32(b"", 0) == 0x02CC5D05

    def test_xxh64_empty(self):
        assert cks.xxh64(b"", 0) == 0xEF46DB3751D8E999

    def test_native_matches_python(self):
        rng = np.random.default_rng(11)
        if native.get_lib() is None:
            pytest.skip("no native lib")
        for ln in (0, 1, 3, 4, 5, 15, 16, 17, 31, 32, 33, 100, 4096):
            buf = rng.integers(0, 256, ln, dtype=np.uint8).tobytes()
            for seed in (0, 1, 0xDEADBEEF):
                assert cks.xxh32(buf, seed) == cks._py_xxh32(buf, seed)
                assert cks.xxh64(buf, seed) == cks._py_xxh64(buf, seed)


@pytest.mark.skipif(not cks.HAVE_JAX, reason="jax required")
class TestCrc32cTpu:
    def test_batch_matches_host(self):
        rng = np.random.default_rng(13)
        for nblk, blen in ((1, 64), (4, 64), (8, 4096), (3, 100), (5, 1)):
            blocks = rng.integers(0, 256, (nblk, blen), dtype=np.uint8)
            out = np.asarray(cks.crc32c_batch_tpu(blocks, init=0xFFFFFFFF))
            for i in range(nblk):
                assert out[i] == cks.crc32c(0xFFFFFFFF, blocks[i]), (nblk, blen, i)

    def test_batch_seed_zero(self):
        rng = np.random.default_rng(17)
        blocks = rng.integers(0, 256, (4, 300), dtype=np.uint8)
        out = np.asarray(cks.crc32c_batch_tpu(blocks, init=0))
        for i in range(4):
            assert out[i] == cks.crc32c(0, blocks[i])


class TestChecksummer:
    @pytest.mark.parametrize("name", ["crc32c", "crc32c_16", "crc32c_8",
                                      "xxhash32", "xxhash64"])
    def test_roundtrip(self, name):
        t = cs.get_csum_string_type(name)
        rng = np.random.default_rng(19)
        data = rng.integers(0, 256, 8 * 4096, dtype=np.uint8).tobytes()
        csum = bytearray()
        cs.Checksummer.calculate(t, 4096, 0, len(data), data, csum)
        assert len(csum) == 8 * cs.get_csum_value_size(t)
        assert cs.Checksummer.verify(t, 4096, 0, len(data), data, csum) == -1

    def test_detects_corruption(self):
        t = cs.CSUM_CRC32C
        rng = np.random.default_rng(23)
        data = bytearray(rng.integers(0, 256, 4 * 4096, dtype=np.uint8).tobytes())
        csum = bytearray()
        cs.Checksummer.calculate(t, 4096, 0, len(data), data, csum)
        data[2 * 4096 + 17] ^= 0xFF
        bad = cs.Checksummer.verify(t, 4096, 0, len(data), data, csum)
        assert bad == 2 * 4096

    def test_partial_range_update(self):
        t = cs.CSUM_CRC32C
        rng = np.random.default_rng(29)
        data = rng.integers(0, 256, 4 * 1024, dtype=np.uint8).tobytes()
        csum = bytearray()
        cs.Checksummer.calculate(t, 1024, 0, len(data), data, csum)
        # re-checksum only block 2 and verify the vector is unchanged
        before = bytes(csum)
        cs.Checksummer.calculate(t, 1024, 2 * 1024, 1024, data, csum)
        assert bytes(csum) == before

    def test_names(self):
        assert cs.get_csum_type_string(cs.CSUM_CRC32C) == "crc32c"
        assert cs.get_csum_string_type("xxhash64") == cs.CSUM_XXHASH64
        with pytest.raises(ValueError):
            cs.get_csum_string_type("nope")


def test_crc32c_partial_bits_words_matches_bytes():
    """The word-layout crc path (device-native int32 rows) produces
    the same crcs as the uint8 path and the host oracle."""
    import jax.numpy as jnp

    from ceph_tpu.ops import checksum as cks

    rng = np.random.default_rng(21)
    block = 4096
    data = rng.integers(0, 256, (6, block), dtype=np.uint8)
    consts = cks.make_crc_consts(block)
    want = [cks.crc32c(0, row.tobytes()) for row in data]
    got_bytes = np.asarray(cks.crc32c_pack_bits(
        cks.crc32c_partial_bits(jnp.asarray(data), consts)))
    words = jnp.asarray(
        np.ascontiguousarray(data).view(np.int32))  # (6, 1024)
    got_words = np.asarray(cks.crc32c_pack_bits(
        cks.crc32c_partial_bits_words(words, consts)))
    assert [int(c) for c in got_bytes] == want
    assert [int(c) for c in got_words] == want


def test_crc_pallas_blocks_bit_exact():
    """ops/crc_pallas.py: the MXU crc kernel (interpret mode on CPU)
    must be bit-exact vs the host crc across block sizes, seeds, and
    non-tile-aligned block counts."""
    import numpy as np

    from ceph_tpu.ops import checksum as cks
    from ceph_tpu.ops import crc_pallas

    if not crc_pallas.HAVE_JAX:
        import pytest

        pytest.skip("no jax")
    import jax.numpy as jnp

    crc_pallas.FORCE_INTERPRET = True
    try:
        rng = np.random.default_rng(11)
        for length, n in [(4096, 5), (4096, 130), (512, 9), (64, 3)]:
            data = rng.integers(0, 256, (n, length), dtype=np.uint8)
            words = jnp.asarray(data.view(np.int32))
            for init in (0, 0xFFFFFFFF, 0xDEADBEEF):
                got = np.asarray(crc_pallas.crc32c_blocks_words(
                    words, length, init=init))
                want = np.array(
                    [cks.crc32c(init, row.tobytes()) for row in data],
                    dtype=np.uint32)
                assert np.array_equal(got, want), (length, n, init)
    finally:
        crc_pallas.FORCE_INTERPRET = False

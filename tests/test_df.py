"""`rados df` / librados cluster_stat + get_pool_stats roles: the
client aggregates each OSD's statfs (store totals + per-pool raw
object/byte breakdown) into cluster and per-pool usage."""

import asyncio

from cluster_helpers import Cluster


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


def test_df_cluster_and_pool_accounting():
    async def main():
        cluster = Cluster(num_osds=3, osds_per_host=1)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "rep", size=3, pg_num=4)
            await cluster.client.create_ec_pool(
                "ec", {"plugin": "ec_jax",
                       "technique": "reed_sol_van", "k": "2",
                       "m": "1", "crush-failure-domain": "osd",
                       "tpu": "false"}, pg_num=4)
            rep = cluster.client.open_ioctx("rep")
            ec = cluster.client.open_ioctx("ec")
            for i in range(5):
                await rep.write_full(f"r{i}", b"R" * 1000)
            await ec.write_full("big", b"E" * 6000)
            df = await cluster.client.df()
            assert df["cluster"]["total_bytes"] > 0
            assert df["cluster"]["used_bytes"] >= 0
            pools = {p["name"]: p for p in df["pools"]}
            # replicated: 5 logical objects, 3 raw copies each,
            # >= 3x bytes stored
            assert pools["rep"]["objects"] == 5
            assert pools["rep"]["objects_raw"] == 15
            assert pools["rep"]["bytes_used"] >= 3 * 5 * 1000
            # EC 2+1: one logical object striped into 3 chunks
            assert pools["ec"]["objects"] == 1
            assert pools["ec"]["objects_raw"] == 3
            assert pools["ec"]["bytes_used"] >= 6000  # k+m overhead
        finally:
            await cluster.stop()
    run(main())

"""Foundation tests: options/config layering, perf counters, admin socket
wire protocol, logging ring, throttles."""

import json
import os
import threading
import time

import pytest

from ceph_tpu.common import admin_socket as asok
from ceph_tpu.common.config import Config
from ceph_tpu.common.log import Log, parse_levels
from ceph_tpu.common.options import OPTIONS, get_option
from ceph_tpu.common.perf_counters import PerfCounters, PerfCountersCollection
from ceph_tpu.common.throttle import Throttle


# -- options ---------------------------------------------------------------


def test_option_cast_types():
    assert get_option("osd_pool_default_size").cast("5") == 5
    assert get_option("bluestore_compression_required_ratio").cast("0.5") == 0.5
    assert get_option("mon_osd_adjust_heartbeat_grace").cast("false") is False
    with pytest.raises(ValueError):
        get_option("osd_pool_default_size").cast("five")
    with pytest.raises(ValueError):
        get_option("bluestore_compression_mode").cast("sometimes")  # enum
    with pytest.raises(ValueError):
        get_option("bluestore_compression_required_ratio").cast("1.5")  # max


def test_options_schema_is_populated():
    assert len(OPTIONS) > 30
    assert "bluestore_csum_type" in OPTIONS
    assert OPTIONS["osd_pool_default_erasure_code_profile"].default.startswith(
        "plugin=jerasure")


# -- config layering -------------------------------------------------------


def test_config_precedence():
    cfg = Config()
    assert cfg.get("osd_pool_default_size") == 3  # default
    cfg.set_val("osd_pool_default_size", "5", source="file")
    assert cfg.get("osd_pool_default_size") == 5
    cfg.set_val("osd_pool_default_size", "4", source="mon")
    assert cfg.get("osd_pool_default_size") == 4  # mon beats file
    cfg.set_val("osd_pool_default_size", "2", source="runtime")
    assert cfg.get("osd_pool_default_size") == 2  # runtime beats all
    cfg.rm_val("osd_pool_default_size", source="runtime")
    assert cfg.get("osd_pool_default_size") == 4  # falls back to mon
    assert cfg.source_of("osd_pool_default_size") == "mon"


def test_config_observers():
    cfg = Config()
    seen = []
    cfg.add_observer(lambda keys: seen.append(sorted(keys)),
                     keys=["osd_heartbeat_grace"])
    cfg.set_val("osd_pool_default_size", 5)      # not watched
    cfg.set_val("osd_heartbeat_grace", "30")
    assert seen == [["osd_heartbeat_grace"]]
    assert cfg.get("osd_heartbeat_grace") == 30.0


def test_config_file_sections(tmp_path):
    conf = tmp_path / "ceph.conf"
    conf.write_text("""
[global]
osd pool default size = 5
[osd]
osd heartbeat grace = 25
[osd.3]
osd heartbeat grace = 40
""")
    cfg = Config(entity="osd.3")
    cfg.parse_config_file(str(conf))
    assert cfg.get("osd_pool_default_size") == 5
    assert cfg.get("osd_heartbeat_grace") == 40.0  # most specific wins
    cfg2 = Config(entity="osd.7")
    cfg2.parse_config_file(str(conf))
    assert cfg2.get("osd_heartbeat_grace") == 25.0


def test_config_argv_and_env():
    cfg = Config()
    leftover = cfg.parse_argv(["--osd-pool-default-size=6", "positional",
                               "--osd_heartbeat_grace", "33", "-x"])
    assert leftover == ["positional", "-x"]
    assert cfg.get("osd_pool_default_size") == 6
    assert cfg.get("osd_heartbeat_grace") == 33.0
    cfg.parse_env({"CEPH_TPU_OSD_POOL_DEFAULT_SIZE": "7"})
    # env is BELOW cli in precedence
    assert cfg.get("osd_pool_default_size") == 6
    assert cfg.diff()["osd_pool_default_size"]["source"] == "cli"


def test_config_rejects_unknown_and_invalid():
    cfg = Config()
    with pytest.raises(KeyError):
        cfg.set_val("nonesuch_option", 1)
    with pytest.raises(ValueError):
        cfg.set_val("bluestore_compression_mode", "sometimes")


# -- perf counters ---------------------------------------------------------


def test_perf_counters_basic():
    pc = PerfCounters("osd")
    pc.add_u64_counter("op_w", "writes")
    pc.add_time_avg("op_w_lat", "write latency")
    pc.add_histogram("op_size", [1024, 4096, 65536])
    pc.inc("op_w")
    pc.inc("op_w", 4)
    pc.tinc("op_w_lat", 0.5)
    pc.tinc("op_w_lat", 1.5)
    pc.hinc("op_size", 100)
    pc.hinc("op_size", 5000)
    pc.hinc("op_size", 10 << 20)
    d = pc.dump()
    assert d["op_w"] == 5
    assert d["op_w_lat"]["avgcount"] == 2 and d["op_w_lat"]["avgtime"] == 1.0
    assert d["op_size"]["buckets"] == [1, 0, 1, 1]


def test_perf_counters_timer():
    pc = PerfCounters("x")
    pc.add_time_avg("lat")
    with pc.time_it("lat"):
        time.sleep(0.01)
    assert pc.avg("lat") >= 0.01


def test_perf_collection():
    coll = PerfCountersCollection()
    a, b = PerfCounters("osd"), PerfCounters("bluestore")
    a.add_u64("n")
    b.add_u64("m")
    coll.add(a)
    coll.add(b)
    a.set("n", 42)
    assert coll.dump()["osd"]["n"] == 42
    assert set(coll.dump()) == {"osd", "bluestore"}
    assert set(coll.dump("osd")) == {"osd"}
    assert "description" in coll.schema()["bluestore"]["m"]


# -- admin socket ----------------------------------------------------------


@pytest.fixture
def admin(tmp_path):
    cfg = Config()
    coll = PerfCountersCollection()
    pc = PerfCounters("osd")
    pc.add_u64_counter("ops")
    pc.inc("ops", 7)
    coll.add(pc)
    sock = asok.AdminSocket(str(tmp_path / "asok"), config=cfg, perf=coll,
                            version="16.0.0-tpu")
    sock.init()
    yield sock
    sock.shutdown()


def test_admin_socket_version(admin):
    out = asok.admin_socket_request(admin.path, "version")
    assert out == {"version": "16.0.0-tpu"}


def test_admin_socket_perf_dump(admin):
    out = asok.admin_socket_request(admin.path, {"prefix": "perf dump"})
    assert out["osd"]["ops"] == 7


def test_admin_socket_config_get_set(admin):
    out = asok.admin_socket_request(
        admin.path, {"prefix": "config get", "var": "osd_heartbeat_grace"})
    assert out == {"osd_heartbeat_grace": 20.0}
    out = asok.admin_socket_request(
        admin.path, "config set osd_heartbeat_grace 42")
    assert out == {"success": ""}
    out = asok.admin_socket_request(
        admin.path, "config get osd_heartbeat_grace")
    assert out == {"osd_heartbeat_grace": 42.0}
    out = asok.admin_socket_request(admin.path, "config diff")
    assert out["osd_heartbeat_grace"]["source"] == "runtime"


def test_admin_socket_help_and_unknown(admin):
    out = asok.admin_socket_request(admin.path, "help")
    assert "perf dump" in out
    out = asok.admin_socket_request(admin.path, "frobnicate")
    assert "error" in out


def test_admin_socket_custom_command(admin):
    admin.register_command("dump_ops_in_flight",
                           lambda cmd: {"ops": [], "num_ops": 0})
    out = asok.admin_socket_request(admin.path, "dump_ops_in_flight")
    assert out == {"ops": [], "num_ops": 0}


# -- logging ---------------------------------------------------------------


def test_parse_levels():
    assert parse_levels("1/5") == (1, 5)
    assert parse_levels("3") == (3, 3)


def test_log_levels_and_ring(tmp_path, capsys):
    cfg = Config()
    log = Log(cfg, name="osd.0")
    log.set_subsys_level("osd", "1/5")
    log.dout("osd", 0, "always visible")
    log.dout("osd", 3, "ring only")       # gathered, not printed
    log.dout("osd", 20, "dropped")
    err = capsys.readouterr().err
    assert "always visible" in err
    assert "ring only" not in err
    import io
    buf = io.StringIO()
    log.dump_recent(out=buf)
    dumped = buf.getvalue()
    assert "ring only" in dumped
    assert "dropped" not in dumped


def test_log_file_async(tmp_path):
    cfg = Config()
    log = Log(cfg, name="osd.1")
    path = str(tmp_path / "osd.log")
    log.set_log_file(path)
    log.set_subsys_level("osd", "5/5")
    for i in range(50):
        log.dout("osd", 1, f"line {i}")
    log.flush()
    log.stop()
    content = open(path).read()
    assert "line 0" in content and "line 49" in content


def test_log_reconfig_via_observer():
    cfg = Config()
    log = Log(cfg, name="x")
    assert log._subsys["ms"] == (0, 5)
    cfg.set_val("debug_ms", "4/9")
    assert log._subsys["ms"] == (4, 9)


# -- throttle --------------------------------------------------------------


def test_throttle_basic():
    t = Throttle("bytes", 100)
    assert t.get(60)
    assert t.get_or_fail(40)
    assert not t.get_or_fail(1)   # full
    t.put(50)
    assert t.get_or_fail(10)
    assert t.get_current() == 60


def test_throttle_blocks_and_wakes():
    t = Throttle("ops", 2)
    t.get(2)
    acquired = []

    def worker():
        t.get(1)
        acquired.append(1)

    th = threading.Thread(target=worker)
    th.start()
    time.sleep(0.05)
    assert not acquired          # blocked
    t.put(1)
    th.join(timeout=2)
    assert acquired == [1]


def test_throttle_oversized_request():
    t = Throttle("x", 10)
    # a request larger than max is admitted when the throttle is empty
    assert t.get(25, timeout=1)
    assert t.get_current() == 25
    assert not t.get_or_fail(1)
    t.put(25)


def test_throttle_timeout():
    t = Throttle("x", 1)
    t.get(1)
    t0 = time.time()
    assert not t.get(1, timeout=0.1)
    assert time.time() - t0 < 1.0


def test_throttle_unlimited():
    t = Throttle("x", 0)  # max 0 = no limit (reference semantics)
    assert t.get_or_fail(1 << 40)
    t.put(1 << 40)


def test_throttle_fifo_no_starvation():
    """A large blocked request must not be starved by later small ones."""
    t = Throttle("x", 100)
    t.get(100)
    order = []

    def big():
        t.get(80)
        order.append("big")
        t.put(80)

    def small():
        t.get(10)
        order.append("small")
        t.put(10)

    tb = threading.Thread(target=big)
    tb.start()
    time.sleep(0.05)
    ts = threading.Thread(target=small)
    ts.start()
    time.sleep(0.05)
    # drain: big (queued first) must acquire before small
    t.put(100)
    tb.join(timeout=2)
    ts.join(timeout=2)
    assert order[0] == "big"


def test_log_runtime_log_file_switch(tmp_path):
    cfg = Config()
    log = Log(cfg, name="osd.9")
    a, b = str(tmp_path / "a.log"), str(tmp_path / "b.log")
    cfg.set_val("log_file", a)
    log.set_subsys_level("osd", "5/5")
    log.dout("osd", 1, "to-a")
    log.flush()
    cfg.set_val("log_file", b)          # runtime switch via observer
    log.dout("osd", 1, "to-b")
    log.flush()
    log.stop()
    assert "to-a" in open(a).read()
    content_b = open(b).read()
    assert "to-b" in content_b and "to-a" not in content_b


def test_admin_socket_perf_dump_filter(admin):
    out = asok.admin_socket_request(admin.path, "perf dump osd")
    assert set(out) == {"osd"}
    out = asok.admin_socket_request(admin.path, "perf dump nonesuch")
    assert out == {}


def test_size_option_suffixes():
    opt = get_option("tpu_min_dispatch_bytes")
    assert opt.cast("64K") == 64 << 10
    assert opt.cast("100M") == 100 << 20
    assert opt.cast("1G") == 1 << 30
    assert opt.cast("2MiB") == 2 << 20
    with pytest.raises(ValueError):
        opt.cast("64Q")


def test_rm_val_notifies_observers():
    cfg = Config()
    seen = []
    cfg.add_observer(lambda keys: seen.append(sorted(keys)),
                     keys=["debug_ms"])
    cfg.set_val("debug_ms", "4/9")
    cfg.rm_val("debug_ms")
    assert seen == [["debug_ms"], ["debug_ms"]]


def test_log_max_recent_config():
    cfg = Config()
    log = Log(cfg, name="x")
    cfg.set_val("log_max_recent", 7)
    log.set_subsys_level("osd", "0/5")
    for i in range(20):
        log.dout("osd", 3, f"r{i}")
    assert len(log._recent) == 7

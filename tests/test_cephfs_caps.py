"""Client caps: delegated caching + recall coherence.

The Locker.cc / Client.cc capability discipline at this build's scale
(/root/reference/src/mds/Locker.cc issue/revoke;
/root/reference/src/client/Client.cc handle_caps, insert_trace):

1. a granted cap lets a client serve stat/read from local cache with
   ZERO MDS round trips (the whole point of the protocol);
2. conflicting access from another client RECALLS the cap first, so
   no client ever observes stale attrs after a foreign mutation;
3. a writer's buffered (dirty) size flushes on recall/close, never
   lost, max-merged;
4. an unresponsive holder is evicted after a timeout — a dead client
   cannot wedge the namespace.
"""

import asyncio

import pytest

from cluster_helpers import Cluster

from ceph_tpu.cephfs import CephFS, CephFSError
from ceph_tpu.mds import MDSDaemon
from ceph_tpu.rados.client import RadosClient


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 150))


async def _fs_cluster(num_clients=2):
    cluster = Cluster(num_osds=4)
    await cluster.start()
    await cluster.client.create_replicated_pool(
        "cephfs.meta", size=2, pg_num=8)
    await cluster.client.create_replicated_pool(
        "cephfs.data", size=2, pg_num=8)
    mds = MDSDaemon(cluster.mon.addr, "cephfs.meta", "cephfs.data",
                    lock_interval=0.3)
    await mds.start()
    clients, fss = [], []
    for i in range(num_clients):
        rc = RadosClient(cluster.mon.addr, name=f"client.caps{i}")
        await rc.connect()
        clients.append(rc)
        fss.append(CephFS(rc, "cephfs.meta", "cephfs.data"))
    return cluster, mds, clients, fss


async def _teardown(cluster, mds, clients):
    await mds.stop()
    for rc in clients:
        await rc.shutdown()
    await cluster.stop()


def test_cached_stat_loop_is_zero_round_trips():
    """VERDICT done-criterion: a cached-stat loop shows no MDS
    traffic."""
    async def main():
        cluster, mds, clients, (fs, _fs2) = await _fs_cluster()
        try:
            await fs.write_file("/hot", b"x" * 1000)
            first = await fs.stat("/hot")
            assert first["size"] == 1000
            baseline = fs.mds_requests
            hits0 = fs.cap_hits
            for _ in range(100):
                st = await fs.stat("/hot")
                assert st["size"] == 1000
            assert fs.mds_requests == baseline, \
                "cached stats still hit the MDS"
            assert fs.cap_hits >= hits0 + 100
            # cached READ path too: open("r") + read off the cap
            base2 = fs.mds_requests
            f = await fs.open("/hot", "r")
            for _ in range(10):
                assert await f.read(0, 1000) == b"x" * 1000
            assert fs.mds_requests == base2, \
                "cap-cached open/read still hit the MDS"
        finally:
            await _teardown(cluster, mds, clients)

    run(main())


def test_foreign_write_recalls_reader_cache():
    """Client B caches a stat; client A overwrites (acquiring rw
    recalls B); B's next stat sees the new size — never the cached
    one."""
    async def main():
        cluster, mds, clients, (fs_a, fs_b) = await _fs_cluster()
        try:
            await fs_a.write_file("/f", b"a" * 100)
            st = await fs_b.stat("/f")
            assert st["size"] == 100
            assert fs_b._caps, "B should hold a cap"
            # A's writable open conflicts: B must be recalled
            f = await fs_a.open("/f", "w+")
            await f.write(0, b"b" * 5000)
            await f.close()
            assert not fs_b._attr_cache, \
                "B's cache survived a foreign write"
            st = await fs_b.stat("/f")
            assert st["size"] == 5000
            assert await fs_b.read_file("/f") == b"b" * 5000
        finally:
            await _teardown(cluster, mds, clients)

    run(main())


def test_writer_buffered_size_flushes_on_foreign_stat():
    """A holds rw and buffers size locally (no per-write flush); B's
    stat recalls A — the flushed size must arrive in B's answer."""
    async def main():
        cluster, mds, clients, (fs_a, fs_b) = await _fs_cluster()
        try:
            f = await fs_a.open("/buf", "w")
            base = fs_a.mds_requests
            await f.write(0, b"1" * 10_000)
            await f.write(10_000, b"2" * 10_000)
            await f.write(20_000, b"3" * 4_000)
            # rw cap held: the three writes buffered their sizes
            assert fs_a.mds_requests == base, \
                "writes flushed size despite the rw cap"
            assert fs_a._dirty, "no dirty record buffered"
            # B's stat recalls A; the ack carries the dirty size
            st = await fs_b.stat("/buf")
            assert st["size"] == 24_000
            assert not fs_a._dirty, "dirty survived the recall"
            assert await fs_b.read_file("/buf") == \
                b"1" * 10_000 + b"2" * 10_000 + b"3" * 4_000
            await f.close()
        finally:
            await _teardown(cluster, mds, clients)

    run(main())


def test_unlink_and_rename_invalidate_foreign_caches():
    async def main():
        cluster, mds, clients, (fs_a, fs_b) = await _fs_cluster()
        try:
            await fs_a.write_file("/gone", b"g" * 64)
            await fs_a.write_file("/moved", b"m" * 64)
            assert (await fs_b.stat("/gone"))["size"] == 64
            assert (await fs_b.stat("/moved"))["size"] == 64
            await fs_a.unlink("/gone")
            await fs_a.rename("/moved", "/here")
            # B's cached entries were recalled: fresh answers
            assert not await fs_b.exists("/gone")
            assert not await fs_b.exists("/moved")
            assert await fs_b.read_file("/here") == b"m" * 64
        finally:
            await _teardown(cluster, mds, clients)

    run(main())


def test_concurrent_writers_max_merge_sizes():
    """Two writers alternate on one file: rw exclusivity bounces the
    cap between them (recall folds each one's dirty size), and the
    final size is the max of everything written."""
    async def main():
        cluster, mds, clients, (fs_a, fs_b) = await _fs_cluster()
        try:
            fa = await fs_a.open("/shared", "w")
            await fa.write(0, b"A" * 3000)
            fb = await fs_b.open("/shared", "r+")   # recalls A
            await fb.write(3000, b"B" * 9000)
            await fa.write(500, b"C" * 100)          # A is capless now
            await fa.close()
            await fb.close()
            st = await fs_a.stat("/shared")
            assert st["size"] == 12_000
            data = await fs_a.read_file("/shared")
            assert data[0:500] == b"A" * 500
            assert data[500:600] == b"C" * 100
            assert data[3000:12_000] == b"B" * 9000
        finally:
            await _teardown(cluster, mds, clients)

    run(main())


def test_unresponsive_holder_is_evicted():
    """A client that never acks a recall must not wedge the MDS: the
    revoke times out, the session is evicted, the mutation
    proceeds."""
    async def main():
        cluster, mds, clients, (fs_a, fs_b) = await _fs_cluster()
        mds.cap_revoke_timeout = 0.5
        try:
            await fs_a.write_file("/stuck", b"s" * 10)
            await fs_b.stat("/stuck")          # B holds r
            fs_b.client.fs_caps_handler = None  # B goes catatonic
            # A's truncate must still complete (after the timeout)
            await fs_a.truncate("/stuck", 4)
            assert (await fs_a.stat("/stuck"))["size"] == 4
            # B's session is gone from every cap table (A's own caps
            # may legitimately remain)
            for holders in mds._caps.values():
                assert not any(
                    getattr(c, "peer_name", "") == "client.caps1"
                    for c in holders), \
                    "catatonic session still holds caps"
        finally:
            await _teardown(cluster, mds, clients)

    run(main())


def test_failover_starts_capless():
    """A new active MDS knows nothing of old grants: the client's
    next op re-discovers, drops its caps, and re-reads fresh."""
    async def main():
        cluster, mds, clients, (fs_a, fs_b) = await _fs_cluster()
        mds2 = MDSDaemon(cluster.mon.addr, "cephfs.meta",
                         "cephfs.data", name="b", lock_interval=0.3)
        await mds2.start()
        try:
            await fs_a.write_file("/ha", b"h" * 256)
            await fs_a.stat("/ha")
            assert fs_a._caps
            await mds.stop()   # failover to mds2
            # next op rides out ESTALE/discovery; caps dropped
            for _ in range(50):
                try:
                    st = await fs_a.stat("/ha")
                    break
                except CephFSError:
                    await asyncio.sleep(0.3)
            assert st["size"] == 256
            assert await fs_a.read_file("/ha") == b"h" * 256
        finally:
            await mds2.stop()
            for rc in clients:
                await rc.shutdown()
            await cluster.stop()

    run(main())


def test_directory_rename_recalls_descendant_caches():
    """Renaming a DIRECTORY invalidates every descendant PATH: cached
    entries under the old prefix must be recalled everywhere, and a
    bystander writer's buffered size must flush and persist (its old
    path still resolved at recall time)."""
    async def main():
        cluster, mds, clients, (fs_a, fs_b) = await _fs_cluster()
        try:
            await fs_a.mkdir("/d")
            await fs_a.write_file("/d/f", b"f" * 128)
            # B caches a descendant stat + holds a dirty rw on another
            assert (await fs_b.stat("/d/f"))["size"] == 128
            w = await fs_b.open("/d/w", "w")
            await w.write(0, b"W" * 7777)
            assert fs_b._dirty, "writer should be buffering"
            await fs_a.rename("/d", "/e")
            # B's cached old-prefix paths are gone, fresh answers only
            assert not await fs_b.exists("/d/f")
            assert (await fs_b.stat("/e/f"))["size"] == 128
            # the buffered size flushed through the recall and
            # persisted under the OLD path before the move
            assert (await fs_a.stat("/e/w"))["size"] == 7777
            assert await fs_a.read_file("/e/w") == b"W" * 7777
        finally:
            await _teardown(cluster, mds, clients)

    run(main())

"""SPMD collective-safety tier (ISSUE 16): the static rules' runtime
twin plus the regression tests for the real findings the analyzer
surfaced.

Four legs:

* **Runtime ⊆ static + order congruence** — a REAL 2-process
  ``jax.distributed`` group (gloo CPU collectives) runs the meshbench
  smoke workload with the collective-trace recorder armed; every
  in-package call site a worker observed must exist in the static
  collective-site map, and every process must observe the SAME
  collective sequence.
* **Seeded-divergence self-test** — a deliberately divergent toy
  module (process 1 raises before ``agree``) is caught by BOTH the
  static ``divergent-collective`` rule and the multi-process replay
  (trace incongruence), while process 0 reads the missing peer as a
  TIMEOUT verdict, never a wedge — the BrokenBlockStore pattern for
  the cross-process plane.
* **Real-finding regressions** — ``ec/plan.py`` declines the mesh
  (instead of proceeding on a divergent local view) when agreement
  infrastructure fails; ``parallel/backend.py`` mesh caches key on
  the topology signature so a cluster-shape change over the same
  chips cannot replay a stale flat/hybrid mesh.
* **Seam discipline** — an ad-hoc coordinator-KV wait outside
  ``parallel/multihost.py`` is flagged even when it carries a
  timeout: half-protocols must ride the agreement seam.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

import ceph_tpu
import conftest
from ceph_tpu.analysis import analyze_paths
from ceph_tpu.analysis.collective import collective_site_map
from ceph_tpu.analysis.core import build_project

jax = pytest.importorskip("jax")

from ceph_tpu.common import circuit  # noqa: E402
from ceph_tpu.ec import plan  # noqa: E402
from ceph_tpu.parallel import backend, multihost  # noqa: E402

PKG = os.path.dirname(os.path.abspath(ceph_tpu.__file__))
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8,
    reason="needs the conftest 8-virtual-device CPU mesh")


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv("CEPH_TPU_MULTIHOST_HOSTS", raising=False)
    circuit.reset_all()
    yield
    circuit.reset_all()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_pair(worker_src: str, tmp_path, extra_env=None,
                timeout: float = 240.0):
    """Two jax.distributed worker processes running `worker_src`;
    returns [(rc, stdout, stderr), ...]."""
    worker = tmp_path / "worker.py"
    worker.write_text(worker_src)
    port = _free_port()
    procs = []
    for pid in range(2):
        env = {k: v for k, v in os.environ.items()
               if k not in ("XLA_FLAGS",)}
        env.update({
            "CEPH_TPU_MULTIHOST_COORD": f"127.0.0.1:{port}",
            "CEPH_TPU_MULTIHOST_NPROC": "2",
            "CEPH_TPU_MULTIHOST_PID": str(pid),
            "CEPH_TPU_MULTIHOST_LOCAL_DEVICES": "2",
            "CEPH_TPU_MULTIHOST_WORKER_DEADLINE_S": str(timeout),
            "CEPH_TPU_COLLECTIVE_TRACE": "1",
            "JAX_PLATFORMS": "cpu",
        })
        env.update(extra_env or {})
        procs.append(subprocess.Popen(
            [sys.executable, str(worker)], stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, env=env))
    outs = []
    try:
        for p in procs:
            so, se = p.communicate(timeout=timeout)
            outs.append((p.returncode, so, se))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs


def _results(outs):
    reports = []
    for rc, so, se in outs:
        assert rc == 0, se[-2000:]
        line = [ln for ln in so.splitlines()
                if ln.startswith("RESULT ")][-1]
        reports.append(json.loads(line[len("RESULT "):]))
    return reports


# -- leg 1: live 2-process runtime ⊆ static + order congruence ---------

_LIVE_WORKER_SRC = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["CEPH_TPU_MESH_MIN_BYTES"] = "0"
    from ceph_tpu.parallel import meshbench
    rep = meshbench.worker_report(smoke=True, iters=1)
    print("RESULT " + json.dumps(rep), flush=True)
""")


@pytest.mark.skipif(conftest.DEVICE_INJECTION,
                    reason="spawns its own process group; injection\
 would fail every dispatch inside it")
def test_two_process_trace_subset_of_static_and_congruent(tmp_path):
    """THE runtime cross-check: every collective call site two real
    processes observe must exist in the static collective-site map,
    and both processes must observe the SAME sequence — the runtime ⊆
    static discipline of the lockdep and interleave checks, extended
    to the cross-process plane."""
    outs = _spawn_pair(_LIVE_WORKER_SRC.format(repo=REPO), tmp_path)
    reports = _results(outs)
    assert all(r.get("bitexact") for r in reports)
    traces = [r.get("collective_trace") for r in reports]
    assert all(t for t in traces), "recorder produced no records"
    # per-process order congruence: same sites, same order
    assert traces[0] == traces[1], (
        "processes observed divergent collective sequences:\n"
        f"  p0={traces[0]}\n  p1={traces[1]}")
    # non-vacuous: the smoke leg drives agreement AND data collectives
    ops = {row[2] for row in traces[0]}
    assert "agreed_healthy" in ops, ops
    assert {"put_global", "gather"} & ops, ops
    # runtime ⊆ static
    smap = collective_site_map(build_project([PKG]))
    pkg_sites = {(p, ln) for p, ln, _op in traces[0]
                 if p.startswith("ceph_tpu/")}
    assert pkg_sites, "no in-package sites recorded"
    unexplained = sorted(s for s in pkg_sites if s not in smap)
    assert not unexplained, (
        "collective sites observed at runtime but absent from the "
        "static site map (collective.py is blind to these):\n"
        + "\n".join(f"  {p}:{ln}" for p, ln in unexplained))


# -- leg 2: seeded-divergence self-test --------------------------------

_DIVERGENT_SRC = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    from ceph_tpu.parallel import multihost
    from ceph_tpu.analysis import interleave


    def broken_round(epoch):
        if multihost.process_index() == 1:
            raise RuntimeError("divergent: bail before the agreement")
        return multihost.agree("toy/%d" % epoch, "x", timeout_s=3.0)


    def main():
        assert multihost.bootstrap_from_env(), "group did not form"
        ok, reports = 1, None
        try:
            reports = broken_round(0)
        except RuntimeError:
            ok = 0
        trace = [[r.path, r.line, r.op]
                 for r in interleave.collective_records()]
        print("RESULT " + json.dumps({{
            "pid": multihost.process_index(), "ok": ok,
            "peer_timed_out": (None if reports is None
                               else int(reports.get(1) is None)),
            "trace": trace}}), flush=True)
        # skip atexit distributed teardown: the divergent process
        # already broke the group by design
        sys.stdout.flush()
        os._exit(0)


    main()
""")


def _divergent_src() -> str:
    return _DIVERGENT_SRC.format(repo=REPO)


@pytest.mark.skipif(conftest.DEVICE_INJECTION,
                    reason="spawns its own process group")
def test_seeded_divergence_caught_by_replay(tmp_path):
    """Harness self-test: one process raises before the agreement.
    The replay must SEE the divergence (incongruent traces), and the
    surviving process must read the missing peer as a timeout verdict
    — completing within the deadline, never wedging."""
    outs = _spawn_pair(_divergent_src(), tmp_path)
    reports = _results(outs)
    by_pid = {r["pid"]: r for r in reports}
    assert by_pid[1]["ok"] == 0           # the seeded bail fired
    assert by_pid[0]["ok"] == 1           # the survivor completed...
    assert by_pid[0]["peer_timed_out"] == 1   # ...with a timeout
    # the replay catches the divergence: the traces are incongruent
    # (process 0 entered the agreement, process 1 never did)
    assert by_pid[0]["trace"] != by_pid[1]["trace"]
    assert any(op == "agree" for _p, _ln, op in by_pid[0]["trace"])
    assert not any(op == "agree"
                   for _p, _ln, op in by_pid[1]["trace"])


def test_seeded_divergence_caught_statically(tmp_path):
    """The same toy module the replay catches must be caught by the
    static rule: the agreement follows a raise guarded by a
    process_index branch — the divergent-collective shape."""
    src = _divergent_src()
    path = tmp_path / "toy_divergent_worker.py"
    path.write_text(src)
    agree_line = next(i for i, ln in enumerate(src.splitlines(), 1)
                      if "multihost.agree(" in ln)
    findings, _ = analyze_paths(
        [str(path)], config={"spmd_paths": ("toy_divergent",)})
    assert {(f.rule, f.line) for f in findings} == {
        ("divergent-collective", agree_line)}


# -- leg 3: regressions for the real findings the analyzer surfaced ----

def test_agreement_failure_declines_mesh(monkeypatch):
    """ec/plan.py finding (divergent-collective): when agreement
    infrastructure fails in a multiprocess group, _healthy_jax_devices
    must DECLINE the mesh (single-device plan; peers retire this
    process by timeout) — before the fix it swallowed the exception
    and proceeded on its unagreed LOCAL view, building a mesh its
    peers don't share."""
    monkeypatch.setattr(multihost, "is_multiprocess", lambda: True)

    def boom(ids):
        raise RuntimeError("coordinator unreachable")

    monkeypatch.setattr(multihost, "agreed_healthy", boom)
    assert plan._healthy_jax_devices() == []

    # the agreed path still filters to the agreed subset
    monkeypatch.setattr(multihost, "agreed_healthy",
                        lambda ids: tuple(sorted(ids)[:1]))
    healthy = plan._healthy_jax_devices()
    assert [d.id for d in healthy] == \
        sorted(d.id for d in jax.devices())[:1]


def test_mesh_cache_keys_on_topology(monkeypatch):
    """parallel/backend.py finding (topology-stale-state): the same
    chip ids under a different cluster shape must rebuild the mesh —
    before the fix the device-id-only cache key replayed the flat
    mesh after the topology grew a second host domain (and vice
    versa)."""
    flat = backend.default_mesh()
    assert "dcn" not in flat.axis_names
    monkeypatch.setenv("CEPH_TPU_MULTIHOST_HOSTS", "2")
    hybrid = backend.default_mesh()
    assert "dcn" in hybrid.axis_names, (
        "topology change over the same chips replayed the stale "
        f"flat mesh {hybrid.axis_names}")
    monkeypatch.delenv("CEPH_TPU_MULTIHOST_HOSTS")
    again = backend.default_mesh()
    assert "dcn" not in again.axis_names


# -- leg 4: seam discipline --------------------------------------------

def test_kv_wait_outside_seam_is_flagged(tmp_path):
    """An ad-hoc coordinator-KV wait outside parallel/multihost.py is
    flagged even WITH a timeout: half-protocols must ride the
    multihost.agree seam (the default spmd_seam_paths scope)."""
    src = tmp_path / "adhoc_kv.py"
    src.write_text(
        "def wait(client):\n"
        "    return client.blocking_key_value_get('k', 1000)\n")
    findings, _ = analyze_paths([str(src)])
    assert {(f.rule, f.line) for f in findings} == {
        ("unguarded-collective-timeout", 2)}

"""Clean twin: awaited acquisition and `async with`."""
import asyncio


class Svc:
    def __init__(self):
        self.state_lock = asyncio.Lock()

    async def grab(self):
        await self.state_lock.acquire()
        try:
            pass
        finally:
            self.state_lock.release()

    async def grab_ctx(self):
        async with self.state_lock:
            pass

"""Clean twin: kernel evaluation through the plan cache (compute
plan kind), the bit-exact numpy host twin, and a breaker-guarded
raw dispatch."""

from ceph_tpu.common import circuit
from ceph_tpu.compute import kernels
from ceph_tpu.ec import plan


def evaluate_wave(name, weights, batch):
    out = plan.compute_eval(name, weights, batch)
    if out is None:
        out = kernels.host_eval(weights, batch)
    return out


def guarded_probe(weights, batch):
    return circuit.device_call(
        "compute",
        lambda: kernels.make_device_eval(weights)(batch), batch=1)

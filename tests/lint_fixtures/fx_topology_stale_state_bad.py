"""Violation twin for topology-stale-state: a module-level cache
keyed by the device-id set alone.  The same chips under a different
cluster shape (1x8 vs 2x4 host domains) replay stale state after a
shrink or a join — the flat-vs-hybrid mesh layout is a function of
topology, not of the id set."""

_mesh_cache = {}


def cached_mesh(devs, build):
    sig = tuple(d.id for d in devs)
    mesh = _mesh_cache.get(sig)  # expect: topology-stale-state
    if mesh is None:
        mesh = _mesh_cache[sig] = build(devs)
    return mesh

"""Clean twin: EC entry points compile through the ExecPlan cache
(ceph_tpu.ec.plan) — bucketed, counted, donated where safe."""

from ceph_tpu.ec import plan


def encode_stripes(mbits, data):
    return mbits @ data


encode_fn = plan.tracked_jit("fx.encode", encode_stripes)


def batched_parity(matrix, stripes):
    return plan.encode(matrix, stripes)

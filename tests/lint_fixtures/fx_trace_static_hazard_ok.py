"""Clean twin: the same shape-driving param declared static."""
import functools

import jax


@functools.partial(jax.jit, static_argnames=("n",))
def kernel(x, n):
    acc = x
    for _ in range(n):
        acc = acc + 1
    if n > 3:
        acc = acc * 2
    return acc

"""Clean twin of fx_transitive_blocking_call_bad: the identical
helper chain shipped off-loop through asyncio.to_thread — the event
loop never runs the blocking leaf."""
import asyncio


def _read_super(path):
    with open(path) as fh:
        return fh.read()


def _load(path):
    return _read_super(path)


async def serve(path):
    return await asyncio.to_thread(_load, path)

"""Seeded violation: two lock classes acquired in both orders — a
would-be deadlock the moment the two paths interleave.  Both edges of
the cycle are findings (each acquisition site participates)."""
import asyncio


class Pair:
    def __init__(self):
        self.alpha_lock = asyncio.Lock()
        self.beta_lock = asyncio.Lock()

    async def forward(self):
        async with self.alpha_lock:
            async with self.beta_lock:    # expect: lock-order
                pass

    async def backward(self):
        async with self.beta_lock:
            async with self.alpha_lock:   # expect: lock-order
                pass

"""Violation: raw device dispatch outside the breaker guard — a
wedged or faulting accelerator raises to the caller instead of
degrading to the bit-exact host path."""

from ceph_tpu.ops import gf
from ceph_tpu.parallel import backend


def reconstruct(dmat, survivors):
    return backend.matmul(dmat, survivors)  # expect: unguarded-device-dispatch


def parity(mat, stripes):
    return gf.gf_matmul_tpu(mat, stripes)  # expect: unguarded-device-dispatch

"""Seeded violations: raw os.environ access with CEPH_TPU_* literal
keys outside the kill-switch registry."""

import os
from os import environ


def read_toggle():
    return os.environ.get("CEPH_TPU_FROB", "1") != "0"  # expect: unregistered-kill-switch


def read_getenv():
    return os.getenv("CEPH_TPU_FROB_LEVEL", "2")  # expect: unregistered-kill-switch


def read_subscript():
    return os.environ["CEPH_TPU_FROB_MODE"]  # expect: unregistered-kill-switch


def write_subscript(value):
    os.environ["CEPH_TPU_FROB"] = value  # expect: unregistered-kill-switch


def probe_membership():
    return "CEPH_TPU_FROB" in os.environ  # expect: unregistered-kill-switch


def pop_from_imported():
    return environ.pop("CEPH_TPU_FROB", None)  # expect: unregistered-kill-switch

"""Clean twin: awaited equivalents; sync I/O stays in a sync helper
shipped to a worker thread."""
import asyncio


def _load(path):
    with open(path) as fh:
        return fh.read()


async def daemon_tick():
    await asyncio.sleep(0.1)
    proc = await asyncio.create_subprocess_exec("true")
    await proc.wait()
    return await asyncio.to_thread(_load, "/tmp/state")

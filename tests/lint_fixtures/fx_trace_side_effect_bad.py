"""Seeded violations: Python side effects inside a jit-traced body.

Parsed by tests/test_lint_rules.py, never imported.  `# expect:` marks
the exact (rule, line) each seeded violation must produce.
"""
import time

import jax
import numpy as np


@jax.jit
def kernel(x):
    print("tracing", x)       # expect: trace-side-effect
    t = time.time()           # expect: trace-side-effect
    noise = np.random.rand()  # expect: trace-side-effect
    return x * t + noise

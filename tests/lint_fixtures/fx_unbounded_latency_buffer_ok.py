"""Clean twin: latency streams into the bounded histogram; ordinary
list building in loops stays unflagged."""

import time

from ceph_tpu.loadgen.stats import LatencyHistogram


async def sweep(target, events):
    hist = LatencyHistogram()
    for ev in events:
        t0 = time.perf_counter()
        await target.op(ev)
        hist.record(time.perf_counter() - t0)
    return hist.to_dict()


def collect_names(rows):
    # a non-latency append in a loop is not a finding
    names = []
    for row in rows:
        names.append(row.name)
    return names


def one_shot(target):
    # an append OUTSIDE any loop is not a finding either
    lats = []
    t0 = time.perf_counter()
    target.sync_op()
    lats.append(time.perf_counter() - t0)
    return lats

"""Violation fixture: rule unused-suppression.

The disable comment below suppresses NOTHING — the violation it once
covered is gone — so it would silently swallow the next real finding
on that line.  The analyzer must flag the dead comment itself."""


async def idle():
    return 0  # lint: disable=async-blocking  # expect: unused-suppression

"""Clean twin: the cache key folds in the topology signature, so a
cluster-shape change over the same chips misses and rebuilds."""

_mesh_cache = {}


def topology_signature():
    return ()


def cached_mesh(devs, build):
    sig = (tuple(d.id for d in devs), topology_signature())
    mesh = _mesh_cache.get(sig)
    if mesh is None:
        mesh = _mesh_cache[sig] = build(devs)
    return mesh

"""Clean twin: .shape-derived values are static Python ints under jit,
so concretizing THEM is not a sync."""
import jax
import jax.numpy as jnp


@jax.jit
def kernel(x):
    rows = int(x.shape[0])
    scale = float(x.ndim)
    return jnp.sum(x) * scale + rows

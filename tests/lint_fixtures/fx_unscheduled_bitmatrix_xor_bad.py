"""Seeded violations: naive bitmatrix row-walk XOR loops that bypass
the schedule compiler (ec/xsched.py)."""

import numpy as np


def xor_matmul(rows, packets):
    out = np.zeros((packets.shape[0], rows.shape[0],
                    packets.shape[2]), dtype=np.uint8)
    for r in range(rows.shape[0]):
        idx = np.flatnonzero(rows[r])
        out[:, r] = np.bitwise_xor.reduce(packets[:, idx, :], axis=1)  # expect: unscheduled-bitmatrix-xor
    return out


def fold_rows(rows, srcs, acc):
    for r in rows:
        acc[:] ^= srcs[r]  # expect: unscheduled-bitmatrix-xor
    return acc

"""Clean twin of fx_cancellation_unsafe_acquire_bad: every safe shape
— acquire after the last pre-use suspension, the gap covered by a
try/finally that pairs the release, or the await shielded from
cancellation."""
import asyncio


class Conn:
    def __init__(self):
        self.send_seq = iter(range(1 << 20))

    async def send_late(self, frame):
        await self._drain()
        seq = next(self.send_seq)
        self._submit(seq, frame)

    async def send_covered(self, frame):
        seq = next(self.send_seq)
        try:
            await asyncio.sleep(0)
        finally:
            self._submit(seq, frame)

    async def send_shielded(self, frame):
        seq = next(self.send_seq)
        await asyncio.shield(self._flush(seq, frame))

    async def _drain(self):
        await asyncio.sleep(0)

    async def _flush(self, seq, frame):
        self._submit(seq, frame)

    def _submit(self, seq, frame):
        pass

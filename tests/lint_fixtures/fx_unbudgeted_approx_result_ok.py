"""Clean twins: the approximate combine gated on check_budget, a
pure solver helper (no combined scores synthesized), and an exact
path (no solve)."""

import numpy as np

from ceph_tpu.inference import model
from ceph_tpu.inference.fisher import check_budget


def combine_missing(spec, data_parts, fused_parts, budget, est):
    k = int(spec["k"])
    missing = [i for i in range(k) if i not in data_parts]
    a = np.asarray(spec["coeff"], dtype=np.float64)
    sub = a[np.asarray(sorted(fused_parts))][:, np.asarray(missing)]
    rhs = np.stack([fused_parts[j].reshape(-1)
                    for j in sorted(fused_parts)])
    sol, _resid, _rank, _sv = np.linalg.lstsq(sub, rhs, rcond=None)
    if not check_budget(est, budget):
        return None
    parts = [data_parts.get(i) for i in range(k)]
    for row, i in enumerate(missing):
        parts[i] = sol[row].reshape(parts[0].shape)
    return model.combine_contributions(spec, parts)


def solver_gain(coeff, fused_ids, missing):
    """Solver internals only: no combined scores leave this scope."""
    sub = np.asarray(coeff)[np.asarray(fused_ids)][:,
                                                   np.asarray(missing)]
    pinv = np.linalg.pinv(sub)
    return pinv, float(np.linalg.norm(pinv, 2))


def exact_combine(spec, parts):
    """Exact path: no solve happened, nothing to budget."""
    return model.combine_contributions(spec, parts)

"""Clean twin: device dispatches ride circuit.device_call — watchdog,
breaker accounting, and the fault-injection seam apply, and a failed
dispatch degrades to the host fold instead of raising."""

from ceph_tpu.common import circuit
from ceph_tpu.ops import gf
from ceph_tpu.parallel import backend


def reconstruct(dmat, survivors):
    status, out = circuit.device_call(
        "ec-decode", backend.matmul, dmat, survivors,
        batch=len(survivors))
    if status == "ok" and out is not None:
        return out
    return gf.gf_matmul_host(dmat, survivors)


def parity(mat, stripes):
    status, out = circuit.device_call(
        "ec-encode", gf.gf_matmul_tpu, mat, stripes,
        batch=len(stripes))
    if status == "ok":
        return out
    return gf.gf_matmul_host(mat, stripes)

"""Violation: a bare asyncio.gather over sub-read jobs completes at
the SLOWEST peer's pace — one degraded OSD sets p99 for every read
through this fan-out — and the spawned tasks are neither EWMA-ranked
nor cancellation-managed."""

import asyncio


class Reader:
    async def fetch_shards(self, pg, oid, acting):
        jobs = [self._read_candidates(pg, shard, osd, oid)
                for shard, osd in enumerate(acting)]
        results = await asyncio.gather(*jobs)  # expect: unhedged-gather
        return [c for sub, _ok in results for c in sub]

    async def _read_candidates(self, pg, shard, osd, oid):
        return [], True

"""Clean twin: process-group setup routed through the
parallel/multihost.py bootstrap seam — the collectives config, host
topology, topology-aware plan keys, and collective-safe membership
agreement all engage."""

from ceph_tpu.parallel import multihost


def join_group(coordinator, nproc, pid):
    return multihost.initialize(coordinator=coordinator,
                                num_processes=nproc, process_id=pid)


def join_from_env():
    return multihost.bootstrap_from_env()

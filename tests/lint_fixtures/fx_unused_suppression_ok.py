"""Clean twin of fx_unused_suppression_bad: the suppression still
covers a live finding (the sleep IS a violation, deliberately
accepted), so it is in use and must not be flagged."""
import time


async def tick():
    time.sleep(0.1)  # lint: disable=async-blocking

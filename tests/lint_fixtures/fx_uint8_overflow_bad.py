"""Seeded violations: uint8 arithmetic that wraps silently at 256.

The rule is path-scoped to the GF(2^8)/EC modules; the fixture test
points it here via the dtype_paths config knob.
"""
import numpy as np


def accumulate(data):
    acc = data.astype(np.uint8)
    total = acc * 3     # expect: uint8-overflow
    shifted = acc << 1  # expect: uint8-overflow
    wide = acc.astype(np.int32)
    return total, shifted, wide + wide

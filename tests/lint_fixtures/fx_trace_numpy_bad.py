"""Seeded violation: bare numpy applied to a traced value."""
import jax
import numpy as np


@jax.jit
def kernel(x):
    return np.tanh(x)  # expect: trace-numpy

"""Clean twin of fx_await_atomicity_bad: the same RMW shapes made
safe — one lockdep.Lock scope covering read AND write, or the value
re-derived after the suspension so no stale read survives an
interleaving."""
import asyncio

from ceph_tpu.common import lockdep


class Daemon:
    def __init__(self):
        self._lock = lockdep.Lock("fx.atomicity")
        self.next_version = 0
        self.bytes_in_flight = 0

    async def alloc_version(self):
        async with self._lock:
            v = self.next_version
            await asyncio.sleep(0)
            self.next_version = v + 1
        return v

    async def account(self, n):
        got = await self._quota(n)
        # read happens AFTER the last suspension: no window
        self.bytes_in_flight = self.bytes_in_flight + got
        return got

    async def _quota(self, n):
        return n

"""Clean twin: encodes ride the awaited micro-batching service;
str.encode() and sync-scope helpers stay silent."""

import json

from ceph_tpu.osd import ec_util


async def write_full(service, sinfo, codec, data):
    return await service.encode_with_hinfo(sinfo, codec, data,
                                           range(6),
                                           logical_len=len(data))


async def attr_bytes(oi):
    return json.dumps(oi).encode()


def host_reencode(sinfo, codec, merged):
    return ec_util.encode(sinfo, codec, merged, range(6))

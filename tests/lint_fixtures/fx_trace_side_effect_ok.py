"""Clean twin: the side-effect-free spellings of the same intents."""
import jax
import jax.numpy as jnp


@jax.jit
def kernel(x):
    jax.debug.print("per-call print {}", x)
    key = jax.random.PRNGKey(0)
    noise = jax.random.uniform(key)
    return jnp.tanh(x) + noise

"""Violation fixture: rule await-atomicity.

Read-modify-write of `self.` daemon state spanning a suspension point
with no lockdep.Lock scope covering both sides — the PR-3 bug class:
a version is allocated, the coroutine suspends, a concurrent task
reads the SAME value, and one of the two increments is silently lost.
"""
import asyncio


class Daemon:
    def __init__(self):
        self.next_version = 0
        self.bytes_in_flight = 0

    async def alloc_version(self):
        v = self.next_version
        await asyncio.sleep(0)
        self.next_version = v + 1  # expect: await-atomicity
        return v

    async def account(self, n):
        got = await self._quota(n)
        self.bytes_in_flight += got
        return got

    async def account_inline(self, n):
        self.bytes_in_flight += await self._quota(n)  # expect: await-atomicity

    async def _quota(self, n):
        return n

"""Clean twin: both arms issue the collectives in the SAME relative
order (the arms may differ in payloads and local work — order is the
cross-process contract, not content)."""
from ceph_tpu.parallel import multihost


def exchange(retrying, epoch):
    if retrying:
        multihost.agree(f"meta/{epoch}", "m-retry")
        multihost.agree(f"data/{epoch}", "d-retry")
    else:
        multihost.agree(f"meta/{epoch}", "m")
        multihost.agree(f"data/{epoch}", "d")

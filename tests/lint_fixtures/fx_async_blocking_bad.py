"""Seeded violations: event-loop-blocking calls in an async body."""
import subprocess
import time


async def daemon_tick():
    time.sleep(0.1)                 # expect: async-blocking
    subprocess.run(["true"])        # expect: async-blocking
    with open("/tmp/state") as fh:  # expect: async-blocking
        return fh.read()

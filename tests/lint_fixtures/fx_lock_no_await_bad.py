"""Seeded violations: asyncio.Lock misuse — an un-awaited .acquire()
returns a coroutine (lock never taken); a sync `with` does not
suspend and raises at runtime."""
import asyncio


class Svc:
    def __init__(self):
        self.state_lock = asyncio.Lock()

    async def grab(self):
        self.state_lock.acquire()  # expect: lock-no-await
        with self.state_lock:      # expect: lock-no-await
            pass

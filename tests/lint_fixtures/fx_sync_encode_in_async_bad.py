"""Violation: synchronous EC encode on the daemon's event loop —
every concurrent write stalls behind the dispatch, and none of them
share a batched device call."""

from ceph_tpu.osd import ec_util


async def write_full(sinfo, codec, data):
    shards, hinfo, crc = ec_util.encode_with_hinfo(  # expect: sync-encode-in-async
        sinfo, codec, data, range(6), logical_len=len(data))
    return shards, hinfo, crc


async def rmw_reencode(sinfo, codec, merged):
    return ec_util.encode(sinfo, codec, merged, range(6))  # expect: sync-encode-in-async


async def codec_direct(codec, want, buf):
    return codec.encode(want, buf)  # expect: sync-encode-in-async

"""Clean twin: jnp on traced values; host numpy only on host
constants outside the traced scope."""
import jax
import jax.numpy as jnp
import numpy as np

TABLE = np.arange(8, dtype=np.int32)


@jax.jit
def kernel(x):
    return jnp.tanh(x) + jnp.asarray(TABLE).sum()

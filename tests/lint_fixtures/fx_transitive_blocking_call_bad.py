"""Violation fixture: rule transitive-blocking-call.

The blocking `open` sits TWO sync frames below the `async def` — the
direct async-blocking rule cannot see it; the interprocedural closure
must name the whole helper chain."""


def _read_super(path):
    with open(path) as fh:
        return fh.read()


def _load(path):
    return _read_super(path)


async def serve(path):
    return _load(path)  # expect: transitive-blocking-call

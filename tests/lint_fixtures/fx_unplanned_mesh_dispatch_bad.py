"""Violation: raw mesh compiles outside the plan cache — the XLA
trace is invisible to plan.stats(), the executable binds whatever
device set existed at build time (a sick chip's mesh is never
retired), and the dispatch skips the breaker guard."""

import jax
from jax.experimental.pjit import pjit

from ceph_tpu.ops import gf


def build_encode(mesh, in_specs, out_specs):
    return jax.shard_map(gf._gf2_matmul_bytes_impl, mesh=mesh,  # expect: unplanned-mesh-dispatch
                         in_specs=in_specs, out_specs=out_specs)


def build_encode_pjit(in_shardings, out_shardings):
    return pjit(gf._gf2_matmul_bytes_impl,  # expect: unplanned-mesh-dispatch
                in_shardings=in_shardings,
                out_shardings=out_shardings)

"""Violation fixture: rule cancellation-unsafe-acquire.

A monotonic frame seq is consumed, then the coroutine can suspend
OUTSIDE try/finally before the paired submit — a cancellation landing
on the suspension consumes the seq without it ever hitting the wire,
and the receiver's replay check sees the gap (the PR-6 msgr class).
"""
import asyncio


class Conn:
    def __init__(self):
        self.send_seq = iter(range(1 << 20))

    async def send_frame(self, frame):
        seq = next(self.send_seq)  # expect: cancellation-unsafe-acquire
        await asyncio.sleep(0)
        self._submit(seq, frame)

    def _submit(self, seq, frame):
        pass

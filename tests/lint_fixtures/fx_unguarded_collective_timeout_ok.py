"""Clean twins: the KV wait carries a hard timeout (the
multihost.agree discipline), and the barrier rides the agreement
seam, whose per-peer timed KV reads turn a dead host into a
membership verdict."""
from ceph_tpu.parallel import multihost


def wait_for_peer(client, topic, peer, timeout_ms):
    return client.blocking_key_value_get(f"{topic}/{peer}",
                                         timeout_ms)


def fleet_barrier(epoch):
    return multihost.agree(f"barrier/{epoch}", "here", timeout_s=5.0)

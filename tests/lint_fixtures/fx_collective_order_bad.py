"""Violation twin for collective-order: the two arms of a branch
issue the same collectives in inverted relative order — a process
taking the `if` arm blocks in the meta round while a peer taking the
`else` arm blocks in the data round, and neither ever completes."""
from ceph_tpu.parallel import multihost


def exchange(retrying, epoch):
    if retrying:  # expect: collective-order
        multihost.agree(f"meta/{epoch}", "m")
        multihost.agree(f"data/{epoch}", "d")
    else:
        multihost.agree(f"data/{epoch}", "d")
        multihost.agree(f"meta/{epoch}", "m")

"""Violation: spans started outside a finally / context manager leak
on the exception path — the op most worth explaining (the one that
raised, or returned early) never reaches the trace ring, the
critical-path stage histograms, or the tail exemplars."""


class Daemon:
    async def handle_op(self, msg):
        span = self.tracer.start(f"osd_op {msg.oid}")  # expect: span-leak
        result = await self.execute(msg)
        span.finish()              # skipped whenever execute() raises
        return result

    async def fire_and_forget(self, msg):
        self.tracer.start(f"osd_op {msg.oid}")  # expect: span-leak
        return await self.execute(msg)

    async def execute(self, msg):
        return None

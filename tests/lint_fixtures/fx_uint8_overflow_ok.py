"""Clean twin: promoted accumulation and GF-style xor (carry-free,
cannot overflow) on the same narrow input."""
import numpy as np


def accumulate(data):
    acc = data.astype(np.int32)
    total = acc * 3
    narrow = data.astype(np.uint8)
    mixed = narrow ^ narrow
    return (total + total).astype(np.uint8), mixed

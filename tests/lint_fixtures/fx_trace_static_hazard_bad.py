"""Seeded violations: dynamic jit params driving Python control flow
and shapes — every new value recompiles (traced values even error)."""
import jax


@jax.jit
def kernel(x, n):
    acc = x
    for _ in range(n):  # expect: trace-static-hazard
        acc = acc + 1
    if n > 3:           # expect: trace-static-hazard
        acc = acc * 2
    return acc

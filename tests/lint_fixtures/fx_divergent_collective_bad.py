"""Violation twins for divergent-collective: wedgeable collectives
whose reachability depends on the process identity — the silent-wedge
class (peers block forever in a collective one process never enters,
or retire a live host whose agreement never arrived)."""
from ceph_tpu.parallel import multihost


def ranked_announce(epoch):
    # only process 0 enters the agreement: every peer's per-process
    # KV read times out and process 0's round reads the group as dead
    if multihost.process_index() == 0:
        multihost.agree(f"announce/{epoch}", "leader")  # expect: divergent-collective


def bail_before_agree(epoch):
    # process 1 raises past the collective its peers block in
    if multihost.process_index() == 1:
        raise RuntimeError("local bail")
    return multihost.agree(f"round/{epoch}", "payload")  # expect: divergent-collective


def swallowed_agreement(ids):
    # a local exception skips the agreement and execution continues
    # with membership state the peers don't share
    try:
        return multihost.agree_healthy(ids)  # expect: divergent-collective
    except Exception:
        pass

"""Seeded violation: raw coded-compute kernel dispatch outside the
plan cache and the breaker guard."""

from ceph_tpu.compute import kernels


def evaluate_wave(weights, batch):
    fn = kernels.make_device_eval(weights)  # expect: unplanned-compute-dispatch
    return fn(batch)

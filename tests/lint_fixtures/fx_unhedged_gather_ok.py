"""Clean twin: the sub-read fan-out rides the hedged first-k gather
primitive — EWMA-ranked launch order, delayed hedges at the p95 mark,
stragglers cancelled AND awaited."""


class Reader:
    async def fetch_shards(self, pg, oid, acting, need):
        jobs = [(osd,
                 lambda shard=shard, osd=osd: self._read_candidates(
                     pg, shard, osd, oid))
                for shard, osd in enumerate(acting)]
        results, _ran_all = await self.hedge.gather(
            jobs, need=need,
            sufficient=lambda rs: sum(len(s) for s, _ok in rs) >= need,
            failed=lambda res: not res[0])
        return [c for sub, _ok in results for c in sub]

    async def _read_candidates(self, pg, shard, osd, oid):
        return [], True

"""Seeded violations: implicit device->host syncs on traced values."""
import jax
import numpy as np


@jax.jit
def kernel(x):
    y = x + 1
    v = y.item()        # expect: trace-host-sync
    f = float(y)        # expect: trace-host-sync
    h = np.asarray(y)   # expect: trace-host-sync
    return v + f + h

"""Clean twin of fx_hot_path_copy_bad: views end to end — memoryview
slices are zero-copy, and lengths come from the parts without ever
concatenating them."""


def reframe(payload, parts):
    view = memoryview(payload)
    head = view[:4]
    body = view[4:]
    total = sum(len(p) for p in parts)
    return head, body, total

"""Clean twin of fx_hot_path_copy_bad: views end to end — memoryview
slices are zero-copy, and lengths come from the parts without ever
concatenating them."""


def reframe(payload, parts):
    view = memoryview(payload)
    head = view[:4]
    body = view[4:]
    total = sum(len(p) for p in parts)
    return head, body, total


def reslice(payload):
    # a BUF-named variable bound to a view constructor slices
    # zero-copy: the rule recognizes the binding and stays silent
    # (re-flagging converted sites would re-list them forever)
    data = memoryview(payload)
    return data[:4], data[4:]

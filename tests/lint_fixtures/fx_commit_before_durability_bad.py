"""Seeded violation: the store acks (fires on_commit) BEFORE its
durability point — the sync KV commit happens after the callbacks, so
a power cut between them erases an acked transaction."""


class LeakyStore:
    def __init__(self, kv):
        self._kv = kv

    def queue_transaction(self, txn):
        kvt = self._kv.get_transaction()
        for op in txn.ops:
            kvt.add(op)
        for cb in txn.on_commit:
            cb()  # expect: commit-before-durability
        self._kv.submit_transaction_sync(kvt)

"""Violation fixture: rule hot-path-copy (severity "info" — the
finding list is ROADMAP item 2's zero-copy worklist, not a gate).
Each line below is one full-buffer memcpy per op at line rate."""


def reframe(payload, parts):
    head = bytes(payload)  # expect: hot-path-copy
    body = payload[4:]  # expect: hot-path-copy
    joined = b"".join(parts)  # expect: hot-path-copy
    return head, body, joined

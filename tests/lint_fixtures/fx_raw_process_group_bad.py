"""Violation: a process group joined outside the multihost bootstrap
seam — no gloo collectives config, no host-topology map, plan keys
never learn the cluster shape, and membership agreement would ride a
collective a dead host wedges."""

import jax
from jax import distributed


def join_group(coordinator, nproc, pid):
    jax.distributed.initialize(  # expect: raw-process-group
        coordinator_address=coordinator, num_processes=nproc,
        process_id=pid)


def leave_group():
    distributed.shutdown()  # expect: raw-process-group

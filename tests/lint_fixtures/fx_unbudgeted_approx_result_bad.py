"""Seeded violation: an approximate combine (least-squares solve of
the missing shard contributions) returned without ever consulting the
error-budget gate."""

import numpy as np

from ceph_tpu.inference import model


def combine_missing(spec, data_parts, fused_parts, budget):
    k = int(spec["k"])
    missing = [i for i in range(k) if i not in data_parts]
    a = np.asarray(spec["coeff"], dtype=np.float64)
    sub = a[np.asarray(sorted(fused_parts))][:, np.asarray(missing)]
    rhs = np.stack([fused_parts[j].reshape(-1)
                    for j in sorted(fused_parts)])
    sol, _resid, _rank, _sv = np.linalg.lstsq(sub, rhs, rcond=None)
    parts = [data_parts.get(i) for i in range(k)]
    for row, i in enumerate(missing):
        parts[i] = sol[row].reshape(parts[0].shape)
    return model.combine_contributions(spec, parts)  # expect: unbudgeted-approx-result

"""Clean twin: flag access through the registry, plus environ uses
the rule must NOT flag (non-CEPH_TPU keys, dynamic keys, whole-dict
copies)."""

import os

from ceph_tpu.common import flags


def read_through_registry():
    return flags.enabled("CEPH_TPU_FROB")


def numeric_through_registry():
    return flags.flag_float("CEPH_TPU_FROB_LEVEL", 2.0)


def write_through_registry(value):
    flags.set_flag("CEPH_TPU_FROB", value)


def foreign_key():
    return os.environ.get("XLA_FLAGS", "")


def dynamic_key(name):
    return os.environ.get(name)


def whole_dict():
    return dict(os.environ)

"""Clean twin: both paths honor one global order (alpha before
beta), including through a callee (the interprocedural summary)."""
import asyncio


class Pair:
    def __init__(self):
        self.alpha_lock = asyncio.Lock()
        self.beta_lock = asyncio.Lock()

    async def _locked_tail(self):
        async with self.beta_lock:
            pass

    async def forward(self):
        async with self.alpha_lock:
            async with self.beta_lock:
                pass

    async def forward_via_call(self):
        async with self.alpha_lock:
            await self._locked_tail()

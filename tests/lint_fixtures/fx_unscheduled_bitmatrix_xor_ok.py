"""Clean twin: XOR programs ride the schedule compiler; loops that
GF-multiply (wide-word field math) are not XOR walks; single
un-looped XOR folds are one-shot reductions, not row walks."""

import numpy as np

from ceph_tpu.ec import xsched


def scheduled_encode(bm, sources, outs):
    sched = xsched.compile_matrix(bm)
    xsched.execute_host(sched, sources, outs)


def wide_word_matmul(mat, words, field):
    out = np.zeros((words.shape[0], mat.shape[0], words.shape[-1]),
                   dtype=words.dtype)
    for j in range(mat.shape[0]):
        for i in range(words.shape[1]):
            out[:, j] ^= field.mul_vec(int(mat[j, i]), words[:, i])
    return out


def one_shot_fold(packets):
    return np.bitwise_xor.reduce(packets, axis=1)

"""Violation: direct jax.jit on shape-polymorphic EC entry points —
every (batch, chunk) shape retraces outside the ExecPlan cache."""

import functools

import jax


def encode_stripes(mbits, data):
    return mbits @ data


encode_fn = jax.jit(encode_stripes)  # expect: jit-bypass-plan


@jax.jit  # expect: jit-bypass-plan
def decode_stripes(dmat_bits, survivors):
    return dmat_bits @ survivors


@functools.partial(jax.jit, donate_argnums=(1,))  # expect: jit-bypass-plan
def fused_encode(mbits, data):
    return mbits @ data

"""Clean twins: collectives under group-uniform guards (every process
takes the same branch), explicit-verdict exception paths, and
data-dependent predicates stay silent."""
from ceph_tpu.parallel import multihost


def guarded_announce(epoch):
    # is_multiprocess() is a group-uniform kill switch: every process
    # evaluates it identically, nobody diverges
    if not multihost.is_multiprocess():
        return {0: "leader"}
    return multihost.agree(f"announce/{epoch}", "leader")


def declined_agreement(ids):
    # the handler RETURNS an explicit verdict — the caller sees "no
    # agreement" instead of silently divergent state
    try:
        return multihost.agree_healthy(ids)
    except Exception:
        return None


def batched_rounds(payloads, epoch):
    # a data-dependent loop: identical inputs on every process (the
    # SPMD contract callers already carry) walk identical rounds
    out = []
    for i, payload in enumerate(payloads):
        out.append(multihost.agree(f"batch/{epoch}/{i}", payload))
    return out

"""Violation twins for unguarded-collective-timeout: a blocking
coordinator-KV wait with no hard timeout, and an untimed global
barrier — a dead host must read as a timeout verdict, never a
wedge."""


def wait_for_peer(client, topic, peer):
    return client.blocking_key_value_get(f"{topic}/{peer}")  # expect: unguarded-collective-timeout


def fleet_barrier():
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices("fleet")  # expect: unguarded-collective-timeout

"""Fixture: per-op latency samples appended to an unbounded list
inside a bench loop."""

import time


async def sweep(target, events):
    lats = []
    for ev in events:
        t0 = time.perf_counter()
        await target.op(ev)
        lats.append(time.perf_counter() - t0)  # expect: unbounded-latency-buffer
    return lats


async def sweep_named(target, events):
    # the receiver NAME alone marks the buffer even when the sample
    # expression carries no visible clock call
    samples = []
    for ev in events:
        dt = await target.timed_op(ev)
        samples.append(dt)  # expect: unbounded-latency-buffer
    return samples

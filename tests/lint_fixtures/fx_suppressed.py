"""Every violation here is suppressed: inline `# lint: disable=`,
comment-above placement, and a file-wide `# lint: disable-file=`.
The fixture test asserts the analyzer reports nothing."""
import time

import jax
import numpy as np

# lint: disable-file=trace-numpy


@jax.jit
def kernel(x):
    t = time.time()  # lint: disable=trace-side-effect
    y = np.sqrt(x)
    return y * t


async def tick():
    # lint: disable=async-blocking
    time.sleep(0.1)

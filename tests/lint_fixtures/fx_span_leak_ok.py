"""Clean twin: every span finishes on every path — the context-manager
surface (tracer.span / tracing.child_span), a try/finally around the
bound span, or the inline finish(start(...)) shape."""


class Daemon:
    async def handle_op(self, msg):
        async with self.tracer.span(f"osd_op {msg.oid}") as span:
            span.event("started")
            return await self.execute(msg)

    async def handle_sub_op(self, msg):
        span = self.tracer.start(f"sub_write {msg.oid}")
        try:
            return await self.execute(msg)
        finally:
            self.tracer.finish(span)

    async def handle_via_helper(self, msg):
        span = self.tracer.start(f"osd_op {msg.oid}")
        try:
            return await self.execute(msg)
        finally:
            self._finish_op_span(span, None)

    def mark_once(self, tracer):
        tracer.finish(tracer.start("probe"))

    async def execute(self, msg):
        return None

"""Clean twin: mesh compiles ride plan.tracked_jit (retraces land in
plan.stats(), the plan key carries the device-set signature) and any
raw dispatch body sits under circuit.device_call."""

import jax

from ceph_tpu.common import circuit
from ceph_tpu.ec import plan
from ceph_tpu.ops import gf


def build_encode(mesh, in_specs, out_specs, label):
    return plan.tracked_jit(
        label,
        jax.shard_map(gf._gf2_matmul_bytes_impl, mesh=mesh,
                      in_specs=in_specs, out_specs=out_specs))


def dispatch(fn, mbits, batch, device_ids):
    status, out = circuit.device_call(
        "fused-crc", jax.shard_map(fn, mesh=None, in_specs=(),
                                   out_specs=()), mbits,
        batch=len(batch), devices=device_ids)
    return out if status == "ok" else None

"""Clean twin: the sync KV commit (the durability point) precedes the
on_commit callbacks, so an ack implies the transaction survives a
power cut."""

import os


class DurableStore:
    def __init__(self, kv, block):
        self._kv = kv
        self._block = block

    def queue_transaction(self, txn):
        kvt = self._kv.get_transaction()
        for op in txn.ops:
            kvt.add(op)
        self._block.flush()
        os.fsync(self._block.fileno())
        self._kv.submit_transaction_sync(kvt)
        for cb in txn.on_commit:
            cb()

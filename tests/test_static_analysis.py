"""Tier-1 gate for ceph_tpu.analysis: the whole package must be clean
or baselined, the CLI exit-code contract must hold, and the two
RUNTIME⊆STATIC cross-checks must hold — every lock order the runtime
detector observed this session must be explained by the static order
graph (rule lock-order), and every await site the deterministic-
interleaving explorer drives a cluster through must exist in the
static async-context map with its lock claims honoured.
"""

from __future__ import annotations

import asyncio
import contextlib
import io
import json
import os
import subprocess
import sys

import pytest

import ceph_tpu
from ceph_tpu.analysis import (
    analyze_paths, build_lock_graph, default_baseline_path,
    default_rules, load_baseline,
)
from ceph_tpu.analysis import cache as lint_cache
from ceph_tpu.analysis import interleave
from ceph_tpu.analysis.__main__ import main as lint_main
from ceph_tpu.analysis.callgraph import await_site_map
from ceph_tpu.analysis.findings import Finding, gating
from ceph_tpu.common import lockdep

from cluster_helpers import Cluster

PKG = os.path.dirname(os.path.abspath(ceph_tpu.__file__))

# Runtime-observed lock-order edges accepted WITHOUT a static-graph
# witness, each with its justification (the "baselined against" escape
# for dynamic dispatch the AST pass cannot see).  Keep empty unless a
# test demonstrably exercises such a path.
#
# The (osd.clslock, osd.objlock) edge that used to live here — cls
# methods dispatched through a function value re-entering the object
# lock — is now WITNESSED statically: the coded-compute engine's
# full-decode fallback (osd/compute.py _wave_fallback) takes the same
# order in plain nested `async with` blocks the lock-graph pass reads
# directly.  Dynamic-dispatch edges should follow that pattern (a
# statically visible taker of the same order) rather than growing
# this baseline.
RUNTIME_EDGE_BASELINE: dict = {}


@pytest.fixture(scope="module")
def package_analysis():
    """One shared full-package pass (it costs seconds, not millis)."""
    return analyze_paths([PKG])


def test_package_clean_or_baselined(package_analysis):
    findings, _ = package_analysis
    path = default_baseline_path()
    baseline = load_baseline(path) if path else None
    # info findings are advisory worklists (hot-path-copy), not gates
    new = [f for f in gating(findings)
           if baseline is None or f not in baseline]
    assert not new, (
        "new static-analysis findings (fix, suppress inline, or "
        "baseline with a justification via --write-baseline):\n"
        + "\n".join(f.render() for f in new))


def test_baseline_entries_live_and_justified(package_analysis):
    """Ratchet hygiene: no stale entries (fixed findings must leave
    the baseline) and every accepted finding carries a reason."""
    findings, _ = package_analysis
    path = default_baseline_path()
    assert path, "tools/lint_baseline.json missing"
    baseline = load_baseline(path)
    stale = baseline.stale(gating(findings))
    assert not stale, f"stale baseline entries: {stale}"
    for entry in baseline.entries.values():
        assert entry.get("justification", "").strip(), (
            f"baseline entry without justification: {entry}")


def test_cli_exit_codes(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    viol = tmp_path / "viol.py"
    viol.write_text(
        "import time\n\n\nasync def tick():\n    time.sleep(1)\n")
    assert lint_main([str(clean)]) == 0
    assert lint_main([str(viol), "--no-baseline"]) == 1
    assert lint_main(["--rules", "no-such-rule", str(clean)]) == 2
    assert lint_main([str(tmp_path / "missing.py")]) == 2


def test_cli_module_invocation(tmp_path):
    """`python -m ceph_tpu.analysis` is the standalone CI gate."""
    viol = tmp_path / "viol.py"
    viol.write_text(
        "import time\n\n\nasync def tick():\n    time.sleep(1)\n")
    r = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.analysis", str(viol),
         "--no-baseline"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 1
    assert "async-blocking" in r.stdout


def test_runtime_lock_edges_subset_of_static(package_analysis):
    """Every order edge the runtime detector recorded so far this
    session must be in the static graph (or the edge baseline): the
    AST pass over-approximates the runtime, never the reverse."""
    _, project = package_analysis
    adj, _ = build_lock_graph(project)

    # drive one known static edge through the runtime detector so the
    # subset check can never pass vacuously
    async def nest():
        a = lockdep.Lock("mds.mutation")
        b = lockdep.Lock("mds.caps")
        async with a:
            async with b:
                pass

    was = lockdep.enabled
    lockdep.enabled = True
    try:
        asyncio.run(nest())
    finally:
        lockdep.enabled = was
    assert "mds.caps" in lockdep._edges.get("mds.mutation", set())

    unexplained = [
        (src, dst)
        for src, dsts in lockdep._edges.items()
        for dst in dsts
        if dst not in adj.get(src, set())
        and (src, dst) not in RUNTIME_EDGE_BASELINE]
    assert not unexplained, (
        f"runtime lock-order edges missing from the static graph "
        f"(teach ceph_tpu/analysis/lockgraph.py to see them, or "
        f"baseline with a justification): {unexplained}")


# -- hot-path-copy worklist (ROADMAP item 2) ---------------------------


def test_hot_path_copy_worklist_enumerates_the_data_path(
        package_analysis):
    """The rule's finding list IS the zero-copy worklist: it must be
    non-empty, advisory (info severity — never a gate failure), and
    still name the osd/ec layers' remaining copies.  The msg layer is
    CLEAN as of the PR-12 zero-copy pass — frame reassembly through
    message decode hands out views — and must stay that way (the
    per-file ratchet below pins it to zero)."""
    findings, _ = package_analysis
    worklist = [f for f in findings if f.rule == "hot-path-copy"]
    assert len(worklist) >= 1
    assert all(f.severity == "info" for f in worklist)
    assert not gating(worklist)
    layers = {f.path.split("/")[1] for f in worklist}
    assert {"osd", "ec"} <= layers
    assert "msg" not in layers


def test_copy_ratchet_holds(package_analysis):
    """CI gate for the zero-copy worklist: the finding count must not
    exceed tools/copy_ratchet.json's ceilings — eliminated copy sites
    cannot silently come back.  Retiring more sites?  LOWER the
    ratchet in the same PR."""
    from collections import Counter

    with open(os.path.join(os.path.dirname(PKG), "tools",
                           "copy_ratchet.json")) as fh:
        ratchet = json.load(fh)
    findings, _ = package_analysis
    worklist = [f for f in findings if f.rule == "hot-path-copy"]
    assert len(worklist) <= ratchet["max_sites"], (
        f"hot-path-copy sites grew to {len(worklist)} > ratchet "
        f"{ratchet['max_sites']}: convert the new site to a view "
        "(memoryview/StridedBuf), or suppress it with a justified "
        "`# lint: disable=hot-path-copy` if the copy is required")
    by_file = Counter(f.path for f in worklist)
    for path, cap in ratchet["max_by_file"].items():
        assert by_file.get(path, 0) <= cap, (
            f"{path}: {by_file.get(path, 0)} hot-path-copy sites > "
            f"ratchet {cap} — this file was converted to zero-copy "
            "views; keep it that way")


def test_hot_path_copy_rule_recognizes_views(package_analysis):
    """The rule must NOT flag slices of names bound to a view
    constructor (memoryview/StridedBuf/.toreadonly()/.bytes_view()):
    those slices are zero-copy — exactly the discipline the worklist
    prescribes — and re-flagging them would re-list every converted
    site forever.  The ok-fixture's `data = memoryview(...)` slice
    exercises this; the package-level proof is the msg layer staying
    at zero findings while slicing views everywhere."""
    findings, _ = package_analysis
    worklist = [f for f in findings if f.rule == "hot-path-copy"]
    assert not [f for f in worklist
                if f.path.startswith("ceph_tpu/msg/")]


# -- CLI: --format=json round-trip, --hot-path-report, cache -----------


def _capture_cli(argv):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = lint_main(argv)
    return rc, buf.getvalue()


def test_format_json_round_trips(tmp_path):
    viol = tmp_path / "viol.py"
    viol.write_text(
        "import time\n\n\nasync def tick():\n    time.sleep(1)\n")
    rc, out = _capture_cli([str(viol), "--no-baseline", "--no-cache",
                            "--format", "json"])
    assert rc == 1
    records = json.loads(out)
    assert records
    for rec in records:
        assert {"path", "line", "col", "rule", "fingerprint",
                "severity", "message", "symbol", "text"} <= set(rec)
    # records reconstruct bit-for-bit into the Findings the library
    # API produces — CI annotation sees exactly what the gate saw
    findings, _ = analyze_paths([str(viol)])
    assert sorted(Finding(**r).as_dict().items() for r in records) == \
        sorted(f.as_dict().items() for f in findings)


def test_hot_path_report_lists_worklist_and_exits_zero(tmp_path):
    viol = tmp_path / "copy.py"
    viol.write_text("def f(payload):\n    return bytes(payload)\n")
    rc, out = _capture_cli(
        [str(viol), "--no-cache", "--hot-path-report",
         "--format", "json"])
    assert rc == 0
    records = json.loads(out)
    # scoped to the production hot path by default: a random file is
    # not on the worklist...
    assert records == []
    # ...but the package IS (count asserted >= 1: the ROADMAP item 2
    # worklist the CLI hands to the zero-copy PR).  osd/, not msg/:
    # the msg layer went to ZERO findings in the PR-12 conversion and
    # the ratchet keeps it there
    pkg_dir = os.path.dirname(os.path.abspath(ceph_tpu.__file__))
    rc, out = _capture_cli([os.path.join(pkg_dir, "osd"), "--no-cache",
                            "--hot-path-report", "--format", "json"])
    assert rc == 0
    records = json.loads(out)
    assert len(records) >= 1
    assert all(r["rule"] == "hot-path-copy" for r in records)


def test_cache_replays_only_bit_identical_trees(tmp_path):
    src = tmp_path / "mod.py"
    src.write_text(
        "import time\n\n\nasync def tick():\n    time.sleep(1)\n")
    files = lint_cache.scan_hashes([str(src)])
    findings, _ = analyze_paths([str(src)])
    cpath = str(tmp_path / ".lint_cache.json")
    rule_names = sorted(default_rules())
    lint_cache.save(cpath, files, rule_names, findings)

    replayed, changed = lint_cache.load(cpath, files, rule_names)
    assert changed == []
    assert [f.as_dict() for f in replayed] == \
        [f.as_dict() for f in findings]

    # an edit invalidates the whole result (interprocedural rules can
    # move findings across modules) and names the changed file
    src.write_text(src.read_text() + "# edited\n")
    files2 = lint_cache.scan_hashes([str(src)])
    replayed2, changed2 = lint_cache.load(cpath, files2, rule_names)
    assert replayed2 is None
    assert changed2 == [os.path.abspath(str(src))]

    # a different rule subset is a structural miss
    replayed3, _ = lint_cache.load(cpath, files, ["async-blocking"])
    assert replayed3 is None


def test_cache_ruleset_entries_are_independent(tmp_path):
    """The cache is keyed by the active rule-set hash: a `--rules`
    subset run stores under its own entry and must neither poison nor
    evict the full gate's (the PR-11 poisoning fix, extended)."""
    src = tmp_path / "mod.py"
    src.write_text(
        "import time\n\n\nasync def tick():\n    time.sleep(1)\n")
    files = lint_cache.scan_hashes([str(src)])
    cpath = str(tmp_path / ".lint_cache.json")
    full_rules = sorted(default_rules())
    full, _ = analyze_paths([str(src)])
    lint_cache.save(cpath, files, full_rules, full)

    # subset run: its own findings under its own entry...
    subset, _ = analyze_paths([str(src)], rules=["async-blocking"])
    lint_cache.save(cpath, files, ["async-blocking"], subset)
    re_sub, _ = lint_cache.load(cpath, files, ["async-blocking"])
    assert [f.as_dict() for f in re_sub] == \
        [f.as_dict() for f in subset]
    # ...and the full entry survives the subset save untouched
    re_full, changed = lint_cache.load(cpath, files, full_rules)
    assert changed == []
    assert [f.as_dict() for f in re_full] == \
        [f.as_dict() for f in full]


def test_cli_cache_scope_and_no_cache_flag(tmp_path, monkeypatch):
    """The cache serves the default whole-package gate invocation:
    explicit path subsets never touch it (they would evict the warm
    whole-tree entry), and --no-cache bypasses it entirely."""
    import ceph_tpu.analysis.__main__ as cli
    viol = tmp_path / "viol.py"
    viol.write_text(
        "import time\n\n\nasync def tick():\n    time.sleep(1)\n")
    cpath = tmp_path / ".lint_cache.json"
    monkeypatch.setattr(lint_cache, "default_cache_path",
                        lambda: str(cpath))
    # explicit path: no cache involvement
    rc, _ = _capture_cli([str(viol), "--no-baseline"])
    assert rc == 1
    assert not cpath.exists()
    # default-path run (monkeypatched to the tmp file): writes it...
    monkeypatch.setattr(cli, "_default_paths", lambda: [str(viol)])
    rc, _ = _capture_cli(["--no-baseline"])
    assert rc == 1
    assert cpath.exists()
    # ...and a warm rerun replays it to the same verdict
    rc, _ = _capture_cli(["--no-baseline"])
    assert rc == 1
    # --no-cache neither reads nor writes
    cpath.unlink()
    rc, _ = _capture_cli(["--no-baseline", "--no-cache"])
    assert rc == 1
    assert not cpath.exists()


# -- deterministic-interleaving explorer: runtime ⊆ static -------------

# Observed await sites accepted WITHOUT a static-map witness, each
# with its justification (the escape hatch for coroutine shapes the
# AST async-context pass cannot see).  Keep empty unless a scenario
# demonstrably drives such a site.
RUNTIME_SITE_BASELINE: dict = {}


async def _interleave_scenario():
    """A real cluster workload with genuine task contention: mon + 3
    OSDs over loopback msgr, concurrent client writes and reads.  Any
    client-visible error fails the test — the zero-client-error
    invariant under every explored schedule."""
    cluster = Cluster(num_osds=3)
    await cluster.start()
    try:
        await cluster.client.create_replicated_pool(
            "ilv", size=2, pg_num=4)
        ioctx = cluster.client.open_ioctx("ilv")
        payloads = {f"obj-{i}": bytes([65 + i]) * (4096 + i)
                    for i in range(6)}
        await asyncio.gather(*(ioctx.write_full(name, data)
                               for name, data in payloads.items()))
        reads = await asyncio.gather(*(ioctx.read(name)
                                       for name in payloads))
        assert list(reads) == list(payloads.values())
    finally:
        await cluster.stop()


@pytest.fixture(scope="module")
def static_await_sites(package_analysis):
    _, project = package_analysis
    return await_site_map(project)


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_interleaved_cluster_runtime_subset_of_static(
        seed, static_await_sites):
    """Drive the cluster through seeded wakeup-order permutations and
    cross-check runtime ⊆ static: every (file, line) a task was
    actually suspended at must be a suspension point in the analyzer's
    async-context map, and where the map claims a lockdep class is
    held at that point, the runtime held-stack must agree — otherwise
    the atomicity verdicts rest on a map that is blind to real
    schedules."""
    interleave.clear_records()
    was = lockdep.enabled
    lockdep.enabled = True
    try:
        with interleave.explore(seed=seed):
            asyncio.run(asyncio.wait_for(_interleave_scenario(), 120))
    finally:
        lockdep.enabled = was
    records = interleave.records()
    sites = interleave.await_sites()
    # non-vacuous: the permuted schedules really drove package code
    # (a site is recorded only when >=2 task wakeups were ready in the
    # same loop iteration — genuine contention, not mere activity)
    assert len(sites) >= 5, f"explorer observed only {sites}"

    unexplained = sorted(
        s for s in sites
        if s not in static_await_sites
        and s not in RUNTIME_SITE_BASELINE)
    assert not unexplained, (
        "await sites observed at runtime but absent from the static "
        "async-context map (callgraph.py is blind to these):\n"
        + "\n".join(f"  {p}:{ln}" for p, ln in unexplained))

    lock_violations = []
    for r in records:
        info = static_await_sites.get((r.path, r.line))
        if info is None:
            continue
        claimed = info["locks"]
        if claimed and not claimed <= set(r.locks):
            lock_violations.append(
                (r.path, r.line, sorted(claimed), list(r.locks)))
    assert not lock_violations, (
        "static lock claims not honoured at runtime: "
        f"{lock_violations[:5]}")


# -- SPMD collective-safety: site map + baselined-finding ratchet ------

SPMD_RULES = {"divergent-collective", "collective-order",
              "unguarded-collective-timeout", "topology-stale-state"}


def test_unscheduled_xor_rule_covers_osd_data_path(tmp_path):
    """The unscheduled-bitmatrix-xor rule gates the OSD data path,
    not just ec/: a naive XOR row-walk under ceph_tpu/osd/ must fire
    (the native fused tape is the hot small-op band), while
    osdmap.py's scalar state-flag XORs stay exempt."""
    pkg = tmp_path / "ceph_tpu"
    osd = pkg / "osd"
    osd.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (osd / "__init__.py").write_text("")
    (osd / "naive.py").write_text(
        "import numpy as np\n\n\n"
        "def fold(rows, srcs, acc):\n"
        "    for r in rows:\n"
        "        acc[:] ^= srcs[r]\n"
        "    return acc\n")
    (osd / "osdmap.py").write_text(
        "def apply_inc(state, inc):\n"
        "    for osd, bits in inc.items():\n"
        "        state[osd] ^= bits\n"
        "    return state\n")
    findings, _ = analyze_paths(
        [str(osd / "naive.py"), str(osd / "osdmap.py")],
        rules=["unscheduled-bitmatrix-xor"])
    hits = {(f.path, f.rule) for f in findings}
    assert hits == {("ceph_tpu/osd/naive.py",
                     "unscheduled-bitmatrix-xor")}, hits


def test_collective_site_map_covers_the_seam(package_analysis):
    """The static collective-site map must see the cross-process
    plane: the agreement seam in ec/plan.py, the data collectives
    (put_global/gather), and the in-tree shard_map lax collective —
    an empty or partial map would make every runtime ⊆ static
    cross-check vacuously green."""
    from ceph_tpu.analysis.collective import (
        collect_sites, collective_site_map)

    _, project = package_analysis
    sites = collect_sites(project)
    kinds = {s.kind for s in sites}
    assert {"agreement", "put-global", "gather", "kv-wait",
            "collective"} <= kinds, kinds
    by_file = {s.mod.relpath.replace("\\", "/") for s in sites}
    assert "ceph_tpu/ec/plan.py" in by_file
    assert "ceph_tpu/parallel/multihost.py" in by_file
    smap = collective_site_map(project)
    assert len(smap) >= len(sites)
    # multi-line call spans key every covered line (a runtime frame's
    # f_lineno can land anywhere inside the call): the agree() call
    # inside agree_healthy spans several lines and every one of them
    # must map back to that one agreement site
    span = [s for s in sites
            if s.callee.endswith("multihost.agree")
            and s.end_line > s.line]
    assert span, "expected a multi-line agree() call in the seam"
    rel = span[0].mod.relpath.replace("\\", "/")
    for line in range(span[0].line, span[0].end_line + 1):
        assert smap[(rel, line)]["kind"] == "agreement", (rel, line)


def test_collective_ratchet_holds(package_analysis):
    """CI gate for the SPMD rules: the count of BASELINED findings
    from the four collective rules must not exceed
    tools/collective_ratchet.json's ceilings (0 at PR-16 enumeration
    time — all three real findings were fixed, not baselined), so
    justified-away divergence hazards cannot silently accumulate as
    the elastic-membership surface (ROADMAP item 1) grows."""
    from collections import Counter

    with open(os.path.join(os.path.dirname(PKG), "tools",
                           "collective_ratchet.json")) as fh:
        ratchet = json.load(fh)
    assert set(ratchet["max_by_rule"]) == SPMD_RULES
    with open(default_baseline_path()) as fh:
        entries = [rec for rec in json.load(fh)["findings"]
                   if rec["rule"] in SPMD_RULES]
    assert len(entries) <= ratchet["max_baselined"], (
        f"baselined SPMD findings grew to {len(entries)} > ratchet "
        f"{ratchet['max_baselined']}: fix the divergence hazard "
        "instead of baselining it (or lower the ratchet when fixing)")
    by_rule = Counter(rec["rule"] for rec in entries)
    for rule, cap in ratchet["max_by_rule"].items():
        assert by_rule.get(rule, 0) <= cap, (
            f"{rule}: {by_rule.get(rule, 0)} baselined findings > "
            f"ratchet {cap}")
    # and the package itself is CURRENTLY clean of live SPMD findings
    findings, _ = package_analysis
    live = [f for f in findings if f.rule in SPMD_RULES]
    assert not live, [f.render() for f in live]

"""Tier-1 gate for ceph_tpu.analysis: the whole package must be clean
or baselined, the CLI exit-code contract must hold, and every lock
order the RUNTIME detector observed during this test session must be
explained by the STATIC order graph (rule lock-order) — the
lint-time/run-time cross-check of the lockdep discipline.
"""

from __future__ import annotations

import asyncio
import os
import subprocess
import sys

import pytest

import ceph_tpu
from ceph_tpu.analysis import (
    analyze_paths, build_lock_graph, default_baseline_path,
    load_baseline,
)
from ceph_tpu.analysis.__main__ import main as lint_main
from ceph_tpu.common import lockdep

PKG = os.path.dirname(os.path.abspath(ceph_tpu.__file__))

# Runtime-observed lock-order edges accepted WITHOUT a static-graph
# witness, each with its justification (the "baselined against" escape
# for dynamic dispatch the AST pass cannot see).  Keep empty unless a
# test demonstrably exercises such a path.
RUNTIME_EDGE_BASELINE: dict = {
    ("osd.clslock", "osd.objlock"):
        "_op_call holds the cls lock and invokes the registered cls "
        "method through a function value (`fn(ctx, data)`); the method "
        "body re-enters _op_write_full/_op_remove which take the "
        "object lock.  The registry indirection is invisible to the "
        "AST call resolver; order is safe — no path takes objlock "
        "then clslock (exec is only reachable from the op dispatcher).",
}


@pytest.fixture(scope="module")
def package_analysis():
    """One shared full-package pass (it costs seconds, not millis)."""
    return analyze_paths([PKG])


def test_package_clean_or_baselined(package_analysis):
    findings, _ = package_analysis
    path = default_baseline_path()
    baseline = load_baseline(path) if path else None
    new = [f for f in findings
           if baseline is None or f not in baseline]
    assert not new, (
        "new static-analysis findings (fix, suppress inline, or "
        "baseline with a justification via --write-baseline):\n"
        + "\n".join(f.render() for f in new))


def test_baseline_entries_live_and_justified(package_analysis):
    """Ratchet hygiene: no stale entries (fixed findings must leave
    the baseline) and every accepted finding carries a reason."""
    findings, _ = package_analysis
    path = default_baseline_path()
    assert path, "tools/lint_baseline.json missing"
    baseline = load_baseline(path)
    stale = baseline.stale(findings)
    assert not stale, f"stale baseline entries: {stale}"
    for entry in baseline.entries.values():
        assert entry.get("justification", "").strip(), (
            f"baseline entry without justification: {entry}")


def test_cli_exit_codes(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    viol = tmp_path / "viol.py"
    viol.write_text(
        "import time\n\n\nasync def tick():\n    time.sleep(1)\n")
    assert lint_main([str(clean)]) == 0
    assert lint_main([str(viol), "--no-baseline"]) == 1
    assert lint_main(["--rules", "no-such-rule", str(clean)]) == 2
    assert lint_main([str(tmp_path / "missing.py")]) == 2


def test_cli_module_invocation(tmp_path):
    """`python -m ceph_tpu.analysis` is the standalone CI gate."""
    viol = tmp_path / "viol.py"
    viol.write_text(
        "import time\n\n\nasync def tick():\n    time.sleep(1)\n")
    r = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.analysis", str(viol),
         "--no-baseline"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 1
    assert "async-blocking" in r.stdout


def test_runtime_lock_edges_subset_of_static(package_analysis):
    """Every order edge the runtime detector recorded so far this
    session must be in the static graph (or the edge baseline): the
    AST pass over-approximates the runtime, never the reverse."""
    _, project = package_analysis
    adj, _ = build_lock_graph(project)

    # drive one known static edge through the runtime detector so the
    # subset check can never pass vacuously
    async def nest():
        a = lockdep.Lock("mds.mutation")
        b = lockdep.Lock("mds.caps")
        async with a:
            async with b:
                pass

    was = lockdep.enabled
    lockdep.enabled = True
    try:
        asyncio.run(nest())
    finally:
        lockdep.enabled = was
    assert "mds.caps" in lockdep._edges.get("mds.mutation", set())

    unexplained = [
        (src, dst)
        for src, dsts in lockdep._edges.items()
        for dst in dsts
        if dst not in adj.get(src, set())
        and (src, dst) not in RUNTIME_EDGE_BASELINE]
    assert not unexplained, (
        f"runtime lock-order edges missing from the static graph "
        f"(teach ceph_tpu/analysis/lockgraph.py to see them, or "
        f"baseline with a justification): {unexplained}")

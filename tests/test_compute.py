"""Coded-compute unit tier: kernel/code commutation, ragged batches,
device-fault degradation, the `compute` plan kind, and the wire types.

The load-bearing property (ceph_tpu/compute): for every registered
LINEAR kernel, evaluating on ANY k of the k+m coded shards and
decoding in the RESULT DOMAIN is bit-exact with decode-then-compute
on the host — across (k, m) shapes, ragged object sizes, and with the
device tier scripted to fail (host fallback stays bit-exact).
"""

from __future__ import annotations

import itertools
import json
import os

import numpy as np
import pytest

import conftest
from ceph_tpu import compute as compute_mod
from ceph_tpu.compute import kernels as ck
from ceph_tpu.ec.registry import create_erasure_code
from ceph_tpu.osd import ec_util

SHAPES = [(2, 1), (3, 2), (4, 2), (6, 3)]
# ragged object sizes: sub-chunk, unaligned, multi-stripe
SIZES = [1, 100, 4096, 3 * 4096 + 123, 8 * 4096 + 1]


def _codec_and_sinfo(k: int, m: int):
    codec = create_erasure_code({
        "plugin": "ec_jax", "technique": "reed_sol_van",
        "k": str(k), "m": str(m)})
    unit = codec.get_chunk_size(k * 4096)
    return codec, ec_util.StripeInfo(k, k * unit)


def _encode_object(codec, sinfo, data: bytes):
    padded = data + bytes(-len(data) % sinfo.get_stripe_width())
    return ec_util.encode(sinfo, codec, padded,
                          range(codec.get_chunk_count()))


def _result_decode(kern, codec, k: int, chosen):
    """First-k result-domain decode + object-level combine — the
    engine's math (osd/compute.py), inlined for the oracle check."""
    rsinfo = ec_util.StripeInfo(k, k * kern.lanes)
    dec = bytes(ec_util.decode(rsinfo, codec, chosen))
    return kern.combine([dec[i * kern.lanes:(i + 1) * kern.lanes]
                         for i in range(k)])


@pytest.mark.parametrize("k,m", SHAPES)
@pytest.mark.parametrize("name", ["gf_fold", "gf_fingerprint"])
def test_linear_kernels_commute_first_k(k, m, name):
    """Bit-exactness of the pushdown across EVERY k-subset of the
    coded shards (parity-only subsets included) vs the host oracle
    on the logical bytes."""
    kern = compute_mod.get_kernel(name)
    assert kern is not None and kern.linear
    codec, sinfo = _codec_and_sinfo(k, m)
    assert codec.supports_result_decode()
    rng = np.random.default_rng(17 * k + m)
    data = rng.integers(0, 256, 2 * sinfo.get_stripe_width() + 321,
                        dtype=np.uint8).tobytes()
    shards = _encode_object(codec, sinfo, data)
    ref = bytes(kern.reference(data, {}, k=k,
                               chunk=sinfo.get_chunk_size()))
    subsets = list(itertools.combinations(
        range(codec.get_chunk_count()), k))
    for chosen_ids in subsets:
        results = compute_mod.shard_eval_batch(
            kern, [shards[i] for i in chosen_ids], {})
        got = _result_decode(
            kern, codec, k,
            {i: r for i, r in zip(chosen_ids, results)})
        assert bytes(got) == ref, (name, k, m, chosen_ids)


@pytest.mark.parametrize("name", ["gf_fold", "gf_fingerprint"])
def test_linear_kernels_ragged_sizes(name):
    """Ragged batches: objects of every size class evaluate in one
    shard_eval_batch call and each matches its per-stream oracle —
    and the zero pad is invariant (a padded object folds identically
    to its unpadded self)."""
    kern = compute_mod.get_kernel(name)
    k, m = 3, 2
    codec, sinfo = _codec_and_sinfo(k, m)
    rng = np.random.default_rng(5)
    streams = []
    for size in SIZES:
        data = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
        shards = _encode_object(codec, sinfo, data)
        streams.extend(shards[i] for i in range(k + m))
    batched = compute_mod.shard_eval_batch(kern, streams, {})
    for stream, got in zip(streams, batched):
        assert bytes(got) == bytes(kern.eval_stream(stream))
    # pad invariance: trailing zeros change nothing
    data = rng.integers(0, 256, 1000, dtype=np.uint8).tobytes()
    assert bytes(kern.eval_stream(data)) == \
        bytes(kern.eval_stream(data + bytes(64)))


@pytest.mark.parametrize("k,m", [(2, 2), (4, 2)])
@pytest.mark.parametrize("name", ["gf_fold", "gf_fingerprint"])
def test_commutation_under_device_failure(k, m, name, monkeypatch):
    """CEPH_TPU_INJECT_DEVICE_FAIL forces every device dispatch to
    fail: the planned path degrades to the numpy host tier and the
    first-k result-domain decode stays bit-exact (no exception ever
    reaches the scan)."""
    from ceph_tpu.common import circuit

    kern = compute_mod.get_kernel(name)
    codec, sinfo = _codec_and_sinfo(k, m)
    rng = np.random.default_rng(23)
    data = rng.integers(0, 256, sinfo.get_stripe_width() + 17,
                        dtype=np.uint8).tobytes()
    shards = _encode_object(codec, sinfo, data)
    ref = bytes(kern.reference(data, {}, k=k,
                               chunk=sinfo.get_chunk_size()))
    monkeypatch.setenv("CEPH_TPU_INJECT_DEVICE_FAIL", "1.0")
    circuit.reset_all()
    try:
        chosen_ids = tuple(range(m, k + m))  # parity-heavy subset
        results = compute_mod.shard_eval_batch(
            kern, [shards[i] for i in chosen_ids], {})
        got = _result_decode(
            kern, codec, k,
            {i: r for i, r in zip(chosen_ids, results)})
        assert bytes(got) == ref
    finally:
        monkeypatch.delenv("CEPH_TPU_INJECT_DEVICE_FAIL")
        circuit.reset_all()


@pytest.mark.skipif(conftest.DEVICE_INJECTION,
                    reason="device dispatches scripted to fail")
def test_compute_plan_kind_is_cached():
    """The `compute` plan kind rides the ExecPlan cache: a repeated
    same-geometry wave HITS instead of recompiling, and dispatches
    land in plan.stats() under the compute label."""
    from ceph_tpu.ec import plan as ec_plan
    from ceph_tpu.ops import gf

    if not gf.backend_available():
        pytest.skip("no jax backend")
    kern = compute_mod.get_kernel("gf_fold")
    rng = np.random.default_rng(3)
    batch = rng.integers(0, 256, (4, 128, kern.lanes),
                         dtype=np.uint8)
    weights = kern.row_weights(128)
    first = ec_plan.compute_eval("gf_fold", weights, batch)
    assert first is not None
    before = ec_plan.stats()["hits"]
    second = ec_plan.compute_eval("gf_fold", weights, batch)
    assert second is not None
    assert np.array_equal(first, second)
    assert ec_plan.stats()["hits"] > before
    assert np.array_equal(
        np.asarray(first), np.asarray(ck.host_eval(weights, batch)))
    assert any("compute[" in label
               for label in ec_plan.stats()["per_plan"])


def test_registry_has_the_advertised_kernel_set():
    kernels = compute_mod.registered_kernels()
    linear = {n for n, kn in kernels.items() if kn.linear}
    assert linear == {"gf_fold", "gf_fingerprint"}
    assert {"count", "sum", "min", "max", "filter",
            "compress_score", "dot_score"} <= set(kernels)


def test_record_aggregates_match_python_oracle():
    rng = np.random.default_rng(11)
    vals = rng.integers(0, 1 << 32, 500, dtype=np.uint64)
    data = vals.astype("<u8").tobytes() + b"tail"  # ragged tail
    args = {"record": 8, "off": 0, "len": 8, "cmp": "lt",
            "value": 1 << 31}
    hits = [int(v) for v in vals if int(v) < (1 << 31)]
    count = json.loads(compute_mod.get_kernel("count").eval_object(
        data, args))
    assert count == {"count": len(hits)}
    total = json.loads(compute_mod.get_kernel("sum").eval_object(
        data, args))
    assert total == {"count": len(hits), "sum": sum(hits)}
    lo = json.loads(compute_mod.get_kernel("min").eval_object(
        data, args))
    assert lo == {"count": len(hits), "min": min(hits)}
    hi = json.loads(compute_mod.get_kernel("max").eval_object(
        data, args))
    assert hi == {"count": len(hits), "max": max(hits)}
    flt = json.loads(compute_mod.get_kernel("filter").eval_object(
        data, {**args, "limit": 7}))
    oracle_idx = [i for i, v in enumerate(vals)
                  if int(v) < (1 << 31)]
    assert flt["count"] == len(oracle_idx)
    assert flt["indices"] == oracle_idx[:7]


def test_record_aggregate_empty_and_bad_args():
    kern = compute_mod.get_kernel("min")
    assert json.loads(kern.eval_object(b"", {"record": 8})) == \
        {"count": 0, "min": None}
    with pytest.raises(compute_mod.ComputeError):
        kern.eval_object(b"x" * 16, {"record": 8, "off": 4,
                                     "len": 8})


def test_malformed_wire_args_surface_as_einval():
    """Args arrive off the wire as client JSON: null/string/negative/
    huge values must come back as ComputeError(EINVAL) — never a
    TypeError that the engine logs as an EIO or that crashes the
    client-side parity path."""
    kern = compute_mod.get_kernel("count")
    for bad in ({"record": None}, {"record": "x"},
                {"record": 1 << 70},
                {"cmp": "lt", "value": -1},
                {"cmp": "lt", "value": None}):
        with pytest.raises(compute_mod.ComputeError) as ei:
            kern.eval_object(b"\x00" * 64, bad)
        assert ei.value.rc == -22
    dot = compute_mod.get_kernel("dot_score")
    with pytest.raises(compute_mod.ComputeError):
        dot.eval_object(b"\x00" * 64,
                        {"dim": 4, "query": ["a", "b", "c", "d"]})
    with pytest.raises(compute_mod.ComputeError):
        dot.validate_args({"dim": None, "query": []})


def test_compress_score_orders_entropy():
    kern = compute_mod.get_kernel("compress_score")
    rng = np.random.default_rng(2)
    noisy = json.loads(kern.eval_object(
        rng.integers(0, 256, 16384, dtype=np.uint8).tobytes(), {}))
    flat = json.loads(kern.eval_object(b"\x00" * 16384, {}))
    assert noisy["entropy_bpb"] > 7.5
    assert flat["entropy_bpb"] == 0.0


def test_dot_score_finds_best_embedding():
    kern = compute_mod.get_kernel("dot_score")
    emb = np.zeros((5, 4), dtype=np.float32)
    emb[3] = [1.0, 2.0, 3.0, 4.0]
    out = json.loads(kern.eval_object(
        emb.tobytes(), {"dim": 4, "query": [1.0, 1.0, 1.0, 1.0]}))
    assert out["best"] == 3 and out["n"] == 5
    with pytest.raises(compute_mod.ComputeError):
        kern.validate_args({"dim": 4, "query": [1.0]})


def test_unsupported_codecs_are_gated_out():
    """Codecs outside the commutation gate must answer False — the
    engine routes them to the full-decode fallback instead of
    producing silently wrong result-domain decodes."""
    lrc = create_erasure_code({
        "plugin": "lrc", "k": "4", "m": "2", "l": "3"})
    fn = getattr(lrc, "supports_result_decode", None)
    assert fn is None or not fn()
    cauchy = create_erasure_code({
        "plugin": "ec_jax", "technique": "cauchy_good",
        "k": "4", "m": "2"})
    assert not cauchy.supports_result_decode()


def test_compute_wire_messages_round_trip():
    """The four MOSDCompute-family messages survive encode/decode
    with the versioned-struct discipline."""
    from ceph_tpu.msg.messages import (
        MOSDCompute, MOSDComputeReply, MOSDSubCompute,
        MOSDSubComputeReply, decode_message,
    )

    op = MOSDCompute(7, "client.x", 3, ["a", "b"], "gf_fold",
                     '{"x":1}', epoch=9, tenant="t1")
    back = decode_message(MOSDCompute.TAG, op.encode())
    assert (back.tid, back.client, back.pool, back.oids,
            back.kernel, back.args, back.epoch, back.tenant) == \
        (7, "client.x", 3, ["a", "b"], "gf_fold", '{"x":1}', 9, "t1")

    rep = MOSDComputeReply(7, 0, {"a": (0, b"\x01" * 32),
                                  "b": (-2, b"")},
                           {"pushdown": 1}, replay_epoch=4)
    back = decode_message(MOSDComputeReply.TAG, rep.encode())
    assert back.results["a"] == (0, b"\x01" * 32)
    assert back.results["b"] == (-2, b"")
    assert back.out == {"pushdown": 1} and back.replay_epoch == 4

    sub = MOSDSubCompute(8, "gf_fold", "", [(3, 5, 1, "a")], epoch=9)
    sub.trace = (123, 456)
    back = decode_message(MOSDSubCompute.TAG, sub.encode())
    assert back.items == [(3, 5, 1, "a")]
    assert back.kernel == "gf_fold" and back.trace == (123, 456)

    srep = MOSDSubComputeReply(8, 0, [(0, "9'4", b"\x02" * 32),
                                      (-2, "", b"")])
    back = decode_message(MOSDSubComputeReply.TAG, srep.encode())
    assert [(rc, v, bytes(r)) for rc, v, r in back.results] == \
        [(0, "9'4", b"\x02" * 32), (-2, "", b"")]


def test_kill_switch_env():
    assert compute_mod.env_enabled()
    os.environ["CEPH_TPU_COMPUTE"] = "0"
    try:
        assert not compute_mod.env_enabled()
    finally:
        del os.environ["CEPH_TPU_COMPUTE"]


def test_cli_scan_verb_parses():
    """The `rados scan` front door: argparse wiring (the live path
    is covered by the cluster tier)."""
    from ceph_tpu.tools import rados as rados_cli

    with pytest.raises(SystemExit):
        rados_cli.main(["-m", "x:1", "scan"])  # kernel required

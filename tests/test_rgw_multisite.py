"""RGW multisite: zone-to-zone replication between two independent
clusters (rgw_data_sync.cc / rgw_sync.cc roles).

1. full sync bootstraps buckets, configs, and objects;
2. incremental sync tails the sharded change log with persisted
   markers (an agent restart resumes, no re-copy);
3. versioned keys replicate with version ids, delete markers, and
   ORDER preserved;
4. active-active (two agents) converges without echoing writes back
   (zone-tagged log entries);
5. applied log entries trim once the peer's position is recorded;
6. bucket deletion propagates.
"""

import asyncio

from cluster_helpers import Cluster

from ceph_tpu.rgw import RGWLite
from ceph_tpu.rgw.gateway import RGWError
from ceph_tpu.rgw.multisite import RGWSyncAgent


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 180))


async def _zone(tag: str) -> tuple:
    cluster = Cluster(num_osds=3)
    await cluster.start()
    await cluster.client.create_replicated_pool("meta", size=2,
                                                pg_num=4)
    await cluster.client.create_replicated_pool("data", size=2,
                                                pg_num=4)
    rgw = RGWLite(cluster.client, "data", "meta",
                  stripe_size=64 * 1024, zone=tag)
    return cluster, rgw


async def _teardown(*zones):
    for cluster, _rgw in zones:
        await cluster.stop()


def test_full_sync_bootstraps_everything():
    async def main():
        za, zb = await _zone("a"), await _zone("b")
        a, b = za[1], zb[1]
        try:
            await a.create_bucket("pics", owner="alice",
                                  acl="public-read")
            await a.put_object("pics", "x.jpg", b"JPGDATA" * 100)
            await a.put_object("pics", "y.jpg", b"other")
            await a.create_bucket("logs")
            await a.put_bucket_lifecycle(
                "logs", [{"expiration_days": 30}])
            agent = RGWSyncAgent(a, b)
            n = await agent.full_sync()
            assert n == 2
            assert sorted(await b.list_buckets()) == ["logs", "pics"]
            assert await b.get_object("pics", "x.jpg") == \
                b"JPGDATA" * 100
            info = await b.get_bucket_acl_info("pics")
            assert info == {"owner": "alice", "acl": "public-read"}
            assert await b.get_bucket_lifecycle("logs") == \
                [{"expiration_days": 30}]
            # idempotent: nothing re-copied
            copied = agent.objects_copied
            await agent.full_sync()
            assert agent.objects_copied == copied
        finally:
            await _teardown(za, zb)
    run(main())


def test_incremental_sync_and_marker_persistence():
    async def main():
        za, zb = await _zone("a"), await _zone("b")
        a, b = za[1], zb[1]
        try:
            await a.create_bucket("bkt")
            agent = RGWSyncAgent(a, b)
            await agent.full_sync()
            await a.put_object("bkt", "one", b"1st")
            await a.put_object("bkt", "two", b"2nd")
            assert await agent.sync_once() > 0
            assert await b.get_object("bkt", "one") == b"1st"
            assert await b.get_object("bkt", "two") == b"2nd"
            # overwrite + delete propagate
            await a.put_object("bkt", "one", b"1st-v2")
            await a.delete_object("bkt", "two")
            await agent.sync_once()
            assert await b.get_object("bkt", "one") == b"1st-v2"
            try:
                await b.get_object("bkt", "two")
                raise AssertionError("delete did not propagate")
            except RGWError as e:
                assert e.code == "NoSuchKey"
            # marker persistence: a FRESH agent applies nothing new
            agent2 = RGWSyncAgent(a, b)
            assert await agent2.sync_once() == 0
            assert agent2.objects_copied == 0
        finally:
            await _teardown(za, zb)
    run(main())


def test_versioned_replication_preserves_ids_and_order():
    async def main():
        za, zb = await _zone("a"), await _zone("b")
        a, b = za[1], zb[1]
        try:
            await a.create_bucket("v")
            await a.put_bucket_versioning("v", "enabled")
            _, v1 = await a.put_object_ex("v", "k", b"gen1")
            _, v2 = await a.put_object_ex("v", "k", b"gen2")
            marker = await a.delete_object("v", "k")
            _, v3 = await a.put_object_ex("v", "k", b"gen3")
            agent = RGWSyncAgent(a, b)
            await agent.full_sync()
            assert await b.get_bucket_versioning("v") == "enabled"
            src_list = await a.list_object_versions("v")
            dst_list = await b.list_object_versions("v")
            assert [(x["version_id"], x["delete_marker"])
                    for x in src_list] == \
                   [(x["version_id"], x["delete_marker"])
                    for x in dst_list]
            assert (await b.get_object_ex("v", "k", v1))[0] == b"gen1"
            assert (await b.get_object_ex("v", "k", v3))[0] == b"gen3"
            assert await b.get_object("v", "k") == b"gen3"
            # incremental: permanent version delete propagates
            await a.delete_object("v", "k", version_id=v2)
            await agent.sync_once()
            ids = {x["version_id"]
                   for x in await b.list_object_versions("v")}
            assert v2 not in ids and v1 in ids and marker in ids
        finally:
            await _teardown(za, zb)
    run(main())


def test_active_active_no_echo():
    async def main():
        za, zb = await _zone("a"), await _zone("b")
        a, b = za[1], zb[1]
        try:
            await a.create_bucket("shared")
            ab = RGWSyncAgent(a, b)
            ba = RGWSyncAgent(b, a)
            await ab.full_sync()
            await ba.full_sync()
            # writes on BOTH sides, different keys
            await a.put_object("shared", "from-a", b"AAA")
            await b.put_object("shared", "from-b", b"BBB")
            for _ in range(3):
                await ab.sync_once()
                await ba.sync_once()
            assert await a.get_object("shared", "from-b") == b"BBB"
            assert await b.get_object("shared", "from-a") == b"AAA"
            # convergence: further rounds apply nothing (no ping-pong)
            applied = ab.entries_applied + ba.entries_applied
            for _ in range(3):
                await ab.sync_once()
                await ba.sync_once()
            assert ab.entries_applied + ba.entries_applied == applied
            assert ab.entries_skipped > 0 or ba.entries_skipped > 0
        finally:
            await _teardown(za, zb)
    run(main())


def test_log_trim_after_apply():
    async def main():
        za, zb = await _zone("a"), await _zone("b")
        a, b = za[1], zb[1]
        try:
            await a.create_bucket("t")
            agent = RGWSyncAgent(a, b)
            await agent.full_sync()
            for i in range(5):
                await a.put_object("t", f"k{i}", b"x" * 10)
            await agent.sync_once()
            trimmed = await agent.trim_source_log()
            assert trimmed >= 5
            # nothing left beyond the markers
            left = 0
            for shard in range(RGWLite.LOG_SHARDS):
                left += len(await a.sync_log_entries(shard))
            assert left == 0
        finally:
            await _teardown(za, zb)
    run(main())


def test_bucket_deletion_propagates():
    async def main():
        za, zb = await _zone("a"), await _zone("b")
        a, b = za[1], zb[1]
        try:
            await a.create_bucket("doomed")
            await a.put_object("doomed", "k", b"bye")
            agent = RGWSyncAgent(a, b)
            await agent.full_sync()
            assert await b.get_object("doomed", "k") == b"bye"
            await a.delete_object("doomed", "k")
            await a.delete_bucket("doomed")
            await agent.sync_once()
            assert "doomed" not in await b.list_buckets()
        finally:
            await _teardown(za, zb)
    run(main())


def test_sync_cli(tmp_path):
    """radosgw-admin sync full/run/trim drives the agent from the
    shell against two live clusters."""
    async def main():
        import subprocess
        import sys

        za, zb = await _zone("east"), await _zone("west")
        a, b = za[1], zb[1]
        try:
            await a.create_bucket("cli-bkt")
            await a.put_object("cli-bkt", "k", b"over the CLI")
            import os
            import pathlib

            repo = pathlib.Path(__file__).resolve().parent.parent
            env = dict(os.environ)
            env["PYTHONPATH"] = str(repo)
            env["JAX_PLATFORMS"] = "cpu"

            async def cli(verb):
                proc = await asyncio.create_subprocess_exec(
                    sys.executable, "-m",
                    "ceph_tpu.tools.radosgw_admin",
                    "-m", za[0].mon.addr, "--data-pool", "data",
                    "--meta-pool", "meta", "sync", verb,
                    "--dest-mon", zb[0].mon.addr,
                    "--zone", "east", "--dest-zone", "west",
                    stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                    env=env)
                out, err = await proc.communicate()
                return proc.returncode, out, err

            rc, out, err = await cli("full")
            assert rc == 0, err
            import json
            assert json.loads(out)["keys_reconciled"] == 1
            assert await b.get_object("cli-bkt", "k") == \
                b"over the CLI"
            await a.put_object("cli-bkt", "k2", b"incremental")
            rc, out, err = await cli("run")
            assert rc == 0, err
            assert await b.get_object("cli-bkt", "k2") == \
                b"incremental"
            rc, out, err = await cli("trim")
            assert rc == 0, err
            assert json.loads(out)["trimmed"] >= 1
        finally:
            await _teardown(za, zb)
    run(main())


def test_continuous_mode():
    async def main():
        za, zb = await _zone("a"), await _zone("b")
        a, b = za[1], zb[1]
        try:
            await a.create_bucket("live")
            agent = RGWSyncAgent(a, b)
            await agent.full_sync()
            await agent.start(interval=0.2)
            try:
                await a.put_object("live", "obj", b"streamed")
                for _ in range(50):
                    await asyncio.sleep(0.2)
                    try:
                        if await b.get_object("live", "obj") == \
                                b"streamed":
                            break
                    except RGWError:
                        pass
                assert await b.get_object("live", "obj") == \
                    b"streamed"
            finally:
                await agent.stop()
        finally:
            await _teardown(za, zb)
    run(main())

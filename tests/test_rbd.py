"""RBD tests: image lifecycle, striped I/O, sparseness, snapshots.

Mirrors the reference's librbd unit shapes
(/root/reference/src/test/librbd/test_librbd.cc: TestLibRBD
CreateAndStat / TestIO / SnapCreate / TestClone read paths) against a
live mini-cluster.
"""

import asyncio

import numpy as np
import pytest

from cluster_helpers import Cluster

from ceph_tpu.rbd import RBD
from ceph_tpu.rados.client import ObjectNotFound, RadosError


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


ORDER = 14  # 16 KiB objects: small enough to stripe in tests


async def _cluster_img(size=200_000):
    cluster = Cluster(num_osds=4)
    await cluster.start()
    await cluster.client.create_replicated_pool("rbd", size=2, pg_num=8)
    ioctx = cluster.client.open_ioctx("rbd")
    rbd = RBD()
    await rbd.create(ioctx, "img", size, order=ORDER)
    img = await rbd.open(ioctx, "img")
    return cluster, ioctx, rbd, img


def test_create_list_stat_remove():
    async def main():
        cluster, ioctx, rbd, img = await _cluster_img()
        try:
            assert await rbd.list(ioctx) == ["img"]
            st = await img.stat()
            assert st["size"] == 200_000
            assert st["obj_size"] == 1 << ORDER
            assert st["num_objs"] == -(-200_000 // (1 << ORDER))
            with pytest.raises(RadosError):
                await rbd.create(ioctx, "img", 1000)   # EEXIST
            await rbd.remove(ioctx, "img")
            assert await rbd.list(ioctx) == []
            with pytest.raises(ObjectNotFound):
                await rbd.open(ioctx, "img")
        finally:
            await cluster.stop()

    run(main())


def test_striped_io_round_trip():
    async def main():
        cluster, ioctx, rbd, img = await _cluster_img()
        try:
            rng = np.random.default_rng(7)
            # a write spanning multiple data objects
            data = rng.integers(0, 256, 50_000, dtype=np.uint8).tobytes()
            off = (1 << ORDER) - 777    # straddles an object boundary
            await img.write(off, data)
            assert await img.read(off, len(data)) == data
            # sparse: untouched ranges read as zeros
            assert await img.read(0, 100) == bytes(100)
            # the data landed striped across multiple rados objects
            objs = [o for o in await ioctx.list_objects()
                    if o.startswith("rbd_data.")]
            assert len(objs) >= 2
            # unaligned overwrite inside one object
            await img.write(off + 100, b"\xff" * 50)
            got = await img.read(off, 200)
            assert got[100:150] == b"\xff" * 50
            assert got[:100] == data[:100]
            # bounds
            with pytest.raises(RadosError):
                await img.write(200_000 - 10, bytes(20))
            assert await img.read(199_990, 100) == \
                (await img.read(199_990, 10))
        finally:
            await cluster.stop()

    run(main())


def test_discard_and_resize():
    async def main():
        cluster, ioctx, rbd, img = await _cluster_img()
        try:
            obj = 1 << ORDER
            await img.write(0, b"\xaa" * (3 * obj))
            # full-object discard returns the object to sparse
            await img.discard(obj, obj)
            assert await img.read(obj, obj) == bytes(obj)
            assert await img.read(0, 16) == b"\xaa" * 16
            # partial discard zeroes in place
            await img.discard(100, 50)
            got = await img.read(0, 200)
            assert got[100:150] == bytes(50)
            assert got[:100] == b"\xaa" * 100
            # shrink then grow: truncated range must come back as zeros
            await img.resize(obj + 100)
            assert img.size() == obj + 100
            await img.resize(3 * obj)
            assert await img.read(obj + 100, 500) == bytes(500)
            # object 1 stays discarded-to-zero; object 0 untouched
            assert await img.read(obj, 100) == bytes(100)
            assert await img.read(0, 16) == b"\xaa" * 16
        finally:
            await cluster.stop()

    run(main())


def test_snapshots_preserve_and_rollback():
    async def main():
        cluster, ioctx, rbd, img = await _cluster_img(size=100_000)
        try:
            v1 = b"generation-one " * 1000
            await img.write(0, v1)
            await img.snap_create("s1")
            v2 = b"GENERATION-TWO " * 1000
            await img.write(0, v2)
            assert (await img.read(0, len(v2))) == v2
            # read-only view at the snap sees v1
            img.snap_set("s1")
            assert (await img.read(0, len(v1))) == v1
            with pytest.raises(RadosError):
                await img.write(0, b"nope")
            img.snap_set(None)
            snaps = await img.snap_list()
            assert [s["name"] for s in snaps] == ["s1"]
            # rollback restores v1 on the head
            await img.snap_rollback("s1")
            assert (await img.read(0, len(v1))) == v1
            # remove the snap; head unaffected
            await img.snap_remove("s1")
            assert await img.snap_list() == []
            assert (await img.read(0, len(v1))) == v1
            # an image with snaps refuses removal
            await img.snap_create("s2")
            with pytest.raises(RadosError):
                await rbd.remove(ioctx, "img")
        finally:
            await cluster.stop()

    run(main())


def test_image_on_ec_data_pool():
    """Erasure-coded backend via --data-pool: metadata omap stays on a
    replicated pool (omap is unsupported on EC pools, as in the
    reference), data objects stripe onto the EC pool."""
    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "rbdmeta", size=2, pg_num=8)
            await cluster.client.create_ec_pool("ecdata", {
                "plugin": "ec_jax", "technique": "reed_sol_van",
                "k": "2", "m": "1", "crush-failure-domain": "osd"},
                pg_num=8)
            ioctx = cluster.client.open_ioctx("rbdmeta")
            rbd = RBD()
            await rbd.create(ioctx, "vol", 80_000, order=ORDER,
                             data_pool="ecdata")
            img = await rbd.open(ioctx, "vol")
            data = bytes(range(256)) * 200
            await img.write(5000, data)
            assert await img.read(5000, len(data)) == data
            # the data objects really live on the EC pool
            ec_ioctx = cluster.client.open_ioctx("ecdata")
            ec_objs = [o for o in await ec_ioctx.list_objects()
                       if o.startswith("rbd_data.")]
            assert ec_objs
            meta_objs = [o for o in await ioctx.list_objects()
                         if o.startswith("rbd_data.")]
            assert not meta_objs
            # omap on an EC pool is refused, like the reference
            with pytest.raises(RadosError):
                await ec_ioctx.omap_set("x", {"k": b"v"})
        finally:
            await cluster.stop()

    run(main())


def test_exclusive_lock_single_writer():
    """librbd ExclusiveLock role: with the feature on, the first
    mutation auto-acquires the header lock; a second live writer is
    refused; a DEAD holder's lock is broken after its renewals go
    stale, and the image stays consistent."""

    async def main():
        from ceph_tpu.rados.client import RadosClient

        cluster = Cluster(num_osds=3)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "rbdx", size=2, pg_num=4)
            rbd = RBD()
            io_a = cluster.client.open_ioctx("rbdx")
            await rbd.create(io_a, "vol", 8 << 20,
                             exclusive_lock=True)

            img_a = await rbd.open(io_a, "vol")
            img_a.LOCK_RENEW = 0.3
            await img_a.write(0, b"A" * 4096)   # auto-acquires
            assert img_a._lock_owned

            # two handles of the SAME client contend like strangers
            # (per-handle cookies): the second is refused while the
            # first is live
            img_c = await rbd.open(io_a, "vol")
            img_c.LOCK_RENEW = 0.3
            with pytest.raises(RadosError):
                await img_c.write(0, b"C" * 512)

            client_b = RadosClient(cluster.mon.addr)
            await client_b.connect()
            io_b = client_b.open_ioctx("rbdx")
            img_b = await rbd.open(io_b, "vol")
            img_b.LOCK_RENEW = 0.3
            # holder is LIVE: B must be refused (EBUSY), not corrupt
            with pytest.raises(RadosError):
                await img_b.write(4096, b"B" * 4096)
            assert not img_b._lock_owned

            # holder dies without unlocking (SIGKILL shape): renewals
            # stop; B breaks the stale lock and proceeds
            img_a._lock_owned = False
            img_a._lock_task.cancel()
            img_b._seen_renewal = None
            await img_b.write(4096, b"B" * 4096)
            assert img_b._lock_owned
            assert await img_b.read(0, 4096) == b"A" * 4096
            assert await img_b.read(4096, 4096) == b"B" * 4096
            await img_b.close()
            await client_b.shutdown()

            # images WITHOUT the feature stay lock-free
            await rbd.create(io_a, "plain", 1 << 20)
            img_p = await rbd.open(io_a, "plain")
            await img_p.write(0, b"z" * 512)
            assert not img_p._lock_owned
        finally:
            await cluster.stop()

    run(main())

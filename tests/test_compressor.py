"""Compressor framework tests.

Mirrors /root/reference/src/test/compressor/test_compression.cc: per-plugin
round-trips over varied payloads, corruption rejection, factory behavior,
plus the BlueStore-style gate and the TPU scoring path.
"""

import numpy as np
import pytest

from ceph_tpu import compressor as comp
from ceph_tpu.compressor import gate, scoring
from ceph_tpu.compressor.plugins import (
    BrotliCompressor,
    Lz4Compressor,
    SnappyCompressor,
    ZlibCompressor,
    ZstdCompressor,
)


def _payloads():
    rng = np.random.default_rng(42)
    text = (b"the quick brown fox jumps over the lazy dog " * 200)
    yield b""
    yield b"x"
    yield b"hello world"
    yield bytes(4096)                                   # zeros
    yield text
    yield rng.integers(0, 256, 4096, dtype=np.uint8).tobytes()   # random
    yield rng.integers(0, 4, 100_000, dtype=np.uint8).tobytes()  # low entropy
    # long match runs crossing block boundaries
    yield (b"abcd" * 5000) + rng.integers(0, 256, 999, dtype=np.uint8).tobytes()
    yield rng.integers(0, 256, (1 << 17) + 7, dtype=np.uint8).tobytes()


@pytest.fixture(params=comp.available_algorithms())
def codec(request):
    c = comp.Compressor.create(request.param)
    assert c is not None
    return c


def test_available_algorithms():
    algs = comp.available_algorithms()
    assert "zlib" in algs
    assert "lz4" in algs
    assert "snappy" in algs
    # bound to the system libzstd/libbrotli (present in this image);
    # on a host without the libs they gate out instead
    assert "zstd" in algs
    assert "brotli" in algs


def test_round_trip(codec):
    for data in _payloads():
        payload, msg = codec.compress(data)
        out = codec.decompress(payload, msg)
        assert out == data, (codec.get_type_name(), len(data))


def test_compresses_compressible(codec):
    data = bytes(64 * 1024)
    payload, _ = codec.compress(data)
    assert len(payload) < len(data) // 4


def test_ratio_on_text(codec):
    data = (b"object storage for the masses " * 1000)
    payload, _ = codec.compress(data)
    assert len(payload) < len(data) // 2


@pytest.mark.parametrize(
    "cls", [Lz4Compressor, SnappyCompressor, ZstdCompressor,
            BrotliCompressor])
def test_corruption_rejected(cls):
    codec = cls()
    data = (b"abcdefgh" * 1000)
    payload, msg = codec.compress(data)
    corrupted = bytearray(payload)
    for pos in (0, len(payload) // 2, len(payload) - 1):
        corrupted2 = bytearray(corrupted)
        corrupted2[pos] ^= 0xFF
        try:
            out = codec.decompress(bytes(corrupted2), msg)
            # a flip may land in literal bytes and still parse; then the
            # output must simply differ — no crash, no over-read
            assert isinstance(out, bytes)
        except ValueError:
            pass
    with pytest.raises(ValueError):
        codec.decompress(b"", msg)


def test_truncation_rejected():
    for cls in (Lz4Compressor, SnappyCompressor, ZstdCompressor,
                BrotliCompressor):
        codec = cls()
        payload, msg = codec.compress(b"abcdefgh" * 1000)
        for cut in (1, len(payload) // 2, len(payload) - 1):
            try:
                out = codec.decompress(payload[:cut], msg)
                assert out != b"abcdefgh" * 1000
            except ValueError:
                pass


def test_factory():
    assert comp.Compressor.create("none") is None
    assert comp.Compressor.create("zstd") is not None
    assert comp.Compressor.create("nonesuch") is None
    c = comp.Compressor.create("random")
    assert c is not None and c.get_type_name() in comp.available_algorithms()
    assert comp.get_comp_alg_name(comp.COMP_ALG_LZ4) == "lz4"
    assert comp.get_comp_alg_type("snappy") == comp.COMP_ALG_SNAPPY
    assert comp.get_comp_mode_type("aggressive") == comp.COMP_AGGRESSIVE
    assert comp.get_comp_mode_name(comp.COMP_PASSIVE) == "passive"


def test_interop_alg_ids():
    # create_by_alg resolves the same codecs through enum values
    for name in comp.available_algorithms():
        alg = comp.get_comp_alg_type(name)
        c = comp.Compressor.create_by_alg(alg)
        assert c is not None and c.get_type() == alg


# -- gate (BlueStore _do_alloc_write semantics) ----------------------------


def test_gate_modes():
    assert not gate.want_compress(comp.COMP_NONE, comp.ALLOC_HINT_COMPRESSIBLE)
    assert gate.want_compress(comp.COMP_FORCE, comp.ALLOC_HINT_INCOMPRESSIBLE)
    assert gate.want_compress(comp.COMP_PASSIVE, comp.ALLOC_HINT_COMPRESSIBLE)
    assert not gate.want_compress(comp.COMP_PASSIVE, 0)
    assert gate.want_compress(comp.COMP_AGGRESSIVE, 0)
    assert not gate.want_compress(
        comp.COMP_AGGRESSIVE, comp.ALLOC_HINT_INCOMPRESSIBLE)


def test_gate_required_ratio():
    codec = comp.Compressor.create("lz4")
    rng = np.random.default_rng(7)
    incompressible = rng.integers(0, 256, 64 * 1024, dtype=np.uint8).tobytes()
    payload, hdr = gate.maybe_compress(incompressible, codec)
    assert hdr is None and payload == incompressible    # rejected, stored raw

    compressible = bytes(64 * 1024)
    payload, hdr = gate.maybe_compress(compressible, codec)
    assert hdr is not None
    assert hdr.original_length == len(compressible)
    assert len(payload) <= len(compressible) * gate.DEFAULT_REQUIRED_RATIO
    assert gate.decompress(payload, hdr) == compressible


def test_gate_round_trip_all_algs():
    data = (b"replicated erasure coded placement group " * 512)
    for name in comp.available_algorithms():
        codec = comp.Compressor.create(name)
        payload, hdr = gate.maybe_compress(data, codec)
        assert hdr is not None, name
        assert gate.decompress(payload, hdr) == data


# -- TPU scoring -----------------------------------------------------------


def test_histograms_match_host():
    rng = np.random.default_rng(3)
    blocks = rng.integers(0, 256, (16, 2048), dtype=np.uint8)
    np.testing.assert_array_equal(
        np.asarray(scoring.byte_histograms(blocks)),
        scoring.byte_histograms_host(blocks))


def test_host_histograms_match_per_row_bincount():
    """The vectorized offset-bincount host path is equivalent to the
    per-row np.bincount it replaced (incl. degenerate shapes)."""
    rng = np.random.default_rng(6)
    for shape in [(1, 1), (3, 7), (32, 1024), (7, 256)]:
        blocks = rng.integers(0, 256, shape, dtype=np.uint8)
        want = np.stack([np.bincount(row, minlength=256)
                         for row in blocks]).astype(np.int32)
        got = scoring.byte_histograms_host(blocks)
        assert got.dtype == np.int32 and got.shape == (shape[0], 256)
        np.testing.assert_array_equal(got, want)
    empty = scoring.byte_histograms_host(
        np.zeros((0, 16), dtype=np.uint8))
    assert empty.shape == (0, 256) and empty.dtype == np.int32
    # saturated single-value rows exercise the minlength tail
    ones = np.full((4, 100), 255, dtype=np.uint8)
    hist = scoring.byte_histograms_host(ones)
    assert hist[:, 255].tolist() == [100] * 4
    assert hist.sum() == 400


def test_entropy_extremes():
    rng = np.random.default_rng(4)
    zeros = np.zeros((4, 4096), dtype=np.uint8)
    rand = rng.integers(0, 256, (4, 4096), dtype=np.uint8)
    e0 = np.asarray(scoring.entropy_bits_per_byte(zeros))
    e8 = np.asarray(scoring.entropy_bits_per_byte(rand))
    assert np.all(e0 < 0.01)
    assert np.all(e8 > 7.5)


def test_compress_decision_splits_blocks():
    rng = np.random.default_rng(5)
    blocks = np.stack([
        np.zeros(4096, dtype=np.uint8),
        rng.integers(0, 256, 4096, dtype=np.uint8),
        np.frombuffer((b"abcd" * 1024), dtype=np.uint8),
        rng.integers(0, 4, 4096, dtype=np.uint8),        # low entropy
    ])
    decision = np.asarray(scoring.compress_decision(blocks))
    assert decision.tolist() == [True, False, True, True]


def test_scoring_predicts_codec_outcome():
    """The TPU pre-filter agrees with what the codec+gate actually do."""
    rng = np.random.default_rng(6)
    codec = comp.Compressor.create("lz4")
    blocks = [
        bytes(8192),
        rng.integers(0, 256, 8192, dtype=np.uint8).tobytes(),
        (b"0123456789abcdef" * 512),
    ]
    arr = np.stack([np.frombuffer(b, dtype=np.uint8) for b in blocks])
    predicted = np.asarray(scoring.compress_decision(arr))
    for data, pred in zip(blocks, predicted):
        _, hdr = gate.maybe_compress(data, codec)
        accepted = hdr is not None
        assert accepted == bool(pred), (len(data), pred)


def test_scoring_catches_periodic_uniform_histogram():
    """A repeating 256-byte random pattern has near-uniform histogram
    (entropy says incompressible) but LZ crushes it; the lag-probe
    repetition signal must keep it on the 'try it' side (advisor)."""
    rng = np.random.default_rng(11)
    pattern = rng.integers(0, 256, 256, dtype=np.uint8).tobytes()
    periodic = np.frombuffer(pattern * 256, dtype=np.uint8)[None, :]
    random = rng.integers(0, 256, (1, 256 * 256), dtype=np.uint8)
    decision_p = np.asarray(scoring.compress_decision(periodic))
    decision_r = np.asarray(scoring.compress_decision(random))
    assert bool(decision_p[0]) is True
    assert bool(decision_r[0]) is False

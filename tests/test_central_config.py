"""Centralized config (ConfigMonitor role) + cluster log (LogMonitor
role): quorum-committed options pushed live to daemons with mask
precedence, durable across mon restarts; one `log last` surface for
multi-daemon incidents."""

import asyncio

import pytest

from cluster_helpers import Cluster


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


def test_config_set_pushes_live_to_daemons():
    async def main():
        cluster = Cluster(num_osds=3)
        await cluster.start()
        try:
            rc, out = await cluster.client.mon_command(
                {"prefix": "config set", "who": "osd",
                 "name": "osd_heartbeat_grace", "value": "7.5"})
            assert rc == 0, out
            # pushed to every subscribed OSD, type-coerced
            for _ in range(100):
                if all(o.config.get("osd_heartbeat_grace") == 7.5
                       for o in cluster.osds.values()):
                    break
                await asyncio.sleep(0.05)
            for osd in cluster.osds.values():
                assert osd.config["osd_heartbeat_grace"] == 7.5

            # per-daemon mask overrides the type section
            rc, _ = await cluster.client.mon_command(
                {"prefix": "config set", "who": "osd.1",
                 "name": "osd_heartbeat_grace", "value": "9.0"})
            assert rc == 0
            for _ in range(100):
                if cluster.osds[1].config.get(
                        "osd_heartbeat_grace") == 9.0:
                    break
                await asyncio.sleep(0.05)
            assert cluster.osds[1].config["osd_heartbeat_grace"] == 9.0
            assert cluster.osds[0].config["osd_heartbeat_grace"] == 7.5

            rc, out = await cluster.client.mon_command(
                {"prefix": "config get", "who": "osd"})
            assert out["config"]["osd_heartbeat_grace"] == "7.5"
            # rm clears the option
            rc, _ = await cluster.client.mon_command(
                {"prefix": "config rm", "who": "osd.1",
                 "name": "osd_heartbeat_grace"})
            assert rc == 0
            rc, out = await cluster.client.mon_command(
                {"prefix": "config get", "who": "osd.1"})
            assert "osd_heartbeat_grace" not in out["config"]
            # the rm reverts LIVE daemons to the next-lower mask value
            for _ in range(100):
                if cluster.osds[1].config.get(
                        "osd_heartbeat_grace") == 7.5:
                    break
                await asyncio.sleep(0.05)
            assert cluster.osds[1].config["osd_heartbeat_grace"] == 7.5
        finally:
            await cluster.stop()

    run(main())


def test_config_replicates_across_quorum():
    async def main():
        cluster = Cluster(num_osds=2, osds_per_host=1, num_mons=3,
                          mon_config={"mon_lease": 0.8,
                                      "mon_election_timeout": 1.0})
        await cluster.start()
        try:
            rc, _ = await cluster.client.mon_command(
                {"prefix": "config set", "who": "global",
                 "name": "rep_test_opt", "value": "42"})
            assert rc == 0
            for _ in range(100):
                if all(m._config_kv.get("global", {}).get(
                        "rep_test_opt") == "42"
                       for m in cluster.mons.values()):
                    break
                await asyncio.sleep(0.05)
            for m in cluster.mons.values():
                assert m._config_kv["global"]["rep_test_opt"] == "42"
            # a NEW leader still serves the committed config
            await cluster.kill_mon(0)
            await cluster.wait_for_quorum(timeout=20.0)
            rc, out = await cluster.client.mon_command(
                {"prefix": "config get", "who": "global"})
            assert out["config"]["rep_test_opt"] == "42"
        finally:
            await cluster.stop()

    run(main())


def test_cluster_log_collects_daemon_events():
    async def main():
        cluster = Cluster(num_osds=3)
        await cluster.start()
        try:
            # daemon-originated entry
            cluster.osds[2]._clog("ERR", "synthetic incident for test")
            # mon-originated entry rides failure adjudication; force
            # one via the command surface instead (deterministic)
            rc, _ = await cluster.client.mon_command(
                {"prefix": "config set", "who": "global",
                 "name": "logged_opt", "value": "1"})
            assert rc == 0
            for _ in range(100):
                rc, out = await cluster.client.mon_command(
                    {"prefix": "log last", "num": 50})
                msgs = [e["message"] for e in out["entries"]]
                if any("synthetic incident" in m for m in msgs) and \
                        any("config set" in m for m in msgs):
                    break
                await asyncio.sleep(0.05)
            whos = {e["who"] for e in out["entries"]}
            assert "osd.2" in whos and any(
                w.startswith("mon.") for w in whos)
        finally:
            await cluster.stop()

    run(main())

"""cephx-lite tests: signed frames end to end.

Mirrors /root/reference/src/test/ cephx shapes at the operative level:
a keyed cluster accepts keyed peers, rejects unkeyed and wrong-keyed
ones, and signatures detect tampering.
"""

import asyncio

import pytest

from cluster_helpers import Cluster

from ceph_tpu.common import auth
from ceph_tpu.msg import frames
from ceph_tpu.rados.client import RadosClient, RadosError


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


@pytest.fixture
def wire_transport():
    """Force real sockets for tests that observe wire BYTES (replay
    recording, sniffing): same-process endpoints otherwise ride the
    messenger's zero-copy loopback fast path and put nothing on the
    wire.  The properties under test are transport-level, so the test
    must pin the transport."""
    import ceph_tpu.msg as msg_mod

    old = msg_mod.LOCAL_FASTPATH
    msg_mod.LOCAL_FASTPATH = False
    yield
    msg_mod.LOCAL_FASTPATH = old


def test_sign_verify_unit():
    key = auth.parse_secret(auth.generate_secret()).active_key
    sig = auth.sign(key, b"pre", b"payload")
    assert len(sig) == auth.SIG_LEN
    assert auth.verify(key, sig, b"pre", b"payload")
    assert not auth.verify(key, sig, b"pre", b"tampered")
    other = auth.parse_secret(auth.generate_secret()).active_key
    assert not auth.verify(other, sig, b"pre", b"payload")
    assert auth.parse_secret(None) is None
    assert auth.parse_secret("") is None


def test_keyring_rotation_format():
    """kid:hex,kid:hex keyring: first entry active, all accepted."""
    a, b = auth.generate_secret(), auth.generate_secret()
    ring = auth.parse_secret(f"2:{a},1:{b}")
    assert ring.active == 2
    assert ring.active_key == bytes.fromhex(a)
    assert ring.get(1) == bytes.fromhex(b)
    assert ring.get(9) is None
    # bare hex remains kid 0 (operator flow unchanged)
    ring0 = auth.parse_secret(a)
    assert ring0.active == 0 and ring0.active_key == bytes.fromhex(a)


def test_session_key_derivation_and_tickets():
    ring = auth.parse_secret(auth.generate_secret())
    na, nb = auth.new_nonce(), auth.new_nonce()
    s1 = auth.derive_session(ring.active_key, na, nb)
    s2 = auth.derive_session(ring.active_key, na, nb)
    assert s1 == s2
    # fresh nonces => fresh session key (the anti-replay property)
    assert auth.derive_session(ring.active_key, auth.new_nonce(),
                               nb) != s1
    ticket = auth.make_ticket(ring, "client.alice", lifetime=60)
    entity, base = auth.check_ticket(ring, ticket)
    assert entity == "client.alice"
    assert base != ring.active_key
    # tampered ticket dies
    assert auth.check_ticket(ring, ticket[:-1] + b"\x00") is None
    # expired ticket dies
    stale = auth.make_ticket(ring, "client.alice", lifetime=-1)
    assert auth.check_ticket(ring, stale) is None
    # foreign keyring cannot mint tickets this ring accepts
    other = auth.parse_secret(auth.generate_secret())
    assert auth.check_ticket(ring,
                             auth.make_ticket(other, "x")) is None


def test_frame_signing_round_trip():
    key = auth.parse_secret(auth.generate_secret()).active_key
    frame = frames.encode_frame(7, 1, b"hello", key=key)
    pre = frame[:frames.PREAMBLE_WIRE_LEN]
    tag, flags, _seq, length = frames.decode_preamble(pre)
    assert flags & frames.FLAG_SIGNED
    payload = frame[frames.PREAMBLE_WIRE_LEN:
                    frames.PREAMBLE_WIRE_LEN + length]
    sig = frame[-auth.SIG_LEN:]
    frames.check_signature(key, flags, pre, payload, sig)
    # tampered payload fails even though its own crc could be fixed up
    with pytest.raises(frames.FrameError):
        frames.check_signature(key, flags, pre, b"hellp", sig)
    # unsigned frame against a keyed receiver fails
    plain = frames.encode_frame(7, 1, b"hello")
    ptag, pflags, _s, _l = frames.decode_preamble(
        plain[:frames.PREAMBLE_WIRE_LEN])
    with pytest.raises(frames.FrameError):
        frames.check_signature(key, pflags,
                               plain[:frames.PREAMBLE_WIRE_LEN],
                               b"hello", b"")
    # keyless receiver accepts anything (auth disabled)
    frames.check_signature(None, pflags,
                           plain[:frames.PREAMBLE_WIRE_LEN],
                           b"hello", b"")


def test_keyed_cluster_accepts_keyed_rejects_unkeyed():
    secret = auth.generate_secret()

    async def main():
        cluster = Cluster(
            num_osds=3,
            osd_config={"auth_secret": secret},
            mon_config={"auth_secret": secret},
            client_secret=secret)
        await cluster.start()
        try:
            # keyed client: full data path works signed end to end
            await cluster.client.create_replicated_pool(
                "p", size=2, pg_num=8)
            io = cluster.client.open_ioctx("p")
            await io.write_full("obj", b"signed payload " * 100)
            assert await io.read("obj") == b"signed payload " * 100

            # unkeyed client: the mon drops its frames — no map, no ops
            intruder = RadosClient(cluster.mon.addr)
            with pytest.raises(Exception):
                await asyncio.wait_for(intruder.connect(), 3.0)
            await intruder.shutdown()

            # wrong-keyed client: same rejection
            intruder2 = RadosClient(cluster.mon.addr,
                                    secret=auth.generate_secret())
            with pytest.raises(Exception):
                await asyncio.wait_for(intruder2.connect(), 3.0)
            await intruder2.shutdown()
        finally:
            await cluster.stop()

    run(main())


def test_replayed_recorded_session_is_rejected(wire_transport):
    """THE cephx property: an attacker who records a whole legitimate
    session (hello + signed command frames) and replays it byte-for-
    byte on a new connection gets dropped — fresh server nonce means a
    fresh session key, so the recorded frames' signatures no longer
    verify, and the recorded command must NOT execute."""
    secret = auth.generate_secret()

    async def main():
        from ceph_tpu.mon import MonDaemon
        from ceph_tpu.msg.messages import MMonCommand

        mon = MonDaemon(2, osds_per_host=1,
                        config={"auth_secret": secret})
        addr = await mon.start()
        try:
            # -- legitimate session, recorded FROM THE FIRST BYTE (the
            # hello included): the replay presents a complete,
            # validly-static-signed session, so its rejection proves
            # the fresh-nonce session-key property — not a missing
            # hello
            recorded = bytearray()
            client = RadosClient(addr, secret=secret)
            # tee at the socket layer, wrapping the writer the moment
            # it exists — the client's REAL hello is byte 0 of the
            # recording, exactly what a wire-tapping attacker has
            import ceph_tpu.msg as msg_mod

            orig_oc = msg_mod.asyncio.open_connection

            async def tee_oc(*args, **kw):
                r, w = await orig_oc(*args, **kw)
                ow = w.write

                def tee(data, _ow=ow):
                    recorded.extend(data)
                    return _ow(data)

                w.write = tee
                return r, w

            msg_mod.asyncio.open_connection = tee_oc
            try:
                await client.connect()
                rc, _ = await client.mon_command(
                    {"prefix": "osd pool create", "name": "legit",
                     "pg_num": 4, "pool_type": "replicated",
                     "size": 2})
                assert rc == 0
            finally:
                msg_mod.asyncio.open_connection = orig_oc
            await client.shutdown()
            assert len(recorded) > 0
            # byte 0 of the recording is the genuine hello frame
            from ceph_tpu.msg import frames as fr
            tag0, _f, _s, _l = fr.decode_preamble(
                bytes(recorded[:fr.PREAMBLE_WIRE_LEN]))
            assert tag0 == 1, "hello not captured"
            pools_before = len(mon.osdmap.pools)

            # -- replay the recorded byte stream on a raw socket ------
            host, port = addr.rsplit(":", 1)
            reader, writer = await asyncio.open_connection(
                host, int(port))
            writer.write(bytes(recorded))
            await writer.drain()
            # the mon must drop the connection (EOF back to us) without
            # executing the replayed pool-create
            try:
                eof = await asyncio.wait_for(reader.read(1 << 16), 5.0)
                while eof:
                    eof = await asyncio.wait_for(
                        reader.read(1 << 16), 5.0)
            except asyncio.TimeoutError:
                pass
            writer.close()
            await asyncio.sleep(0.2)
            assert len(mon.osdmap.pools) == pools_before, \
                "replayed command executed!"
        finally:
            await mon.shutdown()

    run(main())


def test_in_connection_replay_rejected_by_seq(wire_transport):
    """A frame replayed WITHIN a live session fails the strict
    sequence check."""
    secret = auth.generate_secret()

    async def main():
        from ceph_tpu.mon import MonDaemon

        mon = MonDaemon(2, osds_per_host=1,
                        config={"auth_secret": secret})
        addr = await mon.start()
        client = RadosClient(addr, secret=secret)
        try:
            await client.connect()
            conn = await client.msgr.connect(addr)
            captured = []
            orig_write = conn.writer.write

            def tee(data):
                captured.append(bytes(data))
                return orig_write(data)

            conn.writer.write = tee
            rc, _ = await client.mon_command({"prefix": "status"})
            assert rc == 0
            conn.writer.write = orig_write
            # replay the captured signed frames verbatim on the SAME
            # connection: duplicate seq -> dropped, session dies
            for chunk in captured:
                conn.writer.write(chunk)
            await conn.writer.drain()
            await asyncio.sleep(0.3)
            assert conn.closed or conn.reader.at_eof(), \
                "in-session replay not rejected"
        finally:
            await client.shutdown()
            await mon.shutdown()

    run(main())


def test_key_rotation_overlap():
    """Rotation: a cluster listing {old,new} keys accepts peers on
    either; a peer on a dropped key is rejected."""
    old_k, new_k = auth.generate_secret(), auth.generate_secret()

    async def main():
        cluster = Cluster(
            num_osds=3,
            osd_config={"auth_secret": f"2:{new_k},1:{old_k}"},
            mon_config={"auth_secret": f"2:{new_k},1:{old_k}"},
            client_secret=f"2:{new_k},1:{old_k}")
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "p", size=2, pg_num=4)
            # a client still on the OLD key (kid 1 active) works
            oldie = RadosClient(cluster.mon.addr,
                                secret=f"1:{old_k}")
            await oldie.connect()
            io = oldie.open_ioctx("p")
            await io.write_full("o", b"old-key client payload")
            assert await io.read("o") == b"old-key client payload"
            await oldie.shutdown()
            # a client on a key the cluster never listed is rejected
            stranger = RadosClient(cluster.mon.addr,
                                   secret=f"9:{auth.generate_secret()}")
            with pytest.raises(Exception):
                await asyncio.wait_for(stranger.connect(), 3.0)
            await stranger.shutdown()
        finally:
            await cluster.stop()

    run(main())


def test_ticket_grant_and_use():
    """Mon-as-KDC: challenge/proof exchange grants a ticket; the
    client's later connections bind their session to the ticket's base
    key, and services validate it offline."""
    secret = auth.generate_secret()

    async def main():
        cluster = Cluster(
            num_osds=3,
            osd_config={"auth_secret": secret},
            mon_config={"auth_secret": secret},
            client_secret=secret)
        await cluster.start()
        try:
            ticket = await cluster.client.auth_get_ticket()
            assert ticket
            ring = auth.parse_secret(secret)
            entity, base = auth.check_ticket(ring, ticket)
            assert entity == cluster.client.msgr.entity_name
            # ticketed client round-trips the data path (fresh OSD
            # connections carry the ticket in their hellos)
            await cluster.client.create_replicated_pool(
                "t", size=2, pg_num=4)
            io = cluster.client.open_ioctx("t")
            await io.write_full("obj", b"ticketed io")
            assert await io.read("obj") == b"ticketed io"
            # a forged proof is refused
            from ceph_tpu.msg.messages import MAuth
            bad = RadosClient(cluster.mon.addr, secret=secret)
            await bad.connect()
            mon = await bad.msgr.connect(bad.mon_addr)
            fut = asyncio.get_running_loop().create_future()
            tid = bad._next_tid()
            bad._futures[tid] = fut
            await mon.send(MAuth(tid, "client.evil", 2, kid=0,
                                 client_challenge=b"x" * 16,
                                 proof=b"bogus!!!"))
            reply = await asyncio.wait_for(fut, 5.0)
            assert reply.rc != 0
            await bad.shutdown()
        finally:
            await cluster.stop()

    run(main())


def test_secure_mode_encrypts_the_wire(wire_transport):
    """msgr2 secure-mode role: with auth_secure on, payloads are
    encrypted under the per-connection session keystream — a wire
    sniffer sees no plaintext, and the data path still round-trips."""
    secret = auth.generate_secret()
    marker = b"SUPER-SECRET-PAYLOAD-MARKER"

    async def main():
        cluster = Cluster(
            num_osds=3,
            osd_config={"auth_secret": secret, "auth_secure": True},
            mon_config={"auth_secret": secret, "auth_secure": True},
            client_secret=secret, client_secure=True)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "enc", size=2, pg_num=4)
            io = cluster.client.open_ioctx("enc")
            # sniff every byte of every connection the client opens
            # from here on (the OSD data connections are fresh)
            sniffed = bytearray()
            import ceph_tpu.msg as msg_mod

            orig_oc = msg_mod.asyncio.open_connection

            async def tee_oc(*args, **kw):
                r, w = await orig_oc(*args, **kw)
                ow = w.write

                def tee(data, _ow=ow):
                    sniffed.extend(data)
                    return _ow(data)

                w.write = tee
                return r, w

            msg_mod.asyncio.open_connection = tee_oc
            try:
                payload = marker * 200
                await io.write_full("obj", payload)
                assert await io.read("obj") == payload
            finally:
                msg_mod.asyncio.open_connection = orig_oc
            assert len(sniffed) > len(payload)
            assert marker not in bytes(sniffed), \
                "plaintext leaked on the wire in secure mode"

            # a keyed-but-plaintext client is refused by the secure
            # cluster after the handshake
            plain = RadosClient(cluster.mon.addr, secret=secret,
                                secure=False)
            with pytest.raises(Exception):
                await asyncio.wait_for(plain.connect(), 4.0)
            await plain.shutdown()
        finally:
            await cluster.stop()

    run(main())


def test_seal_unseal_unit():
    key = auth.parse_secret(auth.generate_secret()).active_key
    data = b"x" * 100_000
    ct = auth.seal(key, b"c", 7, data)
    assert ct != data
    # this environment has an AEAD (native or cryptography): frames
    # must be real AES-GCM, not the keystream fallback
    assert ct[0] == auth.MODE_AESGCM
    assert auth.unseal(key, b"c", 7, ct) == data
    # direction and seq separate the nonces
    assert auth.seal(key, b"s", 7, data) != ct
    assert auth.seal(key, b"c", 8, data) != ct
    # empty payload still carries an authenticating tag
    e = auth.seal(key, b"c", 7, b"")
    assert len(e) == 17 and auth.unseal(key, b"c", 7, e) == b""


def test_aead_negative_paths():
    """Tamper, replay-context, truncation, and downgrade all FAIL
    CLOSED (crypto_onwire.cc authenticated-decrypt discipline)."""
    key = auth.parse_secret(auth.generate_secret()).active_key
    data = b"secret frame payload" * 100
    ct = auth.seal(key, b"c", 7, data)
    # bit flip anywhere -> tag mismatch
    bad = bytearray(ct)
    bad[len(bad) // 2] ^= 1
    with pytest.raises(auth.SealError):
        auth.unseal(key, b"c", 7, bytes(bad))
    # wrong direction or seq = wrong nonce -> tag mismatch (the
    # reflection/replay shapes)
    with pytest.raises(auth.SealError):
        auth.unseal(key, b"s", 7, ct)
    with pytest.raises(auth.SealError):
        auth.unseal(key, b"c", 8, ct)
    # truncation below the tag
    with pytest.raises(auth.SealError):
        auth.unseal(key, b"c", 7, ct[:10])
    # downgrade: re-labelling an AEAD frame as keystream is rejected
    # outright by an AEAD-capable receiver
    with pytest.raises(auth.SealError):
        auth.unseal(key, b"c", 7, bytes([auth.MODE_XOR]) + ct[1:])
    # nonce-reuse guard at the construction level: same (key, role,
    # seq) produces the same nonce, so the API caller (the messenger)
    # never reuses a seq per direction — verify distinct seqs give
    # unrelated ciphertexts even for identical plaintexts
    c1 = auth.seal(key, b"c", 1, data)
    c2 = auth.seal(key, b"c", 2, data)
    assert c1[1:33] != c2[1:33]


def test_aead_capability_negotiation():
    """A peer that ADVERTISED no AEAD in its signed hello is a
    legitimate keystream fallback, not a downgrade — sealing-mode
    choice follows the peer's advertisement, and the downgrade
    rejection only applies to peers known or presumed capable
    (crypto_onwire mode-selection role)."""
    key = auth.parse_secret(auth.generate_secret()).active_key
    data = b"mixed-capability frame" * 50
    # sender learns the peer can't open AES-GCM -> keystream mode
    ct = auth.seal(key, b"c", 3, data, peer_aead=False)
    assert ct[0] == auth.MODE_XOR
    # receiver with AEAD accepts it BECAUSE the peer advertised False
    assert auth.unseal(key, b"c", 3, ct, peer_aead=False) == data
    # same frame from a capable (True) or silent (None) peer = attack
    with pytest.raises(auth.SealError):
        auth.unseal(key, b"c", 3, ct, peer_aead=True)
    with pytest.raises(auth.SealError):
        auth.unseal(key, b"c", 3, ct)
    # capable peers still get AES-GCM
    assert auth.seal(key, b"c", 3, data,
                     peer_aead=True)[0] == auth.MODE_AESGCM


def test_native_aesgcm_matches_cryptography():
    """The in-repo C++ AES-GCM must be bit-exact vs the OpenSSL-backed
    `cryptography` AESGCM (independent implementation cross-check)."""
    cryptography = pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM

    from ceph_tpu import native

    lib = native.get_lib()
    if lib is None or not hasattr(lib, "ceph_tpu_aesgcm_seal"):
        pytest.skip("native AEAD unavailable")
    import ctypes
    import random as _r

    u8 = ctypes.c_uint8
    rng = _r.Random(7)
    for _ in range(40):
        key = bytes(rng.randrange(256) for _ in range(32))
        iv = bytes(rng.randrange(256) for _ in range(12))
        pt = bytes(rng.randrange(256)
                   for _ in range(rng.choice([0, 1, 15, 16, 17, 4096])))
        out = (u8 * (len(pt) + 16))()
        rc = lib.ceph_tpu_aesgcm_seal(
            (u8 * 32).from_buffer_copy(key),
            (u8 * 12).from_buffer_copy(iv),
            (u8 * 1)(), 0,
            (u8 * max(1, len(pt))).from_buffer_copy(pt or b"\x00"),
            len(pt), out)
        assert rc == 0
        assert bytes(out) == AESGCM(key).encrypt(iv, pt, None)

"""cephx-lite tests: signed frames end to end.

Mirrors /root/reference/src/test/ cephx shapes at the operative level:
a keyed cluster accepts keyed peers, rejects unkeyed and wrong-keyed
ones, and signatures detect tampering.
"""

import asyncio

import pytest

from cluster_helpers import Cluster

from ceph_tpu.common import auth
from ceph_tpu.msg import frames
from ceph_tpu.rados.client import RadosClient, RadosError


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


def test_sign_verify_unit():
    secret = auth.parse_secret(auth.generate_secret())
    sig = auth.sign(secret, b"pre", b"payload")
    assert len(sig) == auth.SIG_LEN
    assert auth.verify(secret, sig, b"pre", b"payload")
    assert not auth.verify(secret, sig, b"pre", b"tampered")
    other = auth.parse_secret(auth.generate_secret())
    assert not auth.verify(other, sig, b"pre", b"payload")
    assert auth.parse_secret(None) is None
    assert auth.parse_secret("") is None


def test_frame_signing_round_trip():
    secret = auth.parse_secret(auth.generate_secret())
    frame = frames.encode_frame(7, 1, b"hello", secret=secret)
    pre = frame[:frames.PREAMBLE_WIRE_LEN]
    tag, flags, _seq, length = frames.decode_preamble(pre)
    assert flags & frames.FLAG_SIGNED
    payload = frame[frames.PREAMBLE_WIRE_LEN:
                    frames.PREAMBLE_WIRE_LEN + length]
    sig = frame[-auth.SIG_LEN:]
    frames.check_signature(secret, flags, pre, payload, sig)
    # tampered payload fails even though its own crc could be fixed up
    with pytest.raises(frames.FrameError):
        frames.check_signature(secret, flags, pre, b"hellp", sig)
    # unsigned frame against a keyed receiver fails
    plain = frames.encode_frame(7, 1, b"hello")
    ptag, pflags, _s, _l = frames.decode_preamble(
        plain[:frames.PREAMBLE_WIRE_LEN])
    with pytest.raises(frames.FrameError):
        frames.check_signature(secret, pflags,
                               plain[:frames.PREAMBLE_WIRE_LEN],
                               b"hello", b"")
    # keyless receiver accepts anything (auth disabled)
    frames.check_signature(None, pflags,
                           plain[:frames.PREAMBLE_WIRE_LEN],
                           b"hello", b"")


def test_keyed_cluster_accepts_keyed_rejects_unkeyed():
    secret = auth.generate_secret()

    async def main():
        cluster = Cluster(
            num_osds=3,
            osd_config={"auth_secret": secret},
            mon_config={"auth_secret": secret},
            client_secret=secret)
        await cluster.start()
        try:
            # keyed client: full data path works signed end to end
            await cluster.client.create_replicated_pool(
                "p", size=2, pg_num=8)
            io = cluster.client.open_ioctx("p")
            await io.write_full("obj", b"signed payload " * 100)
            assert await io.read("obj") == b"signed payload " * 100

            # unkeyed client: the mon drops its frames — no map, no ops
            intruder = RadosClient(cluster.mon.addr)
            with pytest.raises(Exception):
                await asyncio.wait_for(intruder.connect(), 3.0)
            await intruder.shutdown()

            # wrong-keyed client: same rejection
            intruder2 = RadosClient(cluster.mon.addr,
                                    secret=auth.generate_secret())
            with pytest.raises(Exception):
                await asyncio.wait_for(intruder2.connect(), 3.0)
            await intruder2.shutdown()
        finally:
            await cluster.stop()

    run(main())

"""Multi-active MDS: subtree-partitioned ranks, cross-rank rename
coordination, per-rank standby takeover.

Mirrors the reference's multimds coverage (qa/tasks/cephfs multimds,
/root/reference/src/mds/MDSMap.h export pins): multiple active
metadata servers each own a namespace partition, clients route by
path, and a rank failure only stalls that rank's subtree until its
standby takes over."""

import asyncio

from cluster_helpers import Cluster

from ceph_tpu.cephfs import CephFS
from ceph_tpu.mds import MDSDaemon, owner_rank


def run(coro, timeout=120):
    asyncio.run(asyncio.wait_for(coro, timeout))


FAST = {"lock_interval": 0.3}


async def _fs_stack(cluster, num_ranks=2):
    await cluster.client.create_replicated_pool("fsmeta", size=2,
                                                pg_num=4)
    await cluster.client.create_replicated_pool("fsdata", size=2,
                                                pg_num=4)
    daemons = []
    for r in range(num_ranks):
        mds = MDSDaemon(cluster.mon_addrs, "fsmeta", "fsdata",
                        name=f"r{r}", rank=r, num_ranks=num_ranks,
                        **FAST)
        await mds.start()
        daemons.append(mds)
    fs = CephFS(cluster.client, "fsmeta", "fsdata")
    return daemons, fs


def _two_dirs_different_ranks(num_ranks=2):
    """Top-level names landing on rank 0 and rank 1."""
    by_rank = {}
    for i in range(100):
        name = f"dir{i}"
        by_rank.setdefault(owner_rank(f"{name}/x", num_ranks), name)
        if len(by_rank) == num_ranks:
            break
    assert len(by_rank) == num_ranks
    return by_rank[0], by_rank[1]


def test_owner_rank_rule():
    # root-parented ops pin to rank 0; deeper ops hash the first
    # component; single-rank layouts collapse to 0
    assert owner_rank("/", 2) == 0
    assert owner_rank("/anything", 2) == 0
    assert owner_rank("/a/b", 1) == 0
    r = owner_rank("/a/b", 2)
    assert r == owner_rank("/a/b/c/d", 2) == owner_rank("/a/zz", 2)


def test_two_ranks_serve_their_subtrees():
    async def main():
        cluster = Cluster(num_osds=2)
        await cluster.start()
        daemons = []
        try:
            daemons, fs = await _fs_stack(cluster)
            d0, d1 = _two_dirs_different_ranks()
            await fs.mkdir(f"/{d0}")
            await fs.mkdir(f"/{d1}")
            base0, base1 = (d.ops_served for d in daemons)
            await fs.write_file(f"/{d0}/f", b"rank zero data")
            await fs.write_file(f"/{d1}/f", b"rank one data")
            assert await fs.read_file(f"/{d0}/f") == b"rank zero data"
            assert await fs.read_file(f"/{d1}/f") == b"rank one data"
            # deep trees under each partition
            await fs.mkdir(f"/{d1}/sub")
            await fs.write_file(f"/{d1}/sub/g", b"deep")
            assert sorted(await fs.listdir(f"/{d1}")) == ["f", "sub"]
            assert sorted(await fs.listdir("/")) == sorted([d0, d1])
            # BOTH ranks actually executed ops (the partition is real)
            assert daemons[0].ops_served > base0
            assert daemons[1].ops_served > base1
        finally:
            for d in daemons:
                await d.stop()
            await cluster.stop()

    run(main())


def test_cross_rank_rename_coherent():
    async def main():
        cluster = Cluster(num_osds=2)
        await cluster.start()
        daemons = []
        try:
            daemons, fs = await _fs_stack(cluster)
            d0, d1 = _two_dirs_different_ranks()
            await fs.mkdir(f"/{d0}")
            await fs.mkdir(f"/{d1}")
            await fs.write_file(f"/{d0}/src", b"moving target")
            await fs.write_file(f"/{d1}/dst", b"to be clobbered")
            # a SECOND client caches the dst through ITS own session
            from ceph_tpu.rados.client import RadosClient

            c2 = RadosClient(cluster.mon_addrs)
            await c2.connect()
            fs2 = CephFS(c2, "fsmeta", "fsdata")
            st = await fs2.stat(f"/{d1}/dst")
            assert st["size"] == len(b"to be clobbered")
            assert fs2._cached_inode(f"/{d1}/dst") is not None
            # cross-rank rename: src owner coordinates the dst rank
            await fs.rename(f"/{d0}/src", f"/{d1}/dst")
            assert await fs.read_file(f"/{d1}/dst") == b"moving target"
            # the peer revoke reached fs2: its cached dst is gone and a
            # fresh stat sees the NEW inode
            st2 = await fs2.stat(f"/{d1}/dst")
            assert st2["size"] == len(b"moving target")
            assert (await fs.listdir(f"/{d0}")) == []
            await c2.shutdown()
        finally:
            for d in daemons:
                await d.stop()
            await cluster.stop()

    run(main())


def test_rank_standby_takeover():
    async def main():
        cluster = Cluster(num_osds=2)
        await cluster.start()
        daemons, extra = [], []
        try:
            daemons, fs = await _fs_stack(cluster)
            d0, d1 = _two_dirs_different_ranks()
            await fs.mkdir(f"/{d1}")
            await fs.write_file(f"/{d1}/f", b"before failover")
            # standby FOR RANK 1 joins
            standby = MDSDaemon(cluster.mon_addrs, "fsmeta", "fsdata",
                                name="r1b", rank=1, num_ranks=2,
                                **FAST)
            await standby.start()
            extra.append(standby)
            # hard-kill the rank-1 active (no clean unlock)
            await daemons[1].msgr.shutdown()
            daemons[1]._stopping = True
            if daemons[1]._lock_task:
                daemons[1]._lock_task.cancel()
            # ops on rank 1's subtree continue after takeover
            for _ in range(100):
                if standby.state == "active":
                    break
                await asyncio.sleep(0.1)
            assert standby.state == "active"
            assert await fs.read_file(f"/{d1}/f") == b"before failover"
            await fs.write_file(f"/{d1}/g", b"after failover")
            assert await fs.read_file(f"/{d1}/g") == b"after failover"
            # rank 0 never blinked
            assert daemons[0].state == "active"
        finally:
            for d in daemons + extra:
                await d.stop()
            await cluster.stop()

    run(main(), timeout=180)

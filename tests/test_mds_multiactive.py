"""Multi-active MDS: subtree-partitioned ranks, cross-rank rename
coordination, per-rank standby takeover.

Mirrors the reference's multimds coverage (qa/tasks/cephfs multimds,
/root/reference/src/mds/MDSMap.h export pins): multiple active
metadata servers each own a namespace partition, clients route by
path, and a rank failure only stalls that rank's subtree until its
standby takes over."""

import asyncio

from cluster_helpers import Cluster

from ceph_tpu.cephfs import CephFS
from ceph_tpu.mds import MDSDaemon, owner_rank


def run(coro, timeout=120):
    asyncio.run(asyncio.wait_for(coro, timeout))


FAST = {"lock_interval": 0.3}


async def _fs_stack(cluster, num_ranks=2):
    await cluster.client.create_replicated_pool("fsmeta", size=2,
                                                pg_num=4)
    await cluster.client.create_replicated_pool("fsdata", size=2,
                                                pg_num=4)
    daemons = []
    for r in range(num_ranks):
        mds = MDSDaemon(cluster.mon_addrs, "fsmeta", "fsdata",
                        name=f"r{r}", rank=r, num_ranks=num_ranks,
                        **FAST)
        await mds.start()
        daemons.append(mds)
    fs = CephFS(cluster.client, "fsmeta", "fsdata")
    return daemons, fs


def _two_dirs_different_ranks(num_ranks=2):
    """Top-level names landing on rank 0 and rank 1."""
    by_rank = {}
    for i in range(100):
        name = f"dir{i}"
        by_rank.setdefault(owner_rank(f"{name}/x", num_ranks), name)
        if len(by_rank) == num_ranks:
            break
    assert len(by_rank) == num_ranks
    return by_rank[0], by_rank[1]


def test_owner_rank_rule():
    # root-parented ops pin to rank 0; deeper ops hash the first
    # component; single-rank layouts collapse to 0
    assert owner_rank("/", 2) == 0
    assert owner_rank("/anything", 2) == 0
    assert owner_rank("/a/b", 1) == 0
    r = owner_rank("/a/b", 2)
    assert r == owner_rank("/a/b/c/d", 2) == owner_rank("/a/zz", 2)


def test_two_ranks_serve_their_subtrees():
    async def main():
        cluster = Cluster(num_osds=2)
        await cluster.start()
        daemons = []
        try:
            daemons, fs = await _fs_stack(cluster)
            d0, d1 = _two_dirs_different_ranks()
            await fs.mkdir(f"/{d0}")
            await fs.mkdir(f"/{d1}")
            base0, base1 = (d.ops_served for d in daemons)
            await fs.write_file(f"/{d0}/f", b"rank zero data")
            await fs.write_file(f"/{d1}/f", b"rank one data")
            assert await fs.read_file(f"/{d0}/f") == b"rank zero data"
            assert await fs.read_file(f"/{d1}/f") == b"rank one data"
            # deep trees under each partition
            await fs.mkdir(f"/{d1}/sub")
            await fs.write_file(f"/{d1}/sub/g", b"deep")
            assert sorted(await fs.listdir(f"/{d1}")) == ["f", "sub"]
            assert sorted(await fs.listdir("/")) == sorted([d0, d1])
            # BOTH ranks actually executed ops (the partition is real)
            assert daemons[0].ops_served > base0
            assert daemons[1].ops_served > base1
        finally:
            for d in daemons:
                await d.stop()
            await cluster.stop()

    run(main())


def test_cross_rank_rename_coherent():
    async def main():
        cluster = Cluster(num_osds=2)
        await cluster.start()
        daemons = []
        try:
            daemons, fs = await _fs_stack(cluster)
            d0, d1 = _two_dirs_different_ranks()
            await fs.mkdir(f"/{d0}")
            await fs.mkdir(f"/{d1}")
            await fs.write_file(f"/{d0}/src", b"moving target")
            await fs.write_file(f"/{d1}/dst", b"to be clobbered")
            # a SECOND client caches the dst through ITS own session
            from ceph_tpu.rados.client import RadosClient

            c2 = RadosClient(cluster.mon_addrs)
            await c2.connect()
            fs2 = CephFS(c2, "fsmeta", "fsdata")
            st = await fs2.stat(f"/{d1}/dst")
            assert st["size"] == len(b"to be clobbered")
            assert fs2._cached_inode(f"/{d1}/dst") is not None
            # cross-rank rename: src owner coordinates the dst rank
            await fs.rename(f"/{d0}/src", f"/{d1}/dst")
            assert await fs.read_file(f"/{d1}/dst") == b"moving target"
            # the peer revoke reached fs2: its cached dst is gone and a
            # fresh stat sees the NEW inode
            st2 = await fs2.stat(f"/{d1}/dst")
            assert st2["size"] == len(b"moving target")
            assert (await fs.listdir(f"/{d0}")) == []
            await c2.shutdown()
        finally:
            for d in daemons:
                await d.stop()
            await cluster.stop()

    run(main())


def test_rank_standby_takeover():
    async def main():
        cluster = Cluster(num_osds=2)
        await cluster.start()
        daemons, extra = [], []
        try:
            daemons, fs = await _fs_stack(cluster)
            d0, d1 = _two_dirs_different_ranks()
            await fs.mkdir(f"/{d1}")
            await fs.write_file(f"/{d1}/f", b"before failover")
            # standby FOR RANK 1 joins
            standby = MDSDaemon(cluster.mon_addrs, "fsmeta", "fsdata",
                                name="r1b", rank=1, num_ranks=2,
                                **FAST)
            await standby.start()
            extra.append(standby)
            # hard-kill the rank-1 active (no clean unlock)
            await daemons[1].msgr.shutdown()
            daemons[1]._stopping = True
            if daemons[1]._lock_task:
                daemons[1]._lock_task.cancel()
            # ops on rank 1's subtree continue after takeover
            for _ in range(100):
                if standby.state == "active":
                    break
                await asyncio.sleep(0.1)
            assert standby.state == "active"
            assert await fs.read_file(f"/{d1}/f") == b"before failover"
            await fs.write_file(f"/{d1}/g", b"after failover")
            assert await fs.read_file(f"/{d1}/g") == b"after failover"
            # rank 0 never blinked
            assert daemons[0].state == "active"
        finally:
            for d in daemons + extra:
                await d.stop()
            await cluster.stop()

    run(main(), timeout=180)


def test_rehoming_dir_rename_is_exdev():
    """Historically a re-homing directory rename returned EXDEV;
    it now MIGRATES the subtree (the Migrator role — full coverage
    in test_mds_migrator.py).  This test keeps the surrounding
    invariants: file renames across ranks and hash-stable dir
    renames behave as before."""
    async def main():
        cluster = Cluster(num_osds=2)
        await cluster.start()
        daemons = []
        try:
            daemons, fs = await _fs_stack(cluster)
            d0, d1 = _two_dirs_different_ranks()
            await fs.mkdir(f"/{d0}")
            await fs.mkdir(f"/{d1}")
            await fs.mkdir(f"/{d0}/inner")
            await fs.write_file(f"/{d0}/inner/f", b"stay")
            # the re-homing rename now migrates instead of EXDEV
            await fs.rename(f"/{d0}/inner", f"/{d1}/moved")
            assert await fs.read_file(f"/{d1}/moved/f") == b"stay"
            await fs.rename(f"/{d1}/moved", f"/{d0}/inner")
            assert await fs.read_file(f"/{d0}/inner/f") == b"stay"
            # FILE renames across the same ranks still work
            await fs.rename(f"/{d0}/inner/f", f"/{d1}/f")
            assert await fs.read_file(f"/{d1}/f") == b"stay"
            # and a top-level dir rename KEEPING its hash rank works
            same = None
            from ceph_tpu.mds import owner_rank as _or
            for i in range(100, 200):
                if _or(f"cand{i}/x", 2) == _or(f"{d0}/x", 2) \
                        and f"cand{i}" != d0:
                    same = f"cand{i}"
                    break
            await fs.rename(f"/{d0}", f"/{same}")
            assert "inner" in await fs.listdir(f"/{same}")
        finally:
            for d in daemons:
                await d.stop()
            await cluster.stop()

    run(main())


def test_cross_rank_rename_crash_recovery():
    """Crash the src rank right after the rename_intent lands (before
    the dst link): the standby's takeover must drive the intent to
    completion — file at dst, src dentry gone (the EUpdate-replay
    guarantee extended across ranks)."""
    async def main():
        cluster = Cluster(num_osds=2)
        await cluster.start()
        daemons, extra = [], []
        try:
            daemons, fs = await _fs_stack(cluster)
            d0, d1 = _two_dirs_different_ranks()
            await fs.mkdir(f"/{d0}")
            await fs.mkdir(f"/{d1}")
            await fs.write_file(f"/{d0}/victim", b"must survive")
            # standby for the SRC rank (rank of d0-parented ops)
            from ceph_tpu.mds import owner_rank as _or

            src_rank = _or(f"{d0}/victim", 2)
            standby = MDSDaemon(cluster.mon_addrs, "fsmeta", "fsdata",
                                name="sb", rank=src_rank, num_ranks=2,
                                **FAST)
            await standby.start()
            extra.append(standby)
            # arm the failpoint: the src rank dies right after its
            # NEXT journal append — the rename_intent
            daemons[src_rank]._fail_after_journal = True
            try:
                await fs.rename(f"/{d0}/victim", f"/{d1}/rescued")
            except Exception:
                pass  # the crash surfaces as a client-side error/retry
            # takeover + intent recovery
            for _ in range(200):
                if standby.state == "active" and \
                        not standby._pending_intents:
                    break
                await asyncio.sleep(0.1)
            assert standby.state == "active"
            # the rename CONVERGED: dst has the bytes, src is gone
            assert await fs.read_file(f"/{d1}/rescued") == \
                b"must survive"
            try:
                await fs.stat(f"/{d0}/victim")
                assert False, "src dentry survived the recovery"
            except Exception:
                pass
        finally:
            for d in daemons + extra:
                await d.stop()
            await cluster.stop()

    run(main(), timeout=180)


def test_toplevel_rmdir_fences_concurrent_create():
    """peer_rmdir protocol: while rank 0 removes a top-level dir, the
    OWNER rank fences creates into it — no orphaned files, no
    acknowledged-then-destroyed dentries."""
    async def main():
        cluster = Cluster(num_osds=2)
        await cluster.start()
        daemons = []
        try:
            daemons, fs = await _fs_stack(cluster)
            _d0, d1 = _two_dirs_different_ranks()
            await fs.mkdir(f"/{d1}")
            # mark the dir dying at its owner (what peer_rmdir_begin
            # does), then try to create into it through the client
            from ceph_tpu.mds import owner_rank as _or

            owner = daemons[_or(f"{d1}/x", 2)]
            _parent, _name, inode = await owner._resolve(f"/{d1}")
            rc, _ = await owner._op_peer_rmdir_begin(
                {"ino": inode["ino"]})
            assert rc == 0
            try:
                await asyncio.wait_for(
                    fs.write_file(f"/{d1}/sneak", b"x"), 8)
                created = True
            except Exception:
                created = False
            assert not created, \
                "create into a dying dir must be fenced"
            # protocol closes WITHOUT removal: dir usable again
            await owner._op_peer_rmdir_done(
                {"ino": inode["ino"], "removed": False})
            await fs.write_file(f"/{d1}/ok", b"y")
            assert await fs.read_file(f"/{d1}/ok") == b"y"
            # and the real rmdir path works end to end when empty
            await fs.unlink(f"/{d1}/ok")
            await fs.rmdir(f"/{d1}")
            assert d1 not in await fs.listdir("/")
        finally:
            for d in daemons:
                await d.stop()
            await cluster.stop()

    run(main(), timeout=180)

"""Placement-parity tests against the reference's own C mapper.

Compiles the reference CRUSH core (mapper.c/hash.c/builder.c/crush.c, plain
dependency-free C) from /root/reference into a throwaway shared library at
test time and asserts `placement diff = 0` between ceph_tpu.crush.mapper and
the real crush_do_rule across random hierarchies, inputs, and weight
vectors.  Skipped when the reference tree or a C compiler is unavailable —
the in-repo tests (test_crush.py) then still cover mapper-vs-kernel parity.
"""

import ctypes
import os
import subprocess
import tempfile

import numpy as np
import pytest

REF = "/root/reference/src/crush"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference tree not available")


@pytest.fixture(scope="module")
def oracle():
    tmp = tempfile.mkdtemp(prefix="crush_oracle_")
    so = os.path.join(tmp, "liboracle.so")
    # the reference expects a cmake-generated acconfig.h; an empty one makes
    # int_types.h fall back to the portable typedefs
    with open(os.path.join(tmp, "acconfig.h"), "w"):
        pass
    src = os.path.join(os.path.dirname(__file__), "oracle", "crush_oracle.c")
    cmd = ["gcc", "-O2", "-fPIC", "-shared", "-o", so, src,
           os.path.join(REF, "mapper.c"), os.path.join(REF, "hash.c"),
           os.path.join(REF, "builder.c"), os.path.join(REF, "crush.c"),
           "-I", tmp, "-I", os.path.dirname(REF), "-I", REF]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        pytest.skip(f"cannot build oracle: {e}")
    lib = ctypes.CDLL(so)
    lib.oracle_create.restype = ctypes.c_void_p
    lib.oracle_add_bucket.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
    lib.oracle_add_rule.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int)]
    lib.oracle_do_rule.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint), ctypes.c_int]
    lib.oracle_destroy.argtypes = [ctypes.c_void_p]
    lib.oracle_set_max_devices.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.oracle_set_tunables.argtypes = [ctypes.c_void_p] + [ctypes.c_int] * 6
    lib.oracle_finalize.argtypes = [ctypes.c_void_p]
    return lib


def _carr(vals):
    return (ctypes.c_int * len(vals))(*vals)


def build_both(lib, cmap):
    """Replicate a ceph_tpu CrushMap into the oracle. Bucket ids must have
    been allocated contiguously (-1, -2, ...) in insertion order."""
    o = lib.oracle_create(None)
    o = ctypes.c_void_p(o)
    for bid in sorted(cmap.buckets, reverse=True):
        b = cmap.buckets[bid]
        got = lib.oracle_add_bucket(o, b.alg, b.type, b.size,
                                    _carr(b.items), _carr(b.weights))
        assert got == bid, (got, bid)
    lib.oracle_set_max_devices(o, cmap.max_devices)
    for rule in cmap.rules:
        ops = _carr([s.op for s in rule.steps])
        a1 = _carr([s.arg1 for s in rule.steps])
        a2 = _carr([s.arg2 for s in rule.steps])
        lib.oracle_add_rule(o, len(rule.steps), rule.rule_type, ops, a1, a2)
    lib.oracle_set_tunables(
        o, cmap.choose_total_tries, cmap.choose_local_tries,
        cmap.choose_local_fallback_tries, cmap.chooseleaf_descend_once,
        cmap.chooseleaf_vary_r, cmap.chooseleaf_stable)
    lib.oracle_finalize(o)
    return o


def oracle_do_rule(lib, o, ruleno, x, result_max, weights):
    res = (ctypes.c_int * result_max)()
    warr = (ctypes.c_uint * len(weights))(*weights)
    n = lib.oracle_do_rule(o, ruleno, x, res, result_max, warr, len(weights))
    return list(res[:n])


def _compare(lib, cmap, ruleno, xs, result_max, weights=None):
    from ceph_tpu.crush.mapper import crush_do_rule

    o = build_both(lib, cmap)
    w = weights or cmap.full_weight_vector()
    diff = 0
    try:
        for x in xs:
            ref = oracle_do_rule(lib, o, ruleno, x, result_max, w)
            got = crush_do_rule(cmap, ruleno, x, result_max, w)
            if ref != got:
                diff += 1
                if diff <= 3:
                    print(f"x={x}: ref={ref} got={got}")
    finally:
        lib.oracle_destroy(o)
    assert diff == 0


def test_flat_hierarchy_replicated_firstn(oracle):
    from ceph_tpu.crush.map import build_flat_cluster

    cmap = build_flat_cluster(64, osds_per_host=4)
    cmap.add_simple_rule("data", "default", "host", mode="firstn")
    _compare(oracle, cmap, 0, range(1024), 3)


def test_rack_hierarchy_indep_ec(oracle):
    from ceph_tpu.crush.map import build_flat_cluster

    cmap = build_flat_cluster(96, osds_per_host=4, hosts_per_rack=4)
    cmap.add_simple_rule("ecpool", "default", "host", mode="indep",
                         pool_type="erasure")
    _compare(oracle, cmap, 0, range(1024), 11)


def test_choose_osd_direct(oracle):
    # failure domain osd: CHOOSE_FIRSTN type 0
    from ceph_tpu.crush.map import build_flat_cluster

    cmap = build_flat_cluster(40, osds_per_host=40)  # one big bucket
    cmap.add_simple_rule("flat", "default", "osd", mode="firstn")
    _compare(oracle, cmap, 0, range(2048), 3)


def test_reweighted_devices(oracle):
    from ceph_tpu.crush.map import build_flat_cluster

    rng = np.random.default_rng(9)
    cmap = build_flat_cluster(64, osds_per_host=4)
    cmap.add_simple_rule("data", "default", "host", mode="firstn")
    # random in/out weights incl. fully-out and partial
    w = [int(v) for v in rng.integers(0, 0x10001, 64)]
    _compare(oracle, cmap, 0, range(1024), 3, weights=w)


def test_uneven_bucket_weights(oracle):
    from ceph_tpu.crush.map import CrushMap

    rng = np.random.default_rng(11)
    cmap = CrushMap()
    root = cmap.add_bucket(-1, cmap.type_id("root"), "default")
    dev = 0
    for h in range(8):
        host = cmap.add_bucket(None, cmap.type_id("host"), f"host{h}")
        for _ in range(int(rng.integers(1, 6))):
            cmap.add_device(dev)
            host.add_item(dev, int(rng.integers(1, 4)) * 0x8000)
            dev += 1
        root.add_item(host.id, host.weight)
    cmap.add_simple_rule("data", "default", "host", mode="firstn")
    cmap.add_simple_rule("ec", "default", "host", mode="indep",
                         pool_type="erasure")
    _compare(oracle, cmap, 0, range(1024), 3)
    _compare(oracle, cmap, 1, range(1024), 6)


def test_multi_step_rule(oracle):
    # TAKE root / CHOOSE 2 racks / CHOOSELEAF 2 per rack / EMIT
    from ceph_tpu.crush.map import (
        CRUSH_RULE_CHOOSELEAF_FIRSTN, CRUSH_RULE_CHOOSE_FIRSTN,
        CRUSH_RULE_EMIT, CRUSH_RULE_TAKE, Rule, RuleStep, build_flat_cluster)

    cmap = build_flat_cluster(96, osds_per_host=4, hosts_per_rack=4)
    rack_t = cmap.type_id("rack")
    host_t = cmap.type_id("host")
    cmap.add_rule(Rule("spread", [
        RuleStep(CRUSH_RULE_TAKE, cmap.name_to_item("default")),
        RuleStep(CRUSH_RULE_CHOOSE_FIRSTN, 2, rack_t),
        RuleStep(CRUSH_RULE_CHOOSELEAF_FIRSTN, 2, host_t),
        RuleStep(CRUSH_RULE_EMIT),
    ]))
    _compare(oracle, cmap, 0, range(1024), 4)


def test_legacy_tunables(oracle):
    from ceph_tpu.crush.map import build_flat_cluster

    cmap = build_flat_cluster(48, osds_per_host=4)
    cmap.choose_local_tries = 2
    cmap.choose_local_fallback_tries = 5
    cmap.chooseleaf_vary_r = 0
    cmap.chooseleaf_stable = 0
    cmap.chooseleaf_descend_once = 0
    cmap.add_simple_rule("data", "default", "host", mode="firstn")
    _compare(oracle, cmap, 0, range(512), 3)


def test_uniform_and_list_buckets(oracle):
    from ceph_tpu.crush.map import (
        CRUSH_BUCKET_LIST, CRUSH_BUCKET_UNIFORM, CrushMap)

    for alg in (CRUSH_BUCKET_UNIFORM, CRUSH_BUCKET_LIST):
        cmap = CrushMap()
        root = cmap.add_bucket(-1, cmap.type_id("root"), "default")
        dev = 0
        for h in range(6):
            host = cmap.add_bucket(None, cmap.type_id("host"), f"host{h}",
                                   alg=alg)
            for _ in range(4):
                cmap.add_device(dev)
                host.add_item(dev, 0x10000)
                dev += 1
            root.add_item(host.id, host.weight)
        cmap.add_simple_rule("data", "default", "host", mode="firstn")
        _compare(oracle, cmap, 0, range(512), 3)


def test_10k_osd_map_spot(oracle):
    # BASELINE config #4 shape: 10k OSDs; spot-check a slice of inputs
    from ceph_tpu.crush.map import build_flat_cluster

    cmap = build_flat_cluster(10000, osds_per_host=20, hosts_per_rack=10)
    cmap.add_simple_rule("data", "default", "host", mode="firstn")
    cmap.add_simple_rule("ec", "default", "host", mode="indep",
                         pool_type="erasure")
    _compare(oracle, cmap, 0, range(64), 3)
    _compare(oracle, cmap, 1, range(64), 11)

"""Per-tenant mClock QoS tier: tag algebra edges, bounded queues,
the admission gate, and the tenant identity threaded end to end
(MOSDOp v4 -> per-tenant scheduler classes -> EBUSY sheds ->
qos_status / perf-dump / prometheus surfaces).
"""

from __future__ import annotations

import asyncio
import time

import pytest

from ceph_tpu.osd.admission import ADMIT, DELAY, SHED, AdmissionGate
from ceph_tpu.osd.scheduler import (
    CLIENT,
    MClockScheduler,
    QueueFull,
    RECOVERY,
    WPQScheduler,
    make_scheduler,
    tenant_class,
)


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


async def _noop():
    return None


# -- scheduler introspection + bounded queues --------------------------


def test_stats_exposes_depth_and_grants():
    async def main():
        sched = MClockScheduler(max_concurrent=1)
        gate = asyncio.Event()

        async def slow():
            await gate.wait()

        loop = asyncio.get_running_loop()
        jobs = [loop.create_task(sched.run(CLIENT, 1.0, slow))
                for _ in range(5)]
        await asyncio.sleep(0.05)
        st = sched.stats()
        assert st["max_concurrent"] == 1
        assert st["in_flight"] == 1
        assert st["queued"] == 4
        assert st["queue_depths"].get(CLIENT) == 4
        assert st["max_queue_depth"] >= 1
        assert st["overflow"] in ("shed", "block")
        gate.set()
        await asyncio.gather(*jobs)
        assert sched.stats()["granted"][CLIENT] == 5
        assert sched.stats()["queued"] == 0
        await sched.stop()

    run(main())


def test_bounded_queue_sheds_with_queue_full():
    async def main():
        sched = MClockScheduler(max_concurrent=1, max_queue_depth=2,
                                overflow="shed")
        gate = asyncio.Event()

        async def slow():
            await gate.wait()

        loop = asyncio.get_running_loop()
        jobs = []
        for _ in range(3):  # 1 granted + 2 queued (the bound)
            jobs.append(loop.create_task(
                sched.run(CLIENT, 1.0, slow)))
            await asyncio.sleep(0.02)
        assert sched.stats()["queue_depths"].get(CLIENT) == 2
        with pytest.raises(QueueFull):
            await sched.run(CLIENT, 1.0, slow)
        assert sched.stats()["queue_shed"][CLIENT] == 1
        gate.set()
        await asyncio.gather(*jobs)
        await sched.stop()

    run(main())


def test_bounded_queue_block_policy_backpressures():
    async def main():
        sched = MClockScheduler(max_concurrent=1, max_queue_depth=2,
                                overflow="block")
        gate = asyncio.Event()

        async def slow():
            await gate.wait()

        loop = asyncio.get_running_loop()
        jobs = [loop.create_task(sched.run(CLIENT, 1.0, slow))
                for _ in range(3)]
        await asyncio.sleep(0.05)
        blocked = loop.create_task(sched.run(CLIENT, 1.0, slow))
        await asyncio.sleep(0.05)
        assert not blocked.done()         # parked, not shed
        gate.set()                        # drain unblocks it
        await asyncio.gather(*jobs, blocked)
        assert sched.stats()["granted"][CLIENT] == 4
        await sched.stop()

    run(main())


# -- mClock tag algebra edges ------------------------------------------


def test_limit_pinned_class_never_starves_reservation():
    """A class flooding at its limit tag must not starve a
    reservation-backed class: the reservation phase runs FIRST and
    the limited class's excess waits."""
    async def main():
        sched = MClockScheduler(profiles={
            "pinned": (0.0, 100.0, 30.0),   # huge weight, hard cap
            "reserved": (40.0, 0.1, 0.0),   # floor, tiny weight
        }, max_concurrent=2)
        counts = {"pinned": 0, "reserved": 0}
        stop = [False]

        async def bump(cls):
            counts[cls] += 1
            await asyncio.sleep(0.002)

        async def flood():
            while not stop[0]:
                await sched.run("pinned", 1.0,
                                lambda: bump("pinned"))

        loop = asyncio.get_running_loop()
        floods = [loop.create_task(flood()) for _ in range(4)]
        t0 = time.monotonic()
        jobs = []
        while time.monotonic() - t0 < 1.0:
            jobs.append(sched.run("reserved", 1.0,
                                  lambda: bump("reserved")))
            await asyncio.sleep(0.01)
        await asyncio.gather(*jobs)
        stop[0] = True
        for t in floods:
            t.cancel()
        await asyncio.gather(*floods, return_exceptions=True)
        elapsed = time.monotonic() - t0
        # reservation held: >= ~half the 40/s floor despite the flood
        assert counts["reserved"] >= 20 * elapsed * 0.5, counts
        # the pinned class was capped near its 30/s limit, not its
        # weight share (generous ceiling for grant-loop slack)
        assert counts["pinned"] <= 30 * elapsed * 1.8 + 8, counts
        await sched.stop()

    run(main())


def test_cancelled_before_grant_returns_cost():
    """An op cancelled while queued gives back its R/P/L charge: the
    class's next op tags as if the dead op never existed."""
    async def main():
        sched = MClockScheduler(profiles={
            "t": (10.0, 2.0, 20.0)}, max_concurrent=1)
        gate = asyncio.Event()

        async def slow():
            await gate.wait()

        loop = asyncio.get_running_loop()
        holder = loop.create_task(sched.run("t", 1.0, slow))
        await asyncio.sleep(0.02)
        p_before = sched._last_p.get("t")
        r_before = sched._last_r.get("t")
        victim = loop.create_task(sched.run("t", 4.0, _noop))
        await asyncio.sleep(0.02)
        # the queued victim advanced the class tags
        assert sched._last_p["t"] > p_before
        victim.cancel()
        await asyncio.gather(victim, return_exceptions=True)
        gate.set()          # holder finishes; grant loop pops victim
        await holder
        await asyncio.sleep(0.02)
        assert sched.cancelled_before_grant == 1
        # refunded: tags back to (about) the pre-victim values
        assert abs(sched._last_p["t"] - p_before) < 1e-6
        assert abs(sched._last_r["t"] - r_before) < 1e-6
        await sched.stop()

    run(main())


def test_idle_tenant_burst_does_not_replay_idle_tags():
    """The idle-class tag-replay floor: a tenant that sleeps then
    bursts must tag from NOW — not from its stale last tag (which
    would grant it an instant backlog advantage over the classes
    that kept working), and not be penalized either."""
    async def main():
        sched = MClockScheduler(profiles={
            "sleeper": (50.0, 1.0, 0.0),
            "steady": (50.0, 1.0, 0.0)}, max_concurrent=1)
        # steady class works for a while
        for _ in range(5):
            await sched.run("steady", 1.0, _noop)
        await asyncio.sleep(0.3)   # sleeper idle the whole time
        now = time.monotonic()
        gate = asyncio.Event()

        async def slow():
            await gate.wait()

        loop = asyncio.get_running_loop()
        holder = loop.create_task(sched.run("steady", 1.0, slow))
        await asyncio.sleep(0.02)
        burst = [loop.create_task(sched.run("sleeper", 1.0, _noop))
                 for _ in range(3)]
        await asyncio.sleep(0.02)
        # the burst's tags anchor at >= now: no banked idle credit
        # (r_tag floors at now; p_tag at now + cost/weight)
        assert sched._last_r["sleeper"] >= now - 1e-3
        assert sched._last_p["sleeper"] >= now - 1e-3
        gate.set()
        await asyncio.gather(holder, *burst)
        await sched.stop()

    run(main())


def test_wpq_uncharge_on_cancelled_grant():
    async def main():
        sched = WPQScheduler(weights={CLIENT: 2.0}, max_concurrent=1)
        gate = asyncio.Event()

        async def slow():
            await gate.wait()

        loop = asyncio.get_running_loop()
        holder = loop.create_task(sched.run(CLIENT, 1.0, slow))
        await asyncio.sleep(0.02)
        served_before = sched._served.get(CLIENT, 0.0)
        victim = loop.create_task(sched.run(CLIENT, 6.0, _noop))
        await asyncio.sleep(0.02)
        victim.cancel()
        await asyncio.gather(victim, return_exceptions=True)
        gate.set()
        await holder
        await asyncio.sleep(0.02)
        # the pop charged then refunded: net zero for the dead op
        assert abs(sched._served[CLIENT] - served_before) < 1e-9
        assert sched.cancelled_before_grant == 1
        await sched.stop()

    run(main())


# -- per-tenant classes ------------------------------------------------


def test_tenant_profile_resolution():
    sched = MClockScheduler(tenant_default=(1.0, 2.0, 3.0),
                            tenant_profiles={"gold": (9.0, 8.0, 0.0)})
    assert sched.profile_of(tenant_class("gold")) == (9.0, 8.0, 0.0)
    assert sched.profile_of(tenant_class("other")) == (1.0, 2.0, 3.0)
    assert sched.profile_of(CLIENT)[1] == 10.0   # stock class intact
    assert sched.profile_of(RECOVERY)[0] == 25.0
    assert tenant_class("") == CLIENT


def test_make_scheduler_filters_mclock_kwargs_for_wpq():
    w = make_scheduler("wpq", tenant_default=(0, 1, 0),
                       tenant_profiles={}, max_queue_depth=7)
    assert isinstance(w, WPQScheduler)
    assert w.max_queue_depth == 7
    m = make_scheduler("mclock_scheduler",
                       tenant_profiles={"a": (1, 1, 1)})
    assert isinstance(m, MClockScheduler)


def test_tenant_state_stays_bounded():
    """Millions of tenants must not grow the tag maps without bound:
    idle tenant classes are pruned past the cap."""
    from ceph_tpu.osd import scheduler as sched_mod

    async def main():
        sched = MClockScheduler(max_concurrent=4)
        old_cap = sched_mod.TENANT_STATE_CAP
        sched_mod.TENANT_STATE_CAP = 64
        try:
            for i in range(300):
                await sched.run(tenant_class(f"t{i}"), 1.0, _noop)
            assert len(sched._last_p) <= 64 + 4, len(sched._last_p)
        finally:
            sched_mod.TENANT_STATE_CAP = old_cap
        await sched.stop()

    run(main())


def test_tenant_limit_paces_grants():
    """A tenant's limit tag spaces its grants at the limit rate even
    with an idle scheduler (the scrub-trickle discipline, per
    tenant)."""
    async def main():
        sched = MClockScheduler(
            tenant_default=(0.0, 1.0, 0.0),
            tenant_profiles={"capped": (0.0, 10.0, 25.0)},
            max_concurrent=4)
        count = [0]

        async def op():
            count[0] += 1

        loop = asyncio.get_running_loop()
        t0 = time.monotonic()
        jobs = [loop.create_task(
            sched.run(tenant_class("capped"), 1.0, op))
            for _ in range(100)]
        done, pending = await asyncio.wait(jobs, timeout=1.0)
        elapsed = time.monotonic() - t0
        for p in pending:
            p.cancel()
        await asyncio.gather(*pending, return_exceptions=True)
        assert count[0] <= 25 * elapsed * 1.8 + 5, count[0]
        assert count[0] >= 5, count[0]
        await sched.stop()

    run(main())


# -- admission gate ----------------------------------------------------


def test_admission_fast_path_is_sync_with_cached_limit():
    """The hot accept path (ROADMAP item 2 tail): an under-limit op
    admits through the SYNCHRONOUS try_admit — no coroutine, one O(1)
    bucket lookup — and the tenant's limit resolves once per TTL
    window, not once per op."""
    calls = []

    def profile_of(t):
        calls.append(t)
        return (0.0, 1.0, 1000.0)

    g = AdmissionGate(config={"osd_mclock_admission_burst": 2.0},
                      profile_of=profile_of)
    for _ in range(200):
        assert g.try_admit("t", 1.0) == ADMIT
    # one profile resolution for 200 ops (cached in the bucket entry)
    assert len(calls) == 1
    assert g.counters[ADMIT] == 200
    # unlimited tenants admit on the fast path too, same caching
    g2 = AdmissionGate(profile_of=lambda t: (0.0, 1.0, 0.0))
    assert g2.try_admit("free") == ADMIT
    # a drained bucket defers to the slow path (None, caller awaits)
    g3 = AdmissionGate(config={"osd_mclock_admission_burst": 0.5,
                               "osd_mclock_admission_max_delay_ms":
                               0.0},
                       profile_of=lambda t: (0.0, 1.0, 2.0))
    assert g3.try_admit("t", 1.0) == ADMIT
    assert g3.try_admit("t", 1.0) is None

    async def main():
        # and the slow path sheds without double-charging
        assert await g3.admit("t", 1.0) == SHED
        assert g3.counters[SHED] == 1

    run(main())


def test_admission_burst_then_shed():
    async def main():
        g = AdmissionGate(
            config={"osd_mclock_admission_burst": 2.0,
                    "osd_mclock_admission_max_delay_ms": 1.0},
            profile_of=lambda t: (0.0, 1.0, 5.0))
        decisions = [await g.admit("t", 1.0) for _ in range(40)]
        assert decisions.count(ADMIT) == 10   # 5/s x 2s burst
        assert decisions.count(SHED) == 30
        assert g.counters[SHED] == 30

    run(main())


def test_admission_delay_smooths_small_overruns():
    async def main():
        g = AdmissionGate(
            config={"osd_mclock_admission_burst": 0.01,
                    "osd_mclock_admission_max_delay_ms": 100.0},
            profile_of=lambda t: (0.0, 1.0, 50.0))
        t0 = time.monotonic()
        decisions = [await g.admit("t", 1.0) for _ in range(5)]
        elapsed = time.monotonic() - t0
        # delayed ops still ADMIT (the caller proceeds after the
        # in-gate sleep); the smoothing shows in the counters and in
        # wall clock, and nothing was refused
        assert SHED not in decisions
        assert g.counters[DELAY] >= 4
        assert elapsed >= 0.04            # ~4 ops of in-gate pacing

    run(main())


def test_admission_unlimited_and_disabled_paths():
    async def main():
        g = AdmissionGate(profile_of=lambda t: (0.0, 1.0, 0.0))
        for _ in range(100):
            assert await g.admit("free") == ADMIT
        off = AdmissionGate(
            config={"osd_mclock_admission_enable": False},
            profile_of=lambda t: (0.0, 1.0, 0.001))
        for _ in range(10):
            assert await off.admit("t") == ADMIT
        assert off.counters[SHED] == 0

    run(main())


def test_admission_state_is_bounded():
    async def main():
        from ceph_tpu.osd import admission as adm_mod

        g = AdmissionGate(profile_of=lambda t: (0.0, 1.0, 100.0))
        old = adm_mod._BUCKET_CAP
        adm_mod._BUCKET_CAP = 32
        try:
            for i in range(200):
                await g.admit(f"t{i}")
            assert len(g._buckets) <= 32
            assert len(g._tenant_counters) <= 32
        finally:
            adm_mod._BUCKET_CAP = old

    run(main())


# -- scheduler-level isolation (the bench_qos property, fast) ----------


def test_tenant_isolation_under_flood():
    """Tenant B's latency holds while tenant A floods 10x its limit:
    A is capped by its limit tag, B's reservation carries it.  The
    scheduler-level twin of the bench_qos acceptance leg."""
    async def main():
        sched = MClockScheduler(
            tenant_profiles={"A": (0.0, 1.0, 50.0),
                             "B": (50.0, 5.0, 0.0)},
            max_concurrent=2)

        async def work():
            await asyncio.sleep(0.002)

        stop = [False]

        async def flood():
            while not stop[0]:
                try:
                    await sched.run(tenant_class("A"), 1.0, work)
                except QueueFull:
                    await asyncio.sleep(0.001)

        loop = asyncio.get_running_loop()
        floods = [loop.create_task(flood()) for _ in range(8)]
        await asyncio.sleep(0.1)
        lats = []
        for _ in range(30):
            t0 = time.monotonic()
            await sched.run(tenant_class("B"), 1.0, work)
            lats.append(time.monotonic() - t0)
            await asyncio.sleep(0.01)
        stop[0] = True
        for t in floods:
            t.cancel()
        await asyncio.gather(*floods, return_exceptions=True)
        lats.sort()
        p95 = lats[int(0.95 * (len(lats) - 1))]
        # B's reservation keeps p95 in the tens of ms despite the
        # 8-way flood (generous for CI jitter; without QoS this sits
        # behind A's whole backlog)
        assert p95 < 0.25, lats
        await sched.stop()

    run(main())


# -- end to end: tenant identity over the wire -------------------------


def test_cluster_tenant_shed_and_observability():
    """A burst far over a tenant's limit is shed with EBUSY at the
    admission gate BEFORE execution; qos_status, perf dump and the
    prometheus flattener all surface the decisions with tenant
    labels."""
    from cluster_helpers import Cluster
    from ceph_tpu.rados.client import RadosError

    async def main():
        cluster = Cluster(
            num_osds=3, osds_per_host=3,
            osd_config={"osd_mclock_tenant_profiles":
                        '{"bad": [0, 1, 5]}',
                        "osd_mclock_admission_max_delay_ms": 5.0})
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "p", size=2, pg_num=8)
            io = cluster.client.open_ioctx("p")
            await io.write_full("o1", b"x" * 1000)
            bad = cluster.client.open_ioctx("p", tenant="bad")

            async def one():
                try:
                    await bad.stat("o1")
                    return "ok"
                except RadosError as e:
                    assert e.rc == -16, e.rc
                    return "shed"

            res = await asyncio.gather(*(one() for _ in range(40)))
            assert res.count("shed") >= 20, res
            assert res.count("ok") >= 5, res

            sheds = 0
            qos = None
            for o in cluster.osds:
                rc, st = await cluster.client.osd_command(
                    o, {"prefix": "qos_status"})
                assert rc == 0
                sheds += st["admission"]["decisions"]["shed"]
                if st["admission"]["decisions"]["shed"]:
                    qos = st
            assert sheds >= 20
            assert qos is not None
            assert qos["tenant_profiles"]["bad"] == [0.0, 1.0, 5.0]
            assert "bad" in qos["admission"]["tenants"]
            assert qos["admission"]["tenants"]["bad"]["limit_ops"] \
                == 5.0

            # perf dump carries the nested qos section...
            total_shed = 0
            shed_perf = None
            for o in cluster.osds:
                rc, p = await cluster.client.osd_command(
                    o, {"prefix": "perf dump"})
                assert rc == 0 and "qos" in p
                total_shed += p["qos"]["shed"]
                if p["qos"]["shed"]:
                    shed_perf = p
            assert total_shed >= 20
            p = shed_perf
            assert p is not None
            # ...and the prometheus flattener labels tenants
            from ceph_tpu.mgr.prometheus import PrometheusModule

            lines: list = []
            seen: set = set()
            PrometheusModule._emit_perf(
                lines, seen, "ceph_osd_qos", p["qos"],
                {"ceph_daemon": "osd.0"})
            body = "\n".join(lines)
            assert 'tenant="bad"' in body
            assert "ceph_osd_qos_tenant_shed{" in body
            assert "# TYPE ceph_osd_qos_queued gauge" in body
        finally:
            await cluster.stop()

    run(main(), timeout=120)


def test_cluster_qos_kill_switch(monkeypatch):
    """CEPH_TPU_QOS=0: tenant tags are ignored — every client op
    schedules in the shared class, the gate admits everything."""
    monkeypatch.setenv("CEPH_TPU_QOS", "0")
    from cluster_helpers import Cluster

    async def main():
        cluster = Cluster(
            num_osds=3, osds_per_host=3,
            osd_config={"osd_mclock_tenant_profiles":
                        '{"bad": [0, 1, 2]}'})
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "p", size=2, pg_num=8)
            io = cluster.client.open_ioctx("p")
            await io.write_full("o1", b"x" * 100)
            bad = cluster.client.open_ioctx("p", tenant="bad")
            await asyncio.gather(*(bad.stat("o1")
                                   for _ in range(30)))
            granted: dict = {}
            for osd in cluster.osds.values():
                assert not osd._qos_tenants_enabled
                assert osd.admission.counters["shed"] == 0
                for cls, n in osd.scheduler.granted.items():
                    granted[cls] = granted.get(cls, 0) + n
            assert "client.bad" not in granted
            assert granted.get("client", 0) >= 30
        finally:
            await cluster.stop()

    run(main(), timeout=120)


def test_untagged_ops_unaffected_by_tenant_machinery():
    """No tenant on the op (stock clients, MOSDOp <= v3 peers):
    exactly the pre-QoS behavior — shared class, no admission
    charge."""
    from cluster_helpers import Cluster

    async def main():
        cluster = Cluster(num_osds=3, osds_per_host=3)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "p", size=2, pg_num=8)
            io = cluster.client.open_ioctx("p")
            await io.write_full("o1", b"y" * 512)
            assert await io.read("o1") == b"y" * 512
            for osd in cluster.osds.values():
                assert all(not c.startswith("client.")
                           for c in osd.scheduler.granted)
        finally:
            await cluster.stop()

    run(main(), timeout=120)


def test_mosdop_v4_tenant_round_trip_and_v3_compat():
    from ceph_tpu.msg.messages import MOSDOp, OSDOp
    from ceph_tpu.osd.osdmap import PgId

    msg = MOSDOp(7, "client.x", PgId(1, 2), "obj",
                 [OSDOp("read")], 9, tenant="acme")
    got = MOSDOp.decode(msg.encode())
    assert got.tenant == "acme"
    assert got.oid == "obj" and got.tid == 7
    # an untagged (default) op decodes tenant ""
    msg2 = MOSDOp(8, "client.y", PgId(1, 2), "o2",
                  [OSDOp("stat")], 9)
    assert MOSDOp.decode(msg2.encode()).tenant == ""

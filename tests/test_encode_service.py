"""Async micro-batching encode service tier (osd/encode_service.py).

The acceptance shape: N concurrent same-profile writes produce
bit-exact shards/hinfo vs the sequential inline path while the plan
cache records far fewer device dispatches than N; backpressure sheds
into the inline path without deadlock (including stop() with requests
in flight); the kill switch and the no-device-tier default keep
today's behavior unchanged; and the OSD daemon's write path rides the
service end to end.
"""

from __future__ import annotations

import asyncio
import os

import numpy as np
import pytest

import conftest

jax = pytest.importorskip("jax")

from ceph_tpu.ec import plan  # noqa: E402
from ceph_tpu.ec.registry import ErasureCodePluginRegistry  # noqa: E402
from ceph_tpu.osd import ec_util  # noqa: E402
from ceph_tpu.osd.encode_service import EncodeService  # noqa: E402

RNG = np.random.default_rng(17)


def _codec(k=4, m=2, **extra):
    profile = {"plugin": "ec_jax", "technique": "reed_sol_van",
               "k": str(k), "m": str(m), **extra}
    return ErasureCodePluginRegistry.instance().factory(
        "ec_jax", profile)


def _sinfo(k=4, chunk=4096):
    return ec_util.StripeInfo(k, k * chunk)


@pytest.fixture
def fused(monkeypatch):
    """Engage the fused device tier off-TPU (what a real TPU backend
    gets by default with its 1 MiB floor)."""
    monkeypatch.setenv("CEPH_TPU_FUSE_MIN_BYTES", "0")


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 120))


def _dispatches() -> int:
    return plan.stats()["dispatches"]


# -- the acceptance bound ---------------------------------------------------


def test_64_concurrent_writes_bit_exact_with_few_dispatches(fused):
    """A burst of 64 concurrent same-profile 64 KiB writes completes
    with <= 8 plan dispatches (vs 64 inline) and bit-identical
    shards/hinfo/data-crc to the sequential path."""
    codec = _codec()
    sinfo = _sinfo()
    bufs = [RNG.integers(0, 256, 64 << 10, dtype=np.uint8).tobytes()
            for _ in range(64)]
    want = list(range(6))
    expect = [ec_util.encode_with_hinfo(sinfo, codec, b, want,
                                        logical_len=len(b))
              for b in bufs]

    async def main():
        svc = EncodeService()
        outs = await asyncio.gather(
            *(svc.encode_with_hinfo(sinfo, codec, b, want,
                                    logical_len=len(b))
              for b in bufs))
        st = svc.stats()
        await svc.stop()
        return outs, st

    plan.reset_stats()
    outs, st = run(main())
    used = _dispatches()
    assert used <= 8, f"{used} plan dispatches for 64 writes"
    assert st["batched"] == 64 and st["inline"] == 0
    assert st["batches"] >= 1
    for (shards, hinfo, crc), (ws, wh, wc) in zip(outs, expect):
        assert crc == wc
        assert hinfo.total_chunk_size == wh.total_chunk_size
        assert hinfo.cumulative_shard_hashes == \
            wh.cumulative_shard_hashes
        for i in range(6):
            assert bytes(shards[i]) == bytes(ws[i])


def test_encode_and_decode_kinds_batch_and_match(fused):
    """Plain-encode (the RMW/recovery re-encode kind) and decode (the
    recovery/read kind) both batch and stay bit-exact."""
    codec = _codec()
    sinfo = _sinfo(chunk=1024)
    bufs = [RNG.integers(0, 256, 16 << 10, dtype=np.uint8).tobytes()
            for _ in range(12)]

    async def main():
        svc = EncodeService()
        encs = await asyncio.gather(
            *(svc.encode(sinfo, codec, b, range(6)) for b in bufs))
        # erase shard 0 everywhere: decode requests share one survivor
        # set and must fold into few dispatches
        reqs = [{i: sh[i] for i in (1, 2, 3, 4)} for sh in encs]
        decs = await asyncio.gather(
            *(svc.decode(sinfo, codec, m) for m in reqs))
        st = svc.stats()
        await svc.stop()
        return encs, decs, st

    plan.reset_stats()
    encs, decs, st = run(main())
    assert st["batches"] >= 2 and st["batched"] == 24
    for b, sh, d in zip(bufs, encs, decs):
        ref = ec_util.encode(sinfo, codec, b, range(6))
        assert all(bytes(sh[i]) == bytes(ref[i]) for i in range(6))
        assert d == b


def test_decode_many_isolates_per_request_failures(fused):
    """decode_many returns one outcome per request: a malformed map
    surfaces as its own Exception while its neighbours decode."""
    codec = _codec()
    sinfo = _sinfo(chunk=512)
    bufs = [RNG.integers(0, 256, 4096, dtype=np.uint8).tobytes()
            for _ in range(3)]
    shards = [ec_util.encode(sinfo, codec, b, range(6)) for b in bufs]
    maps = [{i: sh[i] for i in (1, 2, 3, 4)} for sh in shards]
    # below k survivors (2 of 4 data shards, real lengths): undecodable
    maps[1] = {1: maps[1][1], 2: maps[1][2]}

    async def main():
        svc = EncodeService()
        outs = await svc.decode_many(sinfo, codec, maps)
        await svc.stop()
        return outs

    outs = run(main())
    assert outs[0] == bufs[0] and outs[2] == bufs[2]
    assert isinstance(outs[1], BaseException)


# -- degradation paths ------------------------------------------------------


def test_backpressure_sheds_inline_without_deadlock(fused):
    codec = _codec()
    sinfo = _sinfo(chunk=512)
    bufs = [RNG.integers(0, 256, 8192, dtype=np.uint8).tobytes()
            for _ in range(32)]

    async def main():
        svc = EncodeService(window_ms=50, max_queue_requests=4)
        outs = await asyncio.gather(
            *(svc.encode_with_hinfo(sinfo, codec, b, range(6))
              for b in bufs))
        st = svc.stats()
        await svc.stop()
        return outs, st

    outs, st = run(main())
    assert len(outs) == 32
    assert st["shed"] > 0, "queue bound never triggered"
    assert st["shed"] + st["batched"] == 32
    for b, (shards, hinfo, _crc) in zip(bufs, outs):
        ref = ec_util.encode(sinfo, codec, b, range(6))
        assert all(bytes(shards[i]) == bytes(ref[i]) for i in range(6))


def test_stop_with_requests_in_flight_resolves_everything(fused):
    codec = _codec()
    sinfo = _sinfo(chunk=512)
    bufs = [RNG.integers(0, 256, 4096, dtype=np.uint8).tobytes()
            for _ in range(8)]

    async def main():
        # a window far beyond the test timeout: only stop() flushes
        svc = EncodeService(window_ms=60_000)
        tasks = [asyncio.ensure_future(
            svc.encode_with_hinfo(sinfo, codec, b, range(6)))
            for b in bufs]
        await asyncio.sleep(0)
        await svc.stop()
        return await asyncio.gather(*tasks)

    outs = run(main())
    assert len(outs) == 8
    assert all(h.total_chunk_size > 0 for _s, h, _c in outs)


def test_kill_switch_restores_inline_behavior(fused, monkeypatch):
    monkeypatch.setenv("CEPH_TPU_ENCODE_SERVICE", "0")
    codec = _codec()
    sinfo = _sinfo()
    buf = RNG.integers(0, 256, 32768, dtype=np.uint8).tobytes()

    async def main():
        svc = EncodeService()
        out = await svc.encode_with_hinfo(sinfo, codec, buf, range(6),
                                          logical_len=len(buf))
        st = svc.stats()
        await svc.stop()
        return out, st

    (shards, hinfo, crc), st = run(main())
    assert not st["enabled"]
    assert st["inline"] == 1 and st["batches"] == 0
    ws, wh, wc = ec_util.encode_with_hinfo(sinfo, codec, buf, range(6),
                                           logical_len=len(buf))
    assert crc == wc
    assert hinfo.cumulative_shard_hashes == wh.cumulative_shard_hashes
    assert all(bytes(shards[i]) == bytes(ws[i]) for i in range(6))


def test_no_device_tier_stays_inline(monkeypatch):
    """Without a fuse floor (the CPU-only default) the service never
    batches — CPU runs keep the pre-service path exactly."""
    monkeypatch.delenv("CEPH_TPU_FUSE_MIN_BYTES", raising=False)
    codec = _codec()
    sinfo = _sinfo()
    buf = RNG.integers(0, 256, 16384, dtype=np.uint8).tobytes()

    async def main():
        svc = EncodeService()
        out = await svc.encode_with_hinfo(sinfo, codec, buf, range(6))
        st = svc.stats()
        await svc.stop()
        return out, st

    (_shards, hinfo, _crc), st = run(main())
    assert st["inline"] == 1 and st["batched"] == 0
    assert hinfo.total_chunk_size == 16384 // 4


# -- the ec_util many-helpers (the service's thread-side body) --------------


@pytest.mark.skipif(conftest.DEVICE_INJECTION,
                    reason="asserts live device-dispatch counters/plans;\
 subject absent under scripted device-fault injection")
def test_encode_many_with_hinfo_matches_per_item(fused):
    codec = _codec()
    sinfo = _sinfo(chunk=512)
    items = [(RNG.integers(0, 256, n * 4 * 512,
                           dtype=np.uint8).tobytes(),
              tuple(range(6)), 100 + n)
             for n in (1, 3, 2, 5)]
    plan.reset_stats()
    outs = ec_util.encode_many_with_hinfo(sinfo, codec, items)
    assert _dispatches() == 1, "ragged batch did not fold into one"
    for (d, w, l), (shards, hinfo, crc) in zip(items, outs):
        ws, wh, wc = ec_util.encode_with_hinfo(sinfo, codec, d, w,
                                               logical_len=l)
        assert crc == wc
        assert hinfo.cumulative_shard_hashes == \
            wh.cumulative_shard_hashes
        assert all(bytes(shards[i]) == bytes(ws[i]) for i in range(6))


def test_encode_many_and_decode_many_host_fallback(monkeypatch):
    """The many-helpers stay bit-exact on the pure host tiers too."""
    monkeypatch.delenv("CEPH_TPU_FUSE_MIN_BYTES", raising=False)
    codec = _codec(tpu="false")
    sinfo = _sinfo(chunk=256)
    datas = [RNG.integers(0, 256, n * 4 * 256,
                          dtype=np.uint8).tobytes()
             for n in (2, 1, 4)]
    outs = ec_util.encode_many(sinfo, codec, datas,
                               [range(6)] * len(datas))
    for d, sh in zip(datas, outs):
        ref = ec_util.encode(sinfo, codec, d, range(6))
        assert all(bytes(sh[i]) == bytes(ref[i]) for i in range(6))
    # heterogeneous wants: slice offsets must advance for every union
    # shard per item, not only the shards an item asked for
    wants = [{0}, {0, 1, 5}, {4}]
    mixed = ec_util.encode_many(sinfo, codec, datas, wants)
    for d, w, sh in zip(datas, wants, mixed):
        ref = ec_util.encode(sinfo, codec, d, w)
        assert set(sh) == set(ref)
        assert all(bytes(sh[i]) == bytes(ref[i]) for i in w)
    maps = [{i: sh[i] for i in (1, 2, 3, 5)} for sh in outs]
    decs = ec_util.decode_many(sinfo, codec, maps)
    assert decs == list(bytes(d) for d in datas)


# -- daemon end to end ------------------------------------------------------


@pytest.mark.skipif(conftest.DEVICE_INJECTION,
                    reason="asserts live device-dispatch counters/plans;\
 subject absent under scripted device-fault injection")
def test_daemon_write_path_rides_the_service(fused):
    """Concurrent client writes through a live cluster batch their
    encodes (fewer plan dispatches than objects) and read back
    bit-exact; the admin surface exposes the counters."""
    from cluster_helpers import Cluster

    EC = {"plugin": "ec_jax", "technique": "reed_sol_van",
          "k": "2", "m": "1", "crush-failure-domain": "osd",
          "stripe_unit": "4096"}
    n_objs = 12
    payloads = [RNG.integers(0, 256, 32 << 10,
                             dtype=np.uint8).tobytes()
                for _ in range(n_objs)]

    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool("svc", profile=EC,
                                                pg_num=8)
            io = cluster.client.open_ioctx("svc")
            plan.reset_stats()
            await asyncio.gather(
                *(io.write_full(f"o{i}", payloads[i])
                  for i in range(n_objs)))
            # only count the fused write-path plans, not read decodes
            crc_dispatches = sum(
                p["dispatches"]
                for label, p in plan.stats()["per_plan"].items()
                if label.startswith("encode_crc"))
            for i in range(n_objs):
                assert await io.read(f"o{i}") == payloads[i]
            svc_stats = [osd.encode_service.stats()
                         for osd in cluster.osds.values()]
            return crc_dispatches, svc_stats
        finally:
            await cluster.stop()

    crc_dispatches, svc_stats = run(main())
    assert 0 < crc_dispatches < n_objs, (
        f"{crc_dispatches} fused dispatches for {n_objs} writes")
    assert sum(s["batched"] for s in svc_stats) == n_objs
    assert sum(s["batches"] for s in svc_stats) >= 1

"""Crash reporting (pybind/mgr/crash + ceph-crash roles) and CephFS
subvolumes (mgr/volumes role).

Crash: post -> ls/info -> RECENT_CRASH health warning -> archive
clears it -> reports survive a mon restart.  Volumes: group +
subvolume lifecycle, getpath, usage accounting, quota intent,
snapshots over the .snap machinery.
"""

import asyncio

import pytest

from cluster_helpers import Cluster

from ceph_tpu.cephfs import CephFS, CephFSError
from ceph_tpu.cephfs.volumes import VolumeClient
from ceph_tpu.common.crash import make_report, post_crash
from ceph_tpu.mds import MDSDaemon


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 150))


def test_crash_post_ls_health_archive():
    async def main():
        cluster = Cluster(num_osds=2)
        await cluster.start()
        try:
            mon = cluster.mon.addr
            try:
                raise RuntimeError("simulated osd abort")
            except RuntimeError as e:
                cid = await post_crash(mon, "osd.7", e)
            assert cid
            rc, out = await cluster.client.mon_command(
                {"prefix": "crash ls"})
            assert rc == 0
            assert [c["crash_id"] for c in out["crashes"]] == [cid]
            assert out["crashes"][0]["entity"] == "osd.7"
            rc, out = await cluster.client.mon_command(
                {"prefix": "crash info", "id": cid})
            assert rc == 0
            assert "simulated osd abort" in out["report"]["exception"]
            assert any("RuntimeError" in ln
                       for ln in out["report"]["backtrace"])
            # health warning until archived
            rc, health = await cluster.client.mon_command(
                {"prefix": "health"})
            assert "RECENT_CRASH" in health["checks"]
            rc, _ = await cluster.client.mon_command(
                {"prefix": "crash archive", "id": cid})
            assert rc == 0
            rc, health = await cluster.client.mon_command(
                {"prefix": "health"})
            assert "RECENT_CRASH" not in health["checks"]
            # ls-new hides archived, ls keeps it
            rc, out = await cluster.client.mon_command(
                {"prefix": "crash ls-new"})
            assert out["crashes"] == []
            rc, out = await cluster.client.mon_command(
                {"prefix": "crash ls"})
            assert out["crashes"][0]["archived"] is True
            # rm drops it
            rc, _ = await cluster.client.mon_command(
                {"prefix": "crash rm", "id": cid})
            assert rc == 0
            rc, out = await cluster.client.mon_command(
                {"prefix": "crash ls"})
            assert out["crashes"] == []
        finally:
            await cluster.stop()
    run(main())


def test_osd_boot_crash_posts_report(tmp_path):
    """A real OSD process whose boot dies posts a crash report the
    monitors list (the ceph-crash scanner role, process-level)."""
    async def main():
        import subprocess
        import sys

        cluster = Cluster(num_osds=1)
        await cluster.start()
        try:
            bad_store = tmp_path / "notadir"
            bad_store.write_bytes(b"i am a file, not a store dir")
            proc = await asyncio.create_subprocess_exec(
                sys.executable, "-m", "ceph_tpu.osd",
                "--id", "9", "--mon", cluster.mon.addr,
                "--store-path", str(bad_store),
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                env={"PYTHONPATH": ".", "JAX_PLATFORMS": "cpu",
                     "PATH": "/usr/bin:/bin:/usr/local/bin"})
            await asyncio.wait_for(proc.communicate(), 60)
            assert proc.returncode != 0
            rc, out = await cluster.client.mon_command(
                {"prefix": "crash ls"})
            assert rc == 0
            assert any(c["entity"] == "osd.9"
                       for c in out["crashes"]), out
        finally:
            await cluster.stop()
    run(main())


def test_crash_report_shape():
    rep = make_report("mds.a", ValueError("boom"))
    assert rep["entity"] == "mds.a"
    assert "mds.a" in rep["crash_id"]
    assert rep["exception"] == "ValueError('boom')"


def test_volumes_lifecycle():
    async def main():
        cluster = Cluster(num_osds=3)
        await cluster.start()
        await cluster.client.create_replicated_pool("m", size=2,
                                                    pg_num=4)
        await cluster.client.create_replicated_pool("d", size=2,
                                                    pg_num=4)
        mds = MDSDaemon(cluster.mon.addr, "m", "d", name="v",
                        lock_interval=0.3)
        await mds.start()
        try:
            fs = CephFS(cluster.client, "m", "d")
            vc = VolumeClient(fs)
            # groups
            await vc.group_create("apps")
            assert await vc.group_ls() == ["apps"]
            # subvolumes (grouped and default-group)
            path = await vc.create("web", group="apps",
                                   size=1 << 20)
            assert path == "/volumes/apps/web"
            await vc.create("scratch")
            assert await vc.ls(group="apps") == ["web"]
            assert await vc.ls() == ["scratch"]
            assert await vc.getpath("web", group="apps") == path
            with pytest.raises(CephFSError):
                await vc.getpath("nope")
            with pytest.raises(CephFSError):
                await vc.create("web", group="apps")  # EEXIST
            # usage + quota intent
            await fs.write_file(f"{path}/blob", b"z" * 4096)
            info = await vc.info("web", group="apps")
            assert info["bytes_used"] == 4096
            assert info["bytes_quota"] == 1 << 20
            out = await vc.resize("web", 2 << 20, group="apps")
            assert out["size"] == 2 << 20
            with pytest.raises(CephFSError):
                await vc.resize("web", 1 << 20, group="apps",
                                no_shrink=True)
            # snapshots ride the .snap machinery
            await vc.snapshot_create("web", "s1", group="apps")
            assert [s["name"]
                    for s in await vc.snapshot_ls("web",
                                                  group="apps")] \
                == ["s1"]
            assert await fs.read_file(
                f"{path}/.snap/s1/blob") == b"z" * 4096
            with pytest.raises(CephFSError):
                await vc.rm("web", group="apps")  # has snapshots
            await vc.snapshot_rm("web", "s1", group="apps")
            await vc.rm("web", group="apps")
            assert await vc.ls(group="apps") == []
            await vc.group_rm("apps")
            assert await vc.group_ls() == []
        finally:
            await mds.stop()
            await cluster.stop()
    run(main())

"""Driver entry-point smoke tests.

Guards the two artifacts the driver records every round: the single-chip
compile check (entry) and the multi-chip sharding dryrun (dryrun_multichip).
Round 1's MULTICHIP artifact went red because dryrun_multichip inherited a
broken default platform; it now pins the CPU backend itself, so this must
pass in any environment.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

jax = pytest.importorskip("jax")

import __graft_entry__ as graft  # noqa: E402


def test_entry_jits_and_runs():
    fn, example_args = graft.entry()
    out = jax.jit(fn)(*example_args)
    mbits, data = example_args
    batch, k, chunk = data.shape
    assert out.shape[0] == batch and out.shape[2] == chunk
    assert np.asarray(out).dtype == np.uint8


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)

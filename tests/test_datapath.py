"""Fused native datapath + zero-copy buffer discipline.

Covers the round-5 write-path redesign: the transpose-free native
encode (datapath.cc ceph_tpu_ec_encode_noT), StridedBuf shard views,
MemStore buffer adoption, the messenger loopback fast path, and the
OSD-returned content digest feeding RGW ETags.  Oracles are the
pre-existing slow paths (ec_util.encode + HashInfo.append, socket
messengers, direct crc32c) so every fast path is pinned bit-exact to
the code it replaced.
"""

import asyncio

import numpy as np
import pytest

from cluster_helpers import Cluster

from ceph_tpu.common.buffer import StridedBuf
from ceph_tpu.ec.registry import create_erasure_code
from ceph_tpu.ops import checksum as cks
from ceph_tpu.osd import ec_util


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


# -- StridedBuf --------------------------------------------------------------

def test_stridedbuf_matches_flat_bytes():
    rng = np.random.default_rng(1)
    arr = rng.integers(0, 256, (7, 4096), dtype=np.uint8)
    base = arr.reshape(-1)[: 7 * 4096].reshape(7, 4096)
    # a strided view: every 3rd row of a bigger array
    big = rng.integers(0, 256, (21, 4096), dtype=np.uint8)
    view = big[::3]
    sb = StridedBuf(view)
    flat = view.tobytes()
    assert len(sb) == len(flat)
    assert bytes(sb) == flat
    assert sb.tobytes() == flat
    # slices at chunk boundaries, inside one chunk, and spanning many
    for a, b in [(0, 4096), (4096, 8192), (100, 200), (4000, 4200),
                 (0, len(flat)), (5000, 20000), (len(flat) - 1,
                                                 len(flat))]:
        assert sb[a:b] == flat[a:b], (a, b)
    assert sb == flat
    del base


# -- fused encode ------------------------------------------------------------

@pytest.mark.parametrize("k,m,nstripes", [(8, 3, 16), (4, 2, 1),
                                          (2, 2, 5)])
def test_encode_with_hinfo_matches_slow_path(k, m, nstripes):
    codec = create_erasure_code({
        "plugin": "ec_jax", "technique": "reed_sol_van",
        "k": str(k), "m": str(m), "tpu": "false"})
    sinfo = ec_util.StripeInfo(k, k * 4096)
    width = sinfo.get_stripe_width()
    data = np.random.default_rng(2).integers(
        0, 256, nstripes * width, dtype=np.uint8).tobytes()

    want = range(codec.get_chunk_count())
    shards, hinfo, crc = ec_util.encode_with_hinfo(
        sinfo, codec, data, want, logical_len=len(data) - 100)

    oracle = ec_util.encode(sinfo, codec, data, want)
    oracle_hi = ec_util.HashInfo(codec.get_chunk_count())
    oracle_hi.append(0, oracle)
    for i in want:
        assert bytes(shards[i]) == bytes(oracle[i]), f"shard {i}"
    assert hinfo.cumulative_shard_hashes == \
        oracle_hi.cumulative_shard_hashes
    assert hinfo.total_chunk_size == oracle_hi.total_chunk_size
    assert crc == cks.crc32c(0xFFFFFFFF, data[:len(data) - 100])
    # data shards must be zero-copy views, not copies
    assert isinstance(shards[0], StridedBuf)


def test_encode_with_hinfo_cumulative_append_contract():
    """hinfo from the fused path must equal a HashInfo that appended
    the same shards (the ECUtil.h:132-147 cumulative ledger)."""
    codec = create_erasure_code({
        "plugin": "ec_jax", "technique": "cauchy_good",
        "k": "4", "m": "2", "tpu": "false"})
    sinfo = ec_util.StripeInfo(4, 4 * 4096)
    data = np.random.default_rng(3).integers(
        0, 256, 8 * sinfo.get_stripe_width(), dtype=np.uint8).tobytes()
    shards, hinfo, _ = ec_util.encode_with_hinfo(
        sinfo, codec, data, range(6))
    ledger = ec_util.HashInfo(6)
    ledger.append(0, {i: bytes(b) for i, b in shards.items()})
    assert hinfo.cumulative_shard_hashes == \
        ledger.cumulative_shard_hashes


# -- MemStore adoption -------------------------------------------------------

def test_memstore_adopts_and_promotes():
    from ceph_tpu.os import ObjectId, Transaction
    from ceph_tpu.os.memstore import MemStore

    store = MemStore()
    store.mkfs()
    store.mount()
    payload = bytes(np.random.default_rng(4).integers(
        0, 256, 256 * 1024, dtype=np.uint8))
    t = Transaction()
    t.create_collection("c")
    t.write("c", ObjectId("o"), 0, len(payload), payload)
    store.queue_transaction(t)
    assert store.read("c", ObjectId("o")) == payload
    # partial overwrite promotes the adopted buffer to a private copy
    t = Transaction()
    t.write("c", ObjectId("o"), 10, 5, b"XXXXX")
    store.queue_transaction(t)
    got = store.read("c", ObjectId("o"))
    assert got[:10] == payload[:10] and got[10:15] == b"XXXXX"
    assert got[15:] == payload[15:]
    # truncate on an adopted buffer narrows without copying the world
    t = Transaction()
    t.write("c", ObjectId("p"), 0, len(payload), payload)
    t.truncate("c", ObjectId("p"), 1000)
    store.queue_transaction(t)
    assert store.read("c", ObjectId("p")) == payload[:1000]
    # StridedBuf adoption
    view = np.frombuffer(payload, dtype=np.uint8).reshape(64, 4096)
    sb = StridedBuf(view[::2])
    t = Transaction()
    t.write("c", ObjectId("q"), 0, len(sb), sb)
    store.queue_transaction(t)
    assert store.read("c", ObjectId("q")) == sb.tobytes()


def test_transaction_snapshots_mutable_buffers():
    """bytearrays are caller-mutable: the transaction must snapshot
    them; immutable buffers ride by reference (claim semantics)."""
    from ceph_tpu.os import ObjectId, Transaction
    from ceph_tpu.os.memstore import MemStore

    store = MemStore()
    store.mkfs()
    store.mount()
    buf = bytearray(b"A" * 128 * 1024)
    t = Transaction()
    t.create_collection("c")
    t.write("c", ObjectId("o"), 0, len(buf), buf)
    buf[:5] = b"BBBBB"  # mutate AFTER queueing, before apply
    store.queue_transaction(t)
    assert store.read("c", ObjectId("o"))[:5] == b"AAAAA"


# -- messenger loopback fast path -------------------------------------------

def test_local_fastpath_used_and_close_propagates():
    from ceph_tpu.msg import LocalConnection, Messenger
    from ceph_tpu.msg.messages import MPing

    async def main():
        got = []
        a, b = Messenger("a"), Messenger("b")
        a.local_fastpath = b.local_fastpath = True

        async def dispatch(conn, msg):
            got.append((conn.peer_name, msg))

        b.dispatcher = dispatch
        addr = await b.bind()
        conn = await a.connect(addr)
        assert isinstance(conn, LocalConnection)
        await conn.send(MPing(0, 1.0))
        await asyncio.sleep(0.05)
        assert len(got) == 1 and got[0][0] == "a"
        faults = []
        b.on_connection_fault = faults.append
        conn.close()
        await asyncio.sleep(0.05)
        # both ends closed, fault handler ran on the peer side
        assert conn.closed and len(faults) == 1
        with pytest.raises(ConnectionError):
            await conn.send(MPing(0, 2.0))
        await a.shutdown()
        await b.shutdown()

    run(main())


def test_local_fastpath_requires_matching_auth():
    """A mis-keyed or differently-secured peer must NOT ride the
    loopback path: that would bypass the socket handshake's
    rejection (permission laundering through the fast path)."""
    from ceph_tpu.common import auth
    from ceph_tpu.msg import LocalConnection, Messenger

    async def main():
        k1, k2 = auth.generate_secret(), auth.generate_secret()
        srv = Messenger("srv", secret=k1)
        srv.local_fastpath = True
        srv.dispatcher = lambda c, m: asyncio.sleep(0)
        addr = await srv.bind()
        # same key: local
        c_ok = Messenger("ok", secret=k1)
        c_ok.local_fastpath = True
        assert isinstance(await c_ok.connect(addr), LocalConnection)
        # wrong key: socket path (and the handshake then rejects it)
        c_bad = Messenger("bad", secret=k2)
        c_bad.local_fastpath = True
        conn = await c_bad.connect(addr)
        assert not isinstance(conn, LocalConnection)
        # secure-mode mismatch: socket path too
        c_sec = Messenger("sec", secret=k1)
        c_sec.local_fastpath = True
        c_sec.secure = True
        conn2 = await c_sec.connect(addr)
        assert not isinstance(conn2, LocalConnection)
        for m in (c_ok, c_bad, c_sec, srv):
            await m.shutdown()

    run(main())


def test_opt_out_messengers_use_sockets():
    from ceph_tpu.msg import LocalConnection, Messenger

    async def main():
        a, b = Messenger("a"), Messenger("b")  # no opt-in
        b.dispatcher = lambda c, m: asyncio.sleep(0)
        addr = await b.bind()
        conn = await a.connect(addr)
        assert not isinstance(conn, LocalConnection)
        await a.shutdown()
        await b.shutdown()

    run(main())


# -- OSD content digest -> ETag ---------------------------------------------

def test_ec_write_reply_carries_data_crc():
    profile = {"plugin": "ec_jax", "technique": "reed_sol_van",
               "k": "2", "m": "1", "crush-failure-domain": "osd",
               "tpu": "false"}

    async def main():
        cluster = Cluster(num_osds=4, osds_per_host=2)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool("ec", profile=profile,
                                                pg_num=4)
            io = cluster.client.open_ioctx("ec")
            payload = bytes(np.random.default_rng(7).integers(
                0, 256, 100_000, dtype=np.uint8))
            out = await io.write_full("obj", payload)
            assert out.get("data_crc") == cks.crc32c(0xFFFFFFFF,
                                                     payload)
            assert await io.read("obj") == payload
        finally:
            await cluster.stop()

    run(main())


def test_rgw_crc_etag_matches_content():
    """crc32c-mode ETags: the manifest-stitched digest must equal the
    digest of the bytes — across multiple stripes (combine math) and
    on the md5 fallback path."""
    profile = {"plugin": "ec_jax", "technique": "reed_sol_van",
               "k": "2", "m": "1", "crush-failure-domain": "osd",
               "tpu": "false"}

    async def main():
        from ceph_tpu.rgw import RGWLite

        cluster = Cluster(num_osds=4, osds_per_host=2)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "meta", size=2, pg_num=4)
            await cluster.client.create_ec_pool(
                "data", profile=profile, pg_num=4)
            rgw = RGWLite(cluster.client, "data", "meta",
                          stripe_size=256 * 1024, etag_hash="crc32c")
            await rgw.create_bucket("b")
            # 3.5 stripes: exercises the crc32c_combine stitching
            payload = bytes(np.random.default_rng(8).integers(
                0, 256, 896 * 1024, dtype=np.uint8))
            etag = await rgw.put_object("b", "k", payload)
            assert etag == "%08x" % cks.crc32c(0xFFFFFFFF, payload)
            got, etag2 = await rgw.get_object_ex("b", "k")
            assert got == payload and etag2 == etag
        finally:
            await cluster.stop()

    run(main())

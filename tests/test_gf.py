"""GF(2^8) kernel substrate tests.

Mirrors the role of the reference's low-level galois/jerasure checks: field
axioms, table integrity, bit-decomposition equivalence, and TPU-kernel vs
host-oracle agreement.
"""

import numpy as np
import pytest

from ceph_tpu.ops import gf


def py_gf_mul(a: int, b: int) -> int:
    """Bit-serial GF(2^8) multiply — independent of the table build."""
    r = 0
    for _ in range(8):
        if b & 1:
            r ^= a
        b >>= 1
        carry = a & 0x80
        a = (a << 1) & 0xFF
        if carry:
            a ^= gf.GF_POLY & 0xFF
    return r


def test_tables_against_bit_serial_mul():
    rng = np.random.default_rng(0)
    for _ in range(2000):
        a, b = int(rng.integers(0, 256)), int(rng.integers(0, 256))
        assert int(gf.gf_mul(np.uint8(a), np.uint8(b))) == py_gf_mul(a, b)


def test_field_axioms():
    # generator order 255; inverses; distributivity (spot check)
    seen = set()
    x = 1
    for _ in range(255):
        seen.add(x)
        x = py_gf_mul(x, 2)
    assert len(seen) == 255 and x == 1
    for a in range(1, 256):
        assert py_gf_mul(a, gf.gf_inv(a)) == 1
    rng = np.random.default_rng(1)
    for _ in range(200):
        a, b, c = (int(v) for v in rng.integers(0, 256, 3))
        assert py_gf_mul(a, b ^ c) == py_gf_mul(a, b) ^ py_gf_mul(a, c)


def test_const_to_bits_linearity():
    rng = np.random.default_rng(2)
    for _ in range(100):
        c, d = int(rng.integers(0, 256)), int(rng.integers(0, 256))
        m = gf.gf_const_to_bits(c)
        dbits = np.array([(d >> b) & 1 for b in range(8)], dtype=np.uint8)
        ybits = (m @ dbits) & 1
        y = int(sum(int(v) << o for o, v in enumerate(ybits)))
        assert y == py_gf_mul(c, d)


def test_gf_matmul_ref_small():
    m = np.array([[1, 1], [1, 2]], dtype=np.uint8)
    d = np.array([[3, 7], [5, 11]], dtype=np.uint8)
    out = gf.gf_matmul_ref(m, d)
    assert out[0, 0] == 3 ^ 5
    assert out[1, 1] == 7 ^ py_gf_mul(2, 11)


def test_invert_matrix():
    rng = np.random.default_rng(3)
    for n in (2, 4, 8):
        while True:
            a = rng.integers(0, 256, (n, n)).astype(np.uint8)
            try:
                inv = gf.gf_invert_matrix(a)
                break
            except np.linalg.LinAlgError:
                continue
        prod = gf.gf_matmul_ref(a, inv)
        assert np.array_equal(prod, np.eye(n, dtype=np.uint8))


@pytest.mark.parametrize("k,m,s", [(2, 1, 64), (4, 2, 256), (8, 3, 1024)])
def test_tpu_kernel_matches_host_oracle(k, m, s):
    rng = np.random.default_rng(4)
    mat = rng.integers(0, 256, (m, k)).astype(np.uint8)
    data = rng.integers(0, 256, (k, s)).astype(np.uint8)
    want = gf.gf_matmul_ref(mat, data)
    got = np.asarray(gf.gf_matmul_tpu(mat, data))
    assert np.array_equal(want, got)


def test_tpu_kernel_batched():
    rng = np.random.default_rng(5)
    k, m, s, b = 4, 2, 128, 5
    mat = rng.integers(0, 256, (m, k)).astype(np.uint8)
    data = rng.integers(0, 256, (b, k, s)).astype(np.uint8)
    got = np.asarray(gf.gf_matmul_tpu(mat, data))
    assert got.shape == (b, m, s)
    for i in range(b):
        assert np.array_equal(gf.gf_matmul_ref(mat, data[i]), got[i])


@pytest.fixture
def pallas_interpret():
    """Run the Pallas words kernels in interpret mode on CPU."""
    from ceph_tpu.ops import gf_pallas
    if not gf_pallas.HAVE_JAX:
        pytest.skip("jax unavailable")
    gf_pallas.FORCE_INTERPRET = True
    try:
        yield gf_pallas
    finally:
        gf_pallas.FORCE_INTERPRET = False
        gf_pallas._spec_call.cache_clear()
        gf_pallas._gen_call.cache_clear()


def test_pallas_words_roundtrip(pallas_interpret):
    gfp = pallas_interpret
    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, (3, 2, 1024), dtype=np.uint8)
    w = gfp.words_from_bytes(data)
    assert w.shape == (3, 2, 2, 128) and w.dtype == np.int32
    assert np.array_equal(gfp.bytes_from_words(w), data)


@pytest.mark.parametrize("k,m,s,b", [(2, 1, 512, 1), (4, 2, 1024, 2),
                                     (8, 3, 1536, 1)])
def test_pallas_generic_kernel_matches_oracle(pallas_interpret, k, m, s, b):
    gfp = pallas_interpret
    rng = np.random.default_rng(12)
    mat = rng.integers(0, 256, (m, k)).astype(np.uint8)
    data = rng.integers(0, 256, (b, k, s)).astype(np.uint8)
    got = gfp.gf_matmul_pallas(mat, data)
    for i in range(b):
        assert np.array_equal(got[i], gf.gf_matmul_ref(mat, data[i]))


def test_pallas_specialized_kernel_matches_oracle(pallas_interpret):
    gfp = pallas_interpret
    from ceph_tpu.models import reed_solomon as rs
    mat = rs.reed_sol_van_matrix(8, 3)
    gfp.register_matrix(mat)
    rng = np.random.default_rng(13)
    data = rng.integers(0, 256, (8, 2048), dtype=np.uint8)
    got = gfp.gf_matmul_pallas(mat, data)
    assert np.array_equal(got, gf.gf_matmul_ref(mat, data))


def test_pallas_decode_matrix_generic_path(pallas_interpret):
    """Decode matrices (unregistered) run the generic SMEM kernel and
    reconstruct erased chunks bit-exactly."""
    gfp = pallas_interpret
    from ceph_tpu.models import reed_solomon as rs
    k, m = 4, 2
    mat = rs.reed_sol_van_matrix(k, m)
    rng = np.random.default_rng(14)
    data = rng.integers(0, 256, (k, 512), dtype=np.uint8)
    parity = gf.gf_matmul_ref(mat, data)
    chunks = np.concatenate([data, parity], axis=0)
    have = [1, 2, 3, 4]
    dmat = rs.decode_matrix(mat, k, [0], have)
    assert gfp._coeff_key(dmat) not in gfp._registered
    got = gfp.gf_matmul_pallas(dmat, chunks[have])
    assert np.array_equal(got[0], data[0])


def test_gf_mul_jax_matches():
    rng = np.random.default_rng(6)
    a = rng.integers(0, 256, 512).astype(np.uint8)
    b = rng.integers(0, 256, 512).astype(np.uint8)
    assert np.array_equal(np.asarray(gf.gf_mul_jax(a, b)), gf.gf_mul(a, b))


@pytest.mark.parametrize("k,m,s", [(2, 1, 64), (4, 2, 4096),
                                   (8, 3, 100_003), (10, 4, 16 * 1024)])
def test_simd_host_matmul_matches_oracle(k, m, s):
    """Native SIMD GF matmul (gf_simd.cc split-table shuffle) is bit-exact
    vs the numpy oracle, incl. non-vector-aligned tails."""
    rng = np.random.default_rng(7)
    mat = rng.integers(0, 256, (m, k)).astype(np.uint8)
    data = rng.integers(0, 256, (k, s)).astype(np.uint8)
    assert np.array_equal(gf.gf_matmul_host(mat, data),
                          gf.gf_matmul_ref(mat, data))


def test_simd_region_mad_matches():
    from ceph_tpu import native
    lib = native.get_lib()
    if lib is None or not hasattr(lib, "ceph_tpu_gf_region_mad_v"):
        pytest.skip("native SIMD tier unavailable")
    import ctypes
    rng = np.random.default_rng(8)
    for n in (1, 15, 16, 31, 32, 63, 64, 1000, 4097):
        src = rng.integers(0, 256, n).astype(np.uint8)
        dst = rng.integers(0, 256, n).astype(np.uint8)
        c = 0x53
        tbl = gf.gf_mul(np.full(256, c, np.uint8),
                        np.arange(256, dtype=np.uint8))
        want = dst ^ gf.gf_mul(np.full(n, c, np.uint8), src)
        got = dst.copy()
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.ceph_tpu_gf_region_mad_v(
            got.ctypes.data_as(u8p), src.ctypes.data_as(u8p), n,
            np.ascontiguousarray(tbl).ctypes.data_as(u8p))
        assert np.array_equal(want, got), n

"""Swift API dialect: TempAuth handshake + account/container/object
verbs, interoperating with the S3 dialect over one gateway.

Reference parity: rgw_rest_swift.cc / rgw_swift_auth.cc — radosgw
serves both APIs over the same buckets; an object PUT via Swift is
readable via S3 and vice versa."""

import asyncio
import json

from cluster_helpers import Cluster

from ceph_tpu.rgw import RGWLite
from ceph_tpu.rgw.swift_frontend import SwiftFrontend


async def _http(addr, method, path, headers=None, body=b""):
    host, port = addr.rsplit(":", 1)
    reader, writer = await asyncio.open_connection(host, int(port),
                                                   limit=8 << 20)
    req = [f"{method} {path} HTTP/1.1\r\n",
           f"Host: {addr}\r\n",
           f"Content-Length: {len(body)}\r\n",
           "Connection: close\r\n"]
    for k, v in (headers or {}).items():
        req.append(f"{k}: {v}\r\n")
    req.append("\r\n")
    writer.write("".join(req).encode() + body)
    await writer.drain()
    status = int((await reader.readline()).split()[1])
    rhdrs = {}
    while True:
        line = await reader.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        k, _, v = line.decode().partition(":")
        rhdrs[k.strip().lower()] = v.strip()
    rbody = await reader.read()
    writer.close()
    return status, rhdrs, rbody


def test_swift_end_to_end_and_s3_interop():
    async def run():
        cluster = Cluster(num_osds=2, osds_per_host=1)
        await cluster.start()
        fe = None
        try:
            await cluster.client.create_replicated_pool(
                "rgw.meta", size=2, pg_num=4)
            await cluster.client.create_replicated_pool(
                "rgw.data", size=2, pg_num=4)
            rgw = RGWLite(cluster.client, "rgw.data", "rgw.meta")
            fe = SwiftFrontend(rgw, {"demo": "sw1ftkey"})
            addr = await fe.start()

            # bad key refused
            st, _, _ = await _http(addr, "GET", "/auth/v1.0",
                                   {"X-Auth-User": "demo",
                                    "X-Auth-Key": "wrong"})
            assert st == 401
            # TempAuth handshake
            st, h, _ = await _http(addr, "GET", "/auth/v1.0",
                                   {"X-Auth-User": "demo:admin",
                                    "X-Auth-Key": "sw1ftkey"})
            assert st == 200
            tok = h["x-auth-token"]
            assert h["x-storage-url"].endswith("/v1/AUTH_demo")
            auth = {"X-Auth-Token": tok}

            # tokenless request bounced
            st, _, _ = await _http(addr, "GET", "/v1/AUTH_demo")
            assert st == 401

            # container + object lifecycle
            st, _, _ = await _http(addr, "PUT",
                                   "/v1/AUTH_demo/photos", auth)
            assert st == 201
            st, _, _ = await _http(addr, "PUT",
                                   "/v1/AUTH_demo/photos", auth)
            assert st == 202  # idempotent re-PUT (Swift semantics)
            data = b"swift object payload" * 100
            st, h, _ = await _http(addr, "PUT",
                                   "/v1/AUTH_demo/photos/pic1",
                                   auth, body=data)
            assert st == 201
            st, h, got = await _http(addr, "GET",
                                     "/v1/AUTH_demo/photos/pic1",
                                     auth)
            assert st == 200 and got == data
            # listings: plain + json
            st, _, listing = await _http(addr, "GET",
                                         "/v1/AUTH_demo/photos",
                                         auth)
            assert st == 200 and listing == b"pic1\n"
            st, _, js = await _http(
                addr, "GET", "/v1/AUTH_demo/photos?format=json",
                auth)
            doc = json.loads(js)
            assert doc[0]["name"] == "pic1"
            assert doc[0]["bytes"] == len(data)
            st, _, accts = await _http(addr, "GET", "/v1/AUTH_demo",
                                       auth)
            assert st == 200 and b"photos" in accts

            # S3-dialect interop: the same object through the S3 op
            # layer (shared bucket namespace, one gateway)
            assert await rgw.get_object("photos", "pic1") == data
            await rgw.put_object("photos", "from-s3", b"s3 bytes")
            st, _, got = await _http(addr, "GET",
                                     "/v1/AUTH_demo/photos/from-s3",
                                     auth)
            assert st == 200 and got == b"s3 bytes"

            # deletes
            st, _, _ = await _http(addr, "DELETE",
                                   "/v1/AUTH_demo/photos/pic1", auth)
            assert st == 204
            st, _, _ = await _http(addr, "DELETE",
                                   "/v1/AUTH_demo/photos", auth)
            assert st == 409  # not empty (from-s3 remains)
            st, _, _ = await _http(addr, "DELETE",
                                   "/v1/AUTH_demo/photos/from-s3",
                                   auth)
            assert st == 204
            st, _, _ = await _http(addr, "DELETE",
                                   "/v1/AUTH_demo/photos", auth)
            assert st == 204
        finally:
            if fe is not None:
                await fe.stop()
            await cluster.stop()

    asyncio.run(asyncio.wait_for(run(), 120))

"""Encoding + OSDMap tests, mirroring TestOSDMap.cc coverage: placement
pipeline (raw->upmap->up->temp), incrementals, encode/decode round trips,
bulk mapping consistency, osdmaptool."""

import pytest

from ceph_tpu.common.encoding import Decoder, DecodeError, Encoder
from ceph_tpu.crush.map import CRUSH_ITEM_NONE
from ceph_tpu.osd.osdmap import (
    CEPH_OSD_EXISTS,
    CEPH_OSD_IN,
    CEPH_OSD_UP,
    Incremental,
    OSDMap,
    OSDMapMapping,
    PgId,
    PgPool,
    TYPE_ERASURE,
    TYPE_REPLICATED,
    ceph_stable_mod,
)


# -- encoding --------------------------------------------------------------


def test_encoding_primitives_round_trip():
    enc = Encoder()
    enc.u8(7)
    enc.u32(0xDEADBEEF)
    enc.s64(-12345678901234)
    enc.f64(3.5)
    enc.string("héllo")
    enc.bytes(b"\x00\x01")
    enc.list([1, 2, 3], Encoder.u16)
    enc.map({"a": 1, "b": 2}, Encoder.string, Encoder.u32)
    enc.optional(None, Encoder.u32)
    enc.optional(9, Encoder.u32)
    dec = Decoder(enc.to_bytes())
    assert dec.u8() == 7
    assert dec.u32() == 0xDEADBEEF
    assert dec.s64() == -12345678901234
    assert dec.f64() == 3.5
    assert dec.string() == "héllo"
    assert dec.bytes() == b"\x00\x01"
    assert dec.list(Decoder.u16) == [1, 2, 3]
    assert dec.map(Decoder.string, Decoder.u32) == {"a": 1, "b": 2}
    assert dec.optional(Decoder.u32) is None
    assert dec.optional(Decoder.u32) == 9
    assert dec.remaining() == 0


def test_encoding_versioned_skip_unknown_tail():
    """A v2 encoder appends fields a v1 decoder doesn't know: DECODE_FINISH
    must skip them (the rolling-upgrade contract)."""
    enc = Encoder()
    enc.start(2, 1)
    enc.u32(42)
    enc.string("new field the old decoder ignores")
    enc.finish()
    enc.u32(777)  # data after the struct
    dec = Decoder(enc.to_bytes())
    v = dec.start(1)
    assert v == 2
    assert dec.u32() == 42
    dec.finish()              # skips the unknown string
    assert dec.u32() == 777


def test_encoding_compat_rejection():
    enc = Encoder()
    enc.start(5, 3)
    enc.u32(1)
    enc.finish()
    dec = Decoder(enc.to_bytes())
    with pytest.raises(DecodeError):
        dec.start(2)          # we only understand compat 2 < 3


def test_encoding_bounds_checked():
    dec = Decoder(b"\x01\x00")
    with pytest.raises(DecodeError):
        dec.u32()


# -- stable mod ------------------------------------------------------------


def test_ceph_stable_mod():
    # pg_num 12, mask 15: values >= 12 fold to & 7
    assert ceph_stable_mod(5, 12, 15) == 5
    assert ceph_stable_mod(13, 12, 15) == 13 & 7
    for x in range(64):
        assert 0 <= ceph_stable_mod(x, 12, 15) < 12


# -- OSDMap placement ------------------------------------------------------


@pytest.fixture
def osdmap():
    m = OSDMap.build_simple(12, osds_per_host=3)
    m.create_pool("data", size=3, pg_num=32)
    return m


def test_build_simple(osdmap):
    assert osdmap.max_osd == 12
    assert all(osdmap.is_up(o) and osdmap.is_in(o) for o in range(12))
    assert osdmap.lookup_pool("data") == 1
    assert osdmap.lookup_pool("nope") == -1


def test_placement_basic(osdmap):
    seen = set()
    for ps in range(32):
        up, up_p, acting, acting_p = osdmap.pg_to_up_acting_osds(
            PgId(1, ps))
        assert len(up) == 3
        assert len(set(up)) == 3             # distinct osds
        assert up_p == up[0]
        assert acting == up and acting_p == up_p
        seen.update(up)
    assert len(seen) >= 10                   # spread over the cluster


def test_placement_out_of_range_pg(osdmap):
    up, up_p, acting, acting_p = osdmap.pg_to_up_acting_osds(PgId(1, 999))
    assert up == [] and up_p == -1
    up, up_p, acting, acting_p = osdmap.pg_to_up_acting_osds(PgId(9, 0))
    assert up == [] and acting == []


def test_down_osd_filtered(osdmap):
    pg = PgId(1, 5)
    up0, _p, _a, _ap = osdmap.pg_to_up_acting_osds(pg)
    victim = up0[0]
    osdmap.osd_state[victim] &= ~CEPH_OSD_UP
    up1, p1, _a1, _ap1 = osdmap.pg_to_up_acting_osds(pg)
    assert victim not in up1
    assert len(up1) == 2                     # replicated pool shifts
    assert p1 == up1[0]


def test_erasure_pool_holes():
    m = OSDMap.build_simple(12, osds_per_host=3)
    ruleno = m.crush.add_simple_rule(
        "ecrule", "default", "host", "", "indep", pool_type="erasure")
    m.create_pool("ecpool", type_=TYPE_ERASURE, size=4, pg_num=16,
                  crush_rule=ruleno)
    pg = PgId(1, 3)
    up0, _p, _a, _ap = m.pg_to_up_acting_osds(pg)
    assert len(up0) == 4
    victim = up0[2]
    m.osd_state[victim] &= ~CEPH_OSD_UP
    up1, _p1, _a1, _ap1 = m.pg_to_up_acting_osds(pg)
    assert len(up1) == 4
    assert up1[2] == CRUSH_ITEM_NONE         # positional hole, no shift
    assert [o for i, o in enumerate(up1) if i != 2] == \
        [o for i, o in enumerate(up0) if i != 2]


def test_pg_temp_overrides_acting(osdmap):
    pg = PgId(1, 7)
    up, up_p, acting, acting_p = osdmap.pg_to_up_acting_osds(pg)
    override = [o for o in range(12) if o not in up][:3]
    osdmap.pg_temp[pg] = override
    up2, up_p2, acting2, acting_p2 = osdmap.pg_to_up_acting_osds(pg)
    assert up2 == up                         # up unchanged
    assert acting2 == override
    assert acting_p2 == override[0]
    osdmap.primary_temp[pg] = override[1]
    _u, _up, _a, acting_p3 = osdmap.pg_to_up_acting_osds(pg)
    assert acting_p3 == override[1]


def test_pg_upmap(osdmap):
    pg = PgId(1, 9)
    up0, _p, _a, _ap = osdmap.pg_to_up_acting_osds(pg)
    spare = [o for o in range(12) if o not in up0]
    target = [spare[0], spare[1], up0[2]]
    osdmap.pg_upmap[pg] = target
    up1, _p1, _a1, _ap1 = osdmap.pg_to_up_acting_osds(pg)
    assert up1 == target


def test_pg_upmap_items(osdmap):
    pg = PgId(1, 11)
    up0, _p, _a, _ap = osdmap.pg_to_up_acting_osds(pg)
    spare = [o for o in range(12) if o not in up0][0]
    osdmap.pg_upmap_items[pg] = [(up0[1], spare)]
    up1, _p1, _a1, _ap1 = osdmap.pg_to_up_acting_osds(pg)
    assert up1[1] == spare
    assert up1[0] == up0[0] and up1[2] == up0[2]


def test_upmap_rejected_when_target_out(osdmap):
    pg = PgId(1, 9)
    up0, _p, _a, _ap = osdmap.pg_to_up_acting_osds(pg)
    spare = [o for o in range(12) if o not in up0][0]
    osdmap.osd_weight[spare] = 0             # marked out
    osdmap.pg_upmap[pg] = [spare] + up0[1:]
    up1, _p1, _a1, _ap1 = osdmap.pg_to_up_acting_osds(pg)
    assert up1 == up0                        # explicit mapping ignored


def test_primary_affinity(osdmap):
    pg = PgId(1, 4)
    up0, p0, _a, _ap = osdmap.pg_to_up_acting_osds(pg)
    osdmap.osd_primary_affinity = [0x10000] * 12
    osdmap.osd_primary_affinity[p0] = 0      # never primary
    up1, p1, _a1, _ap1 = osdmap.pg_to_up_acting_osds(pg)
    assert p1 != p0
    assert p1 in up0
    assert up1[0] == p1                      # replicated: moved to front


def test_incremental_apply(osdmap):
    epoch0 = osdmap.epoch
    inc = Incremental(epoch=epoch0 + 1)
    inc.new_state[3] = CEPH_OSD_UP           # XOR: up -> down
    inc.new_weight[5] = 0                    # mark out
    inc.new_erasure_code_profiles["myprofile"] = {
        "plugin": "jerasure", "k": "4", "m": "2"}
    osdmap.apply_incremental(inc)
    assert osdmap.epoch == epoch0 + 1
    assert osdmap.is_down(3)
    assert osdmap.is_out(5)
    assert osdmap.erasure_code_profiles["myprofile"]["k"] == "4"
    # wrong epoch rejected
    with pytest.raises(AssertionError):
        osdmap.apply_incremental(Incremental(epoch=epoch0 + 5))
    # revive via XOR
    inc2 = Incremental(epoch=osdmap.epoch + 1)
    inc2.new_state[3] = CEPH_OSD_UP
    inc2.new_weight[5] = CEPH_OSD_IN
    osdmap.apply_incremental(inc2)
    assert osdmap.is_up(3) and osdmap.is_in(5)


def test_pg_temp_incremental_removal(osdmap):
    pg = PgId(1, 2)
    inc = Incremental(epoch=osdmap.epoch + 1)
    inc.new_pg_temp[pg] = [0, 1, 2]
    osdmap.apply_incremental(inc)
    assert osdmap.pg_temp[pg] == [0, 1, 2]
    inc2 = Incremental(epoch=osdmap.epoch + 1)
    inc2.new_pg_temp[pg] = []                # empty list removes
    osdmap.apply_incremental(inc2)
    assert pg not in osdmap.pg_temp


def test_osdmap_encode_decode(osdmap):
    osdmap.erasure_code_profiles["p"] = {"plugin": "jerasure", "k": "2",
                                         "m": "1"}
    osdmap.pg_temp[PgId(1, 3)] = [4, 5, 6]
    osdmap.pg_upmap_items[PgId(1, 4)] = [(1, 7)]
    data = osdmap.encode()
    m2 = OSDMap.decode(data)
    assert m2.epoch == osdmap.epoch
    assert m2.max_osd == osdmap.max_osd
    assert m2.pools[1].name == "data"
    assert m2.erasure_code_profiles == osdmap.erasure_code_profiles
    assert m2.pg_temp == osdmap.pg_temp
    assert m2.pg_upmap_items == {PgId(1, 4): [(1, 7)]}
    # placements identical after the round trip
    for ps in range(32):
        assert m2.pg_to_up_acting_osds(PgId(1, ps)) == \
            osdmap.pg_to_up_acting_osds(PgId(1, ps))


def test_bulk_mapping_matches_single(osdmap):
    osdmap.pg_temp[PgId(1, 6)] = [0, 4, 8]
    mapping = OSDMapMapping(osdmap)
    for ps in range(32):
        pg = PgId(1, ps)
        assert mapping.get(pg) == osdmap.pg_to_up_acting_osds(pg), pg
    by_osd = mapping.pgs_by_osd()
    assert sum(len(v) for v in by_osd.values()) == 32 * 3


def test_osdmaptool(tmp_path, capsys):
    from ceph_tpu.tools import osdmaptool

    path = str(tmp_path / "osdmap")
    assert osdmaptool.run([path, "--createsimple", "8",
                           "--with-default-pool"]) == 0
    assert osdmaptool.run([path, "--print"]) == 0
    out = capsys.readouterr().out
    assert "max_osd 8" in out and "pool 1 'rbd'" in out
    assert osdmaptool.run([path, "--test-map-pgs"]) == 0
    out = capsys.readouterr().out
    assert "avg" in out
    assert osdmaptool.run([path, "--test-map-pg", "1.3"]) == 0
    out = capsys.readouterr().out
    assert "acting" in out
    crush_out = str(tmp_path / "crush.json")
    assert osdmaptool.run([path, "--export-crush", crush_out]) == 0
    assert osdmaptool.run([path, "--import-crush", crush_out]) == 0


def test_min_size_defaults():
    m = OSDMap.build_simple(8)
    repl = m.create_pool("r4", size=4)
    assert repl.min_size == 2                # size - size/2
    m.erasure_code_profiles["p83"] = {"plugin": "jerasure", "k": "8",
                                      "m": "3"}
    ec = m.create_pool("ec", type_=TYPE_ERASURE, size=11,
                       erasure_code_profile="p83")
    assert ec.min_size == 9                  # k + 1


def test_upmap_rejected_precludes_upmap_items(osdmap):
    """An explicit pg_upmap entry rejected (target out) must also suppress
    pg_upmap_items for that pg (OSDMap::_apply_upmap returns early)."""
    pg = PgId(1, 9)
    up0, _p, _a, _ap = osdmap.pg_to_up_acting_osds(pg)
    spares = [o for o in range(12) if o not in up0]
    osdmap.osd_weight[spares[0]] = 0          # out -> upmap rejected
    osdmap.pg_upmap[pg] = [spares[0]] + up0[1:]
    osdmap.pg_upmap_items[pg] = [(up0[1], spares[1])]
    up1, _p1, _a1, _ap1 = osdmap.pg_to_up_acting_osds(pg)
    assert up1 == up0                         # items NOT applied either


def test_pool_opts_typed_round_trip(osdmap):
    """Typed pool opts (ints/floats) survive encode/decode (advisor)."""
    pool = osdmap.pools[1]
    pool.opts = {"compression_mode": "force", "csum_type": 3,
                 "compression_required_ratio": 0.7}
    m2 = OSDMap.decode(osdmap.encode())
    assert m2.pools[1].opts == pool.opts
    assert isinstance(m2.pools[1].opts["csum_type"], int)
    assert isinstance(m2.pools[1].opts["compression_required_ratio"], float)


def test_upmap_applied_falls_through_to_items(osdmap):
    """An APPLIED explicit pg_upmap does NOT suppress pg_upmap_items:
    the reference falls through and applies both
    (OSDMap.cc:2478-2481 "continue to check and apply")."""
    pg = PgId(1, 9)
    up0, _p, _a, _ap = osdmap.pg_to_up_acting_osds(pg)
    spares = [o for o in range(12) if o not in up0]
    explicit = [spares[0], up0[1], up0[2]]
    osdmap.pg_upmap[pg] = explicit
    osdmap.pg_upmap_items[pg] = [(up0[1], spares[1])]
    up1, _p1, _a1, _ap1 = osdmap.pg_to_up_acting_osds(pg)
    assert up1 == [spares[0], spares[1], up0[2]]

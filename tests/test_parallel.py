"""Sharded pipeline tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from ceph_tpu import parallel
from ceph_tpu.crush import kernel as ck
from ceph_tpu.crush.map import build_flat_cluster
from ceph_tpu.ec.registry import create_erasure_code
from ceph_tpu.models import reed_solomon as rs
from ceph_tpu.ops import checksum as cks
from ceph_tpu.ops import gf


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest should force 8 CPU devices"
    return parallel.make_mesh()


def test_mesh_axes(mesh):
    assert mesh.shape["dp"] * mesh.shape["sp"] == 8
    assert mesh.shape["sp"] == 4


def _mk_pipeline(mesh, k=4, m=2, chunk=512, rule=None, result_max=0):
    return parallel.ShardedPipeline(
        mesh, k, m, chunk, rs.reed_sol_van_matrix(k, m),
        placement_rule=rule, result_max=result_max)


class TestShardedEncode:
    def test_parity_matches_host(self, mesh):
        k, m, chunk, b = 4, 2, 512, 8
        pipe = _mk_pipeline(mesh, k, m, chunk)
        rng = np.random.default_rng(31)
        data = rng.integers(0, 256, (b, k, chunk), dtype=np.uint8)
        parity, crcs, _ = pipe.encode(pipe.put_stripes(data))
        parity = np.asarray(parity)
        for i in range(b):
            ref = gf.gf_matmul_ref(rs.reed_sol_van_matrix(k, m), data[i])
            np.testing.assert_array_equal(parity[i], ref)

    def test_hinfo_crcs_match_host(self, mesh):
        k, m, chunk, b = 4, 2, 512, 8
        pipe = _mk_pipeline(mesh, k, m, chunk)
        rng = np.random.default_rng(37)
        data = rng.integers(0, 256, (b, k, chunk), dtype=np.uint8)
        parity, crcs, _ = pipe.encode(pipe.put_stripes(data))
        parity, crcs = np.asarray(parity), np.asarray(crcs)
        for i in range(b):
            for c in range(k):
                assert crcs[i, c] == cks.crc32c(0xFFFFFFFF, data[i, c])
            for j in range(m):
                assert crcs[i, k + j] == cks.crc32c(0xFFFFFFFF, parity[i, j])

    def test_bit_exact_vs_codec(self, mesh):
        """Sharded parity == the single-chip ec_jax plugin == host oracle."""
        k, m, chunk = 8, 3, 1024
        pipe = _mk_pipeline(mesh, k, m, chunk)
        rng = np.random.default_rng(41)
        data = rng.integers(0, 256, (8, k, chunk), dtype=np.uint8)
        parity = np.asarray(pipe.encode(pipe.put_stripes(data))[0])
        codec = create_erasure_code(
            {"plugin": "ec_jax", "k": str(k), "m": str(m)})
        ref = codec.encode_batch(data)
        np.testing.assert_array_equal(parity, np.asarray(ref))

    def test_decode_recovers(self, mesh):
        k, m, chunk, b = 4, 2, 512, 8
        pipe = _mk_pipeline(mesh, k, m, chunk)
        rng = np.random.default_rng(43)
        data = rng.integers(0, 256, (b, k, chunk), dtype=np.uint8)
        parity = np.asarray(pipe.encode(pipe.put_stripes(data))[0])
        # erase chunks 1 and 4 (one data, one parity); decode data chunk 1
        have = [0, 2, 3, 4]  # logical chunk ids used for reconstruction
        full = np.concatenate([data, parity], axis=1)
        survivors = full[:, have, :]
        matrix = rs.reed_sol_van_matrix(k, m)
        dmat = rs.decode_matrix(matrix, k, [1], have)
        out = np.asarray(pipe.decode(dmat, pipe.put_stripes(survivors)))
        np.testing.assert_array_equal(out[:, 0, :], data[:, 1, :])


class TestShardedPlacement:
    def test_placement_matches_host_kernel(self, mesh):
        cmap = build_flat_cluster(32, osds_per_host=4)
        ruleno = cmap.add_simple_rule(
            "ecrule", "default", "host", "", "indep", pool_type="erasure")
        rule = ck.compile_rule(cmap, ruleno, result_max=3)
        pipe = _mk_pipeline(mesh, rule=rule, result_max=3)
        rng = np.random.default_rng(47)
        data = rng.integers(0, 256, (8, 4, 512), dtype=np.uint8)
        pgs = np.arange(8, dtype=np.int32) * 131
        _, _, placement = pipe.encode(pipe.put_stripes(data), pgs)
        expected = rule(pgs)
        np.testing.assert_array_equal(np.asarray(placement), expected)


def test_codec_device_path_rides_mesh_pipeline(mesh):
    """The EC codec's device dispatch must route through the
    default-mesh ShardedPipeline (parallel/backend.py) — the cluster's
    own datapath and the multi-chip dryrun share one program."""
    import numpy as np

    from ceph_tpu.ec.registry import create_erasure_code
    from ceph_tpu.ops import gf
    from ceph_tpu.parallel import backend

    assert mesh.shape["dp"] * mesh.shape["sp"] == 8
    backend._pipeline.cache_clear()
    old = backend.default_mesh
    backend.default_mesh = lambda: mesh
    try:
        codec = create_erasure_code({
            "plugin": "ec_jax", "technique": "reed_sol_van",
            "k": "4", "m": "2", "tpu": "true", "tpu-min-bytes": "1"})
        rng = np.random.default_rng(3)
        # batch NOT divisible by dp, byte axis divisible by sp
        data = rng.integers(0, 256, (5, 4, 64 * mesh.shape["sp"]),
                            dtype=np.uint8)
        before = backend.stats["matmul_calls"]
        par = codec.encode_batch(data)
        assert backend.stats["matmul_calls"] > before
        want = np.stack([gf.gf_matmul_host(codec.matrix, d)
                         for d in data])
        assert np.array_equal(np.asarray(par), want)
        # decode rows over the same path
        dec = codec.decode_batch((2, 3, 4, 5), (0, 1),
                                 data[:, :4, :])
        assert np.asarray(dec).shape == (5, 2, data.shape[2])
    finally:
        backend.default_mesh = old
        backend._pipeline.cache_clear()

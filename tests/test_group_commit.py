"""Group commit (os/groupcommit.py + TPUStore.submit_batch) and the
zero-copy buffer discipline (PR 12).

Four tiers:

1. Store: submit_batch merges N txns into ONE sync commit + at most
   one fsync, read-your-writes spans the batch, per-txn on_commit
   fires in order after the shared barrier, and a failing txn is
   isolated (it alone reports; the rest commit).
2. Committer: concurrent awaits share a barrier, FIFO ordering holds
   across window/bypass/sync-flush lanes, the kill switch is
   behavior-parity, and drains leave nothing stranded.
3. Crash: the PR-8 sweep with batching ARMED — zero violations, the
   broken-store self-tests still caught — plus a cut INSIDE an
   accumulating window: unacked txns vanish wholesale, acked never.
4. Zero-copy: bit-exact readback through the REAL wire path while
   the client thrashes its buffers after each ack, and the
   sub-read-reply views' immutability discipline.
"""

from __future__ import annotations

import asyncio
import os

import numpy as np
import pytest

from ceph_tpu.os import ObjectId, Transaction
from ceph_tpu.os.faultstore import (
    BrokenBlockStore, BrokenCommitStore, CrashSweep, FaultStore,
    build_image, write_image,
)
from ceph_tpu.os.groupcommit import GroupCommitter
from ceph_tpu.os.memstore import MemStore
from ceph_tpu.os.tpustore import TPUStore

from cluster_helpers import Cluster, tpustore_factory


def _store(path) -> TPUStore:
    s = TPUStore(str(path))
    s.mkfs()
    s.mount()
    t = Transaction()
    t.create_collection("cc")
    s.queue_transaction(t)
    return s


def _wtxn(i: int, size: int = 8192, oid: str = None) -> Transaction:
    t = Transaction()
    data = bytes([i % 256]) * size
    t.write("cc", ObjectId(oid or f"o{i}"), 0, len(data), data)
    return t


# -- 1. store tier ----------------------------------------------------------


def test_submit_batch_one_barrier_for_n_txns(tmp_path):
    s = _store(tmp_path)
    before = dict(s.perf)
    fired = []
    txns = []
    for i in range(8):
        t = _wtxn(i, size=100 * 1024)
        t.register_on_commit(lambda i=i: fired.append(i))
        txns.append(t)
    assert s.submit_batch(txns) == [None] * 8
    # ONE kv sync commit, ONE block fsync — for eight durable writes
    assert s.perf["kv_commits"] - before["kv_commits"] == 1
    assert s.perf["block_fsyncs"] - before["block_fsyncs"] == 1
    assert s.perf["gc_batches"] == 1
    assert s.perf["gc_txns"] == 8
    assert s.perf["gc_fsyncs_saved"] == 7
    assert s.perf["gc_kv_commits_saved"] == 7
    # acks in batch order, after the shared barrier
    assert fired == list(range(8))
    for i in range(8):
        assert s.read("cc", ObjectId(f"o{i}")) == \
            bytes([i % 256]) * (100 * 1024)
    # durable across remount
    s.umount()
    s2 = TPUStore(str(tmp_path))
    s2.mount()
    for i in range(8):
        assert s2.read("cc", ObjectId(f"o{i}")) == \
            bytes([i % 256]) * (100 * 1024)
    s2.umount()


def test_submit_batch_read_your_writes_spans_the_batch(tmp_path):
    """txn j reads what txn i<j wrote — a batch applies exactly like
    committing its members in order."""
    s = _store(tmp_path)
    t1 = Transaction()
    t1.write("cc", ObjectId("x"), 0, 4, b"abcd")
    t2 = Transaction()
    # same-object overwrite in the same batch: last writer wins
    t2.write("cc", ObjectId("x"), 2, 2, b"ZZ")
    t3 = Transaction()
    t3.clone("cc", ObjectId("x"), ObjectId("x_clone"))
    assert s.submit_batch([t1, t2, t3]) == [None] * 3
    assert s.read("cc", ObjectId("x")) == b"abZZ"
    # the clone captured BOTH earlier txns' effects
    assert s.read("cc", ObjectId("x_clone")) == b"abZZ"


def test_submit_batch_failure_isolated_per_txn(tmp_path):
    s = _store(tmp_path)
    good1 = _wtxn(1, oid="g1")
    bad = Transaction()
    bad.ops.append(("no-such-op", "cc"))
    good2 = _wtxn(2, oid="g2")
    res = s.submit_batch([good1, bad, good2])
    assert res[0] is None and res[2] is None
    assert isinstance(res[1], ValueError)
    assert s.read("cc", ObjectId("g1")) == bytes([1]) * 8192
    assert s.read("cc", ObjectId("g2")) == bytes([2]) * 8192


def test_submit_batch_base_impl_on_memstore():
    """MemStore keeps the base loop-per-txn submit_batch: same
    results, same per-txn isolation."""
    s = MemStore()
    s.mkfs()
    s.mount()
    t = Transaction()
    t.create_collection("cc")
    s.queue_transaction(t)
    bad = Transaction()
    bad.ops.append(("no-such-op", "cc"))
    res = s.submit_batch([_wtxn(1, oid="a"), bad])
    assert res[0] is None and isinstance(res[1], Exception)
    assert s.read("cc", ObjectId("a")) == bytes([1]) * 8192


# -- 2. committer tier ------------------------------------------------------


def test_committer_concurrent_txns_share_one_fsync(tmp_path):
    s = _store(tmp_path)

    async def main():
        gc = GroupCommitter(s, window_ms=1.0)
        assert gc.engaged
        before = s.perf["kv_commits"]
        await asyncio.gather(
            *(gc.queue_transaction(_wtxn(i)) for i in range(16)))
        commits = s.perf["kv_commits"] - before
        await gc.stop()
        return commits, gc.stats()

    commits, stats = asyncio.run(main())
    # 16 concurrent writers, measurably fewer barriers than writers
    assert commits < 16
    assert stats["batched"] == 16
    assert stats["batches"] == commits
    assert sum(stats["txns_per_batch_hist"].values()) == commits
    for i in range(16):
        assert s.read("cc", ObjectId(f"o{i}")) == bytes([i]) * 8192


def test_committer_kill_switch_is_inline_parity(tmp_path, monkeypatch):
    monkeypatch.setenv("CEPH_TPU_GROUP_COMMIT", "0")
    s = _store(tmp_path)

    async def main():
        gc = GroupCommitter(s)
        assert not gc.engaged
        before = s.perf["kv_commits"]
        await asyncio.gather(
            *(gc.queue_transaction(_wtxn(i)) for i in range(4)))
        assert gc.stats()["inline"] == 4
        # exactly the pre-batching behavior: one commit per txn
        assert s.perf["kv_commits"] - before == 4

    asyncio.run(main())


def test_committer_memstore_stays_inline():
    s = MemStore()
    s.mkfs()
    s.mount()

    async def main():
        gc = GroupCommitter(s)
        # no barriers to amortize: never engages, never adds latency
        assert not gc.engaged

    asyncio.run(main())


def test_committer_flush_sync_is_a_total_order_barrier(tmp_path):
    s = _store(tmp_path)

    async def main():
        gc = GroupCommitter(s, window_ms=50.0)  # long window
        fut = asyncio.ensure_future(
            gc.queue_transaction(_wtxn(7, oid="pending")))
        await asyncio.sleep(0)  # let it enqueue into the window
        assert gc.stats()["pending"] == 1
        # the sync barrier commits the open window before returning
        gc.flush_sync()
        assert s.read("cc", ObjectId("pending")) == bytes([7]) * 8192
        await fut
        await gc.stop()

    asyncio.run(main())


def test_committer_commit_now_drains_first(tmp_path):
    """Barrier bypass: same-object window txn commits BEFORE the
    bypass txn — FIFO holds across lanes."""
    s = _store(tmp_path)

    async def main():
        gc = GroupCommitter(s, window_ms=50.0)
        f1 = asyncio.ensure_future(
            gc.queue_transaction(_wtxn(1, oid="ord")))
        await asyncio.sleep(0)
        t2 = Transaction()
        t2.write("cc", ObjectId("ord"), 0, 8192, bytes([2]) * 8192)
        await gc.commit_now(t2)
        await f1
        await gc.stop()

    asyncio.run(main())
    assert s.read("cc", ObjectId("ord")) == bytes([2]) * 8192


def test_committer_error_reaches_the_right_caller(tmp_path):
    s = _store(tmp_path)

    async def main():
        gc = GroupCommitter(s, window_ms=1.0)
        bad = Transaction()
        bad.ops.append(("no-such-op", "cc"))
        good = gc.queue_transaction(_wtxn(3, oid="ok"))
        res = await asyncio.gather(gc.queue_transaction(bad), good,
                                   return_exceptions=True)
        assert isinstance(res[0], ValueError)
        assert res[1] is None
        await gc.stop()

    asyncio.run(main())
    assert s.read("cc", ObjectId("ok")) == bytes([3]) * 8192


def test_committer_stop_drains_and_latches_inline(tmp_path):
    s = _store(tmp_path)

    async def main():
        gc = GroupCommitter(s, window_ms=50.0)
        fut = asyncio.ensure_future(
            gc.queue_transaction(_wtxn(4, oid="drained")))
        await asyncio.sleep(0)
        await gc.stop()
        await fut  # resolved by the drain, not stranded
        # post-stop txns run inline (teardown must not park callers)
        await gc.queue_transaction(_wtxn(5, oid="late"))

    asyncio.run(main())
    assert s.read("cc", ObjectId("drained")) == bytes([4]) * 8192
    assert s.read("cc", ObjectId("late")) == bytes([5]) * 8192


# -- 3. crash tier ----------------------------------------------------------

SWEEP_TXNS = int(os.environ.get("CEPH_TPU_CRASH_SWEEP_TXNS", "10"))
SWEEP_POINTS = int(os.environ.get("CEPH_TPU_CRASH_SWEEP_POINTS", "80"))


def test_crash_sweep_with_group_commit_armed(tmp_path):
    """The PR-8 sweep over the mixed workload, recorded through
    submit_batch: the merged batch is a legal CrashLog trace — every
    explored cut satisfies every invariant."""
    rep = CrashSweep(str(tmp_path)).run(
        txns=SWEEP_TXNS, batch=4, max_points=SWEEP_POINTS)
    assert rep["violations"] == []
    assert rep["points"] >= 20


def test_batched_sweep_still_catches_broken_stores(tmp_path):
    """Self-test: batching must not blunt the harness — a store with
    no pre-commit fsync, and one whose commit point is not sync, must
    both still be caught."""
    rep = CrashSweep(str(tmp_path / "b1"),
                     store_cls=BrokenBlockStore).run(
        txns=8, batch=4, max_points=60, double_crash=False)
    assert rep["violations"]
    rep = CrashSweep(str(tmp_path / "b2"),
                     store_cls=BrokenCommitStore).run(
        txns=8, batch=4, max_points=60, double_crash=False)
    assert rep["violations"]


def test_cut_inside_accumulating_window(tmp_path):
    """Power cut while a batch is ACCUMULATING (before its shared
    barrier): the window's txns vanish WHOLESALE — none was acked, so
    nothing is lost-after-ack — while every txn acked by an earlier
    batch survives."""
    s = FaultStore(str(tmp_path / "fs"))
    s.mkfs()
    s.mount()
    t = Transaction()
    t.create_collection("cc")
    s.queue_transaction(t)
    s.crashlog.events.clear()
    base_block = b""
    base_kv = None
    acked = []
    # batch 1: committed, acked
    batch1 = []
    for i in range(3):
        t = _wtxn(i, oid=f"acked{i}")
        t.register_on_commit(lambda i=i: acked.append(i))
        batch1.append(t)
    assert s.submit_batch(batch1) == [None] * 3
    assert acked == [0, 1, 2]
    cut_after_batch1 = len(s.crashlog.events)
    # batch 2: applied into the store's lock but the power dies
    # BEFORE its commit — simulate by cutting the trace at the
    # pre-batch point (everything the window wrote is un-synced)
    batch2 = [_wtxn(10 + i, oid=f"unacked{i}") for i in range(3)]
    assert s.submit_batch(batch2) == [None] * 3
    events = list(s.crashlog.events)
    img = str(tmp_path / "img")
    block, ops = build_image(events, cut_after_batch1,
                             drop_pending=True, kv_keep="min",
                             base_block=base_block)
    write_image(img, block, ops, base_kv=s.base_kv
                if base_kv is None else base_kv)
    s.crash()
    r = TPUStore(img)
    r.mount()
    try:
        # acked txns never vanish
        for i in range(3):
            assert r.read("cc", ObjectId(f"acked{i}")) == \
                bytes([i]) * 8192
        # the un-synced window vanished wholesale
        for i in range(3):
            with pytest.raises(KeyError):
                r.read("cc", ObjectId(f"unacked{i}"))
    finally:
        r.umount()


# -- 4. zero-copy tier ------------------------------------------------------


def _run(coro, timeout=180.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


EC_PROFILE = {"plugin": "ec_jax", "technique": "reed_sol_van",
              "k": "2", "m": "1", "crush-failure-domain": "osd"}


def test_zero_copy_bit_exact_readback_under_thrash(monkeypatch):
    """Writes and reads through the REAL socket path (local fastpath
    off, so frames are encoded, reassembled, and decoded to views),
    with the client MUTATING its buffer after every ack: the durable
    shards and every readback must hold the pre-mutation bytes — the
    view discipline never lets a store or a reply alias a
    caller-mutable buffer."""
    from ceph_tpu import msg as msg_mod

    monkeypatch.setattr(msg_mod, "LOCAL_FASTPATH", False)

    async def main():
        cluster = Cluster(num_osds=3, osds_per_host=3)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "zc", profile=EC_PROFILE, pg_num=4)
            io = cluster.client.open_ioctx("zc")
            rng = np.random.default_rng(7)
            originals = {}
            for i in range(6):
                buf = bytearray(
                    rng.integers(0, 256, 16384, dtype=np.uint8)
                    .tobytes())
                originals[f"t{i}"] = bytes(buf)
                await io.write_full(f"t{i}", buf)
                # thrash: the caller reuses its buffer immediately
                for j in range(len(buf)):
                    buf[j] = 0xAA
            for i in range(6):
                got = await io.read(f"t{i}")
                assert isinstance(got, bytes)
                assert got == originals[f"t{i}"], f"t{i} corrupted"
                # ranged reads slice views server-side: still exact
                got = await io.read(f"t{i}", offset=1000, length=500)
                assert got == originals[f"t{i}"][1000:1500]
        finally:
            await cluster.stop()

    _run(main())


def test_group_commit_on_persistent_cluster(tmp_path):
    """End to end: N concurrent client writes into a TPUStore-backed
    cluster; the primaries' and replicas' stores must show fewer
    barriers than the un-batched path would pay, and the committer's
    batch histogram must show real multi-txn batches."""

    async def main():
        cluster = Cluster(num_osds=3, osds_per_host=3,
                          store_factory=tpustore_factory(tmp_path),
                          persistent=True)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "gc", profile=EC_PROFILE, pg_num=4)
            io = cluster.client.open_ioctx("gc")
            payloads = {f"g{i}": bytes([i]) * 4096 for i in range(24)}
            await asyncio.gather(
                *(io.write_full(oid, data)
                  for oid, data in payloads.items()))
            batched = sum(
                osd.committer.stats()["batched"]
                for osd in cluster.osds.values())
            batches = sum(
                osd.committer.stats()["batches"]
                for osd in cluster.osds.values())
            saved = sum(
                osd.store.perf["gc_kv_commits_saved"]
                for osd in cluster.osds.values())
            assert batched > 0, "group commit never engaged"
            assert batches < batched, \
                "no txns actually shared a barrier"
            assert saved > 0
            for oid, data in payloads.items():
                assert await io.read(oid) == data
        finally:
            await cluster.stop()

    _run(main())


def test_sub_read_reply_data_is_a_view():
    """The wire decode of a sub-read reply hands the payload out as a
    zero-copy view of the frame buffer."""
    from ceph_tpu.msg.messages import MOSDSubReadReply

    msg = MOSDSubReadReply(1, 0, b"x" * 4096, {}, shard=0)
    raw = msg.encode()
    back = MOSDSubReadReply.decode(raw)
    assert isinstance(back.data, memoryview)
    assert bytes(back.data) == b"x" * 4096


def test_encode_decode_views_are_immutable_and_exact():
    """ec_util's batch tiers hand out FROZEN views: store-adoptable
    (is_immutable) and bit-exact against materialized copies."""
    from ceph_tpu.common.buffer import is_immutable
    from ceph_tpu.ec.registry import create_erasure_code
    from ceph_tpu.osd import ec_util

    codec = create_erasure_code(
        {"plugin": "ec_jax", "technique": "reed_sol_van",
         "k": "2", "m": "1"})
    sinfo = ec_util.StripeInfo(2, 8192)
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, 65536, dtype=np.uint8).tobytes()
    shards = ec_util.encode(sinfo, codec, data, range(3))
    for i, shard in shards.items():
        assert is_immutable(shard), f"shard {i} is caller-mutable"
    out = ec_util.decode(sinfo, codec,
                         {0: bytes(shards[0]), 1: bytes(shards[1])})
    assert bytes(out) == data
    # decode-from-parity produces the same bytes
    out = ec_util.decode(sinfo, codec,
                         {0: bytes(shards[0]), 2: bytes(shards[2])})
    assert bytes(out) == data

"""Multi-daemon RADOS-lite tier.

The qa/standalone shape (test-erasure-code.sh:21-63): spawn mon + OSDs
on loopback, create pools, write/read over the wire, kill daemons, read
through reconstruction, revive and watch recovery converge."""

import asyncio

import numpy as np
import pytest

from ceph_tpu.osd.pg_log import PGInfo, PGLog, make_entry

from cluster_helpers import Cluster

EC_PROFILE = {"plugin": "ec_jax", "technique": "reed_sol_van",
              "k": "2", "m": "1", "crush-failure-domain": "osd"}


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


# -- pg log unit tier ------------------------------------------------------


def test_pg_log_append_trim():
    log = PGLog()
    for v in range(1, 6):
        log.append(make_entry((1, v), (1, v - 1), f"o{v}", "modify"))
    assert log.info.last_update == (1, 5)
    log.trim_to(2)
    assert len(log.entries) == 2
    assert log.info.log_tail == (1, 3)


def test_pg_log_merge_catches_up_missing():
    """Peer behind the auth head: entries past its head become missing."""
    auth = PGLog()
    for v in range(1, 6):
        auth.append(make_entry((1, v), (1, v - 1), f"o{v}", "modify"))
    peer = PGLog()
    for v in range(1, 3):
        peer.append(make_entry((1, v), (1, v - 1), f"o{v}", "modify"))
    missing = peer.merge(auth.info, auth.entries)
    assert missing == {"o3": (1, 3), "o4": (1, 4), "o5": (1, 5)}
    assert peer.info.last_update == (1, 5)
    assert [e["version"] for e in peer.entries] == \
        [e["version"] for e in auth.entries]


def test_pg_log_merge_rewinds_divergent():
    """Peer wrote entries the auth log never saw (old-primary writes):
    they are divergent; their objects get recovered to auth state."""
    shared = [make_entry((1, v), (1, v - 1), f"o{v}", "modify")
              for v in range(1, 4)]
    auth = PGLog()
    peer = PGLog()
    for e in shared:
        auth.append(dict(e))
        peer.append(dict(e))
    # divergence: peer got (1,4) on oX from a dying primary; auth moved
    # on in a new interval with (2,4) and (2,5)
    peer.append(make_entry((1, 4), (1, 3), "oX", "modify"))
    auth.append(make_entry((2, 4), (1, 3), "o9", "modify"))
    auth.append(make_entry((2, 5), (2, 4), "oX", "modify"))
    missing = peer.merge(auth.info, auth.entries)
    assert missing["o9"] == (2, 4)
    assert missing["oX"] == (2, 5)   # auth's newer version wins
    assert peer.info.last_update == (2, 5)


def test_pg_log_merge_fully_divergent_peer():
    auth = PGLog()
    for v in range(1, 4):
        auth.append(make_entry((2, v), (2, v - 1), f"a{v}", "modify"))
    peer = PGLog()
    peer.append(make_entry((1, 1), (0, 0), "stale", "modify"))
    missing = peer.merge(auth.info, auth.entries)
    assert missing["stale"] == (0, 0)          # rollback target unknown
    assert set(missing) == {"stale", "a1", "a2", "a3"}


# -- live cluster ----------------------------------------------------------


def test_cluster_boot_and_health():
    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            rc, out = await cluster.client.mon_command({"prefix": "status"})
            assert rc == 0
            assert out["num_up_osds"] == 4
            assert out["health"]["status"] == "HEALTH_OK"
        finally:
            await cluster.stop()

    run(main())


def test_replicated_pool_over_the_wire():
    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "rbd", size=3, pg_num=8)
            ioctx = cluster.client.open_ioctx("rbd")
            payloads = {f"obj-{i}":
                        np.random.default_rng(i).integers(
                            0, 256, 20_000 + i, dtype=np.uint8).tobytes()
                        for i in range(8)}
            for name, data in payloads.items():
                await ioctx.write_full(name, data)
            for name, data in payloads.items():
                assert await ioctx.read(name) == data
            stat = await ioctx.stat("obj-0")
            assert stat["size"] == 20_000
            assert await ioctx.list_objects() == sorted(payloads)
            await ioctx.remove("obj-3")
            with pytest.raises(Exception):
                await ioctx.read("obj-3")
        finally:
            await cluster.stop()

    run(main())


def test_ec_pool_over_the_wire():
    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ecpool", EC_PROFILE, pg_num=8)
            ioctx = cluster.client.open_ioctx("ecpool")
            data = np.random.default_rng(7).integers(
                0, 256, 100_000, dtype=np.uint8).tobytes()
            await ioctx.write_full("big", data)
            assert await ioctx.read("big") == data
            # partial read
            assert await ioctx.read("big", 100, 500) == data[100:600]
            # partial overwrite (EC RMW path)
            await ioctx.write("big", b"X" * 1000, 4096)
            expect = bytearray(data)
            expect[4096:5096] = b"X" * 1000
            assert await ioctx.read("big") == bytes(expect)
        finally:
            await cluster.stop()

    run(main())


def test_ec_degraded_read_after_kill():
    """Kill an OSD; EC reads must reconstruct through the erasure."""
    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ecpool", EC_PROFILE, pg_num=8)
            ioctx = cluster.client.open_ioctx("ecpool")
            payloads = {f"o{i}": np.random.default_rng(100 + i).integers(
                0, 256, 50_000, dtype=np.uint8).tobytes()
                for i in range(6)}
            for name, data in payloads.items():
                await ioctx.write_full(name, data)
            await cluster.kill_osd(0)
            await cluster.wait_for_osd_down(0)
            # every object still readable (reconstruct where osd.0 held
            # a shard, possibly via a new acting primary)
            for name, data in payloads.items():
                assert await ioctx.read(name) == data
            rc, health = await cluster.client.mon_command(
                {"prefix": "health"})
            assert health["status"] == "HEALTH_WARN"
            assert "OSD_DOWN" in health["checks"]
        finally:
            await cluster.stop()

    run(main())


def test_failure_detection_marks_down_via_reports():
    """No manual mark_osd_down: peers detect the dead OSD via heartbeat
    misses and the mon adjudicates the failure reports."""
    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            assert cluster.mon.osdmap.is_up(2)
            await cluster.kill_osd(2)
            # only heartbeat-driven MOSDFailure reports can do this
            await cluster.wait_for_osd_down(2)
        finally:
            await cluster.stop()

    run(main())


def test_osd_revive_rejoins_and_recovers():
    """Kill an OSD, write while it's down, revive: peering + log-driven
    recovery must converge every shard."""
    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "rbd", size=3, pg_num=8)
            ioctx = cluster.client.open_ioctx("rbd")
            await ioctx.write_full("before", b"before-kill " * 1000)
            await cluster.kill_osd(1)
            await cluster.wait_for_osd_down(1)
            # below min_size the PG blocks writes (undersized); marking
            # the dead OSD out lets CRUSH remap — the thrashosds flow
            await cluster.client.mon_command(
                {"prefix": "osd out", "osd": 1})
            payloads = {f"during-{i}": bytes([i]) * 10_000
                        for i in range(6)}
            for name, data in payloads.items():
                await ioctx.write_full(name, data)
            await cluster.revive_osd(1)
            await cluster.wait_for_osd_up(1)
            await cluster.client.mon_command(
                {"prefix": "osd in", "osd": 1})
            await cluster.wait_for_clean()
            # all data correct after recovery
            assert await ioctx.read("before") == b"before-kill " * 1000
            for name, data in payloads.items():
                assert await ioctx.read(name) == data
            # osd.1's own copies converged: read its stores directly
            store = cluster.stores[1]
            recovered = set()
            for cid in store.list_collections():
                for obj in store.list_objects(cid):
                    recovered.add(str(obj))
            # at least some of the during-writes landed on osd.1
            # (placement spreads over 3-of-4 OSDs, so overlap is certain
            # across 6 objects + pgmeta entries)
            assert any(name in recovered for name in payloads)
        finally:
            await cluster.stop()

    run(main())


def test_ec_revive_recovers_shards():
    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ecpool", EC_PROFILE, pg_num=8)
            ioctx = cluster.client.open_ioctx("ecpool")
            await cluster.kill_osd(3)
            await cluster.wait_for_osd_down(3)
            await cluster.client.mon_command(
                {"prefix": "osd out", "osd": 3})
            payloads = {f"x{i}": np.random.default_rng(i).integers(
                0, 256, 30_000, dtype=np.uint8).tobytes()
                for i in range(5)}
            for name, data in payloads.items():
                await ioctx.write_full(name, data)
            await cluster.revive_osd(3)
            await cluster.wait_for_osd_up(3)
            await cluster.client.mon_command(
                {"prefix": "osd in", "osd": 3})
            await cluster.wait_for_clean()
            for name, data in payloads.items():
                assert await ioctx.read(name) == data
            # now kill a DIFFERENT osd: the recovered shards on osd.3
            # must carry the reconstruction
            await cluster.kill_osd(0)
            await cluster.wait_for_osd_down(0)
            for name, data in payloads.items():
                assert await ioctx.read(name) == data
        finally:
            await cluster.stop()

    run(main())


def test_overwrite_hides_and_trims_rollback_clones():
    """Rollback-generation clones (_rbgen_*) must never leak into
    list_objects, and once every shard acked the overwrite they are
    trimmed from the stores (advisor r2; ECBackend rollback trim)."""
    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ecpool", profile=EC_PROFILE, pg_num=8)
            ioctx = cluster.client.open_ioctx("ecpool")
            await ioctx.write_full("obj", b"v1" * 4000)
            await ioctx.write_full("obj", b"v2" * 5000)
            assert await ioctx.read("obj") == b"v2" * 5000
            assert await ioctx.list_objects() == ["obj"]
            # client ops must not address rollback names
            with pytest.raises(Exception):
                await ioctx.read("_rbgen_obj")
            # trim is fire-and-forget: give it a beat, then assert no
            # _rbgen_ object survives in any OSD's store
            await asyncio.sleep(0.5)
            for osd in cluster.osds.values():
                store = osd.store
                for cid in store.list_collections():
                    for obj in store.list_objects(cid):
                        assert not str(obj).startswith("_rbgen_"), \
                            f"stale rollback clone {obj} in {cid}"
        finally:
            await cluster.stop()

    run(main())


def test_unfound_object_blocks_reads_until_source_returns():
    """Kill every holder of an EC object's decodable set: reads must
    BLOCK (EAGAIN resend loop), not ENOENT — the acked data still
    exists on the dead OSDs.  When one revives, the read completes
    with the original bytes (MissingLoc unfound semantics +
    waiting_for_unreadable_object)."""
    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ecpool", profile=EC_PROFILE, pg_num=8)
            ioctx = cluster.client.open_ioctx("ecpool")
            payload = bytes(range(256)) * 64
            await ioctx.write_full("victim", payload)
            pg = ioctx.object_pg("victim")
            acting, _p = cluster.mon.osdmap.pg_to_acting_osds(pg)
            holders = [o for o in acting if o >= 0]
            # kill 2 of the 3 shard holders: below k=2, undecodable
            dead = holders[1:3]
            for osd in dead:
                await cluster.kill_osd(osd)
                await cluster.wait_for_osd_down(osd)
            for osd in dead:
                await cluster.client.mon_command(
                    {"prefix": "osd out", "osd": osd})
            # the read must hang (EAGAIN retry loop), not fail ENOENT
            read_task = asyncio.get_running_loop().create_task(
                ioctx.read("victim"))
            done, _pending = await asyncio.wait([read_task], timeout=3.0)
            assert not done, (
                "read of an unfound object completed instead of "
                f"blocking: {read_task.result() if done else None!r}")
            # revive one holder: data becomes locatable, read completes
            await cluster.revive_osd(dead[0])
            await cluster.wait_for_osd_up(dead[0])
            await cluster.client.mon_command(
                {"prefix": "osd in", "osd": dead[0]})
            assert await asyncio.wait_for(read_task, 60.0) == payload
        finally:
            await cluster.stop()

    run(main())


def test_osd_lost_completes_probe_adjudication():
    """`osd lost` declares a dead OSD's data permanently gone: stray
    probes then count it definitively absent, so unfound adjudication
    (divergent-create GC, missing-version checks) can conclude instead
    of blocking until the OSD returns."""
    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "p", size=2, pg_num=8)
            io = cluster.client.open_ioctx("p")
            await io.write_full("o", b"z" * 1000)
            pg = io.object_pg("o")
            acting, primary = cluster.mon.osdmap.pg_to_acting_osds(pg)
            victim = next(o for o in range(4) if o not in acting)
            await cluster.kill_osd(victim)
            await cluster.wait_for_osd_down(victim)
            posd = cluster.osds[primary]
            state = posd.pgs[pg]
            pool = posd.osdmap.pools[pg.pool]
            # a plain-down OSD leaves the stray search inconclusive
            _c, complete = await posd._gather_stray_shards(
                state, pool, "o", set())
            assert not complete
            # refusing without the safety latch
            rc, out = await cluster.client.mon_command(
                {"prefix": "osd lost", "osd": victim})
            assert rc != 0
            rc, _ = await cluster.client.mon_command(
                {"prefix": "osd lost", "osd": victim,
                 "yes_i_really_mean_it": True})
            assert rc == 0
            await cluster._wait(
                lambda: posd.osdmap is not None
                and posd.osdmap.is_destroyed(victim),
                10.0, "lost state never reached the OSDs")
            _c, complete = await posd._gather_stray_shards(
                state, pool, "o", set())
            assert complete
            # a live OSD cannot be declared lost
            rc, _ = await cluster.client.mon_command(
                {"prefix": "osd lost", "osd": acting[0],
                 "yes_i_really_mean_it": True})
            assert rc != 0
            # data was never on the victim: cluster still serves it
            assert await io.read("o") == b"z" * 1000
        finally:
            await cluster.stop()

    run(main())


def test_recovery_batches_device_dispatches():
    """Recovering many EC objects must decode/encode in O(PGs) device
    dispatches, not O(objects) (RecoveryOp batching, ECBackend.h:249):
    dispatch-per-object pays host<->device latency per object and was
    round-2 weakness #2."""
    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ecb", profile=EC_PROFILE, pg_num=8)
            io = cluster.client.open_ioctx("ecb")
            n_objects = 24
            for i in range(n_objects):
                await io.write_full(f"b{i}", bytes([i]) * 6000)
            baseline = {o: (osd.perf["decode_dispatches"],
                            osd.perf["encode_dispatches"])
                        for o, osd in cluster.osds.items()}
            await cluster.kill_osd(3)
            await cluster.wait_for_osd_down(3)
            await cluster.client.mon_command(
                {"prefix": "osd out", "osd": 3})
            await cluster.wait_for_clean(timeout=60)
            dec = sum(osd.perf["decode_dispatches"] - baseline[o][0]
                      for o, osd in cluster.osds.items())
            enc = sum(osd.perf["encode_dispatches"] - baseline[o][1]
                      for o, osd in cluster.osds.items())
            # batched: <= a few dispatches per PG per peering round,
            # NOT one per object (24 objects -> would be >= 24 each)
            assert dec < n_objects, f"unbatched decode: {dec}"
            assert enc < n_objects, f"unbatched encode: {enc}"
            # every object still reads back intact
            for i in range(n_objects):
                assert await io.read(f"b{i}") == bytes([i]) * 6000
        finally:
            await cluster.stop()

    run(main())


def test_heartbeat_inject_failure_marks_down_then_recovers():
    """heartbeat_inject_failure (options.cc:1087-1108 family): push the
    option through CENTRAL CONFIG to one live daemon; it goes
    heartbeat-silent (no pings, no replies) without dying, peers report
    it, the mon marks it down — and when the injected outage expires the
    daemon notices the false down-mark and re-boots (MOSDAlive role)."""
    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            assert cluster.mon.osdmap.is_up(2)
            rc, _ = await cluster.client.mon_command(
                {"prefix": "config set", "who": "osd.2",
                 "name": "heartbeat_inject_failure", "value": "8"})
            assert rc == 0
            # mute > grace (2.5s): peers must report, mon must adjudicate
            await cluster.wait_for_osd_down(2)
            # daemon is ALIVE the whole time (this is not a crash)
            assert not cluster.osds[2]._stopping
            # drop the central override so the post-reboot config
            # re-push cannot re-arm the injection
            rc, _ = await cluster.client.mon_command(
                {"prefix": "config rm", "who": "osd.2",
                 "name": "heartbeat_inject_failure"})
            assert rc == 0
            # outage expires -> heartbeats resume -> self re-boot
            await cluster.wait_for_osd_up(2, timeout=30.0)
        finally:
            await cluster.stop()

    run(main())

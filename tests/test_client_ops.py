"""Extended client op surface: append, xattrs, omap, watch/notify
(the ObjectOperation + linger-op surface of librados/Objecter;
/root/reference/src/osdc/Objecter.cc linger ops, src/cls substrate)."""

import asyncio

import numpy as np
import pytest

from ceph_tpu.rados.client import RadosError

from cluster_helpers import Cluster

EC22 = {"plugin": "ec_jax", "technique": "reed_sol_van",
        "k": "2", "m": "2", "crush-failure-domain": "osd",
        "tpu": "false"}


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 180))


def test_append_and_xattrs_replicated():
    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "p", size=3, pg_num=8)
            io = cluster.client.open_ioctx("p")
            await io.write_full("obj", b"hello")
            await io.append("obj", b" world")
            await io.append("obj", b"!")
            assert await io.read("obj") == b"hello world!"
            # concurrent appends serialize (no lost updates)
            await asyncio.gather(*(io.append("obj", bytes([65 + i]))
                                   for i in range(8)))
            data = await io.read("obj")
            assert len(data) == len(b"hello world!") + 8
            assert sorted(data[-8:]) == list(range(65, 73))

            await io.setxattr("obj", "color", b"blue")
            await io.setxattr("obj", "shape", b"round")
            assert await io.getxattr("obj", "color") == b"blue"
            attrs = await io.getxattrs("obj")
            assert attrs == {"color": b"blue", "shape": b"round"}
            await io.rmxattr("obj", "color")
            with pytest.raises(RadosError):
                await io.getxattr("obj", "color")
            # xattr on a missing object
            with pytest.raises(RadosError):
                await io.setxattr("nope", "a", b"b")
        finally:
            await cluster.stop()

    run(main())


def test_append_and_xattrs_ec():
    async def main():
        cluster = Cluster(num_osds=5)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ec", profile=EC22, pg_num=8)
            io = cluster.client.open_ioctx("ec")
            blob = bytes(np.random.default_rng(4).integers(
                0, 256, 30_000, dtype=np.uint8))
            await io.write_full("obj", blob)
            await io.append("obj", b"tail" * 100)
            assert await io.read("obj") == blob + b"tail" * 100
            await io.setxattr("obj", "k", b"v")
            assert await io.getxattr("obj", "k") == b"v"
            # omap is refused on EC pools, like the reference
            with pytest.raises(RadosError):
                await io.omap_set("obj", {"a": b"1"})
        finally:
            await cluster.stop()

    run(main())


def test_omap_round_trip_and_recovery():
    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "p", size=3, pg_num=8)
            io = cluster.client.open_ioctx("p")
            await io.write_full("idx", b"")
            await io.omap_set("idx", {"k1": b"v1", "k2": b"v2",
                                      "k3": b"v3"})
            await io.omap_rm_keys("idx", ["k2"])
            assert await io.omap_get("idx") == {"k1": b"v1",
                                                "k3": b"v3"}
            # omap survives an OSD kill + revive (recovery carries it)
            await cluster.kill_osd(0)
            await cluster.wait_for_osd_down(0)
            assert await io.omap_get("idx") == {"k1": b"v1",
                                                "k3": b"v3"}
            # mark it OUT (the mon's down-out interval role) so CRUSH
            # re-places the PG and degraded writes regain min_size
            await cluster.client.mon_command(
                {"prefix": "osd out", "osd": 0})
            await io.omap_set("idx", {"k4": b"v4"})
            await cluster.client.mon_command(
                {"prefix": "osd in", "osd": 0})
            await cluster.revive_osd(0)
            await cluster.wait_for_osd_up(0)
            await cluster.wait_for_clean()
            assert await io.omap_get("idx") == {"k1": b"v1",
                                                "k3": b"v3",
                                                "k4": b"v4"}
        finally:
            await cluster.stop()

    run(main())


def test_watch_notify():
    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "p", size=3, pg_num=8)
            io = cluster.client.open_ioctx("p")
            await io.write_full("obj", b"watched")

            got: list = []
            cookie = await io.watch("obj", lambda p: got.append(p))
            res = await io.notify("obj", b"ping-1")
            # watchers are identified by (client, cookie) pairs —
            # cookies alone collide across clients
            me = cluster.client.msgr.entity_name
            assert res["acked"] == [[me, cookie]]
            assert res["missed"] == []
            assert got == [b"ping-1"]

            # a second watcher from a second client
            from ceph_tpu.rados.client import RadosClient

            client2 = RadosClient(cluster.mon.addr, name="client.2")
            await client2.connect()
            try:
                io2 = client2.open_ioctx("p")
                got2: list = []
                c2 = await io2.watch("obj", lambda p: got2.append(p))
                res = await io.notify("obj", b"ping-2")
                assert sorted(map(tuple, res["acked"])) == sorted(
                    [(me, cookie), ("client.2", c2)])
                assert got[-1] == b"ping-2" and got2 == [b"ping-2"]
                await io2.unwatch("obj", c2)
            finally:
                await client2.shutdown()

            res = await io.notify("obj", b"ping-3")
            assert res["acked"] == [[me, cookie]]
            await io.unwatch("obj", cookie)
            res = await io.notify("obj", b"ping-4")
            assert res["acked"] == []
            assert got[-1] == b"ping-3"
        finally:
            await cluster.stop()

    run(main())

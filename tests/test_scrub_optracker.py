"""Daemon-side scrub + OpTracker tier (PG scrub / be_deep_scrub and
TrackedOp/OpTracker roles; /root/reference/src/common/TrackedOp.h,
src/osd/PG.cc scrub, ECBackend.cc:2494 be_deep_scrub)."""

import asyncio
import json
import socket
import struct

import numpy as np
import pytest

from ceph_tpu.os import ObjectId, Transaction
from ceph_tpu.osd.op_tracker import OpTracker
from ceph_tpu.osd.osdmap import PgId

from cluster_helpers import Cluster

EC22 = {"plugin": "ec_jax", "technique": "reed_sol_van",
        "k": "2", "m": "2", "crush-failure-domain": "osd",
        "tpu": "false"}


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 180))


def _pg_of(cluster, pool_name, oid):
    osdmap = cluster.mon.osdmap
    pool = [p for p in osdmap.pools.values() if p.name == pool_name][0]
    from ceph_tpu.ops.rjenkins import ceph_str_hash_rjenkins

    pg = pool.raw_pg_to_pg(
        PgId(pool.id, ceph_str_hash_rjenkins(oid.encode())))
    _acting, primary = osdmap.pg_to_acting_osds(pg)
    return pool, pg, primary


# -- OpTracker unit tier ---------------------------------------------------


def test_op_tracker_lifecycle_and_slow():
    t = OpTracker(history_size=2, complaint_time=0.0, who="osd.9")
    a = t.create("op-a")
    t.mark(a, "started")
    assert t.dump_in_flight()["num_ops"] == 1
    slow = t.check_slow()           # complaint_time 0: instantly slow
    assert len(slow) == 1 and t.slow_ops == 1
    assert not t.check_slow()       # warn once per op
    t.finish(a)
    assert t.dump_in_flight()["num_ops"] == 0
    hist = t.dump_historic()
    assert hist["num_ops"] == 1
    assert hist["ops"][0]["description"] == "op-a"
    assert [e["event"] for e in hist["ops"][0]["events"]] == \
        ["initiated", "started", "done"]
    for i in range(3):              # ring bounded at 2
        t.finish(t.create(f"op-{i}"))
    assert t.dump_historic()["num_ops"] == 2


def test_op_tracker_lock_consistency_and_perf():
    """Satellite regression: mark/check_slow mutate per-op state under
    the tracker lock, so an admin-socket thread dumping concurrently
    never observes a half-updated event list or double-counts slow
    ops; perf() carries the lifetime op count + in-flight gauge."""
    import threading

    t = OpTracker(history_size=8, complaint_time=0.0, who="osd.7")
    ids = [t.create(f"op-{i}") for i in range(4)]
    stop = threading.Event()
    errors = []

    def dumper():
        while not stop.is_set():
            try:
                t.dump_in_flight()
                t.dump_historic()
                t.check_slow()
                t.perf()
            except Exception as e:  # pragma: no cover
                errors.append(e)

    threads = [threading.Thread(target=dumper) for _ in range(3)]
    for th in threads:
        th.start()
    try:
        for _ in range(300):
            for op_id in ids:
                t.mark(op_id, "event")
        t.check_slow()
        for op_id in ids:
            t.finish(op_id)
    finally:
        stop.set()
        for th in threads:
            th.join()
    assert not errors, errors
    # warn once per op, however many racing check_slow calls ran
    assert t.slow_ops == 4
    p = t.perf()
    assert p["ops_total"] == 4
    assert p["ops_in_flight"] == 0
    assert p["slow_ops"] == 4


def test_op_tracker_tail_policy_and_exemplars():
    """is_tail: complaint-time breach always retains; the rolling p99
    engages only past the warmup; the exemplar ring is bounded and
    served by trace id."""
    t = OpTracker(history_size=4, complaint_time=1.0, who="osd.8")
    assert t.is_tail(2.0)              # complaint breach
    assert not t.is_tail(0.5)          # too few samples for p99
    for _ in range(200):
        op = t.finish(t.create("fast"))
        assert op is not None and op.duration is not None
    assert t.is_tail(0.9)              # >> rolling p99 of ~instant ops
    op = t.finish(t.create("slow"))
    doc = {"trace_id": "aa" * 8, "critical_path":
           {"stages": {"subread": 123}, "path": []}, "spans": []}
    t.retain_trace(op, doc)
    assert t.get_trace("aa" * 8) is doc
    assert ("aa" * 8) in t.exemplar_ids()
    hist = t.dump_historic()
    assert any(o.get("trace_id") == "aa" * 8
               and o.get("stages_us") == {"subread": 123}
               for o in hist["ops"])
    # ring bound
    from ceph_tpu.osd.op_tracker import EXEMPLAR_CAP
    for i in range(EXEMPLAR_CAP + 5):
        o = t.finish(t.create("x"))
        t.retain_trace(o, {"trace_id": f"{i:032x}",
                           "critical_path": {}, "spans": []})
    assert len(t.exemplar_ids()) == EXEMPLAR_CAP


# -- scrub cluster tier ----------------------------------------------------


def test_scrub_detects_and_repairs_corrupt_ec_shard():
    async def main():
        cluster = Cluster(num_osds=5)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ec", profile=EC22, pg_num=8)
            io = cluster.client.open_ioctx("ec")
            data = bytes(np.random.default_rng(8).integers(
                0, 256, 50_000, dtype=np.uint8))
            await io.write_full("obj", data)
            pool, pg, primary = _pg_of(cluster, "ec", "obj")
            prim = cluster.osds[primary]
            state = prim.pgs[pg]
            # corrupt shard 1 ON DISK behind the daemon's back
            victim_osd = state.acting[1]
            store = cluster.osds[victim_osd].store
            cid = f"{pg}_s1"
            from ceph_tpu.rados.embedded import shard_collection

            cid = shard_collection(pg, 1)
            raw = store.read(cid, ObjectId("obj"))
            t = Transaction()
            t.write(cid, ObjectId("obj"), 100, 4, b"\xde\xad\xbe\xef")
            store.queue_transaction(t)
            assert store.read(cid, ObjectId("obj")) != raw
            # scheduled scrub catches it (not a client read)
            res = await prim.scrub_pg(state, pool)
            assert res["errors"] >= 1 and res["repaired"] >= 1
            # the shard is byte-identical to the original again
            await cluster.wait_for_clean()
            assert store.read(cid, ObjectId("obj")) == raw
            assert await io.read("obj") == data
            # second scrub pass is clean
            res2 = await prim.scrub_pg(state, pool)
            assert res2["errors"] == 0
        finally:
            await cluster.stop()

    run(main())


def test_scrub_detects_and_repairs_replicated_bitrot():
    async def main():
        # one OSD per host: a size-3 pool really gets 3 replicas (on
        # the 2-host default the pool has only 2 copies and scrub
        # rightly refuses to adjudicate a 1-vs-1 digest tie)
        cluster = Cluster(num_osds=4, osds_per_host=1)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "p", size=3, pg_num=8)
            io = cluster.client.open_ioctx("p")
            await io.write_full("obj", b"pristine" * 2000)
            pool, pg, primary = _pg_of(cluster, "p", "obj")
            prim = cluster.osds[primary]
            state = prim.pgs[pg]
            from ceph_tpu.rados.embedded import shard_collection

            victim = [o for o in state.acting if o != primary][0]
            store = cluster.osds[victim].store
            cid = shard_collection(pg, -1)
            t = Transaction()
            t.write(cid, ObjectId("obj"), 0, 3, b"rot")
            store.queue_transaction(t)
            res = await prim.scrub_pg(state, pool)
            assert res["errors"] >= 1 and res["repaired"] >= 1
            await cluster.wait_for_clean()
            assert store.read(cid, ObjectId("obj")) == \
                b"pristine" * 2000
        finally:
            await cluster.stop()

    run(main())


# -- admin socket tier -----------------------------------------------------


def _admin(path, cmd):
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
        s.connect(path)
        s.sendall(json.dumps(cmd).encode() + b"\0")
        ln = struct.unpack(">I", s.recv(4))[0]
        buf = b""
        while len(buf) < ln:
            buf += s.recv(ln - len(buf))
        return json.loads(buf)


def test_admin_socket_dump_ops(tmp_path):
    async def main():
        sock_path = str(tmp_path / "osd.asok")
        cluster = Cluster(
            num_osds=4,
            osd_config={"admin_socket": ""})  # default: none
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "p", size=3, pg_num=8)
            io = cluster.client.open_ioctx("p")
            await io.write_full("obj", b"x" * 1000)
            pool, pg, primary = _pg_of(cluster, "p", "obj")
            prim = cluster.osds[primary]
            # wire an admin socket onto the live daemon
            prim._start_admin_socket(sock_path)
            await io.read("obj")
            await io.write_full("obj", b"y" * 1000)
            hist = _admin(sock_path, {"prefix": "dump_historic_ops"})
            assert hist["num_ops"] >= 1
            descs = " ".join(o["description"] for o in hist["ops"])
            assert "obj" in descs
            inflight = _admin(sock_path,
                              {"prefix": "dump_ops_in_flight"})
            assert inflight["num_ops"] == 0
            pgs = _admin(sock_path, {"prefix": "dump_pgs"})
            assert str(pg) in pgs
        finally:
            await cluster.stop()

    run(main())


def test_scheduled_scrub_loop_catches_corruption():
    """The BACKGROUND loop (osd_scrub_interval) finds and repairs
    corruption with no client read involved."""
    async def main():
        cluster = Cluster(num_osds=4, osds_per_host=1,
                          osd_config={"osd_scrub_interval": 0.4})
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "p", size=3, pg_num=8)
            io = cluster.client.open_ioctx("p")
            await io.write_full("obj", b"good" * 3000)
            pool, pg, primary = _pg_of(cluster, "p", "obj")
            prim = cluster.osds[primary]
            from ceph_tpu.rados.embedded import shard_collection

            victim = [o for o in prim.pgs[pg].acting
                      if o != primary][0]
            store = cluster.osds[victim].store
            cid = shard_collection(pg, -1)
            t = Transaction()
            t.write(cid, ObjectId("obj"), 8, 4, b"BAD!")
            store.queue_transaction(t)
            for _ in range(60):
                if prim.scrub_stats["repaired"] >= 1:
                    break
                await asyncio.sleep(0.2)
            else:
                raise TimeoutError("scheduled scrub never repaired")
            await cluster.wait_for_clean()
            assert store.read(cid, ObjectId("obj")) == b"good" * 3000
        finally:
            await cluster.stop()

    run(main())



def test_scrub_refuses_two_copy_digest_tie():
    """With only two readable copies a digest mismatch is undecidable:
    scrub must report the inconsistency and touch NOTHING (repairing on
    a tie can destroy the good copy)."""
    async def main():
        cluster = Cluster(num_osds=4)  # 2 hosts -> size-3 pool, 2 copies
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "p", size=3, pg_num=8)
            io = cluster.client.open_ioctx("p")
            await io.write_full("obj", b"truth" * 2000)
            pool, pg, primary = _pg_of(cluster, "p", "obj")
            prim = cluster.osds[primary]
            state = prim.pgs[pg]
            from ceph_tpu.rados.embedded import shard_collection

            victim = [o for o in state.acting if o != primary][0]
            store = cluster.osds[victim].store
            cid = shard_collection(pg, -1)
            t = Transaction()
            t.write(cid, ObjectId("obj"), 0, 3, b"rot")
            store.queue_transaction(t)
            res = await prim.scrub_pg(state, pool)
            assert res["errors"] >= 1
            assert res["repaired"] == 0
            # both copies untouched: good copy still serves reads
            good_store = cluster.osds[primary].store
            assert good_store.read(cid, ObjectId("obj")) == \
                b"truth" * 2000
        finally:
            await cluster.stop()

    run(main())

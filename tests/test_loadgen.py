"""Open-loop load harness tier (ceph_tpu/loadgen).

The acceptance shape: >= 1000 simulated tenants drive the embedded
cluster in smoke mode with streaming percentiles (bounded memory),
deterministic under a fixed seed, goodput + p50/p95/p99 out.  The
full knee sweep is `slow`; CEPH_TPU_LOAD_SMOKE=1 (the tier-1 default
here) keeps the resident leg small enough for the gate.
"""

from __future__ import annotations

import asyncio
import math
import os

import numpy as np
import pytest

from ceph_tpu.loadgen import (
    EmbeddedTarget,
    LatencyHistogram,
    SheddedOp,
    Target,
    TenantSpec,
    make_tenants,
    parse_blend,
    run_embedded,
    run_open_loop,
    schedule_fingerprint,
    tenant_events,
)
from ceph_tpu.loadgen.stats import _NBINS

# tier-1 smoke sizing (CEPH_TPU_LOAD_SMOKE=0 upsizes to the full
# sweep shape for manual runs; the slow-marked test below always
# runs full size)
_SMOKE = os.environ.get("CEPH_TPU_LOAD_SMOKE", "1") != "0"


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


# -- streaming stats ---------------------------------------------------


def test_histogram_percentiles_match_numpy_oracle():
    rng = np.random.default_rng(3)
    samples = rng.lognormal(mean=-6.0, sigma=1.0, size=20_000)
    h = LatencyHistogram()
    for s in samples:
        h.record(float(s))
    for q in (0.5, 0.95, 0.99):
        want = float(np.quantile(samples, q))
        got = h.percentile(q)
        # log-bucket resolution: within ~5% relative
        assert abs(got - want) / want < 0.06, (q, got, want)
    assert h.count == len(samples)
    assert abs(h.mean() - samples.mean()) / samples.mean() < 0.05


def test_histogram_memory_is_bounded():
    """The whole point: a million records cost the same few hundred
    counters as ten."""
    h = LatencyHistogram()
    assert len(h.bins) == _NBINS
    for i in range(100_000):
        h.record((i % 997) * 1e-5)
    assert len(h.bins) == _NBINS  # no growth, ever
    assert h.count == 100_000


def test_histogram_merge_equals_union():
    rng = np.random.default_rng(5)
    a_s, b_s = rng.random(500) * 0.01, rng.random(300) * 0.1
    a, b, u = (LatencyHistogram() for _ in range(3))
    for s in a_s:
        a.record(float(s))
        u.record(float(s))
    for s in b_s:
        b.record(float(s))
        u.record(float(s))
    a.merge(b)
    assert a.bins == u.bins and a.count == u.count
    assert a.percentile(0.99) == u.percentile(0.99)


def test_histogram_edges():
    h = LatencyHistogram()
    assert h.percentile(0.5) is None
    h.record(0.002)
    assert abs(h.percentile(0.5) - 0.002) / 0.002 < 0.05
    h2 = LatencyHistogram()
    h2.record(-1.0)   # clamped, not a crash
    h2.record(1e9)    # saturates the top bin
    assert h2.count == 2


# -- workload / schedules ----------------------------------------------


def test_parse_blend():
    b = parse_blend("read=0.5,write=0.5")
    assert abs(b["read"] - 0.5) < 1e-9 and abs(b["write"] - 0.5) < 1e-9
    b = parse_blend("read=3,write=1")
    assert abs(b["read"] - 0.75) < 1e-9
    assert parse_blend("")  # default blend
    with pytest.raises(ValueError):
        parse_blend("bogus=1")
    with pytest.raises(ValueError):
        parse_blend("read=0")


def test_schedule_deterministic_under_fixed_seed():
    """Same seed -> bit-identical op schedule (times, kinds, object
    indices), across generator invocations; different seed differs."""
    spec = TenantSpec(name="t7", arrival_rate=50.0, zipf_theta=1.2,
                      objects=32)
    a = list(tenant_events(spec, 2.0, seed=9))
    b = list(tenant_events(spec, 2.0, seed=9))
    c = list(tenant_events(spec, 2.0, seed=10))
    assert a == b
    assert a != c
    tenants = make_tenants(40, rate=5.0)
    assert schedule_fingerprint(tenants, 1.0, seed=3) == \
        schedule_fingerprint(tenants, 1.0, seed=3)
    assert schedule_fingerprint(tenants, 1.0, seed=3) != \
        schedule_fingerprint(tenants, 1.0, seed=4)


def test_schedule_is_time_ordered_and_rate_shaped():
    from ceph_tpu.loadgen import merged_schedule

    tenants = make_tenants(20, rate=20.0)
    evs = list(merged_schedule(tenants, 2.0, seed=1))
    assert all(evs[i].t <= evs[i + 1].t for i in range(len(evs) - 1))
    # Poisson: ~20 tenants x 20/s x 2s = 800 expected; 5 sigma slack
    expect = 20 * 20.0 * 2.0
    assert abs(len(evs) - expect) < 5 * math.sqrt(expect) + 20
    assert all(0 <= e.t < 2.0 for e in evs)


def test_deterministic_mode_spacing():
    spec = TenantSpec(name="d", arrival_rate=10.0, poisson=False)
    evs = list(tenant_events(spec, 1.0, seed=2))
    gaps = [round(evs[i + 1].t - evs[i].t, 6) for i in range(len(evs) - 1)]
    assert all(abs(g - 0.1) < 1e-6 for g in gaps), gaps


# -- open-loop runner --------------------------------------------------


class _FakeTarget(Target):
    """Scripted target: optional fixed service delay, scripted sheds
    and errors."""

    def __init__(self, delay=0.0, shed_every=0, err_every=0):
        self.delay = delay
        self.shed_every = shed_every
        self.err_every = err_every
        self.calls = 0

    async def setup(self, objects, object_size):
        pass

    async def op(self, tenant, kind, obj, size):
        self.calls += 1
        if self.delay:
            await asyncio.sleep(self.delay)
        if self.shed_every and self.calls % self.shed_every == 0:
            raise SheddedOp(tenant)
        if self.err_every and self.calls % self.err_every == 0:
            raise RuntimeError("boom")
        return size


def test_runner_accounts_shed_and_errors_separately():
    async def main():
        tenants = make_tenants(10, rate=40.0)
        tgt = _FakeTarget(shed_every=5, err_every=7)
        rep = await run_open_loop(tgt, tenants, duration=0.5, seed=1)
        assert rep["shed"] > 0
        assert rep["errors"] > 0
        assert rep["completed"] + rep["shed"] + rep["errors"] == \
            rep["offered"]
        return rep

    run(main())


def test_runner_open_loop_measures_queueing_delay():
    """A slow target under open-loop load shows the backlog in the
    tail: with 0.05 s service and arrivals every ~0.01 s, measured
    latency must reflect service time at least (closed-loop would
    throttle the offering instead)."""
    async def main():
        tenants = make_tenants(4, rate=25.0)
        rep = await run_open_loop(_FakeTarget(delay=0.05), tenants,
                                  duration=0.5, seed=2)
        assert rep["p50_ms"] >= 45.0
        return rep

    run(main())


def test_runner_bounds_inflight_and_counts_drops():
    async def main():
        tenants = make_tenants(8, rate=50.0)
        rep = await run_open_loop(_FakeTarget(delay=5.0), tenants,
                                  duration=0.4, seed=3,
                                  max_outstanding=4,
                                  drain_timeout=0.2)
        assert rep["dropped"] > 0
        assert rep["completed"] == 0  # nothing finished in time
        return rep

    run(main())


def test_runner_per_tenant_breakdown_is_bounded():
    async def main():
        tenants = make_tenants(50, rate=10.0)
        rep = await run_open_loop(_FakeTarget(), tenants,
                                  duration=0.3, seed=4,
                                  per_tenant=("t0", "t1"))
        assert set(rep["per_tenant"]) == {"t0", "t1"}  # ONLY tracked
        return rep

    run(main())


# -- the acceptance leg: >= 1000 tenants over the embedded cluster -----


def test_open_loop_1000_tenants_embedded_smoke():
    """Tier-1 smoke acceptance: 1000 simulated tenants, open loop,
    against the real embedded storage slice — goodput + streaming
    p50/p95/p99, zero errors, deterministic schedule, bounded
    memory."""
    n = 1000 if _SMOKE else 2000
    duration = 1.0 if _SMOKE else 4.0
    tenants = make_tenants(n, rate=2.0, zipf_theta=1.1, objects=64,
                           object_size=2048)
    rep = run(run_embedded(tenants, duration=duration, seed=7))
    assert rep["tenants"] >= 1000
    assert rep["errors"] == 0
    assert rep["completed"] >= n  # ~rate x duration x n, > n ops
    assert rep["goodput_mib_s"] > 0
    for key in ("p50_ms", "p95_ms", "p99_ms"):
        assert rep[key] is not None and rep[key] > 0
    # deterministic under the same seed (fingerprint proof is cheap;
    # wall-clock latencies of course differ run to run)
    assert schedule_fingerprint(tenants, duration, seed=7) == \
        schedule_fingerprint(tenants, duration, seed=7)


def test_embedded_target_op_kinds_move_real_bytes():
    async def main():
        from ceph_tpu.rados.embedded import LocalCluster

        cluster = LocalCluster(num_osds=4)
        try:
            cluster.create_replicated_pool("p", size=2, pg_num=8)
            tgt = EmbeddedTarget(cluster.open_ioctx("p"))
            await tgt.setup(8, 4096)
            assert await tgt.op("t", "read", 3, 4096) == 4096
            ranged = await tgt.op("t", "ranged", 3, 4096)
            assert ranged == 1024  # size//4 window
            assert await tgt.op("t", "stat", 3, 4096) == 0
            assert await tgt.op("t", "write", 3, 4096) == 4096
        finally:
            cluster.shutdown()

    run(main())


@pytest.mark.slow
def test_full_load_sweep_finds_monotone_goodput():
    """The full (non-smoke) sweep: goodput grows with offered rate
    until the knee; the sweep itself stays bounded-memory."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    out = bench.bench_load()
    rows = out["load_sweep"]
    assert len(rows) >= 3
    assert rows[1]["goodput_mib_s"] > rows[0]["goodput_mib_s"] * 1.2


# -- CLI front door ----------------------------------------------------


def test_cli_bench_tenants_flag_drives_loadgen(capsys):
    """`rados bench <s> seq --tenants N --arrival-rate R --blend ...`
    delegates to the open-loop harness over the networked client."""
    import json

    from cluster_helpers import Cluster
    from ceph_tpu.tools import rados as rados_cli

    async def main():
        cluster = Cluster(num_osds=3, osds_per_host=3)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "b", size=2, pg_num=8)
            io = cluster.client.open_ioctx("b")
            import argparse

            args = argparse.Namespace(
                seconds=1, mode="seq", block_size=2048,
                concurrency=4, read_skew=1.0, objects=16, seed=5,
                tenants=50, arrival_rate=4.0,
                blend="read=0.6,write=0.2,stat=0.2")
            rc = await rados_cli._bench(io, args)
            assert rc == 0
        finally:
            await cluster.stop()

    run(main())
    out = capsys.readouterr().out
    rep = json.loads(out)
    assert rep["mode"] == "loadgen"
    assert rep["tenants"] == 50
    assert rep["completed"] > 0
    assert rep["errors"] == 0
    assert rep["p99_ms"] > 0
    assert abs(sum(rep["blend"].values()) - 1.0) < 1e-9

"""RGW bucket notifications (rgw_notify + cls_2pc_queue roles):
per-bucket rules emit S3-shaped event records into persistent topic
queues that consumers pull and ack."""

import asyncio

from cluster_helpers import Cluster

from ceph_tpu.rgw import RGWLite


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


async def _rgw():
    cluster = Cluster(num_osds=3)
    await cluster.start()
    await cluster.client.create_replicated_pool("meta", size=2,
                                                pg_num=4)
    await cluster.client.create_replicated_pool("data", size=2,
                                                pg_num=4)
    return cluster, RGWLite(cluster.client, "data", "meta",
                            stripe_size=64 * 1024)


def test_events_created_removed_and_ack():
    async def main():
        cluster, rgw = await _rgw()
        try:
            await rgw.create_bucket("b")
            await rgw.put_bucket_notification("b", [
                {"id": "all", "topic": "t1",
                 "events": ["s3:ObjectCreated:*",
                            "s3:ObjectRemoved:*"]}])
            assert (await rgw.get_bucket_notification("b"))[0][
                "topic"] == "t1"
            etag = await rgw.put_object("b", "k1", b"payload!")
            await rgw.delete_object("b", "k1")
            events = await rgw.pull_notifications("t1")
            names = [e["eventName"] for _k, e in events]
            assert names == ["s3:ObjectCreated:Put",
                             "s3:ObjectRemoved:Delete"]
            created = events[0][1]
            assert created["bucket"] == "b"
            assert created["key"] == "k1"
            assert created["etag"] == etag
            assert created["size"] == 8
            # ack drains the queue
            await rgw.ack_notifications("t1",
                                        [k for k, _e in events])
            assert await rgw.pull_notifications("t1") == []
        finally:
            await cluster.stop()
    run(main())


def test_filters_versioning_and_multipart():
    async def main():
        cluster, rgw = await _rgw()
        try:
            await rgw.create_bucket("b")
            await rgw.put_bucket_notification("b", [
                {"id": "logs-only", "topic": "logs",
                 "events": ["s3:ObjectCreated:*"],
                 "filter_prefix": "logs/"},
                {"id": "rm", "topic": "removals",
                 "events": ["s3:ObjectRemoved:DeleteMarkerCreated"]}])
            await rgw.put_object("b", "logs/a", b"x")
            await rgw.put_object("b", "other/a", b"x")  # filtered out
            ev = await rgw.pull_notifications("logs")
            assert [e["key"] for _k, e in ev] == ["logs/a"]
            # versioned delete marker hits ONLY the marker rule
            await rgw.put_bucket_versioning("b", "enabled")
            _, vid = await rgw.put_object_ex("b", "logs/a", b"v2")
            marker = await rgw.delete_object("b", "logs/a")
            ev = await rgw.pull_notifications("removals")
            assert [e["eventName"] for _k, e in ev] == \
                ["s3:ObjectRemoved:DeleteMarkerCreated"]
            assert ev[0][1]["version_id"] == marker
            # multipart completion has its own event name
            up = await rgw.init_multipart("b", "logs/big")
            petag = await rgw.upload_part("b", "logs/big", up, 1,
                                          b"p" * (64 * 1024))
            await rgw.complete_multipart("b", "logs/big", up,
                                         [(1, petag)])
            ev = await rgw.pull_notifications("logs")
            assert ev[-1][1]["eventName"] == \
                "s3:ObjectCreated:CompleteMultipartUpload"
        finally:
            await cluster.stop()
    run(main())

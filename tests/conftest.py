"""Test config: run JAX on a virtual 8-device CPU mesh.

Real-TPU behavior is validated by bench.py and the driver's
__graft_entry__.py compile checks; unit tests must be hermetic and fast, so
they force the CPU backend with 8 virtual devices to exercise the same
sharding code paths the multi-chip mesh uses.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

"""Test config: run JAX on a virtual 8-device CPU mesh.

Real-TPU behavior is validated by bench.py and the driver's
__graft_entry__.py compile checks; unit tests must be hermetic and fast, so
they force the CPU backend with 8 virtual devices to exercise the same
sharding code paths the multi-chip mesh uses.

Note: this environment preloads jax at interpreter startup (axon TPU
tunnel .pth hook), so setting JAX_PLATFORMS here is too late; the backend
is still uninitialized though, so jax.config wins.
"""

import os

# Arm the runtime lock-order detector for the whole tier (the
# WITH_TSAN-style discipline: detection tooling on in CI, off in
# production).  Set BEFORE ceph_tpu.common.lockdep is imported — it
# reads the env at import time — and mirrored onto the module flag in
# case a plugin already pulled it in.
os.environ.setdefault("CEPH_TPU_LOCKDEP", "1")
import sys  # noqa: E402

if "ceph_tpu.common.lockdep" in sys.modules:
    sys.modules["ceph_tpu.common.lockdep"].enabled = (
        os.environ["CEPH_TPU_LOCKDEP"] == "1")

# Arm the deterministic-interleaving explorer for the WHOLE tier when
# CEPH_TPU_INTERLEAVE=1 (lockdep's schedule twin: every event loop any
# test creates permutes ready-task wakeup order with a seeded PRNG, so
# the entire suite runs under an adversarial-but-replayable schedule).
# Off by default; tests/test_static_analysis.py drives cluster
# scenarios under it explicitly via interleave.explore(seed).
from ceph_tpu.analysis import interleave  # noqa: E402

interleave.install_if_enabled()


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running multi-process/thrash tier")


# True when the device fault-injection seam is scripted for the whole
# run (the degraded-mode acceptance tier: CEPH_TPU_INJECT_DEVICE_FAIL
# forces dispatches to fail).  Bit-exactness tests must PASS via the
# host fallback in that mode; tests that assert live device-dispatch
# COUNTERS (plans compiled, batches folded, retraces bounded) mark
# themselves skipif(DEVICE_INJECTION) — their subject is definitionally
# absent while every dispatch is scripted to fail.
DEVICE_INJECTION = os.environ.get(
    "CEPH_TPU_INJECT_DEVICE_FAIL", "") not in ("", "0")


flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

try:
    import jax  # noqa: E402
except ImportError:  # jax-free env: ops fall back to numpy, jax tests skip
    pass
else:
    jax.config.update("jax_platforms", "cpu")

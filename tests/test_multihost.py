"""Cross-host EC data plane tier (parallel/multihost.py).

Four acceptance legs:

* **Real multi-process bit-exactness** — encode (fused crc) AND
  decode (decode-matrix matmul) across a REAL 2-process
  ``jax.distributed`` group (gloo CPU collectives, 2 virtual devices
  per process, hybrid ("dcn", "dp") mesh) must equal the
  single-process plans and the host numpy oracle, on odd chunk
  widths and ragged batches.
* **Host-loss shrink** — over the emulated 2-host topology, a
  ``down_host`` injection must retire the host as ONE event: one
  ``host:<id>`` breaker trip, zero per-chip breaker trips (no
  storm), ONE mesh shrink, zero host fallbacks, the ``fused-crc``
  family still closed, output bit-exact; healing re-admits the host.
* **Plan-key topology stability** — the process-topology element
  keeps plans from different cluster shapes (1x8 vs 2x4 over the
  same chips) apart, while identical topologies key identically.
* **Kill switch** — CEPH_TPU_MULTIHOST=0 collapses everything to the
  single-process PR-9 behavior bit-identically.
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import conftest

jax = pytest.importorskip("jax")

from ceph_tpu.common import circuit  # noqa: E402
from ceph_tpu.ec import plan  # noqa: E402
from ceph_tpu.models import reed_solomon as rs  # noqa: E402
from ceph_tpu.ops import gf  # noqa: E402
from ceph_tpu.parallel import multihost, striped  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RNG = np.random.default_rng(1313)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8,
    reason="needs the conftest 8-virtual-device CPU mesh")

# the shared worker-vs-local case list: odd chunks, ragged batches
CASES = [(16, 1024), (5, 1001), (3, 768)]


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.setenv("CEPH_TPU_MESH_MIN_BYTES", "0")
    monkeypatch.delenv("CEPH_TPU_MESH", raising=False)
    monkeypatch.delenv("CEPH_TPU_MULTIHOST_HOSTS", raising=False)
    circuit.reset_all()
    plan.reset_stats()
    yield
    circuit.reset_all()


def _case_results(encode_crc, matmul):
    """Run every case through the given entry points; digest the
    outputs so in-process and subprocess runs compare equal."""
    out = {}
    mat = rs.reed_sol_van_matrix(4, 2)
    for b, s in CASES:
        rng = np.random.default_rng(b * 100000 + s)
        data = rng.integers(0, 256, (b, 4, s), dtype=np.uint8)
        enc = encode_crc(mat, data, f"mh-{b}-{s}")
        assert enc is not None, (b, s)
        parity, crcs = enc
        # decode leg: chunks 0,1 erased, survivors 2,3 + both parity
        # (a decode IS the decode-rows matmul, so the mesh encode
        # kind carries it across hosts — odd widths included)
        dmat = rs.decode_matrix(mat, 4, [0, 1], [2, 3, 4, 5])
        surv = np.concatenate([data[:, 2:4, :], parity], axis=1)
        dec = matmul(dmat, np.ascontiguousarray(surv),
                     f"mh-dec-{b}-{s}")
        assert dec is not None and np.array_equal(
            np.asarray(dec), data[:, :2, :]), (b, s)
        assert dec is not None, (b, s)
        out[f"{b}x{s}"] = {
            "parity_sha": hashlib.sha256(
                np.ascontiguousarray(parity)).hexdigest(),
            "crc_sha": hashlib.sha256(
                np.ascontiguousarray(crcs)).hexdigest(),
            "decode_sha": hashlib.sha256(
                np.ascontiguousarray(dec)).hexdigest(),
        }
    return out


def _host_oracle_results():
    def encode_crc(mat, data, sig):
        b = data.shape[0]
        parity = np.stack([gf.gf_matmul_host(mat, data[i])
                           for i in range(b)])
        from ceph_tpu.ops import checksum as cks

        crcs = np.zeros((b, 6), dtype=np.uint32)
        for i in range(b):
            chunks = np.concatenate([data[i], parity[i]], axis=0)
            for j in range(6):
                crcs[i, j] = cks.crc32c(0, chunks[j].tobytes())
        return parity, crcs

    def matmul(mat, data, sig):
        return np.stack([gf.gf_matmul_host(mat, data[i])
                         for i in range(data.shape[0])])

    return _case_results(encode_crc, matmul)


def _plan_results():
    return _case_results(
        lambda m, d, s: plan.encode_with_crc(m, d, sig=s),
        lambda m, d, s: plan.encode(m, d, sig=s))


_WORKER_SRC = textwrap.dedent("""
    import hashlib, json, os, sys
    import numpy as np
    sys.path.insert(0, {repo!r})
    sys.path.insert(0, os.path.join({repo!r}, "tests"))
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["CEPH_TPU_MESH_MIN_BYTES"] = "0"
    from ceph_tpu.parallel import multihost
    assert multihost.bootstrap_from_env(), "group did not form"
    import test_multihost as tm
    out = tm._plan_results()
    out["topology"] = list(multihost.topology_signature())
    out["processes"] = multihost.process_count()
    print("RESULT " + json.dumps(out), flush=True)
""")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.skipif(conftest.DEVICE_INJECTION,
                    reason="spawns its own process group; injection\
 would fail every dispatch inside it")
def test_two_process_encode_decode_bitexact(tmp_path):
    """THE tentpole acceptance: bit-exact encode (fused crc) and
    decode across >= 2 jax.distributed processes vs the
    single-process plans and the host oracle (odd chunks, ragged
    batches)."""
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER_SRC.format(repo=REPO))
    port = _free_port()
    procs = []
    for pid in range(2):
        env = {k: v for k, v in os.environ.items()
               if k not in ("XLA_FLAGS",)}
        env.update({
            "CEPH_TPU_MULTIHOST_COORD": f"127.0.0.1:{port}",
            "CEPH_TPU_MULTIHOST_NPROC": "2",
            "CEPH_TPU_MULTIHOST_PID": str(pid),
            "CEPH_TPU_MULTIHOST_LOCAL_DEVICES": "2",
            "JAX_PLATFORMS": "cpu",
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(worker)], stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, env=env))
    try:
        outs = [p.communicate(timeout=240) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, (so, se) in zip(procs, outs):
        assert p.returncode == 0, se[-2000:]
    reports = []
    for so, _se in outs:
        line = [ln for ln in so.splitlines()
                if ln.startswith("RESULT ")][-1]
        reports.append(json.loads(line[len("RESULT "):]))
    # both processes computed the SAME global result (SPMD + gather)
    assert reports[0] == reports[1]
    assert reports[0]["processes"] == 2
    assert reports[0]["topology"][0] == 2  # two host domains
    # vs the host oracle and the single-process plans, case by case
    oracle = _host_oracle_results()
    single = _plan_results()
    for case in oracle:
        assert reports[0][case] == oracle[case], case
        assert single[case] == oracle[case], case


def test_host_loss_is_one_event(monkeypatch):
    """Losing a host retires ALL its chips in ONE event: a single
    host:<id> breaker trip, a single mesh shrink, zero per-chip
    breaker trips, zero host fallbacks, fused-crc still closed —
    then healing re-admits the host."""
    monkeypatch.setenv("CEPH_TPU_MULTIHOST_HOSTS", "2")
    assert multihost.host_count() == 2
    ids = [d.id for d in jax.devices()]
    lost_host = 1
    lost_ids = set(multihost.hosts()[lost_host])
    mat = rs.reed_sol_van_matrix(4, 2)
    data = RNG.integers(0, 256, (16, 4, 1024), dtype=np.uint8)
    want = np.stack([gf.gf_matmul_host(mat, data[i])
                     for i in range(16)])

    out = plan.encode_with_crc(mat, data, sig="hostloss")
    assert out is not None and np.array_equal(out[0], want)
    assert plan.stats()["mesh_shrinks"] == 0

    monkeypatch.setenv("CEPH_TPU_INJECT_DEVICE_FAIL",
                       f"down_host={lost_host}")
    out2 = plan.encode_with_crc(mat, data, sig="hostloss")
    assert out2 is not None and np.array_equal(out2[0], want)
    st = plan.stats()
    # ONE shrink, ONE host retirement, zero host fallbacks
    assert st["mesh_shrinks"] == 1
    assert st["host_retirements"] == 1
    assert st["host_fallbacks"] == 0
    # the host breaker holds every chip out; NO chip breaker tripped
    assert circuit.host_degraded(lost_host)
    for did in ids:
        assert circuit.device_breaker(did).state == circuit.CLOSED
        assert circuit.device_degraded(did) == (did in lost_ids)
    assert circuit.breaker("fused-crc").state == circuit.CLOSED
    healthy = plan.mesh_info()["healthy"]
    assert set(healthy).isdisjoint(lost_ids)

    # steady state: survivors keep serving without another shrink
    circuit.host_breaker(lost_host).force_open(duration=3600.0)
    out3 = plan.encode_with_crc(mat, data, sig="hostloss")
    assert out3 is not None and np.array_equal(out3[0], want)
    assert plan.stats()["mesh_shrinks"] == 1

    # heal: injection cleared + backoff expired -> the host's chips
    # rejoin and the first successful dispatch re-closes its breaker
    monkeypatch.delenv("CEPH_TPU_INJECT_DEVICE_FAIL")
    circuit.host_breaker(lost_host).force_probe()
    out4 = plan.encode_with_crc(mat, data, sig="hostloss")
    assert out4 is not None and np.array_equal(out4[0], want)
    assert set(plan.mesh_info()["healthy"]) >= lost_ids
    assert circuit.host_breaker(lost_host).state == circuit.CLOSED


def test_single_sick_chip_still_chip_level_under_host_topology(
        monkeypatch):
    """A single sick chip inside a live host must NOT retire the
    host: chip-level attribution survives the host-aware path."""
    monkeypatch.setenv("CEPH_TPU_MULTIHOST_HOSTS", "2")
    sick = jax.devices()[-1].id
    monkeypatch.setenv("CEPH_TPU_INJECT_DEVICE_FAIL", f"sick={sick}")
    mat = rs.reed_sol_van_matrix(4, 2)
    data = RNG.integers(0, 256, (16, 4, 512), dtype=np.uint8)
    want = np.stack([gf.gf_matmul_host(mat, data[i])
                     for i in range(16)])
    out = plan.encode_with_crc(mat, data, sig="sickchip")
    assert out is not None and np.array_equal(out[0], want)
    st = plan.stats()
    assert st["mesh_shrinks"] >= 1
    assert st["host_retirements"] == 0
    assert st["host_fallbacks"] == 0
    assert circuit.device_breaker(sick).state == circuit.OPEN
    assert not circuit.host_degraded(multihost.host_of_id(sick))


def test_plan_key_topology_stability():
    """The process-topology element: identical topologies key
    identically; different cluster shapes over the same chips never
    collide; the trivial single-host shape keys exactly as the
    pre-multihost 8-tuple form did (same leading elements, empty
    proc)."""
    sig = "b" * 16
    topo_2x4 = (2, ((0, (0, 1, 2, 3)), (1, (4, 5, 6, 7))))
    topo_4x2 = (4, ((0, (0, 1)), (1, (2, 3)), (2, (4, 5)),
                    (3, (6, 7))))
    base = plan.plan_key(sig, "mesh_encode", 2, 4, 16, 1024,
                         mesh=tuple(range(8)))
    k24 = plan.plan_key(sig, "mesh_encode", 2, 4, 16, 1024,
                        mesh=tuple(range(8)), proc=topo_2x4)
    k42 = plan.plan_key(sig, "mesh_encode", 2, 4, 16, 1024,
                        mesh=tuple(range(8)), proc=topo_4x2)
    assert len({base, k24, k42}) == 3
    assert k24 == plan.plan_key(sig, "mesh_encode", 2, 4, 16, 1024,
                                mesh=tuple(range(8)), proc=topo_2x4)
    # single-host: proc is empty and the key round-trips through
    # JSON identically (process-stable, like the PR-2 stability test)
    assert base[-1] == ()
    norm = json.loads(json.dumps(list(base)[:7]))
    assert norm == list(base)[:7]


def test_topology_signature_shapes(monkeypatch):
    # trivial single-host: empty (keys stay PR-9-compatible)
    assert multihost.topology_signature() == ()
    monkeypatch.setenv("CEPH_TPU_MULTIHOST_HOSTS", "2")
    sig = multihost.topology_signature()
    assert sig[0] == 2 and len(sig[1]) == 2
    hostmap = multihost.hosts()
    assert sorted(sum((list(v) for v in hostmap.values()), [])) == \
        sorted(d.id for d in jax.devices())
    # every device maps into its block
    for h, ids in hostmap.items():
        for did in ids:
            assert multihost.host_of_id(did) == h


def test_kill_switch_single_process_parity(monkeypatch):
    """CEPH_TPU_MULTIHOST=0: emulated topology ignored, bootstrap
    refuses to join a group, plan outputs bit-identical to the
    multihost-on single-host run."""
    mat = rs.reed_sol_van_matrix(4, 2)
    data = RNG.integers(0, 256, (8, 4, 1024), dtype=np.uint8)
    on = plan.encode_with_crc(mat, data, sig="ks")
    monkeypatch.setenv("CEPH_TPU_MULTIHOST", "0")
    monkeypatch.setenv("CEPH_TPU_MULTIHOST_HOSTS", "2")
    monkeypatch.setenv("CEPH_TPU_MULTIHOST_COORD", "127.0.0.1:1")
    monkeypatch.setenv("CEPH_TPU_MULTIHOST_NPROC", "2")
    assert multihost.topology_signature() == ()
    assert multihost.host_count() == 1
    assert not multihost.initialize()
    off = plan.encode_with_crc(mat, data, sig="ks")
    assert on is not None and off is not None
    assert np.array_equal(on[0], off[0])
    assert np.array_equal(on[1], off[1])


def test_hybrid_mesh_and_logical_rules(monkeypatch):
    """Devices spanning two hosts lay out as ("dcn", "dp") with
    `stripe` mapping across BOTH axes; a one-host set stays flat
    ("dp",) with `stripe` -> "dp" exactly as before."""
    from jax.sharding import PartitionSpec as P

    monkeypatch.setenv("CEPH_TPU_MULTIHOST_HOSTS", "2")
    mesh = striped.stripe_mesh(jax.devices())
    assert mesh.axis_names == ("dcn", "dp")
    assert dict(mesh.shape) == {"dcn": 2, "dp": 4}
    assert striped.logical_spec("stripe", "shard", "byte",
                                mesh=mesh) == \
        P(("dcn", "dp"), None, None)
    assert striped.data_parallel_size(mesh) == 8
    # one host's devices: flat, and the spec collapses to plain "dp"
    sub = striped.stripe_mesh(jax.devices()[:4])
    assert sub.axis_names == ("dp",)
    assert striped.logical_spec("stripe", "shard", "byte",
                                mesh=sub) == P("dp", None, None)
    # ragged per-host counts fall back to flat (still dispatchable)
    ragged = striped.stripe_mesh(jax.devices()[:7])
    assert ragged.axis_names == ("dp",)


def test_down_host_injection_spec():
    spec = circuit.parse_injection("down_host=3")
    assert spec["down_host"] == 3
    spec = circuit.parse_injection("p=0.1,down-host=1")
    assert spec["down_host"] == 1 and spec["p"] == 0.1


def test_retire_host_is_one_breaker_event(monkeypatch):
    monkeypatch.setenv("CEPH_TPU_MULTIHOST_HOSTS", "2")
    circuit.retire_host(1)
    st = circuit.host_breaker(1).stats()
    assert st["trips"] == 1
    assert circuit.host_degraded(1)
    # every chip of host 1 degraded through the ONE host breaker
    for did in multihost.hosts()[1]:
        assert circuit.device_degraded(did)
        assert circuit.device_breaker(did).state == circuit.CLOSED
    for did in multihost.hosts()[0]:
        assert not circuit.device_degraded(did)
    # host families stay out of perf_dump (label-map surface instead)
    assert not any(f.startswith("host:") for f in circuit.perf_dump())
    assert "1" in circuit.host_stats()


def test_agreement_single_process_identity():
    assert multihost.agree("t", "x") == {0: "x"}
    assert multihost.agreed_healthy([3, 1, 2]) == (1, 2, 3)


def test_mesh_info_surfaces_hosts(monkeypatch):
    monkeypatch.setenv("CEPH_TPU_MULTIHOST_HOSTS", "2")
    info = plan.mesh_info()
    assert info["host_count"] == 2
    assert set(info["hosts"]) == {"0", "1"}
    assert info["hosts"]["0"]["degraded"] == 0
    assert "host_retirements" in info

"""In-loop cluster harness (the qa/standalone/ceph-helpers.sh role).

Spins a mini-mon + N OSD daemons on loopback inside one asyncio loop —
all "nodes" are endpoints on 127.0.0.1, exactly like ceph-helpers runs
real daemons on one host (SURVEY.md §4.2).  kill_osd drops a daemon off
the network without clean shutdown; revive_osd boots a fresh daemon.

Store lifecycle across kill/revive is an EXPLICIT contract
(`persistent=`):

- persistent=False (default, MemStore): the in-RAM store object
  survives the kill and the revived daemon reboots on it — a crashed
  process with an intact page cache, no remount path exercised.
- persistent=True (TPUStore via `tpustore_factory`): kill_osd
  crash-closes the store (no clean umount, no deferred-WAL flush —
  and, with CEPH_TPU_CRASH_INJECT armed on a FaultStore, a synthesized
  POWER-CUT image); revive_osd builds a fresh store over the same
  directory, mounts it (replaying the deferred WAL) and asserts the
  remounted fsid matches the killed store's — the revived OSD got ITS
  disk back, not a fresh one.
"""

from __future__ import annotations

import asyncio
import os
from typing import Dict, List, Optional

from ceph_tpu.mon import MonDaemon
from ceph_tpu.os.memstore import MemStore
from ceph_tpu.osd.daemon import OSDDaemon
from ceph_tpu.rados.client import RadosClient


def tpustore_factory(base_dir, fault: bool = False):
    """Per-OSD TPUStore directories under `base_dir` (the
    Cluster(store_factory=..., persistent=True) mode).  fault=True
    arms the FaultStore recording shim so kill_osd can synthesize
    power-cut images (CEPH_TPU_CRASH_INJECT) and tests can script
    bit-rot into live shards."""
    from ceph_tpu.os.faultstore import FaultStore
    from ceph_tpu.os.tpustore import TPUStore

    def make(osd_id: int):
        cls = FaultStore if fault else TPUStore
        return cls(os.path.join(str(base_dir), f"osd-{osd_id}"))

    return make

FAST_CONFIG = {
    # tight timings so failure-detection tests run in seconds — but not
    # so tight that a CPU-contended test host (full-suite runs, JAX
    # compiles) stalls the shared event loop past the grace and the mon
    # falsely marks live OSDs down, churning every test into remap
    # storms.  Real kills are still detected in ~3s << the 15s
    # wait_for_osd_down budget.
    "osd_heartbeat_interval": 0.3,
    "osd_heartbeat_grace": 2.5,
    # generous: a DEAD peer fails fast via connection refusal; this
    # only bites for alive-but-CPU-stalled peers, where a short
    # timeout manufactures indeterminate sub-writes by the hundreds
    "osd_sub_op_timeout": 8.0,
}
FAST_MON_CONFIG = {
    "mon_osd_min_down_reporters": 1,
    "osd_heartbeat_grace": 2.5,
}


class Cluster:
    def __init__(self, num_osds: int = 4, osds_per_host: int = 2,
                 osd_config: Optional[dict] = None,
                 mon_config: Optional[dict] = None,
                 store_factory=None, persistent: bool = False,
                 client_secret: Optional[str] = None,
                 num_mons: int = 1, client_secure: bool = False):
        self.num_osds = num_osds
        self.osds_per_host = osds_per_host
        self.num_mons = num_mons
        self.osd_config = dict(FAST_CONFIG)
        if num_osds > 8:
            # one shared event loop: scale grace with daemon count so
            # scheduling jitter can't masquerade as failures
            self.osd_config["osd_heartbeat_interval"] = 0.5
            self.osd_config["osd_heartbeat_grace"] = 6.0
        self.osd_config.update(osd_config or {})
        self.mon_config = dict(FAST_MON_CONFIG)
        self.mon_config.update(mon_config or {})
        self.store_factory = store_factory or (lambda osd_id: MemStore())
        self.persistent = persistent
        assert not (persistent and store_factory is None), \
            "persistent=True needs a disk-backed store_factory" \
            " (tpustore_factory)"
        self.fsids: Dict[int, str] = {}
        self.client_secret = client_secret
        self.client_secure = client_secure
        self.mons: Dict[int, MonDaemon] = {}
        self.mon_addrs: List[str] = []
        self.osds: Dict[int, OSDDaemon] = {}
        self.stores: Dict[int, object] = {}
        self.client: Optional[RadosClient] = None

    @property
    def mon(self) -> Optional[MonDaemon]:
        """The current quorum leader (falls back to any live mon) —
        the handle tests use for map/adjudication assertions."""
        live = [m for m in self.mons.values()]
        if not live:
            return None
        for m in live:
            if m.is_leader():
                return m
        return live[0]

    async def start(self) -> None:
        for rank in range(self.num_mons):
            mon = MonDaemon(self.num_osds,
                            osds_per_host=self.osds_per_host,
                            config=self.mon_config, rank=rank)
            self.mons[rank] = mon
        # two-phase: bind all, then install the monmap + elections
        self.mon_addrs = [await m.start() for m in self.mons.values()]
        if self.num_mons > 1:
            for m in self.mons.values():
                await m.set_peers(self.mon_addrs)
            await self.wait_for_quorum()
        for osd_id in range(self.num_osds):
            store = self.store_factory(osd_id)
            store.mkfs()
            store.mount()
            self.stores[osd_id] = store
            self.fsids[osd_id] = getattr(store, "fsid", "")
            await self._boot_osd(osd_id)
        self.client = RadosClient(self.mon_addrs,
                                  secret=self.client_secret,
                                  secure=self.client_secure)
        await self.client.connect()

    async def wait_for_quorum(self, timeout: float = 15.0) -> None:
        def _quorum() -> bool:
            leaders = {m.elector.leader for m in self.mons.values()
                       if m.elector is not None
                       and not m.elector.electing}
            return len(leaders) == 1 and None not in leaders

        await self._wait(_quorum, timeout, "mons never formed a quorum")

    async def _boot_osd(self, osd_id: int) -> None:
        osd = OSDDaemon(osd_id, self.mon_addrs,
                        store=self.stores[osd_id],
                        config=self.osd_config)
        self.osds[osd_id] = osd
        await osd.start()

    async def stop(self) -> None:
        if self.client is not None:
            await self.client.shutdown()
        for osd in self.osds.values():
            await osd.stop()
        for store in self.stores.values():
            try:
                store.umount()
            except Exception:
                pass
        for mon in self.mons.values():
            await mon.shutdown()

    # -- mon failure injection (thrash the control plane) ------------------

    async def kill_mon(self, rank: int) -> None:
        """Drop a mon off the network without clean shutdown."""
        mon = self.mons.pop(rank)
        await mon.msgr.shutdown()
        if mon._check_task is not None:
            mon._check_task.cancel()
        if mon._lease_watch_task is not None:
            mon._lease_watch_task.cancel()
        if mon.elector is not None:
            mon.elector.shutdown()
        if mon.paxos is not None:
            mon.paxos.shutdown()

    async def revive_mon(self, rank: int) -> None:
        """Boot a fresh mon at the dead rank's address; it rejoins the
        quorum and catches up via collect/OP_FULL."""
        assert rank not in self.mons
        host, port = self.mon_addrs[rank].rsplit(":", 1)
        mon = MonDaemon(self.num_osds,
                        osds_per_host=self.osds_per_host,
                        config=self.mon_config, rank=rank)
        self.mons[rank] = mon
        await mon.start(host=host, port=int(port))
        await mon.set_peers(self.mon_addrs)

    # -- failure injection (thrashosds kill_osd/revive_osd role) -----------

    async def kill_osd(self, osd_id: int) -> None:
        """Crash an OSD: the daemon drops off the network without
        clean shutdown.  In persistent mode the STORE crashes too —
        no clean umount, no deferred-WAL flush; with
        CEPH_TPU_CRASH_INJECT armed on a FaultStore, the on-disk
        directory is rewritten to a synthesized power-cut image
        (un-synced writes lost) before any revive can remount it."""
        await self.osds[osd_id].kill()
        del self.osds[osd_id]
        if self.persistent:
            from ceph_tpu.os.faultstore import (
                FaultStore, crash_inject_enabled)

            store = self.stores.pop(osd_id)
            if isinstance(store, FaultStore) and crash_inject_enabled():
                store.crash_powercut()
            else:
                store.crash()

    async def revive_osd(self, osd_id: int) -> None:
        """Boot a fresh daemon at the dead rank.

        CONTRACT: with persistent=False (the MemStore default) the
        daemon reboots on the SURVIVING in-memory store object — no
        remount happens and nothing was ever lost.  With
        persistent=True the store object died with the daemon; a new
        store is built over the same directory and MOUNTED (journal
        replay runs here), and the remounted fsid must match the
        killed store's — booting a different/fresh disk under a
        revived OSD id is a harness bug this assert catches."""
        assert osd_id not in self.osds
        if self.persistent:
            assert osd_id not in self.stores
            store = self.store_factory(osd_id)
            store.mount()   # remount the same directory: WAL replays
            want = self.fsids.get(osd_id)
            got = getattr(store, "fsid", "")
            if want and got != want:
                # don't leak the mounted handle: stop() only umounts
                # stores that made it into self.stores
                store.umount()
                raise AssertionError(
                    f"osd.{osd_id} remounted fsid {got!r} != {want!r}"
                    " (fresh store under a revived OSD?)")
            self.stores[osd_id] = store
        await self._boot_osd(osd_id)

    async def wait_for_osd_down(self, osd_id: int,
                                timeout: float = 30.0) -> None:
        await self._wait(lambda: self.mon.osdmap.is_down(osd_id),
                         timeout, f"osd.{osd_id} never marked down")

    async def wait_for_osd_up(self, osd_id: int,
                              timeout: float = 30.0) -> None:
        await self._wait(lambda: self.mon.osdmap.is_up(osd_id),
                         timeout, f"osd.{osd_id} never marked up")

    async def wait_for_clean(self, timeout: float = 30.0) -> None:
        """All PGs of all pools active on their primaries
        (wait_for_clean role)."""
        def _clean() -> bool:
            epoch = self.mon.osdmap.epoch
            for osd in self.osds.values():
                if osd.osdmap is None or osd.osdmap.epoch < epoch:
                    return False
            for pool in self.mon.osdmap.pools.values():
                from ceph_tpu.osd.osdmap import PgId

                for ps in range(pool.pg_num):
                    pg = PgId(pool.id, ps)
                    _a, primary = self.mon.osdmap.pg_to_acting_osds(pg)
                    if primary < 0 or primary not in self.osds:
                        return False
                    state = self.osds[primary].pgs.get(pg)
                    if state is None or state.state != "active" or \
                            state.unfound:
                        return False
            return True

        await self._wait(_clean, timeout, "cluster never went clean")

    async def _wait(self, cond, timeout: float, what: str) -> None:
        for _ in range(int(timeout / 0.05)):
            if cond():
                return
            await asyncio.sleep(0.05)
        raise TimeoutError(what)

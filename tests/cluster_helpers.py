"""In-loop cluster harness (the qa/standalone/ceph-helpers.sh role).

Spins a mini-mon + N OSD daemons on loopback inside one asyncio loop —
all "nodes" are endpoints on 127.0.0.1, exactly like ceph-helpers runs
real daemons on one host (SURVEY.md §4.2).  kill_osd drops a daemon off
the network without clean shutdown (its store survives, like a crashed
process with an intact disk); revive_osd boots a fresh daemon on the
surviving store.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional

from ceph_tpu.mon import MonDaemon
from ceph_tpu.os.memstore import MemStore
from ceph_tpu.osd.daemon import OSDDaemon
from ceph_tpu.rados.client import RadosClient

FAST_CONFIG = {
    # tight timings so failure-detection tests run in seconds — but not
    # so tight that a CPU-contended test host (full-suite runs, JAX
    # compiles) stalls the shared event loop past the grace and the mon
    # falsely marks live OSDs down, churning every test into remap
    # storms.  Real kills are still detected in ~3s << the 15s
    # wait_for_osd_down budget.
    "osd_heartbeat_interval": 0.3,
    "osd_heartbeat_grace": 2.5,
    # generous: a DEAD peer fails fast via connection refusal; this
    # only bites for alive-but-CPU-stalled peers, where a short
    # timeout manufactures indeterminate sub-writes by the hundreds
    "osd_sub_op_timeout": 8.0,
}
FAST_MON_CONFIG = {
    "mon_osd_min_down_reporters": 1,
    "osd_heartbeat_grace": 2.5,
}


class Cluster:
    def __init__(self, num_osds: int = 4, osds_per_host: int = 2,
                 osd_config: Optional[dict] = None,
                 mon_config: Optional[dict] = None,
                 store_factory=None,
                 client_secret: Optional[str] = None):
        self.num_osds = num_osds
        self.osds_per_host = osds_per_host
        self.osd_config = dict(FAST_CONFIG)
        if num_osds > 8:
            # one shared event loop: scale grace with daemon count so
            # scheduling jitter can't masquerade as failures
            self.osd_config["osd_heartbeat_interval"] = 0.5
            self.osd_config["osd_heartbeat_grace"] = 6.0
        self.osd_config.update(osd_config or {})
        self.mon_config = dict(FAST_MON_CONFIG)
        self.mon_config.update(mon_config or {})
        self.store_factory = store_factory or (lambda osd_id: MemStore())
        self.client_secret = client_secret
        self.mon: Optional[MonDaemon] = None
        self.osds: Dict[int, OSDDaemon] = {}
        self.stores: Dict[int, object] = {}
        self.client: Optional[RadosClient] = None

    async def start(self) -> None:
        self.mon = MonDaemon(self.num_osds,
                             osds_per_host=self.osds_per_host,
                             config=self.mon_config)
        await self.mon.start()
        for osd_id in range(self.num_osds):
            store = self.store_factory(osd_id)
            store.mkfs()
            store.mount()
            self.stores[osd_id] = store
            await self._boot_osd(osd_id)
        self.client = RadosClient(self.mon.addr,
                                  secret=self.client_secret)
        await self.client.connect()

    async def _boot_osd(self, osd_id: int) -> None:
        osd = OSDDaemon(osd_id, self.mon.addr,
                        store=self.stores[osd_id],
                        config=self.osd_config)
        self.osds[osd_id] = osd
        await osd.start()

    async def stop(self) -> None:
        if self.client is not None:
            await self.client.shutdown()
        for osd in self.osds.values():
            await osd.stop()
        for store in self.stores.values():
            try:
                store.umount()
            except Exception:
                pass
        if self.mon is not None:
            await self.mon.shutdown()

    # -- failure injection (thrashosds kill_osd/revive_osd role) -----------

    async def kill_osd(self, osd_id: int) -> None:
        await self.osds[osd_id].kill()
        del self.osds[osd_id]

    async def revive_osd(self, osd_id: int) -> None:
        assert osd_id not in self.osds
        await self._boot_osd(osd_id)

    async def wait_for_osd_down(self, osd_id: int,
                                timeout: float = 30.0) -> None:
        await self._wait(lambda: self.mon.osdmap.is_down(osd_id),
                         timeout, f"osd.{osd_id} never marked down")

    async def wait_for_osd_up(self, osd_id: int,
                              timeout: float = 30.0) -> None:
        await self._wait(lambda: self.mon.osdmap.is_up(osd_id),
                         timeout, f"osd.{osd_id} never marked up")

    async def wait_for_clean(self, timeout: float = 30.0) -> None:
        """All PGs of all pools active on their primaries
        (wait_for_clean role)."""
        def _clean() -> bool:
            epoch = self.mon.osdmap.epoch
            for osd in self.osds.values():
                if osd.osdmap is None or osd.osdmap.epoch < epoch:
                    return False
            for pool in self.mon.osdmap.pools.values():
                from ceph_tpu.osd.osdmap import PgId

                for ps in range(pool.pg_num):
                    pg = PgId(pool.id, ps)
                    _a, primary = self.mon.osdmap.pg_to_acting_osds(pg)
                    if primary < 0 or primary not in self.osds:
                        return False
                    state = self.osds[primary].pgs.get(pg)
                    if state is None or state.state != "active" or \
                            state.unfound:
                        return False
            return True

        await self._wait(_clean, timeout, "cluster never went clean")

    async def _wait(self, cond, timeout: float, what: str) -> None:
        for _ in range(int(timeout / 0.05)):
            if cond():
                return
            await asyncio.sleep(0.05)
        raise TimeoutError(what)

"""RBD journaling + mirroring: write-ahead events, crash replay, and
journal-based replication to a second pool.

Mirrors the reference's librbd journal / rbd_mirror coverage
(/root/reference/src/test/librbd/journal/, test/rbd_mirror/): the
write-ahead contract (event durable before apply), open-time replay
of unapplied events, and an ImageReplayer keeping a secondary in
sync through writes, resizes and snapshots."""

import asyncio

import numpy as np

from cluster_helpers import Cluster

from ceph_tpu.rbd import RBD
from ceph_tpu.rbd.journal import ImageJournal, decode_events, \
    encode_event
from ceph_tpu.rbd.mirror import MirrorReplayer


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


ORDER = 14  # 16 KiB objects


def test_event_codec_and_torn_tail():
    evs = [encode_event(1, {"op": "write", "offset": 7,
                            "data": b"abc"}),
           encode_event(2, {"op": "resize", "size": 99})]
    blob = b"".join(evs)
    out = decode_events(blob)
    assert [e["seq"] for e in out] == [1, 2]
    assert out[0]["data"] == b"abc" and out[1]["size"] == 99
    # torn tail (crashed append): intact prefix survives
    out = decode_events(blob + evs[0][: len(evs[0]) // 2])
    assert [e["seq"] for e in out] == [1, 2]


def test_commit_position_is_contiguous():
    async def main():
        cluster = Cluster(num_osds=2)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "p", size=2, pg_num=4)
            io = cluster.client.open_ioctx("p")
            j = ImageJournal(io, "imgX")
            await j.open()
            s1 = await j.append({"op": "write", "offset": 0,
                                 "data": b"a"})
            s2 = await j.append({"op": "write", "offset": 1,
                                 "data": b"b"})
            # out-of-order completion: committing s2 first must NOT
            # advance past the still-applying s1
            await j.commit(s2)
            assert j.hdr["committed"] == 0
            await j.commit(s1)
            assert j.hdr["committed"] == s2
        finally:
            await cluster.stop()

    run(main())


def test_crash_replay_applies_unapplied_events():
    """An event journaled but never applied (crash between append and
    data write) must be applied by open-time replay."""
    async def main():
        cluster = Cluster(num_osds=2)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "rbd", size=2, pg_num=4)
            ioctx = cluster.client.open_ioctx("rbd")
            rbd = RBD()
            await rbd.create(ioctx, "jimg", 100_000, order=ORDER,
                             exclusive_lock=True, journaling=True)
            img = await rbd.open(ioctx, "jimg")
            await img.write(0, b"applied bytes")
            # forge the crash: append an event straight to the journal
            # (as a dying writer would have) without applying it
            j = ImageJournal(ioctx, img.id)
            await j.open()
            await j.append({"op": "write", "offset": 50_000,
                            "data": b"ghost write"})
            await img.close()

            img2 = await rbd.open(ioctx, "jimg")   # replay happens here
            got = await img2.read(50_000, len(b"ghost write"))
            assert got == b"ghost write"
            got = await img2.read(0, len(b"applied bytes"))
            assert got == b"applied bytes"
            # replay advanced the commit position: a THIRD open
            # replays nothing (journal drained)
            j2 = ImageJournal(ioctx, img2.id)
            await j2.open()
            assert await j2.events_since(
                j2.hdr["committed"]) == []
            await img2.close()
        finally:
            await cluster.stop()

    run(main())


def test_mirror_bootstrap_and_tail():
    """Full mirror flow: bootstrap copies current content, replay
    tails subsequent writes/resize/snap onto the secondary pool."""
    async def main():
        cluster = Cluster(num_osds=3, osds_per_host=1)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "site-a", size=2, pg_num=4)
            await cluster.client.create_replicated_pool(
                "site-b", size=2, pg_num=4)
            src_io = cluster.client.open_ioctx("site-a")
            dst_io = cluster.client.open_ioctx("site-b")
            rbd = RBD()
            await rbd.create(src_io, "vm-disk", 200_000, order=ORDER,
                             exclusive_lock=True, journaling=True)
            src = await rbd.open(src_io, "vm-disk")
            rng = np.random.default_rng(7)
            base = rng.integers(0, 256, 60_000,
                                dtype=np.uint8).tobytes()
            await src.write(0, base)
            await src.close()

            mirror = MirrorReplayer(src_io, dst_io, "vm-disk")
            await mirror.bootstrap()
            dst = await rbd.open(dst_io, "vm-disk")
            assert await dst.read(0, len(base)) == base
            await dst.close()

            # tail: writes + resize + snapshot after bootstrap
            src = await rbd.open(src_io, "vm-disk")
            patch = b"post-bootstrap" * 100
            await src.write(100_000, patch)
            await src.snap_create("s1")
            await src.write(100_000, b"after-snap!")
            await src.resize(300_000)
            await src.write(250_000, b"grown")
            await src.close()

            applied = await mirror.replay_once()
            assert applied >= 4
            dst = await rbd.open(dst_io, "vm-disk")
            assert dst.size() == 300_000
            assert await dst.read(250_000, 5) == b"grown"
            assert await dst.read(100_000, 11) == b"after-snap!"
            # the snapshot replicated — and preserves pre-snap bytes
            dst.snap_set("s1")
            assert await dst.read(100_000, 14) == patch[:14]
            dst.snap_set(None)
            await dst.close()

            # idempotent: nothing new -> nothing applied
            assert await mirror.replay_once() == 0

            # continuous mode keeps the secondary converged
            await mirror.start(interval=0.1)
            src = await rbd.open(src_io, "vm-disk")
            await src.write(0, b"live-tail")
            await src.close()
            for _ in range(50):
                dst = await rbd.open(dst_io, "vm-disk")
                got = await dst.read(0, 9)
                await dst.close()
                if got == b"live-tail":
                    break
                await asyncio.sleep(0.1)
            await mirror.stop()
            assert got == b"live-tail"
        finally:
            await cluster.stop()

    run(main())

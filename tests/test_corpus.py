"""Cross-version encoding corpus (the ceph-object-corpus /
readable.sh role): every release archives one encoded blob + canonical
dump per versioned wire type under tests/corpus/<tag>/; this test
decodes EVERY archived release's blobs with TODAY's code and compares
dumps — a wire change that breaks or silently reinterprets an older
release's bytes fails here, BEFORE it ships.

Adding a new version: python -m ceph_tpu.tools.dencoder corpus_create
tests/corpus/<new-tag>  (never regenerate an old tag's directory)."""

import glob
import os

from ceph_tpu.tools import dencoder

CORPUS_ROOT = os.path.join(os.path.dirname(__file__), "corpus")


def test_all_archived_versions_decode():
    dirs = sorted(d for d in glob.glob(os.path.join(CORPUS_ROOT, "*"))
                  if os.path.isdir(d))
    assert dirs, "no archived corpus versions"
    for d in dirs:
        assert dencoder.corpus_check(d) == 0, f"corpus {d} drifted"


def test_fresh_corpus_round_trips(tmp_path):
    """Harness self-check: a corpus generated NOW must verify NOW."""
    out = str(tmp_path / "fresh")
    assert dencoder.corpus_create(out) == 0
    assert dencoder.corpus_check(out) == 0
    assert len(glob.glob(out + "/*.bin")) >= 30

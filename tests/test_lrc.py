"""LRC plugin tests, mirroring the reference's TestErasureCodeLrc.cc
coverage: kml profile generation, layered encode/decode, local-repair
minimum_to_decode, error paths."""

import numpy as np
import pytest

from ceph_tpu.ec.interface import ErasureCodeError
from ceph_tpu.ec.lrc import ErasureCodeLrc
from ceph_tpu.ec.registry import create_erasure_code


def make_kml(k=4, m=2, l=3):
    return create_erasure_code(
        {"plugin": "lrc", "k": str(k), "m": str(m), "l": str(l)})


def payload(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


def test_kml_generation():
    lrc = make_kml(4, 2, 3)
    prof = lrc.get_profile()
    assert prof["mapping"] == "DD__DD__"
    assert lrc.get_chunk_count() == 8
    assert lrc.get_data_chunk_count() == 4
    assert lrc.get_coding_chunk_count() == 4
    assert len(lrc.layers) == 3  # one global + two locals
    assert lrc.layers[0].chunks_map == "DDc_DDc_"
    assert lrc.layers[1].chunks_map == "DDDc____"
    assert lrc.layers[2].chunks_map == "____DDDc"


def test_kml_modulo_errors():
    with pytest.raises(ErasureCodeError):
        make_kml(4, 2, 4)   # (k+m) % l != 0
    with pytest.raises(ErasureCodeError):
        make_kml(5, 1, 3)   # k % groups != 0
    with pytest.raises(ErasureCodeError):
        create_erasure_code({"plugin": "lrc", "k": "4", "m": "2"})  # partial kml


def test_kml_rejects_generated_keys():
    with pytest.raises(ErasureCodeError):
        create_erasure_code({"plugin": "lrc", "k": "4", "m": "2", "l": "3",
                             "mapping": "DD__DD__"})


def test_missing_layers():
    with pytest.raises(ErasureCodeError):
        create_erasure_code({"plugin": "lrc"})
    with pytest.raises(ErasureCodeError):
        create_erasure_code({"plugin": "lrc",
                             "layers": '[["DDc",""]]'})  # no mapping
    with pytest.raises(ErasureCodeError):
        create_erasure_code({"plugin": "lrc", "mapping": "DD_",
                             "layers": "not json"})


def test_round_trip_no_erasure():
    lrc = make_kml()
    data = payload(4096)
    chunks = lrc.encode(range(lrc.get_chunk_count()), data)
    assert len(chunks) == 8
    assert lrc.decode_concat(chunks)[:len(data)] == data


@pytest.mark.parametrize("erased", [
    [0], [3], [2], [7],            # single erasures (local repair)
    [0, 4],                        # one per group
    [0, 1],                        # two in one group (needs global layer)
    [0, 1, 4],                     # mixed
    [2, 3],                        # global parity + local parity of group 0
])
def test_decode_with_erasures(erased):
    lrc = make_kml()
    data = payload(8192, seed=len(erased))
    full = lrc.encode(range(8), data)
    available = {i: c for i, c in full.items() if i not in erased}
    decoded = lrc.decode(set(erased), available)
    for i in erased:
        assert decoded[i] == full[i], f"chunk {i}"
    assert lrc.decode_concat(available)[:len(data)] == data


def test_too_many_erasures():
    lrc = make_kml()
    data = payload(4096)
    full = lrc.encode(range(8), data)
    # all of group 0's data + parity beyond recoverability:
    # global layer can fix 2 erasures, local 1 — 0,1,2,3 erased kills group 0
    available = {i: c for i, c in full.items() if i not in (0, 1, 2, 3)}
    with pytest.raises(ErasureCodeError):
        lrc.decode({0, 1, 2, 3}, available)


def test_minimum_to_decode_local_repair():
    """The LRC headline property: a single lost chunk reads only its local
    group (l chunks), not k chunks."""
    lrc = make_kml(4, 2, 3)
    want = set(range(8))
    # chunk 1 lost: local layer DDDc____ covers it with the other 3 members
    minimum = lrc.minimum_to_decode({1}, want - {1})
    assert set(minimum) == {0, 2, 3}
    # compare: a plain RS k=4 code would need 4 chunks


def test_minimum_to_decode_no_erasure():
    lrc = make_kml()
    m = lrc.minimum_to_decode({0, 5}, set(range(8)))
    assert set(m) == {0, 5}


def test_minimum_to_decode_cascade():
    """Erasures needing cascaded recovery fall through to case 3."""
    lrc = make_kml()
    # lose 1 (data) and 3 (its local parity): local layer of group 0 has two
    # erasures > its single parity, so the global layer must recover 1
    available = set(range(8)) - {1, 3}
    minimum = lrc.minimum_to_decode({1}, available)
    assert 1 not in minimum
    assert set(minimum) <= available


def test_minimum_to_decode_insufficient():
    lrc = make_kml()
    with pytest.raises(ErasureCodeError):
        lrc.minimum_to_decode({0}, {4, 5, 6, 7})


def test_explicit_layers_profile():
    """Hand-written mapping/layers (the non-kml path)."""
    profile = {
        "plugin": "lrc",
        "mapping": "DDD__",
        "layers": '[["DDDc_", ""], ["DDD_c", ""]]',
    }
    lrc = create_erasure_code(profile)
    assert lrc.get_chunk_count() == 5
    assert lrc.get_data_chunk_count() == 3
    data = payload(3000, seed=9)
    full = lrc.encode(range(5), data)
    for erased in ([3], [4], [0]):
        avail = {i: c for i, c in full.items() if i not in erased}
        out = lrc.decode(set(erased), avail)
        for i in erased:
            assert out[i] == full[i]


def test_layer_map_length_mismatch():
    with pytest.raises(ErasureCodeError):
        create_erasure_code({
            "plugin": "lrc", "mapping": "DD_",
            "layers": '[["DDc_", ""]]'})


def test_trailing_comma_layers():
    """json_spirit-style trailing commas (the reference kml generator emits
    them) must parse."""
    profile = {
        "plugin": "lrc",
        "mapping": "DD_",
        "layers": '[ [ "DDc", "" ], ]',
    }
    lrc = create_erasure_code(profile)
    assert lrc.get_chunk_count() == 3


def test_create_rule():
    from ceph_tpu.crush.map import build_flat_cluster

    cmap = build_flat_cluster(12, osds_per_host=2)
    lrc = make_kml(4, 2, 3)
    ruleno = lrc.create_rule("lrcrule", cmap)
    assert ruleno >= 0
    rule = cmap.rules[ruleno]
    assert rule.rule_type == 3
    # default kml steps: chooseleaf host 0
    assert len(rule.steps) == 3  # take, chooseleaf, emit


def test_create_rule_locality():
    from ceph_tpu.crush.map import build_flat_cluster

    cmap = build_flat_cluster(16, osds_per_host=2)
    lrc = create_erasure_code({
        "plugin": "lrc", "k": "4", "m": "2", "l": "3",
        "crush-locality": "host", "crush-failure-domain": "osd"})
    ruleno = lrc.create_rule("lrcrule2", cmap)
    rule = cmap.rules[ruleno]
    # take / choose host groups / chooseleaf osd l+1 / emit
    assert len(rule.steps) == 4


def test_kml_8_4_6():
    """A larger valid kml shape (k=8 m=4 l=6 -> 2 groups of 7)."""
    lrc = make_kml(8, 4, 6)
    assert lrc.get_chunk_count() == 14
    assert lrc.get_data_chunk_count() == 8
    data = payload(1 << 16, seed=11)
    full = lrc.encode(range(14), data)
    for erased in ([0], [6], [13], [0, 7], [1, 2]):
        avail = {i: c for i, c in full.items() if i not in erased}
        out = lrc.decode(set(erased), avail)
        for i in erased:
            assert out[i] == full[i]
    assert lrc.decode_concat(full)[:len(data)] == data


def test_reference_implicit_parity_cascade():
    """The reference's own tricky pattern (TestErasureCodeLrc.cc:525-600):
    mapping __DDD__DD, erasures {2,7,8}: layer c_DDD____ recovers 2, then
    _cDDD_cDD recovers 7 and 8.  Their truly-unrecoverable case {2,3,7,8}
    must still fail."""
    profile = {
        "plugin": "lrc",
        "mapping": "__DDD__DD",
        "layers": '[ [ "_cDDD_cDD", "" ], [ "c_DDD____", "" ],'
                  ' [ "_____cDDD", "" ], ]',
    }
    lrc = create_erasure_code(profile)
    assert lrc.get_chunk_count() == 9

    minimum = lrc.minimum_to_decode({8}, set(range(9)) - {2, 7, 8})
    assert set(minimum) <= set(range(9)) - {2, 7, 8}

    data = payload(9 * 512, seed=21)
    full = lrc.encode(range(9), data)
    avail = {i: c for i, c in full.items() if i not in (2, 7, 8)}
    out = lrc.decode({8}, avail)
    assert out[8] == full[8]

    with pytest.raises(ErasureCodeError):
        lrc.minimum_to_decode({8}, set(range(9)) - {2, 3, 7, 8})

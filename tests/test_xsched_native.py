"""Native fused XOR-tape executor (native/src/xor_sched.cc lowered
via ec/xsched.py lower_program/execute_native): bit-parity of the
native tape against execute_host AND naive_xor_matmul across the
bitmatrix (technique, k, w) space and random matrices, the packed
multi-object arena path (ec_util._encode_many_bitmatrix) against
per-item encode_with_hinfo, the CEPH_TPU_NATIVE_XSCHED=0 kill switch
/ automatic host fallback, the crc-span folding kernel against
direct crc32c folds, and the tape-cache + native-vs-host executor
counters in xsched.stats().
"""

from __future__ import annotations

import numpy as np
import pytest

from ceph_tpu.ec import xsched
from ceph_tpu.ec.registry import create_erasure_code
from ceph_tpu.ops import checksum as cks
from ceph_tpu.osd import ec_util

RNG = np.random.default_rng(0xFA57)

NATIVE = xsched.native_available()
needs_native = pytest.mark.skipif(
    not NATIVE, reason="native xor_sched executor not built")


def _codec(technique: str, **extra):
    profile = {"plugin": "ec_jax", "technique": technique, "k": "4",
               "m": "2", "packetsize": "32", "tpu": "false"}
    profile.update({k: str(v) for k, v in extra.items()})
    return create_erasure_code(profile)


def _exec_host(sched: xsched.XorSchedule,
               pk: np.ndarray) -> np.ndarray:
    """Host tier over a (B, C, ps) packet stack -> (B, R, ps)."""
    b, c, ps = pk.shape
    out = np.zeros((b, sched.n_out, ps), dtype=np.uint8)
    xsched.execute_host(sched, [pk[:, i, :] for i in range(c)],
                        [out[:, r, :] for r in range(sched.n_out)])
    return out


def _exec_native(sched: xsched.XorSchedule,
                 pk: np.ndarray) -> np.ndarray:
    """Native tape over the same packet stack: each of the B packet
    blocks is one arena object (the multi-object replay path)."""
    b, c, ps = pk.shape
    prog = xsched.lower_program(sched)
    arena = np.zeros((b, prog.n_regions, ps), dtype=np.uint8)
    arena[:, :c, :] = pk
    xsched.execute_native(prog, arena)
    return np.ascontiguousarray(arena[:, prog.out_base:, :])


# -- tape lowering invariants ------------------------------------------


def test_lowered_tape_shape_and_region_space():
    bm = (RNG.integers(0, 2, (10, 24), dtype=np.uint8))
    sched = xsched.compile_matrix(bm)
    prog = xsched.lower_program(sched)
    assert prog.sig == sched.sig
    assert prog.n_in == sched.n_in and prog.n_out == sched.n_out
    assert prog.n_slots == sched.n_slots
    assert prog.out_base == prog.n_in + prog.n_slots
    assert prog.n_regions == prog.out_base + prog.n_out
    assert prog.tape.dtype == np.int32
    assert prog.tape.shape == (prog.n_ops, 3)
    assert prog.tape.flags.c_contiguous
    assert not prog.tape.flags.writeable
    # every dst is a temp slot or an output region, never an input
    assert int(prog.tape[:, 0].min()) >= prog.n_in
    # every output region is written at least once
    written = set(prog.tape[:, 0].tolist())
    for r in range(prog.n_out):
        assert prog.out_base + r in written


def test_tape_cache_hits_and_misses_counted():
    bm = RNG.integers(0, 2, (8, 16), dtype=np.uint8)
    sched = xsched.compile_matrix(bm)
    xsched.clear()
    sched = xsched.compile_matrix(bm)  # repopulate schedule cache
    xsched.reset_stats()
    p1 = xsched.lower_program(sched)
    p2 = xsched.lower_program(sched)
    st = xsched.stats()
    assert st["tape_misses"] == 1 and st["tape_hits"] == 1
    assert p1 is p2  # memoized artifact, not a re-lowering


# -- bit-parity: native vs host vs naive -------------------------------


@needs_native
@pytest.mark.parametrize("shape,ps,b", [
    ((8, 16), 64, 1), ((14, 28), 32, 3), ((24, 48), 16, 7),
    ((6, 64), 128, 2),
])
def test_random_matrix_parity_three_tiers(shape, ps, b):
    """naive row-walk == host schedule == native tape, byte for byte,
    including multi-object arenas (b packed objects per run)."""
    for trial in range(6):
        bm = RNG.integers(0, 2, shape, dtype=np.uint8)
        pk = RNG.integers(0, 256, (b, shape[1], ps), dtype=np.uint8)
        want = xsched.naive_xor_matmul(bm, pk)
        sched = xsched.compile_matrix(bm)
        assert np.array_equal(_exec_host(sched, pk), want)
        assert np.array_equal(_exec_native(sched, pk), want)


@needs_native
@pytest.mark.parametrize("technique,k,w", [
    ("liberation", 4, 7), ("liberation", 7, 11),
    ("blaum_roth", 4, 6), ("blaum_roth", 6, 10),
    ("liber8tion", 4, 8), ("liber8tion", 8, 8),
])
def test_bitmatrix_family_parity_sweep(technique, k, w):
    """(k, m, w) sweep over the bitmatrix trio: the codec's generator
    matrix runs identically through all three executors."""
    codec = _codec(technique, k=k, w=w)
    bm = codec.bitmatrix
    ps = 32
    pk = RNG.integers(0, 256, (2, k * w, ps), dtype=np.uint8)
    want = xsched.naive_xor_matmul(bm, pk)
    sched = xsched.compile_matrix(bm, sig=codec._sig)
    assert np.array_equal(_exec_host(sched, pk), want)
    assert np.array_equal(_exec_native(sched, pk), want)


@needs_native
@pytest.mark.parametrize("technique,w,blocks", [
    ("liberation", 7, 1), ("liberation", 7, 3),
    ("blaum_roth", 6, 2), ("liber8tion", 8, 1), ("liber8tion", 8, 4),
])
def test_codec_encode_parity_native_vs_host_vs_naive(
        monkeypatch, technique, w, blocks):
    """Full-codec parity: encode under the native tape, the host tier
    (CEPH_TPU_NATIVE_XSCHED=0), and the naive row-walk
    (CEPH_TPU_XSCHED=0) produces identical chunks — single-block and
    multi-block chunk geometries both."""
    ps = 32
    # k=4 chunks of `blocks` w-packet blocks each (blocks==1 is the
    # flat-copy packing fast path, >1 the strided transpose copy)
    payload = bytes(RNG.integers(
        0, 256, 4 * w * ps * blocks, dtype=np.uint8))

    def encode(**env):
        for key in ("CEPH_TPU_XSCHED", "CEPH_TPU_NATIVE_XSCHED"):
            monkeypatch.delenv(key, raising=False)
        for key, val in env.items():
            monkeypatch.setenv(key, val)
        codec = _codec(technique, w=w, packetsize=ps)
        out = codec.encode(range(codec.k + codec.m), payload)
        return {i: bytes(b) for i, b in out.items()}

    native = encode()
    host = encode(CEPH_TPU_NATIVE_XSCHED="0")
    naive = encode(CEPH_TPU_XSCHED="0")
    assert native == host == naive


@needs_native
def test_codec_decode_parity_all_erasures(monkeypatch):
    """Decode schedules (inverted submatrices) hold the same parity
    across every 1- and 2-erasure pattern."""
    import itertools

    codec = _codec("liber8tion", w=8, packetsize=32)
    n = codec.k + codec.m
    payload = bytes(RNG.integers(0, 256, codec.get_alignment() * 2,
                                 dtype=np.uint8))
    encoded = codec.encode(range(n), payload)
    chunk_len = len(encoded[0])
    for erased in itertools.combinations(range(n), 2):
        avail = {i: bytes(encoded[i]) for i in range(n)
                 if i not in erased}
        got_native = codec.decode(range(n), avail, chunk_len)
        monkeypatch.setenv("CEPH_TPU_NATIVE_XSCHED", "0")
        got_host = codec.decode(range(n), dict(avail), chunk_len)
        monkeypatch.delenv("CEPH_TPU_NATIVE_XSCHED")
        for i in range(n):
            assert bytes(got_native[i]) == bytes(encoded[i]), erased
            assert bytes(got_host[i]) == bytes(encoded[i]), erased


# -- the execute() tier seam + kill switch -----------------------------


@needs_native
def test_execute_seam_picks_native_and_counts_it():
    bm = RNG.integers(0, 2, (6, 12), dtype=np.uint8)
    sched = xsched.compile_matrix(bm)
    pk = RNG.integers(0, 256, (1, 12, 64), dtype=np.uint8)
    outs = np.zeros((1, 6, 64), dtype=np.uint8)
    xsched.reset_stats()
    tier = xsched.execute(sched, [pk[:, i, :] for i in range(12)],
                          [outs[:, r, :] for r in range(6)])
    assert tier == "native"
    st = xsched.stats()
    assert st["exec_native"] == 1 and st["exec_host"] == 0
    assert np.array_equal(outs, xsched.naive_xor_matmul(bm, pk))


def test_kill_switch_falls_back_to_host(monkeypatch):
    monkeypatch.setenv("CEPH_TPU_NATIVE_XSCHED", "0")
    assert not xsched.native_enabled()
    assert not xsched.native_available()
    bm = RNG.integers(0, 2, (6, 12), dtype=np.uint8)
    sched = xsched.compile_matrix(bm)
    pk = RNG.integers(0, 256, (1, 12, 64), dtype=np.uint8)
    outs = np.zeros((1, 6, 64), dtype=np.uint8)
    xsched.reset_stats()
    tier = xsched.execute(sched, [pk[:, i, :] for i in range(12)],
                          [outs[:, r, :] for r in range(6)])
    assert tier == "host"
    st = xsched.stats()
    assert st["exec_host"] == 1 and st["exec_native"] == 0
    assert st["native_enabled"] is False
    assert np.array_equal(outs, xsched.naive_xor_matmul(bm, pk))


@needs_native
def test_execute_seam_host_on_ragged_sources():
    """Mixed-size source views cannot share one uniform region arena:
    the seam must quietly take the host tier, same bytes."""
    bm = np.array([[1, 1, 0], [0, 1, 1]], dtype=np.uint8)
    sched = xsched.compile_matrix(bm)
    srcs = [RNG.integers(0, 256, 64, dtype=np.uint8),
            RNG.integers(0, 256, 64, dtype=np.uint8),
            RNG.integers(0, 256, 1, dtype=np.uint8)]  # ragged nbytes
    outs = [np.zeros(64, dtype=np.uint8), np.zeros(64, dtype=np.uint8)]
    tier = xsched.execute(sched, srcs, outs)
    assert tier == "host"
    assert np.array_equal(outs[0], srcs[0] ^ srcs[1])
    assert np.array_equal(outs[1], srcs[1] ^ srcs[2])


# -- the crc-span folding kernel ---------------------------------------


@needs_native
def test_crc_spans_match_direct_folds():
    """crc_regions_native folds (start, count, slot) spans exactly
    like sequential ceph_tpu crc32c over the same bytes — including
    multiple spans accumulating into ONE slot in order (the
    multi-stripe shard ledger)."""
    arena = RNG.integers(0, 256, (3, 5, 64), dtype=np.uint8)
    flat = arena.reshape(-1, 64)
    spans = np.array([
        (0, 2, 0),       # regions 0-1 -> slot 0
        (3, 1, 1),       # region 3 -> slot 1
        (5, 2, 0),       # regions 5-6 APPEND into slot 0
        (14, 1, 2),      # last region -> slot 2
    ], dtype=np.int32)
    crcs = np.full(3, 0xFFFFFFFF, dtype=np.uint32)
    xsched.crc_regions_native(arena, spans, crcs)
    want = [0xFFFFFFFF] * 3
    for start, count, slot in spans.tolist():
        chunk = np.ascontiguousarray(
            flat[start:start + count]).reshape(-1)
        want[slot] = cks.crc32c(want[slot], chunk.data)
    assert crcs.tolist() == want


# -- the packed multi-object encode tier -------------------------------


def _bitmatrix_codec_and_sinfo(k=4, w=8, ps=512):
    codec = _codec("liber8tion", k=k, w=w, packetsize=ps)
    chunk = w * ps
    return codec, ec_util.StripeInfo(k, k * chunk), chunk


@needs_native
def test_packed_multi_object_parity_with_inline():
    """_encode_many_bitmatrix: shards, cumulative per-shard CRC
    ledger, total_chunk_size and logical data crc all byte-identical
    to per-item encode_with_hinfo — ragged per-item stripe counts
    included."""
    codec, sinfo, chunk = _bitmatrix_codec_and_sinfo()
    width = sinfo.get_stripe_width()
    n = codec.k + codec.m
    want = list(range(n))
    items = []
    for stripes in (1, 3, 1, 2, 5, 1):
        d = bytes(RNG.integers(0, 256, stripes * width,
                               dtype=np.uint8))
        items.append((d, want, len(d) - 7))
    packed = ec_util._encode_many_bitmatrix(sinfo, codec, items)
    assert packed is not None
    assert ec_util.bitmatrix_native_available(codec)
    for (shards, hinfo, crc), (d, w_, l) in zip(packed, items):
        ws, wh, wc = ec_util.encode_with_hinfo(sinfo, codec, d, w_,
                                               logical_len=l)
        assert crc == wc
        assert hinfo.total_chunk_size == wh.total_chunk_size
        assert hinfo.cumulative_shard_hashes == \
            wh.cumulative_shard_hashes
        for i in range(n):
            assert bytes(shards[i]) == bytes(ws[i]), i


@needs_native
def test_packed_tier_routes_through_encode_many():
    """encode_many_with_hinfo reaches the packed tier for bitmatrix
    codecs (one exec_native for the whole batch) and matches it."""
    codec, sinfo, chunk = _bitmatrix_codec_and_sinfo()
    width = sinfo.get_stripe_width()
    items = [(bytes(RNG.integers(0, 256, width, dtype=np.uint8)),
              list(range(6)), width) for _ in range(9)]
    xsched.reset_stats()
    outs = ec_util.encode_many_with_hinfo(sinfo, codec, items)
    st = xsched.stats()
    assert st["exec_native"] == 1     # ONE tape run for all 9 objects
    direct = ec_util._encode_many_bitmatrix(sinfo, codec, items)
    for (shards, hinfo, crc), (ds, dh, dc) in zip(outs, direct):
        assert crc == dc
        assert hinfo.cumulative_shard_hashes == \
            dh.cumulative_shard_hashes
        for i in range(6):
            assert bytes(shards[i]) == bytes(ds[i])


@needs_native
def test_packed_tier_refuses_bad_geometry(monkeypatch):
    """Multi-block chunks, unaligned items and the kill switch all
    return None (callers fall back inline, bit-identically)."""
    codec, sinfo, chunk = _bitmatrix_codec_and_sinfo()
    width = sinfo.get_stripe_width()
    good = [(bytes(RNG.integers(0, 256, width, dtype=np.uint8)),
             [0, 1], None)]
    # chunk != w*ps: a 2-block stripe geometry
    big = ec_util.StripeInfo(codec.k, codec.k * chunk * 2)
    assert ec_util._encode_many_bitmatrix(big, codec, [
        (bytes(RNG.integers(0, 256, chunk * 2 * codec.k,
                            dtype=np.uint8)), [0], None)]) is None
    # item not stripe-aligned / empty
    assert ec_util._encode_many_bitmatrix(
        sinfo, codec, [(b"x" * (width - 1), [0], None)]) is None
    assert ec_util._encode_many_bitmatrix(
        sinfo, codec, [(b"", [0], None)]) is None
    # kill switch: gate closes entirely
    monkeypatch.setenv("CEPH_TPU_NATIVE_XSCHED", "0")
    assert not ec_util.bitmatrix_native_available(codec)
    assert ec_util._encode_many_bitmatrix(sinfo, codec, good) is None
    monkeypatch.delenv("CEPH_TPU_NATIVE_XSCHED")
    monkeypatch.setenv("CEPH_TPU_XSCHED", "0")
    assert not ec_util.bitmatrix_native_available(codec)
    monkeypatch.delenv("CEPH_TPU_XSCHED")
    # non-bitmatrix codecs never qualify
    rs = create_erasure_code({"plugin": "ec_jax",
                              "technique": "reed_sol_van", "k": "4",
                              "m": "2", "tpu": "false"})
    assert not ec_util.bitmatrix_native_available(rs)


@needs_native
def test_packed_tier_data_shards_are_views_parity_immutable():
    """Data shards come back as zero-copy strided views of the frozen
    source and parity buffers are read-only — store-adoptable, like
    the datapath tier's contract."""
    codec, sinfo, chunk = _bitmatrix_codec_and_sinfo()
    width = sinfo.get_stripe_width()
    d = bytes(RNG.integers(0, 256, 2 * width, dtype=np.uint8))
    [(shards, hinfo, _)] = ec_util._encode_many_bitmatrix(
        sinfo, codec, [(d, list(range(6)), None)])
    for i in range(4):
        got = bytes(shards[i])
        stripes = np.frombuffer(d, np.uint8).reshape(2, 4, chunk)
        assert got == np.ascontiguousarray(
            stripes[:, i, :]).tobytes()
    for j in (4, 5):
        mv = memoryview(shards[j])
        assert mv.readonly and len(mv) == 2 * chunk

"""ObjectStore tests, mirroring store_test.cc: the same suite runs against
MemStore and TPUStore (parameterized fixture, like the reference's
bluestore/memstore fixture), plus TPUStore-specific persistence, checksum
corruption detection, and compression behavior."""

import os

import numpy as np
import pytest

from ceph_tpu.common.config import Config
from ceph_tpu.kv import MemDB, SQLiteDB
from ceph_tpu.os import ObjectId, Transaction
from ceph_tpu.os.memstore import MemStore
from ceph_tpu.os.tpustore import Allocator, TPUStore

CID = "1.0_head"
OID = ObjectId("obj1")


# -- kv --------------------------------------------------------------------


@pytest.mark.parametrize("make_db", [
    lambda p: MemDB(),
    lambda p: SQLiteDB(os.path.join(p, "kv.db")),
])
def test_kv_basic(tmp_path, make_db):
    db = make_db(str(tmp_path))
    db.create_and_open()
    t = db.get_transaction()
    t.set("P", b"a", b"1")
    t.set("P", b"b", b"2")
    t.set("Q", b"a", b"other")
    db.submit_transaction(t)
    assert db.get("P", b"a") == b"1"
    assert db.get("Q", b"a") == b"other"
    assert db.get("P", b"z") is None
    assert list(db.get_iterator("P")) == [(b"a", b"1"), (b"b", b"2")]
    t2 = db.get_transaction()
    t2.rmkey("P", b"a")
    t2.rm_range_keys("P", b"b", b"c")
    db.submit_transaction(t2)
    assert list(db.get_iterator("P")) == []
    assert db.get("Q", b"a") == b"other"
    db.close()


def test_sqlite_persistence(tmp_path):
    path = os.path.join(str(tmp_path), "kv.db")
    db = SQLiteDB(path)
    db.create_and_open()
    t = db.get_transaction()
    t.set("P", b"k", b"v")
    db.submit_transaction(t)
    db.close()
    db2 = SQLiteDB(path)
    db2.create_and_open()
    assert db2.get("P", b"k") == b"v"
    db2.close()


# -- allocator -------------------------------------------------------------


def test_allocator_first_fit_and_merge():
    a = Allocator()
    o1 = a.allocate(100)
    o2 = a.allocate(50)
    assert (o1, o2) == (0, 100)
    a.release(o1, 100)
    assert a.allocate(40) == 0      # reuses the freed hole
    a.release(0, 40)
    assert a.free == [(0, 100)]     # adjacent frees merged back
    assert a.allocate(100) == 0


# -- parameterized store suite (store_test.cc shape) -----------------------


@pytest.fixture(params=["memstore", "tpustore"])
def store(request, tmp_path):
    if request.param == "memstore":
        s = MemStore()
        s.mkfs()
        s.mount()
    else:
        s = TPUStore(str(tmp_path / "store"))
        s.mkfs()
        s.mount()
    t = Transaction()
    t.create_collection(CID)
    s.queue_transaction(t)
    yield s
    s.umount()


def _write(store, oid, offset, data, cid=CID):
    t = Transaction()
    t.write(cid, oid, offset, len(data), data)
    store.queue_transaction(t)


def test_write_read_round_trip(store):
    data = np.random.default_rng(0).integers(
        0, 256, 200_000, dtype=np.uint8).tobytes()
    _write(store, OID, 0, data)
    assert store.read(CID, OID) == data
    assert store.stat(CID, OID)["size"] == len(data)
    assert store.read(CID, OID, 1000, 500) == data[1000:1500]
    assert store.read(CID, OID, len(data) - 10, 100) == data[-10:]


def test_overwrite_and_extend(store):
    _write(store, OID, 0, b"a" * 1000)
    _write(store, OID, 500, b"b" * 1000)      # overlap + extend
    out = store.read(CID, OID)
    assert out == b"a" * 500 + b"b" * 1000
    _write(store, OID, 100_000, b"far")       # sparse write
    out = store.read(CID, OID)
    assert len(out) == 100_003
    assert out[1500:100_000] == bytes(98_500)  # hole reads as zeros
    assert out.endswith(b"far")


def test_zero_truncate(store):
    _write(store, OID, 0, b"x" * 10_000)
    t = Transaction()
    t.zero(CID, OID, 1000, 2000)
    t.truncate(CID, OID, 5000)
    store.queue_transaction(t)
    out = store.read(CID, OID)
    assert len(out) == 5000
    assert out[:1000] == b"x" * 1000
    assert out[1000:3000] == bytes(2000)
    assert out[3000:] == b"x" * 2000


def test_touch_remove_exists(store):
    t = Transaction()
    t.touch(CID, OID)
    store.queue_transaction(t)
    assert store.exists(CID, OID)
    assert store.stat(CID, OID)["size"] == 0
    t = Transaction()
    t.remove(CID, OID)
    store.queue_transaction(t)
    assert not store.exists(CID, OID)
    with pytest.raises(KeyError):
        store.read(CID, OID)


def test_xattrs(store):
    t = Transaction()
    t.touch(CID, OID)
    t.setattr(CID, OID, "_", b"object_info")
    t.setattrs(CID, OID, {"snapset": b"\x01\x02", "hinfo_key": b"{}"})
    store.queue_transaction(t)
    assert store.getattr(CID, OID, "_") == b"object_info"
    attrs = store.getattrs(CID, OID)
    assert set(attrs) == {"_", "snapset", "hinfo_key"}
    t = Transaction()
    t.rmattr(CID, OID, "snapset")
    store.queue_transaction(t)
    assert "snapset" not in store.getattrs(CID, OID)


def test_omap(store):
    t = Transaction()
    t.touch(CID, OID)
    t.omap_setheader(CID, OID, b"hdr")
    t.omap_setkeys(CID, OID, {"k1": b"v1", "k2": b"v2", "k3": b"v3"})
    store.queue_transaction(t)
    assert store.omap_get(CID, OID) == {"k1": b"v1", "k2": b"v2",
                                        "k3": b"v3"}
    assert store.omap_get_header(CID, OID) == b"hdr"
    t = Transaction()
    t.omap_rmkeys(CID, OID, ["k2"])
    store.queue_transaction(t)
    assert set(store.omap_get(CID, OID)) == {"k1", "k3"}
    t = Transaction()
    t.omap_clear(CID, OID)
    store.queue_transaction(t)
    assert store.omap_get(CID, OID) == {}


def test_clone(store):
    _write(store, OID, 0, b"payload" * 100)
    t = Transaction()
    t.setattr(CID, OID, "a", b"1")
    t.omap_setkeys(CID, OID, {"ok": b"ov"})
    store.queue_transaction(t)
    dst = ObjectId("obj1", snap=4)
    t = Transaction()
    t.clone(CID, OID, dst)
    store.queue_transaction(t)
    assert store.read(CID, dst) == b"payload" * 100
    assert store.getattr(CID, dst, "a") == b"1"
    assert store.omap_get(CID, dst) == {"ok": b"ov"}
    # diverge the clone; the original is untouched
    _write(store, dst, 0, b"CHANGED")
    assert store.read(CID, OID)[:7] == b"payload"


def test_collection_move_rename(store):
    cid2 = "1.1_head"
    t = Transaction()
    t.create_collection(cid2)
    store.queue_transaction(t)
    _write(store, OID, 0, b"moving")
    t = Transaction()
    t.omap_setkeys(CID, OID, {"k": b"v"})
    store.queue_transaction(t)
    dst = ObjectId("obj1_renamed")
    t = Transaction()
    t.collection_move_rename(CID, OID, cid2, dst)
    store.queue_transaction(t)
    assert not store.exists(CID, OID)
    assert store.read(cid2, dst) == b"moving"
    assert store.omap_get(cid2, dst) == {"k": b"v"}


def test_list_objects_and_collections(store):
    assert CID in store.list_collections()
    for i in range(5):
        _write(store, ObjectId(f"o{i}"), 0, b"d")
    names = [str(o) for o in store.list_objects(CID)]
    assert names == [f"o{i}" for i in range(5)]


def test_on_commit_callback(store):
    fired = []
    t = Transaction()
    t.touch(CID, OID)
    t.register_on_commit(lambda: fired.append(1))
    store.queue_transaction(t)
    assert fired == [1]


def test_statfs(store):
    _write(store, OID, 0, np.random.default_rng(1).integers(
        0, 256, 100_000, dtype=np.uint8).tobytes())
    fs = store.statfs()
    assert fs["allocated"] > 0


# -- TPUStore specifics ----------------------------------------------------


def test_tpustore_remount_persistence(tmp_path):
    path = str(tmp_path / "store")
    s = TPUStore(path)
    s.mkfs()
    s.mount()
    t = Transaction()
    t.create_collection(CID)
    s.queue_transaction(t)
    data = np.random.default_rng(2).integers(
        0, 256, 300_000, dtype=np.uint8).tobytes()
    _write(s, OID, 0, data)
    t = Transaction()
    t.setattr(CID, OID, "hinfo_key", b"ledger")
    t.omap_setkeys(CID, OID, {"pk": b"pv"})
    s.queue_transaction(t)
    alloc_before = s.statfs()["allocated"]
    fsid = s.fsid
    assert fsid
    s.umount()

    s2 = TPUStore(path)
    s2.mount()
    assert s2.fsid == fsid  # the same disk presents the same identity
    assert s2.read(CID, OID) == data
    assert s2.getattr(CID, OID, "hinfo_key") == b"ledger"
    assert s2.omap_get(CID, OID) == {"pk": b"pv"}
    assert s2.statfs()["allocated"] == alloc_before
    # COW overwrite reuses freed extents rather than leaking
    _write(s2, OID, 0, data)
    _write(s2, OID, 0, data)
    assert s2.statfs()["allocated"] <= alloc_before + s2.max_blob_size
    s2.umount()


def test_tpustore_detects_bitrot(tmp_path):
    """_verify_csum: a flipped bit on the device fails the read."""
    path = str(tmp_path / "store")
    s = TPUStore(path)
    s.mkfs()
    s.mount()
    t = Transaction()
    t.create_collection(CID)
    s.queue_transaction(t)
    data = np.random.default_rng(3).integers(
        0, 256, 50_000, dtype=np.uint8).tobytes()
    _write(s, OID, 0, data)
    s.umount()
    # corrupt one byte in the block file
    with open(os.path.join(path, "block"), "r+b") as f:
        f.seek(12345)
        b = f.read(1)
        f.seek(12345)
        f.write(bytes([b[0] ^ 0x40]))
    s2 = TPUStore(path)
    s2.mount()
    with pytest.raises(IOError):
        s2.read(CID, OID)
    s2.umount()


def test_tpustore_compression(tmp_path):
    cfg = Config()
    cfg.set_val("bluestore_compression_mode", "aggressive")
    cfg.set_val("bluestore_compression_algorithm", "lz4")
    s = TPUStore(str(tmp_path / "store"), config=cfg)
    s.mkfs()
    s.mount()
    t = Transaction()
    t.create_collection(CID)
    s.queue_transaction(t)
    compressible = (b"the quick brown fox " * 20_000)  # 400 KB
    _write(s, OID, 0, compressible)
    assert s.read(CID, OID) == compressible
    fs = s.statfs()
    assert fs["allocated"] < len(compressible) // 2   # actually compressed
    # incompressible data is stored raw (ratio gate)
    rnd = np.random.default_rng(4).integers(
        0, 256, 200_000, dtype=np.uint8).tobytes()
    _write(s, ObjectId("rand"), 0, rnd)
    assert s.read(CID, ObjectId("rand")) == rnd
    s.umount()


def test_tpustore_csum_disabled(tmp_path):
    cfg = Config()
    cfg.set_val("bluestore_csum_type", "none")
    s = TPUStore(str(tmp_path / "store"), config=cfg)
    s.mkfs()
    s.mount()
    t = Transaction()
    t.create_collection(CID)
    s.queue_transaction(t)
    _write(s, OID, 0, b"no csums")
    assert s.read(CID, OID) == b"no csums"
    s.umount()


def test_tpustore_requires_collection(tmp_path):
    s = TPUStore(str(tmp_path / "store"))
    s.mkfs()
    s.mount()
    with pytest.raises(KeyError):
        _write(s, OID, 0, b"x", cid="nonexistent")
    s.umount()


def test_tpustore_failed_txn_leaves_store_intact(tmp_path):
    """A transaction failing mid-apply must not corrupt the allocator or
    commit partial state (review finding: released extents of live data)."""
    s = TPUStore(str(tmp_path / "store"))
    s.mkfs()
    s.mount()
    t = Transaction()
    t.create_collection(CID)
    s.queue_transaction(t)
    data = b"live data " * 5000
    _write(s, OID, 0, data)
    free_before = list(s._alloc.free)
    # txn: overwrite OID (releases its extent) then fail on a missing object
    t = Transaction()
    t.write(CID, OID, 0, 9, b"newdata!!")
    t.rmattr(CID, ObjectId("missing"), "x")
    with pytest.raises(KeyError):
        s.queue_transaction(t)
    # old data still intact, allocator restored, later writes safe
    assert s._alloc.free == free_before
    assert s.read(CID, OID) == data
    _write(s, ObjectId("other"), 0, b"z" * 100_000)
    assert s.read(CID, OID) == data
    s.umount()


def test_tpustore_mkcoll_and_write_one_txn(tmp_path):
    """create_collection + write in one transaction (no mid-txn commit)."""
    s = TPUStore(str(tmp_path / "store"))
    s.mkfs()
    s.mount()
    t = Transaction()
    t.create_collection("c2")
    t.write("c2", OID, 0, 5, b"hello")
    s.queue_transaction(t)
    assert s.read("c2", OID) == b"hello"
    s.umount()


def test_tpustore_csum_config_change_keeps_data_readable(tmp_path):
    """Blobs carry their csum params; switching bluestore_csum_type must not
    invalidate existing data (review finding)."""
    path = str(tmp_path / "store")
    s = TPUStore(path)
    s.mkfs()
    s.mount()
    t = Transaction()
    t.create_collection(CID)
    s.queue_transaction(t)
    _write(s, OID, 0, b"written with crc32c" * 100)
    s.umount()
    cfg = Config()
    cfg.set_val("bluestore_csum_type", "xxhash64")
    s2 = TPUStore(path, config=cfg)
    s2.mount()
    assert s2.read(CID, OID) == b"written with crc32c" * 100
    s2.umount()


def test_tpustore_deferred_release_within_txn(tmp_path):
    """Extents freed by one op must NOT be reusable by a later op in the
    same transaction (advisor high finding; sizes above
    prefer_deferred_size so the COW path — the one with extent
    churn — is what's exercised): a txn that rewrites A, writes
    B (first-fit would reuse A's freed extent), then fails must leave
    committed A readable after the abort — and the same early-release
    crash window must not exist on the success path either."""
    s = TPUStore(str(tmp_path / "store"))
    s.mkfs()
    s.mount()
    t = Transaction()
    t.create_collection(CID)
    s.queue_transaction(t)
    data_a = b"A" * 40_000
    _write(s, OID, 0, data_a)
    a_off = s._get_onode(CID, OID).blobs[0].offset

    # failing txn: rewrite A (frees its extent), write B (same size —
    # first-fit would grab A's extent if released early), then fail
    t = Transaction()
    t.write(CID, OID, 0, len(data_a), b"a" * 40_000)
    t.write(CID, ObjectId("B"), 0, 40_000, b"B" * 40_000)
    t.rmattr(CID, ObjectId("missing"), "x")
    with pytest.raises(KeyError):
        s.queue_transaction(t)
    assert s.read(CID, OID) == data_a          # A survives the abort
    with pytest.raises(KeyError):
        s.read(CID, ObjectId("B"))

    # success path: same shape without the failure — B must not have been
    # written over A's old extent before the commit point
    t = Transaction()
    t.write(CID, OID, 0, len(data_a), b"a" * 40_000)
    t.write(CID, ObjectId("B"), 0, 40_000, b"B" * 40_000)
    s.queue_transaction(t)
    assert s.read(CID, OID) == b"a" * 40_000
    assert s.read(CID, ObjectId("B")) == b"B" * 40_000
    b_off = s._get_onode(CID, ObjectId("B")).blobs[0].offset
    assert b_off != a_off
    # after commit the freed extent IS reusable
    t = Transaction()
    t.write(CID, ObjectId("C"), 0, 40_000, b"C" * 40_000)
    s.queue_transaction(t)
    assert s._get_onode(CID, ObjectId("C")).blobs[0].offset == a_off
    s.umount()


def test_tpustore_remove_defers_release(tmp_path):
    """_object_remove frees extents only after the KV commit: a remove+write
    txn that fails must leave the removed object fully readable."""
    s = TPUStore(str(tmp_path / "store"))
    s.mkfs()
    s.mount()
    t = Transaction()
    t.create_collection(CID)
    s.queue_transaction(t)
    data = b"keep me " * 4000
    _write(s, OID, 0, data)
    t = Transaction()
    t.remove(CID, OID)
    t.write(CID, ObjectId("B"), 0, len(data), b"B" * len(data))
    t.rmattr(CID, ObjectId("missing"), "x")
    with pytest.raises(KeyError):
        s.queue_transaction(t)
    assert s.read(CID, OID) == data
    s.umount()


def test_tpustore_deferred_write_wal(tmp_path):
    """Small overwrites take the deferred path: journaled in the KV
    batch, applied in place after commit, REPLAYED on mount if the
    block file never caught up (BlueStore _deferred_replay)."""
    s = TPUStore(str(tmp_path / "store"))
    s.mkfs()
    s.mount()
    t = Transaction()
    t.create_collection(CID)
    s.queue_transaction(t)
    _write(s, OID, 0, b"x" * 8000)
    base_off = s._get_onode(CID, OID).blobs[0].offset

    # small overwrite: same extent (in-place), journal entry present
    _write(s, OID, 1000, b"Y" * 500)
    assert s._get_onode(CID, OID).blobs[0].offset == base_off
    got = s.read(CID, OID)
    assert got[1000:1500] == b"Y" * 500 and got[:1000] == b"x" * 1000

    # crash before the lazy block flush: nuke the block file's new
    # bytes by restoring pre-overwrite content, then remount — the
    # journal must replay the overwrite
    s._block.flush()
    import os

    with open(s._block_path, "r+b") as f:
        f.seek(base_off)
        f.write(b"x" * 8000)  # simulate lost in-place write
    s._kv.close()
    s._block.close()
    s._mounted = False
    s2 = TPUStore(str(tmp_path / "store"))
    s2.mount()
    got = s2.read(CID, OID)
    assert got[1000:1500] == b"Y" * 500, "WAL replay lost the write"
    # replay trims the journal
    assert list(s2._kv.get_iterator("D")) == []
    s2.umount()


def test_tpustore_deferred_batch_trim(tmp_path):
    s = TPUStore(str(tmp_path / "store"))
    s.deferred_batch = 4
    s.mkfs()
    s.mount()
    t = Transaction()
    t.create_collection(CID)
    s.queue_transaction(t)
    _write(s, OID, 0, b"x" * 4000)
    for i in range(6):
        _write(s, OID, 100 * i, bytes([i]) * 50)
    # after 4+ deferred commits the batch flushed: <= 2 entries remain
    assert len(list(s._kv.get_iterator("D"))) <= 2
    assert len(s._pending_defer) <= 2
    out = s.read(CID, OID)
    for i in range(6):
        assert out[100 * i:100 * i + 50] == bytes([i]) * 50, i
    s.umount()
    # umount flushed everything
    s3 = TPUStore(str(tmp_path / "store"))
    s3.mount()
    assert list(s3._kv.get_iterator("D")) == []
    s3.umount()

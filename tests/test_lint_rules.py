"""Static-analyzer rule tier: every rule fires exactly on its seeded
fixture violation, stays silent on the clean twin, and the
suppression/baseline machinery suppresses what it claims to.

Fixtures live in tests/lint_fixtures/ and are parsed, never imported;
`# expect: <rule>` on a line declares that exactly that (rule, line)
finding must be produced.
"""

from __future__ import annotations

import os
import re

import pytest

from ceph_tpu.analysis import analyze_paths, load_baseline, write_baseline

FIXDIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "lint_fixtures")
EXPECT_RE = re.compile(r"#\s*expect:\s*([\w-]+)")

RULES = [
    "trace-side-effect",
    "trace-host-sync",
    "uint8-overflow",
    "trace-static-hazard",
    "trace-numpy",
    "jit-bypass-plan",
    "unguarded-device-dispatch",
    "unplanned-mesh-dispatch",
    "unplanned-compute-dispatch",
    "unscheduled-bitmatrix-xor",
    "raw-process-group",
    "unhedged-gather",
    "span-leak",
    "unbounded-latency-buffer",
    "unbudgeted-approx-result",
    "commit-before-durability",
    "unregistered-kill-switch",
    "async-blocking",
    "sync-encode-in-async",
    "lock-order",
    "lock-no-await",
    "await-atomicity",
    "cancellation-unsafe-acquire",
    "transitive-blocking-call",
    "hot-path-copy",
    "divergent-collective",
    "collective-order",
    "unguarded-collective-timeout",
    "topology-stale-state",
    "unused-suppression",
]

# the dtype, plan, and encode rules are path-scoped to their
# production modules; point them at their fixture families here
CONFIG = {"dtype_paths": ("fx_uint8",),
          "plan_paths": ("fx_jit_bypass_plan",),
          "encode_paths": ("fx_sync_encode_in_async",),
          "device_paths": ("fx_unguarded_device_dispatch",),
          "mesh_paths": ("fx_unplanned_mesh_dispatch",),
          "compute_paths": ("fx_unplanned_compute_dispatch",),
          "gather_paths": ("fx_unhedged_gather",),
          "latency_paths": ("fx_unbounded_latency_buffer",),
          "approx_paths": ("fx_unbudgeted_approx_result",),
          "durability_paths": ("fx_commit_before_durability",),
          "atomicity_paths": ("fx_await_atomicity",),
          "cancel_paths": ("fx_cancellation_unsafe_acquire",),
          "transitive_paths": ("fx_transitive_blocking_call",),
          "hot_paths": ("fx_hot_path_copy",),
          "xsched_paths": ("fx_unscheduled_bitmatrix_xor",),
          "spmd_paths": ("fx_divergent_collective",
                         "fx_collective_order"),
          "spmd_seam_paths": ("fx_unguarded_collective_timeout",),
          "spmd_state_paths": ("fx_topology_stale_state",)}


def _fixture(name: str) -> str:
    path = os.path.join(FIXDIR, name)
    assert os.path.exists(path), f"missing fixture {path}"
    return path


def _expected(path: str) -> set:
    out = set()
    with open(path) as fh:
        for i, line in enumerate(fh, 1):
            m = EXPECT_RE.search(line)
            if m:
                out.add((m.group(1), i))
    return out


def _findings(path: str) -> set:
    findings, _ = analyze_paths([path], config=CONFIG)
    return {(f.rule, f.line) for f in findings}


@pytest.mark.parametrize("rule", RULES)
def test_rule_fires_exactly_on_seeded_violation(rule):
    bad = _fixture(f"fx_{rule.replace('-', '_')}_bad.py")
    expected = _expected(bad)
    assert expected, f"{bad} declares no `# expect:` markers"
    assert _findings(bad) == expected


@pytest.mark.parametrize("rule", RULES)
def test_rule_silent_on_clean_twin(rule):
    ok = _fixture(f"fx_{rule.replace('-', '_')}_ok.py")
    assert _findings(ok) == set()


def test_inline_and_file_suppressions_silence_findings():
    assert _findings(_fixture("fx_suppressed.py")) == set()


def test_baseline_suppresses_old_but_not_new_findings(tmp_path):
    bad = _fixture("fx_async_blocking_bad.py")
    findings, _ = analyze_paths([bad])
    assert findings
    bl_path = tmp_path / "baseline.json"
    write_baseline(str(bl_path), findings)
    baseline = load_baseline(str(bl_path))
    assert all(f in baseline for f in findings)
    fresh, _ = analyze_paths([_fixture("fx_trace_numpy_bad.py")])
    assert fresh
    assert all(f not in baseline for f in fresh)
    assert not baseline.stale(findings)
    assert baseline.stale(fresh)  # none of the old entries are live


def test_suppressions_in_strings_are_inert(tmp_path):
    """Only real comment tokens may suppress — a docstring or error
    message *describing* the `# lint: disable=` syntax must not
    disable rules for the file."""
    src = tmp_path / "doc.py"
    src.write_text(
        '"""Silence with # lint: disable-file=async-blocking."""\n'
        "import time\n"
        'MSG = "add # lint: disable=async-blocking to silence"\n'
        "async def f():\n"
        "    time.sleep(1)\n"
        "async def g():\n"
        "    time.sleep(1)  # lint: disable=async-blocking\n")
    from ceph_tpu.analysis.core import parse_module
    mod = parse_module(str(src))
    assert mod.file_suppress == set()
    assert list(mod.suppress) == [7]      # only the real comment
    assert _findings(str(src)) == {("async-blocking", 5)}


def test_relative_imports_anchor_like_python(tmp_path):
    """`from .sub import f` in pkg/__init__.py must resolve to
    pkg.sub (Python anchors level 1 at the package itself there, at
    the parent package for a plain module) — a mis-anchored import
    table silently drops cross-module traced-set and lock edges."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text(
        "from .sub import helper\nfrom . import sub\n")
    (pkg / "sub.py").write_text(
        "from .other import thing\ndef helper():\n    pass\n")
    (pkg / "other.py").write_text("def thing():\n    pass\n")
    from ceph_tpu.analysis.core import build_project
    proj = build_project([str(pkg)])
    init = proj.modules["pkg"]
    assert init.imports["helper"] == ("pkg.sub", "helper")
    assert init.imports["sub"] == ("pkg.sub", None)
    assert proj.modules["pkg.sub"].imports["thing"] == \
        ("pkg.other", "thing")


def test_fingerprint_survives_line_drift(tmp_path):
    """The baseline keys on (rule, file, symbol, line text), not line
    numbers — unrelated edits above a finding must not un-baseline it."""
    bad = _fixture("fx_trace_numpy_bad.py")
    before, _ = analyze_paths([bad])
    shifted = tmp_path / os.path.basename(bad)
    with open(bad) as fh:
        shifted.write_text("# padding line\n# padding line\n" + fh.read())
    after, _ = analyze_paths([str(shifted)])
    assert {f.fingerprint for f in before} == \
        {f.fingerprint for f in after}
    assert sorted(f.line for f in after) != sorted(f.line for f in before)

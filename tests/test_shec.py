"""SHEC plugin tests, mirroring the reference's TestErasureCodeShec*.cc:
parameter validation, exhaustive erasure sweeps up to c failures, reduced
recovery-read property, decode-table cache."""

import itertools

import numpy as np
import pytest

from ceph_tpu.ec.interface import ErasureCodeError
from ceph_tpu.ec.registry import create_erasure_code
from ceph_tpu.ec.shec import recovery_efficiency1, shec_matrix


def make(k=4, m=3, c=2, technique=None):
    profile = {"plugin": "shec", "k": str(k), "m": str(m), "c": str(c)}
    if technique:
        profile["technique"] = technique
    return create_erasure_code(profile)


def payload(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


def test_defaults():
    shec = create_erasure_code({"plugin": "shec"})
    assert (shec.k, shec.m, shec.c) == (4, 3, 2)
    assert shec.get_chunk_count() == 7
    assert shec.get_profile()["technique"] == "multiple"


def test_validation():
    for bad in (
        {"k": "4", "m": "3"},                       # partial kmc
        {"k": "0", "m": "3", "c": "2"},             # k <= 0
        {"k": "4", "m": "0", "c": "2"},             # m <= 0
        {"k": "4", "m": "3", "c": "0"},             # c <= 0
        {"k": "4", "m": "3", "c": "4"},             # c > m
        {"k": "13", "m": "3", "c": "2"},            # k > 12
        {"k": "12", "m": "12", "c": "2"},           # k+m > 20 (m > k too)
        {"k": "3", "m": "4", "c": "2"},             # m > k
        {"k": "x", "m": "3", "c": "2"},             # not an int
    ):
        with pytest.raises(ErasureCodeError):
            create_erasure_code({"plugin": "shec", **bad})
    with pytest.raises(ErasureCodeError):
        make(technique="bogus")


def test_matrix_is_shingled():
    """Each parity row covers a strict subset of data columns; every data
    column is covered by at least one parity."""
    mat = shec_matrix(6, 4, 2, "multiple")
    assert mat.shape == (4, 6)
    nonzero_cols = [set(np.nonzero(mat[r])[0]) for r in range(4)]
    assert any(len(s) < 6 for s in nonzero_cols)  # shingling happened
    covered = set().union(*nonzero_cols)
    assert covered == set(range(6))


def test_single_vs_multiple_matrices_differ():
    ms = shec_matrix(6, 4, 2, "single")
    mm = shec_matrix(6, 4, 2, "multiple")
    assert ms.shape == mm.shape == (4, 6)
    assert not np.array_equal(ms, mm)


def test_recovery_efficiency_sane():
    r = recovery_efficiency1(6, 2, 2, 1, 1)
    assert r > 0
    assert recovery_efficiency1(6, 0, 2, 1, 1) == -1.0  # invalid split


@pytest.mark.parametrize("technique", ["single", "multiple"])
@pytest.mark.parametrize("kmc", [(4, 3, 2), (6, 4, 2), (8, 4, 3), (10, 5, 2)])
def test_exhaustive_erasures_up_to_c(kmc, technique):
    """Any pattern of <= c erasures must decode bit-exactly (the SHEC
    durability contract; reference TestErasureCodeShec_all sweeps)."""
    k, m, c = kmc
    shec = make(k, m, c, technique)
    n = k + m
    data = payload(k * 256, seed=k * 100 + m)
    full = shec.encode(range(n), data)
    assert len(full) == n
    for r in range(1, c + 1):
        for erased in itertools.combinations(range(n), r):
            avail = {i: ch for i, ch in full.items() if i not in erased}
            out = shec.decode(set(erased), avail)
            for i in erased:
                assert out[i] == full[i], (kmc, technique, erased)


def test_decode_concat_round_trip():
    shec = make()
    data = payload(10_000, seed=5)
    full = shec.encode(range(7), data)
    assert shec.decode_concat(full)[:len(data)] == data
    # with erasures
    avail = {i: ch for i, ch in full.items() if i not in (1, 5)}
    assert shec.decode_concat(avail)[:len(data)] == data


def test_minimum_to_decode_reduced_reads():
    """The SHEC selling point: recovering one data chunk reads fewer than k
    chunks (a shingle's width), unlike plain RS."""
    shec = make(8, 4, 3)
    n = 12
    want = {2}
    minimum = shec.minimum_to_decode(want, set(range(n)) - want)
    assert len(minimum) < 8, sorted(minimum)
    # and it actually decodes using just that set
    data = payload(8 * 512, seed=7)
    full = shec.encode(range(n), data)
    avail = {i: full[i] for i in minimum}
    out = shec.decode(want, avail)
    assert out[2] == full[2]


def test_minimum_to_decode_no_erasure():
    shec = make()
    m = shec.minimum_to_decode({0, 3}, set(range(7)))
    assert set(m) == {0, 3}


def test_unrecoverable_pattern():
    """More erasures than any parity subset can solve -> EIO."""
    shec = make(4, 3, 2)
    data = payload(2048)
    full = shec.encode(range(7), data)
    # erase all parities plus a data chunk: nothing can recover chunk 0
    erased = {0, 4, 5, 6}
    avail = {i: ch for i, ch in full.items() if i not in erased}
    with pytest.raises(ErasureCodeError):
        shec.decode({0}, avail)
    with pytest.raises(ErasureCodeError):
        shec.minimum_to_decode({0}, set(avail))


def test_missing_parity_reencoded():
    """A wanted missing parity chunk is recomputed from its data window."""
    shec = make()
    data = payload(4096, seed=3)
    full = shec.encode(range(7), data)
    avail = {i: ch for i, ch in full.items() if i != 5}
    out = shec.decode({5}, avail)
    assert out[5] == full[5]


def test_decode_cache_reused():
    shec = make()
    data = payload(1024)
    full = shec.encode(range(7), data)
    avail = {i: ch for i, ch in full.items() if i != 2}
    shec.decode({2}, avail)
    hits_before = len(shec._decode_cache)
    shec.decode({2}, avail)
    assert len(shec._decode_cache) == hits_before  # same signature, cached


def test_chunk_size_alignment():
    shec = make(4, 3, 2)
    # alignment k*w*4 = 128; chunk = padded/k
    assert shec.get_chunk_size(1) == 32
    assert shec.get_chunk_size(4 * 32) == 32
    assert shec.get_chunk_size(4 * 32 + 1) == 64

"""cephfs-mirror: snapshot-based directory replication between two
independent clusters (the PeerReplayer role,
/root/reference/src/tools/cephfs_mirror/).

1. first snapshot bootstraps a full tree copy; the remote gets the
   same-named snapshot;
2. later snapshots replicate INCREMENTALLY (unchanged files are not
   re-copied — asserted via the copy counter);
3. renames/deletes/type-changes converge; remote snapshot views match
   the source's view-by-view;
4. source snapshot deletion propagates to the remote;
5. continuous mode tails new snapshots.
"""

import asyncio

from cluster_helpers import Cluster

from ceph_tpu.cephfs import CephFS
from ceph_tpu.cephfs.mirror import DirMirror
from ceph_tpu.mds import MDSDaemon
from ceph_tpu.rados.client import RadosClient


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 180))


async def _one_fs(tag: str):
    cluster = Cluster(num_osds=3)
    await cluster.start()
    await cluster.client.create_replicated_pool("m", size=2, pg_num=4)
    await cluster.client.create_replicated_pool("d", size=2, pg_num=4)
    mds = MDSDaemon(cluster.mon.addr, "m", "d", name=tag,
                    lock_interval=0.3)
    await mds.start()
    rc = RadosClient(cluster.mon.addr, name=f"client.{tag}")
    await rc.connect()
    fs = CephFS(rc, "m", "d")
    return cluster, mds, rc, fs


async def _pair():
    return await _one_fs("srcfs"), await _one_fs("dstfs")


async def _teardown(*stacks):
    for cluster, mds, rc, _fs in stacks:
        await mds.stop()
        await rc.shutdown()
        await cluster.stop()


def test_bootstrap_and_incremental_sync():
    async def main():
        s_stack, d_stack = await _pair()
        src, dst = s_stack[3], d_stack[3]
        try:
            await src.mkdir("/data")
            await src.mkdir("/data/sub")
            await src.write_file("/data/a", b"alpha")
            await src.write_file("/data/sub/b", b"beta-bytes")
            await src.symlink("a", "/data/lnk")
            await src.mksnap("/data", "s1")
            mirror = DirMirror(src, dst, "/data")
            assert await mirror.sync_once() == 1
            # remote head AND remote snapshot both match
            assert await dst.read_file("/data/a") == b"alpha"
            assert await dst.read_file("/data/sub/b") == b"beta-bytes"
            assert await dst.readlink("/data/lnk") == "a"
            assert await dst.read_file("/data/.snap/s1/a") == b"alpha"
            copied_after_s1 = mirror.files_copied
            assert copied_after_s1 == 2  # a, sub/b (symlink isn't a copy)

            # incremental: touch ONE file, add one, delete one
            await src.write_file("/data/a", b"alpha-v2!")
            await src.write_file("/data/new", b"fresh")
            await src.unlink("/data/sub/b")
            await src.mksnap("/data", "s2")
            assert await mirror.sync_once() == 1
            assert await dst.read_file("/data/a") == b"alpha-v2!"
            assert await dst.read_file("/data/new") == b"fresh"
            assert await dst.listdir("/data/sub") == []
            # only the two changed files moved
            assert mirror.files_copied == copied_after_s1 + 2
            # both snapshot views preserved remotely
            assert await dst.read_file("/data/.snap/s1/a") == b"alpha"
            assert await dst.read_file("/data/.snap/s1/sub/b") == \
                b"beta-bytes"
            assert await dst.read_file("/data/.snap/s2/a") == \
                b"alpha-v2!"
            assert sorted(await dst.listdir("/data/.snap")) == \
                ["s1", "s2"]
            # nothing new: idempotent
            assert await mirror.sync_once() == 0
        finally:
            await _teardown(s_stack, d_stack)
    run(main())


def test_snapshot_deletion_propagates():
    async def main():
        s_stack, d_stack = await _pair()
        src, dst = s_stack[3], d_stack[3]
        try:
            await src.mkdir("/p")
            await src.write_file("/p/f", b"one")
            await src.mksnap("/p", "old")
            await src.write_file("/p/f", b"two")
            await src.mksnap("/p", "keep")
            mirror = DirMirror(src, dst, "/p")
            await mirror.sync_once()
            assert sorted(await dst.listdir("/p/.snap")) == \
                ["keep", "old"]
            await src.rmsnap("/p", "old")
            await mirror.sync_once()
            assert await dst.listdir("/p/.snap") == ["keep"]
            assert await dst.read_file("/p/.snap/keep/f") == b"two"
        finally:
            await _teardown(s_stack, d_stack)
    run(main())


def test_type_change_and_dir_replacement():
    async def main():
        s_stack, d_stack = await _pair()
        src, dst = s_stack[3], d_stack[3]
        try:
            await src.mkdir("/t")
            await src.write_file("/t/x", b"file-then-dir")
            await src.mksnap("/t", "s1")
            mirror = DirMirror(src, dst, "/t")
            await mirror.sync_once()
            # x becomes a directory with content
            await src.unlink("/t/x")
            await src.mkdir("/t/x")
            await src.write_file("/t/x/inner", b"nested")
            await src.mksnap("/t", "s2")
            await mirror.sync_once()
            assert await dst.read_file("/t/x/inner") == b"nested"
            assert (await dst.stat("/t/x"))["type"] == "dir"
            assert await dst.read_file("/t/.snap/s1/x") == \
                b"file-then-dir"
        finally:
            await _teardown(s_stack, d_stack)
    run(main())


def test_recreated_same_name_snapshot_resyncs():
    """A snapshot deleted and re-created under the same name between
    passes must be detected by SOURCE snapid and re-synced — name
    alone is not identity."""
    async def main():
        s_stack, d_stack = await _pair()
        src, dst = s_stack[3], d_stack[3]
        try:
            await src.mkdir("/w")
            await src.write_file("/w/f", b"first-cut")
            await src.mksnap("/w", "daily")
            mirror = DirMirror(src, dst, "/w")
            await mirror.sync_once()
            assert await dst.read_file("/w/.snap/daily/f") == \
                b"first-cut"
            # recreate under the same name with different content
            await src.rmsnap("/w", "daily")
            await src.write_file("/w/f", b"second-cut!")
            await src.mksnap("/w", "daily")
            await mirror.sync_once()
            assert await dst.read_file("/w/.snap/daily/f") == \
                b"second-cut!"
        finally:
            await _teardown(s_stack, d_stack)
    run(main())


def test_continuous_mode_tails_snapshots():
    async def main():
        s_stack, d_stack = await _pair()
        src, dst = s_stack[3], d_stack[3]
        try:
            await src.mkdir("/live")
            await src.write_file("/live/f", b"gen1")
            await src.mksnap("/live", "g1")
            mirror = DirMirror(src, dst, "/live")
            await mirror.start(interval=0.2)
            try:
                for _ in range(50):
                    await asyncio.sleep(0.2)
                    if mirror.snaps_synced >= 1:
                        break
                await src.write_file("/live/f", b"gen2!")
                await src.mksnap("/live", "g2")
                for _ in range(50):
                    await asyncio.sleep(0.2)
                    if mirror.snaps_synced >= 2:
                        break
            finally:
                await mirror.stop()
            assert await dst.read_file("/live/.snap/g1/f") == b"gen1"
            assert await dst.read_file("/live/.snap/g2/f") == b"gen2!"
        finally:
            await _teardown(s_stack, d_stack)
    run(main())

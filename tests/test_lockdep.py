"""lockdep tier (§5.2 race detection): the asyncio lock-order checker
flags would-be deadlocks at acquisition time, and a real cluster run
under lockdep records clean cross-class orders."""

import asyncio

import pytest

from ceph_tpu.common import lockdep


@pytest.fixture
def lockdep_on():
    was = lockdep.enabled
    lockdep.enabled = True
    lockdep.reset()
    try:
        yield
    finally:
        lockdep.enabled = was
        lockdep.reset()


def test_order_inversion_detected(lockdep_on):
    a, b = asyncio.Lock(), asyncio.Lock()

    async def main():
        # task 1 teaches the order A -> B
        async def ab():
            async with lockdep.guard(a, "A"):
                async with lockdep.guard(b, "B"):
                    pass

        await ab()
        # the REVERSE order is a would-be deadlock: flagged before any
        # unlucky interleaving is needed
        with pytest.raises(lockdep.LockOrderInversion):
            async with lockdep.guard(b, "B"):
                async with lockdep.guard(a, "A"):
                    pass

    asyncio.run(main())


def test_same_class_nesting_allowed(lockdep_on):
    a, b = asyncio.Lock(), asyncio.Lock()

    async def main():
        async with lockdep.guard(a, "objlock"):
            async with lockdep.guard(b, "objlock"):
                pass

    asyncio.run(main())


def test_transitive_cycle_detected(lockdep_on):
    la, lb, lc = asyncio.Lock(), asyncio.Lock(), asyncio.Lock()

    async def main():
        async with lockdep.guard(la, "A"):
            async with lockdep.guard(lb, "B"):
                pass
        async with lockdep.guard(lb, "B"):
            async with lockdep.guard(lc, "C"):
                pass
        with pytest.raises(lockdep.LockOrderInversion):
            async with lockdep.guard(lc, "C"):
                async with lockdep.guard(la, "A"):
                    pass

    asyncio.run(main())


def test_cluster_lock_orders_are_clean(lockdep_on):
    """A real workload (writes, cls exec, scrub) under lockdep: the
    OSD's documented lock classes must form an acyclic order."""
    from cluster_helpers import Cluster

    async def main():
        cluster = Cluster(num_osds=3)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "ld", size=2, pg_num=4)
            io = cluster.client.open_ioctx("ld")
            await io.write_full("obj", b"x" * 9000)
            await io.write("obj", b"yyy", 100)
            # cls exec nests clslock -> objlock
            import json
            await io.execute("ctr", "numops", "add", json.dumps(
                {"key": "n", "value": 2}).encode())
            for osd_id in sorted(cluster.osds):
                await cluster.client.osd_command(
                    osd_id, {"prefix": "scrub"})
            await cluster.wait_for_clean(timeout=30.0)
            assert await io.read("obj", 100, 3) == b"yyy"
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(main(), 120))

"""Stock-curl interop: an INDEPENDENT sigv4 implementation (libcurl's
--aws-sigv4) drives the S3 frontend end-to-end.

The in-repo spec-level client (tests/test_s3_http.py) shares no code
with libcurl's signer — but it was written by the same hands as the
verifier, so this leg is the real interop proof: if curl's
canonicalization and ours disagree anywhere, authentication fails
here.  Skips when curl (or sigv4 support) is absent."""

import asyncio
import hashlib
import shutil
import subprocess

import pytest

from cluster_helpers import Cluster

from ceph_tpu.rgw import RGWLite
from ceph_tpu.rgw.s3_frontend import S3Frontend

ACCESS, SECRET = "AKIDCURLTEST", "curl-interop-secret"

_curl = shutil.which("curl")


def _curl_supports_sigv4() -> bool:
    if _curl is None:
        return False
    out = subprocess.run([_curl, "--help", "all"],
                         capture_output=True, text=True).stdout
    return "--aws-sigv4" in out


pytestmark = pytest.mark.skipif(
    not _curl_supports_sigv4(),
    reason="curl with --aws-sigv4 not available")


async def _curl_s3(addr: str, method: str, path: str,
                   body: bytes = None, secret: str = SECRET) -> tuple:
    """One signed curl invocation; returns (status, body_bytes)."""
    args = [_curl, "-s", "-o", "-", "-w", "\n%{http_code}",
            "--aws-sigv4", "aws:amz:us-east-1:s3",
            "--user", f"{ACCESS}:{secret}",
            "-X", method, f"http://{addr}{path}"]
    if body is not None:
        args += ["--data-binary", "@-",
                 "-H", "Content-Type: application/octet-stream"]
    proc = await asyncio.create_subprocess_exec(
        *args, stdin=asyncio.subprocess.PIPE,
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.PIPE)
    out, err = await asyncio.wait_for(
        proc.communicate(body if body is not None else None), 30)
    assert proc.returncode == 0, err.decode()
    payload, _, code = out.rpartition(b"\n")
    return int(code), payload


def test_curl_sigv4_object_round_trip():
    async def run():
        cluster = Cluster(num_osds=2, osds_per_host=1)
        await cluster.start()
        fe = None
        try:
            await cluster.client.create_replicated_pool(
                "rgw.meta", size=2, pg_num=4)
            await cluster.client.create_replicated_pool(
                "rgw.data", size=2, pg_num=4)
            rgw = RGWLite(cluster.client, "rgw.data", "rgw.meta")
            fe = S3Frontend(rgw, {ACCESS: SECRET})
            addr = await fe.start()

            st, _ = await _curl_s3(addr, "PUT", "/curlbucket")
            assert st == 200
            data = bytes(range(256)) * 1000
            st, _ = await _curl_s3(addr, "PUT", "/curlbucket/blob",
                                   body=data)
            assert st == 200
            st, got = await _curl_s3(addr, "GET", "/curlbucket/blob")
            assert st == 200 and got == data
            # server-side object really is the curl-uploaded bytes
            assert (await rgw.head_object(
                "curlbucket", "blob"))["etag"] == \
                hashlib.md5(data).hexdigest()
            st, listing = await _curl_s3(addr, "GET", "/curlbucket")
            assert st == 200 and b"blob" in listing
            # query-bearing request: curl <8.3 signs the RAW query
            # string (no spec canonicalization) — the verifier's
            # legacy-form fallback must accept it
            st, acl_xml = await _curl_s3(addr, "GET",
                                         "/curlbucket/blob?acl")
            assert st == 200 and b"AccessControlPolicy" in acl_xml
            st, listing = await _curl_s3(addr, "GET",
                                         "/curlbucket?prefix=bl")
            assert st == 200 and b"blob" in listing
            st, _ = await _curl_s3(addr, "DELETE", "/curlbucket/blob")
            assert st == 204
            st, _ = await _curl_s3(addr, "DELETE", "/curlbucket")
            assert st == 204
            # a WRONG secret must fail signature verification
            st, body = await _curl_s3(addr, "GET", "/curlbucket2",
                                      secret="not-the-secret")
            assert st == 403 and b"SignatureDoesNotMatch" in body
        finally:
            if fe is not None:
                await fe.stop()
            await cluster.stop()

    asyncio.run(asyncio.wait_for(run(), 120))

/* Test-only oracle shim: builds a CRUSH map with the *reference's own*
 * builder/mapper C code (compiled from /root/reference at test time, never
 * vendored into this repo) and exposes crush_do_rule through a flat C ABI
 * for ctypes.  Used by test_crush_oracle.py to assert placement diff = 0
 * between ceph_tpu.crush and the reference kernel.  This file contains only
 * original shim code. */

#include <stdlib.h>
#include <string.h>

#include "crush/crush.h"
#include "crush/hash.h"
#include "crush/builder.h"
#include "crush/mapper.h"

struct oracle {
    struct crush_map *map;
};

void *oracle_create(void) {
    struct oracle *o = calloc(1, sizeof(*o));
    o->map = crush_create();
    /* modern tunables, matching ceph_tpu.crush.map defaults */
    o->map->choose_local_tries = 0;
    o->map->choose_local_fallback_tries = 0;
    o->map->choose_total_tries = 50;
    o->map->chooseleaf_descend_once = 1;
    o->map->chooseleaf_vary_r = 1;
    o->map->chooseleaf_stable = 1;
    return o;
}

/* alg: 1=uniform 2=list 3=tree 4=straw 5=straw2; returns bucket id (<0) */
int oracle_add_bucket(void *vo, int alg, int type, int size,
                      const int *items, const int *weights) {
    struct oracle *o = vo;
    struct crush_bucket *b = crush_make_bucket(
        o->map, alg, CRUSH_HASH_RJENKINS1, type, size,
        (int *)items, (int *)weights);
    int id = 0;
    if (!b)
        return 1;  /* invalid (positive) to signal failure */
    if (crush_add_bucket(o->map, 0, b, &id) < 0)
        return 1;
    return id;
}

int oracle_add_rule(void *vo, int len, int type,
                    const int *ops, const int *arg1s, const int *arg2s) {
    struct oracle *o = vo;
    struct crush_rule *r = crush_make_rule(len, 0, type, 1, 10);
    int i;
    if (!r)
        return -1;
    for (i = 0; i < len; i++)
        crush_rule_set_step(r, i, ops[i], arg1s[i], arg2s[i]);
    return crush_add_rule(o->map, r, -1);
}

void oracle_set_max_devices(void *vo, int n) {
    struct oracle *o = vo;
    o->map->max_devices = n;
}

void oracle_set_tunables(void *vo, int total_tries, int local_tries,
                         int local_fallback, int descend_once, int vary_r,
                         int stable) {
    struct oracle *o = vo;
    o->map->choose_total_tries = total_tries;
    o->map->choose_local_tries = local_tries;
    o->map->choose_local_fallback_tries = local_fallback;
    o->map->chooseleaf_descend_once = descend_once;
    o->map->chooseleaf_vary_r = vary_r;
    o->map->chooseleaf_stable = stable;
}

void oracle_finalize(void *vo) {
    struct oracle *o = vo;
    crush_finalize(o->map);
}

/* returns result length; result must hold result_max ints */
int oracle_do_rule(void *vo, int ruleno, int x, int *result, int result_max,
                   const unsigned *weight, int weight_max) {
    struct oracle *o = vo;
    int scratch_len = result_max * 3;
    void *cwin = malloc(o->map->working_size + scratch_len * sizeof(int));
    int n;
    crush_init_workspace(o->map, cwin);
    n = crush_do_rule(o->map, ruleno, x, result, result_max,
                      weight, weight_max, cwin, NULL);
    free(cwin);
    return n;
}

void oracle_destroy(void *vo) {
    struct oracle *o = vo;
    crush_destroy(o->map);
    free(o);
}

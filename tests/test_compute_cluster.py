"""Coded-compute cluster tier: the end-to-end scan through live
daemons — pushdown vs the CEPH_TPU_COMPUTE=0 read-then-compute parity
leg, bytes-moved accounting, the straggler/dead-OSD legs riding the
hedged first-k sub-compute fan-out, and the nonlinear full-decode
fallback (replicated + EC)."""

import asyncio
import json
import os
import time

import numpy as np
import pytest

from cluster_helpers import Cluster

EC22 = {"plugin": "ec_jax", "technique": "reed_sol_van",
        "k": "2", "m": "2", "crush-failure-domain": "osd"}


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 180))


async def _fill(io, n, seed=7, size=8192):
    payloads = {}
    rng = np.random.default_rng(seed)
    for i in range(n):
        data = rng.integers(0, 256, size + 17 * i,
                            dtype=np.uint8).tobytes()
        payloads[f"o{i}"] = data
        await io.write_full(f"o{i}", data)
    return payloads


def test_scan_pushdown_matches_read_then_compute():
    """The acceptance bit-exactness leg: pushdown results ==
    client-side read-then-compute for a linear AND a nonlinear
    kernel, with the pushdown moving only result bytes (no sub-READ
    traffic at all for the linear kernel) and the engine counters
    attributing the paths."""
    async def main():
        cluster = Cluster(num_osds=5, osds_per_host=5)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool("cpool",
                                                profile=EC22,
                                                pg_num=8)
            io = cluster.client.open_ioctx("cpool")
            payloads = await _fill(io, 10)
            oids = sorted(payloads)

            def subread_bytes():
                return sum(o.perf["subread_bytes"]
                           for o in cluster.osds.values())

            before = subread_bytes()
            results, errors = await io.compute("gf_fold", oids)
            assert not errors
            assert set(results) == set(oids)
            # the pushdown moved ZERO payload bytes: sub-compute
            # replies carry lane-width results, never chunk streams
            assert subread_bytes() == before
            # parity: kill switch -> client-side read-then-compute
            os.environ["CEPH_TPU_COMPUTE"] = "0"
            try:
                ref, referr = await io.compute("gf_fold", oids)
            finally:
                del os.environ["CEPH_TPU_COMPUTE"]
            assert not referr
            assert {o: bytes(r) for o, r in results.items()} == \
                {o: bytes(r) for o, r in ref.items()}
            # the parity leg DID move the payloads over sub-reads
            assert subread_bytes() > before

            # nonlinear kernel: full-decode fallback, still only
            # result bytes back to the client
            res, err = await io.compute("count", oids, {"record": 8})
            assert not err
            for oid, r in res.items():
                assert json.loads(r)["count"] == \
                    len(payloads[oid]) // 8
            os.environ["CEPH_TPU_COMPUTE"] = "0"
            try:
                ref2, _ = await io.compute("count", oids,
                                           {"record": 8})
            finally:
                del os.environ["CEPH_TPU_COMPUTE"]
            assert {o: bytes(r) for o, r in res.items()} == \
                {o: bytes(r) for o, r in ref2.items()}

            pushed = sum(o.compute.perf()["pushdown_objects"]
                         for o in cluster.osds.values())
            fell = sum(o.compute.perf()["fallback_objects"]
                       for o in cluster.osds.values())
            assert pushed == len(oids)   # gf_fold rode the code
            assert fell == len(oids)     # count took full decode
            # a scan of a missing object reports ENOENT, scan-style
            res3, err3 = await io.compute("gf_fold", ["nope"])
            assert not res3 and err3 == {"nope": -2}
        finally:
            await cluster.stop()

    run(main())


def test_scan_p99_flat_under_one_slow_osd():
    """The straggler leg: one acting-set OSD gets a large injected
    delay; the hedged first-k sub-compute fan-out completes every
    object from the other k shards, so the scan finishes in a small
    fraction of the delay — and bit-exactly."""
    async def main():
        delay = 2.0
        cluster = Cluster(num_osds=5, osds_per_host=5,
                          osd_config={"osd_heartbeat_interval": 3.0,
                                      "osd_heartbeat_grace": 20.0})
        await cluster.start()
        try:
            await cluster.client.create_ec_pool("spool",
                                                profile=EC22,
                                                pg_num=8)
            io = cluster.client.open_ioctx("spool")
            payloads = await _fill(io, 8, seed=9)
            oids = sorted(payloads)
            ref, _ = await io.compute("gf_fold", oids)
            # slow the OSD that primaries the FEWEST of our objects,
            # so it sits on sub-compute fan-outs, not op targets
            counts = {o: 0 for o in cluster.osds}
            for oid in oids:
                pg = io.object_pg(oid)
                _a, p = cluster.mon.osdmap.pg_to_acting_osds(pg)
                counts[p] = counts.get(p, 0) + 1
            slow = min(sorted(counts), key=lambda o: counts[o])
            targets = [oid for oid in oids
                       if cluster.mon.osdmap.pg_to_acting_osds(
                           io.object_pg(oid))[1] != slow]
            assert targets
            cluster.osds[slow].msgr.inject_internal_delays = delay
            t0 = time.monotonic()
            results, errors = await io.compute("gf_fold", targets)
            elapsed = time.monotonic() - t0
            assert not errors
            assert {o: bytes(results[o]) for o in targets} == \
                {o: bytes(ref[o]) for o in targets}
            # first-k completion: the wave never waited out the
            # injected delay (unhedged, every pg touching the slow
            # OSD would stall >= delay)
            assert elapsed < delay, elapsed
        finally:
            await cluster.stop()

    run(main())


def test_scan_survives_a_dead_osd():
    """A DEAD acting-set member is the straggler limit case: the
    remaining k+m-1 shards still complete every object, bit-exact."""
    async def main():
        cluster = Cluster(num_osds=5, osds_per_host=5)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool("dpool",
                                                profile=EC22,
                                                pg_num=8)
            io = cluster.client.open_ioctx("dpool")
            payloads = await _fill(io, 6, seed=13)
            oids = sorted(payloads)
            ref, _ = await io.compute("gf_fold", oids)
            victim = max(cluster.osds)
            await cluster.kill_osd(victim)
            await cluster.wait_for_osd_down(victim)
            await cluster.wait_for_clean(60.0)
            results, errors = await io.compute("gf_fold", oids)
            assert not errors
            assert {o: bytes(results[o]) for o in oids} == \
                {o: bytes(ref[o]) for o in oids}
        finally:
            await cluster.stop()

    run(main())


def test_compute_on_replicated_pool_and_scoring_kernels():
    """Replicated pools take the fallback path (k=1 semantics) for
    every kernel; the scoring kernels return their canonical JSON."""
    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool("rp", size=3,
                                                        pg_num=8)
            io = cluster.client.open_ioctx("rp")
            rng = np.random.default_rng(3)
            noisy = rng.integers(0, 256, 16384,
                                 dtype=np.uint8).tobytes()
            await io.write_full("noisy", noisy)
            await io.write_full("flat", b"\x00" * 16384)
            emb = np.zeros((4, 8), dtype=np.float32)
            emb[2] = 1.0
            await io.write_full("emb", emb.tobytes())

            res, err = await io.compute(
                "compress_score", ["noisy", "flat"])
            assert not err
            assert json.loads(res["noisy"])["entropy_bpb"] > 7.5
            assert json.loads(res["flat"])["entropy_bpb"] == 0.0

            res, err = await io.compute(
                "dot_score", ["emb"],
                {"dim": 8, "query": [1.0] * 8})
            assert not err
            assert json.loads(res["emb"])["best"] == 2

            # linear kernel on a replicated pool: k=1 fallback parity
            res, err = await io.compute("gf_fold", ["noisy"])
            assert not err
            os.environ["CEPH_TPU_COMPUTE"] = "0"
            try:
                ref, _ = await io.compute("gf_fold", ["noisy"])
            finally:
                del os.environ["CEPH_TPU_COMPUTE"]
            assert bytes(res["noisy"]) == bytes(ref["noisy"])

            # unknown kernel is an explicit refusal
            res, err = await io.compute("no_such_kernel", ["noisy"])
            assert not res and err == {"noisy": -22}
        finally:
            await cluster.stop()

    run(main())


def test_scan_traces_name_the_compute_stages():
    """The per-stage observability contract: a scan leaves `compute`
    / `subcompute` stage samples in the primaries' critical-path
    histograms (the stage rows the bench's trace decomposition
    reads)."""
    async def main():
        cluster = Cluster(num_osds=5, osds_per_host=5)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool("tpool",
                                                profile=EC22,
                                                pg_num=8)
            io = cluster.client.open_ioctx("tpool")
            await _fill(io, 6, seed=21)
            _res, err = await io.compute(
                "gf_fold", [f"o{i}" for i in range(6)])
            assert not err
            stages = set()
            for osd in cluster.osds.values():
                stages.update(osd.tracer.stage_perf())
            assert any(s.startswith("compute") for s in stages), \
                stages
            assert "subcompute" in stages or \
                any("subcompute" in s for s in stages), stages
        finally:
            await cluster.stop()

    run(main())

"""libradosstriper-role tests: RAID-0 layout math against a brute
oracle, round-trips over EC pools, layout persistence, append/
truncate/remove semantics."""

import asyncio

import numpy as np
import pytest

from cluster_helpers import Cluster

from ceph_tpu.rados.striper import RadosStriper
from ceph_tpu.rados.client import ObjectNotFound, RadosError


def test_extent_walk_matches_brute_force():
    s = RadosStriper.__new__(RadosStriper)
    s.stripe_unit, s.stripe_count, s.object_size = 4096, 3, 16384
    per_set = s.object_size * s.stripe_count

    def brute(off):
        unit = off // s.stripe_unit
        setno = off // per_set
        units_per_obj = s.object_size // s.stripe_unit
        unit_in_set = unit % (s.stripe_count * units_per_obj)
        obj = setno * s.stripe_count + unit_in_set % s.stripe_count
        row = unit_in_set // s.stripe_count
        return obj, row * s.stripe_unit + off % s.stripe_unit

    rng = np.random.default_rng(0)
    for _ in range(300):
        off = int(rng.integers(0, 400_000))
        ln = int(rng.integers(1, 50_000))
        covered = 0
        for objectno, obj_off, span in s._extents(off, ln):
            o, oo = brute(off + covered)
            assert (objectno, obj_off) == (o, oo), (off, covered)
            covered += span
        assert covered == ln


def test_striper_round_trip_ec_pool():
    async def run():
        cluster = Cluster(num_osds=4, osds_per_host=2)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "st", {"plugin": "ec_jax", "technique": "reed_sol_van",
                       "k": "2", "m": "1",
                       "crush-failure-domain": "osd", "tpu": "false"},
                pg_num=4)
            io = cluster.client.open_ioctx("st")
            st = RadosStriper(io, stripe_unit=64 * 1024,
                              stripe_count=3,
                              object_size=256 * 1024)
            data = np.random.default_rng(5).integers(
                0, 256, 2_000_000, dtype=np.uint8).tobytes()
            await st.write("big", data)
            assert await st.size("big") == len(data)
            assert await st.read("big") == data
            # ranged reads cross stripe/object-set boundaries
            assert await st.read("big", 60_000, 300_000) == \
                data[60_000:360_000]
            # the stream spread over MULTIPLE rados objects
            names = await io.list_objects()
            assert sum(1 for n in names if n.startswith("big.")) > 3
            # append + reopen with a FRESH striper (layout persisted)
            await st.append("big", b"tail-bytes")
            st2 = RadosStriper(io, stripe_unit=64 * 1024,
                               stripe_count=3,
                               object_size=256 * 1024)
            assert (await st2.read("big"))[-10:] == b"tail-bytes"
            # layout mismatch is refused, not silently corrupted
            bad = RadosStriper(io, stripe_unit=32 * 1024,
                               stripe_count=2,
                               object_size=128 * 1024)
            with pytest.raises(RadosError):
                await bad.write("big", b"x")
            # truncate + remove
            await st.truncate("big", 1000)
            assert await st.read("big") == data[:1000]
            await st.remove("big")
            with pytest.raises(ObjectNotFound):
                await st.size("big")
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(run(), 120))

"""Multi-monitor quorum tier: elections, Paxos replication, leader
failover, peon forwarding, catch-up.

Mirrors the reference's mon thrasher / paxos unit coverage
(/root/reference/src/test/mon/test_election.cc, qa mon_thrash role):
map mutations must survive the loss of any minority of mons, including
the leader mid-stream, and a rejoining mon must converge.
"""

import asyncio

import pytest

from ceph_tpu.rados.client import RadosClient

from cluster_helpers import Cluster

FAST_QUORUM = {
    "mon_lease": 0.8,
    "mon_election_timeout": 1.0,
    "mon_accept_timeout": 1.5,
}


def quorum_cluster(num_osds=4, **kw):
    return Cluster(num_osds=num_osds, osds_per_host=1, num_mons=3,
                   mon_config=dict(FAST_QUORUM), **kw)


def test_election_lowest_rank_wins():
    async def run():
        cluster = quorum_cluster(num_osds=2)
        await cluster.start()
        try:
            leaders = {m.elector.leader
                       for m in cluster.mons.values()}
            assert leaders == {0}, leaders
            assert cluster.mons[0].is_leader()
            assert not cluster.mons[1].is_leader()
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(run(), 60))


def test_mutations_replicate_to_all_mons():
    async def run():
        cluster = quorum_cluster(num_osds=3)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "qpool", size=2, pg_num=4)
            # commit is majority-durable; peons may apply a beat later
            for _ in range(100):
                if all(m.osdmap.lookup_pool("qpool") >= 0
                       for m in cluster.mons.values()):
                    break
                await asyncio.sleep(0.05)
            epochs = {m.osdmap.epoch for m in cluster.mons.values()}
            assert len(epochs) == 1, epochs
            lcs = {m.paxos.last_committed
                   for m in cluster.mons.values()}
            assert len(lcs) == 1, lcs
            for m in cluster.mons.values():
                assert m.osdmap.lookup_pool("qpool") >= 0
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(run(), 60))


def test_command_via_peon_is_forwarded():
    async def run():
        cluster = quorum_cluster(num_osds=2)
        await cluster.start()
        try:
            # a client connected ONLY to a peon still mutates the map
            peon = RadosClient([cluster.mon_addrs[2]])
            await peon.connect()
            try:
                rc, out = await peon.mon_command(
                    {"prefix": "osd pool create", "name": "fwd",
                     "pg_num": 4, "pool_type": "replicated",
                     "size": 2})
                assert rc == 0, out
                rc, out = await peon.mon_command({"prefix": "mon stat"})
                assert rc == 0
                assert out["leader"] == 0
                assert sorted(out["quorum"]) == [0, 1, 2]
            finally:
                await peon.shutdown()
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(run(), 60))


def test_leader_kill_fails_over_and_serves():
    async def run():
        cluster = quorum_cluster(num_osds=3)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "fail", size=2, pg_num=4)
            ioctx = cluster.client.open_ioctx("fail")
            await ioctx.write_full("before", b"x" * 4096)
            await cluster.kill_mon(0)
            # surviving 2-of-3 elect a new leader and keep serving
            await cluster.wait_for_quorum(timeout=20.0)
            assert cluster.mon.rank in (1, 2)
            rc, out = await cluster.client.mon_command(
                {"prefix": "status"})
            assert rc == 0
            # map mutations still commit on the 2-mon majority
            rc, out = await cluster.client.mon_command(
                {"prefix": "osd pool create", "name": "after",
                 "pg_num": 4, "pool_type": "replicated", "size": 2})
            assert rc == 0, out
            # and the data plane still works end to end
            await ioctx.write_full("after-failover", b"y" * 8192)
            assert await ioctx.read("before") == b"x" * 4096
            assert await ioctx.read("after-failover") == b"y" * 8192
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(run(), 120))


def test_peon_kill_quorum_continues():
    async def run():
        cluster = quorum_cluster(num_osds=2)
        await cluster.start()
        try:
            await cluster.kill_mon(2)
            rc, out = await cluster.client.mon_command(
                {"prefix": "osd pool create", "name": "p2",
                 "pg_num": 4, "pool_type": "replicated", "size": 2})
            assert rc == 0, out
            assert cluster.mons[0].is_leader()
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(run(), 60))


def test_rejoining_mon_catches_up():
    async def run():
        cluster = quorum_cluster(num_osds=2)
        await cluster.start()
        try:
            await cluster.kill_mon(2)
            for i in range(5):
                rc, _ = await cluster.client.mon_command(
                    {"prefix": "osd pool create", "name": f"cu{i}",
                     "pg_num": 4, "pool_type": "replicated",
                     "size": 2})
                assert rc == 0
            lead_lc = cluster.mons[0].paxos.last_committed
            await cluster.revive_mon(2)
            for _ in range(200):
                m2 = cluster.mons[2]
                if m2.paxos is not None and \
                        m2.paxos.last_committed >= lead_lc:
                    break
                await asyncio.sleep(0.05)
            m2 = cluster.mons[2]
            assert m2.paxos.last_committed >= lead_lc
            assert m2.osdmap.lookup_pool("cu4") >= 0
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(run(), 90))


@pytest.mark.slow
def test_leader_kill_mid_write_load():
    """The mon-thrash shape: kill the LEADER while a write workload
    runs; no acked write may be lost and the cluster must go clean."""

    async def run():
        cluster = quorum_cluster(num_osds=4)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "load", size=3, pg_num=8)
            ioctx = cluster.client.open_ioctx("load")
            acked = {}
            maybe: dict = {}  # indeterminate attempts since last ack

            async def workload():
                seq = 0
                while True:
                    seq += 1
                    oid = f"o-{seq % 12}"
                    data = bytes([seq % 256]) * (1000 + seq % 5000)
                    # record BEFORE submitting: a timed-out attempt may
                    # still commit (RadosModel indeterminacy rule)
                    maybe.setdefault(oid, []).append(data)
                    try:
                        await ioctx.write_full(oid, data)
                        acked[oid] = data
                        maybe[oid] = []
                    except Exception:
                        pass
                    await asyncio.sleep(0)

            task = asyncio.get_running_loop().create_task(workload())
            try:
                await asyncio.sleep(2.0)
                await cluster.kill_mon(0)   # leader, mid-write
                await cluster.wait_for_quorum(timeout=20.0)
                await asyncio.sleep(3.0)    # writes continue post-failover
            finally:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
            assert len(acked) >= 5
            await cluster.wait_for_clean(timeout=60.0)
            for oid, data in acked.items():
                got = await ioctx.read(oid)
                legal = [data] + maybe.get(oid, [])
                assert any(got == want for want in legal), \
                    f"{oid}: read {got[:8]!r}x{len(got)} matches " \
                    f"neither ack nor {len(legal) - 1} attempts"
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(run(), 180))

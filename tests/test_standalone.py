"""True multi-process tier (qa/standalone/ceph-helpers.sh role).

Spawns the mon and each OSD as a REAL separate python process on
loopback (TPUStore-backed so data survives a SIGKILL), drives them with
the networked client, kills an OSD process with SIGKILL mid-run, reads
through reconstruction, restarts the process, and checks recovery —
the test-erasure-code.sh shape end to end."""

import asyncio
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent

OSD_CONFIG = ('{"osd_heartbeat_interval": 0.3, '
              '"osd_heartbeat_grace": 2.5}')
MON_CONFIG = ('{"mon_osd_min_down_reporters": 1, '
              '"osd_heartbeat_grace": 2.5}')


def _spawn(args):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # daemons never need a device
    env["PYTHONPATH"] = str(REPO)
    return subprocess.Popen(
        [sys.executable, "-u", *args], cwd=str(REPO), env=env,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)


def _read_addr(proc, tag: str, timeout: float = 60.0) -> str:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError(f"daemon exited: rc={proc.poll()}")
        if line.startswith(tag):
            return line.split()[1]
    raise TimeoutError(f"no {tag} line")


@pytest.mark.slow
def test_multiprocess_cluster_ec_kill_restart(tmp_path):
    procs = {}
    mon = _spawn(["-m", "ceph_tpu.mon", "--num-osds", "4",
                  "--config", MON_CONFIG])
    try:
        mon_addr = _read_addr(mon, "MON_ADDR")
        for i in range(4):
            procs[i] = _spawn(
                ["-m", "ceph_tpu.osd", "--id", str(i),
                 "--mon", mon_addr,
                 "--store-path", str(tmp_path / f"osd.{i}"),
                 "--config", OSD_CONFIG])
        for i in range(4):
            _read_addr(procs[i], "OSD_ADDR")

        async def drive():
            from ceph_tpu.rados.client import RadosClient

            client = RadosClient(mon_addr)
            await client.connect()
            try:
                await client.create_ec_pool("ecpool", {
                    "plugin": "ec_jax", "technique": "reed_sol_van",
                    "k": "2", "m": "1",
                    "crush-failure-domain": "osd"}, pg_num=8)
                ioctx = client.open_ioctx("ecpool")
                payloads = {
                    f"obj{i}": np.random.default_rng(i).integers(
                        0, 256, 40_000, dtype=np.uint8).tobytes()
                    for i in range(6)}
                for name, data in payloads.items():
                    await ioctx.write_full(name, data)
                for name, data in payloads.items():
                    assert await ioctx.read(name) == data

                # SIGKILL osd.2's PROCESS: no clean shutdown at all
                procs[2].send_signal(signal.SIGKILL)
                procs[2].wait()
                # wait for the mon to mark it down via failure reports
                for _ in range(300):
                    rc, out = await client.mon_command(
                        {"prefix": "status"})
                    if out["num_up_osds"] == 3:
                        break
                    await asyncio.sleep(0.1)
                else:
                    raise TimeoutError("osd.2 never marked down")
                # degraded reads reconstruct through the lost shard
                for name, data in payloads.items():
                    assert await ioctx.read(name) == data

                # restart the process on the surviving store
                procs[2] = _spawn(
                    ["-m", "ceph_tpu.osd", "--id", "2",
                     "--mon", mon_addr,
                     "--store-path", str(tmp_path / "osd.2"),
                     "--config", OSD_CONFIG])
                _read_addr(procs[2], "OSD_ADDR")
                for _ in range(300):
                    rc, out = await client.mon_command(
                        {"prefix": "status"})
                    if out["num_up_osds"] == 4:
                        break
                    await asyncio.sleep(0.1)
                # data still correct post-rejoin
                for name, data in payloads.items():
                    assert await ioctx.read(name) == data
            finally:
                await client.shutdown()

        asyncio.run(asyncio.wait_for(drive(), 180))
    finally:
        for proc in list(procs.values()) + [mon]:
            if proc.poll() is None:
                proc.kill()
                proc.wait()


@pytest.mark.slow
def test_mon_restart_survives(tmp_path):
    """SIGKILL the MON process mid-run and restart it on its persisted
    MonitorDBStore: pools, epochs, OSD states, and client I/O survive
    (the Paxos-commit durability discipline, MonitorDBStore.h)."""
    procs = {}
    mon = _spawn(["-m", "ceph_tpu.mon", "--num-osds", "3",
                  "--config", MON_CONFIG,
                  "--store-path", str(tmp_path / "mon.db")])
    try:
        mon_addr = _read_addr(mon, "MON_ADDR")
        mon_port = mon_addr.rsplit(":", 1)[1]
        for i in range(3):
            procs[i] = _spawn(
                ["-m", "ceph_tpu.osd", "--id", str(i),
                 "--mon", mon_addr,
                 "--store-path", str(tmp_path / f"osd.{i}"),
                 "--config", OSD_CONFIG])
        for i in range(3):
            _read_addr(procs[i], "OSD_ADDR")

        async def drive():
            from ceph_tpu.rados.client import RadosClient

            client = RadosClient(mon_addr)
            await client.connect()
            try:
                await client.create_replicated_pool(
                    "rbd", size=3, pg_num=8)
                ioctx = client.open_ioctx("rbd")
                await ioctx.write_full("before", b"pre" * 5000)
                epoch_before = client.osdmap.epoch

                # SIGKILL the mon, restart on the SAME port + store
                mon.send_signal(signal.SIGKILL)
                mon.wait()
                mon2 = _spawn(["-m", "ceph_tpu.mon", "--num-osds", "3",
                               "--config", MON_CONFIG,
                               "--port", mon_port,
                               "--store-path",
                               str(tmp_path / "mon.db")])
                # register for cleanup IMMEDIATELY: a failing assert
                # below must not leak the process (and its port)
                procs["mon2"] = mon2
                addr2 = _read_addr(mon2, "MON_ADDR")
                assert addr2 == mon_addr

                # cluster state survived: pool exists, epoch not reset
                rc, out = await client.mon_command({"prefix": "status"})
                assert rc == 0
                assert out["epoch"] >= epoch_before
                # old data reads and new writes work (OSDs re-subscribe)
                assert await ioctx.read("before") == b"pre" * 5000
                await ioctx.write_full("after", b"post" * 5000)
                assert await ioctx.read("after") == b"post" * 5000
            finally:
                await client.shutdown()

        asyncio.run(asyncio.wait_for(drive(), 180))
    finally:
        for proc in list(procs.values()) + [mon]:
            if proc.poll() is None:
                proc.kill()
                proc.wait()


@pytest.mark.slow
def test_multiprocess_thrash_sigkill_under_load(tmp_path):
    """Process-grade thrasher: a continuous write workload runs while
    random TPUStore-backed OSD PROCESSES are SIGKILLed mid-write and
    restarted on their surviving stores — and the mon itself is
    SIGKILLed and restarted once mid-thrash.  The RadosModel acked/
    indeterminate discipline must hold with zero data loss
    (qa/tasks/thrashosds + ceph_test_rados, at process granularity)."""
    import random

    NUM = 6
    rng = random.Random(4242)
    procs = {}
    mon_port = [None]
    mon_box = [None]

    def spawn_osd(i):
        return _spawn(
            ["-m", "ceph_tpu.osd", "--id", str(i),
             "--mon", f"127.0.0.1:{mon_port[0]}",
             "--store-path", str(tmp_path / f"osd.{i}"),
             "--config", OSD_CONFIG])

    def spawn_mon(port=0):
        return _spawn(
            ["-m", "ceph_tpu.mon", "--num-osds", str(NUM),
             "--config", MON_CONFIG, "--port", str(port),
             "--store-path", str(tmp_path / "mon.db")])

    mon_box[0] = _spawn(["-m", "ceph_tpu.mon", "--num-osds", str(NUM),
                         "--config", MON_CONFIG,
                         "--store-path", str(tmp_path / "mon.db")])
    try:
        mon_addr = _read_addr(mon_box[0], "MON_ADDR")
        mon_port[0] = mon_addr.rsplit(":", 1)[1]
        for i in range(NUM):
            procs[i] = spawn_osd(i)
        for i in range(NUM):
            _read_addr(procs[i], "OSD_ADDR")

        async def drive():
            from ceph_tpu.rados.client import ObjectNotFound
            from ceph_tpu.rados.client import RadosClient, RadosError

            client = RadosClient(mon_addr)
            await client.connect()
            try:
                await client.create_ec_pool("thrash", {
                    "plugin": "ec_jax", "technique": "reed_sol_van",
                    "k": "2", "m": "2",
                    "crush-failure-domain": "osd"}, pg_num=8)
                ioctx = client.open_ioctx("thrash")
                model: dict = {}
                maybe: dict = {}
                acked = [0]

                async def workload():
                    seq = 0
                    while True:
                        seq += 1
                        oid = f"o-{rng.randrange(10)}"
                        data = np.random.default_rng(seq).integers(
                            0, 256, rng.randrange(1000, 40_000),
                            dtype=np.uint8).tobytes()
                        maybe.setdefault(oid, []).append(data)
                        try:
                            await ioctx.write_full(oid, data)
                            model[oid] = data
                            maybe[oid] = []
                            acked[0] += 1
                        except RadosError:
                            pass
                        await asyncio.sleep(0)

                async def up_count(want, timeout=60.0):
                    for _ in range(int(timeout / 0.1)):
                        try:
                            rc, out = await client.mon_command(
                                {"prefix": "status"})
                            if rc == 0 and \
                                    out["num_up_osds"] == want:
                                return
                        except RadosError:
                            pass
                        await asyncio.sleep(0.1)
                    raise TimeoutError(f"never reached {want} up osds")

                task = asyncio.get_running_loop().create_task(
                    workload())
                try:
                    for cycle in range(5):
                        victim = rng.randrange(NUM)
                        procs[victim].send_signal(signal.SIGKILL)
                        procs[victim].wait()
                        await up_count(NUM - 1)
                        # keep writing degraded for a beat
                        await asyncio.sleep(1.0)
                        if cycle == 2:
                            # SIGKILL + restart the mon mid-thrash on
                            # its durable store: cluster state and the
                            # in-flight workload must survive
                            mon_box[0].send_signal(signal.SIGKILL)
                            mon_box[0].wait()
                            mon_box[0] = spawn_mon(mon_port[0])
                            procs[f"mon-{cycle}"] = mon_box[0]
                            _read_addr(mon_box[0], "MON_ADDR")
                        procs[victim] = spawn_osd(victim)
                        _read_addr(procs[victim], "OSD_ADDR")
                        await up_count(NUM)
                    # liveness floor: writes must complete once the
                    # cluster is whole again.  On a slow host most of
                    # the thrash window is spent degraded (writes
                    # parked behind recovery), so give the workload a
                    # bounded HEALTHY window to reach the floor
                    # rather than racing the kill schedule
                    for _ in range(1200):
                        if acked[0] >= 10:
                            break
                        await asyncio.sleep(0.1)
                finally:
                    task.cancel()
                    try:
                        await task
                    except asyncio.CancelledError:
                        pass
                assert acked[0] >= 10, f"only {acked[0]} acked writes"
                # settle: health returns to OK (recovery converged)
                for _ in range(600):
                    try:
                        rc, out = await client.mon_command(
                            {"prefix": "health"})
                        if rc == 0 and out["status"] == "HEALTH_OK":
                            break
                    except RadosError:
                        pass
                    await asyncio.sleep(0.1)
                # zero data loss across process kills + mon restart
                for oid, data in model.items():
                    try:
                        got = await ioctx.read(oid)
                    except ObjectNotFound:
                        got = None
                    legal = [data] + maybe.get(oid, [])
                    assert any(got == want for want in legal), \
                        f"{oid}: acked write lost"
            finally:
                await client.shutdown()

        # 5 kill/respawn cycles + mon restart + the bounded healthy
        # window for the acked floor + the health settle: the backstop
        # must cover their worst-case sum, or a slow host dies here
        # with a bare TimeoutError instead of a diagnosable assert
        asyncio.run(asyncio.wait_for(drive(), 600))
    finally:
        for proc in list(procs.values()) + [mon_box[0]]:
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait()


@pytest.mark.slow
def test_quorum_survives_permanent_leader_loss(tmp_path):
    """3 real mon PROCESSES with durable stores forming a Paxos
    quorum; SIGKILL the leader PERMANENTLY (never restarted).  The
    2-of-3 majority must elect a new leader, keep committing map
    mutations, and keep serving client I/O."""
    import socket

    # reserve three loopback ports for a static monmap
    socks = [socket.socket() for _ in range(3)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    monmap = ",".join(f"127.0.0.1:{p}" for p in ports)

    quorum_cfg = ('{"mon_osd_min_down_reporters": 1, '
                  '"osd_heartbeat_grace": 2.5, "mon_lease": 1.0, '
                  '"mon_election_timeout": 1.5}')
    mons = {}
    procs = {}
    try:
        for rank in range(3):
            mons[rank] = _spawn(
                ["-m", "ceph_tpu.mon", "--num-osds", "3",
                 "--rank", str(rank), "--mon-addrs", monmap,
                 "--store-path", str(tmp_path / f"mon.{rank}"),
                 "--config", quorum_cfg])
        for rank in range(3):
            _read_addr(mons[rank], "MON_ADDR")
        for i in range(3):
            procs[i] = _spawn(
                ["-m", "ceph_tpu.osd", "--id", str(i),
                 "--mon", monmap,
                 "--store-path", str(tmp_path / f"osd.{i}"),
                 "--config", OSD_CONFIG])
        for i in range(3):
            _read_addr(procs[i], "OSD_ADDR")

        async def drive():
            from ceph_tpu.rados.client import RadosClient

            client = RadosClient(monmap)
            await client.connect()
            try:
                # quorum up: leader must be rank 0
                rc, out = await client.mon_command(
                    {"prefix": "mon stat"})
                assert rc == 0 and out["leader"] == 0, out
                await client.create_replicated_pool(
                    "qs", size=2, pg_num=8)
                ioctx = client.open_ioctx("qs")
                await ioctx.write_full("pre", b"p" * 9000)

                # permanent leader loss
                mons[0].send_signal(signal.SIGKILL)
                mons[0].wait()

                # 2-of-3 elect a new leader and keep committing
                deadline = time.monotonic() + 60
                while True:
                    try:
                        rc, out = await client.mon_command(
                            {"prefix": "mon stat"})
                        if rc == 0 and out["leader"] in (1, 2):
                            break
                    except Exception:
                        pass
                    if time.monotonic() > deadline:
                        raise TimeoutError("no new leader elected")
                    await asyncio.sleep(0.3)
                rc, out = await client.mon_command(
                    {"prefix": "osd pool create", "name": "post",
                     "pg_num": 4, "pool_type": "replicated",
                     "size": 2})
                assert rc == 0, out
                # data plane alive through the failover
                await ioctx.write_full("post", b"q" * 5000)
                assert await ioctx.read("pre") == b"p" * 9000
                assert await ioctx.read("post") == b"q" * 5000
            finally:
                await client.shutdown()

        asyncio.run(asyncio.wait_for(drive(), 240))
    finally:
        for proc in list(procs.values()) + list(mons.values()):
            if proc.poll() is None:
                proc.kill()
                proc.wait()

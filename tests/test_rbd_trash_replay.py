"""RBD trash (librbd api/Trash.cc role) and rbd-replay
(src/rbd_replay role).

Trash: mv hides the image but keeps its objects; restore brings it
back intact (new name supported); rm respects the deferment window;
purge reclaims expired entries; protected snaps / clones refuse.

Replay: a recorded workload re-executes faithfully against another
image (content-identical with data capture; deterministic synthetic
payloads without).
"""

import asyncio
import io
import json

import pytest

from cluster_helpers import Cluster

from ceph_tpu.rados.client import ObjectNotFound, RadosError
from ceph_tpu.rbd import RBD
from ceph_tpu.rbd.replay import ImageTracer, replay_trace


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 150))


async def _cluster():
    cluster = Cluster(num_osds=3)
    await cluster.start()
    await cluster.client.create_replicated_pool("rbd", size=2,
                                                pg_num=4)
    return cluster


def test_trash_mv_restore_cycle():
    async def main():
        cluster = await _cluster()
        try:
            io_ = cluster.client.open_ioctx("rbd")
            rbd = RBD()
            await rbd.create(io_, "vm", 1 << 20, order=18)
            img = await rbd.open(io_, "vm")
            await img.write(0, b"precious data")
            await img.snap_create("s1")
            await img.close()
            image_id = await rbd.trash_mv(io_, "vm")
            # hidden from the namespace, objects intact
            assert "vm" not in await rbd.list(io_)
            with pytest.raises(ObjectNotFound):
                await rbd.open(io_, "vm")
            entries = await rbd.trash_ls(io_)
            assert [e["id"] for e in entries] == [image_id]
            assert entries[0]["name"] == "vm"
            # restore under a NEW name; snapshots survive the trip
            name = await rbd.trash_restore(io_, image_id,
                                           new_name="vm2")
            assert name == "vm2"
            back = await rbd.open(io_, "vm2")
            assert await back.read(0, 13) == b"precious data"
            assert [s["name"] for s in await back.snap_list()] == \
                ["s1"]
            assert await rbd.trash_ls(io_) == []
        finally:
            await cluster.stop()
    run(main())


def test_trash_rm_deferment_and_purge():
    async def main():
        cluster = await _cluster()
        try:
            io_ = cluster.client.open_ioctx("rbd")
            rbd = RBD()
            await rbd.create(io_, "later", 1 << 20, order=18)
            await rbd.create(io_, "now", 1 << 20, order=18)
            deferred = await rbd.trash_mv(io_, "later", delay=3600)
            expired = await rbd.trash_mv(io_, "now")
            # inside the window: refused without force
            with pytest.raises(RadosError):
                await rbd.trash_rm(io_, deferred)
            # purge reclaims ONLY the expired entry
            assert await rbd.trash_purge(io_) == 1
            ids = [e["id"] for e in await rbd.trash_ls(io_)]
            assert ids == [deferred]
            assert expired not in ids
            # force overrides the window
            await rbd.trash_rm(io_, deferred, force=True)
            assert await rbd.trash_ls(io_) == []
        finally:
            await cluster.stop()
    run(main())


def test_trash_rm_snapshotted_image():
    async def main():
        cluster = await _cluster()
        try:
            io_ = cluster.client.open_ioctx("rbd")
            rbd = RBD()
            await rbd.create(io_, "snapped", 1 << 20, order=18)
            img = await rbd.open(io_, "snapped")
            await img.write(0, b"x" * 4096)
            await img.snap_create("keep")
            await img.close()
            image_id = await rbd.trash_mv(io_, "snapped")
            # unprotected snaps are swept by trash rm
            await rbd.trash_rm(io_, image_id)
            assert await rbd.trash_ls(io_) == []
            # protected snaps refuse
            await rbd.create(io_, "prot", 1 << 20, order=18)
            img = await rbd.open(io_, "prot")
            await img.snap_create("locked")
            await img.snap_protect("locked")
            await img.close()
            pid = await rbd.trash_mv(io_, "prot")
            with pytest.raises(RadosError):
                await rbd.trash_rm(io_, pid)
        finally:
            await cluster.stop()
    run(main())


def test_record_and_replay_workload():
    async def main():
        cluster = await _cluster()
        try:
            io_ = cluster.client.open_ioctx("rbd")
            rbd = RBD()
            await rbd.create(io_, "src", 1 << 20, order=18)
            await rbd.create(io_, "dst", 1 << 20, order=18)
            src = await rbd.open(io_, "src")
            buf = io.StringIO()
            traced = ImageTracer(src, buf, record_data=True)
            await traced.write(0, b"header block")
            await traced.write(64 << 10, b"Z" * 8192)
            await traced.read(0, 12)
            await traced.discard(64 << 10, 4096)
            await traced.close()
            # replay full-speed onto dst; content must match src
            dst = await rbd.open(io_, "dst")
            lines = buf.getvalue().splitlines()
            stats = await replay_trace(lines, dst, speed=0)
            assert stats["ops"] == 4
            assert stats["writes"] == 2 and stats["reads"] == 1
            for off, ln in ((0, 12), (64 << 10, 8192)):
                s = await rbd.open(io_, "src")
                a = await s.read(off, ln)
                b = await dst.read(off, ln)
                assert a == b, off
            await dst.close()
        finally:
            await cluster.stop()
    run(main())


def test_bench_trace_then_replay_cli(tmp_path):
    async def main():
        import subprocess
        import sys

        cluster = await _cluster()
        try:
            mon = cluster.mon.addr
            env = {"PYTHONPATH": ".", "JAX_PLATFORMS": "cpu",
                   "PATH": "/usr/bin:/bin:/usr/local/bin"}

            async def cli(*args):
                proc = await asyncio.create_subprocess_exec(
                    sys.executable, "-m", "ceph_tpu.tools.rbd",
                    "-m", mon, "-p", "rbd", *args,
                    stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                    env=env)
                out, err = await proc.communicate()
                return proc.returncode, out, err

            rc, _, err = await cli("create", "b1", "--size", "256K",
                                   "--order", "14")
            assert rc == 0, err
            trace = tmp_path / "wk.jsonl"
            rc, out, err = await cli(
                "bench", "b1", "--io-type", "write", "--io-size",
                "4K", "--io-total", "32K", "--trace", str(trace))
            assert rc == 0, err
            assert len(trace.read_text().splitlines()) == 8
            rc, _, err = await cli("create", "b2", "--size", "256K",
                                   "--order", "14")
            assert rc == 0, err
            rc, out, err = await cli("replay", str(trace), "b2",
                                     "--speed", "0")
            assert rc == 0, err
            assert json.loads(out)["writes"] == 8
        finally:
            await cluster.stop()
    run(main())

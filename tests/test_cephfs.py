"""CephFS tests: MDS + client over a live mini-cluster.

Mirrors the reference's libcephfs unit shapes
(/root/reference/src/test/libcephfs/test.cc: MountRemount, Dir ops,
ReadWrite, Rename, Symlink) plus the MDS failover discipline
(qa/tasks/mds_thrash.py role at small scale).
"""

import asyncio

import numpy as np
import pytest

from cluster_helpers import Cluster

from ceph_tpu.cephfs import CephFS, CephFSError
from ceph_tpu.mds import MDSDaemon


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 150))


async def _fs_cluster(num_mds=1):
    cluster = Cluster(num_osds=4)
    await cluster.start()
    await cluster.client.create_replicated_pool(
        "cephfs.meta", size=2, pg_num=8)
    await cluster.client.create_replicated_pool(
        "cephfs.data", size=2, pg_num=8)
    mdss = []
    for i in range(num_mds):
        mds = MDSDaemon(cluster.mon.addr, "cephfs.meta", "cephfs.data",
                        name=chr(ord("a") + i), lock_interval=0.3)
        await mds.start()
        mdss.append(mds)
    fs = CephFS(cluster.client, "cephfs.meta", "cephfs.data")
    return cluster, mdss, fs


async def _teardown(cluster, mdss):
    for mds in mdss:
        await mds.stop()
    await cluster.stop()


def test_namespace_round_trip():
    async def main():
        cluster, mdss, fs = await _fs_cluster()
        try:
            await fs.mkdir("/a")
            await fs.mkdir("/a/b")
            with pytest.raises(CephFSError):
                await fs.mkdir("/a")          # EEXIST
            with pytest.raises(CephFSError):
                await fs.mkdir("/nope/c")     # ENOENT mid-path
            await fs.write_file("/a/b/f.txt", b"hello fs")
            assert await fs.read_file("/a/b/f.txt") == b"hello fs"
            assert await fs.listdir("/") == ["a"]
            assert await fs.listdir("/a") == ["b"]
            assert await fs.listdir("/a/b") == ["f.txt"]
            st = await fs.stat("/a/b/f.txt")
            assert st["type"] == "file" and st["size"] == 8
            assert (await fs.stat("/a"))["type"] == "dir"
            with pytest.raises(CephFSError):
                await fs.rmdir("/a")          # ENOTEMPTY
            await fs.unlink("/a/b/f.txt")
            assert not await fs.exists("/a/b/f.txt")
            await fs.rmdir("/a/b")
            await fs.rmdir("/a")
            assert await fs.listdir("/") == []
        finally:
            await _teardown(cluster, mdss)

    run(main())


def test_large_file_striping_and_truncate():
    async def main():
        cluster, mdss, fs = await _fs_cluster()
        try:
            rng = np.random.default_rng(3)
            # small blocks so the file stripes across objects
            f = await fs.open("/big", "w", mode=0o600,
                              block_size=16384)
            data = rng.integers(0, 256, 100_000,
                                dtype=np.uint8).tobytes()
            await f.write(0, data)
            assert await f.read(0, len(data)) == data
            # unaligned overwrite across a block boundary
            await f.write(16000, b"\xee" * 1000)
            got = await f.read(15900, 1200)
            assert got[100:1100] == b"\xee" * 1000
            # data objects actually striped
            objs = [o for o in await fs.data.list_objects()
                    if o.startswith("fsdata.")]
            assert len(objs) >= 6
            # sparse read past a hole
            f2 = await fs.open("/big", "r")
            assert len(await f2.read(0, 100_000)) == 100_000
            # truncate drops tail objects and shrinks size
            await fs.truncate("/big", 20_000)
            assert (await fs.stat("/big"))["size"] == 20_000
            assert await fs.read_file("/big") == \
                data[:16000] + b"\xee" * 1000 + data[17000:20_000]
        finally:
            await _teardown(cluster, mdss)

    run(main())


def test_rename_and_symlink():
    async def main():
        cluster, mdss, fs = await _fs_cluster()
        try:
            await fs.mkdir("/src")
            await fs.mkdir("/dst")
            await fs.write_file("/src/f", b"payload")
            await fs.rename("/src/f", "/dst/g")
            assert not await fs.exists("/src/f")
            assert await fs.read_file("/dst/g") == b"payload"
            # rename over an existing file replaces it
            await fs.write_file("/dst/h", b"old")
            await fs.rename("/dst/g", "/dst/h")
            assert await fs.read_file("/dst/h") == b"payload"
            await fs.symlink("/dst/h", "/link")
            assert await fs.readlink("/link") == "/dst/h"
            assert (await fs.stat("/link"))["type"] == "symlink"
        finally:
            await _teardown(cluster, mdss)

    run(main())


def test_metadata_survives_mds_restart():
    """Write-through metadata: a brand-new MDS on the same pools
    serves the namespace with zero replay."""
    async def main():
        cluster, mdss, fs = await _fs_cluster()
        try:
            await fs.mkdir("/keep")
            await fs.write_file("/keep/f", b"durable" * 100)
            await mdss[0].stop()
            mds2 = MDSDaemon(cluster.mon.addr, "cephfs.meta",
                             "cephfs.data", name="b",
                             lock_interval=0.3)
            await mds2.start()
            mdss.append(mds2)
            assert await fs.read_file("/keep/f") == b"durable" * 100
            await fs.write_file("/keep/g", b"post-restart")
            assert sorted(await fs.listdir("/keep")) == ["f", "g"]
        finally:
            await _teardown(cluster, mdss)

    run(main())


@pytest.mark.slow
def test_standby_mds_takes_over():
    """Active/standby: killing the active MDS mid-run moves the lock
    to the standby and clients fail over transparently."""
    async def main():
        cluster, mdss, fs = await _fs_cluster(num_mds=2)
        try:
            await fs.mkdir("/d")
            await fs.write_file("/d/f", b"before failover")
            active = next(m for m in mdss if m.state == "active")
            standby = next(m for m in mdss if m is not active)
            # hard-stop the active (no unlock: the standby must BREAK
            # the stale lock)
            active._stopping = True
            active._lock_task.cancel()
            await active.msgr.shutdown()
            await active.client.shutdown()
            # client ops ride through the takeover
            for _ in range(200):
                if standby.state == "active":
                    break
                await asyncio.sleep(0.1)
            assert standby.state == "active"
            assert await fs.read_file("/d/f") == b"before failover"
            await fs.write_file("/d/g", b"after failover")
            assert sorted(await fs.listdir("/d")) == ["f", "g"]
        finally:
            await _teardown(cluster, mdss)

    run(main())

"""CephFS tests: MDS + client over a live mini-cluster.

Mirrors the reference's libcephfs unit shapes
(/root/reference/src/test/libcephfs/test.cc: MountRemount, Dir ops,
ReadWrite, Rename, Symlink) plus the MDS failover discipline
(qa/tasks/mds_thrash.py role at small scale).
"""

import asyncio

import numpy as np
import pytest

from cluster_helpers import Cluster

from ceph_tpu.cephfs import CephFS, CephFSError
from ceph_tpu.mds import MDSDaemon


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 150))


async def _fs_cluster(num_mds=1):
    cluster = Cluster(num_osds=4)
    await cluster.start()
    await cluster.client.create_replicated_pool(
        "cephfs.meta", size=2, pg_num=8)
    await cluster.client.create_replicated_pool(
        "cephfs.data", size=2, pg_num=8)
    mdss = []
    for i in range(num_mds):
        mds = MDSDaemon(cluster.mon.addr, "cephfs.meta", "cephfs.data",
                        name=chr(ord("a") + i), lock_interval=0.3)
        await mds.start()
        mdss.append(mds)
    fs = CephFS(cluster.client, "cephfs.meta", "cephfs.data")
    return cluster, mdss, fs


async def _teardown(cluster, mdss):
    for mds in mdss:
        await mds.stop()
    await cluster.stop()


def test_namespace_round_trip():
    async def main():
        cluster, mdss, fs = await _fs_cluster()
        try:
            await fs.mkdir("/a")
            await fs.mkdir("/a/b")
            with pytest.raises(CephFSError):
                await fs.mkdir("/a")          # EEXIST
            with pytest.raises(CephFSError):
                await fs.mkdir("/nope/c")     # ENOENT mid-path
            await fs.write_file("/a/b/f.txt", b"hello fs")
            assert await fs.read_file("/a/b/f.txt") == b"hello fs"
            assert await fs.listdir("/") == ["a"]
            assert await fs.listdir("/a") == ["b"]
            assert await fs.listdir("/a/b") == ["f.txt"]
            st = await fs.stat("/a/b/f.txt")
            assert st["type"] == "file" and st["size"] == 8
            assert (await fs.stat("/a"))["type"] == "dir"
            with pytest.raises(CephFSError):
                await fs.rmdir("/a")          # ENOTEMPTY
            await fs.unlink("/a/b/f.txt")
            assert not await fs.exists("/a/b/f.txt")
            await fs.rmdir("/a/b")
            await fs.rmdir("/a")
            assert await fs.listdir("/") == []
        finally:
            await _teardown(cluster, mdss)

    run(main())


def test_large_file_striping_and_truncate():
    async def main():
        cluster, mdss, fs = await _fs_cluster()
        try:
            rng = np.random.default_rng(3)
            # small blocks so the file stripes across objects
            f = await fs.open("/big", "w", mode=0o600,
                              block_size=16384)
            data = rng.integers(0, 256, 100_000,
                                dtype=np.uint8).tobytes()
            await f.write(0, data)
            assert await f.read(0, len(data)) == data
            # unaligned overwrite across a block boundary
            await f.write(16000, b"\xee" * 1000)
            got = await f.read(15900, 1200)
            assert got[100:1100] == b"\xee" * 1000
            # data objects actually striped
            objs = [o for o in await fs.data.list_objects()
                    if o.startswith("fsdata.")]
            assert len(objs) >= 6
            # sparse read past a hole
            f2 = await fs.open("/big", "r")
            assert len(await f2.read(0, 100_000)) == 100_000
            # truncate drops tail objects and shrinks size
            await fs.truncate("/big", 20_000)
            assert (await fs.stat("/big"))["size"] == 20_000
            assert await fs.read_file("/big") == \
                data[:16000] + b"\xee" * 1000 + data[17000:20_000]
        finally:
            await _teardown(cluster, mdss)

    run(main())


def test_rename_and_symlink():
    async def main():
        cluster, mdss, fs = await _fs_cluster()
        try:
            await fs.mkdir("/src")
            await fs.mkdir("/dst")
            await fs.write_file("/src/f", b"payload")
            await fs.rename("/src/f", "/dst/g")
            assert not await fs.exists("/src/f")
            assert await fs.read_file("/dst/g") == b"payload"
            # rename over an existing file replaces it
            await fs.write_file("/dst/h", b"old")
            await fs.rename("/dst/g", "/dst/h")
            assert await fs.read_file("/dst/h") == b"payload"
            await fs.symlink("/dst/h", "/link")
            assert await fs.readlink("/link") == "/dst/h"
            assert (await fs.stat("/link"))["type"] == "symlink"
        finally:
            await _teardown(cluster, mdss)

    run(main())


def test_metadata_survives_mds_restart():
    """Write-through metadata: a brand-new MDS on the same pools
    serves the namespace with zero replay."""
    async def main():
        cluster, mdss, fs = await _fs_cluster()
        try:
            await fs.mkdir("/keep")
            await fs.write_file("/keep/f", b"durable" * 100)
            await mdss[0].stop()
            mds2 = MDSDaemon(cluster.mon.addr, "cephfs.meta",
                             "cephfs.data", name="b",
                             lock_interval=0.3)
            await mds2.start()
            mdss.append(mds2)
            assert await fs.read_file("/keep/f") == b"durable" * 100
            await fs.write_file("/keep/g", b"post-restart")
            assert sorted(await fs.listdir("/keep")) == ["f", "g"]
        finally:
            await _teardown(cluster, mdss)

    run(main())


@pytest.mark.slow
def test_standby_mds_takes_over():
    """Active/standby: killing the active MDS mid-run moves the lock
    to the standby and clients fail over transparently."""
    async def main():
        cluster, mdss, fs = await _fs_cluster(num_mds=2)
        try:
            await fs.mkdir("/d")
            await fs.write_file("/d/f", b"before failover")
            active = next(m for m in mdss if m.state == "active")
            standby = next(m for m in mdss if m is not active)
            # hard-stop the active (no unlock: the standby must BREAK
            # the stale lock)
            active._stopping = True
            active._lock_task.cancel()
            await active.msgr.shutdown()
            await active.client.shutdown()
            # client ops ride through the takeover
            for _ in range(200):
                if standby.state == "active":
                    break
                await asyncio.sleep(0.1)
            assert standby.state == "active"
            assert await fs.read_file("/d/f") == b"before failover"
            await fs.write_file("/d/g", b"after failover")
            assert sorted(await fs.listdir("/d")) == ["f", "g"]
        finally:
            await _teardown(cluster, mdss)

    run(main())


def test_rename_crash_atomicity():
    """SIGKILLing the MDS mid-rename never leaves both or neither
    dentry: crash BEFORE the journal append -> exactly the source;
    crash AFTER the append -> the standby's replay finishes the
    rename -> exactly the destination (the MDLog/EUpdate property)."""

    async def main():
        from ceph_tpu.msg.messages import MClientRequest

        cluster, mdss, fs = await _fs_cluster(num_mds=2)

        async def one_shot_rename(addr, src, dst):
            """Single unretried request — the dying MDS never answers,
            exactly like a client watching its server get SIGKILLed."""
            client = cluster.client
            tid = client._next_tid()
            fut = asyncio.get_running_loop().create_future()
            client._futures[tid] = fut
            try:
                await client.msgr.send_to(addr, MClientRequest(
                    tid, "rename", {"src": src, "dst": dst}))
                await asyncio.wait_for(fut, 3.0)
            except Exception:
                pass
            finally:
                client._futures.pop(tid, None)

        try:
            mds_a, mds_b = mdss
            await fs.mkdir("/d1")
            await fs.mkdir("/d2")
            await fs.write_file("/d1/x", b"payload-x")
            await fs.write_file("/d1/y", b"payload-y")
            active = mds_a if mds_a.state == "active" else mds_b

            # crash BEFORE the append: rename never happened
            active._fail_before_journal = True
            await one_shot_rename(active.msgr.addr, "/d1/x", "/d2/x")
            for _ in range(100):
                if any(m.state == "active" and m is not active
                       for m in mdss):
                    break
                await asyncio.sleep(0.2)
            names1 = await fs.listdir("/d1")
            names2 = await fs.listdir("/d2")
            assert "x" in names1 and "x" not in names2, \
                (names1, names2)
            assert await fs.read_file("/d1/x") == b"payload-x"

            # crash AFTER the append (mid-rename, nothing applied):
            # replay must FINISH the rename.  Phase 1 consumed one
            # standby, so enlist a fresh one first.
            from ceph_tpu.mds import MDSDaemon

            survivor = MDSDaemon(cluster.mon.addr, "cephfs.meta",
                                 "cephfs.data", name="c",
                                 lock_interval=0.3)
            await survivor.start()
            mdss.append(survivor)
            active2 = next(m for m in mdss if m.state == "active")
            active2._fail_after_journal = True
            await one_shot_rename(active2.msgr.addr, "/d1/y", "/d2/y")
            for _ in range(100):
                if survivor.state == "active":
                    break
                await asyncio.sleep(0.2)
            assert survivor.state == "active"
            names1 = await fs.listdir("/d1")
            names2 = await fs.listdir("/d2")
            assert "y" not in names1 and "y" in names2, \
                (names1, names2)
            assert await fs.read_file("/d2/y") == b"payload-y"
        finally:
            await _teardown(cluster, mdss)

    run(main())


def test_deposed_active_is_fenced():
    """The ADVICE finding: a partitioned ex-active whose lock a
    standby broke must not be able to land metadata mutations — the
    journal epoch fence rejects its appends server-side (no clocks
    involved)."""

    async def main():
        cluster, mdss, fs = await _fs_cluster(num_mds=2)
        try:
            mds_a, mds_b = mdss
            await fs.mkdir("/safe")
            old = mds_a if mds_a.state == "active" else mds_b
            new = mds_b if old is mds_a else mds_a
            # freeze the old active's lock loop (partition): it still
            # believes it is active and keeps its warm cache
            old._lock_task.cancel()
            # the standby breaks the stale lock and takes over
            for _ in range(150):
                if new.state == "active":
                    break
                await asyncio.sleep(0.2)
            assert new.state == "active"
            # the deposed active tries to mutate directly: the fenced
            # journal append must refuse and step it down
            from ceph_tpu.mds import MDSError
            with pytest.raises(MDSError):
                await old._commit([old._dentry(1, "evil",
                                               {"ino": 999,
                                                "type": "file",
                                                "mode": 0o644,
                                                "size": 0,
                                                "mtime": 0})])
            assert old.state == "standby"
            # namespace unpolluted; the NEW active serves writes fine
            assert "evil" not in await fs.listdir("/")
            await fs.write_file("/safe/f", b"after fencing")
            assert await fs.read_file("/safe/f") == b"after fencing"
        finally:
            await _teardown(cluster, mdss)

    run(main())

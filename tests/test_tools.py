"""CLI tool tests: benchmark, ec-tool, non-regression, crushtool.

Each tool is driven through its run(argv) entry (what `python -m
ceph_tpu.tools.<name>` calls), mirroring the reference's smoke tests
(src/test/ceph-erasure-code-tool/test_ceph-erasure-code-tool.sh and the
crushtool round-trip fixtures).
"""

import json
import os

import numpy as np
import pytest

from ceph_tpu.tools import (
    crushtool,
    erasure_code_benchmark as ecb,
    erasure_code_tool as ect,
    non_regression,
)


# -- ceph_erasure_code_benchmark -------------------------------------------


def test_benchmark_encode(capsys):
    assert ecb.run(["-p", "jerasure", "-P", "k=4", "-P", "m=2",
                    "-s", "65536", "-i", "2"]) == 0
    out = capsys.readouterr().out.strip()
    seconds, kib = out.split("\t")
    assert float(seconds) > 0
    assert int(kib) == 2 * 64


def test_benchmark_decode_random(capsys):
    assert ecb.run(["-w", "decode", "-p", "jerasure", "-P", "k=4",
                    "-P", "m=2", "-s", "16384", "-i", "3",
                    "-e", "2"]) == 0
    assert "\t" in capsys.readouterr().out


def test_benchmark_decode_exhaustive(capsys):
    assert ecb.run(["-w", "decode", "-p", "jerasure", "-P", "k=2",
                    "-P", "m=2", "-s", "8192", "-E", "exhaustive",
                    "-e", "2"]) == 0


def test_benchmark_decode_erased_list(capsys):
    assert ecb.run(["-w", "decode", "-p", "isa", "-P", "k=4", "-P", "m=2",
                    "-s", "8192", "--erased", "0", "--erased", "3"]) == 0
    out = capsys.readouterr().out
    assert "(0)" in out and "(3)" in out  # display_chunks marks erased


def test_benchmark_plan_cache_toggle(capsys):
    """--plan-cache/--no-plan-cache flip the ExecPlan cache and the
    retrace counters print to stderr; stdout keeps the reference
    one-line contract either way."""
    from ceph_tpu.ec import plan

    assert ecb.run(["-p", "ec_jax", "-P", "k=4", "-P", "m=2",
                    "-s", "16384", "-i", "2", "--plan-cache"]) == 0
    cap = capsys.readouterr()
    assert len(cap.out.strip().splitlines()) == 1 and "\t" in cap.out
    assert "plan-cache: enabled=True" in cap.err
    assert "retraces=" in cap.err

    assert ecb.run(["-p", "ec_jax", "-P", "k=4", "-P", "m=2",
                    "-s", "16384", "--no-plan-cache"]) == 0
    cap = capsys.readouterr()
    assert "plan-cache: enabled=False" in cap.err
    assert plan.enabled()  # the toggle was restored after the run


# -- ceph-erasure-code-tool ------------------------------------------------

PROFILE = "plugin=jerasure,technique=reed_sol_van,k=4,m=2"


def test_ec_tool_plugin_exists():
    assert ect.run(["test-plugin-exists", "jerasure"]) == 0
    assert ect.run(["test-plugin-exists", "nonesuch"]) != 0


def test_ec_tool_validate_profile(capsys):
    assert ect.run(["validate-profile", PROFILE]) == 0
    out = capsys.readouterr().out
    assert "chunk_count=6" in out
    assert ect.run(["validate-profile", PROFILE, "data_chunk_count"]) == 0
    assert capsys.readouterr().out.strip() == "4"


def test_ec_tool_calc_chunk_size(capsys):
    assert ect.run(["calc-chunk-size", PROFILE, "4096"]) == 0
    assert int(capsys.readouterr().out) >= 1024


def test_ec_tool_encode_decode_round_trip(tmp_path):
    fname = str(tmp_path / "object")
    data = np.random.default_rng(0).integers(
        0, 256, 100_000, dtype=np.uint8).tobytes()
    with open(fname, "wb") as f:
        f.write(data)
    shards = ",".join(str(i) for i in range(6))
    assert ect.run(["encode", PROFILE, "4096", shards, fname]) == 0
    for i in range(6):
        assert os.path.exists(f"{fname}.{i}")
    # decode from a subset (drop shards 1 and 4)
    os.unlink(fname)
    assert ect.run(["decode", PROFILE, "4096", "0,2,3,5", fname]) == 0
    with open(fname, "rb") as f:
        restored = f.read()
    assert restored[:len(data)] == data


def test_ec_tool_usage(capsys):
    assert ect.run([]) == 1
    assert ect.run(["bogus-command"]) == 1


# -- non-regression corpus -------------------------------------------------


def test_non_regression_create_check(tmp_path):
    base = str(tmp_path)
    args = ["--plugin", "jerasure", "--base", base,
            "-P", "k=2", "-P", "m=2", "-P", "technique=reed_sol_van"]
    assert non_regression.run(args + ["--create"]) == 0
    dirs = os.listdir(base)
    assert len(dirs) == 1 and "plugin=jerasure" in dirs[0]
    archive = os.path.join(base, dirs[0])
    assert sorted(os.listdir(archive)) == ["0", "1", "2", "3", "content"]
    assert non_regression.run(args + ["--check"]) == 0


def test_non_regression_detects_corruption(tmp_path):
    base = str(tmp_path)
    args = ["--plugin", "jerasure", "--base", base, "-P", "k=2", "-P", "m=1"]
    assert non_regression.run(args + ["--create"]) == 0
    archive = os.path.join(base, os.listdir(base)[0])
    chunk = os.path.join(archive, "1")
    with open(chunk, "r+b") as f:
        f.seek(10)
        byte = f.read(1)
        f.seek(10)
        f.write(bytes([byte[0] ^ 0xFF]))
    assert non_regression.run(args + ["--check"]) == 1


# -- crushtool -------------------------------------------------------------

CRUSH_TEXT = """\
# begin crush map
tunable choose_local_tries 0
tunable choose_local_fallback_tries 0
tunable choose_total_tries 50
tunable chooseleaf_descend_once 1
tunable chooseleaf_vary_r 1
tunable chooseleaf_stable 1

# devices
device 0 osd.0 class hdd
device 1 osd.1 class ssd
device 2 osd.2 class hdd
device 3 osd.3 class ssd
device 4 osd.4 class hdd
device 5 osd.5 class hdd

# types
type 0 osd
type 1 host
type 11 root

# buckets
host host0 {
\tid -2
\talg straw2
\thash 0\t# rjenkins1
\titem osd.0 weight 1.00000
\titem osd.1 weight 1.00000
}
host host1 {
\tid -3
\talg straw2
\thash 0
\titem osd.2 weight 1.00000
\titem osd.3 weight 1.00000
}
host host2 {
\tid -4
\talg straw2
\thash 0
\titem osd.4 weight 1.00000
\titem osd.5 weight 2.00000
}
root default {
\tid -1
\talg straw2
\thash 0
\titem host0 weight 2.00000
\titem host1 weight 2.00000
\titem host2 weight 3.00000
}

# rules
rule replicated_rule {
\tid 0
\ttype replicated
\tmin_size 1
\tmax_size 10
\tstep take default
\tstep chooseleaf firstn 0 type host
\tstep emit
}
rule hdd_rule {
\tid 1
\ttype replicated
\tmin_size 1
\tmax_size 10
\tstep take default class hdd
\tstep chooseleaf firstn 0 type host
\tstep emit
}
# end crush map
"""


@pytest.fixture
def crush_text_file(tmp_path):
    path = str(tmp_path / "map.txt")
    with open(path, "w") as f:
        f.write(CRUSH_TEXT)
    return path


def test_crushtool_compile_decompile_round_trip(crush_text_file, tmp_path):
    compiled = str(tmp_path / "map.json")
    assert crushtool.run(["-c", crush_text_file, "-o", compiled]) == 0
    data = json.loads(open(compiled).read())
    assert len(data["buckets"]) >= 4
    decompiled = str(tmp_path / "map2.txt")
    assert crushtool.run(["-d", compiled, "-o", decompiled]) == 0
    text2 = open(decompiled).read()
    # recompile of the decompiled text parses to the same placements
    recompiled = str(tmp_path / "map3.json")
    with open(str(tmp_path / "map2b.txt"), "w") as f:
        f.write(text2)
    assert crushtool.run(["-c", decompiled, "-o", recompiled]) == 0


def test_crushtool_test_utilization(crush_text_file, capsys):
    assert crushtool.run(["-i", crush_text_file, "--test", "--num-rep", "3",
                          "--max-x", "255", "--show-utilization",
                          "--show-statistics"]) == 0
    out = capsys.readouterr().out
    assert "device 0:" in out
    assert "stored" in out and "expected" in out
    assert "result size == 3" in out


def test_crushtool_mappings_match_host_mapper(crush_text_file, capsys):
    """The --test path (TPU kernel or host) equals the exact host mapper."""
    from ceph_tpu.crush import mapper as m
    cmap = crushtool.load_map(crush_text_file)
    assert crushtool.run(["-i", crush_text_file, "--test", "--rule", "0",
                          "--num-rep", "3", "--max-x", "63",
                          "--show-mappings"]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    weights = cmap.full_weight_vector()
    for line in out:
        # CRUSH rule 0 x X [a,b,c]
        parts = line.split()
        x = int(parts[4])
        got = [int(v) for v in parts[5].strip("[]").split(",") if v]
        want = [v for v in m.crush_do_rule(cmap, 0, x, 3, weights)
                if v >= 0]
        assert got == want, (x, got, want)


def test_crushtool_class_rule(crush_text_file, capsys):
    """Rule with `class hdd` places only on hdd devices (0,2,4,5)."""
    assert crushtool.run(["-i", crush_text_file, "--test", "--rule", "1",
                          "--num-rep", "2", "--max-x", "127",
                          "--show-mappings"]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    hdd = {0, 2, 4, 5}
    for line in out:
        devs = [int(v) for v in line.split()[5].strip("[]").split(",") if v]
        assert set(devs) <= hdd, line


def test_crushtool_compare_self(crush_text_file, tmp_path, capsys):
    ref = str(tmp_path / "mappings.txt")
    assert crushtool.run(["-i", crush_text_file, "--test", "--rule", "0",
                          "--num-rep", "3", "--max-x", "127",
                          "--show-mappings"]) == 0
    with open(ref, "w") as f:
        f.write(capsys.readouterr().out)
    assert crushtool.run(["-i", crush_text_file, "--test", "--rule", "0",
                          "--num-rep", "3", "--max-x", "127",
                          "--compare", ref]) == 0
    assert "0 mismatches" in capsys.readouterr().out


def test_crushtool_bad_rule(crush_text_file, capsys):
    assert crushtool.run(["-i", crush_text_file, "--test",
                          "--rule", "9"]) == 1


def test_crushtool_predeclared_class_ids(tmp_path, capsys):
    """A map that pre-declares shadow ids (`id -N class c`) must still
    materialize the shadow hierarchy when a class rule runs (the reference
    always emits those lines on decompile)."""
    text = CRUSH_TEXT.replace(
        "host host0 {\n\tid -2",
        "host host0 {\n\tid -2\n\tid -12 class hdd")
    path = str(tmp_path / "declared.txt")
    with open(path, "w") as f:
        f.write(text)
    assert crushtool.run(["-i", path, "--test", "--rule", "1",
                          "--num-rep", "2", "--max-x", "63",
                          "--show-mappings"]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 64
    hdd = {0, 2, 4, 5}
    for line in out:
        devs = [int(v) for v in line.split()[5].strip("[]").split(",") if v]
        assert devs and set(devs) <= hdd, line


def test_crushtool_choose_args_round_trip(tmp_path):
    text = CRUSH_TEXT + """
# choose_args
choose_args 0 {
  {
    bucket_id -1
    weight_set [
      [ 2.00000 2.00000 3.00000 ]
      [ 1.00000 2.00000 3.00000 ]
    ]
    ids [ -2 -3 -4 ]
  }
}
"""
    path = str(tmp_path / "ca.txt")
    with open(path, "w") as f:
        f.write(text)
    cmap = crushtool.load_map(path)
    assert -1 in cmap.choose_args
    assert cmap.choose_args[-1].weight_set[1] == [0x10000, 0x20000, 0x30000]
    assert cmap.choose_args[-1].ids == [-2, -3, -4]
    # decompile -> recompile preserves choose_args
    from ceph_tpu.crush import compiler as cc
    text2 = cc.decompile(cmap)
    cmap2 = cc.compile_text(text2)
    assert cmap2.choose_args[-1].weight_set == cmap.choose_args[-1].weight_set
    assert cmap2.choose_args[-1].ids == cmap.choose_args[-1].ids


def test_benchmark_exhaustive_with_erased(capsys):
    """--erased + -E exhaustive verifies against pristine chunks."""
    assert ecb.run(["-w", "decode", "-p", "jerasure", "-P", "k=2",
                    "-P", "m=2", "-s", "4096", "-E", "exhaustive",
                    "-e", "1", "--erased", "0"]) == 0


def test_ec_tool_incompatible_stripe_unit(tmp_path, capsys):
    fname = str(tmp_path / "f")
    with open(fname, "wb") as f:
        f.write(b"x" * 1000)
    rc = ect.run(["encode", "plugin=clay,k=4,m=2", "100",
                  "0,1,2,3,4,5", fname])
    assert rc == 1
    err = capsys.readouterr().err
    assert "incompatible" in err or "usage" in err


# -- rados bench zipf sampler (the skewed-read tier leg) --------------------


def test_zipf_indices_deterministic_and_skewed():
    from ceph_tpu.tools.rados import zipf_indices

    a = zipf_indices(1.2, 64, 10_000, seed=5)
    b = zipf_indices(1.2, 64, 10_000, seed=5)
    assert np.array_equal(a, b), "same seed must reproduce the stream"
    assert not np.array_equal(a, zipf_indices(1.2, 64, 10_000, seed=6))
    assert a.min() >= 0 and a.max() < 64
    # rank 0 dominates under theta=1.2 and the mass is monotone-ish
    counts = np.bincount(a, minlength=64)
    assert counts[0] == counts.max()
    assert counts[0] > 10_000 / 64 * 4, "head not hot enough"
    # theta=0 degenerates to uniform (no rank dominates 3x the mean)
    flat = np.bincount(zipf_indices(0.0, 64, 10_000, seed=5),
                       minlength=64)
    assert flat.max() < 3 * 10_000 / 64

"""Mesh-sharded EC data plane tier: the same batch must be
bit-identical through the single-device plan, the N-device mesh plan,
and the host numpy oracle (odd chunk widths, ragged batches, batches
smaller than the mesh); a scripted sick chip must SHRINK the mesh —
its ``device:<id>`` breaker trips, the family breaker is absolved,
the dispatch re-plans on the survivors — never degrade the batch to
host; and the healthy-set mesh in parallel/backend.py must reshape
cleanly for awkward survivor counts.

Runs on the conftest 8-virtual-CPU-device mesh (the same sharding
code paths the real multi-chip mesh compiles).
"""

from __future__ import annotations

import os

import numpy as np
import pytest

import conftest

jax = pytest.importorskip("jax")

from ceph_tpu.common import circuit  # noqa: E402
from ceph_tpu.ec import plan  # noqa: E402
from ceph_tpu.models import reed_solomon as rs  # noqa: E402
from ceph_tpu.ops import checksum as cks  # noqa: E402
from ceph_tpu.ops import gf  # noqa: E402
from ceph_tpu.parallel import backend, striped  # noqa: E402

RNG = np.random.default_rng(4242)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8,
    reason="needs the conftest 8-virtual-device CPU mesh")


@pytest.fixture(autouse=True)
def _mesh_engaged(monkeypatch):
    """Every test here wants the mesh gates open (tiny batches) and a
    clean breaker/plan slate on both sides."""
    monkeypatch.setenv("CEPH_TPU_MESH_MIN_BYTES", "0")
    monkeypatch.delenv("CEPH_TPU_MESH", raising=False)
    monkeypatch.delenv("CEPH_TPU_MESH_MAX_DEVICES", raising=False)
    circuit.reset_all()
    plan.reset_stats()
    yield
    circuit.reset_all()


def _host_parity(mat, data):
    return np.stack([gf.gf_matmul_host(mat, data[i])
                     for i in range(data.shape[0])])


def _host_crcs(data, parity):
    b = data.shape[0]
    out = np.zeros((b, data.shape[1] + parity.shape[1]),
                   dtype=np.uint32)
    for i in range(b):
        chunks = np.concatenate([data[i], parity[i]], axis=0)
        for j in range(chunks.shape[0]):
            out[i, j] = cks.crc32c(0, chunks[j].tobytes())
    return out


# -- bit-exactness: 1-device plan vs N-device mesh plan vs host oracle ------


@pytest.mark.skipif(conftest.DEVICE_INJECTION,
                    reason="asserts live mesh-dispatch counters;\
 subject absent under scripted device-fault injection")
@pytest.mark.parametrize("b,s", [
    (16, 1024),    # even batch, pow2 chunk
    (5, 1001),     # ragged batch, odd chunk width
    (3, 768),      # batch smaller than the 8-device mesh
    (17, 4096),    # ragged past a pow2 bucket edge
])
def test_mesh_encode_bitexact_vs_single_device_and_host(
        monkeypatch, b, s):
    mat = rs.reed_sol_van_matrix(4, 2)
    data = RNG.integers(0, 256, (b, 4, s), dtype=np.uint8)
    want = _host_parity(mat, data)

    meshed = plan.encode(mat, data, sig=f"mesh-{b}-{s}")
    assert meshed is not None and np.array_equal(meshed, want)
    assert plan.stats()["mesh_dispatches"] >= 1

    monkeypatch.setenv("CEPH_TPU_MESH", "0")
    single = plan.encode(mat, data, sig=f"mesh-{b}-{s}")
    assert single is not None and np.array_equal(single, want)
    assert np.array_equal(meshed, single)


@pytest.mark.skipif(conftest.DEVICE_INJECTION,
                    reason="asserts live mesh-dispatch counters;\
 subject absent under scripted device-fault injection")
@pytest.mark.parametrize("b,s", [(12, 2048), (7, 1000)])
def test_mesh_fused_crc_bitexact(monkeypatch, b, s):
    """The flush path's product shape: parity AND the zero-seeded
    per-chunk crc32c from one stripe-parallel dispatch, vs the host
    ledger and the single-device fused plan."""
    mat = rs.reed_sol_van_matrix(6, 3)
    data = RNG.integers(0, 256, (b, 6, s), dtype=np.uint8)
    want_parity = _host_parity(mat, data)
    want_crcs = _host_crcs(data, want_parity)

    meshed = plan.encode_with_crc(mat, data, sig=f"crc-{b}-{s}")
    assert meshed is not None
    assert np.array_equal(meshed[0], want_parity)
    assert np.array_equal(meshed[1], want_crcs)
    assert plan.stats()["mesh_dispatches"] >= 1

    monkeypatch.setenv("CEPH_TPU_MESH", "0")
    single = plan.encode_with_crc(mat, data, sig=f"crc-{b}-{s}")
    assert single is not None
    assert np.array_equal(single[0], meshed[0])
    assert np.array_equal(single[1], meshed[1])


@pytest.mark.skipif(conftest.DEVICE_INJECTION,
                    reason="asserts live device-dispatch results;\
 subject absent under scripted device-fault injection")
def test_small_batches_stay_single_device():
    """Below the stripe gate the mesh declines — one stripe must not
    pay an 8-chip fan-out."""
    mat = rs.reed_sol_van_matrix(4, 2)
    data = RNG.integers(0, 256, (1, 4, 512), dtype=np.uint8)
    out = plan.encode(mat, data, sig="tiny")
    assert out is not None and np.array_equal(out,
                                              _host_parity(mat, data))
    assert plan.stats()["mesh_dispatches"] == 0


@pytest.mark.skipif(conftest.DEVICE_INJECTION,
                    reason="asserts live device-dispatch results;\
 subject absent under scripted device-fault injection")
def test_mesh_min_bytes_gate(monkeypatch):
    monkeypatch.setenv("CEPH_TPU_MESH_MIN_BYTES", str(1 << 30))
    mat = rs.reed_sol_van_matrix(4, 2)
    data = RNG.integers(0, 256, (16, 4, 512), dtype=np.uint8)
    out = plan.encode(mat, data, sig="gated")
    assert out is not None and np.array_equal(out,
                                              _host_parity(mat, data))
    assert plan.stats()["mesh_dispatches"] == 0


# -- sick chip: shrink the mesh, never fall to host -------------------------


@pytest.mark.skipif(conftest.DEVICE_INJECTION,
                    reason="scripts its own injection spec")
def test_sick_chip_shrinks_mesh_not_host(monkeypatch):
    sick = jax.devices()[-1].id
    monkeypatch.setenv("CEPH_TPU_INJECT_DEVICE_FAIL", f"sick={sick}")
    mat = rs.reed_sol_van_matrix(4, 2)
    data = RNG.integers(0, 256, (16, 4, 512), dtype=np.uint8)
    want_parity = _host_parity(mat, data)

    out = plan.encode_with_crc(mat, data, sig="sick")
    assert out is not None and np.array_equal(out[0], want_parity)
    st = plan.stats()
    # the mesh SHRANK (sick chip probed out, survivors re-planned):
    # no host fallback, the family breaker absolved (closed), the
    # chip's own breaker tripped
    assert st["mesh_shrinks"] >= 1
    assert st["mesh_dispatches"] >= 1
    assert st["host_fallbacks"] == 0
    assert circuit.device_breaker(sick).state == circuit.OPEN
    assert circuit.breaker("fused-crc").state == circuit.CLOSED

    # steady state: with the chip pinned out (its jittered backoff
    # could otherwise expire within ms and trigger a legitimate
    # re-probe cycle), the survivor mesh serves the next batch
    # without another shrink
    circuit.device_breaker(sick).force_open(duration=3600.0)
    out2 = plan.encode_with_crc(mat, data, sig="sick")
    assert out2 is not None and np.array_equal(out2[0], want_parity)
    assert plan.stats()["mesh_shrinks"] == st["mesh_shrinks"]
    assert sick not in plan.mesh_info()["healthy"]

    # heal: injection cleared + backoff expired -> the chip's next
    # mesh dispatch is its de-facto half-open probe and it recovers
    monkeypatch.delenv("CEPH_TPU_INJECT_DEVICE_FAIL")
    circuit.device_breaker(sick).force_probe()
    out3 = plan.encode_with_crc(mat, data, sig="sick")
    assert out3 is not None and np.array_equal(out3[0], want_parity)
    assert sick in plan.mesh_info()["healthy"]


@pytest.mark.skipif(conftest.DEVICE_INJECTION,
                    reason="scripts its own injection spec")
def test_sick_chip_decode_path_shrinks(monkeypatch):
    """The matmul/decode kind rides the healthy-set mesh too: a sick
    chip shrinks it, output bit-exact, no host fold."""
    sick = jax.devices()[-1].id
    monkeypatch.setenv("CEPH_TPU_INJECT_DEVICE_FAIL", f"sick={sick}")
    mat = rs.reed_sol_van_matrix(6, 3)
    data = RNG.integers(0, 256, (8, 6, 512), dtype=np.uint8)
    out = plan.matmul(mat, data, sig="sick-mm")
    assert out is not None
    assert np.array_equal(out, _host_parity(mat, data))
    st = plan.stats()
    assert st["mesh_shrinks"] >= 1
    assert st["host_fallbacks"] == 0
    assert circuit.device_breaker(sick).state == circuit.OPEN


def test_probe_devices_attributes_only_the_sick_chip(monkeypatch):
    ids = [d.id for d in jax.devices()]
    monkeypatch.setenv("CEPH_TPU_INJECT_DEVICE_FAIL",
                       f"sick={ids[3]}")
    sick = plan._probe_devices(tuple(ids))
    assert sick == [ids[3]]
    assert circuit.device_breaker(ids[3]).state == circuit.OPEN
    for other in ids:
        if other != ids[3]:
            assert circuit.device_breaker(other).state == \
                circuit.CLOSED


# -- plan keys + policy -----------------------------------------------------


def test_mesh_plan_keys_are_device_set_aware():
    sig = "a" * 16
    base = plan.plan_key(sig, "mesh_encode", 2, 4, 16, 1024)
    m1 = plan.plan_key(sig, "mesh_encode", 2, 4, 16, 1024,
                       mesh=(0, 1, 2, 3))
    m2 = plan.plan_key(sig, "mesh_encode", 2, 4, 16, 1024,
                       mesh=(0, 1, 2))
    assert len({base, m1, m2}) == 3
    # whole stripes per chip: the pow2 bucket rounds UP to a multiple
    # of the mesh size
    assert m2[4] % 3 == 0
    # the fused-crc kinds keep the chunk axis length-exact
    mk = plan.plan_key(sig, "mesh_encode_crc", 2, 4, 16, 1001,
                       mesh=(0, 1))
    assert mk[5] == 1001


def test_mesh_devices_policy(monkeypatch):
    devs = plan._mesh_devices(16, 1 << 20)
    assert devs is not None and len(devs) == 8
    # one chip per stripe at most
    assert len(plan._mesh_devices(3, 1 << 20)) == 3
    # gates
    assert plan._mesh_devices(1, 1 << 20) is None
    monkeypatch.setenv("CEPH_TPU_MESH", "0")
    assert plan._mesh_devices(16, 1 << 20) is None
    monkeypatch.delenv("CEPH_TPU_MESH")
    monkeypatch.setenv("CEPH_TPU_MESH_MAX_DEVICES", "4")
    assert len(plan._mesh_devices(16, 1 << 20)) == 4


# -- backend: healthy-set mesh, awkward survivor counts ---------------------


def test_backend_mesh_derives_from_healthy_set():
    mat = rs.reed_sol_van_matrix(4, 2)
    data = RNG.integers(0, 256, (8, 4, 256), dtype=np.uint8)
    want = _host_parity(mat, data)
    assert np.array_equal(backend.matmul(mat, data), want)
    full = dict(backend.default_mesh().shape)
    assert full.get("dp", 1) * full.get("sp", 1) == 8
    # hold one chip out: the mesh reshapes over the 7 survivors (an
    # awkward count -> pure data-parallel) and stays bit-exact
    sick = jax.devices()[-1].id
    circuit.device_breaker(sick).force_open(duration=3600.0)
    try:
        mesh = backend.default_mesh()
        ids = [d.id for d in mesh.devices.flat]
        assert sick not in ids and len(ids) == 7
        assert dict(mesh.shape).get("sp", 1) == 1
        assert np.array_equal(backend.matmul(mat, data), want)
        assert backend.stats["mesh_rebuilds"] >= 1
    finally:
        circuit.reset_all()


@pytest.mark.parametrize("n", [3, 5, 6, 7])
def test_partial_meshes_reshape_instead_of_raising(n):
    from ceph_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(jax.devices()[:n])
    shape = dict(mesh.shape)
    assert shape.get("dp", 1) * shape.get("sp", 1) == n
    # a pipeline over the partial mesh accepts chunk widths the full
    # mesh's sp split could not divide
    pipe = striped.ShardedPipeline(
        make_mesh(jax.devices()[:n], dp=n, sp=1), 4, 2, 100,
        rs.reed_sol_van_matrix(4, 2))
    assert pipe.sp == 1 and pipe.dp == n


def test_kill_switch_pins_backend_to_one_device(monkeypatch):
    monkeypatch.setenv("CEPH_TPU_MESH", "0")
    assert len(backend.healthy_devices()) == 1
    mat = rs.reed_sol_van_matrix(4, 2)
    data = RNG.integers(0, 256, (4, 4, 256), dtype=np.uint8)
    assert np.array_equal(backend.matmul(mat, data),
                          _host_parity(mat, data))


# -- logical axis rules -----------------------------------------------------


def test_logical_axis_rules_map_stripe_to_dp():
    from jax.sharding import PartitionSpec as P

    mesh = striped.stripe_mesh(jax.devices())
    assert striped.logical_spec("stripe", "shard", "byte",
                                mesh=mesh) == P("dp", None, None)
    full = backend.default_mesh()
    if "sp" in dict(full.shape):
        assert striped.logical_spec("stripe", "shard", "byte",
                                    mesh=full) == P("dp", None, "sp")
    # absent mesh axes resolve to replicated, same kernel everywhere
    assert striped.logical_spec("stripe", mesh=mesh) == P("dp")


# -- surfaces ---------------------------------------------------------------


def test_mesh_info_and_stats_surface():
    info = plan.mesh_info()
    assert info["enabled"] is True
    assert info["devices_total"] == 8
    assert info["healthy"] == [d.id for d in jax.devices()]
    st = plan.stats()
    assert "mesh" in st and st["mesh"]["devices_total"] == 8
    for key in ("mesh_dispatches", "mesh_rows", "mesh_shrinks",
                "mesh_probes"):
        assert key in st


def test_prometheus_devices_label_map():
    """Per-chip breaker rows flatten to a `device` label, state as a
    gauge — the ceph_osd_device_*{device=...} satellite surface."""
    from ceph_tpu.mgr.prometheus import PrometheusModule

    circuit.device_breaker(0).record_success()
    circuit.device_breaker(1).force_open()
    devices = {dev: {k: v for k, v in st.items()
                     if not isinstance(v, str)}
               for dev, st in circuit.device_stats().items()}
    for dev, st in devices.items():
        st["mesh_member"] = int(not circuit.device_degraded(int(dev)))
    lines: list = []
    PrometheusModule._emit_perf(
        lines, set(), "ceph_osd_device_health_devices", devices,
        {"ceph_daemon": "osd.0"})
    text = "\n".join(lines)
    assert ('ceph_osd_device_health_device_state_code'
            '{ceph_daemon="osd.0",device="1"} 2') in text
    assert ('ceph_osd_device_health_device_dispatches'
            '{ceph_daemon="osd.0",device="0"} 1') in text
    assert ('ceph_osd_device_health_device_mesh_member'
            '{ceph_daemon="osd.0",device="1"} 0') in text
    assert "# TYPE ceph_osd_device_health_device_state_code gauge" \
        in text
    assert "# TYPE ceph_osd_device_health_device_mesh_member gauge" \
        in text


@pytest.mark.skipif(conftest.DEVICE_INJECTION,
                    reason="asserts per-chip success/failure verdicts;\
 every dispatch fails under scripted injection")
def test_device_call_attribution():
    """The choke point records per-chip SUCCESS on every participant;
    failures are attributed only by an actual probe (family IS the
    chip's breaker) — an ordinary dispatch failure, single- or
    multi-chip, must not trip a threshold-1 chip breaker on a
    transient the family breaker would tolerate."""
    status, out = circuit.device_call(
        "test-mesh-fam", lambda: 7, devices=(0, 1, 2))
    assert status == "ok" and out == 7
    for d in (0, 1, 2):
        assert circuit.device_breaker(d).counters["successes"] >= 1
    # multi-chip failure: unattributed (the mesh layer probes)
    status, _ = circuit.device_call(
        "test-mesh-fam", lambda: (_ for _ in ()).throw(
            RuntimeError("boom")), devices=(3, 4))
    assert status == "fail"
    assert circuit.device_breaker(3).state == circuit.CLOSED
    assert circuit.device_breaker(4).state == circuit.CLOSED
    # ordinary single-chip failure: family verdict only — the chip's
    # breaker stays closed (a 1-chip host must not lose its only
    # device to one transient)
    status, _ = circuit.device_call(
        "test-mesh-fam2", lambda: (_ for _ in ()).throw(
            RuntimeError("boom")), devices=(5,))
    assert status == "fail"
    assert circuit.device_breaker(5).state == circuit.CLOSED
    # an actual probe (family IS the chip's breaker): decisive,
    # threshold 1 trips
    status, _ = circuit.device_call(
        f"{circuit.DEVICE_FAMILY_PREFIX}6",
        lambda: (_ for _ in ()).throw(RuntimeError("boom")),
        devices=(6,))
    assert status == "fail"
    assert circuit.device_breaker(6).state == circuit.OPEN

"""Op scheduler tests: WPQ weighting + mClock reservation/limit.

Mirrors the reference's dmclock unit shapes
(/root/reference/src/dmclock/test/ — reservation met under competing
load, limit enforced, proportional weights) plus cluster integration:
recovery makes progress under a client flood.
"""

import asyncio
import time

import pytest

from ceph_tpu.osd.scheduler import (
    CLIENT,
    MClockScheduler,
    RECOVERY,
    SCRUB,
    WPQScheduler,
    make_scheduler,
)


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 60))


def test_factory():
    assert isinstance(make_scheduler("wpq"), WPQScheduler)
    assert isinstance(make_scheduler("mclock_scheduler"),
                      MClockScheduler)


def test_wpq_respects_weights():
    """Under sustained backlog of both classes, the grant ORDER shares
    ~4:1 by weight — the low-weight class is slowed, never starved."""
    async def main():
        sched = WPQScheduler(weights={CLIENT: 8.0, RECOVERY: 2.0},
                             max_concurrent=1)
        order: list = []

        async def op(cls):
            order.append(cls)
            await asyncio.sleep(0)

        jobs = []
        for _ in range(40):
            jobs.append(sched.run(CLIENT, 1.0,
                                  lambda: op(CLIENT)))
            jobs.append(sched.run(RECOVERY, 1.0,
                                  lambda: op(RECOVERY)))
        await asyncio.gather(*jobs)
        assert sched.granted[CLIENT] == 40
        assert sched.granted[RECOVERY] == 40
        # within the first 20 grants (both classes backlogged the
        # whole time) the split tracks the 8:2 weights — and crucially
        # recovery IS served during the client backlog, not after it
        head = order[:20]
        assert 2 <= head.count(RECOVERY) <= 8, head
        assert head.count(CLIENT) >= 12, head
        await sched.stop()

    run(main())


def test_run_after_stop_fails_fast():
    async def main():
        sched = WPQScheduler(max_concurrent=1)
        sched.start()
        await sched.stop()

        async def op():
            return 1

        with pytest.raises(RuntimeError):
            await sched.run(CLIENT, 1.0, op)

    run(main())


def test_mclock_reservation_under_flood():
    """A client flood must not starve recovery below its reservation
    (the property mClock exists for)."""
    async def main():
        sched = MClockScheduler(profiles={
            CLIENT: (0.0, 100.0, 0.0),      # huge weight, no floor
            RECOVERY: (50.0, 0.1, 0.0),     # 50 ops/s guaranteed
        }, max_concurrent=2)
        counts = {CLIENT: 0, RECOVERY: 0}
        stop = [False]

        async def client_flood():
            while not stop[0]:
                await sched.run(
                    CLIENT, 1.0, lambda: _bump(counts, CLIENT))

        async def _bump(counts, cls):
            counts[cls] += 1
            await asyncio.sleep(0.002)  # simulated service time

        flood = [asyncio.get_running_loop().create_task(client_flood())
                 for _ in range(4)]
        t0 = time.monotonic()
        # offer recovery work continuously for ~1s
        recov = []
        while time.monotonic() - t0 < 1.0:
            recov.append(sched.run(RECOVERY, 1.0,
                                   lambda: _bump(counts, RECOVERY)))
            await asyncio.sleep(0.01)
        await asyncio.gather(*recov)
        stop[0] = True
        for t in flood:
            t.cancel()
        await asyncio.gather(*flood, return_exceptions=True)
        elapsed = time.monotonic() - t0
        # reservation: >= ~50% of the guaranteed 50/s floor, despite a
        # 1000x weight disadvantage (slack for CI jitter)
        assert counts[RECOVERY] >= 25 * elapsed * 0.5, counts
        # the flood still dominated overall (weight worked too)
        assert counts[CLIENT] > counts[RECOVERY], counts
        await sched.stop()

    run(main())


def test_mclock_limit_caps_class():
    """A limited class cannot exceed its limit even with an idle
    cluster (scrub trickle discipline)."""
    async def main():
        sched = MClockScheduler(profiles={
            SCRUB: (0.0, 10.0, 30.0),       # hard 30 ops/s cap
        }, max_concurrent=4)
        count = [0]

        async def op():
            count[0] += 1

        t0 = time.monotonic()
        loop = asyncio.get_running_loop()
        jobs = [loop.create_task(sched.run(SCRUB, 1.0, op))
                for _ in range(200)]
        done, pending = await asyncio.wait(jobs, timeout=1.0)
        elapsed = time.monotonic() - t0
        for p in pending:
            p.cancel()
        await asyncio.gather(*pending, return_exceptions=True)
        # ~30/s cap: after ~1s no more than ~30 + slack completed,
        # far below the 200 offered
        assert count[0] <= 30 * elapsed * 1.8 + 5, count[0]
        assert count[0] >= 10, count[0]
        await sched.stop()

    run(main())


@pytest.mark.slow
def test_recovery_progresses_under_client_flood():
    """Cluster integration: recovery completes while a client hammers
    the same OSDs (the starvation case an unscheduled loop risks)."""
    from cluster_helpers import Cluster

    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool("ec", {
                "plugin": "ec_jax", "technique": "reed_sol_van",
                "k": "2", "m": "1", "crush-failure-domain": "osd"},
                pg_num=8)
            io = cluster.client.open_ioctx("ec")
            for i in range(20):
                await io.write_full(f"o{i}", bytes([i]) * 20_000)
            await cluster.kill_osd(3)
            await cluster.wait_for_osd_down(3)
            await cluster.client.mon_command(
                {"prefix": "osd out", "osd": 3})
            stop = [False]

            async def flood():
                j = 0
                while not stop[0]:
                    j += 1
                    try:
                        await io.write_full(f"flood-{j % 8}",
                                            b"f" * 8000)
                    except Exception:
                        pass

            tasks = [asyncio.get_running_loop().create_task(flood())
                     for _ in range(3)]
            try:
                await cluster.wait_for_clean(timeout=60.0)
            finally:
                stop[0] = True
                for t in tasks:
                    t.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)
            for i in range(20):
                assert await io.read(f"o{i}") == bytes([i]) * 20_000
            # scheduler actually arbitrated both classes
            granted = {}
            for osd in cluster.osds.values():
                for cls, n in osd.scheduler.granted.items():
                    granted[cls] = granted.get(cls, 0) + n
            assert granted.get("client", 0) > 0
            assert granted.get("background_recovery", 0) > 0
        finally:
            await cluster.stop()

    run(main())

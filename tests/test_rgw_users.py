"""RGW durable users (rgw_user / radosgw-admin roles): admin-created
users authenticate against the live HTTP frontend (header and
presigned auth), suspension/removal take effect within the cache
TTL, and the CLI drives the whole lifecycle."""

import asyncio
import json
import subprocess
import sys

import pytest

from cluster_helpers import Cluster

from ceph_tpu.rgw import RGWLite
from ceph_tpu.rgw.gateway import RGWError
from ceph_tpu.rgw.s3_frontend import S3Frontend, presign_url

from test_s3_http import ACCESS, SECRET, MiniS3, _stack


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 150))


def test_durable_user_lifecycle_through_frontend():
    async def main():
        cluster = Cluster(num_osds=3, osds_per_host=1)
        await cluster.start()
        fe = None
        try:
            fe, addr = await _stack(cluster)
            fe.USER_CACHE_TTL = 0.5  # fast suspension visibility
            fe.USER_NEG_TTL = 0.5    # fast re-enable visibility
            rgw = fe.rgw
            doc = await rgw.user_create("alice",
                                        display_name="Alice A")
            ak = doc["keys"][0]["access_key"]
            sk = doc["keys"][0]["secret_key"]
            assert await rgw.user_list() == ["alice"]
            with pytest.raises(RGWError):
                await rgw.user_create("alice")
            # alice signs requests with her OWN keys (never in the
            # frontend's static bootstrap dict)
            s3 = MiniS3(addr, access=ak, secret=sk)
            st, _, _ = await s3.request("PUT", "/alice-bucket")
            assert st == 200
            st, _, _ = await s3.request("PUT", "/alice-bucket/f",
                                        body=b"hers")
            assert st == 200
            # presigned by alice works too
            url = presign_url("GET", addr, "/alice-bucket/f",
                              ak, sk, expires=60)
            st, _, body = await s3.request(
                "GET", url[len(f"http://{addr}"):].partition("?")[0]
                + "?" + url.partition("?")[2], sign=False)
            assert st == 200 and body == b"hers"
            # suspension takes effect within the TTL
            await rgw.user_set_suspended("alice", True)
            await asyncio.sleep(0.7)
            st, _, _ = await s3.request("GET", "/alice-bucket/f")
            assert st == 403
            await rgw.user_set_suspended("alice", False)
            await asyncio.sleep(0.7)
            st, _, body = await s3.request("GET", "/alice-bucket/f")
            assert st == 200 and body == b"hers"
            # removal revokes the key permanently
            await rgw.user_rm("alice")
            await asyncio.sleep(0.7)
            st, _, _ = await s3.request("GET", "/alice-bucket/f")
            assert st == 403
            # the static bootstrap user still authenticates (its own
            # namespace; alice's private bucket stays hers)
            boot = MiniS3(addr, access=ACCESS, secret=SECRET)
            st, _, _ = await boot.request("PUT", "/boot-bucket")
            assert st == 200
            st, _, _ = await boot.request("GET", "/alice-bucket/f")
            assert st == 403  # private ACL, different owner
        finally:
            if fe is not None:
                await fe.stop()
            await cluster.stop()
    run(main())


def test_radosgw_admin_cli(tmp_path):
    async def main():
        cluster = Cluster(num_osds=2)
        await cluster.start()
        try:
            mon = cluster.mon.addr
            await cluster.client.create_replicated_pool(
                "rgw.meta", size=2, pg_num=4)
            await cluster.client.create_replicated_pool(
                "rgw.data", size=2, pg_num=4)
            env = {"PYTHONPATH": ".", "JAX_PLATFORMS": "cpu",
                   "PATH": "/usr/bin:/bin:/usr/local/bin"}

            async def cli(*args):
                proc = await asyncio.create_subprocess_exec(
                    sys.executable, "-m",
                    "ceph_tpu.tools.radosgw_admin", "-m", mon,
                    *args, stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE, env=env)
                out, err = await proc.communicate()
                return proc.returncode, out, err

            rc, out, err = await cli("user", "create", "--uid",
                                     "bob", "--display-name", "Bob")
            assert rc == 0, err
            doc = json.loads(out)
            assert doc["uid"] == "bob"
            assert doc["keys"][0]["access_key"].startswith("AK")
            rc, out, _ = await cli("user", "ls")
            assert json.loads(out) == ["bob"]
            rc, out, _ = await cli("user", "info", "--uid", "bob")
            assert json.loads(out)["display_name"] == "Bob"
            rc, _, _ = await cli("user", "suspend", "--uid", "bob")
            assert rc == 0
            rc, out, _ = await cli("user", "info", "--uid", "bob")
            assert json.loads(out)["suspended"] is True
            rc, _, _ = await cli("user", "rm", "--uid", "bob")
            assert rc == 0
            rc, out, _ = await cli("user", "ls")
            assert json.loads(out) == []
        finally:
            await cluster.stop()
    run(main())
